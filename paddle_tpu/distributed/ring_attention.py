"""Context parallelism: ring flash attention + Ulysses (alltoall) attention.

≙ reference PaddleNLP `ring_flash_attention.py` (RingFlashAttention: ring
P2P of KV blocks with online-softmax merge over the `sep` group) and the
DeepSpeed-Ulysses-style alltoall head-scatter variant — SURVEY.md §2.3
"CP / ring attention" row. The reference builds these from NCCL send/recv;
here they are `shard_map` programs over a mesh axis: the KV rotation is a
`ppermute` (collective_permute riding ICI) and the schedule is a `lax.scan`,
so the whole thing jits, differentiates (scan + ppermute both have
transpose rules), and composes with every other mesh axis.

Layout convention (B, S, H, D) — paddle flash_attn convention; activations
arrive sequence-sharded over the `sep` axis.

Ring v1 computes each (q-chunk, kv-chunk) step with an XLA chunk kernel
that returns (o, lse) for the online merge; fully-masked steps contribute
lse = -inf and drop out of the merge exactly. Causal uses per-step masking
(no zigzag load-balancing yet). Ulysses runs the *local* full-sequence
attention through the Pallas flash kernel when shapes allow.
"""
from __future__ import annotations

import functools
import math
from typing import Optional

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

# version-conditional shard_map kwargs (check_vma vs check_rep) live in
# collective.py; reuse them so the older-jax fallback actually works here
from .collective import _SM_KW, shard_map as _shard_map

from ..core.tensor import Tensor, apply
from .mesh import ProcessMesh, get_mesh

NEG_INF = -1e30


def _chunk_attn_with_lse(q, k, v, scale, mask):
    """One (q-chunk, kv-chunk) attention step, GQA-native.

    q: (B, Sq, H, D); k, v: (B, Sk, HK, D) with H a multiple of HK — the
    kv-head group dim is folded into the einsum, so GQA never expands KV
    in memory (the ring rotates the small (B, c, HK, D) buffers).
    mask: (Sq, Sk) bool or None. Returns (o (B,Sq,H,D), lse (B,Sq,H))
    with lse = -inf for fully-masked rows (their o rows are 0).
    """
    b, sq, h, d = q.shape
    hk = k.shape[2]
    g = h // hk
    qg = q.astype(jnp.float32).reshape(b, sq, hk, g, d)
    s = jnp.einsum("bqegd,bked->begqk", qg,
                   k.astype(jnp.float32)) * scale        # (B,HK,G,Sq,Sk)
    if mask is not None:
        s = jnp.where(mask[None, None, None], s, NEG_INF)
    m = jnp.max(s, axis=-1, keepdims=True)               # (B,HK,G,Sq,1)
    masked_row = m <= NEG_INF * 0.5
    p = jnp.where(s > NEG_INF * 0.5,
                  jnp.exp(s - jnp.where(masked_row, 0.0, m)), 0.0)
    l = jnp.sum(p, axis=-1, keepdims=True)
    o = jnp.einsum("begqk,bked->bqegd", p,
                   v.astype(jnp.float32))                # (B,Sq,HK,G,D)
    l_q = jnp.transpose(l[..., 0], (0, 3, 1, 2))         # (B,Sq,HK,G)
    o = o / jnp.maximum(l_q[..., None], 1e-30)
    lse = jnp.where(masked_row, NEG_INF,
                    m + jnp.log(jnp.maximum(l, 1e-30)))[..., 0]
    lse = jnp.transpose(lse, (0, 3, 1, 2))               # (B,Sq,HK,G)
    return o.reshape(b, sq, h, d), lse.reshape(b, sq, h)


def _merge(o_a, lse_a, o_b, lse_b):
    """Associative online-softmax merge of two partial attention results."""
    lse_m = jnp.logaddexp(lse_a, lse_b)                  # (B,Sq,H)
    both_masked = lse_m <= NEG_INF * 0.5
    wa = jnp.where(both_masked, 0.0, jnp.exp(lse_a - lse_m))[..., None]
    wb = jnp.where(both_masked, 0.0, jnp.exp(lse_b - lse_m))[..., None]
    return o_a * wa + o_b * wb, lse_m


def ring_attention_values(q, k, v, mesh: Optional[ProcessMesh] = None,
                          axis: str = "sep", causal: bool = False,
                          scale: Optional[float] = None,
                          balance: Optional[str] = None):
    """jnp-level ring attention. q/k/v: GLOBAL (B, S, H, D), sequence-
    sharded over `axis`; returns the globally-sharded output.

    `balance='zigzag'` (causal only) assigns each rank the block pair
    (i, 2n-1-i) of 2n sequence blocks, so every ring step does ~the same
    work — the contiguous layout leaves rank r busy in only r+1 of n
    steps, and since the ring is tick-synchronous the idle ranks wait
    anyway (wall time = dense). Zigzag halves causal wall time."""
    mesh = mesh or get_mesh()
    if mesh is None or axis not in mesh.dim_names or \
            mesh.get_dim_size(axis) == 1:
        from ..ops.flash_attention import flash_attention_values
        return flash_attention_values(q, k, v, causal=causal, scale=scale)

    n = mesh.get_dim_size(axis)
    b, s_global, h, d = q.shape
    if scale is None:
        scale = 1.0 / math.sqrt(d)
    hk = k.shape[2]
    if h % hk:
        raise ValueError(f"ring attention: q heads {h} not a multiple of "
                         f"kv heads {hk}")
    if balance == "zigzag" and causal and n > 1 and \
            s_global % (2 * n) == 0:
        # (a sequence divisible by n but not 2n falls back to the
        # contiguous schedule rather than truncating blocks)
        return _ring_zigzag(q, k, v, mesh, axis, float(scale), n)
    # GQA stays compressed: the ring rotates (B, c, HK, D) KV chunks and
    # the chunk kernel folds the group dim into its einsum — no
    # jnp.repeat HBM expansion (H/HK x memory and ICI traffic saved)
    c = s_global // n  # local chunk length

    def local_fn(ql, kl, vl):
        # ql/kl/vl: (B, c, H, D) — this device's sequence chunk
        my = jax.lax.axis_index(axis)
        perm = [(j, (j + 1) % n) for j in range(n)]

        def step(carry, i):
            o_acc, lse_acc, k_cur, v_cur = carry
            src = (my - i) % n  # whose chunk we hold at step i
            if causal:
                # chunk-level relation: src < my full, == local causal,
                # > fully masked
                q_pos = my * c + jnp.arange(c)[:, None]
                k_pos = src * c + jnp.arange(c)[None, :]
                mask = q_pos >= k_pos
            else:
                mask = None
            o_i, lse_i = _chunk_attn_with_lse(ql, k_cur, v_cur, scale, mask)
            o_acc, lse_acc = _merge(o_acc, lse_acc, o_i, lse_i)
            k_nxt = jax.lax.ppermute(k_cur, axis, perm)
            v_nxt = jax.lax.ppermute(v_cur, axis, perm)
            return (o_acc, lse_acc, k_nxt, v_nxt), None

        o0 = jnp.zeros(ql.shape, jnp.float32)
        lse0 = jnp.full(ql.shape[:3], NEG_INF, jnp.float32)
        (o, lse, _, _), _ = jax.lax.scan(
            step, (o0, lse0, kl, vl), jnp.arange(n))
        return o.astype(ql.dtype)

    spec = P(None, axis, None, None)
    return _shard_map(local_fn, mesh=mesh.jax_mesh,
                      in_specs=(spec, spec, spec), out_specs=spec,
                      **_SM_KW)(q, k, v)


def _ring_zigzag(q, k, v, mesh, axis, scale, n):
    """Zigzag-balanced causal ring (≙ the load-balanced RingFlashAttention
    variant; SURVEY.md §5 long-context row, VERDICT r2 weak 4).

    The global sequence splits into 2n blocks; rank r owns blocks
    (r, 2n-1-r). Per ring step the 4 (q-block, k-block) pairs reduce to
    exactly ~2 full-block attentions on EVERY rank (src<my: q_lo/q_hi vs
    k_lo; src==my: the two diagonal causals + one full; src>my: q_hi vs
    both), selected by `lax.switch` so masked pairs cost nothing. The
    permutation happens globally outside the shard_map; output is
    unpermuted back, so callers keep the contiguous layout contract.
    """
    b, s_global, h, d = q.shape
    bs = s_global // (2 * n)
    # global zigzag gather: rank r's rows = blocks r and 2n-1-r
    blocks = np.arange(2 * n)
    order = np.concatenate([np.stack([blocks[:n], blocks[::-1][:n]], 1)
                            .reshape(-1)])
    perm_idx = np.concatenate(
        [np.arange(bb * bs, (bb + 1) * bs) for bb in order])
    inv_idx = np.argsort(perm_idx)
    qz = jnp.take(q, jnp.asarray(perm_idx), axis=1)
    kz = jnp.take(k, jnp.asarray(perm_idx), axis=1)
    vz = jnp.take(v, jnp.asarray(perm_idx), axis=1)

    tri = jnp.tril(jnp.ones((bs, bs), bool))

    def local_fn(ql, kl, vl):
        my = jax.lax.axis_index(axis)
        ring = [(j, (j + 1) % n) for j in range(n)]
        q_lo, q_hi = ql[:, :bs], ql[:, bs:]

        def attn(qq, kk, vv, mask):
            return _chunk_attn_with_lse(qq, kk, vv, scale, mask)

        def empty(qq):
            return (jnp.zeros(qq.shape, jnp.float32),
                    jnp.full(qq.shape[:3], NEG_INF, jnp.float32))

        def step(carry, i):
            o_lo, l_lo, o_hi, l_hi, k_cur, v_cur = carry
            src = (my - i) % n
            k_s, v_s = k_cur[:, :bs], v_cur[:, :bs]
            k_S, v_S = k_cur[:, bs:], v_cur[:, bs:]

            def case_lt():   # src < my: q_lo@k_s full, q_hi@k_s full
                return (attn(q_lo, k_s, v_s, None),
                        attn(q_hi, k_s, v_s, None))

            def case_eq():   # src == my: diagonals causal + q_hi@k_s full
                lo = attn(q_lo, k_s, v_s, tri)
                hi = _merge(*attn(q_hi, k_s, v_s, None),
                            *attn(q_hi, k_S, v_S, tri))
                return (lo, hi)

            def case_gt():   # src > my: q_hi@k_s full, q_hi@k_S full
                return (empty(q_lo),
                        _merge(*attn(q_hi, k_s, v_s, None),
                               *attn(q_hi, k_S, v_S, None)))

            branch = (src >= my).astype(jnp.int32) + \
                (src > my).astype(jnp.int32)
            (lo_i, hi_i) = jax.lax.switch(
                branch, [case_lt, case_eq, case_gt])
            o_lo, l_lo = _merge(o_lo, l_lo, *lo_i)
            o_hi, l_hi = _merge(o_hi, l_hi, *hi_i)
            k_nxt = jax.lax.ppermute(k_cur, axis, ring)
            v_nxt = jax.lax.ppermute(v_cur, axis, ring)
            return (o_lo, l_lo, o_hi, l_hi, k_nxt, v_nxt), None

        z_lo = empty(q_lo)
        z_hi = empty(q_hi)
        (o_lo, _, o_hi, _, _, _), _ = jax.lax.scan(
            step, (z_lo[0], z_lo[1], z_hi[0], z_hi[1], kl, vl),
            jnp.arange(n))
        return jnp.concatenate([o_lo, o_hi], axis=1).astype(ql.dtype)

    spec = P(None, axis, None, None)
    oz = _shard_map(local_fn, mesh=mesh.jax_mesh,
                    in_specs=(spec, spec, spec), out_specs=spec,
                    **_SM_KW)(qz, kz, vz)
    return jnp.take(oz, jnp.asarray(inv_idx), axis=1)


def ulysses_attention_values(q, k, v, mesh: Optional[ProcessMesh] = None,
                             axis: str = "sep", causal: bool = False,
                             scale: Optional[float] = None):
    """Ulysses sequence parallelism: alltoall scatters heads / gathers
    sequence, full-length attention runs locally per head shard (through
    the Pallas flash kernel when aligned), alltoall back."""
    mesh = mesh or get_mesh()
    from ..ops.flash_attention import flash_attention_values
    if mesh is None or axis not in mesh.dim_names or \
            mesh.get_dim_size(axis) == 1:
        return flash_attention_values(q, k, v, causal=causal, scale=scale)

    n = mesh.get_dim_size(axis)
    b, s_global, h, d = q.shape
    hk = k.shape[2]
    if h % n or (hk % n and h != hk):
        # heads must split evenly across the axis; expand GQA if the kv
        # heads alone cannot
        if h % n:
            raise ValueError(f"ulysses: num heads {h} not divisible by "
                             f"sep degree {n}")
        k = jnp.repeat(k, h // hk, axis=2)
        v = jnp.repeat(v, h // hk, axis=2)
        hk = h

    def local_fn(ql, kl, vl):
        # (B, c, H, D) -> tiled alltoall: scatter heads, gather sequence
        def head_scatter(x):
            return jax.lax.all_to_all(x, axis, split_axis=2, concat_axis=1,
                                      tiled=True)   # (B, S, H/n, D)

        qf, kf, vf = head_scatter(ql), head_scatter(kl), head_scatter(vl)
        of = flash_attention_values(qf, kf, vf, causal=causal, scale=scale)
        # (B, S, H/n, D) -> inverse alltoall -> (B, c, H, D)
        return jax.lax.all_to_all(of, axis, split_axis=1, concat_axis=2,
                                  tiled=True)

    spec = P(None, axis, None, None)
    return _shard_map(local_fn, mesh=mesh.jax_mesh,
                      in_specs=(spec, spec, spec), out_specs=spec,
                      **_SM_KW)(q, k, v)


def ring_flash_attention(q: Tensor, k: Tensor, v: Tensor,
                         mesh: Optional[ProcessMesh] = None,
                         axis: str = "sep", causal: bool = False,
                         scale=None, balance: Optional[str] = None) -> Tensor:
    """Eager/tape entry point. ≙ PaddleNLP RingFlashAttention [U?].
    balance='zigzag' enables the load-balanced causal schedule."""
    def fn(qq, kk, vv):
        return ring_attention_values(qq, kk, vv, mesh, axis, causal, scale,
                                     balance=balance)
    return apply("ring_flash_attention", fn, (q, k, v))


def ulysses_flash_attention(q: Tensor, k: Tensor, v: Tensor,
                            mesh: Optional[ProcessMesh] = None,
                            axis: str = "sep", causal: bool = False,
                            scale=None) -> Tensor:
    def fn(qq, kk, vv):
        return ulysses_attention_values(qq, kk, vv, mesh, axis, causal,
                                        scale)
    return apply("ulysses_flash_attention", fn, (q, k, v))
