"""Context parallelism: ring flash attention + Ulysses (alltoall) attention.

≙ reference PaddleNLP `ring_flash_attention.py` (RingFlashAttention: ring
P2P of KV blocks with online-softmax merge over the `sep` group) and the
DeepSpeed-Ulysses-style alltoall head-scatter variant — SURVEY.md §2.3
"CP / ring attention" row. The reference builds these from NCCL send/recv;
here they are `shard_map` programs over a mesh axis: the KV rotation is a
`ppermute` (collective_permute riding ICI) and the schedule is a `lax.scan`,
so the whole thing jits, differentiates (scan + ppermute both have
transpose rules), and composes with every other mesh axis.

Layout convention (B, S, H, D) — paddle flash_attn convention; activations
arrive sequence-sharded over the `sep` axis.

Ring v1 computes each (q-chunk, kv-chunk) step with an XLA chunk kernel
that returns (o, lse) for the online merge; fully-masked steps contribute
lse = -inf and drop out of the merge exactly. Causal uses per-step masking
(no zigzag load-balancing yet). Ulysses runs the *local* full-sequence
attention through the Pallas flash kernel when shapes allow.
"""
from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

# version-conditional shard_map kwargs (check_vma vs check_rep) live in
# collective.py; reuse them so the older-jax fallback actually works here
from .collective import _SM_KW, shard_map as _shard_map

from ..core.tensor import Tensor, apply
from .mesh import ProcessMesh, get_mesh

NEG_INF = -1e30


def _chunk_attn_with_lse(q, k, v, scale, mask):
    """One (q-chunk, kv-chunk) attention step, GQA-native.

    q: (B, Sq, H, D); k, v: (B, Sk, HK, D) with H a multiple of HK — the
    kv-head group dim is folded into the einsum, so GQA never expands KV
    in memory (the ring rotates the small (B, c, HK, D) buffers).
    mask: (Sq, Sk) bool or None. Returns (o (B,Sq,H,D), lse (B,Sq,H))
    with lse = -inf for fully-masked rows (their o rows are 0).
    """
    b, sq, h, d = q.shape
    hk = k.shape[2]
    g = h // hk
    qg = q.astype(jnp.float32).reshape(b, sq, hk, g, d)
    s = jnp.einsum("bqegd,bked->begqk", qg,
                   k.astype(jnp.float32)) * scale        # (B,HK,G,Sq,Sk)
    if mask is not None:
        s = jnp.where(mask[None, None, None], s, NEG_INF)
    m = jnp.max(s, axis=-1, keepdims=True)               # (B,HK,G,Sq,1)
    masked_row = m <= NEG_INF * 0.5
    p = jnp.where(s > NEG_INF * 0.5,
                  jnp.exp(s - jnp.where(masked_row, 0.0, m)), 0.0)
    l = jnp.sum(p, axis=-1, keepdims=True)
    o = jnp.einsum("begqk,bked->bqegd", p,
                   v.astype(jnp.float32))                # (B,Sq,HK,G,D)
    l_q = jnp.transpose(l[..., 0], (0, 3, 1, 2))         # (B,Sq,HK,G)
    o = o / jnp.maximum(l_q[..., None], 1e-30)
    lse = jnp.where(masked_row, NEG_INF,
                    m + jnp.log(jnp.maximum(l, 1e-30)))[..., 0]
    lse = jnp.transpose(lse, (0, 3, 1, 2))               # (B,Sq,HK,G)
    return o.reshape(b, sq, h, d), lse.reshape(b, sq, h)


def _merge(o_a, lse_a, o_b, lse_b):
    """Associative online-softmax merge of two partial attention results."""
    lse_m = jnp.logaddexp(lse_a, lse_b)                  # (B,Sq,H)
    both_masked = lse_m <= NEG_INF * 0.5
    wa = jnp.where(both_masked, 0.0, jnp.exp(lse_a - lse_m))[..., None]
    wb = jnp.where(both_masked, 0.0, jnp.exp(lse_b - lse_m))[..., None]
    return o_a * wa + o_b * wb, lse_m


def ring_attention_values(q, k, v, mesh: Optional[ProcessMesh] = None,
                          axis: str = "sep", causal: bool = False,
                          scale: Optional[float] = None):
    """jnp-level ring attention. q/k/v: GLOBAL (B, S, H, D), sequence-
    sharded over `axis`; returns the globally-sharded output."""
    mesh = mesh or get_mesh()
    if mesh is None or axis not in mesh.dim_names or \
            mesh.get_dim_size(axis) == 1:
        from ..ops.flash_attention import flash_attention_values
        return flash_attention_values(q, k, v, causal=causal, scale=scale)

    n = mesh.get_dim_size(axis)
    b, s_global, h, d = q.shape
    if scale is None:
        scale = 1.0 / math.sqrt(d)
    hk = k.shape[2]
    if h % hk:
        raise ValueError(f"ring attention: q heads {h} not a multiple of "
                         f"kv heads {hk}")
    # GQA stays compressed: the ring rotates (B, c, HK, D) KV chunks and
    # the chunk kernel folds the group dim into its einsum — no
    # jnp.repeat HBM expansion (H/HK x memory and ICI traffic saved)
    c = s_global // n  # local chunk length

    def local_fn(ql, kl, vl):
        # ql/kl/vl: (B, c, H, D) — this device's sequence chunk
        my = jax.lax.axis_index(axis)
        perm = [(j, (j + 1) % n) for j in range(n)]

        def step(carry, i):
            o_acc, lse_acc, k_cur, v_cur = carry
            src = (my - i) % n  # whose chunk we hold at step i
            if causal:
                # chunk-level relation: src < my full, == local causal,
                # > fully masked
                q_pos = my * c + jnp.arange(c)[:, None]
                k_pos = src * c + jnp.arange(c)[None, :]
                mask = q_pos >= k_pos
            else:
                mask = None
            o_i, lse_i = _chunk_attn_with_lse(ql, k_cur, v_cur, scale, mask)
            o_acc, lse_acc = _merge(o_acc, lse_acc, o_i, lse_i)
            k_nxt = jax.lax.ppermute(k_cur, axis, perm)
            v_nxt = jax.lax.ppermute(v_cur, axis, perm)
            return (o_acc, lse_acc, k_nxt, v_nxt), None

        o0 = jnp.zeros(ql.shape, jnp.float32)
        lse0 = jnp.full(ql.shape[:3], NEG_INF, jnp.float32)
        (o, lse, _, _), _ = jax.lax.scan(
            step, (o0, lse0, kl, vl), jnp.arange(n))
        return o.astype(ql.dtype)

    spec = P(None, axis, None, None)
    return _shard_map(local_fn, mesh=mesh.jax_mesh,
                      in_specs=(spec, spec, spec), out_specs=spec,
                      **_SM_KW)(q, k, v)


def ulysses_attention_values(q, k, v, mesh: Optional[ProcessMesh] = None,
                             axis: str = "sep", causal: bool = False,
                             scale: Optional[float] = None):
    """Ulysses sequence parallelism: alltoall scatters heads / gathers
    sequence, full-length attention runs locally per head shard (through
    the Pallas flash kernel when aligned), alltoall back."""
    mesh = mesh or get_mesh()
    from ..ops.flash_attention import flash_attention_values
    if mesh is None or axis not in mesh.dim_names or \
            mesh.get_dim_size(axis) == 1:
        return flash_attention_values(q, k, v, causal=causal, scale=scale)

    n = mesh.get_dim_size(axis)
    b, s_global, h, d = q.shape
    hk = k.shape[2]
    if h % n or (hk % n and h != hk):
        # heads must split evenly across the axis; expand GQA if the kv
        # heads alone cannot
        if h % n:
            raise ValueError(f"ulysses: num heads {h} not divisible by "
                             f"sep degree {n}")
        k = jnp.repeat(k, h // hk, axis=2)
        v = jnp.repeat(v, h // hk, axis=2)
        hk = h

    def local_fn(ql, kl, vl):
        # (B, c, H, D) -> tiled alltoall: scatter heads, gather sequence
        def head_scatter(x):
            return jax.lax.all_to_all(x, axis, split_axis=2, concat_axis=1,
                                      tiled=True)   # (B, S, H/n, D)

        qf, kf, vf = head_scatter(ql), head_scatter(kl), head_scatter(vl)
        of = flash_attention_values(qf, kf, vf, causal=causal, scale=scale)
        # (B, S, H/n, D) -> inverse alltoall -> (B, c, H, D)
        return jax.lax.all_to_all(of, axis, split_axis=1, concat_axis=2,
                                  tiled=True)

    spec = P(None, axis, None, None)
    return _shard_map(local_fn, mesh=mesh.jax_mesh,
                      in_specs=(spec, spec, spec), out_specs=spec,
                      **_SM_KW)(q, k, v)


def ring_flash_attention(q: Tensor, k: Tensor, v: Tensor,
                         mesh: Optional[ProcessMesh] = None,
                         axis: str = "sep", causal: bool = False,
                         scale=None) -> Tensor:
    """Eager/tape entry point. ≙ PaddleNLP RingFlashAttention [U?]."""
    def fn(qq, kk, vv):
        return ring_attention_values(qq, kk, vv, mesh, axis, causal, scale)
    return apply("ring_flash_attention", fn, (q, k, v))


def ulysses_flash_attention(q: Tensor, k: Tensor, v: Tensor,
                            mesh: Optional[ProcessMesh] = None,
                            axis: str = "sep", causal: bool = False,
                            scale=None) -> Tensor:
    def fn(qq, kk, vv):
        return ulysses_attention_values(qq, kk, vv, mesh, axis, causal,
                                        scale)
    return apply("ulysses_flash_attention", fn, (q, k, v))
