"""Eager collective API. ≙ reference
«python/paddle/distributed/communication/» over ProcessGroupNCCL
(SURVEY.md §2.3 'Collective API').

TPU-native contract (single-controller SPMD): the reference is
multi-controller — each rank holds a LOCAL tensor and collectives combine
them over NCCL. Here, the per-rank tensors of a group are represented as ONE
global array whose leading axis is the group axis, sharded over the mesh;
each collective is a `shard_map`ped `lax.p*` over that axis, which is exactly
the collective XLA emits over ICI. `Group.stack()` / `Group.unstack()`
convert between the two views. Real training code rarely calls these — GSPMD
inserts collectives automatically; this module exists for API parity, tests,
and custom shard_map code."""
from __future__ import annotations

from typing import Optional, Sequence

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec

try:
    from jax import shard_map  # jax >= 0.8
    _SM_KW = {"check_vma": False}
except ImportError:  # pragma: no cover — older jax
    from jax.experimental.shard_map import shard_map  # type: ignore
    _SM_KW = {"check_rep": False}

from ..core.tensor import Tensor, to_tensor
from .mesh import ProcessMesh


class ReduceOp:
    SUM = "sum"
    MAX = "max"
    MIN = "min"
    PROD = "prod"
    AVG = "avg"


class Group:
    """A communicator = one axis of a (possibly 1-D) device mesh.
    ≙ reference ProcessGroup («paddle/fluid/distributed/collective/») [U]."""

    def __init__(self, mesh: ProcessMesh, axis: str, group_id: int = 0):
        self.mesh = mesh
        self.axis = axis
        self.id = group_id

    @property
    def nranks(self) -> int:
        return self.mesh.get_dim_size(self.axis)

    @property
    def world_size(self) -> int:
        return self.nranks

    @property
    def rank(self) -> int:
        return 0  # single-controller: queries are global

    @property
    def ranks(self) -> list:
        return list(range(self.nranks))

    def get_group_rank(self, rank):
        return rank

    # -- view conversion -----------------------------------------------------
    def stack(self, tensors: Sequence[Tensor]) -> Tensor:
        """List of per-rank tensors -> global (nranks, ...) array sharded
        over the group axis."""
        vals = [t._value if isinstance(t, Tensor) else jnp.asarray(t)
                for t in tensors]
        stacked = jnp.stack(vals, 0)
        sharding = NamedSharding(self.mesh.jax_mesh,
                                 PartitionSpec(self.axis))
        return Tensor(jax.device_put(stacked, sharding))

    def unstack(self, t: Tensor) -> list:
        return [Tensor(v) for v in t._value]

    def _run(self, fn, t: Tensor, out_spec=None, in_spec=None) -> Tensor:
        v = t._value if isinstance(t, Tensor) else jnp.asarray(t)
        in_specs = in_spec if in_spec is not None else PartitionSpec(self.axis)
        out_specs = out_spec if out_spec is not None \
            else PartitionSpec(self.axis)
        mapped = shard_map(fn, mesh=self.mesh.jax_mesh,
                           in_specs=(in_specs,), out_specs=out_specs,
                           **_SM_KW)
        return Tensor(mapped(v))


_default_group: Optional[Group] = None
_group_counter = 0


def _get_group(group: Optional[Group]) -> Group:
    global _default_group
    if group is not None:
        return group
    if _default_group is None:
        n = len(jax.devices())
        mesh = ProcessMesh(shape=(n,), dim_names=("world",))
        _default_group = Group(mesh, "world")
    return _default_group


def new_group(ranks=None, backend=None, timeout=None) -> Group:
    """≙ paddle.distributed.new_group. Builds a 1-D mesh over the given
    device ids (defaults to all)."""
    global _group_counter
    _group_counter += 1
    if ranks is None:
        ranks = list(range(len(jax.devices())))
    mesh = ProcessMesh(shape=(len(ranks),), dim_names=("world",),
                       process_ids=ranks)
    return Group(mesh, "world", _group_counter)


def get_group(gid: int = 0) -> Group:
    return _get_group(None)


# -- collectives over the stacked representation -----------------------------
def all_reduce(tensor: Tensor, op: str = ReduceOp.SUM,
               group: Optional[Group] = None, sync_op: bool = True) -> Tensor:
    """Input: (nranks, ...) stacked view. Output: same shape, every rank
    slice = reduction over ranks."""
    g = _get_group(group)
    red = {ReduceOp.SUM: jax.lax.psum, ReduceOp.MAX: jax.lax.pmax,
           ReduceOp.MIN: jax.lax.pmin,
           ReduceOp.AVG: lambda v, a: jax.lax.pmean(v, a)}[op]

    def fn(v):
        return red(v, g.axis)
    out = g._run(fn, tensor)
    if isinstance(tensor, Tensor):
        tensor._value = out._value
        return tensor
    return out


def all_gather(tensor_list, tensor: Tensor = None,
               group: Optional[Group] = None, sync_op: bool = True):
    """Paddle signature: results appended to tensor_list. Input is the
    stacked (nranks, ...) view; appends each rank's gathered copy."""
    g = _get_group(group)

    def fn(v):
        # tiled concat along the stacked axis; result identical on every
        # shard -> replicated out_spec
        return jax.lax.all_gather(v, g.axis, axis=0, tiled=True)
    out = g._run(fn, tensor, out_spec=PartitionSpec())  # (nranks, ...)
    if tensor_list is not None:
        for i in range(g.nranks):
            tensor_list.append(Tensor(out._value[i]))
        return tensor_list
    return out


def all_gather_object(object_list, obj, group=None):
    # single-controller: every "rank" sees the same object
    g = _get_group(group)
    object_list.extend([obj] * g.nranks)
    return object_list


def reduce_scatter(tensor: Tensor, tensor_list=None, op: str = ReduceOp.SUM,
                   group: Optional[Group] = None, sync_op: bool = True):
    """Stacked view in (nranks, nranks*chunk, ...) semantics: reduces over
    ranks then scatters chunks."""
    g = _get_group(group)

    def fn(v):
        # v: (1, chunks...) local slice of the stacked axis
        summed = jax.lax.psum(v, g.axis)            # (1, n*chunk)
        idx = jax.lax.axis_index(g.axis)
        chunk = summed.shape[1] // g.nranks
        return jax.lax.dynamic_slice_in_dim(summed, idx * chunk, chunk, 1)
    out = g._run(fn, tensor)
    if isinstance(tensor, Tensor) and tensor_list is None:
        return out
    return out


def broadcast(tensor: Tensor, src: int = 0, group: Optional[Group] = None,
              sync_op: bool = True) -> Tensor:
    g = _get_group(group)

    def fn(v):
        # every rank receives rank-src's slice
        gathered = jax.lax.all_gather(v, g.axis, axis=0)  # (n, 1, ...)
        return gathered[src]
    out = g._run(fn, tensor)
    if isinstance(tensor, Tensor):
        tensor._value = out._value
        return tensor
    return out


def reduce(tensor: Tensor, dst: int = 0, op: str = ReduceOp.SUM,
           group: Optional[Group] = None, sync_op: bool = True) -> Tensor:
    # single-controller: same as all_reduce but only dst slice meaningful
    return all_reduce(tensor, op, group, sync_op)


def scatter(tensor: Tensor, tensor_list=None, src: int = 0,
            group: Optional[Group] = None, sync_op: bool = True):
    g = _get_group(group)
    if tensor_list is not None:
        src_stack = g.stack(tensor_list)
        if isinstance(tensor, Tensor):
            tensor._value = src_stack._value
            return tensor
        return src_stack
    return tensor


def alltoall(out_tensor_list, in_tensor_list, group: Optional[Group] = None,
             sync_op: bool = True):
    """in: stacked (n, n, ...) view (rank-major, then destination chunk)."""
    g = _get_group(group)
    if isinstance(in_tensor_list, (list, tuple)):
        stacked = g.stack([t if isinstance(t, Tensor) else to_tensor(t)
                           for t in in_tensor_list])
    else:
        stacked = in_tensor_list

    def fn(v):
        # v: (1, n, ...) — local row; all_to_all swaps axis 1 across ranks
        return jax.lax.all_to_all(v, g.axis, split_axis=1, concat_axis=0,
                                  tiled=False)
    out = g._run(fn, stacked)
    if out_tensor_list is not None:
        val = out._value  # (n, 1, n?, ...) -> recover per-rank rows
        flat = val.reshape((g.nranks, g.nranks) + val.shape[2:]) \
            if val.ndim >= 2 else val
        for i in range(g.nranks):
            out_tensor_list.append(Tensor(flat[i]))
        return out_tensor_list
    return out


def alltoall_single(out_tensor, in_tensor, in_split_sizes=None,
                    out_split_sizes=None, group=None, sync_op=True):
    g = _get_group(group)

    def fn(v):
        n = g.nranks
        chunk = v.shape[1] // n
        v4 = v.reshape((1, n, chunk) + v.shape[2:])
        out = jax.lax.all_to_all(v4, g.axis, split_axis=1, concat_axis=0)
        return out.reshape((1, n * chunk) + v.shape[2:])
    out = g._run(fn, in_tensor)
    if isinstance(out_tensor, Tensor):
        out_tensor._value = out._value
        return out_tensor
    return out


def send(tensor, dst=0, group=None, sync_op=True):
    raise NotImplementedError(
        "eager p2p send/recv has no single-controller equivalent; use "
        "paddle_tpu.distributed.fleet pipeline parallelism (ppermute inside "
        "the compiled program) instead — SURVEY.md §2.3 PP row.")


def recv(tensor, src=0, group=None, sync_op=True):
    raise NotImplementedError(
        "see send(); p2p lives inside shard_map as lax.ppermute on TPU.")


def barrier(group=None):
    jax.effects_barrier()


def destroy_process_group(group=None):
    global _default_group
    _default_group = None


def get_backend(group=None) -> str:
    return "xla"  # ICI/DCN collectives emitted by XLA
