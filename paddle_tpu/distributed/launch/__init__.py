"""Launch CLI. ≙ reference «python/paddle/distributed/launch/» (Context +
CollectiveController + Master rendezvous + Job/Pod/Container env injection —
SURVEY.md §3.5).

TPU-native: the TPU VM model is ONE process per host with all local chips
attached, so there is no per-device fork/exec, no ETCD, no endpoint list:
`jax.distributed.initialize()` (GCE metadata autodetect on TPU pods, or
explicit --master) is the whole rendezvous. The controller reduces to: set
env, initialize, exec the training script, propagate exit codes, and
restart on failure when --elastic_level > 0 (checkpoint-restart elasticity,
SURVEY.md §5 "Failure detection").
"""
from __future__ import annotations

import os
import random
import runpy
import subprocess
import sys
import time

from ... import observability as telemetry

__all__ = ["main", "launch", "restart_backoff"]

_M_RESTARTS = telemetry.counter(
    "pdt_launch_restarts_total",
    "Elastic restarts of the training script, by job id.", ("job",))
_M_BACKOFF = telemetry.histogram(
    "pdt_launch_restart_backoff_seconds",
    "Backoff delays slept before elastic restarts.")


def _parse(argv):
    import argparse
    p = argparse.ArgumentParser(
        prog="paddle_tpu.distributed.launch",
        description="Launch a training script on this host's TPU chips "
                    "(one process per host; multi-host via --master or TPU "
                    "pod metadata autodetection).")
    p.add_argument("--master", default=os.environ.get("PADDLE_MASTER"),
                   help="coordinator ip:port for multi-host rendezvous")
    p.add_argument("--nnodes", type=int,
                   default=int(os.environ.get("PADDLE_NNODES", "1")))
    p.add_argument("--rank", type=int,
                   default=int(os.environ.get("PADDLE_TRAINER_ID", "0")))
    p.add_argument("--run_mode", default="collective")
    p.add_argument("--job_id", default="default")
    p.add_argument("--log_dir", default=None)
    p.add_argument("--elastic_level", type=int, default=0,
                   help=">0: restart the script on failure (checkpoint-"
                        "restart elasticity), up to --max_restart times")
    p.add_argument("--max_restart", type=int, default=3)
    p.add_argument("--restart_backoff", type=float, default=1.0,
                   help="base seconds between restarts; doubles per "
                        "consecutive failure with +/-50%% jitter so a "
                        "crash-looping fleet does not hammer the "
                        "coordinator in lockstep (0 disables)")
    p.add_argument("--restart_backoff_max", type=float, default=60.0,
                   help="backoff ceiling in seconds")
    p.add_argument("--devices", default=None,
                   help="ignored on TPU (all host chips attach to the one "
                        "process); kept for CLI compat")
    p.add_argument("script", help="training script (.py) to run")
    p.add_argument("script_args", nargs="...", default=[])
    return p.parse_args(argv)


def _child_env(args):
    env = dict(os.environ)
    if args.master:
        env["PADDLE_MASTER"] = args.master
        env["COORDINATOR_ADDRESS"] = args.master
    env["PADDLE_NNODES"] = str(args.nnodes)
    env["PADDLE_TRAINERS_NUM"] = str(args.nnodes)
    env["PADDLE_TRAINER_ID"] = str(args.rank)
    env["PADDLE_JOB_ID"] = args.job_id
    return env


def _run_logged(cmd, env, log_path):
    """Run cmd streaming combined stdout/stderr to BOTH the console and
    `log_path` (≙ the reference launcher's per-rank log capture,
    «.../launch/job/container.py» [U])."""
    if log_path is None:
        return subprocess.run(cmd, env=env).returncode
    with open(log_path, "ab") as f:
        proc = subprocess.Popen(cmd, env=env, stdout=subprocess.PIPE,
                                stderr=subprocess.STDOUT)
        for line in proc.stdout:
            sys.stdout.buffer.write(line)
            sys.stdout.buffer.flush()
            f.write(line)
            f.flush()
        return proc.wait()


def restart_backoff(attempt: int, base: float, cap: float,
                    rng: random.Random) -> float:
    """Delay before restart `attempt` (1-based): exponential
    base * 2^(attempt-1) with +/-50% multiplicative jitter (restarting
    ranks decorrelate instead of stampeding the rendezvous coordinator
    in lockstep), clamped to `cap` AFTER jitter — the cap is a hard
    ceiling."""
    if base <= 0:
        return 0.0
    return min(cap, base * (2.0 ** (attempt - 1)) * (0.5 + rng.random()))


def launch(args, *, sleep=time.sleep, rng: random.Random | None = None):
    env = _child_env(args)
    log_path = None
    if args.log_dir:
        os.makedirs(args.log_dir, exist_ok=True)
        log_path = os.path.join(
            args.log_dir, f"{args.job_id}.rank{args.rank}.log")
    # duck-typed args objects (tests, embedders) predate the backoff
    # knobs: default them to NO backoff so legacy callers keep their
    # immediate-restart behavior (CLI users get 1.0 from argparse)
    base = getattr(args, "restart_backoff", 0.0)
    cap = getattr(args, "restart_backoff_max", 60.0)
    rng = rng if rng is not None else random.Random()
    attempt = 0
    while True:
        t0 = time.time()
        rc = _run_logged([sys.executable, args.script, *args.script_args],
                         env, log_path)
        if rc == 0:
            return 0
        attempt += 1
        if args.elastic_level <= 0 or attempt > args.max_restart:
            return rc
        delay = restart_backoff(attempt, base, cap, rng)
        _M_RESTARTS.inc(job=getattr(args, "job_id", "default"))
        _M_BACKOFF.observe(delay)
        telemetry.event("launch.restart", rc=rc, attempt=attempt,
                        delay_s=delay,
                        job=getattr(args, "job_id", "default"))
        msg = (f"[launch] script exited {rc} after "
               f"{time.time() - t0:.0f}s — restart {attempt}/"
               f"{args.max_restart} in {delay:.1f}s (elastic "
               "checkpoint-restart, exponential backoff)")
        print(msg, file=sys.stderr)
        if log_path:
            with open(log_path, "a") as f:
                f.write(msg + "\n")
        if delay > 0:
            sleep(delay)


def main(argv=None):
    args = _parse(argv if argv is not None else sys.argv[1:])
    return launch(args)
