"""Launch CLI. ≙ reference «python/paddle/distributed/launch/» (Context +
CollectiveController + Master rendezvous + Job/Pod/Container env injection —
SURVEY.md §3.5).

TPU-native: the TPU VM model is ONE process per host with all local chips
attached, so there is no per-device fork/exec, no ETCD, no endpoint list:
`jax.distributed.initialize()` (GCE metadata autodetect on TPU pods, or
explicit --master) is the whole rendezvous. The controller reduces to: set
env, initialize, exec the training script, propagate exit codes, and
restart on failure when --elastic_level > 0 (checkpoint-restart elasticity,
SURVEY.md §5 "Failure detection").
"""
from __future__ import annotations

import os
import runpy
import subprocess
import sys
import time

__all__ = ["main", "launch"]


def _parse(argv):
    import argparse
    p = argparse.ArgumentParser(
        prog="paddle_tpu.distributed.launch",
        description="Launch a training script on this host's TPU chips "
                    "(one process per host; multi-host via --master or TPU "
                    "pod metadata autodetection).")
    p.add_argument("--master", default=os.environ.get("PADDLE_MASTER"),
                   help="coordinator ip:port for multi-host rendezvous")
    p.add_argument("--nnodes", type=int,
                   default=int(os.environ.get("PADDLE_NNODES", "1")))
    p.add_argument("--rank", type=int,
                   default=int(os.environ.get("PADDLE_TRAINER_ID", "0")))
    p.add_argument("--run_mode", default="collective")
    p.add_argument("--job_id", default="default")
    p.add_argument("--log_dir", default=None)
    p.add_argument("--elastic_level", type=int, default=0,
                   help=">0: restart the script on failure (checkpoint-"
                        "restart elasticity), up to --max_restart times")
    p.add_argument("--max_restart", type=int, default=3)
    p.add_argument("--devices", default=None,
                   help="ignored on TPU (all host chips attach to the one "
                        "process); kept for CLI compat")
    p.add_argument("script", help="training script (.py) to run")
    p.add_argument("script_args", nargs="...", default=[])
    return p.parse_args(argv)


def _child_env(args):
    env = dict(os.environ)
    if args.master:
        env["PADDLE_MASTER"] = args.master
        env["COORDINATOR_ADDRESS"] = args.master
    env["PADDLE_NNODES"] = str(args.nnodes)
    env["PADDLE_TRAINERS_NUM"] = str(args.nnodes)
    env["PADDLE_TRAINER_ID"] = str(args.rank)
    env["PADDLE_JOB_ID"] = args.job_id
    return env


def launch(args):
    env = _child_env(args)
    attempt = 0
    while True:
        t0 = time.time()
        proc = subprocess.run(
            [sys.executable, args.script, *args.script_args], env=env)
        if proc.returncode == 0:
            return 0
        attempt += 1
        if args.elastic_level <= 0 or attempt > args.max_restart:
            return proc.returncode
        print(f"[launch] script exited {proc.returncode} after "
              f"{time.time() - t0:.0f}s — restart {attempt}/"
              f"{args.max_restart} (elastic checkpoint-restart)",
              file=sys.stderr)


def main(argv=None):
    args = _parse(argv if argv is not None else sys.argv[1:])
    return launch(args)
