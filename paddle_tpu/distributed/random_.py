"""RNG state tracker for model-parallel determinism. ≙ reference
`get_rng_state_tracker` («.../fleet/meta_parallel/parallel_layers/random.py»
[U]): dropout inside TP regions must be identical across TP ranks for
replicated activations and different for sharded ones.

TPU-native: there are no per-rank RNG states — a traced PRNG key is folded
with the mesh axis index (`jax.random.fold_in` of `lax.axis_index`) inside
shard_map regions, giving exactly the local-seed/global-seed split the
reference maintains by hand."""
from __future__ import annotations

import contextlib

import jax

from ..tensor.random import default_generator


class RNGStatesTracker:
    def __init__(self):
        self.states = {}

    def add(self, name: str, seed: int):
        if name in self.states:
            raise ValueError(f"state {name} already exists")
        self.states[name] = jax.random.key(seed)

    def get_states_tracker(self):
        return dict(self.states)

    def set_states_tracker(self, states):
        self.states = dict(states)

    @contextlib.contextmanager
    def rng_state(self, name: str = "global_seed"):
        """Within the context, the default generator draws from the named
        stream (≙ reference's CUDA rng state swap)."""
        if name not in self.states:
            self.states[name] = jax.random.key(len(self.states) + 1234)
        old = default_generator._key
        default_generator._key = self.states[name]
        try:
            yield
        finally:
            self.states[name] = default_generator._key
            default_generator._key = old


_tracker = RNGStatesTracker()


def get_rng_state_tracker() -> RNGStatesTracker:
    return _tracker


def model_parallel_random_seed(seed: int = 2048):
    """≙ fleet.meta_parallel.model_parallel_random_seed: seed global +
    local (axis-folded) streams."""
    global _tracker
    _tracker = RNGStatesTracker()
    _tracker.add("global_seed", seed)
    _tracker.add("local_seed", seed + 1)


def local_key_for_axis(key, axis_name: str):
    """Fold the mesh-axis index into a key (call inside shard_map)."""
    return jax.random.fold_in(key, jax.lax.axis_index(axis_name))
