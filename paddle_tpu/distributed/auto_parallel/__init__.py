"""Semi-auto parallel API. ≙ reference «python/paddle/distributed/
auto_parallel/» (shard_tensor/Placement/ProcessMesh + static Engine with
completion/partition/reshard passes — SURVEY.md §2.3 "Semi-auto parallel",
§3.3).

TPU-native: this IS GSPMD. `shard_tensor` lowers to NamedSharding,
"completion" (sharding propagation) is XLA's propagation pass, the
partitioner is SPMD partitioning, and reshard insertion is the compiler's
collective insertion — so the Engine below is a thin trainer that jits the
whole train step under the mesh instead of running three Python passes.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Optional

import numpy as np

from ..mesh import (Partial, Placement, ProcessMesh, Replicate,  # noqa: F401
                    Shard, dtensor_from_local, get_mesh, reshard,
                    set_mesh, shard_layer, shard_tensor, use_mesh)

__all__ = ["ProcessMesh", "Placement", "Shard", "Replicate", "Partial",
           "shard_tensor", "reshard", "shard_layer", "dtensor_from_local",
           "get_mesh", "set_mesh", "Strategy", "Engine", "shard_optimizer",
           "shard_dataloader", "to_static"]


@dataclass
class _AmpConfig:
    enable: bool = False
    dtype: str = "bfloat16"
    level: str = "O1"


@dataclass
class _ShardingConfig:
    enable: bool = False
    stage: int = 1
    degree: int = 1


@dataclass
class _RecomputeConfig:
    enable: bool = False


@dataclass
class _PipelineConfig:
    enable: bool = False
    schedule_mode: str = "1F1B"
    micro_batch_size: int = 1
    accumulate_steps: int = 1


@dataclass
class Strategy:
    """≙ auto_parallel.Strategy (config tree; SURVEY.md §5 config row)."""
    amp: _AmpConfig = field(default_factory=_AmpConfig)
    sharding: _ShardingConfig = field(default_factory=_ShardingConfig)
    recompute: _RecomputeConfig = field(default_factory=_RecomputeConfig)
    pipeline: _PipelineConfig = field(default_factory=_PipelineConfig)


def shard_optimizer(optimizer, shard_fn=None):
    """≙ paddle.distributed.shard_optimizer: optimizer state follows the
    param placements inside the compiled step — identity here."""
    return optimizer


def shard_dataloader(dataloader, meshes, shard_dims=None, input_keys=None):
    """≙ paddle.distributed.shard_dataloader: wrap a loader so each batch's
    dim 0 is sharded over the data-parallel MESH axis. `shard_dims` names
    the mesh dimension (str name or mesh-dim index, matching the reference
    API) — defaulting to the axis named 'dp' (or 'data'), else axis 0."""
    mesh = meshes[0] if isinstance(meshes, (list, tuple)) else meshes

    names = list(mesh.dim_names)
    if isinstance(shard_dims, str):
        mesh_axis = names.index(shard_dims)
    elif isinstance(shard_dims, int):
        mesh_axis = shard_dims
    elif "dp" in names:
        mesh_axis = names.index("dp")
    elif "data" in names:
        mesh_axis = names.index("data")
    else:
        mesh_axis = 0

    class _Sharded:
        def __iter__(self):
            import paddle_tpu as paddle
            from ...core.tensor import Tensor
            for batch in dataloader:
                items = batch if isinstance(batch, (list, tuple)) else \
                    [batch]
                out = []
                for it in items:
                    t = it if isinstance(it, Tensor) else \
                        paddle.to_tensor(np.asarray(it))
                    placements = [Replicate() for _ in mesh.dim_names]
                    placements[mesh_axis] = Shard(0)
                    out.append(shard_tensor(t, mesh, placements))
                yield out if len(out) > 1 else out[0]

        def __len__(self):
            return len(dataloader)

    return _Sharded()


def to_static(layer, loader=None, loss=None, optimizer=None, strategy=None):
    """≙ paddle.distributed.to_static — returns the jit-compiled trainer
    pieces (the 'static program' equivalent is the XLA computation)."""
    import paddle_tpu as paddle
    step = paddle.jit.TrainStep(
        layer, optimizer,
        loss_fn=(lambda m, x, y: loss(m(x), y)) if loss else None)
    return step


class Engine:
    """≙ auto_parallel.static.Engine (fit/evaluate/predict — SURVEY.md
    §3.3). The completion/partition/reshard passes are XLA's; Engine just
    owns the jitted step + data sharding + the trainer loop."""

    def __init__(self, model=None, loss=None, optimizer=None, metrics=None,
                 strategy=None):
        self._model = model
        self._loss = loss
        self._optimizer = optimizer
        self._metrics = metrics if isinstance(metrics, (list, tuple)) else \
            ([metrics] if metrics else [])
        self._strategy = strategy or Strategy()
        self._step = None

    def _ensure(self):
        if self._step is None:
            import paddle_tpu as paddle

            def loss_fn(m, *batch):
                *xs, y = batch
                out = m(*xs)
                out0 = out[0] if isinstance(out, (tuple, list)) else out
                return self._loss(out0, y)

            self._step = paddle.jit.TrainStep(
                self._model, self._optimizer, loss_fn=loss_fn,
                accumulate_steps=self._strategy.pipeline.accumulate_steps
                if self._strategy.pipeline.enable else 1)
        return self._step

    def fit(self, train_data, epochs=1, batch_size=1, steps_per_epoch=None,
            valid_data=None, log_freq=10, verbose=1):
        from ...io import DataLoader, Dataset
        mesh = get_mesh()
        if isinstance(train_data, Dataset):
            # a ragged tail batch cannot be Shard(0) over the dp axis —
            # drop it when running on a mesh
            train_data = DataLoader(train_data, batch_size=batch_size,
                                    shuffle=True,
                                    drop_last=mesh is not None)
        if mesh is not None:
            # mesh-aware input sharding: batches arrive Shard(0) over the
            # data axis (≙ the reference Engine's dataloader sharding)
            train_data = shard_dataloader(train_data, mesh)
        history = []
        step_fn = self._ensure()
        for epoch in range(epochs):
            losses = []
            for i, batch in enumerate(train_data):
                items = list(batch) if isinstance(batch, (list, tuple)) \
                    else [batch]
                loss = step_fn(*items)
                losses.append(float(loss if not isinstance(loss, tuple)
                                    else loss[0]))
                if steps_per_epoch and i + 1 >= steps_per_epoch:
                    break
            history.append(float(np.mean(losses)))
            if verbose:
                print(f"[auto_parallel.Engine] epoch {epoch}: "
                      f"loss {history[-1]:.4f}")
        return history

    def evaluate(self, eval_data, batch_size=1, steps=None, verbose=1):
        from ...io import DataLoader, Dataset
        from ...core.tape import no_grad
        if isinstance(eval_data, Dataset):
            eval_data = DataLoader(eval_data, batch_size=batch_size)
        losses = []
        self._model.eval()
        with no_grad():
            for i, batch in enumerate(eval_data):
                *xs, y = list(batch)
                out = self._model(*xs)
                out0 = out[0] if isinstance(out, (tuple, list)) else out
                losses.append(float(self._loss(out0, y)))
                if steps and i + 1 >= steps:
                    break
        self._model.train()
        return {"loss": float(np.mean(losses))}

    def predict(self, test_data, batch_size=1, steps=None):
        from ...io import DataLoader, Dataset
        from ...core.tape import no_grad
        if isinstance(test_data, Dataset):
            test_data = DataLoader(test_data, batch_size=batch_size)
        outs = []
        self._model.eval()
        with no_grad():
            for i, batch in enumerate(test_data):
                items = list(batch) if isinstance(batch, (list, tuple)) \
                    else [batch]
                outs.append(self._model(*items[:1]))
                if steps and i + 1 >= steps:
                    break
        self._model.train()
        return outs

    def save(self, path, training=True):
        import paddle_tpu as paddle
        paddle.save(self._model.state_dict(), path + ".pdparams")

    def load(self, path):
        import paddle_tpu as paddle
        self._model.set_state_dict(paddle.load(path + ".pdparams"))
