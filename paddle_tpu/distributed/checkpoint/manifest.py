"""Integrity manifests + verification for durable checkpoints.

A sharded checkpoint is MANY files committed independently (GSPMD-style
arrays, one tensorstore per array — arxiv 2105.04663), so partial
failure is the common case, not the rare one. The durability protocol
(docs/checkpointing.md) therefore records, next to the data, a
`MANIFEST.json` describing every array the writer intended to commit:

    {
      "format": "paddle-tpu-ckpt-manifest",
      "version": 1,
      "step": 42,
      "wall_time": 1722700000.0,
      "mesh": {"device_count": 8, "process_count": 1},
      "groups": {
        "model": {
          "layers.0.attn.q_proj.weight": {
            "shape": [256, 256], "dtype": "float32",
            "nbytes": 262144, "checksum": "sha256:ab12...",
            "sharding": "PartitionSpec('mp', None)"
          }, ...
        },
        "opt": {...}
      }
    }

`verify_checkpoint` replays that intent against what is actually on
disk: manifest present and parsable, `.done` marker valid, every group
restorable, key sets equal, shapes/dtypes/nbytes matching — and, with
`rehash=True`, content checksums re-hashed so silently flipped bytes
are caught, not just torn writes. `ElasticManager.resume` runs this
before trusting a checkpoint; the CLI form is

    python -m paddle_tpu.distributed.checkpoint verify <dir> [--rehash]
"""
from __future__ import annotations

import hashlib
import json
import os
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from ... import observability as telemetry

__all__ = [
    "MANIFEST_NAME", "DONE_NAME", "CheckpointIntegrityError",
    "VerifyResult", "array_checksum", "describe_arrays",
    "build_manifest", "write_manifest", "read_manifest", "write_done",
    "parse_done", "verify_checkpoint",
]

MANIFEST_NAME = "MANIFEST.json"
DONE_NAME = ".done"
_FORMAT = "paddle-tpu-ckpt-manifest"
_VERSION = 1

_M_VERIFY_SECONDS = telemetry.histogram(
    "pdt_checkpoint_verify_seconds",
    "Wall time of verify_checkpoint passes.")


class CheckpointIntegrityError(RuntimeError):
    """A checkpoint failed its integrity manifest (missing, torn, or
    content-mismatched). `errors` carries the individual findings."""

    def __init__(self, path: str, errors: List[str]):
        super().__init__(
            f"checkpoint {path!r} failed integrity verification: "
            + "; ".join(errors))
        self.path = path
        self.errors = errors


@dataclass
class VerifyResult:
    """Outcome of one `verify_checkpoint` pass."""
    path: str
    errors: List[str] = field(default_factory=list)
    arrays_checked: int = 0
    rehashed: bool = False
    step: Optional[int] = None

    @property
    def ok(self) -> bool:
        return not self.errors

    def raise_if_failed(self):
        if self.errors:
            raise CheckpointIntegrityError(self.path, self.errors)
        return self


def array_checksum(arr) -> str:
    """Content checksum of one (possibly sharded) array: sha256 over the
    row-major host bytes. Sharded jax.Arrays are gathered to the host
    first — fine at single-process scale; multi-host writers would hash
    per-shard instead (noted in docs/checkpointing.md)."""
    import numpy as np
    host = np.ascontiguousarray(np.asarray(arr))
    return "sha256:" + hashlib.sha256(host.tobytes()).hexdigest()


def _sharding_summary(arr) -> Optional[str]:
    sharding = getattr(arr, "sharding", None)
    spec = getattr(sharding, "spec", None)
    return str(spec) if spec is not None else None


def describe_arrays(flat: Dict[str, Any]) -> Dict[str, Dict[str, Any]]:
    """Manifest entries for a flat {dotted_key: array} dict."""
    out = {}
    for key, arr in sorted(flat.items()):
        entry = {
            "shape": [int(d) for d in getattr(arr, "shape", ())],
            "dtype": str(getattr(arr, "dtype", "")),
            "nbytes": int(getattr(arr, "nbytes", 0)),
            "checksum": array_checksum(arr),
        }
        spec = _sharding_summary(arr)
        if spec is not None:
            entry["sharding"] = spec
        out[key] = entry
    return out


def build_manifest(groups: Dict[str, Dict[str, Any]],
                   step: Optional[int] = None,
                   wall_time: Optional[float] = None) -> Dict[str, Any]:
    """Assemble the manifest dict for {group_name: flat_arrays}.
    Pass `wall_time` when the caller runs on an injectable clock (as
    ElasticManager does) so the manifest and the `.done` marker tell
    the same post-mortem timeline."""
    import jax
    return {
        "format": _FORMAT,
        "version": _VERSION,
        "step": step,
        # pdt-lint: disable=PDT001 persisted post-mortem metadata IS
        # wall-clock by contract; injectable via the wall_time= param
        "wall_time": time.time() if wall_time is None else wall_time,
        "mesh": {
            "device_count": jax.device_count(),
            "process_count": jax.process_count(),
        },
        "groups": {g: describe_arrays(flat)
                   for g, flat in groups.items()},
    }


def _atomic_write_text(path: str, text: str):
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        f.write(text)
    os.replace(tmp, path)


def write_manifest(ckpt_dir: str, manifest: Dict[str, Any]) -> str:
    """Write MANIFEST.json into `ckpt_dir` atomically (tmp + rename —
    the same discipline as heartbeat files: a reader must never observe
    a truncated manifest from a healthy writer)."""
    path = os.path.join(ckpt_dir, MANIFEST_NAME)
    _atomic_write_text(path, json.dumps(manifest, indent=1, sort_keys=True))
    return path


def read_manifest(ckpt_dir: str) -> Dict[str, Any]:
    """Load and structurally validate MANIFEST.json; raises
    :class:`CheckpointIntegrityError` when absent or unparsable."""
    path = os.path.join(ckpt_dir, MANIFEST_NAME)
    try:
        with open(path) as f:
            manifest = json.load(f)
    except OSError:
        raise CheckpointIntegrityError(
            ckpt_dir, [f"missing {MANIFEST_NAME}"])
    except ValueError as e:
        raise CheckpointIntegrityError(
            ckpt_dir, [f"unparsable {MANIFEST_NAME}: {e}"])
    if (not isinstance(manifest, dict)
            or manifest.get("format") != _FORMAT
            or not isinstance(manifest.get("groups"), dict)):
        raise CheckpointIntegrityError(
            ckpt_dir, [f"malformed {MANIFEST_NAME}: not a "
                       f"{_FORMAT} document"])
    return manifest


def write_done(ckpt_dir: str, step: Optional[int] = None,
               wall_time: Optional[float] = None) -> str:
    """Commit marker, written atomically AFTER the data + manifest are
    in place. JSON payload so `parse_done` can reject torn markers."""
    path = os.path.join(ckpt_dir, DONE_NAME)
    payload = {"step": step,
               # pdt-lint: disable=PDT001 persisted post-mortem
               # metadata IS wall-clock; injectable via wall_time=
               "time": time.time() if wall_time is None else wall_time}
    _atomic_write_text(path, json.dumps(payload))
    return path


def parse_done(done_path: str) -> Optional[Dict[str, Any]]:
    """Parse a `.done` marker. Returns its payload dict, or None when
    the marker is missing, empty, or garbage — a zero-byte `.done` from
    a non-atomic writer must read as NOT committed. Accepts the legacy
    bare-float payload (pre-manifest checkpoints) for backward compat."""
    try:
        with open(done_path) as f:
            raw = f.read().strip()
    except OSError:
        return None
    if not raw:
        return None
    try:
        payload = json.loads(raw)
        if isinstance(payload, dict):
            return payload
        # bool is an int subclass: a garbage marker reading "true" must
        # NOT parse as a legacy bare-float timestamp
        if (isinstance(payload, (int, float))
                and not isinstance(payload, bool)):
            return {"step": None, "time": float(payload)}
        return None
    except ValueError:
        pass
    try:
        return {"step": None, "time": float(raw)}
    except ValueError:
        return None


def _restore_raw(path: str) -> Dict[str, Any]:
    # direct orbax restore: verify reads must not count as checkpoint
    # "load" traffic in pdt_checkpoint_ops_total
    import orbax.checkpoint as ocp
    return ocp.PyTreeCheckpointer().restore(path)


def _metadata_raw(path: str) -> Dict[str, Any]:
    # tensorstore-spec read only — no array bytes touched, which is
    # what makes the light verify tier cheap on multi-GB checkpoints
    import orbax.checkpoint as ocp
    md = ocp.PyTreeCheckpointer().metadata(path)
    if md is None:
        raise FileNotFoundError(f"no checkpoint metadata under {path}")
    return md


def verify_checkpoint(path: str, rehash: bool = False) -> VerifyResult:
    """Integrity pass over one committed checkpoint directory (a
    `step_N` produced by the atomic commit protocol).

    Checks, accumulating every finding instead of stopping at the
    first: MANIFEST.json present/parsable, `.done` marker valid, each
    group directory readable, on-disk key set == manifest key set, and
    per-array shape/dtype/nbytes match. With `rehash=False` (the light
    tier) the group check reads only checkpoint *metadata* — no array
    bytes are materialized, so it stays cheap on multi-GB checkpoints.
    With `rehash=True` every array is restored and its content checksum
    recomputed — the only check that catches silently flipped bytes
    that still deserialize.
    """
    res = VerifyResult(path=os.path.abspath(path), rehashed=rehash)
    # baselined PDT001 (.pdt-lint-baseline.json): verify timing
    # predates the lint — the entry shrinks away when this offline
    # path grows a clock parameter
    t0 = time.monotonic()
    try:
        with telemetry.span("checkpoint.verify", path=res.path,
                            rehash=rehash):
            _verify_into(res, path, rehash)
    finally:
        _M_VERIFY_SECONDS.observe(time.monotonic() - t0)
    return res


def _verify_into(res: VerifyResult, path: str, rehash: bool):
    if not os.path.isdir(path):
        res.errors.append("not a directory")
        return
    try:
        manifest = read_manifest(path)
    except CheckpointIntegrityError as e:
        res.errors.extend(e.errors)
        return
    res.step = manifest.get("step")
    if parse_done(os.path.join(path, DONE_NAME)) is None:
        res.errors.append(f"missing or unparsable {DONE_NAME} marker")
    for group, expected in sorted(manifest["groups"].items()):
        gdir = os.path.join(path, group)
        try:
            restored = _restore_raw(gdir) if rehash else _metadata_raw(gdir)
        except Exception as e:      # torn tensorstore, missing dir, ...
            res.errors.append(
                f"group {group!r} unrestorable: "
                f"{type(e).__name__}: {e}")
            continue
        missing = sorted(set(expected) - set(restored))
        unexpected = sorted(set(restored) - set(expected))
        if missing:
            res.errors.append(
                f"group {group!r} missing arrays: {missing}")
        if unexpected:
            res.errors.append(
                f"group {group!r} has arrays absent from the "
                f"manifest: {unexpected}")
        for key in sorted(set(expected) & set(restored)):
            want, arr = expected[key], restored[key]
            res.arrays_checked += 1
            got_shape = [int(d) for d in getattr(arr, "shape", ())]
            if got_shape != list(want.get("shape", [])):
                res.errors.append(
                    f"{group}/{key}: shape {got_shape} != manifest "
                    f"{want.get('shape')}")
            if str(getattr(arr, "dtype", "")) != want.get("dtype"):
                res.errors.append(
                    f"{group}/{key}: dtype "
                    f"{getattr(arr, 'dtype', None)} != manifest "
                    f"{want.get('dtype')}")
            got_nbytes = _entry_nbytes(arr, got_shape)
            if got_nbytes is not None and got_nbytes != want.get("nbytes"):
                res.errors.append(
                    f"{group}/{key}: nbytes {got_nbytes} != manifest "
                    f"{want.get('nbytes')}")
            elif rehash and array_checksum(arr) != want.get("checksum"):
                res.errors.append(
                    f"{group}/{key}: content checksum mismatch "
                    "(flipped bytes?)")


def _entry_nbytes(arr, shape: List[int]) -> Optional[int]:
    """On-disk byte size of one verified entry. Restored arrays carry
    it; metadata-only objects (light tier) don't, so it is derived from
    the on-disk shape x dtype itemsize. None when the dtype is unknown
    (reported upstream as a dtype mismatch, not a phantom nbytes one)."""
    nbytes = getattr(arr, "nbytes", None)
    if nbytes is not None:
        return int(nbytes)
    import numpy as np
    try:
        itemsize = int(np.dtype(str(getattr(arr, "dtype", ""))).itemsize)
    except TypeError:
        return None
    size = 1
    for d in shape:
        size *= d
    return size * itemsize
