"""Distributed checkpoint: sharded save + cross-mesh reshard restore.

≙ reference «python/paddle/distributed/checkpoint/» (`save_state_dict` /
`load_state_dict`: each rank writes its owned shards + global metadata;
load computes a reshard plan so a ckpt saved on mesh A restores onto mesh
B — SURVEY.md §5 "Checkpoint / resume"). TPU-native: orbax/tensorstore is
that mechanism, mature — every array is written as a sharded tensorstore
with a global-shape manifest, and restore hands each tensor its NEW
NamedSharding so resharding happens on read (different dp/mp/pp degrees,
different device counts).

Durability layer (`manifest.py`, protocol in docs/checkpointing.md):
integrity manifests (`build_manifest`/`write_manifest`), commit markers
(`write_done`/`parse_done`), and `verify_checkpoint` — also a CLI:
`python -m paddle_tpu.distributed.checkpoint verify <dir>`. The atomic
tmp+rename commit protocol itself lives in `fleet.elastic.ElasticManager`.
"""
from __future__ import annotations

import os
from typing import Any, Dict, Optional

import numpy as np
import jax

from ... import observability as telemetry
from ...core.tensor import Parameter, Tensor
from .manifest import (CheckpointIntegrityError, DONE_NAME,  # noqa: F401
                       MANIFEST_NAME, VerifyResult, array_checksum,
                       build_manifest, describe_arrays, parse_done,
                       read_manifest, verify_checkpoint, write_done,
                       write_manifest)

__all__ = [
    "save_state_dict", "load_state_dict", "load_state_dict_raw",
    # durability layer (manifest.py; protocol in docs/checkpointing.md)
    "MANIFEST_NAME", "DONE_NAME", "CheckpointIntegrityError",
    "VerifyResult", "array_checksum", "describe_arrays",
    "build_manifest", "write_manifest", "read_manifest", "write_done",
    "parse_done", "verify_checkpoint", "flat_arrays",
]

_M_CKPT_OPS = telemetry.counter(
    "pdt_checkpoint_ops_total",
    "Completed checkpoint operations, by direction.", ("op",))
_M_CKPT_BYTES = telemetry.counter(
    "pdt_checkpoint_bytes_total",
    "Array bytes moved through checkpoint operations, by direction.",
    ("op",))


def _nbytes(vals) -> int:
    return int(sum(getattr(v, "nbytes", 0) for v in vals
                   if v is not None))


def _flatten(d, prefix=""):
    out = {}
    for k, v in d.items():
        key = f"{prefix}.{k}" if prefix else str(k)
        if isinstance(v, dict):
            out.update(_flatten(v, key))
        else:
            out[key] = v
    return out


def _unflatten_into(flat, d, prefix=""):
    for k, v in d.items():
        key = f"{prefix}.{k}" if prefix else str(k)
        if isinstance(v, dict):
            _unflatten_into(flat, v, key)
        elif key in flat:
            d[k] = flat[key]
    return d


def _values(flat):
    vals = {}
    for k, t in flat.items():
        if isinstance(t, Tensor):
            vals[k] = t._value
        elif t is not None:
            vals[k] = jax.numpy.asarray(np.asarray(t))
    return vals


def flat_arrays(state_dict: Dict[str, Any]) -> Dict[str, Any]:
    """Flatten a (possibly nested) state_dict of Tensors/arrays into the
    {dotted_key: jax.Array} form the on-disk checkpoint uses — the same
    keys `save_state_dict` writes and manifests describe."""
    return _values(_flatten(state_dict))


def save_state_dict(state_dict: Dict[str, Any], path: str,
                    process_group=None, coordinator_rank: int = 0,
                    async_save: bool = False):
    """Write a (possibly nested) state_dict of Tensors/arrays as a sharded
    orbax checkpoint at `path`. Sharded tensors write only their owned
    shards per host."""
    # chaos site: fires BEFORE any byte is written, so an injected save
    # failure leaves no partial checkpoint (the .done marker protocol in
    # fleet.elastic then ignores interrupted step directories)
    from ...utils.faults import fault_point
    path = os.path.abspath(path)
    with telemetry.span("checkpoint.save", path=path,
                        async_save=bool(async_save)):
        fault_point("checkpoint.save")
        import orbax.checkpoint as ocp
        flat = _values(_flatten(state_dict))
        ckptr = (ocp.AsyncCheckpointer(ocp.PyTreeCheckpointHandler())
                 if async_save else ocp.PyTreeCheckpointer())
        ckptr.save(path, flat, force=True)
        # chaos site: fires AFTER this group's bytes are on disk — an
        # injected write failure mid-protocol leaves a torn multi-group
        # checkpoint (some groups written, no manifest), which is what
        # resume-time verification must catch. An async save has only
        # been DISPATCHED here, so the site fires in
        # wait_until_finished() instead, once the bytes actually land.
        nbytes = _nbytes(flat.values())
        if not async_save:
            fault_point("checkpoint.write")
            _M_CKPT_OPS.inc(op="save")
            _M_CKPT_BYTES.inc(nbytes, op="save")
    if async_save:
        # an async save has only been DISPATCHED here — counting it as
        # completed would report a save that may still fail in flight.
        # Count when the caller's wait_until_finished() returns clean.
        orig_wait = ckptr.wait_until_finished

        def _wait_and_count(*a, _done=[False], **kw):
            out = orig_wait(*a, **kw)
            if not _done[0]:
                fault_point("checkpoint.write")
                _done[0] = True
                _M_CKPT_OPS.inc(op="save")
                _M_CKPT_BYTES.inc(nbytes, op="save")
            return out

        ckptr.wait_until_finished = _wait_and_count
        return ckptr  # caller may wait_until_finished()
    return None


def load_state_dict(state_dict: Dict[str, Any], path: str,
                    process_group=None, coordinator_rank: int = 0):
    """Restore `path` INTO state_dict (in place): every Tensor receives the
    checkpoint values resharded to that tensor's CURRENT sharding — the
    cross-mesh reshard plan of the reference, done by tensorstore reads."""
    from ...utils.faults import fault_point
    import orbax.checkpoint as ocp
    path = os.path.abspath(path)
    with telemetry.span("checkpoint.load", path=path):
        fault_point("checkpoint.load")
        flat_t = _flatten(state_dict)
        restore_args = {}
        targets = {}
        for k, t in flat_t.items():
            if isinstance(t, Tensor):
                v = t._value
                sharding = getattr(v, "sharding", None)
                restore_args[k] = ocp.ArrayRestoreArgs(
                    sharding=sharding, global_shape=tuple(v.shape),
                    dtype=v.dtype)
                targets[k] = t
        ckptr = ocp.PyTreeCheckpointer()
        restored = ckptr.restore(
            path, args=ocp.args.PyTreeRestore(restore_args=restore_args))
        for k, arr in restored.items():
            if k in targets and arr is not None:
                targets[k]._value = arr
        _M_CKPT_OPS.inc(op="load")
        _M_CKPT_BYTES.inc(_nbytes(restored.values()), op="load")
    return state_dict


def load_state_dict_raw(path: str) -> Dict[str, Any]:
    """Restore a checkpoint WITHOUT a target structure: returns the flat
    {dotted_key: jax.Array} dict as saved. For consumers whose state is
    created lazily (optimizer accumulators) — feed into set_state_dict."""
    from ...utils.faults import fault_point
    import orbax.checkpoint as ocp
    path = os.path.abspath(path)
    with telemetry.span("checkpoint.load", path=path, raw=True):
        fault_point("checkpoint.load")
        ckptr = ocp.PyTreeCheckpointer()
        restored = ckptr.restore(path)
        _M_CKPT_OPS.inc(op="load")
        _M_CKPT_BYTES.inc(_nbytes(restored.values()), op="load")
    return restored
