"""Checkpoint maintenance CLI.

    python -m paddle_tpu.distributed.checkpoint verify <dir> [--rehash]

`<dir>` is either one committed checkpoint (a `step_N` directory with a
MANIFEST.json) or a checkpoint root — then every complete `step_*`
under it is verified. Exit code 0 iff every verified checkpoint is
clean; 1 otherwise (also when the root holds no complete checkpoint —
"nothing to resume from" is a failure for an operator asking whether a
job can restart). Installed as `paddle-tpu-checkpoint` too.
"""
from __future__ import annotations

import argparse
import os
import sys
from typing import List

from . import DONE_NAME, MANIFEST_NAME, parse_done, verify_checkpoint


def _targets(path: str) -> List[str]:
    if (os.path.exists(os.path.join(path, MANIFEST_NAME))
            or parse_done(os.path.join(path, DONE_NAME)) is not None):
        return [path]
    from ..fleet.elastic import complete_checkpoints
    return [p for _, p in complete_checkpoints(path)]


def _cmd_verify(args) -> int:
    targets = _targets(args.dir)
    if not targets:
        print(f"no checkpoint with a {MANIFEST_NAME} and no complete "
              f"step_* checkpoints under {args.dir!r}", file=sys.stderr)
        return 1
    rc = 0
    for path in targets:
        if not os.path.exists(os.path.join(path, MANIFEST_NAME)):
            # pre-manifest checkpoint: resume() loads these unverified
            # rather than quarantining them for predating the protocol
            # — mirror that here instead of reporting CORRUPT
            print(f"{'LEGACY':8s} {path}  (no {MANIFEST_NAME}; "
                  "pre-protocol checkpoint, loadable but unverifiable)")
            continue
        res = verify_checkpoint(path, rehash=args.rehash)
        status = "OK" if res.ok else "CORRUPT"
        mode = "rehash" if args.rehash else "light"
        print(f"{status:8s} {path}  (step={res.step}, "
              f"{res.arrays_checked} arrays, {mode})")
        for err in res.errors:
            print(f"         - {err}")
        if not res.ok:
            rc = 1
    return rc


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="paddle_tpu.distributed.checkpoint",
        description="Durable-checkpoint maintenance "
                    "(docs/checkpointing.md)")
    sub = p.add_subparsers(dest="cmd", required=True)
    v = sub.add_parser(
        "verify", help="verify integrity manifests of one checkpoint "
                       "or every complete checkpoint under a root")
    v.add_argument("dir", help="step_N directory or checkpoint root")
    v.add_argument("--rehash", action="store_true",
                   help="also re-hash array contents against the "
                        "manifest checksums (reads all data; catches "
                        "silent bit flips, not just torn writes)")
    v.set_defaults(fn=_cmd_verify)
    args = p.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
