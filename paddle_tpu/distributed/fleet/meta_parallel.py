"""Megatron-style parallel layers + pipeline segmentation.
≙ reference «.../fleet/layers/mpu/mp_layers.py» (ColumnParallelLinear,
RowParallelLinear, VocabParallelEmbedding, ParallelCrossEntropy),
«.../fleet/meta_parallel/parallel_layers/pp_layers.py» (PipelineLayer,
LayerDesc) — SURVEY.md §2.3 TP/PP rows.

TPU-native: a TP layer is its weight's GSPMD placement. Column = shard the
output dim over 'mp'; Row = shard the input dim; XLA then partitions the
matmuls and inserts the identity/allreduce pattern the reference codes by
hand (c_identity fwd / allreduce bwd etc.). No mp_ops module is needed —
those collectives exist only inside the compiled program."""
from __future__ import annotations

import math
from typing import Optional

import numpy as np
import jax
import jax.numpy as jnp

import paddle_tpu as paddle
from ...core.tensor import Parameter, Tensor, apply
from ...nn import functional as F
from ...nn import initializer as I
from ...nn.layer.layers import Layer, LayerList
from ..mesh import (ProcessMesh, Replicate, Shard, get_mesh, shard_tensor,
                    shard_constraint)


def _mp_mesh():
    from . import get_hybrid_communicate_group, fleet_initialized
    if fleet_initialized():
        return get_hybrid_communicate_group().mesh
    return get_mesh()


def _placements(mesh, **axis_to_dim):
    pl = [Replicate() for _ in mesh.dim_names]
    for axis, dim in axis_to_dim.items():
        if axis in mesh.dim_names:
            pl[mesh.dim_names.index(axis)] = Shard(dim)
    return pl


class ColumnParallelLinear(Layer):
    """weight (in, out) with out sharded over 'mp'.
    ≙ mp_layers.ColumnParallelLinear [U]."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=True, gather_output=True, fuse_matmul_bias=False,
                 mp_group=None, name=None):
        super().__init__()
        self.in_features = in_features
        self.out_features = out_features
        self.gather_output = gather_output
        self.weight = self.create_parameter(
            (in_features, out_features), attr=weight_attr,
            default_initializer=I.XavierNormal())
        self.bias = self.create_parameter(
            (out_features,), is_bias=True) if has_bias else None
        mesh = _mp_mesh()
        if mesh is not None:
            w = shard_tensor(self.weight, mesh, _placements(mesh, mp=1))
            self.weight._value = w._value
            self.weight.dist_attr = w.dist_attr
            if self.bias is not None:
                b = shard_tensor(self.bias, mesh, _placements(mesh, mp=0))
                self.bias._value = b._value
                self.bias.dist_attr = b.dist_attr

    def forward(self, x):
        out = F.linear(x, self.weight, self.bias)
        mesh = _mp_mesh()
        if mesh is not None and not self.gather_output:
            # keep activation sharded on the feature dim
            axes = [None] * (out.ndim - 1) + ["mp"]
            out_v = shard_constraint(out._value, *axes, mesh=mesh)
            res = Tensor(out_v, stop_gradient=out.stop_gradient)
            res._node, res._out_index = out._node, out._out_index
            return res
        return out


class RowParallelLinear(Layer):
    """weight (in, out) with in sharded over 'mp'.
    ≙ mp_layers.RowParallelLinear [U]."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=True, input_is_parallel=False,
                 fuse_matmul_bias=False, mp_group=None, name=None):
        super().__init__()
        self.in_features = in_features
        self.out_features = out_features
        self.input_is_parallel = input_is_parallel
        self.weight = self.create_parameter(
            (in_features, out_features), attr=weight_attr,
            default_initializer=I.XavierNormal())
        self.bias = self.create_parameter(
            (out_features,), is_bias=True) if has_bias else None
        mesh = _mp_mesh()
        if mesh is not None:
            w = shard_tensor(self.weight, mesh, _placements(mesh, mp=0))
            self.weight._value = w._value
            self.weight.dist_attr = w.dist_attr

    def forward(self, x):
        return F.linear(x, self.weight, self.bias)


class VocabParallelEmbedding(Layer):
    """embedding table sharded over vocab dim.
    ≙ mp_layers.VocabParallelEmbedding [U]."""

    def __init__(self, num_embeddings, embedding_dim, weight_attr=None,
                 mp_group=None, name=None):
        super().__init__()
        self.num_embeddings = num_embeddings
        self.embedding_dim = embedding_dim
        self.weight = self.create_parameter(
            (num_embeddings, embedding_dim), attr=weight_attr,
            default_initializer=I.Normal(0.0, 0.02))
        mesh = _mp_mesh()
        if mesh is not None:
            w = shard_tensor(self.weight, mesh, _placements(mesh, mp=0))
            self.weight._value = w._value
            self.weight.dist_attr = w.dist_attr

    def forward(self, x):
        return F.embedding(x, self.weight)


class ParallelCrossEntropy(Layer):
    """CE over class-dim-sharded logits; the partial-softmax allreduce the
    reference hand-codes is emitted by XLA from the sharding.
    ≙ mp_layers.ParallelCrossEntropy [U]."""

    def __init__(self, mp_group=None, name=None, ignore_index=-100):
        super().__init__()
        self.ignore_index = ignore_index

    def forward(self, input, label):
        return F.cross_entropy(input, label, reduction="none",
                               ignore_index=self.ignore_index)


# -- sequence parallel utils -------------------------------------------------
class ColumnSequenceParallelLinear(ColumnParallelLinear):
    """≙ «.../fleet/utils/sequence_parallel_utils.py» [U]: SP activations are
    sequence-dim sharded outside TP regions; with GSPMD this is an input
    constraint, the all-gather/reduce-scatter pair is compiler-inserted."""

    def forward(self, x):
        mesh = _mp_mesh()
        if mesh is not None:
            axes = [None, "mp"] + [None] * (x.ndim - 2)
            xv = shard_constraint(x._value, *axes, mesh=mesh)
            t = Tensor(xv, stop_gradient=x.stop_gradient)
            t._node, t._out_index = x._node, x._out_index
            x = t
        return super().forward(x)


class RowSequenceParallelLinear(RowParallelLinear):
    def forward(self, x):
        out = super().forward(x)
        mesh = _mp_mesh()
        if mesh is not None:
            axes = [None, "mp"] + [None] * (out.ndim - 2)
            ov = shard_constraint(out._value, *axes, mesh=mesh)
            t = Tensor(ov, stop_gradient=out.stop_gradient)
            t._node, t._out_index = out._node, out._out_index
            return t
        return out


def mark_as_sequence_parallel_parameter(param):
    param.sequence_parallel = True


def register_sequence_parallel_allreduce_hooks(model, *a, **k):
    """No-op on TPU: SP grad sync is inside the compiled program."""
    return model


# -- pipeline segmentation ---------------------------------------------------
class LayerDesc:
    """≙ pp_layers.LayerDesc — deferred layer construction for stage
    assignment."""

    def __init__(self, layer_cls, *args, **kwargs):
        self.layer_cls = layer_cls
        self.args = args
        self.kwargs = kwargs

    def build_layer(self):
        return self.layer_cls(*self.args, **self.kwargs)


class SharedLayerDesc(LayerDesc):
    """≙ pp_layers.SharedLayerDesc — embedding/output weight sharing across
    stages. With GSPMD + one program there is one parameter object; sharing
    is simple aliasing."""

    def __init__(self, key, layer_cls, forward_func=None, shared_weight_attr
                 ="weight", *args, **kwargs):
        super().__init__(layer_cls, *args, **kwargs)
        self.layer_name = key
        self.forward_func = forward_func
        self.shared_weight_attr = shared_weight_attr


class PipelineLayer(Layer):
    """≙ pp_layers.PipelineLayer: a sequence of LayerDescs segmented into
    pp stages. In this framework every stage's params carry a 'pp'-axis
    placement; the schedule (1F1B over microbatches) is applied by
    PipelineParallel.train_batch via shard_map (SURVEY.md §7 stage 7)."""

    def __init__(self, layers, num_stages=None, topology=None, loss_fn=None,
                 seg_method="uniform", recompute_interval=0, **kwargs):
        super().__init__()
        self._loss_fn = loss_fn
        descs = list(layers)
        built = []
        shared = {}
        for d in descs:
            if isinstance(d, SharedLayerDesc):
                if d.layer_name in shared:
                    built.append(("shared", d, shared[d.layer_name]))
                else:
                    layer = d.build_layer()
                    shared[d.layer_name] = layer
                    built.append(("layer", d, layer))
            elif isinstance(d, LayerDesc):
                built.append(("layer", d, d.build_layer()))
            else:
                built.append(("layer", None, d))
        self.run_funcs = []
        self.layers = LayerList([b[2] for b in built
                                 if b[0] == "layer"])
        self._built = built
        from . import fleet_initialized, get_hybrid_communicate_group
        self.num_stages = num_stages
        if num_stages is None and fleet_initialized():
            self.num_stages = get_hybrid_communicate_group() \
                .get_pipe_parallel_world_size()
        self.num_stages = self.num_stages or 1
        self._segment()

    def _segment(self):
        """Uniform segmentation of layers into stages (≙ seg_method
        'uniform'; 'layer:' prefix counting deferred)."""
        n = len(self._built)
        per = math.ceil(n / self.num_stages)
        self.stage_of = [min(i // per, self.num_stages - 1)
                         for i in range(n)]

    def get_stage_layers(self, stage: int):
        return [b[2] for b, s in zip(self._built, self.stage_of)
                if s == stage and b[0] == "layer"]

    def forward(self, x):
        for kind, desc, layer in self._built:
            if kind == "shared" and desc.forward_func is not None:
                x = desc.forward_func(layer, x)
            else:
                x = layer(x)
        return x


class PipelineParallel(Layer):
    """≙ «.../fleet/meta_parallel/pipeline_parallel.py» PipelineParallel.
    train_batch keeps the reference's eager micro-batch-loop API. The
    TRUE 1F1B SPMD schedule (S-bounded activation residency) lives in
    `distributed.fleet.pipeline.pipeline_1f1b` and is what
    `models.llama_pipe.LlamaForCausalLMPipe` runs for fused training —
    use that path for real pipelined workloads."""

    def __init__(self, layers, hcg=None, strategy=None):
        super().__init__()
        self._layers = layers
        self._strategy = strategy
        self.accumulate_steps = (strategy.pipeline_configs.get(
            "accumulate_steps", 1) if strategy else 1)

    def forward(self, *args, **kwargs):
        return self._layers(*args, **kwargs)

    def train_batch(self, data, optimizer, lr_scheduler=None, scaler=None):
        inputs, labels = data
        micro = self.accumulate_steps
        bs = inputs.shape[0]
        mb = max(bs // micro, 1)
        total = None
        for i in range(0, bs, mb):
            x = inputs[i:i + mb]
            y = labels[i:i + mb]
            out = self._layers(x)
            loss_fn = getattr(self._layers, "_loss_fn", None)
            loss = loss_fn(out, y) if loss_fn else F.cross_entropy(out, y)
            scaled = loss / micro if micro > 1 else loss
            if scaler is not None:
                scaler.scale(scaled).backward()
            else:
                scaled.backward()
            total = float(loss) if total is None else total + float(loss)
        if scaler is not None:
            scaler.step(optimizer)
        else:
            optimizer.step()
        optimizer.clear_grad()
        if lr_scheduler is not None:
            lr_scheduler.step()
        return paddle.to_tensor(total / max(micro, 1))
