"""fleet — hybrid-parallel API. ≙ reference «python/paddle/distributed/fleet/»
(SURVEY.md §2.3/§3.2): `fleet.init(strategy)`, `DistributedStrategy`,
`HybridCommunicateGroup`, `distributed_model`, `distributed_optimizer`.

TPU-native: instead of building NCCL process groups per axis, `init` builds
ONE jax mesh with named axes (pp, dp, sharding, sep, mp) — sub-"groups" are
just axis names; DP/sharding/TP/SP compose as GSPMD shardings inside the
single compiled train step, and 1F1B pipeline runs as a shard_map schedule
(meta_parallel.PipelineParallel)."""
from __future__ import annotations

from typing import Optional

import numpy as np
import jax

from ...core.tensor import Parameter, Tensor
from ..mesh import (ProcessMesh, Replicate, Shard, create_mesh, get_mesh,
                    set_mesh, shard_tensor)
from ..collective import Group
from ..random_ import get_rng_state_tracker, model_parallel_random_seed  # noqa: F401
from . import meta_parallel  # noqa: F401
from .meta_parallel import (ColumnParallelLinear, RowParallelLinear,  # noqa: F401
                            VocabParallelEmbedding, ParallelCrossEntropy,
                            PipelineLayer, LayerDesc, SharedLayerDesc,
                            PipelineParallel)


class DistributedStrategy:
    """≙ fleet.base.distributed_strategy.DistributedStrategy (protobuf of
    toggles in the reference [U]); a plain typed config here."""

    def __init__(self):
        self.hybrid_configs = {
            "dp_degree": 1,
            "mp_degree": 1,
            "pp_degree": 1,
            "sharding_degree": 1,
            "sep_degree": 1,
        }
        self.amp = False
        self.amp_configs = {}
        self.recompute = False
        self.recompute_configs = {}
        self.gradient_merge = False
        self.gradient_merge_configs = {"k_steps": 1}
        self.sharding = False
        self.sharding_configs = {}
        self.pipeline_configs = {"accumulate_steps": 1}
        self.find_unused_parameters = False

    def __repr__(self):
        return f"DistributedStrategy({self.hybrid_configs})"


class HybridCommunicateGroup:
    """≙ «.../fleet/base/topology.py» HybridCommunicateGroup: axis handles
    over the one global mesh."""

    AXES = ("pp", "dp", "sharding", "sep", "mp")

    def __init__(self, strategy: DistributedStrategy):
        cfg = strategy.hybrid_configs
        degrees = {
            "pp": cfg.get("pp_degree", 1),
            "dp": cfg.get("dp_degree", 1),
            "sharding": cfg.get("sharding_degree", 1),
            "sep": cfg.get("sep_degree", 1),
            "mp": cfg.get("mp_degree", 1),
        }
        n_dev = len(jax.devices())
        used = int(np.prod(list(degrees.values())))
        if used > n_dev:
            raise ValueError(
                f"hybrid degrees {degrees} need {used} devices, "
                f"have {n_dev}")
        # absorb leftover devices into dp
        if used < n_dev and n_dev % used == 0 and degrees["dp"] == 1 \
                and cfg.get("dp_degree", 1) == 1:
            degrees["dp"] = n_dev // used
        self.degrees = degrees
        self.mesh = create_mesh({a: degrees[a] for a in self.AXES})
        set_mesh(self.mesh)

    # group handles (axis views)
    def get_data_parallel_group(self) -> Group:
        return Group(self.mesh, "dp")

    def get_model_parallel_group(self) -> Group:
        return Group(self.mesh, "mp")

    def get_pipe_parallel_group(self) -> Group:
        return Group(self.mesh, "pp")

    def get_sharding_parallel_group(self) -> Group:
        return Group(self.mesh, "sharding")

    def get_sep_parallel_group(self) -> Group:
        return Group(self.mesh, "sep")

    def get_data_parallel_world_size(self) -> int:
        return self.degrees["dp"]

    def get_model_parallel_world_size(self) -> int:
        return self.degrees["mp"]

    def get_pipe_parallel_world_size(self) -> int:
        return self.degrees["pp"]

    def get_sharding_parallel_world_size(self) -> int:
        return self.degrees["sharding"]

    def get_sep_parallel_world_size(self) -> int:
        return self.degrees["sep"]

    # single-controller: ranks are global views
    def get_data_parallel_rank(self) -> int:
        return 0

    def get_model_parallel_rank(self) -> int:
        return 0

    def get_stage_id(self) -> int:
        return 0

    def topology(self):
        return self


_hcg: Optional[HybridCommunicateGroup] = None
_strategy: Optional[DistributedStrategy] = None


def init(is_collective: bool = True, strategy: DistributedStrategy | None = None,
         role_maker=None):
    """≙ fleet.init (SURVEY.md §3.2)."""
    global _hcg, _strategy
    from .. import parallel
    parallel.init_parallel_env()
    _strategy = strategy or DistributedStrategy()
    _hcg = HybridCommunicateGroup(_strategy)
    return _hcg


def get_hybrid_communicate_group() -> HybridCommunicateGroup:
    if _hcg is None:
        raise RuntimeError("call fleet.init() first")
    return _hcg


def fleet_initialized() -> bool:
    return _hcg is not None


def worker_index() -> int:
    return jax.process_index()


def worker_num() -> int:
    return jax.process_count()


def distributed_model(model):
    """≙ fleet.distributed_model: place every parameter on the mesh.
    TP layers (Column/RowParallelLinear…) carry their own placements;
    everything else is replicated over mp/pp and (ZeRO) sharded over the
    sharding axis on dim 0 when divisible."""
    hcg = get_hybrid_communicate_group()
    mesh = hcg.mesh
    shard_deg = hcg.get_sharding_parallel_world_size()
    for name, p in model.named_parameters():
        if getattr(p, "dist_attr", None) is not None:
            continue  # TP layer already annotated
        placements = [Replicate() for _ in mesh.dim_names]
        if shard_deg > 1 and p._value.ndim > 0 and \
                p._value.shape[0] % shard_deg == 0:
            placements[mesh.dim_names.index("sharding")] = Shard(0)
        sharded = shard_tensor(p, mesh, placements)
        p._value = sharded._value
        p.dist_attr = sharded.dist_attr
    return model


def distributed_optimizer(optimizer, strategy=None):
    """≙ fleet.distributed_optimizer → HybridParallelOptimizer: optimizer
    state inherits each parameter's placement (ZeRO-1 falls out of the
    sharding-axis placement + GSPMD)."""
    orig_acc = optimizer._acc

    def _acc(name, p, init=None, dtype=None):
        store = optimizer._accumulators.setdefault(name, {})
        k = id(p)
        created = k not in store
        out = orig_acc(name, p, init=init, dtype=dtype)
        if created and hasattr(p._value, "sharding") and \
                not isinstance(out, jax.core.Tracer):
            try:
                out = jax.device_put(out, p._value.sharding)
                store[k] = out
            except Exception:
                pass
        return out
    optimizer._acc = _acc

    orig_master = optimizer._master

    def _master(p):
        k = id(p)
        created = k not in optimizer._master_weights
        out = orig_master(p)
        if created and hasattr(p._value, "sharding") and \
                not isinstance(out, jax.core.Tracer):
            try:
                out = jax.device_put(out, p._value.sharding)
                optimizer._master_weights[k] = out
            except Exception:
                pass
        return out
    optimizer._master = _master
    return optimizer


class DataParallel:
    """≙ paddle.DataParallel wrapper + C++ Reducer
    («.../collective/reducer.cc» [U]). On TPU there is no bucketed
    allreduce to write: with params replicated over dp and the batch
    sharded over dp, XLA's gradient psum IS the fused, overlapped
    allreduce. This wrapper shards inputs and places params."""

    def __init__(self, layers, strategy=None, comm_buffer_size=25,
                 last_comm_buffer_size=1, find_unused_parameters=False,
                 group=None):
        self._layers = layers
        if not fleet_initialized():
            init()
        distributed_model(layers)
        self.mesh = get_hybrid_communicate_group().mesh

    def __call__(self, *args, **kwargs):
        sharded = []
        for a in args:
            if isinstance(a, Tensor) and a.ndim > 0:
                placements = [Replicate() for _ in self.mesh.dim_names]
                placements[self.mesh.dim_names.index("dp")] = Shard(0)
                sharded.append(shard_tensor(a, self.mesh, placements,
                                            stop_gradient=a.stop_gradient))
            else:
                sharded.append(a)
        return self._layers(*sharded, **kwargs)

    def __getattr__(self, name):
        return getattr(self._layers, name)

    def state_dict(self, *a, **k):
        return self._layers.state_dict(*a, **k)

    def set_state_dict(self, *a, **k):
        return self._layers.set_state_dict(*a, **k)
