"""SPMD pipeline parallelism — the TPU-native replacement for the
reference's PipelineParallel.train_batch 1F1B schedule
(«.../fleet/meta_parallel/pipeline_parallel.py», p2p_communication.py —
SURVEY.md §2.3 PP row, §7 hard part #1).

Design (circular pipelined scan, scaling-book style): stage parameters are
STACKED along a leading (n_stages,) dim sharded over the `pp` mesh axis;
inside one `shard_map` every device runs the same `lax.scan` over
M + S - 1 ticks. At tick t, device s computes microbatch t - s; activations
hop stage→stage+1 through a single `ppermute` per tick (collective_permute
over ICI). The reference's send/recv meta-negotiation, batched isend/irecv
and per-stage Python scheduling all collapse into this one compiled loop.

Backward is `jax.grad` through the scan: XLA replays the schedule in
reverse (the ppermute transposes to the opposite rotation), which yields
GPipe-equivalent ordering; activation memory is bounded by rematerializing
each tick (`jax.checkpoint` around the stage body) so only the per-tick
carry survives — the 1F1B memory profile without hand-written scheduling.
"""
from __future__ import annotations

import functools
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

# version-conditional shard_map kwargs (check_vma vs check_rep) live in
# collective.py; reuse them so the older-jax fallback actually works here
from ..collective import _SM_KW, shard_map as _shard_map

from ..mesh import ProcessMesh

__all__ = ["pipeline_forward", "stack_stage_params"]


def stack_stage_params(per_stage_params):
    """[pytree per stage] -> one pytree with leading (S,) dim (to be
    sharded Shard(0) over 'pp')."""
    return jax.tree_util.tree_map(
        lambda *leaves: jnp.stack(leaves, axis=0), *per_stage_params)


def pipeline_forward(stage_fn: Callable, stacked_params, x, mesh: ProcessMesh,
                     num_microbatches: int, axis: str = "pp",
                     remat: bool = True, extra_args: tuple = (),
                     param_specs=None, x_spec=None):
    """Run the pipelined forward: y = stage_{S-1}(...stage_0(x)).

    stage_fn(params_one_stage, activation, *extra) -> activation; must keep
    the activation shape (classic transformer-stack property).
    stacked_params: pytree, every leaf (S, ...) — sharded over `axis`.
    x: (B, ...) global input; split into M = num_microbatches along dim 0.
    extra_args: replicated side inputs every stage sees (rope tables etc.).
    param_specs: optional pytree of PartitionSpec (leading entry must be
    `axis`) to compose TP/ZeRO shardings inside the pipeline — stage_fn then
    sees LOCAL shards and is responsible for its own collectives (psum over
    'mp' etc.; every mesh axis name is bound inside). x_spec: optional
    PartitionSpec for one microbatch (e.g. P('dp', None, None) to keep the
    batch dp-sharded through the pipeline).
    Returns y: (B, ...) final-stage output. Differentiable.
    """
    s_count = mesh.get_dim_size(axis)
    m = num_microbatches
    b = x.shape[0]
    assert b % m == 0, f"batch {b} not divisible by microbatches {m}"
    mb = b // m
    xs = x.reshape(m, mb, *x.shape[1:])
    ticks = m + s_count - 1

    body = stage_fn
    if remat:
        body = jax.checkpoint(stage_fn)

    def local_fn(params_local, xs_local, *extra):
        # params_local leaves: (1, ...) — this device's stage; squeeze
        params1 = jax.tree_util.tree_map(lambda l: l[0], params_local)
        s = jax.lax.axis_index(axis)
        perm = [(j, (j + 1) % s_count) for j in range(s_count)]

        def tick(carry, t):
            state, buf = carry
            # stage 0 ingests microbatch t (clamped; inactive ticks are
            # overwritten later), others take the ppermuted activation
            x_t = jax.lax.dynamic_index_in_dim(
                xs_local, jnp.clip(t, 0, m - 1), 0, keepdims=False)
            inp = jnp.where(s == 0, x_t.astype(state.dtype), state)
            y = body(params1, inp, *extra)
            # last stage's tick-t output is microbatch t - (S-1)
            idx = t - (s_count - 1)
            idx_c = jnp.clip(idx, 0, m - 1)
            valid = (idx >= 0) & (idx < m)
            cur = jax.lax.dynamic_index_in_dim(buf, idx_c, 0,
                                               keepdims=False)
            upd = jnp.where(valid, y, cur)
            buf = jax.lax.dynamic_update_index_in_dim(buf, upd, idx_c, 0)
            state = jax.lax.ppermute(y, axis, perm)
            return (state, buf), None

        state0 = jnp.zeros_like(xs_local[0])
        buf0 = jnp.zeros_like(xs_local)
        (_, buf), _ = jax.lax.scan(tick, (state0, buf0),
                                   jnp.arange(ticks))
        # every device filled a buffer; only the last stage's is the real
        # output — replicate it with a masked psum
        sel = jnp.where(s == s_count - 1, 1.0, 0.0)
        return jax.lax.psum(buf * sel.astype(buf.dtype), axis)

    if param_specs is None:
        param_specs = jax.tree_util.tree_map(
            lambda l: P(axis, *([None] * (l.ndim - 1))), stacked_params)
    if x_spec is None:
        x_spec = P(*([None] * xs.ndim))
    else:
        # caller gives the per-microbatch activation spec; prepend the
        # microbatch dim
        x_spec = P(None, *tuple(x_spec))
    extra_specs = tuple(P(*([None] * jnp.asarray(e).ndim))
                        for e in extra_args)
    out = _shard_map(local_fn, mesh=mesh.jax_mesh,
                     in_specs=(param_specs, x_spec) + extra_specs,
                     out_specs=x_spec,
                     **_SM_KW)(stacked_params, xs, *extra_args)
    return out.reshape(b, *out.shape[2:])
