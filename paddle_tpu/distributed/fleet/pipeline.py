"""SPMD pipeline parallelism — the TPU-native replacement for the
reference's PipelineParallel.train_batch 1F1B schedule
(«.../fleet/meta_parallel/pipeline_parallel.py», p2p_communication.py —
SURVEY.md §2.3 PP row, §7 hard part #1).

Design (circular pipelined scan, scaling-book style): stage parameters are
STACKED along a leading (n_stages,) dim sharded over the `pp` mesh axis;
inside one `shard_map` every device runs the same `lax.scan` over
M + S - 1 ticks. At tick t, device s computes microbatch t - s; activations
hop stage→stage+1 through a single `ppermute` per tick (collective_permute
over ICI). The reference's send/recv meta-negotiation, batched isend/irecv
and per-stage Python scheduling all collapse into this one compiled loop.

TWO schedules are provided:

* `pipeline_forward` — forward pipelining with backward = `jax.grad`
  through the scan: XLA replays the schedule in reverse (the ppermute
  transposes to the opposite rotation), a GPipe-with-remat profile
  (per-tick `jax.checkpoint` bounds residuals to one activation per
  tick, so the stash grows with the microbatch count M). Supports
  interleaved virtual stages (`virtual_chunks`), including M > S via
  sequential rounds.
* `pipeline_1f1b` — TRUE 1F1B (≙ the reference's
  `PipelineParallel.train_batch` steady-state schedule): ONE fused
  forward+backward scan under `jax.custom_vjp`. Each device alternates
  F and B slots on opposite parities — F(i, s) at slot s + 2i,
  B(i, s) at slot 2S-1-s + 2i, total 2(M+S-1) slots, the canonical
  1F1B timing — and keeps a circular stash of at most S stage-input
  activations (the in-flight count at stage s is S-s). Because the
  scan is the *manually written* backward, XLA saves nothing per tick:
  activation residency is ∝ S and independent of M, which is exactly
  the 1F1B memory profile the GPipe path lacks.

Output handling: by default every device returns the (M, mb, ...) buffer
and the last stage's copy is broadcast with a one-hop `ppermute` fan-out
(cheaper than the old masked psum: no ring reduction, pure
collective-permute traffic). Passing `reduce_fn` (e.g. the LM head + loss)
collapses each microbatch's output to a scalar ON the last stage, so the
cross-stage broadcast is O(M) scalars and the big buffer never exists —
use this for training steps.
"""
from __future__ import annotations

import functools
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

# version-conditional shard_map kwargs (check_vma vs check_rep) live in
# collective.py; reuse them so the older-jax fallback actually works here
from ..collective import _SM_KW, shard_map as _shard_map

from ..mesh import ProcessMesh

__all__ = ["pipeline_forward", "pipeline_1f1b", "stack_stage_params"]


def stack_stage_params(per_stage_params):
    """[pytree per stage] -> one pytree with leading (S,) dim (to be
    sharded Shard(0) over 'pp')."""
    return jax.tree_util.tree_map(
        lambda *leaves: jnp.stack(leaves, axis=0), *per_stage_params)


def pipeline_forward(stage_fn: Callable, stacked_params, x, mesh: ProcessMesh,
                     num_microbatches: int, axis: str = "pp",
                     remat: bool = True, extra_args: tuple = (),
                     param_specs=None, x_spec=None,
                     reduce_fn: Optional[Callable] = None,
                     reduce_args: tuple = (), reduce_arg_specs=None,
                     reduce_mean_axes: tuple = (),
                     reduce_shape: tuple = (),
                     virtual_chunks: int = 1):
    """Run the pipelined forward: y = stage_{S-1}(...stage_0(x)).

    stage_fn(params_one_stage, activation, *extra) -> activation; must keep
    the activation shape (classic transformer-stack property).
    stacked_params: pytree, every leaf (S, ...) — sharded over `axis`.
    x: (B, ...) global input; split into M = num_microbatches along dim 0.
    extra_args: replicated side inputs every stage sees (rope tables etc.).
    param_specs: optional pytree of PartitionSpec (leading entry must be
    `axis`) to compose TP/ZeRO shardings inside the pipeline — stage_fn then
    sees LOCAL shards and is responsible for its own collectives (psum over
    'mp' etc.; every mesh axis name is bound inside). x_spec: optional
    PartitionSpec for one microbatch (e.g. P('dp', None, None) to keep the
    batch dp-sharded through the pipeline).
    reduce_fn(y_microbatch, microbatch_index, *reduce_args) -> scalar or
    small fixed-shape array (e.g. (loss_sum, token_count)): when given,
    each microbatch's final-stage output reduces immediately (the
    training-loss fusion) and the function returns the (M, *r) stacked
    reductions instead of activations — the (M, mb, ...) output buffer
    and its broadcast disappear, and a `lax.cond` skips the reduction
    compute on non-final stages (each device branches on its own stage
    id at runtime). reduce_args ride the shard_map with reduce_arg_specs
    (default replicated); reduce_mean_axes names mesh axes (e.g. 'dp')
    the reductions are pmean-averaged over when inputs are sharded there;
    reduce_shape declares reduce_fn's output shape (() = scalar) — it
    cannot be probed because reduce_fn may contain collectives only valid
    inside the shard_map.

    virtual_chunks=V > 1 enables the INTERLEAVED virtual pipeline
    (≙ reference `PipelineParallelWithInterleave`, SURVEY.md §2.3 PP
    row): stacked_params leaves are (S, V, ...) — device s owns the V
    model chunks {v*S + s}, each 1/V of a contiguous stage — and the
    activation makes V laps around the SAME ring (chunk v's stage S-1
    hands to chunk v+1's stage 0 via the one ppermute). Per-tick work
    drops to 1/V of a fat stage, shrinking the fill/drain bubble from
    (S-1) fat-stage units to ~(S-1)/V-ish: ticks go (M + S - 1) ->
    (M + V*S - 1) at 1/V the cost each. The conflict-free schedule
    handles S microbatches per lap; for M > S the pipeline runs
    ceil(M/S) sequential ROUNDS inside the same compiled scan (M must
    divide into rounds of S, i.e. M % S == 0), lifting the old M <= S
    constraint — gradient accumulation composes across rounds because
    the rounds are an outer `lax.scan` the autodiff sums over.
    Returns y: (B, ...) final-stage output, or (M, *reduce_shape) with
    reduce_fn. Differentiable.
    """
    s_count = mesh.get_dim_size(axis)
    m = num_microbatches
    v_chunks = int(virtual_chunks)
    b = x.shape[0]
    assert b % m == 0, f"batch {b} not divisible by microbatches {m}"
    rounds = 1
    m_round = m
    if v_chunks > 1 and m > s_count:
        if m % s_count != 0:
            raise ValueError(
                f"interleaved pipeline with num_microbatches ({m}) > pp "
                f"degree ({s_count}) needs microbatches divisible into "
                f"rounds of {s_count} (got {m} % {s_count} != 0)")
        rounds = m // s_count
        m_round = s_count
    mb = b // m
    xs = x.reshape(m, mb, *x.shape[1:])
    ticks = m_round + v_chunks * s_count - 1

    body = stage_fn
    if remat:
        body = jax.checkpoint(stage_fn)

    n_extra = len(extra_args)

    def local_fn(params_local, xs_local, *rest):
        extra = rest[:n_extra]
        r_args = rest[n_extra:]
        # params_local leaves: (1, ...) — this device's stage (or
        # (1, V, ...) — its V interleaved chunks); squeeze the shard dim
        params1 = jax.tree_util.tree_map(lambda l: l[0], params_local)
        s = jax.lax.axis_index(axis)
        perm = [(j, (j + 1) % s_count) for j in range(s_count)]

        def run_round(xs_round, r_off):
            def tick(carry, t):
                state, buf = carry
                if v_chunks > 1:
                    # interleave schedule: at tick t this device runs
                    # chunk v for microbatch t - v*S - s (at most one
                    # valid (m, v) since the round has <= S microbatches);
                    # garbage flows on inactive ticks, never recorded
                    rel = t - s
                    v = jnp.clip(rel // s_count, 0, v_chunks - 1)
                    m_i = rel - v * s_count
                    x_t = jax.lax.dynamic_index_in_dim(
                        xs_round, jnp.clip(m_i, 0, m_round - 1), 0,
                        keepdims=False)
                    inp = jnp.where((s == 0) & (v == 0),
                                    x_t.astype(state.dtype), state)
                    params_t = jax.tree_util.tree_map(
                        lambda l: jax.lax.dynamic_index_in_dim(
                            l, v, 0, keepdims=False), params1)
                else:
                    # stage 0 ingests microbatch t (clamped; inactive
                    # ticks are overwritten later), others take the
                    # ppermuted activation
                    x_t = jax.lax.dynamic_index_in_dim(
                        xs_round, jnp.clip(t, 0, m_round - 1), 0,
                        keepdims=False)
                    inp = jnp.where(s == 0, x_t.astype(state.dtype), state)
                    params_t = params1
                y = body(params_t, inp, *extra)
                # the final (stage, chunk)'s tick-t output is microbatch
                # t - (V-1)*S - (S-1)
                idx = t - (v_chunks - 1) * s_count - (s_count - 1)
                idx_c = jnp.clip(idx, 0, m_round - 1)
                valid = (idx >= 0) & (idx < m_round)
                if reduce_fn is not None:
                    # only the final stage's reduction matters; lax.cond
                    # lets every other device skip the (lm-head-sized)
                    # compute — the predicate is per-device so each takes
                    # its own branch
                    r = jax.lax.cond(
                        (s == s_count - 1) & valid,
                        lambda: reduce_fn(y, idx_c + r_off, *r_args)
                        .astype(buf.dtype).reshape(buf.shape[1:]),
                        lambda: buf[idx_c])
                    buf = buf.at[idx_c].set(r)
                else:
                    cur = jax.lax.dynamic_index_in_dim(buf, idx_c, 0,
                                                       keepdims=False)
                    upd = jnp.where(valid, y, cur)
                    buf = jax.lax.dynamic_update_index_in_dim(buf, upd,
                                                              idx_c, 0)
                state = jax.lax.ppermute(y, axis, perm)
                return (state, buf), None

            state0 = jnp.zeros_like(xs_round[0])
            buf0 = (jnp.zeros((m_round,) + tuple(reduce_shape),
                              jnp.float32)
                    if reduce_fn is not None else jnp.zeros_like(xs_round))
            (_, buf), _ = jax.lax.scan(tick, (state0, buf0),
                                       jnp.arange(ticks))
            return buf

        if rounds == 1:
            buf = run_round(xs_local, 0)
        else:
            xs_r = xs_local.reshape(rounds, m_round, *xs_local.shape[1:])

            def rbody(_, rx):
                r_idx, xs_round = rx
                return None, run_round(xs_round, r_idx * m_round)

            _, bufs = jax.lax.scan(
                rbody, None, (jnp.arange(rounds), xs_r))
            buf = bufs.reshape((m,) + bufs.shape[2:])
        # only the last stage holds the real output: recursive-doubling
        # broadcast from stage S-1 — ceil(log2 S) ppermute hops, each
        # device receives the buffer exactly once ((S-1)·|buf| total
        # traffic, no floating-point reduction; the old masked psum was a
        # full ring allreduce at ~2x the traffic plus adds)
        have = {s_count - 1}
        while len(have) < s_count:
            srcs = sorted(have)
            dsts = [d for d in range(s_count) if d not in have]
            pairs = list(zip(srcs, dsts))
            recv = jax.lax.ppermute(buf, axis, pairs)
            keep = jnp.isin(s, jnp.asarray(srcs))
            buf = jnp.where(keep, buf, recv)
            have |= {d for _, d in pairs}
        for ax in reduce_mean_axes:
            buf = jax.lax.pmean(buf, ax)
        return buf

    if param_specs is None:
        param_specs = jax.tree_util.tree_map(
            lambda l: P(axis, *([None] * (l.ndim - 1))), stacked_params)
    if x_spec is None:
        x_spec = P(*([None] * xs.ndim))
    else:
        # caller gives the per-microbatch activation spec; prepend the
        # microbatch dim
        x_spec = P(None, *tuple(x_spec))
    extra_specs = tuple(P(*([None] * jnp.asarray(e).ndim))
                        for e in extra_args)
    if reduce_arg_specs is None:
        reduce_arg_specs = tuple(P(*([None] * jnp.asarray(a).ndim))
                                 for a in reduce_args)
    out_spec = (P(*([None] * (1 + len(reduce_shape))))
                if reduce_fn is not None else x_spec)
    out = _shard_map(local_fn, mesh=mesh.jax_mesh,
                     in_specs=(param_specs, x_spec) + extra_specs
                     + tuple(reduce_arg_specs),
                     out_specs=out_spec,
                     **_SM_KW)(stacked_params, xs, *extra_args,
                               *reduce_args)
    if reduce_fn is not None:
        return out                      # (M,) per-microbatch scalars
    return out.reshape(b, *out.shape[2:])


# ---------------------------------------------------------------------------
# Interleaved 1F1B: static schedule tables (host-side simulation)
# ---------------------------------------------------------------------------
def _interleaved_1f1b_schedule(s_count: int, v_chunks: int, m: int):
    """Build the static slot tables for the interleaved 1F1B schedule
    (≙ reference `PipelineParallelWithInterleave`, SURVEY.md §2.3 PP).

    The Megatron-style per-rank op ORDER (microbatch groups of size
    min(S, m); warmup (S-s-1)*2 + (V-1)*G forwards, then 1F1B steady
    state, then drain) is fixed host-side, and the exact global TIMING is
    resolved by an event simulation: at each slot every rank executes its
    next op iff the op's inputs were produced at a strictly earlier slot
    (ppermute delivers at slot+1). The result is a set of numpy tables —
    one row per slot, one column per rank — that the compiled scan
    indexes with (tick, axis_index): no data-dependent control flow ever
    reaches XLA. Also computes the minimal ring-buffer depths (forward
    inbox, backward inbox, input stash) such that i -> i mod D never
    holds two live entries at once.

    Returns a dict of tables (T, S) int32/bool + depths + slot count.
    Any m is supported (the last microbatch group may be partial) —
    this lifts the GPipe interleave's m % S == 0 constraint.
    """
    import numpy as _np
    S, V = int(s_count), int(v_chunks)
    total = V * m
    G = min(S, m)

    groups = []
    st = 0
    while st < m:
        sz = min(G, m - st)
        groups.append((st, sz))
        st += sz

    f_order = [(v, g0 + j) for g0, gs in groups
               for v in range(V) for j in range(gs)]
    b_order = [(v, g0 + j) for g0, gs in groups
               for v in reversed(range(V)) for j in range(gs)]

    seqs = []
    for s in range(S):
        w = min((S - s - 1) * 2 + (V - 1) * G, total)
        seq = [("F",) + f_order[k] for k in range(w)]
        bi = 0
        for fi in range(w, total):
            seq.append(("F",) + f_order[fi])
            seq.append(("B",) + b_order[bi])
            bi += 1
        seq.extend(("B",) + b_order[k] for k in range(bi, total))
        seqs.append(seq)

    done_f, done_b = {}, {}
    ptr = [0] * S
    t = 0
    while any(ptr[s] < len(seqs[s]) for s in range(S)):
        executed = []
        for s in range(S):
            if ptr[s] >= len(seqs[s]):
                continue
            op, v, i = seqs[s][ptr[s]]
            u = v * S + s
            if op == "F":
                if u == 0:
                    ok = True
                else:
                    pv, ps = (v, s - 1) if s > 0 else (v - 1, S - 1)
                    tp = done_f.get((pv, i, ps))
                    ok = tp is not None and tp < t
            else:
                tf = done_f.get((v, i, s))
                ok = tf is not None and tf < t
                if ok and u != V * S - 1:
                    nv, ns = (v, s + 1) if s < S - 1 else (v + 1, 0)
                    tn = done_b.get((nv, i, ns))
                    ok = tn is not None and tn < t
            if ok:
                executed.append((s, op, v, i))
        if not executed:
            raise RuntimeError(
                f"interleaved 1F1B schedule deadlocked at slot {t} "
                f"(S={S}, V={V}, m={m}) — please report")
        for s, op, v, i in executed:
            (done_f if op == "F" else done_b)[(v, i, s)] = t
            ptr[s] += 1
        t += 1
    T = t

    def tbl(dtype=_np.int32, fill=0):
        return _np.full((T, S), fill, dtype)

    f_do, b_do = tbl(bool, False), tbl(bool, False)
    f_v, f_i, b_v, b_i = tbl(), tbl(), tbl(), tbl()
    fr_do, br_do = tbl(bool, False), tbl(bool, False)
    fr_v, fr_i, br_v, br_i = tbl(), tbl(), tbl(), tbl()
    for (v, i, s), tt in done_f.items():
        f_do[tt, s], f_v[tt, s], f_i[tt, s] = True, v, i
        if v * S + s != V * S - 1 and tt + 1 < T:
            cv, cs = (v, s + 1) if s < S - 1 else (v + 1, 0)
            fr_do[tt + 1, cs] = True
            fr_v[tt + 1, cs], fr_i[tt + 1, cs] = cv, i
    for (v, i, s), tt in done_b.items():
        b_do[tt, s], b_v[tt, s], b_i[tt, s] = True, v, i
        if v * S + s != 0 and tt + 1 < T:
            cv, cs = (v, s - 1) if s > 0 else (v - 1, S - 1)
            br_do[tt + 1, cs] = True
            br_v[tt + 1, cs], br_i[tt + 1, cs] = cv, i

    def color(intervals):
        """intervals: {(s, v, i): (t_from, t_to)} — live ranges, both
        ends inclusive (an entry written at the START of slot a' must
        not reuse a slot read at slot b unless a' > b). Greedy
        interval-graph coloring PER RANK (chunks share the pool, so the
        buffer depth equals the rank's true peak in-flight count —
        independent of m, the defining 1F1B bound). Returns
        ({(s, v, i): slot}, depth)."""
        by_rank = {}
        for key, iv in intervals.items():
            by_rank.setdefault(key[0], []).append((iv, key))
        out, depth = {}, 1
        for items in by_rank.values():
            items.sort(key=lambda kv: kv[0])
            busy = []                       # (end, color) active list
            free = []
            next_c = 0
            for (a, bnd), key in items:
                still = []
                for end, c0 in busy:
                    if end < a:
                        free.append(c0)
                    else:
                        still.append((end, c0))
                busy = still
                if free:
                    c = min(free)
                    free.remove(c)
                else:
                    c = next_c
                    next_c += 1
                out[key] = c
                busy.append((bnd, c))
            depth = max(depth, next_c)
        return out, depth

    inbox_f_iv = {}
    for (v, i, s), tt in done_f.items():
        u = v * S + s
        if u == 0:
            continue
        pv, ps = (v, s - 1) if s > 0 else (v - 1, S - 1)
        inbox_f_iv[(s, v, i)] = (done_f[(pv, i, ps)] + 1, tt)
    inbox_b_iv = {}
    for (v, i, s), tt in done_b.items():
        u = v * S + s
        if u == V * S - 1:
            continue
        nv, ns = (v, s + 1) if s < S - 1 else (v + 1, 0)
        inbox_b_iv[(s, v, i)] = (done_b[(nv, i, ns)] + 1, tt)
    stash_iv = {(s, v, i): (tt, done_b[(v, i, s)])
                for (v, i, s), tt in done_f.items()}

    inf_slot, d_inf = color(inbox_f_iv)
    inb_slot, d_inb = color(inbox_b_iv)
    st_slot, d_stash = color(stash_iv)

    # slot tables: read-side (the op rows) and write-side (arrival rows)
    f_in, f_st = tbl(), tbl()
    b_in, b_st = tbl(), tbl()
    fr_slot, br_slot = tbl(), tbl()
    for (v, i, s), tt in done_f.items():
        f_in[tt, s] = inf_slot.get((s, v, i), 0)
        f_st[tt, s] = st_slot[(s, v, i)]
    for (v, i, s), tt in done_b.items():
        b_in[tt, s] = inb_slot.get((s, v, i), 0)
        b_st[tt, s] = st_slot[(s, v, i)]
        if v * S + s != 0 and tt + 1 < T:
            cv, cs = (v, s - 1) if s > 0 else (v - 1, S - 1)
            br_slot[tt + 1, cs] = inb_slot[(cs, cv, i)]
    for (v, i, s), tt in done_f.items():
        if v * S + s != V * S - 1 and tt + 1 < T:
            cv, cs = (v, s + 1) if s < S - 1 else (v + 1, 0)
            fr_slot[tt + 1, cs] = inf_slot[(cs, cv, i)]

    return {
        "T": T,
        "f": (f_do, f_v, f_i, f_in, f_st),
        "b": (b_do, b_v, b_i, b_in, b_st),
        "fr": (fr_do, fr_slot), "br": (br_do, br_slot),
        "d_inf": d_inf, "d_inb": d_inb, "d_stash": d_stash,
    }


# ---------------------------------------------------------------------------
# True 1F1B (one-forward-one-backward) schedule
# ---------------------------------------------------------------------------
def _spec_axes(spec):
    """Set of mesh axis names appearing in a PartitionSpec."""
    out = set()
    for entry in tuple(spec):
        if entry is None:
            continue
        if isinstance(entry, (tuple, list)):
            out.update(a for a in entry if a is not None)
        else:
            out.add(entry)
    return out


def _tree_spec_axes(specs):
    out = set()
    for s in jax.tree_util.tree_leaves(
            specs, is_leaf=lambda l: isinstance(l, P)):
        out.update(_spec_axes(s))
    return out


def _psum_tree(tree, axes):
    if not axes:
        return tree
    return jax.tree_util.tree_map(
        lambda l: jax.lax.psum(l, tuple(axes)), tree)


def pipeline_1f1b(stage_fn: Callable, stacked_params, x, mesh: ProcessMesh,
                  num_microbatches: int, axis: str = "pp",
                  extra_args: tuple = (), param_specs=None, x_spec=None,
                  reduce_fn: Optional[Callable] = None,
                  reduce_args: tuple = (), reduce_arg_specs=None,
                  reduce_mean_axes: tuple = (),
                  reduce_shape: tuple = (),
                  grad_component: int = 0,
                  need_input_grad: bool = True,
                  virtual_chunks: int = 1):
    """TRUE 1F1B pipelined training step (≙ the reference
    `PipelineParallel.train_batch` 1F1B schedule,
    «.../fleet/meta_parallel/pipeline_parallel.py», SURVEY.md §7 hard
    part #1) — same signature family as `pipeline_forward` with
    `reduce_fn`, same return value (the (M, *reduce_shape) per-microbatch
    reductions), but the backward pass is a MANUALLY interleaved 1F1B
    schedule instead of grad-of-scan GPipe:

    * One `lax.scan` over 2(M+S-1) slots. Device s runs F(i) at slot
      s + 2i and B(i) at slot 2S-1-s + 2i — F slots have parity s, B
      slots parity s+1, so the two never collide and the wall-clock
      matches the canonical 1F1B timeline.
    * A circular stash holds at most S stage-INPUT activations (the
      in-flight bound at stage s is S - s). The stage body is
      rematerialized inside each B slot via `jax.vjp`, so activation
      residency is ∝ S·microbatch and INDEPENDENT of M — the 1F1B
      memory profile that grad-of-scan cannot express.
    * Activations ppermute s→s+1 every slot; grad-activations ppermute
      s→s-1 every slot; garbage flows on inactive lanes and is gated
      off by each receiver's own schedule predicate.

    Differentiation contract: the function is wrapped in
    `jax.custom_vjp`, so `jax.grad` / `loss.backward()` through the
    returned reductions Just Works — with one documented assumption:
    the cotangent of the `grad_component`-th reduction component must
    be UNIFORM across microbatches (true for every mean/sum-style loss
    combiner, including the global-token-mean sum/count pattern, where
    d loss/d sum_i = 1/total_count for all i). Components other than
    `grad_component` must be gradient-free w.r.t. the network (e.g.
    valid-token counts). This is exactly the reference's gradient
    -accumulation semantics (each microbatch backward seeded with the
    same scale).

    need_input_grad=False drops the (M, mb, ...) input-cotangent buffer
    (use when x is not a function of trained parameters).

    virtual_chunks=V > 1 runs the INTERLEAVED 1F1B schedule
    (≙ reference `PipelineParallelWithInterleave` composed with 1F1B —
    VERDICT r4 missing #2): stacked_params leaves are (S, V, ...) —
    device s owns model chunks {v*S + s} — and the static slot tables
    from `_interleaved_1f1b_schedule` (Megatron-order op sequence, exact
    timing resolved by host simulation) drive the same fused scan. Ring
    buffers (forward inbox, backward inbox, input stash) are sized by
    interval-graph coloring to the schedule's true peak in-flight count
    — ~2(S-1) + (V-1)S + 1 activations, INDEPENDENT of M — so the
    1F1B memory profile carries over to the interleaved form, while the
    fill/drain bubble shrinks ~1/V. Any M is supported (no M % S
    constraint; the last microbatch group may be partial).
    """
    s_count = mesh.get_dim_size(axis)
    m = num_microbatches
    v_chunks = int(virtual_chunks)
    b = x.shape[0]
    assert b % m == 0, f"batch {b} not divisible by microbatches {m}"
    if reduce_fn is None:
        raise ValueError("pipeline_1f1b is a training-step schedule: it "
                         "needs reduce_fn (the per-microbatch loss head); "
                         "use pipeline_forward for inference")
    mb = b // m
    xs = x.reshape(m, mb, *x.shape[1:])
    slots = 2 * (m + s_count - 1)
    tables = (_interleaved_1f1b_schedule(s_count, v_chunks, m)
              if v_chunks > 1 else None)
    r_shape = tuple(reduce_shape)
    if r_shape == ():
        seed = jnp.float32(1.0)
    else:
        import numpy as _np0
        _gc_idx = _np0.unravel_index(grad_component, r_shape)
        seed = jnp.zeros(r_shape, jnp.float32).at[_gc_idx].set(1.0)

    if param_specs is None:
        param_specs = jax.tree_util.tree_map(
            lambda l: P(axis, *([None] * (l.ndim - 1))), stacked_params)
    if x_spec is None:
        xs_spec = P(*([None] * xs.ndim))
    else:
        xs_spec = P(None, *tuple(x_spec))
    extra_specs = tuple(P(*([None] * jnp.asarray(e).ndim))
                        for e in extra_args)
    if reduce_arg_specs is None:
        reduce_arg_specs = tuple(P(*([None] * jnp.asarray(a).ndim))
                                 for a in reduce_args)
    reduce_arg_specs = tuple(reduce_arg_specs)

    # differentiable reduce_args = inexact-dtype leaves (labels etc. are
    # integer arrays: no cotangent)
    r_diff = tuple(i for i, a in enumerate(reduce_args)
                   if jnp.issubdtype(jnp.asarray(a).dtype, jnp.inexact))

    # mesh axes that carry any input sharding: gradients must be
    # psum-reduced over every such axis that is absent from their own
    # output spec (axes with no input sharding are replicated-compute —
    # summing over them would overcount)
    used_axes = (_tree_spec_axes(param_specs) | _spec_axes(xs_spec)
                 | _tree_spec_axes(list(extra_specs))
                 | _tree_spec_axes(list(reduce_arg_specs)) | {axis})
    used_axes &= set(mesh.dim_names)

    def _grad_axes(spec):
        return tuple(sorted(used_axes - _spec_axes(spec)))

    losses_spec = P(*([None] * (1 + len(r_shape))))

    def combined(sp, xv, extra, rargs):
        """shard_map body builder: returns (losses, gparams, gx, gextra,
        grargs) — all grads already cross-axis psum-reduced."""

        def local_fn(params_local, xs_local, *rest):
            n_extra = len(extra)
            extra_l = rest[:n_extra]
            rargs_l = rest[n_extra:]
            params1 = jax.tree_util.tree_map(lambda l: l[0], params_local)
            s = jax.lax.axis_index(axis)
            perm_f = [(j, (j + 1) % s_count) for j in range(s_count)]
            perm_b = [(j, (j - 1) % s_count) for j in range(s_count)]
            act0 = jnp.zeros_like(xs_local[0])
            rargs_d = tuple(rargs_l[i] for i in r_diff)

            def slot(carry, t):
                (state_f, state_b, stash, gp_acc, gx_buf, gex_acc,
                 gra_acc, loss_buf) = carry
                # ---- forward slot -----------------------------------
                rel_f = t - s
                i_f = jnp.clip(rel_f // 2, 0, m - 1)
                do_f = (rel_f >= 0) & (rel_f % 2 == 0) & (rel_f // 2 < m)
                x_t = jax.lax.dynamic_index_in_dim(xs_local, i_f, 0,
                                                   keepdims=False)
                x_in = jnp.where(s == 0, x_t.astype(act0.dtype), state_f)
                y = jax.lax.cond(
                    do_f,
                    lambda: stage_fn(params1, x_in, *extra_l)
                    .astype(act0.dtype),
                    lambda: act0)
                old = jax.lax.dynamic_index_in_dim(stash, i_f % s_count,
                                                   0, keepdims=False)
                stash = jax.lax.dynamic_update_index_in_dim(
                    stash, jnp.where(do_f, x_in, old), i_f % s_count, 0)
                # ---- backward slot ----------------------------------
                rel_b = t - (2 * s_count - 1 - s)
                i_b = jnp.clip(rel_b // 2, 0, m - 1)
                do_b = (rel_b >= 0) & (rel_b % 2 == 0) & (rel_b // 2 < m)
                inp = jax.lax.dynamic_index_in_dim(stash, i_b % s_count,
                                                   0, keepdims=False)

                def bwd_last():
                    def f(p, a, ex, rd):
                        ra = list(rargs_l)
                        for k, i in enumerate(r_diff):
                            ra[i] = rd[k]
                        out = reduce_fn(stage_fn(p, a, *ex), i_b, *ra)
                        return out.astype(jnp.float32).reshape(r_shape)
                    r_val, vjp = jax.vjp(f, params1, inp, extra_l,
                                         rargs_d)
                    gp, ga, gex, grd = vjp(seed)
                    return gp, ga, gex, grd, r_val

                def bwd_mid():
                    def f(p, a, ex):
                        return stage_fn(p, a, *ex).astype(act0.dtype)
                    _, vjp = jax.vjp(f, params1, inp, extra_l)
                    gp, ga, gex = vjp(state_b)
                    return (gp, ga, gex,
                            jax.tree_util.tree_map(jnp.zeros_like,
                                                   rargs_d),
                            jnp.zeros(r_shape, jnp.float32))

                zeros_b = (
                    jax.tree_util.tree_map(jnp.zeros_like, params1),
                    jnp.zeros_like(act0),
                    jax.tree_util.tree_map(jnp.zeros_like, extra_l),
                    jax.tree_util.tree_map(jnp.zeros_like, rargs_d),
                    jnp.zeros(r_shape, jnp.float32))
                gp, ga, gex, grd, r_val = jax.lax.cond(
                    do_b,
                    lambda: jax.lax.cond(s == s_count - 1, bwd_last,
                                         bwd_mid),
                    lambda: zeros_b)
                gp_acc = jax.tree_util.tree_map(jnp.add, gp_acc, gp)
                gex_acc = jax.tree_util.tree_map(jnp.add, gex_acc, gex)
                gra_acc = jax.tree_util.tree_map(jnp.add, gra_acc, grd)
                if gx_buf is not None:
                    cur = jax.lax.dynamic_index_in_dim(gx_buf, i_b, 0,
                                                       keepdims=False)
                    gx_buf = jax.lax.dynamic_update_index_in_dim(
                        gx_buf, jnp.where(do_b & (s == 0), ga, cur),
                        i_b, 0)
                cur_l = jax.lax.dynamic_index_in_dim(loss_buf, i_b, 0,
                                                     keepdims=False)
                loss_buf = jax.lax.dynamic_update_index_in_dim(
                    loss_buf,
                    jnp.where(do_b & (s == s_count - 1), r_val, cur_l),
                    i_b, 0)
                # ---- ring hops --------------------------------------
                state_f = jax.lax.ppermute(y, axis, perm_f)
                state_b = jax.lax.ppermute(ga, axis, perm_b)
                return (state_f, state_b, stash, gp_acc, gx_buf, gex_acc,
                        gra_acc, loss_buf), None

            # ---- interleaved (V > 1): table-driven slots -------------
            def chunk_params(v):
                return jax.tree_util.tree_map(
                    lambda l: jax.lax.dynamic_index_in_dim(
                        l, v, 0, keepdims=False), params1)

            def slot_v(carry, row):
                (state_f, state_b, inbox_f, inbox_b, stash_v, gp_acc,
                 gx_buf, gex_acc, gra_acc, loss_buf) = carry
                (f_do, f_v, f_i, f_in, f_st, b_do, b_v, b_i, b_in,
                 b_st, fr_do, fr_sl, br_do, br_sl) = [r[s] for r in row]
                # ingest the previous slot's ppermute arrivals into the
                # colored inbox slots (write-before-read is safe: the
                # coloring forbids same-slot reuse)
                inbox_f = inbox_f.at[fr_sl].set(
                    jnp.where(fr_do, state_f, inbox_f[fr_sl]))
                inbox_b = inbox_b.at[br_sl].set(
                    jnp.where(br_do, state_b, inbox_b[br_sl]))
                # ---- forward op ---------------------------------------
                x_t = jax.lax.dynamic_index_in_dim(xs_local, f_i, 0,
                                                   keepdims=False)
                first = (s == 0) & (f_v == 0)
                x_in = jnp.where(first, x_t.astype(act0.dtype),
                                 inbox_f[f_in])
                pf = chunk_params(f_v)
                y = jax.lax.cond(
                    f_do,
                    lambda: stage_fn(pf, x_in, *extra_l)
                    .astype(act0.dtype),
                    lambda: act0)
                stash_v = stash_v.at[f_st].set(
                    jnp.where(f_do, x_in, stash_v[f_st]))
                # ---- backward op --------------------------------------
                inp = stash_v[b_st]
                ct_in = inbox_b[b_in]
                pb = chunk_params(b_v)
                last = (s == s_count - 1) & (b_v == v_chunks - 1)

                def bwd_last():
                    def f(p, a, ex, rd):
                        ra = list(rargs_l)
                        for k2, i2 in enumerate(r_diff):
                            ra[i2] = rd[k2]
                        out = reduce_fn(stage_fn(p, a, *ex), b_i, *ra)
                        return out.astype(jnp.float32).reshape(r_shape)
                    r_val, vjp = jax.vjp(f, pb, inp, extra_l, rargs_d)
                    gp, ga, gex, grd = vjp(seed)
                    return gp, ga, gex, grd, r_val

                def bwd_mid():
                    def f(p, a, ex):
                        return stage_fn(p, a, *ex).astype(act0.dtype)
                    _, vjp = jax.vjp(f, pb, inp, extra_l)
                    gp, ga, gex = vjp(ct_in)
                    return (gp, ga, gex,
                            jax.tree_util.tree_map(jnp.zeros_like,
                                                   rargs_d),
                            jnp.zeros(r_shape, jnp.float32))

                zeros_b = (
                    jax.tree_util.tree_map(jnp.zeros_like,
                                           chunk_params(0)),
                    jnp.zeros_like(act0),
                    jax.tree_util.tree_map(jnp.zeros_like, extra_l),
                    jax.tree_util.tree_map(jnp.zeros_like, rargs_d),
                    jnp.zeros(r_shape, jnp.float32))
                gp, ga, gex, grd, r_val = jax.lax.cond(
                    b_do,
                    lambda: jax.lax.cond(last, bwd_last, bwd_mid),
                    lambda: zeros_b)
                gp_acc = jax.tree_util.tree_map(
                    lambda a, g: a.at[b_v].add(g), gp_acc, gp)
                gex_acc = jax.tree_util.tree_map(jnp.add, gex_acc, gex)
                gra_acc = jax.tree_util.tree_map(jnp.add, gra_acc, grd)
                if gx_buf is not None:
                    cur = jax.lax.dynamic_index_in_dim(gx_buf, b_i, 0,
                                                       keepdims=False)
                    gx_buf = jax.lax.dynamic_update_index_in_dim(
                        gx_buf,
                        jnp.where(b_do & (s == 0) & (b_v == 0), ga, cur),
                        b_i, 0)
                cur_l = jax.lax.dynamic_index_in_dim(loss_buf, b_i, 0,
                                                     keepdims=False)
                loss_buf = jax.lax.dynamic_update_index_in_dim(
                    loss_buf, jnp.where(b_do & last, r_val, cur_l),
                    b_i, 0)
                # ---- ring hops ----------------------------------------
                state_f = jax.lax.ppermute(y, axis, perm_f)
                state_b = jax.lax.ppermute(ga, axis, perm_b)
                return (state_f, state_b, inbox_f, inbox_b, stash_v,
                        gp_acc, gx_buf, gex_acc, gra_acc, loss_buf), None

            if v_chunks > 1:
                rows = tuple(jnp.asarray(a) for a in
                             (tables["f"] + tables["b"]
                              + tables["fr"] + tables["br"]))
                carry0 = (
                    act0, jnp.zeros_like(act0),
                    jnp.zeros((tables["d_inf"],) + act0.shape,
                              act0.dtype),
                    jnp.zeros((tables["d_inb"],) + act0.shape,
                              act0.dtype),
                    jnp.zeros((tables["d_stash"],) + act0.shape,
                              act0.dtype),
                    jax.tree_util.tree_map(jnp.zeros_like, params1),
                    (jnp.zeros((m,) + act0.shape, act0.dtype)
                     if need_input_grad else None),
                    jax.tree_util.tree_map(jnp.zeros_like, extra_l),
                    jax.tree_util.tree_map(jnp.zeros_like, rargs_d),
                    jnp.zeros((m,) + r_shape, jnp.float32))
                (_, _, _, _, _, gp_acc, gx_buf, gex_acc, gra_acc,
                 loss_buf), _ = jax.lax.scan(slot_v, carry0, rows)
            else:
                carry0 = (
                    act0, jnp.zeros_like(act0),
                    jnp.zeros((s_count,) + act0.shape, act0.dtype),
                    jax.tree_util.tree_map(jnp.zeros_like, params1),
                    (jnp.zeros((m,) + act0.shape, act0.dtype)
                     if need_input_grad else None),
                    jax.tree_util.tree_map(jnp.zeros_like, extra_l),
                    jax.tree_util.tree_map(jnp.zeros_like, rargs_d),
                    jnp.zeros((m,) + r_shape, jnp.float32))
                (_, _, _, gp_acc, gx_buf, gex_acc, gra_acc,
                 loss_buf), _ = jax.lax.scan(slot, carry0,
                                             jnp.arange(slots))
            # cross-axis reductions: each grad psums over every
            # input-sharded axis absent from its own placement
            loss_buf = jax.lax.psum(loss_buf, axis)
            for ax in reduce_mean_axes:
                loss_buf = jax.lax.pmean(loss_buf, ax)
            gp_out = jax.tree_util.tree_map(
                lambda g, sp_: _psum_tree(g, _grad_axes(sp_))[None],
                gp_acc, param_specs,
                is_leaf=lambda l: isinstance(l, P))
            if gx_buf is not None:
                gx_buf = _psum_tree(gx_buf, _grad_axes(xs_spec))
            gex_out = tuple(
                _psum_tree(g, _grad_axes(sp_))
                for g, sp_ in zip(gex_acc, extra_specs))
            gra_out = tuple(
                _psum_tree(g, _grad_axes(reduce_arg_specs[i]))
                for g, i in zip(gra_acc, r_diff))
            return (loss_buf, gp_out, gx_buf, gex_out, gra_out)

        gx_spec = xs_spec if need_input_grad else None
        out_specs = (losses_spec, param_specs, gx_spec,
                     tuple(extra_specs),
                     tuple(reduce_arg_specs[i] for i in r_diff))
        return _shard_map(
            local_fn, mesh=mesh.jax_mesh,
            in_specs=(param_specs, xs_spec) + tuple(extra_specs)
            + tuple(reduce_arg_specs),
            out_specs=out_specs, **_SM_KW)(sp, xv, *extra, *rargs)

    from jax import dtypes as _jdt
    import numpy as _np

    def _int_ct(a):
        return _np.zeros(jnp.shape(a), _jdt.float0)

    # an UNdifferentiated call (eval / loss monitoring) must not pay the
    # fused fwd+bwd scan's backward compute and gradient-accumulator
    # memory (advisor r4): the custom_vjp PRIMAL runs the forward-only
    # schedule; jax.grad routes through run_fwd (the fused scan) instead.
    # The GPipe interleave needs M % S == 0 — outside that, eval keeps
    # the fused scan (correct, just not cheaper).
    _fwd_only_ok = (v_chunks == 1 or m <= s_count or m % s_count == 0)

    @jax.custom_vjp
    def run(sp, xv, extra, rargs):
        if _fwd_only_ok:
            return pipeline_forward(
                stage_fn, sp, xv.reshape(b, *x.shape[1:]), mesh, m,
                axis=axis, remat=False, extra_args=extra,
                param_specs=param_specs, x_spec=x_spec,
                reduce_fn=reduce_fn, reduce_args=rargs,
                reduce_arg_specs=reduce_arg_specs,
                reduce_mean_axes=reduce_mean_axes, reduce_shape=r_shape,
                virtual_chunks=v_chunks)
        return combined(sp, xv, extra, rargs)[0]

    def run_fwd(sp, xv, extra, rargs):
        losses, gp, gx, gex, gra = combined(sp, xv, extra, rargs)
        return losses, (gp, gx, gex, gra, rargs)

    def run_bwd(res, ct):
        gp, gx, gex, gra, rargs = res
        # uniform-cotangent assumption (gradient-accumulation semantics):
        # scale the accumulated grads by the per-microbatch cotangent of
        # the grad component (same flat index the forward seed used)
        if r_shape == ():
            c = ct
        else:
            import numpy as _np1
            c = ct[(slice(None),)
                   + tuple(_np1.unravel_index(grad_component, r_shape))]
        # the assumption is CHECKED, not trusted (VERDICT r4 weak #3): a
        # non-uniform combiner (e.g. microbatch-weighted loss) would
        # silently mis-train. Eager backward sees a concrete cotangent
        # and raises; under jit the scale is poisoned to NaN instead
        # (surfaced by loss monitoring / FLAGS_check_nan_inf), because a
        # traced value cannot raise.
        c32 = c.astype(jnp.float32)
        c_mean = jnp.mean(c32)
        c_dev = jnp.max(jnp.abs(c32 - c_mean))
        c_tol = 1e-5 * (jnp.abs(c_mean) + 1e-12)
        if not isinstance(c_dev, jax.core.Tracer):
            if float(c_dev) > float(c_tol):
                raise ValueError(
                    "pipeline_1f1b: the cotangent of reduction component "
                    f"{grad_component} is not uniform across microbatches "
                    f"(max deviation {float(c_dev):.3e}). The fused 1F1B "
                    "backward seeds every microbatch with ONE shared "
                    "scale (gradient-accumulation semantics) — combine "
                    "the per-microbatch losses with a uniform-weight "
                    "reduction (mean / sum / global sum-over-count), or "
                    "use pipeline_forward (grad-of-scan) for arbitrary "
                    "combiners.")
            scale = c_mean
        else:
            scale = jnp.where(c_dev <= c_tol, c_mean, jnp.nan)
        # the returned losses were pmean'd over reduce_mean_axes, so the
        # caller's cotangent is w.r.t. the MEAN — but the grads were
        # psum-accumulated raw over those (input-sharded) axes; undo the
        # double counting
        for ax in reduce_mean_axes:
            if ax in used_axes:
                scale = scale / mesh.get_dim_size(ax)

        def mul(g):
            return (g * scale).astype(g.dtype)

        g_sp = jax.tree_util.tree_map(mul, gp)
        # cotangent for the primal's second arg, which is xs (M, mb, ...)
        # — the caller-side reshape transposes it back to (B, ...)
        g_x = (mul(gx) if gx is not None
               else jnp.zeros((m, mb) + x.shape[1:], x.dtype))
        g_extra = jax.tree_util.tree_map(mul, gex)
        gra_it = iter(gra)
        g_rargs = tuple(
            mul(next(gra_it)) if i in r_diff else _int_ct(a)
            for i, a in enumerate(rargs))
        return g_sp, g_x, g_extra, g_rargs

    run.defvjp(run_fwd, run_bwd)
    return run(stacked_params, xs, tuple(extra_args), tuple(reduce_args))
