"""SPMD pipeline parallelism — the TPU-native replacement for the
reference's PipelineParallel.train_batch 1F1B schedule
(«.../fleet/meta_parallel/pipeline_parallel.py», p2p_communication.py —
SURVEY.md §2.3 PP row, §7 hard part #1).

Design (circular pipelined scan, scaling-book style): stage parameters are
STACKED along a leading (n_stages,) dim sharded over the `pp` mesh axis;
inside one `shard_map` every device runs the same `lax.scan` over
M + S - 1 ticks. At tick t, device s computes microbatch t - s; activations
hop stage→stage+1 through a single `ppermute` per tick (collective_permute
over ICI). The reference's send/recv meta-negotiation, batched isend/irecv
and per-stage Python scheduling all collapse into this one compiled loop.

Backward is `jax.grad` through the scan: XLA replays the schedule in
reverse (the ppermute transposes to the opposite rotation), which yields
GPipe-equivalent ordering; per-tick rematerialization (`jax.checkpoint`
around the stage body) bounds residuals to one activation per tick —
O(B·hidden) total, a GPipe-with-remat profile (NOT true 1F1B's
S·microbatch bound, and no interleaved virtual stages yet — both remain
future work; a functional 1F1B needs fwd/bwd tick interleaving that XLA's
grad-of-scan does not express directly).

Output handling: by default every device returns the (M, mb, ...) buffer
and the last stage's copy is broadcast with a one-hop `ppermute` fan-out
(cheaper than the old masked psum: no ring reduction, pure
collective-permute traffic). Passing `reduce_fn` (e.g. the LM head + loss)
collapses each microbatch's output to a scalar ON the last stage, so the
cross-stage broadcast is O(M) scalars and the big buffer never exists —
use this for training steps.
"""
from __future__ import annotations

import functools
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

# version-conditional shard_map kwargs (check_vma vs check_rep) live in
# collective.py; reuse them so the older-jax fallback actually works here
from ..collective import _SM_KW, shard_map as _shard_map

from ..mesh import ProcessMesh

__all__ = ["pipeline_forward", "stack_stage_params"]


def stack_stage_params(per_stage_params):
    """[pytree per stage] -> one pytree with leading (S,) dim (to be
    sharded Shard(0) over 'pp')."""
    return jax.tree_util.tree_map(
        lambda *leaves: jnp.stack(leaves, axis=0), *per_stage_params)


def pipeline_forward(stage_fn: Callable, stacked_params, x, mesh: ProcessMesh,
                     num_microbatches: int, axis: str = "pp",
                     remat: bool = True, extra_args: tuple = (),
                     param_specs=None, x_spec=None,
                     reduce_fn: Optional[Callable] = None,
                     reduce_args: tuple = (), reduce_arg_specs=None,
                     reduce_mean_axes: tuple = (),
                     reduce_shape: tuple = (),
                     virtual_chunks: int = 1):
    """Run the pipelined forward: y = stage_{S-1}(...stage_0(x)).

    stage_fn(params_one_stage, activation, *extra) -> activation; must keep
    the activation shape (classic transformer-stack property).
    stacked_params: pytree, every leaf (S, ...) — sharded over `axis`.
    x: (B, ...) global input; split into M = num_microbatches along dim 0.
    extra_args: replicated side inputs every stage sees (rope tables etc.).
    param_specs: optional pytree of PartitionSpec (leading entry must be
    `axis`) to compose TP/ZeRO shardings inside the pipeline — stage_fn then
    sees LOCAL shards and is responsible for its own collectives (psum over
    'mp' etc.; every mesh axis name is bound inside). x_spec: optional
    PartitionSpec for one microbatch (e.g. P('dp', None, None) to keep the
    batch dp-sharded through the pipeline).
    reduce_fn(y_microbatch, microbatch_index, *reduce_args) -> scalar or
    small fixed-shape array (e.g. (loss_sum, token_count)): when given,
    each microbatch's final-stage output reduces immediately (the
    training-loss fusion) and the function returns the (M, *r) stacked
    reductions instead of activations — the (M, mb, ...) output buffer
    and its broadcast disappear, and a `lax.cond` skips the reduction
    compute on non-final stages (each device branches on its own stage
    id at runtime). reduce_args ride the shard_map with reduce_arg_specs
    (default replicated); reduce_mean_axes names mesh axes (e.g. 'dp')
    the reductions are pmean-averaged over when inputs are sharded there;
    reduce_shape declares reduce_fn's output shape (() = scalar) — it
    cannot be probed because reduce_fn may contain collectives only valid
    inside the shard_map.

    virtual_chunks=V > 1 enables the INTERLEAVED virtual pipeline
    (≙ reference `PipelineParallelWithInterleave`, SURVEY.md §2.3 PP
    row): stacked_params leaves are (S, V, ...) — device s owns the V
    model chunks {v*S + s}, each 1/V of a contiguous stage — and the
    activation makes V laps around the SAME ring (chunk v's stage S-1
    hands to chunk v+1's stage 0 via the one ppermute). Per-tick work
    drops to 1/V of a fat stage, shrinking the fill/drain bubble from
    (S-1) fat-stage units to ~(S-1)/V-ish: ticks go (M + S - 1) ->
    (M + V*S - 1) at 1/V the cost each. Constraint: M <= S (the
    conflict-free schedule; run multiple rounds for larger batches).
    Returns y: (B, ...) final-stage output, or (M, *reduce_shape) with
    reduce_fn. Differentiable.
    """
    s_count = mesh.get_dim_size(axis)
    m = num_microbatches
    v_chunks = int(virtual_chunks)
    b = x.shape[0]
    assert b % m == 0, f"batch {b} not divisible by microbatches {m}"
    if v_chunks > 1 and m > s_count:
        raise ValueError(
            f"interleaved pipeline needs num_microbatches ({m}) <= pp "
            f"degree ({s_count}); run multiple rounds for larger batches")
    mb = b // m
    xs = x.reshape(m, mb, *x.shape[1:])
    ticks = m + v_chunks * s_count - 1

    body = stage_fn
    if remat:
        body = jax.checkpoint(stage_fn)

    n_extra = len(extra_args)

    def local_fn(params_local, xs_local, *rest):
        extra = rest[:n_extra]
        r_args = rest[n_extra:]
        # params_local leaves: (1, ...) — this device's stage (or
        # (1, V, ...) — its V interleaved chunks); squeeze the shard dim
        params1 = jax.tree_util.tree_map(lambda l: l[0], params_local)
        s = jax.lax.axis_index(axis)
        perm = [(j, (j + 1) % s_count) for j in range(s_count)]

        def tick(carry, t):
            state, buf = carry
            if v_chunks > 1:
                # interleave schedule: at tick t this device runs chunk
                # v for microbatch t - v*S - s (at most one valid (m, v)
                # since M <= S); garbage flows on inactive ticks and is
                # never recorded
                rel = t - s
                v = jnp.clip(rel // s_count, 0, v_chunks - 1)
                m_i = rel - v * s_count
                x_t = jax.lax.dynamic_index_in_dim(
                    xs_local, jnp.clip(m_i, 0, m - 1), 0, keepdims=False)
                inp = jnp.where((s == 0) & (v == 0),
                                x_t.astype(state.dtype), state)
                params_t = jax.tree_util.tree_map(
                    lambda l: jax.lax.dynamic_index_in_dim(
                        l, v, 0, keepdims=False), params1)
            else:
                # stage 0 ingests microbatch t (clamped; inactive ticks
                # are overwritten later), others take the ppermuted
                # activation
                x_t = jax.lax.dynamic_index_in_dim(
                    xs_local, jnp.clip(t, 0, m - 1), 0, keepdims=False)
                inp = jnp.where(s == 0, x_t.astype(state.dtype), state)
                params_t = params1
            y = body(params_t, inp, *extra)
            # the final (stage, chunk)'s tick-t output is microbatch
            # t - (V-1)*S - (S-1)
            idx = t - (v_chunks - 1) * s_count - (s_count - 1)
            idx_c = jnp.clip(idx, 0, m - 1)
            valid = (idx >= 0) & (idx < m)
            if reduce_fn is not None:
                # only the final stage's reduction matters; lax.cond lets
                # every other device skip the (lm-head-sized) compute —
                # the predicate is per-device so each takes its own branch
                r = jax.lax.cond(
                    (s == s_count - 1) & valid,
                    lambda: reduce_fn(y, idx_c, *r_args)
                    .astype(buf.dtype).reshape(buf.shape[1:]),
                    lambda: buf[idx_c])
                buf = buf.at[idx_c].set(r)
            else:
                cur = jax.lax.dynamic_index_in_dim(buf, idx_c, 0,
                                                   keepdims=False)
                upd = jnp.where(valid, y, cur)
                buf = jax.lax.dynamic_update_index_in_dim(buf, upd,
                                                          idx_c, 0)
            state = jax.lax.ppermute(y, axis, perm)
            return (state, buf), None

        state0 = jnp.zeros_like(xs_local[0])
        buf0 = (jnp.zeros((m,) + tuple(reduce_shape), jnp.float32)
                if reduce_fn is not None else jnp.zeros_like(xs_local))
        (_, buf), _ = jax.lax.scan(tick, (state0, buf0),
                                   jnp.arange(ticks))
        # only the last stage holds the real output: recursive-doubling
        # broadcast from stage S-1 — ceil(log2 S) ppermute hops, each
        # device receives the buffer exactly once ((S-1)·|buf| total
        # traffic, no floating-point reduction; the old masked psum was a
        # full ring allreduce at ~2x the traffic plus adds)
        have = {s_count - 1}
        while len(have) < s_count:
            srcs = sorted(have)
            dsts = [d for d in range(s_count) if d not in have]
            pairs = list(zip(srcs, dsts))
            recv = jax.lax.ppermute(buf, axis, pairs)
            keep = jnp.isin(s, jnp.asarray(srcs))
            buf = jnp.where(keep, buf, recv)
            have |= {d for _, d in pairs}
        for ax in reduce_mean_axes:
            buf = jax.lax.pmean(buf, ax)
        return buf

    if param_specs is None:
        param_specs = jax.tree_util.tree_map(
            lambda l: P(axis, *([None] * (l.ndim - 1))), stacked_params)
    if x_spec is None:
        x_spec = P(*([None] * xs.ndim))
    else:
        # caller gives the per-microbatch activation spec; prepend the
        # microbatch dim
        x_spec = P(None, *tuple(x_spec))
    extra_specs = tuple(P(*([None] * jnp.asarray(e).ndim))
                        for e in extra_args)
    if reduce_arg_specs is None:
        reduce_arg_specs = tuple(P(*([None] * jnp.asarray(a).ndim))
                                 for a in reduce_args)
    out_spec = (P(*([None] * (1 + len(reduce_shape))))
                if reduce_fn is not None else x_spec)
    out = _shard_map(local_fn, mesh=mesh.jax_mesh,
                     in_specs=(param_specs, x_spec) + extra_specs
                     + tuple(reduce_arg_specs),
                     out_specs=out_spec,
                     **_SM_KW)(stacked_params, xs, *extra_args,
                               *reduce_args)
    if reduce_fn is not None:
        return out                      # (M,) per-microbatch scalars
    return out.reshape(b, *out.shape[2:])
