"""fleet.utils — recompute (activation checkpointing) and helpers.

≙ reference `paddle.distributed.fleet.utils.recompute`
(«python/paddle/distributed/fleet/utils/» [U]) and the recompute
meta-optimizer / pass (SURVEY.md §2.4). TPU-native design: the wrapped
function becomes ONE tape op whose values-level computation is
`jax.checkpoint`-wrapped — under `TrainStep` jit tracing XLA rematerializes
the block's activations in the backward pass instead of saving them,
trading FLOPs for HBM (the Llama-8B north-star memory budget depends on
this; SURVEY.md §6).

RNG: the recomputed function runs twice (fwd + recompute-in-bwd); dropout
must see the SAME key both times (≙ reference preserve_rng_state). The key
is snapshotted once per call and pinned inside the checkpointed region.
"""
from __future__ import annotations

from typing import Any, Callable

import jax

from ....core.tensor import Tensor, apply
from ....tensor.random import default_generator

# string policy names -> jax.checkpoint policies (≙ the reference's
# recompute granularity knobs: full / selective)
_POLICIES = {
    None: None,                       # save nothing: full recompute
    "full": None,
    "dots": "checkpoint_dots",
    "dots_saveable": "checkpoint_dots",
    "dots_with_no_batch_dims": "checkpoint_dots_with_no_batch_dims",
    "nothing_saveable": "nothing_saveable",
    "everything_saveable": "everything_saveable",
}


def _resolve_policy(name):
    if name is None or name == "full":
        return None
    key = _POLICIES.get(name, name)
    pol = getattr(jax.checkpoint_policies, key, None)
    if pol is None:
        raise ValueError(
            f"unknown recompute policy {name!r}; known: "
            f"{sorted(k for k in _POLICIES if isinstance(k, str))}")
    return pol


def _collect_params(function) -> list:
    """Parameters the recomputed function depends on: a Layer's own, a bound
    method's owner's, and any Layer/Parameter closed over by a plain
    function — all must become differentiable tape inputs, or their grads
    would silently vanish."""
    from ....core.tensor import Parameter
    from ....nn.layer.layers import Layer

    found = []
    if isinstance(function, Layer):
        found += list(function.parameters())
    owner = getattr(function, "__self__", None)
    if isinstance(owner, Layer):
        found += list(owner.parameters())
    for cell in getattr(function, "__closure__", None) or ():
        try:
            v = cell.cell_contents
        except ValueError:  # pragma: no cover - empty cell
            continue
        if isinstance(v, Layer):
            found += list(v.parameters())
        elif isinstance(v, Parameter):
            found.append(v)
    out, ids = [], set()
    for p in found:
        if id(p) not in ids:
            ids.add(id(p))
            out.append(p)
    return out


def recompute(function: Callable, *args,
              preserve_rng_state: bool = True,
              use_reentrant: bool = True,
              policy=None,
              **kwargs) -> Any:
    """Run `function(*args, **kwargs)` without saving its internal
    activations; recompute them during backward.

    `function` may be an `nn.Layer` (its parameters are captured as
    differentiable inputs automatically) or any callable over Tensors.
    Non-Tensor args/kwargs pass through statically. `policy` selects what
    XLA may save anyway ('full' = nothing, 'dots' = matmul outputs with
    batch dims, ...).
    """
    from ....nn.layer.layers import Layer

    params = _collect_params(function)
    tensor_idx = [i for i, a in enumerate(args)
                  if isinstance(a, Tensor)]
    tensor_args = [args[i] for i in tensor_idx]
    inputs = params + tensor_args
    n_p = len(params)

    # pin one key for both executions (fwd trace and bwd rematerialization);
    # the global generator still advances exactly once per recompute() call
    key_snap = default_generator.next_key() if preserve_rng_state else None

    # out_struct records the user function's real output structure (filled
    # during any trace of values_fn, including the abstract probe below)
    out_struct: dict = {}

    def values_fn(*vals):
        pvals, avals = vals[:n_p], vals[n_p:]
        old_p = [p._value for p in params]
        old_key = default_generator._key
        try:
            for p, v in zip(params, pvals):
                p._value = v
            if key_snap is not None:
                default_generator._key = key_snap
            new_args = list(args)
            for i, v in zip(tensor_idx, avals):
                new_args[i] = Tensor(v)
            out = function(*new_args, **kwargs)
            if isinstance(out, (tuple, list)):
                out_struct["type"] = type(out)
                out_vals = tuple(t._value if isinstance(t, Tensor) else t
                                 for t in out)
                # a 1-tuple must flow through the tape as a single output
                # (the tape's vjp routing treats n_outputs==1 as a leaf)
                return out_vals if len(out_vals) > 1 else out_vals[0]
            out_struct["type"] = None
            return out._value if isinstance(out, Tensor) else out
        finally:
            for p, v in zip(params, old_p):
                p._value = v
            default_generator._key = old_key

    in_vals = [t._value for t in inputs]
    probe = jax.eval_shape(values_fn, *in_vals)
    multi = isinstance(probe, tuple)
    ckpt = jax.checkpoint(values_fn, policy=_resolve_policy(policy))
    outs = apply("recompute", ckpt, inputs, multi_output=multi)
    kind = out_struct["type"]
    if kind is None:
        return outs
    if not multi:  # user returned a 1-element tuple/list
        return kind([outs])
    return outs if kind is tuple else kind(outs)


__all__ = ["recompute"]
