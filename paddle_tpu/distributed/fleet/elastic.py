"""Elastic training — checkpoint-restart based.

≙ reference «python/paddle/distributed/fleet/elastic/manager.py»
(ElasticManager: ETCD membership, peer watch, scale-up/down classification,
kill + relaunch with new ranks — SURVEY.md §5 "Failure detection").

TPU-native: there is no ETCD and no per-device process set to re-rank.
Elasticity is (1) the launch CLI's restart-on-failure loop
(distributed.launch --elastic_level), (2) fast resume from the latest
async sharded checkpoint (distributed.checkpoint — restore reshapes onto
whatever mesh the restarted job has), and (3) coordinator health from
jax.distributed. This module provides the train-loop-side helper: periodic
checkpoints + latest-checkpoint discovery on restart.
"""
from __future__ import annotations

import os
import random
import re
import shutil
import time
from typing import List, Optional, Tuple

from ... import observability as telemetry
from ...utils.faults import fault_point

__all__ = ["ElasticManager", "latest_checkpoint",
           "complete_checkpoints", "HeartbeatMembership"]

_M_HB_STALENESS = telemetry.gauge(
    "pdt_elastic_heartbeat_staleness_seconds",
    "Seconds since each worker's last heartbeat, sampled at alive().",
    ("rank",))
_M_MEMBERSHIP_EVENTS = telemetry.counter(
    "pdt_elastic_membership_events_total",
    "Membership deltas observed by poll(), by classification.",
    ("event",))
_M_SAVE_RETRIES = telemetry.counter(
    "pdt_checkpoint_save_retries_total",
    "Checkpoint save attempts retried after a write/finalize failure.")
_M_LOAD_RETRIES = telemetry.counter(
    "pdt_checkpoint_load_retries_total",
    "Resume-time load attempts retried before quarantining.")
_M_CORRUPT = telemetry.counter(
    "pdt_checkpoint_corrupt_total",
    "Checkpoints quarantined at resume, by detection path.", ("reason",))
_M_FALLBACKS = telemetry.counter(
    "pdt_checkpoint_resume_fallbacks_total",
    "Resume attempts that fell back past a bad checkpoint.")
_M_FALLBACK_DEPTH = telemetry.gauge(
    "pdt_checkpoint_resume_fallback_depth",
    "How many checkpoints the last resume() skipped before loading "
    "one (0 = newest was good).")


def complete_checkpoints(ckpt_dir: str) -> List[Tuple[int, str]]:
    """All COMMITTED step checkpoints under ckpt_dir, newest first.

    Committed means a `step_N/.done` marker that actually parses
    (`checkpoint.parse_done`) — a zero-byte or torn marker from a
    non-atomic writer must read as NOT committed, never as a loadable
    checkpoint. `.tmp` and `.corrupt` directories never qualify."""
    from ..checkpoint import DONE_NAME, parse_done
    if not os.path.isdir(ckpt_dir):
        return []
    out = []
    for name in os.listdir(ckpt_dir):
        m = re.fullmatch(r"step_(\d+)", name)
        if not m:
            continue
        path = os.path.join(ckpt_dir, name)
        if parse_done(os.path.join(path, DONE_NAME)) is not None:
            out.append((int(m.group(1)), path))
    out.sort(reverse=True)
    return out


def latest_checkpoint(ckpt_dir: str) -> Optional[str]:
    """Newest COMMITTED step-numbered checkpoint dir, or None. Rejects
    unparsable `.done` payloads (see `complete_checkpoints`)."""
    complete = complete_checkpoints(ckpt_dir)
    return complete[0][1] if complete else None


def _free_suffixed(base: str, suffix: str) -> str:
    """First non-existing `base``suffix`[.k] name. Quarantine's
    `.corrupt` and _commit's `.old` move-aside share this probe; the
    _gc stale-sweep regex must keep matching both families."""
    dst = base + suffix
    n = 0
    while os.path.exists(dst):
        n += 1
        dst = f"{base}{suffix}.{n}"
    return dst


def _rmtree_checkpoint(path: str):
    """Delete a checkpoint dir with its `.done` marker removed FIRST:
    rmtree is not atomic (and ignore_errors swallows partial failures),
    so a kill mid-delete must not leave a half-deleted directory that
    discovery still trusts — with MANIFEST.json among the missing files,
    resume's legacy-checkpoint path would even load it unverified."""
    from ..checkpoint import DONE_NAME
    try:
        os.remove(os.path.join(path, DONE_NAME))
    except OSError:
        pass
    shutil.rmtree(path, ignore_errors=True)


def _touch(path: str, now: Optional[float] = None):
    """Restart the stale-age clock on a renamed dir: os.replace keeps
    the data files' old mtimes, so without this the very next _gc could
    sweep a just-quarantined (or just-moved-aside) checkpoint whose
    data predates `stale_grace` — destroying the post-mortem evidence
    the rename exists to preserve. `now` comes from the manager's
    injectable clock so ages stay consistent with `_gc`'s."""
    try:
        os.utime(path, None if now is None else (now, now))
    except OSError:
        pass


def _newest_mtime(path: str) -> float:
    """Newest mtime anywhere under `path`. The top-level dir's own
    mtime freezes when its first entry is created, so a long in-flight
    orbax write deep under `step_N.tmp/model/d/` would look stale by
    the dir mtime alone — the stale-age GC must see the write
    activity, not the directory creation time."""
    newest = os.stat(path).st_mtime
    for root, dirs, files in os.walk(path):
        for name in dirs + files:
            try:
                ts = os.stat(os.path.join(root, name)).st_mtime
            except OSError:
                continue
            if ts > newest:
                newest = ts
    return newest


class ElasticManager:
    """Checkpoint-cadence + resume bookkeeping for an elastic train loop.

    Usage::

        em = ElasticManager(ckpt_dir, save_interval_steps=100)
        start = em.resume(model, opt)      # 0 if fresh
        for step in range(start, total):
            loss = train_step(...)
            em.maybe_save(step, model, opt)

    Durability (docs/checkpointing.md): `save` runs an **atomic commit
    protocol** — all data is written into `step_N.tmp` together with a
    `MANIFEST.json` integrity manifest, then the directory is renamed
    to `step_N` and a `.done` marker committed via tmp+rename, so a
    crash at ANY point leaves either the previous complete checkpoint
    or a new complete one, never a half-trusted directory. Failed write
    attempts are retried with exponential backoff (`save_retries`,
    reusing the launcher's backoff shape). `resume` walks complete
    checkpoints newest-first, verifies each against its manifest
    (`verify_on_resume`: "rehash" re-hashes content checksums, "light"
    checks structure against checkpoint metadata without reading array
    bytes, "off" trusts `.done`), retries transient load errors
    (`load_retries`), and **quarantines** a bad one (`step_N` ->
    `step_N.corrupt`) before falling back to the next-newest — a torn
    or bit-flipped checkpoint degrades resume by one interval instead
    of crash-looping the launcher.
    """

    #: resume-time verification modes (constructor `verify_on_resume`)
    VERIFY_MODES = ("rehash", "light", "off")

    def __init__(self, ckpt_dir: str, save_interval_steps: int = 100,
                 keep_last: int = 2, save_retries: int = 3,
                 retry_backoff: float = 0.25,
                 retry_backoff_max: float = 5.0,
                 load_retries: int = 2,
                 verify_on_resume: str = "rehash",
                 stale_grace: float = 3600.0,
                 sleep=time.sleep, rng: Optional[random.Random] = None,
                 clock=time.time):
        if verify_on_resume not in self.VERIFY_MODES:
            raise ValueError(
                f"verify_on_resume must be one of {self.VERIFY_MODES}, "
                f"got {verify_on_resume!r}")
        self.ckpt_dir = ckpt_dir
        self.save_interval_steps = save_interval_steps
        self.keep_last = keep_last
        self.save_retries = max(1, save_retries)   # total attempts
        self.retry_backoff = retry_backoff
        self.retry_backoff_max = retry_backoff_max
        self.load_retries = max(1, load_retries)   # total attempts
        self.verify_on_resume = verify_on_resume
        # age guard for GC of incomplete/.tmp/.corrupt dirs: a LIVE
        # save's tmp dir (or a checkpoint an operator is inspecting)
        # must not be swept by a concurrent manager
        self.stale_grace = stale_grace
        self._sleep = sleep
        self._rng = rng if rng is not None else random.Random()
        self._clock = clock
        os.makedirs(ckpt_dir, exist_ok=True)

    # -- resume (corruption-tolerant fallback chain) -------------------
    def resume(self, model, optimizer=None) -> int:
        """Restore the newest checkpoint that verifies AND loads;
        returns the next step (0 if no loadable checkpoint remains).

        A checkpoint that fails its integrity manifest or raises during
        load is quarantined (`step_N` -> `step_N.corrupt`, kept on disk
        for post-mortem until GC'd by the stale-age guard) and the
        chain falls back to the next-newest complete checkpoint. Load
        errors get `load_retries` total attempts (the save path's
        backoff shape) first, so one transient I/O hiccup doesn't cost
        a save interval."""
        from ..checkpoint import (MANIFEST_NAME, load_state_dict,
                                  load_state_dict_raw, verify_checkpoint)
        from ..launch import restart_backoff
        self._recover_replaced()
        depth = 0
        model_mutated = False
        for step, path in complete_checkpoints(self.ckpt_dir):
            reason = None
            try:
                if (self.verify_on_resume != "off"
                        and os.path.exists(
                            os.path.join(path, MANIFEST_NAME))):
                    # pre-manifest (legacy) checkpoints skip straight to
                    # the load attempt rather than being quarantined for
                    # predating the protocol
                    reason = "verify"
                    verify_checkpoint(
                        path,
                        rehash=self.verify_on_resume == "rehash",
                    ).raise_if_failed()
                reason = "load"
                for attempt in range(1, self.load_retries + 1):
                    try:
                        load_state_dict(model.state_dict(),
                                        os.path.join(path, "model"))
                        model_mutated = True
                        if (optimizer is not None
                                and hasattr(optimizer, "set_state_dict")):
                            opt_path = os.path.join(path, "opt")
                            if os.path.isdir(opt_path):
                                # raw restore: optimizer accumulators
                                # are created lazily, so there is no
                                # target structure to reshard onto yet
                                optimizer.set_state_dict(
                                    load_state_dict_raw(opt_path))
                        break
                    except Exception:
                        # a transient I/O error must not quarantine the
                        # newest GOOD checkpoint (losing a full save
                        # interval): retry like save does, quarantine
                        # only when the failure persists. A retry that
                        # got past the model group re-assigns it whole.
                        if attempt == self.load_retries:
                            raise
                        delay = restart_backoff(attempt,
                                                self.retry_backoff,
                                                self.retry_backoff_max,
                                                self._rng)
                        _M_LOAD_RETRIES.inc()
                        telemetry.event("checkpoint.load_retry",
                                        path=path, attempt=attempt,
                                        delay_s=delay)
                        if delay > 0:
                            self._sleep(delay)
            except Exception as e:
                self._quarantine(path, reason or "load", e)
                depth += 1
                _M_FALLBACKS.inc()
                continue
            _M_FALLBACK_DEPTH.set(depth)
            return step + 1
        _M_FALLBACK_DEPTH.set(depth)
        if model_mutated:
            # a quarantined attempt got as far as assigning the model's
            # weights before its optimizer group failed, and no later
            # candidate overwrote them: returning 0 ("train fresh")
            # would silently train on a corrupt checkpoint's weights
            raise RuntimeError(
                "resume() exhausted all checkpoints after partially "
                "loading a quarantined one — the model now holds that "
                "checkpoint's weights; reinitialize it before training "
                "from scratch")
        return 0

    def _recover_replaced(self):
        """Undo a crash inside _commit's re-save window: the only
        complete copy of step N may sit under `step_N.old` — committed
        in every respect but the name, which discovery ignores and the
        stale sweep would eventually destroy. Rename it back so the
        fallback chain can use it. An uncommitted `step_N` squatting on
        the name is the dead re-save's droppings (no valid `.done` by
        the commit ordering) and is cleared first; if the re-save DID
        commit, its `.old` is redundant and left for the stale sweep."""
        from ..checkpoint import DONE_NAME, parse_done
        for name in sorted(os.listdir(self.ckpt_dir)):
            m = re.fullmatch(r"(step_\d+)\.old(\.\d+)?", name)
            if not m:
                continue
            src = os.path.join(self.ckpt_dir, name)
            if parse_done(os.path.join(src, DONE_NAME)) is None:
                continue
            dst = os.path.join(self.ckpt_dir, m.group(1))
            if parse_done(os.path.join(dst, DONE_NAME)) is not None:
                continue
            if os.path.exists(dst):
                _rmtree_checkpoint(dst)
            try:
                os.replace(src, dst)
            except OSError as e:
                # the squatter's deletion can partially fail (NFS
                # silly-renames, EACCES — swallowed by rmtree above);
                # recovery must degrade to "not this restart", keeping
                # the .old for a later attempt, never crash-loop
                # resume() on the way to the fallback chain
                telemetry.event("checkpoint.recover_error", src=src,
                                error=f"{type(e).__name__}: {e}")
                continue
            telemetry.event("checkpoint.recovered", path=dst, src=src)

    def _quarantine(self, path: str, reason: str, err: Exception):
        """step_N -> step_N.corrupt (first free suffix), so the bad
        checkpoint leaves the resume chain but stays inspectable."""
        dst = _free_suffixed(path, ".corrupt")
        try:
            os.replace(path, dst)
        except OSError:
            # cannot rename (permissions? foreign mount?): delete the
            # .done marker instead so discovery stops trusting it
            from ..checkpoint import DONE_NAME
            try:
                os.remove(os.path.join(path, DONE_NAME))
            except OSError:
                pass
            dst = path
        else:
            _touch(dst, self._clock())
        _M_CORRUPT.inc(reason=reason)
        telemetry.event("checkpoint.quarantine", path=path,
                        quarantined_as=dst, reason=reason,
                        error=f"{type(err).__name__}: {err}")

    # -- save (atomic commit protocol) ---------------------------------
    def maybe_save(self, step: int, model, optimizer=None) -> bool:
        if (step + 1) % self.save_interval_steps:
            return False
        self.save(step, model, optimizer)
        return True

    def save(self, step: int, model, optimizer=None):
        """Write checkpoint `step` via tmp + manifest + rename + `.done`
        (class docstring). Write/finalize failures are retried up to
        `save_retries` total attempts with exponential backoff; the tmp
        directory is torn down between attempts so a retry never
        commits a mix of two attempts' files."""
        from ..launch import restart_backoff
        final = os.path.join(self.ckpt_dir, f"step_{step}")
        tmp = final + ".tmp"
        for attempt in range(1, self.save_retries + 1):
            try:
                self._write_tmp(tmp, step, model, optimizer)
                self._commit(tmp, final, step)
                break
            except Exception:
                # the torn tmp dir is deliberately LEFT on disk — the
                # identical state a hard kill leaves. The next attempt
                # (or any later save of this step) clears it first, and
                # _gc sweeps it once stale; discovery never trusts it.
                if attempt == self.save_retries:
                    raise
                delay = restart_backoff(attempt, self.retry_backoff,
                                        self.retry_backoff_max,
                                        self._rng)
                _M_SAVE_RETRIES.inc()
                telemetry.event("checkpoint.save_retry", step=step,
                                attempt=attempt, delay_s=delay)
                if delay > 0:
                    self._sleep(delay)
        try:
            self._gc()
        except Exception as e:
            # the checkpoint above COMMITTED: failing the train loop
            # because cleanup of old checkpoints hiccuped (NFS race,
            # ENOSPC during rmtree) would trade durability for tidiness
            telemetry.event("checkpoint.gc_error",
                            error=f"{type(e).__name__}: {e}")

    def _write_tmp(self, tmp: str, step: int, model, optimizer):
        from ..checkpoint import (build_manifest, flat_arrays,
                                  save_state_dict, write_manifest)
        shutil.rmtree(tmp, ignore_errors=True)   # leftovers of a crash
        groups = {"model": model.state_dict()}
        if optimizer is not None and hasattr(optimizer, "state_dict"):
            sd = optimizer.state_dict()
            if sd:
                groups["opt"] = sd
        flats = {}
        for name, sd in groups.items():
            save_state_dict(sd, os.path.join(tmp, name))
            flats[name] = flat_arrays(sd)
        # manifest LAST, after every group's bytes: its presence asserts
        # the writer got through all data writes
        write_manifest(tmp, build_manifest(flats, step=step,
                                           wall_time=self._clock()))

    def _commit(self, tmp: str, final: str, step: int):
        from ..checkpoint import write_done
        fault_point("checkpoint.finalize")
        replaced = None
        if os.path.exists(final):
            # re-save of the same step (resumed job repeating the
            # interval): the fresh tmp replaces the old dir wholesale.
            # Never rmtree the live dir here — a crash mid-delete would
            # destroy what may be the only complete copy of this step.
            # Move it aside atomically and drop it only after the fresh
            # dir is fully committed; a crash in between leaves the old
            # copy intact (with its .done) under the .old name, which
            # the next resume()'s _recover_replaced renames back.
            replaced = _free_suffixed(final, ".old")
            os.replace(final, replaced)
            _touch(replaced, self._clock())
        os.replace(tmp, final)
        # .done marker strictly after the rename: a crash between the
        # two leaves a manifest-complete but UNcommitted dir, which
        # discovery ignores — same discipline as heartbeat()
        write_done(final, step=step, wall_time=self._clock())
        if replaced is not None:
            _rmtree_checkpoint(replaced)

    # -- gc ------------------------------------------------------------
    def _gc(self):
        """Prune old checkpoints. Only COMPLETE (`.done`-committed)
        checkpoints count toward `keep_last`, and the newest complete
        checkpoint is never deleted (even with keep_last=0 a crash must
        always find something to resume from). Incomplete `step_N` /
        `step_N.tmp` / `step_N.corrupt` / `step_N.old` dirs are swept
        separately, and only once older than `stale_grace` seconds — a
        live writer's tmp dir is younger than that by construction, and
        quarantine/move-aside renames restart the clock (`_touch`)."""
        fault_point("elastic.gc")
        complete = complete_checkpoints(self.ckpt_dir)   # newest first
        keep = max(1, self.keep_last)
        for _, path in complete[keep:]:
            _rmtree_checkpoint(path)
        complete_names = {os.path.basename(p) for _, p in complete}
        now = self._clock()
        for name in os.listdir(self.ckpt_dir):
            if name in complete_names:
                continue
            if not re.fullmatch(
                    r"step_\d+(\.tmp|(\.corrupt|\.old)(\.\d+)?)?", name):
                continue
            path = os.path.join(self.ckpt_dir, name)
            try:
                # only a live writer mutates files deep inside a dir,
                # and only under `.tmp` — for `.corrupt`/`.old`/bare
                # dirs the top-level mtime (stamped by the rename's
                # _touch, or frozen at the crash) suffices, sparing a
                # full stat walk of a multi-GB dir on every save
                if name.endswith(".tmp"):
                    age = now - _newest_mtime(path)
                else:
                    age = now - os.stat(path).st_mtime
            except OSError:
                continue
            if age > self.stale_grace:
                _rmtree_checkpoint(path)
                telemetry.event("checkpoint.gc_stale", path=path,
                                age_s=age)


class HeartbeatMembership:
    """File-backed membership + heartbeat watch — the launcher-local
    form of the reference ElasticManager's ETCD register/watch
    («.../fleet/elastic/manager.py»: register np, watch peers, classify
    scale-up/down). Workers on one host (or a shared filesystem)
    register by writing `<dir>/worker_<rank>.hb` timestamps from a
    daemon thread; the watcher classifies peers dead after
    `timeout` seconds of silence, and `poll()` reports joins/deaths so
    a controller can relaunch (checkpoint-restart does the resume).
    """

    def __init__(self, dir: str, rank: Optional[int] = None,
                 interval: float = 1.0, timeout: float = 5.0,
                 clock=None):
        self.dir = dir
        self.rank = rank
        self.interval = interval
        self.timeout = timeout
        # injectable clock: deterministic freshness tests (the clock
        # only feeds the mtime comparison, never the beat contents)
        self._clock = clock if clock is not None else time.time
        self._stop = False
        self._thread = None
        self._last_alive: set = set()
        self._staleness_ranks: set = set()  # gauge series this watcher
        # exported; retired when the beat file disappears
        os.makedirs(dir, exist_ok=True)

    # -- worker side ---------------------------------------------------
    def _beat_path(self, rank: int) -> str:
        return os.path.join(self.dir, f"worker_{rank}.hb")

    def start(self):
        """Register this worker and heartbeat from a daemon thread
        (restartable: a stopped membership can start() again)."""
        assert self.rank is not None, "worker needs a rank"
        self._stop = False
        import threading

        def beat():
            while not self._stop:
                self.heartbeat()
                time.sleep(self.interval)

        self.heartbeat()                  # register immediately
        self._thread = threading.Thread(target=beat, daemon=True)
        self._thread.start()
        return self

    def heartbeat(self):
        """One manual beat (for loops that prefer explicit control).
        Atomic write (tmp + rename): a reader must never observe a
        truncated/empty file and misclassify the worker as dead. The
        payload comes from the injectable clock — freshness uses the
        file's mtime, so the content only needs to parse as a
        timestamp (`_beat_valid`), and a fake-clock test writes
        fake-clock beats."""
        assert self.rank is not None
        tmp = self._beat_path(self.rank) + ".tmp"
        with open(tmp, "w") as f:
            f.write(str(self._clock()))
        os.replace(tmp, self._beat_path(self.rank))

    def stop(self):
        self._stop = True
        if self._thread is not None:
            self._thread.join(timeout=2 * self.interval)
        if self.rank is not None:
            try:
                os.remove(self._beat_path(self.rank))
            except OSError:
                pass

    def __enter__(self):
        return self.start()

    def __exit__(self, *a):
        self.stop()
        return False

    # -- watcher side --------------------------------------------------
    @staticmethod
    def _beat_valid(path: str) -> bool:
        """A beat counts only if its payload parses as a timestamp.
        Our writer is atomic (tmp + rename), but on filesystems without
        atomic rename (some network/FUSE mounts) — or with foreign
        writers — a reader can observe a truncated/empty file. Treat
        any such corrupt beat as STALE rather than raising: a mid-write
        worker will land a valid beat within one interval, and a watcher
        crash-looping on a garbage file would be strictly worse."""
        try:
            with open(path) as f:
                float(f.read().strip())
            return True
        except (OSError, ValueError):
            return False

    def alive(self) -> set:
        """Ranks with a fresh heartbeat. Freshness uses the heartbeat
        file's mtime (stamped by the filesystem, which on a shared FS is
        the server clock) rather than the writer's embedded timestamp —
        cross-host clock skew must not misclassify live workers. A beat
        exactly `timeout` old still counts; corrupt beats never do."""
        now = self._clock()
        out = set()
        seen_ranks = set()
        for name in os.listdir(self.dir):
            m = re.fullmatch(r"worker_(\d+)\.hb", name)
            if not m:
                continue
            path = os.path.join(self.dir, name)
            try:
                ts = os.stat(path).st_mtime
            except OSError:
                continue
            seen_ranks.add(m.group(1))
            _M_HB_STALENESS.set(now - ts, rank=m.group(1))
            if now - ts <= self.timeout and self._beat_valid(path):
                out.add(int(m.group(1)))
        # a departed worker (stop() removed its beat file) must not keep
        # exporting its last staleness value forever — retire the series
        for rank in self._staleness_ranks - seen_ranks:
            _M_HB_STALENESS.remove(rank=rank)
        self._staleness_ranks = seen_ranks
        return out

    def wait_for_peers(self, np_: int, timeout: float = 60.0,
                       sleep=time.sleep) -> set:
        """Block until np_ workers are registered (rendezvous barrier).

        The deadline runs on the injectable `self._clock` (NOT
        `time.time()`), so tests drive it deterministically with a fake
        clock; pass a `sleep` that advances that clock, or the loop
        would spin on a frozen one. Always checks at least once, even
        with timeout <= 0."""
        deadline = self._clock() + timeout
        while True:
            a = self.alive()
            if len(a) >= np_:
                self._last_alive = a
                return a
            if self._clock() >= deadline:
                break
            sleep(self.interval / 2)
        raise TimeoutError(
            f"only {len(a)}/{np_} workers registered within "
            f"{timeout}s")

    def poll(self) -> dict:
        """Membership delta since the last poll: {'alive', 'joined',
        'dead', 'event'} with event in (None, 'scale_up', 'scale_down')
        — the reference's scale classification."""
        a = self.alive()
        joined = a - self._last_alive
        dead = self._last_alive - a
        event = None
        if dead:
            event = "scale_down"
        elif joined and self._last_alive:
            event = "scale_up"
        self._last_alive = a
        if event is not None:
            _M_MEMBERSHIP_EVENTS.inc(event=event)
            telemetry.event("elastic.membership", event=event,
                            alive=sorted(a), joined=sorted(joined),
                            dead=sorted(dead))
        return {"alive": a, "joined": joined, "dead": dead,
                "event": event}
