"""Elastic training — checkpoint-restart based.

≙ reference «python/paddle/distributed/fleet/elastic/manager.py»
(ElasticManager: ETCD membership, peer watch, scale-up/down classification,
kill + relaunch with new ranks — SURVEY.md §5 "Failure detection").

TPU-native: there is no ETCD and no per-device process set to re-rank.
Elasticity is (1) the launch CLI's restart-on-failure loop
(distributed.launch --elastic_level), (2) fast resume from the latest
async sharded checkpoint (distributed.checkpoint — restore reshapes onto
whatever mesh the restarted job has), and (3) coordinator health from
jax.distributed. This module provides the train-loop-side helper: periodic
checkpoints + latest-checkpoint discovery on restart.
"""
from __future__ import annotations

import os
import re
import time
from typing import Optional

from ... import observability as telemetry

__all__ = ["ElasticManager", "latest_checkpoint", "HeartbeatMembership"]

_M_HB_STALENESS = telemetry.gauge(
    "pdt_elastic_heartbeat_staleness_seconds",
    "Seconds since each worker's last heartbeat, sampled at alive().",
    ("rank",))
_M_MEMBERSHIP_EVENTS = telemetry.counter(
    "pdt_elastic_membership_events_total",
    "Membership deltas observed by poll(), by classification.",
    ("event",))


def latest_checkpoint(ckpt_dir: str) -> Optional[str]:
    """Newest step-numbered checkpoint directory under ckpt_dir, or None."""
    if not os.path.isdir(ckpt_dir):
        return None
    best = None
    best_step = -1
    for name in os.listdir(ckpt_dir):
        m = re.fullmatch(r"step_(\d+)", name)
        if m and int(m.group(1)) > best_step:
            done = os.path.join(ckpt_dir, name, ".done")
            if os.path.exists(done):
                best_step = int(m.group(1))
                best = os.path.join(ckpt_dir, name)
    return best


class ElasticManager:
    """Checkpoint-cadence + resume bookkeeping for an elastic train loop.

    Usage::

        em = ElasticManager(ckpt_dir, save_interval_steps=100)
        start = em.resume(model, opt)      # 0 if fresh
        for step in range(start, total):
            loss = train_step(...)
            em.maybe_save(step, model, opt)
    """

    def __init__(self, ckpt_dir: str, save_interval_steps: int = 100,
                 keep_last: int = 2):
        self.ckpt_dir = ckpt_dir
        self.save_interval_steps = save_interval_steps
        self.keep_last = keep_last
        os.makedirs(ckpt_dir, exist_ok=True)

    def resume(self, model, optimizer=None) -> int:
        """Restore the newest complete checkpoint; returns the next step."""
        from ..checkpoint import load_state_dict, load_state_dict_raw
        path = latest_checkpoint(self.ckpt_dir)
        if path is None:
            return 0
        load_state_dict(model.state_dict(), os.path.join(path, "model"))
        if optimizer is not None and hasattr(optimizer, "set_state_dict"):
            opt_path = os.path.join(path, "opt")
            if os.path.isdir(opt_path):
                # raw restore: optimizer accumulators are created lazily,
                # so there is no target structure to reshard onto yet
                optimizer.set_state_dict(load_state_dict_raw(opt_path))
        return int(re.search(r"step_(\d+)$", path).group(1)) + 1

    def maybe_save(self, step: int, model, optimizer=None) -> bool:
        if (step + 1) % self.save_interval_steps:
            return False
        self.save(step, model, optimizer)
        return True

    def save(self, step: int, model, optimizer=None):
        from ..checkpoint import save_state_dict
        path = os.path.join(self.ckpt_dir, f"step_{step}")
        save_state_dict(model.state_dict(), os.path.join(path, "model"))
        if optimizer is not None and hasattr(optimizer, "state_dict"):
            sd = optimizer.state_dict()
            if sd:
                save_state_dict(sd, os.path.join(path, "opt"))
        with open(os.path.join(path, ".done"), "w") as f:
            f.write(str(time.time()))
        self._gc()

    def _gc(self):
        steps = sorted(
            (int(m.group(1)) for m in (re.fullmatch(r"step_(\d+)", n)
                                       for n in os.listdir(self.ckpt_dir))
             if m))
        for s in steps[:-self.keep_last]:
            import shutil
            shutil.rmtree(os.path.join(self.ckpt_dir, f"step_{s}"),
                          ignore_errors=True)


class HeartbeatMembership:
    """File-backed membership + heartbeat watch — the launcher-local
    form of the reference ElasticManager's ETCD register/watch
    («.../fleet/elastic/manager.py»: register np, watch peers, classify
    scale-up/down). Workers on one host (or a shared filesystem)
    register by writing `<dir>/worker_<rank>.hb` timestamps from a
    daemon thread; the watcher classifies peers dead after
    `timeout` seconds of silence, and `poll()` reports joins/deaths so
    a controller can relaunch (checkpoint-restart does the resume).
    """

    def __init__(self, dir: str, rank: Optional[int] = None,
                 interval: float = 1.0, timeout: float = 5.0,
                 clock=None):
        self.dir = dir
        self.rank = rank
        self.interval = interval
        self.timeout = timeout
        # injectable clock: deterministic freshness tests (the clock
        # only feeds the mtime comparison, never the beat contents)
        self._clock = clock if clock is not None else time.time
        self._stop = False
        self._thread = None
        self._last_alive: set = set()
        self._staleness_ranks: set = set()  # gauge series this watcher
        # exported; retired when the beat file disappears
        os.makedirs(dir, exist_ok=True)

    # -- worker side ---------------------------------------------------
    def _beat_path(self, rank: int) -> str:
        return os.path.join(self.dir, f"worker_{rank}.hb")

    def start(self):
        """Register this worker and heartbeat from a daemon thread
        (restartable: a stopped membership can start() again)."""
        assert self.rank is not None, "worker needs a rank"
        self._stop = False
        import threading

        def beat():
            while not self._stop:
                self.heartbeat()
                time.sleep(self.interval)

        self.heartbeat()                  # register immediately
        self._thread = threading.Thread(target=beat, daemon=True)
        self._thread.start()
        return self

    def heartbeat(self):
        """One manual beat (for loops that prefer explicit control).
        Atomic write (tmp + rename): a reader must never observe a
        truncated/empty file and misclassify the worker as dead."""
        assert self.rank is not None
        tmp = self._beat_path(self.rank) + ".tmp"
        with open(tmp, "w") as f:
            f.write(str(time.time()))
        os.replace(tmp, self._beat_path(self.rank))

    def stop(self):
        self._stop = True
        if self._thread is not None:
            self._thread.join(timeout=2 * self.interval)
        if self.rank is not None:
            try:
                os.remove(self._beat_path(self.rank))
            except OSError:
                pass

    def __enter__(self):
        return self.start()

    def __exit__(self, *a):
        self.stop()
        return False

    # -- watcher side --------------------------------------------------
    @staticmethod
    def _beat_valid(path: str) -> bool:
        """A beat counts only if its payload parses as a timestamp.
        Our writer is atomic (tmp + rename), but on filesystems without
        atomic rename (some network/FUSE mounts) — or with foreign
        writers — a reader can observe a truncated/empty file. Treat
        any such corrupt beat as STALE rather than raising: a mid-write
        worker will land a valid beat within one interval, and a watcher
        crash-looping on a garbage file would be strictly worse."""
        try:
            with open(path) as f:
                float(f.read().strip())
            return True
        except (OSError, ValueError):
            return False

    def alive(self) -> set:
        """Ranks with a fresh heartbeat. Freshness uses the heartbeat
        file's mtime (stamped by the filesystem, which on a shared FS is
        the server clock) rather than the writer's embedded timestamp —
        cross-host clock skew must not misclassify live workers. A beat
        exactly `timeout` old still counts; corrupt beats never do."""
        now = self._clock()
        out = set()
        seen_ranks = set()
        for name in os.listdir(self.dir):
            m = re.fullmatch(r"worker_(\d+)\.hb", name)
            if not m:
                continue
            path = os.path.join(self.dir, name)
            try:
                ts = os.stat(path).st_mtime
            except OSError:
                continue
            seen_ranks.add(m.group(1))
            _M_HB_STALENESS.set(now - ts, rank=m.group(1))
            if now - ts <= self.timeout and self._beat_valid(path):
                out.add(int(m.group(1)))
        # a departed worker (stop() removed its beat file) must not keep
        # exporting its last staleness value forever — retire the series
        for rank in self._staleness_ranks - seen_ranks:
            _M_HB_STALENESS.remove(rank=rank)
        self._staleness_ranks = seen_ranks
        return out

    def wait_for_peers(self, np_: int, timeout: float = 60.0) -> set:
        """Block until np_ workers are registered (rendezvous barrier)."""
        deadline = time.time() + timeout
        while time.time() < deadline:
            a = self.alive()
            if len(a) >= np_:
                self._last_alive = a
                return a
            time.sleep(self.interval / 2)
        raise TimeoutError(
            f"only {len(self.alive())}/{np_} workers registered within "
            f"{timeout}s")

    def poll(self) -> dict:
        """Membership delta since the last poll: {'alive', 'joined',
        'dead', 'event'} with event in (None, 'scale_up', 'scale_down')
        — the reference's scale classification."""
        a = self.alive()
        joined = a - self._last_alive
        dead = self._last_alive - a
        event = None
        if dead:
            event = "scale_down"
        elif joined and self._last_alive:
            event = "scale_up"
        self._last_alive = a
        if event is not None:
            _M_MEMBERSHIP_EVENTS.inc(event=event)
            telemetry.event("elastic.membership", event=event,
                            alive=sorted(a), joined=sorted(joined),
                            dead=sorted(dead))
        return {"alive": a, "joined": joined, "dead": dead,
                "event": event}
