"""ZeRO parameter/grad/optimizer-state sharding API.

≙ reference «python/paddle/distributed/sharding/» `group_sharded_parallel`
(GroupShardedStage2/3 + GroupShardedOptimizerStage2,
«.../fleet/meta_parallel/sharding/», SURVEY.md §2.3 Sharding row).

TPU-native: ZeRO is a PLACEMENT, not a wrapper class — parameters (and
therefore their grads and optimizer state, which follow the param sharding
inside the compiled train step) are Shard()-placed over the 'sharding'
mesh axis, and XLA's partitioner emits the reduce-scatter/all-gather
pattern the reference implements with hand-written bucketed broadcasts.
The stage2/stage3 distinction collapses: both are "shard the state; gather
on use", which is exactly GSPMD semantics.
"""
from __future__ import annotations

from typing import Optional

__all__ = ["group_sharded_parallel", "save_group_sharded_model"]


def group_sharded_parallel(model, optimizer, level="p_g_os", scaler=None,
                           group=None, offload=False, sync_buffers=False,
                           buffer_max_size=None, segment_size=None,
                           sync_comm=False, dp_group=None,
                           exclude_layer=None, axis="sharding"):
    """≙ paddle.distributed.sharding.group_sharded_parallel.

    level: 'os' (stage 1), 'os_g' (stage 2), 'p_g_os' (stage 3) — all map
    to sharding the parameters over the `axis` mesh axis; optimizer state
    and grads inherit the placement inside the compiled step.

    Placement report: parameters that could not be sharded (no dim
    divisible by the axis size, or every divisible dim already taken by
    another axis) are NOT silent — they are collected on
    `model._group_sharded_skipped` (list of (name, shape, reason)) and a
    summary warning fires when any parameter stayed replicated.
    """
    import warnings

    from ..mesh import Replicate, Shard, get_mesh, shard_tensor

    mesh = get_mesh()
    if mesh is None or axis not in mesh.dim_names or \
            mesh.get_dim_size(axis) == 1:
        return model, optimizer, scaler

    n = mesh.get_dim_size(axis)
    skipped = []
    named = getattr(model, "named_parameters", None)
    params = (list(named()) if callable(named)
              else [(f"param_{i}", p)
                    for i, p in enumerate(model.parameters())])
    for name, p in params:
        shape = tuple(p._value.shape)
        if p._value.ndim == 0:
            skipped.append((name, shape, "0-d parameter"))
            continue
        # shard the largest divisible dim over the sharding axis
        dims = sorted(range(p._value.ndim),
                      key=lambda d: -p._value.shape[d])
        target = next((d for d in dims if p._value.shape[d] % n == 0),
                      None)
        if target is None:
            skipped.append((name, shape,
                            f"no dim divisible by {axis}={n}"))
            continue
        existing = getattr(p, "dist_attr", None)
        placements = (list(existing[1]) if existing
                      else [Replicate() for _ in mesh.dim_names])
        ax_i = mesh.dim_names.index(axis)
        if not isinstance(placements[ax_i], Replicate):
            continue  # already placed on this axis (not a skip)
        taken = {pl.dim for pl in placements if isinstance(pl, Shard)}
        if target in taken:
            target = next((d for d in dims if p._value.shape[d] % n == 0
                           and d not in taken), None)
            if target is None:
                skipped.append((name, shape,
                                "all divisible dims taken by other "
                                "mesh axes"))
                continue
        placements[ax_i] = Shard(target)
        s = shard_tensor(p, mesh, placements)
        p._value = s._value
        p.dist_attr = s.dist_attr
    model._group_sharded_skipped = skipped
    if skipped:
        warnings.warn(
            f"group_sharded_parallel: {len(skipped)} parameter(s) stayed "
            f"replicated on '{axis}' (see model._group_sharded_skipped): "
            + "; ".join(f"{nm} {sh}: {why}"
                        for nm, sh, why in skipped[:3])
            + ("..." if len(skipped) > 3 else ""))
    return model, optimizer, scaler


def save_group_sharded_model(model, output, optimizer=None):
    """≙ paddle.distributed.sharding.save_group_sharded_model — with GSPMD
    the state_dict is already global; plain save applies."""
    import paddle_tpu as paddle
    paddle.save(model.state_dict(), output + ".pdparams")
    if optimizer is not None and hasattr(optimizer, "state_dict"):
        paddle.save(optimizer.state_dict(), output + ".pdopt")
