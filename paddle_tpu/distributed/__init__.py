"""paddle_tpu.distributed — mesh-based parallelism over ICI/DCN.
≙ reference «python/paddle/distributed/» (SURVEY.md §2.3)."""
from .parallel import (init_parallel_env, get_rank, get_world_size,  # noqa: F401
                       is_initialized, is_available, ParallelEnv)
from .mesh import (ProcessMesh, Placement, Shard, Replicate, Partial,  # noqa: F401
                   ReduceType, shard_tensor, reshard, shard_layer,
                   dtensor_from_local, local_map, create_mesh,
                   create_hybrid_mesh, get_mesh, set_mesh, use_mesh,
                   shard_constraint)
from .collective import (ReduceOp, Group, new_group, all_reduce,  # noqa: F401
                         all_gather, all_gather_object, reduce_scatter,
                         broadcast, reduce, scatter, alltoall,
                         alltoall_single, send, recv, barrier,
                         destroy_process_group, get_backend, get_group)
from .random_ import get_rng_state_tracker  # noqa: F401
from .ring_attention import (ring_flash_attention,  # noqa: F401
                             ring_attention_values,
                             ulysses_flash_attention,
                             ulysses_attention_values)
from . import fleet  # noqa: F401
from .fleet import DataParallel  # noqa: F401


def __getattr__(name):
    import importlib
    if name in ("checkpoint", "launch", "sharding", "auto_parallel"):
        mod = importlib.import_module("." + name, __name__)
        globals()[name] = mod
        return mod
    raise AttributeError(name)


def spawn(func, args=(), nprocs=-1, **kwargs):
    """≙ paddle.distributed.spawn — multi-process worker fork with
    jax.distributed rendezvous (see parallel.spawn). nprocs<=1 runs func
    inline (the TPU runtime is one process per host; the mesh provides
    chip parallelism)."""
    if nprocs <= 1:
        func(*args)
        return [0]
    from .parallel import spawn as _spawn
    return _spawn(func, args=args, nprocs=nprocs, **kwargs)


def split(x, size, operation, axis=0, num_partitions=1, gather_out=True,
          weight_attr=None, bias_attr=None, name=None):
    raise NotImplementedError(
        "paddle.distributed.split: use fleet.meta_parallel "
        "Column/RowParallelLinear / VocabParallelEmbedding placements.")
