"""paddle_tpu.signal — STFT/ISTFT.
≙ reference «python/paddle/signal.py» [U]. Framing is a gather + window
multiply + batched FFT — all XLA-native on TPU."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .core.tensor import Tensor, apply, to_tensor

__all__ = ["frame", "overlap_add", "stft", "istft"]


def _t(x):
    return x if isinstance(x, Tensor) else to_tensor(x)


def frame(x, frame_length, hop_length, axis=-1, name=None):
    """≙ paddle.signal.frame: slice overlapping frames along `axis`."""
    def fn(v):
        n = v.shape[axis]
        num = 1 + (n - frame_length) // hop_length
        starts = jnp.arange(num) * hop_length
        idx = starts[:, None] + jnp.arange(frame_length)[None, :]
        out = jnp.take(v, idx.reshape(-1), axis=axis)
        shape = list(v.shape)
        ax = axis % v.ndim
        new_shape = shape[:ax] + [num, frame_length] + shape[ax + 1:]
        out = out.reshape(new_shape)
        # paddle layout: frame axis after data axis -> (..., frame_length, num)
        return jnp.swapaxes(out, ax, ax + 1)
    return apply("frame", fn, (_t(x),))


def overlap_add(x, hop_length, axis=-1, name=None):
    """≙ paddle.signal.overlap_add: inverse of frame (sum overlaps)."""
    def fn(v):
        # v: (..., frame_length, num_frames) with axis=-1 (default layout)
        if axis not in (-1, v.ndim - 1):
            raise NotImplementedError("overlap_add: axis=-1 only")
        fl = v.shape[-2]
        num = v.shape[-1]
        out_len = (num - 1) * hop_length + fl
        lead = v.shape[:-2]
        v2 = v.reshape((-1, fl, num))

        def body(i, acc):
            seg = jax.lax.dynamic_slice_in_dim(v2, i, 1, axis=2)[..., 0]
            return jax.lax.dynamic_update_slice_in_dim(
                acc, jax.lax.dynamic_slice_in_dim(
                    acc, i * hop_length, fl, axis=1) + seg,
                i * hop_length, axis=1)

        acc = jnp.zeros((v2.shape[0], out_len), v.dtype)
        acc = jax.lax.fori_loop(0, num, body, acc)
        return acc.reshape(*lead, out_len)
    return apply("overlap_add", fn, (_t(x),))


def stft(x, n_fft, hop_length=None, win_length=None, window=None,
         center=True, pad_mode="reflect", normalized=False, onesided=True,
         name=None):
    """≙ paddle.signal.stft. x: (B, T) or (T,) real (or complex with
    onesided=False). Returns (B, n_fft//2+1 | n_fft, num_frames) complex."""
    hop_length = hop_length or n_fft // 4
    win_length = win_length or n_fft
    xt = _t(x)
    win_t = _t(window) if window is not None else None

    def fn(v, *w):
        if w:
            win = w[0].astype(jnp.float32)
        else:
            win = jnp.ones((win_length,), jnp.float32)
        # center-pad window to n_fft
        if win_length < n_fft:
            lp = (n_fft - win_length) // 2
            win = jnp.pad(win, (lp, n_fft - win_length - lp))
        squeeze = v.ndim == 1
        if squeeze:
            v = v[None]
        if center:
            v = jnp.pad(v, ((0, 0), (n_fft // 2, n_fft // 2)),
                        mode=pad_mode)
        n = v.shape[-1]
        num = 1 + (n - n_fft) // hop_length
        starts = jnp.arange(num) * hop_length
        idx = starts[:, None] + jnp.arange(n_fft)[None, :]
        frames = v[:, idx]                      # (B, num, n_fft)
        frames = frames * win[None, None, :]
        if onesided and not jnp.iscomplexobj(frames):
            spec = jnp.fft.rfft(frames, axis=-1)
        else:
            spec = jnp.fft.fft(frames, axis=-1)
        if normalized:
            spec = spec / jnp.sqrt(jnp.asarray(n_fft, jnp.float32))
        out = jnp.swapaxes(spec, -1, -2)        # (B, freq, num)
        return out[0] if squeeze else out
    args = (xt,) + ((win_t,) if win_t is not None else ())
    return apply("stft", fn, args)


def istft(x, n_fft, hop_length=None, win_length=None, window=None,
          center=True, normalized=False, onesided=True, length=None,
          return_complex=False, name=None):
    """≙ paddle.signal.istft (least-squares overlap-add inversion)."""
    hop_length = hop_length or n_fft // 4
    win_length = win_length or n_fft
    xt = _t(x)
    win_t = _t(window) if window is not None else None

    def fn(v, *w):
        if w:
            win = w[0].astype(jnp.float32)
        else:
            win = jnp.ones((win_length,), jnp.float32)
        if win_length < n_fft:
            lp = (n_fft - win_length) // 2
            win = jnp.pad(win, (lp, n_fft - win_length - lp))
        squeeze = v.ndim == 2
        if squeeze:
            v = v[None]
        spec = jnp.swapaxes(v, -1, -2)          # (B, num, freq)
        if normalized:
            spec = spec * jnp.sqrt(jnp.asarray(n_fft, jnp.float32))
        if onesided:
            frames = jnp.fft.irfft(spec, n=n_fft, axis=-1)
        else:
            frames = jnp.fft.ifft(spec, axis=-1)
            if not return_complex:
                frames = frames.real
        frames = frames * win[None, None, :]
        b, num, _ = frames.shape
        out_len = (num - 1) * hop_length + n_fft

        def body(i, carry):
            acc, wsum = carry
            seg = jax.lax.dynamic_slice_in_dim(frames, i, 1, axis=1)[:, 0]
            acc = jax.lax.dynamic_update_slice_in_dim(
                acc, jax.lax.dynamic_slice_in_dim(
                    acc, i * hop_length, n_fft, axis=1) + seg,
                i * hop_length, axis=1)
            wsum = jax.lax.dynamic_update_slice_in_dim(
                wsum, jax.lax.dynamic_slice_in_dim(
                    wsum, i * hop_length, n_fft, axis=0) + win * win,
                i * hop_length, axis=0)
            return acc, wsum

        acc = jnp.zeros((b, out_len), frames.dtype)
        wsum = jnp.zeros((out_len,), jnp.float32)
        acc, wsum = jax.lax.fori_loop(0, num, body, (acc, wsum))
        out = acc / jnp.maximum(wsum, 1e-11)[None, :].astype(acc.dtype)
        if center:
            out = out[:, n_fft // 2: out_len - n_fft // 2]
        if length is not None:
            out = out[:, :length]
        return out[0] if squeeze else out
    args = (xt,) + ((win_t,) if win_t is not None else ())
    return apply("istft", fn, args)
