"""paddle_tpu.quantization — QAT / PTQ.
≙ reference «python/paddle/quantization/» [U]: QuantConfig, QAT (fake-quant
training), PTQ (observer calibration + convert), quanters/observers.

TPU-native: fake-quant is a pure elementwise round-through-STE op that XLA
fuses into the surrounding matmul; int8 inference on TPU lowers through
XLA's int8 dot support (quantized Linear converts to int8 weights +
fp scale)."""
from __future__ import annotations

from typing import Dict, Optional, Type

import numpy as np
import jax
import jax.numpy as jnp

import paddle_tpu as paddle
from ..core.tensor import Tensor, apply, to_tensor
from ..nn.layer.layers import Layer
from ..nn.quant import absmax_round_clip_values

__all__ = ["fake_quant", "quantize_linear", "dequantize_linear",
           "AbsmaxObserver", "EMAObserver", "FakeQuanterWithAbsMax",
           "QuantConfig", "QAT", "PTQ", "QuantedLinear",
           "WeightOnlyLinear", "quantize_model_weight_only",
           "absmax_round_clip_values", "QuantServingConfig"]


def __getattr__(name):
    # QuantServingConfig (the serving engine's quant=... mode) lives in
    # models/serving.py next to SpecConfig; re-exported here lazily so
    # `from paddle_tpu.quantization import QuantServingConfig` works
    # without importing the serving stack at package-import time
    if name == "QuantServingConfig":
        from ..models.serving import QuantServingConfig
        return QuantServingConfig
    raise AttributeError(name)


def _ste_round(x):
    """Round with straight-through-estimator gradient."""
    return x + jax.lax.stop_gradient(jnp.round(x) - x)


def fake_quant(x: Tensor, scale, bit_length=8, channel_axis=None) -> Tensor:
    """Quantize-dequantize with STE. ≙ fake_quantize_dequantize ops [U]."""
    qmax = float(2 ** (bit_length - 1) - 1)

    def fn(v, s):
        if channel_axis is not None:
            shape = [1] * v.ndim
            shape[channel_axis] = -1
            s = s.reshape(shape)
        q = absmax_round_clip_values(v, s, qmax, round_fn=_ste_round)
        return q * jnp.maximum(s, 1e-9) / qmax
    s_t = scale if isinstance(scale, Tensor) else to_tensor(scale)
    return apply("fake_quant", fn, (x, s_t))


def quantize_linear(x: Tensor, scale, zero_point=0, bit_length=8,
                    axis=None) -> Tensor:
    qmax = float(2 ** (bit_length - 1) - 1)

    def fn(v, s):
        if axis is not None:
            shape = [1] * v.ndim
            shape[axis] = -1
            s = s.reshape(shape)
        return absmax_round_clip_values(v, s, qmax, out_dtype=jnp.int8)
    s_t = scale if isinstance(scale, Tensor) else to_tensor(scale)
    return apply("quantize_linear", fn, (x, s_t))


def dequantize_linear(x: Tensor, scale, zero_point=0, bit_length=8,
                      axis=None) -> Tensor:
    qmax = float(2 ** (bit_length - 1) - 1)

    def fn(v, s):
        if axis is not None:
            shape = [1] * v.ndim
            shape[axis] = -1
            s = s.reshape(shape)
        return v.astype(jnp.float32) * s / qmax
    s_t = scale if isinstance(scale, Tensor) else to_tensor(scale)
    return apply("dequantize_linear", fn, (x, s_t))


class AbsmaxObserver:
    """PTQ calibration observer: running abs-max. ≙ quantization
    observers [U]."""

    def __init__(self, quant_bits=8, channel_axis=None):
        self.quant_bits = quant_bits
        self.channel_axis = channel_axis
        self._scale = None

    def observe(self, x):
        v = x._value if isinstance(x, Tensor) else jnp.asarray(x)
        if self.channel_axis is not None:
            axes = tuple(i for i in range(v.ndim)
                         if i != self.channel_axis)
            m = jnp.max(jnp.abs(v), axis=axes)
        else:
            m = jnp.max(jnp.abs(v))
        self._scale = m if self._scale is None else jnp.maximum(
            self._scale, m)

    def scale(self):
        return self._scale if self._scale is not None else jnp.float32(1.0)


class EMAObserver(AbsmaxObserver):
    """Exponential-moving-average abs-max (activation observer)."""

    def __init__(self, quant_bits=8, decay=0.9):
        super().__init__(quant_bits)
        self.decay = decay

    def observe(self, x):
        v = x._value if isinstance(x, Tensor) else jnp.asarray(x)
        m = jnp.max(jnp.abs(v))
        self._scale = m if self._scale is None else \
            self.decay * self._scale + (1 - self.decay) * m


class FakeQuanterWithAbsMax(Layer):
    """QAT quanter: fake-quant with live abs-max scale (weight) or EMA
    (activation). ≙ FakeQuanterWithAbsMaxObserver [U]."""

    def __init__(self, quant_bits=8, dtype="float32", name=None,
                 moving_rate=0.9, is_weight=False, channel_axis=None):
        super().__init__()
        self.quant_bits = quant_bits
        self.channel_axis = channel_axis
        self.is_weight = is_weight
        self.moving_rate = moving_rate
        self._ema = None

    def forward(self, x):
        v = x._value
        if self.channel_axis is not None:
            axes = tuple(i for i in range(v.ndim)
                         if i != self.channel_axis)
            scale = jnp.max(jnp.abs(jax.lax.stop_gradient(v)), axis=axes)
        else:
            scale = jnp.max(jnp.abs(jax.lax.stop_gradient(v)))
            if not self.is_weight:
                self._ema = scale if self._ema is None else (
                    self.moving_rate * self._ema
                    + (1 - self.moving_rate) * scale)
                scale = self._ema
        return fake_quant(x, Tensor(scale), self.quant_bits,
                          self.channel_axis)


class QuantedLinear(Layer):
    """Linear with weight+activation fake-quant (QAT) or int8 weights
    (converted). ≙ quantized nn.QuantedLinear [U]."""

    def __init__(self, linear, q_config=None):
        super().__init__()
        self.linear = linear
        cfg = q_config or QuantConfig()
        self.weight_quanter = FakeQuanterWithAbsMax(
            cfg.weight_bits, is_weight=True, channel_axis=1)
        self.act_quanter = FakeQuanterWithAbsMax(
            cfg.activation_bits, is_weight=False)
        self._converted = False

    def forward(self, x):
        from ..nn import functional as F
        if self._converted == "w8a8":
            # MXU-native int8 execution: dynamic per-tensor activation
            # scale, per-channel weight scale, int8xint8->int32 dot
            from ..nn.quant import (int8_dot_values,
                                    quantize_activation_dynamic_values)
            iw, ws = self._int_weight, self._w_scale
            bias = self.linear.bias

            def fn(xv, wv, sv, *b):
                xq, xs = quantize_activation_dynamic_values(xv)
                out = int8_dot_values(xq, wv, xs, sv)
                if b:
                    out = out + b[0].astype(out.dtype)
                return out.astype(xv.dtype)
            args = (x, iw, ws) + ((bias,) if bias is not None else ())
            return apply("quanted_linear_w8a8", fn, args)
        if self._converted:
            wq = dequantize_linear(self._int_weight, self._w_scale,
                                   axis=1)
            return F.linear(x, wq, self.linear.bias)
        xq = self.act_quanter(x)
        wq = self.weight_quanter(self.linear.weight)
        return F.linear(xq, wq, self.linear.bias)

    def convert(self, mode: str = "dequant"):
        """Freeze: int8 weights + per-channel scales.

        mode='dequant' — weights stored int8, dequantized into the fp
        matmul (weight-only memory win). mode='w8a8' — activations
        dynamically quantized per call and the matmul runs on the MXU's
        int8 path (datasheet 2x-peak; 1.22x measured on v5e, r5 chip gate;
        ≙ the cuBLASLt int8 fused linear)."""
        if mode not in ("dequant", "w8a8"):
            raise ValueError(f"unknown convert mode {mode!r}")
        w = self.linear.weight
        scale = jnp.max(jnp.abs(w._value), axis=0)
        self._w_scale = Tensor(scale.astype(jnp.float32))
        self._int_weight = quantize_linear(w, self._w_scale, axis=1)
        self._converted = mode
        return self


class WeightOnlyLinear(Layer):
    """Serving-path Linear with int8/int4 weights in HBM, dequantized on
    the fly into the bf16 matmul (≙ paddle.nn.quant weight-only path for
    LLM decode — HBM-bandwidth-bound, so 1/2 or 1/4 the weight bytes is
    a direct decode speedup)."""

    def __init__(self, linear, weight_dtype: str = "int8",
                 group_size: int = -1):
        super().__init__()
        from ..nn.quant import weight_quantize_values
        self.weight_dtype = weight_dtype
        self.group_size = group_size
        self._algo = f"weight_only_{weight_dtype}"
        qw, sc = weight_quantize_values(
            linear.weight._value, self._algo, group_size)
        self.register_buffer("quant_weight", Tensor(qw))
        self.register_buffer("weight_scale", Tensor(sc))
        self.bias = linear.bias
        self.in_features = linear.weight.shape[0]
        self.out_features = linear.weight.shape[1]

    def forward(self, x):
        from ..nn.quant import weight_only_linear
        return weight_only_linear(
            x, self.quant_weight, bias=self.bias,
            weight_scale=self.weight_scale,
            weight_dtype=self.weight_dtype, group_size=self.group_size)


def quantize_model_weight_only(model, weight_dtype: str = "int8",
                               group_size: int = -1, exclude=()):
    """Swap every nn.Linear in `model` for a WeightOnlyLinear (the LLM
    serving conversion; pass e.g. exclude=('lm_head',) to keep the
    output head in full precision). Returns the model, modified in
    place. Layers that cannot be quantized (odd in-features for int4,
    in-features not divisible by group_size) are left in fp, collected
    on `model._weight_only_skipped`, and warned about — never a
    mid-walk crash with a half-converted model."""
    import warnings

    from ..nn import Linear
    skipped = []
    for parent in model.sublayers(include_self=True):
        for name, sub in list(parent._sub_layers.items()):
            if not isinstance(sub, Linear) or name in exclude:
                continue
            k = sub.weight.shape[0]
            if weight_dtype == "int4" and k % 2:
                skipped.append((name, tuple(sub.weight.shape),
                                "odd in-features for int4 packing"))
                continue
            if group_size not in (-1, None) and k % int(group_size):
                skipped.append((name, tuple(sub.weight.shape),
                                f"in-features not divisible by "
                                f"group_size={group_size}"))
                continue
            # setattr, not _sub_layers[name]=...: sublayers also live in
            # the instance __dict__, and attribute-access forwards
            # (self.q_proj(x)) would otherwise keep the stale fp layer
            setattr(parent, name, WeightOnlyLinear(sub, weight_dtype,
                                                   group_size))
    model._weight_only_skipped = skipped
    if skipped:
        warnings.warn(
            f"quantize_model_weight_only: {len(skipped)} layer(s) left "
            "in fp (see model._weight_only_skipped): "
            + "; ".join(f"{nm} {sh}: {why}"
                        for nm, sh, why in skipped[:3])
            + ("..." if len(skipped) > 3 else ""))
    return model


class QuantConfig:
    """≙ paddle.quantization.QuantConfig."""

    def __init__(self, activation=None, weight=None, weight_bits=8,
                 activation_bits=8):
        self.activation = activation
        self.weight = weight
        self.weight_bits = weight_bits
        self.activation_bits = activation_bits
        self._layer_types: Dict[Type, Type] = {}

    def add_type_config(self, layer_type, activation=None, weight=None):
        self._layer_types[layer_type] = (activation, weight)


def _swap_linears(model, fn):
    from ..nn import Linear
    for parent in model.sublayers(include_self=True):
        for name, sub in list(parent._sub_layers.items()):
            if isinstance(sub, Linear):
                # setattr keeps _sub_layers and the instance __dict__ in
                # sync (attribute-access forwards see the new layer)
                setattr(parent, name, fn(sub))
    return model


class QAT:
    """Quantization-aware training driver. ≙ paddle.quantization.QAT."""

    def __init__(self, q_config=None):
        self.q_config = q_config or QuantConfig()

    def quantize(self, model, inplace=False):
        return _swap_linears(model,
                             lambda lin: QuantedLinear(lin, self.q_config))

    def convert(self, model, inplace=False):
        for sub in model.sublayers(include_self=True):
            if isinstance(sub, QuantedLinear) and not sub._converted:
                sub.convert()
        return model


class PTQ:
    """Post-training quantization: observe activations on calibration
    data, then convert. ≙ paddle.quantization.PTQ."""

    def __init__(self, q_config=None):
        self.q_config = q_config or QuantConfig()
        self._observers = []

    def quantize(self, model, inplace=False):
        ptq = self

        class _ObservedLinear(Layer):
            def __init__(self, lin):
                super().__init__()
                self.linear = lin
                self.obs = EMAObserver(ptq.q_config.activation_bits)
                ptq._observers.append(self.obs)

            def forward(self, x):
                self.obs.observe(x)
                return self.linear(x)

        return _swap_linears(model, _ObservedLinear)

    def convert(self, model, inplace=False):
        def conv(sub):
            ql = QuantedLinear(sub.linear
                               if hasattr(sub, "linear") else sub,
                               self.q_config)
            ql.convert()
            return ql

        for parent in model.sublayers(include_self=True):
            for name, sub in list(parent._sub_layers.items()):
                if sub.__class__.__name__ == "_ObservedLinear":
                    setattr(parent, name, conv(sub))
        return model
