"""paddle.callbacks namespace — re-export of the hapi callback set.
≙ reference «python/paddle/callbacks/» (alias tier over hapi) [U]."""
from .hapi.callbacks import (Callback, CallbackList, EarlyStopping,  # noqa: F401
                             LRSchedulerCallback as LRScheduler,
                             ModelCheckpoint, ProgBarLogger, VisualDL)
