"""paddle_tpu.text — tokenization + tokenized-dataset tier.

≙ the reference ecosystem's dataset/tokenizer layer (PaddleNLP tokenizers
and `paddle.text` datasets — outside-repo model zoo per SURVEY.md §1, and
the §2.2 vision/audio/text row). Offline-first design: a byte-level
tokenizer (no vocab files, 256+special ids — every string round-trips), a
whitespace/word tokenizer with a built vocab, and block datasets that
deterministically produce the LM / MLM batch shapes the north-star
recipes need, from either a file-backed token stream (np.memmap over a
.bin of uint16/uint32 ids, or raw .txt) or a synthetic generator.
"""
from __future__ import annotations

import os
from typing import Iterable, Optional

import numpy as np

from ..io import Dataset

__all__ = ["ByteTokenizer", "WordTokenizer", "Vocab", "LMBlockDataset",
           "MLMBlockDataset", "SyntheticTokens", "FileTokens",
           "encode_file", "BPETokenizer", "viterbi_decode",
           "ViterbiDecoder"]


class ByteTokenizer:
    """UTF-8 byte-level tokenizer: ids 0..255 are bytes; specials follow.
    No files, no OOV, exact round-trip — the offline-friendly default."""

    def __init__(self, specials=("<pad>", "<unk>", "<s>", "</s>",
                                 "<mask>")):
        self.specials = list(specials)
        self._special_ids = {s: 256 + i for i, s in enumerate(specials)}

    @property
    def vocab_size(self) -> int:
        return 256 + len(self.specials)

    @property
    def pad_id(self):
        return self._special_ids.get("<pad>")

    @property
    def mask_id(self):
        return self._special_ids.get("<mask>")

    @property
    def bos_id(self):
        return self._special_ids.get("<s>")

    @property
    def eos_id(self):
        return self._special_ids.get("</s>")

    def encode(self, text: str, add_bos=False, add_eos=False):
        ids = list(text.encode("utf-8"))
        if add_bos and self.bos_id is not None:
            ids = [self.bos_id] + ids
        if add_eos and self.eos_id is not None:
            ids = ids + [self.eos_id]
        return np.asarray(ids, np.int32)

    def decode(self, ids) -> str:
        bs = bytes(int(i) for i in np.asarray(ids).ravel() if 0 <= i < 256)
        return bs.decode("utf-8", errors="replace")


class Vocab:
    """token <-> id table with specials first. ≙ paddlenlp Vocab [U?]."""

    def __init__(self, tokens: Iterable[str],
                 specials=("<pad>", "<unk>", "<s>", "</s>", "<mask>")):
        self.itos = list(specials) + [t for t in tokens
                                      if t not in set(specials)]
        self.stoi = {t: i for i, t in enumerate(self.itos)}
        self.unk_id = self.stoi.get("<unk>", 0)

    def __len__(self):
        return len(self.itos)

    def __getitem__(self, tok: str) -> int:
        return self.stoi.get(tok, self.unk_id)


class WordTokenizer:
    """Whitespace/word tokenizer over a built Vocab."""

    def __init__(self, vocab: Vocab, lowercase: bool = True):
        self.vocab = vocab
        self.lowercase = lowercase

    @staticmethod
    def build(texts: Iterable[str], max_vocab: int = 30000,
              lowercase: bool = True) -> "WordTokenizer":
        from collections import Counter
        c: Counter = Counter()
        for t in texts:
            c.update((t.lower() if lowercase else t).split())
        toks = [w for w, _ in c.most_common(max_vocab)]
        return WordTokenizer(Vocab(toks), lowercase)

    @property
    def vocab_size(self):
        return len(self.vocab)

    @property
    def pad_id(self):
        return self.vocab.stoi.get("<pad>")

    @property
    def mask_id(self):
        return self.vocab.stoi.get("<mask>")

    def encode(self, text: str):
        t = text.lower() if self.lowercase else text
        return np.asarray([self.vocab[w] for w in t.split()], np.int32)

    def decode(self, ids):
        return " ".join(self.vocab.itos[int(i)] for i in np.asarray(
            ids).ravel() if 0 <= int(i) < len(self.vocab))


# -- token sources -----------------------------------------------------------
class SyntheticTokens:
    """Deterministic synthetic token stream (CI / smoke runs)."""

    def __init__(self, vocab_size: int, length: int, seed: int = 0):
        rng = np.random.default_rng(seed)
        self.ids = rng.integers(0, vocab_size, length, dtype=np.int32)
        self.vocab_size = vocab_size


class FileTokens:
    """File-backed token stream.

    .bin → zero-copy np.memmap of uint16/uint32 ids (dtype by header-less
    convention: uint16 when vocab fits, else uint32 — pass `dtype`);
    .txt → tokenized on load with the given tokenizer.
    """

    def __init__(self, path: str, tokenizer=None, dtype=None):
        if path.endswith(".bin"):
            dt = dtype or np.uint16
            self.ids = np.memmap(path, dtype=dt, mode="r")
            self.vocab_size = int(self.ids.max()) + 1 if len(self.ids) \
                else 0
        else:
            tok = tokenizer or ByteTokenizer()
            with open(path, "r", encoding="utf-8") as f:
                self.ids = tok.encode(f.read())
            self.vocab_size = tok.vocab_size


def encode_file(src_txt: str, dst_bin: str, tokenizer=None,
                dtype=np.uint16) -> int:
    """Tokenize a text file to a flat .bin of ids; returns token count."""
    tok = tokenizer or ByteTokenizer()
    with open(src_txt, "r", encoding="utf-8") as f:
        ids = tok.encode(f.read())
    np.asarray(ids, dtype).tofile(dst_bin)
    return len(ids)


# -- block datasets ----------------------------------------------------------
class LMBlockDataset(Dataset):
    """Next-token-prediction blocks: item = (input [S], label [S]) from a
    flat token stream (label = input shifted by one)."""

    def __init__(self, source, seq_len: int):
        self.ids = np.asarray(source.ids, np.int32)
        self.seq_len = seq_len
        self.n = max((len(self.ids) - 1) // seq_len, 0)

    def __len__(self):
        return self.n

    def __getitem__(self, i):
        s = self.seq_len
        chunk = self.ids[i * s: i * s + s + 1]
        return chunk[:-1].copy(), chunk[1:].copy()


class MLMBlockDataset(Dataset):
    """BERT-style masked-LM blocks: item = (masked_input [S], labels [S])
    with labels = -100 except at masked positions (the 80/10/10 rule)."""

    def __init__(self, source, seq_len: int, mask_id: int,
                 vocab_size: Optional[int] = None, mask_prob: float = 0.15,
                 seed: int = 0, ignore_label: int = -100):
        self.ids = np.asarray(source.ids, np.int32)
        self.seq_len = seq_len
        self.mask_id = mask_id
        self.vocab_size = vocab_size or source.vocab_size
        self.mask_prob = mask_prob
        self.seed = seed
        self.ignore = ignore_label
        self.n = max(len(self.ids) // seq_len, 0)

    def __len__(self):
        return self.n

    def __getitem__(self, i):
        rng = np.random.default_rng(self.seed * 1_000_003 + i)
        s = self.seq_len
        block = self.ids[i * s:(i + 1) * s].copy()
        labels = np.full(s, self.ignore, np.int32)
        pick = rng.random(s) < self.mask_prob
        if not pick.any():
            pick[rng.integers(0, s)] = True
        labels[pick] = block[pick]
        r = rng.random(s)
        block[pick & (r < 0.8)] = self.mask_id
        rand = pick & (r >= 0.8) & (r < 0.9)
        block[rand] = rng.integers(0, self.vocab_size,
                                   rand.sum(), dtype=np.int32)
        return block, labels
from .bpe import BPETokenizer  # noqa: F401


def viterbi_decode(potentials, transition_params, lengths=None,
                   include_bos_eos_tag=True, name=None):
    """≙ paddle.text.viterbi_decode / ViterbiDecoder [U]: CRF max-score
    path. potentials (B, T, N) emission scores, transition_params (N, N)
    — the tag dim of both MUST match. With include_bos_eos_tag (the
    reference contract), N *includes* the BOS/EOS tags: the start scores
    are `transitions[-1]` (BOS row), the stop scores `transitions[:, -2]`
    (EOS column), and the decode runs over the first N-2 real labels.
    TPU-first: the forward max-pass and the backtrace are both
    `lax.scan`s inside one jittable program (static shapes; `lengths`
    masks shorter sequences).

    Returns (scores (B,), paths (B, T) int32)."""
    import jax
    import jax.numpy as jnp
    from ..core.tensor import Tensor, apply, to_tensor

    pot = potentials if isinstance(potentials, Tensor) \
        else to_tensor(potentials)
    trans = transition_params if isinstance(transition_params, Tensor) \
        else to_tensor(transition_params)
    lens = (lengths if isinstance(lengths, Tensor)
            else to_tensor(lengths)) if lengths is not None else None

    n_tags = pot.shape[-1]
    if tuple(trans.shape) != (n_tags, n_tags):
        raise ValueError(
            "viterbi_decode: transition_params must be square with the "
            "same tag dim as potentials (got transitions "
            f"{tuple(trans.shape)} vs potentials tag dim {n_tags}). With "
            "include_bos_eos_tag=True the tag dim includes BOS/EOS: "
            "start=transitions[-1], stop=transitions[:, -2].")
    if include_bos_eos_tag and n_tags < 3:
        raise ValueError(
            "viterbi_decode: include_bos_eos_tag=True needs at least one "
            f"real label besides BOS/EOS (got num_tags={n_tags})")

    def fn(p, tr, *rest):
        ln = rest[0] if rest else None
        b, t, _ = p.shape
        if include_bos_eos_tag:
            # reference contract: BOS = last tag, EOS = second-to-last.
            # Decode runs over the FULL tag space — BOS/EOS are only
            # discouraged via their transition scores (the reference
            # seeds alpha at -10000 everywhere but BOS and never slices
            # the tag dim), so a potentials matrix that favors them
            # mid-sequence legitimately selects them, matching upstream.
            n = n_tags
            core = tr
            start = tr[-1, :]        # BOS -> tag
            stop = tr[:, -2]         # tag -> EOS
        else:
            n = n_tags
            core = tr
            start = jnp.zeros((n,), p.dtype)
            stop = jnp.zeros((n,), p.dtype)
        alpha0 = p[:, 0] + start[None, :]
        if ln is None:
            ln_arr = jnp.full((b,), t, jnp.int32)
        else:
            ln_arr = ln.astype(jnp.int32)

        def step(carry, xs):
            alpha, idx = carry
            emit, pos = xs                     # (B, N), scalar
            # scores[b, i, j] = alpha[b, i] + core[i, j]
            s = alpha[:, :, None] + core[None, :, :]
            best_prev = jnp.argmax(s, axis=1)              # (B, N)
            best_score = jnp.max(s, axis=1) + emit         # (B, N)
            live = (pos < ln_arr)[:, None]
            alpha_new = jnp.where(live, best_score, alpha)
            return (alpha_new, idx), jnp.where(
                live, best_prev, jnp.arange(n)[None, :])

        (alpha_f, _), backptrs = jax.lax.scan(
            step, (alpha0, 0),
            (jnp.swapaxes(p[:, 1:], 0, 1), jnp.arange(1, t)))
        final = alpha_f + stop[None, :]
        scores = jnp.max(final, axis=-1)
        last_tag = jnp.argmax(final, axis=-1)              # (B,)

        # backtrace: walk backpointers from each sequence's end
        def back(carry, ptrs_pos):
            tag = carry
            ptrs, pos = ptrs_pos                          # (B, N), scalar
            prev = jnp.take_along_axis(ptrs, tag[:, None],
                                       1)[:, 0]
            live = pos < ln_arr
            tag_new = jnp.where(live, prev, tag)
            # emit the stepped-back tag: outputs are tag(T-2)..tag(0)
            return tag_new, tag_new

        _, path_rev = jax.lax.scan(
            back, last_tag,
            (backptrs[::-1], jnp.arange(t - 1, 0, -1)))
        paths = jnp.concatenate(
            [path_rev[::-1], last_tag[None]], axis=0)      # (T, B)
        return scores, jnp.swapaxes(paths, 0, 1).astype(jnp.int32)

    args = (pot, trans) + ((lens,) if lens is not None else ())
    return apply("viterbi_decode", fn, args, multi_output=True)


class ViterbiDecoder:
    """≙ paddle.text.ViterbiDecoder."""

    def __init__(self, transitions, include_bos_eos_tag=True, name=None):
        self.transitions = transitions
        self.include_bos_eos_tag = include_bos_eos_tag

    def __call__(self, potentials, lengths=None):
        return viterbi_decode(potentials, self.transitions, lengths,
                              self.include_bos_eos_tag)
