"""Byte-level BPE tokenizer — train + encode/decode.

≙ the reference ecosystem's BPE tier (PaddleNLP tokenizers over a native
faster-tokenizer core, outside-repo zoo per SURVEY.md §1). Design:

* pure byte-level: base vocabulary = 256 bytes, merge rank r creates
  token id 256 + r — no unk token, any bytes round-trip exactly.
* training: iterative highest-frequency adjacent-pair merging (Sennrich
  2016) over the raw byte corpus.
* encode hot path: C++ (`csrc/native.cc bpe_encode`, ctypes-bound) with
  a pure-Python fallback of identical semantics (parity-tested).
"""
from __future__ import annotations

import json
from collections import Counter

import numpy as np

__all__ = ["BPETokenizer"]


class BPETokenizer:
    def __init__(self, merges=None):
        # merges: list of (left_id, right_id) in rank order
        self.merges = [tuple(m) for m in (merges or [])]
        self._refresh()

    def _refresh(self):
        self._rank = {m: r for r, m in enumerate(self.merges)}
        self._ml = np.asarray([m[0] for m in self.merges], np.int32)
        self._mr = np.asarray([m[1] for m in self.merges], np.int32)
        # id -> byte sequence, for decode
        self._bytes = {i: bytes([i]) for i in range(256)}
        for r, (a, b) in enumerate(self.merges):
            self._bytes[256 + r] = self._bytes[a] + self._bytes[b]

    @property
    def vocab_size(self) -> int:
        return 256 + len(self.merges)

    # -- training ------------------------------------------------------------
    @classmethod
    def train(cls, corpus, vocab_size: int = 512) -> "BPETokenizer":
        """corpus: str | bytes | iterable of either. Learns
        vocab_size - 256 merges."""
        if isinstance(corpus, (str, bytes)):
            corpus = [corpus]
        seqs = [list(c.encode("utf-8") if isinstance(c, str) else c)
                for c in corpus]
        merges = []
        for r in range(max(0, vocab_size - 256)):
            counts: Counter = Counter()
            for s in seqs:
                counts.update(zip(s[:-1], s[1:]))
            if not counts:
                break
            (a, b), freq = counts.most_common(1)[0]
            if freq < 2:
                break
            new_id = 256 + r
            merges.append((int(a), int(b)))
            for si, s in enumerate(seqs):
                res = []
                i = 0
                while i < len(s):
                    if i + 1 < len(s) and s[i] == a and s[i + 1] == b:
                        res.append(new_id)
                        i += 2
                    else:
                        res.append(s[i])
                        i += 1
                seqs[si] = res
        return cls(merges)

    # -- encode/decode -------------------------------------------------------
    def _encode_py(self, data: bytes) -> np.ndarray:
        toks = list(data)
        rank = self._rank
        while True:
            best = None
            best_r = len(self.merges)
            for pair in zip(toks[:-1], toks[1:]):
                r = rank.get(pair, best_r)
                if r < best_r:
                    best_r, best = r, pair
            if best is None:
                break
            a, b = best
            merged = 256 + best_r
            res = []
            i = 0
            while i < len(toks):
                if i + 1 < len(toks) and toks[i] == a and toks[i + 1] == b:
                    res.append(merged)
                    i += 2
                else:
                    res.append(toks[i])
                    i += 1
            toks = res
        return np.asarray(toks, np.int32)

    def encode(self, text) -> np.ndarray:
        data = text.encode("utf-8") if isinstance(text, str) else bytes(text)
        if not data:
            return np.zeros((0,), np.int32)
        from .._native import bpe_encode_native
        out = bpe_encode_native(data, self._ml, self._mr)
        if out is None:                       # no compiler: python fallback
            out = self._encode_py(data)
        return out

    def decode(self, ids) -> str:
        data = b"".join(self._bytes[int(i)] for i in np.asarray(ids)
                        .reshape(-1))
        return data.decode("utf-8", errors="replace")

    # -- persistence ---------------------------------------------------------
    def save(self, path: str) -> None:
        with open(path, "w") as f:
            json.dump({"merges": self.merges}, f)

    @classmethod
    def load(cls, path: str) -> "BPETokenizer":
        with open(path) as f:
            return cls(json.load(f)["merges"])
