"""paddle_tpu.audio — audio feature extraction.

≙ reference «python/paddle/audio/» (features: Spectrogram, MelSpectrogram,
LogMelSpectrogram, MFCC; functional: window/mel helpers) [U]. Built on the
framework's own stft (paddle_tpu.signal) so the whole pipeline jits —
feature extraction can run on-device inside the train step instead of the
CPU data loader.
"""
from . import features  # noqa: F401
from . import functional  # noqa: F401
