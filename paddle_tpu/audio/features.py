"""audio.features — Spectrogram / MelSpectrogram / LogMelSpectrogram / MFCC
layers. ≙ reference «python/paddle/audio/features/layers.py» [U]. Each is an
nn.Layer whose forward jits (stft is the framework's own)."""
from __future__ import annotations

import jax.numpy as jnp

from ..core.tensor import Tensor, apply
from ..nn.layer.layers import Layer
from .. import signal as _signal
from . import functional as AF

__all__ = ["Spectrogram", "MelSpectrogram", "LogMelSpectrogram", "MFCC"]


class Spectrogram(Layer):
    def __init__(self, n_fft=512, hop_length=None, win_length=None,
                 window="hann", power=2.0, center=True, pad_mode="reflect"):
        super().__init__()
        self.n_fft = n_fft
        self.hop_length = hop_length or n_fft // 4
        self.win_length = win_length or n_fft
        self.power = power
        self.center = center
        self.pad_mode = pad_mode
        self.register_buffer("window",
                             AF.get_window(window, self.win_length),
                             persistable=False)

    def forward(self, x):
        spec = _signal.stft(x, self.n_fft, self.hop_length,
                            self.win_length, self.window,
                            center=self.center, pad_mode=self.pad_mode)
        p = self.power

        def fn(s):
            mag = jnp.abs(s)
            return mag if p == 1.0 else mag ** p
        return apply("spec_power", fn, (spec,))


class MelSpectrogram(Layer):
    def __init__(self, sr=22050, n_fft=512, hop_length=None,
                 win_length=None, window="hann", power=2.0, n_mels=64,
                 f_min=50.0, f_max=None, htk=False, norm="slaney"):
        super().__init__()
        self.spectrogram = Spectrogram(n_fft, hop_length, win_length,
                                       window, power)
        fb = AF.compute_fbank_matrix(sr, n_fft, n_mels, f_min, f_max,
                                     htk, norm)
        from ..core.tensor import to_tensor
        self.register_buffer("fbank", to_tensor(fb), persistable=False)

    def forward(self, x):
        spec = self.spectrogram(x)      # (..., freq, time)

        def fn(s, fb):
            return jnp.einsum("mf,...ft->...mt", fb, s)
        return apply("mel", fn, (spec, self.fbank))


class LogMelSpectrogram(Layer):
    def __init__(self, sr=22050, n_fft=512, hop_length=None,
                 win_length=None, window="hann", power=2.0, n_mels=64,
                 f_min=50.0, f_max=None, ref_value=1.0, amin=1e-10,
                 top_db=None):
        super().__init__()
        self.mel = MelSpectrogram(sr, n_fft, hop_length, win_length,
                                  window, power, n_mels, f_min, f_max)
        self.ref_value = ref_value
        self.amin = amin
        self.top_db = top_db

    def forward(self, x):
        return AF.power_to_db(self.mel(x), self.ref_value, self.amin,
                              self.top_db)


class MFCC(Layer):
    def __init__(self, sr=22050, n_mfcc=40, n_fft=512, hop_length=None,
                 n_mels=64, f_min=50.0, f_max=None, top_db=None):
        super().__init__()
        self.logmel = LogMelSpectrogram(sr, n_fft, hop_length, None,
                                        "hann", 2.0, n_mels, f_min, f_max,
                                        top_db=top_db)
        from ..core.tensor import to_tensor
        self.register_buffer(
            "dct", to_tensor(AF.create_dct(n_mfcc, n_mels)),
            persistable=False)

    def forward(self, x):
        lm = self.logmel(x)             # (..., n_mels, time)

        def fn(v, d):
            return jnp.einsum("mk,...mt->...kt", d, v)
        return apply("mfcc", fn, (lm, self.dct))
