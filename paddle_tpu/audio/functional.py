"""audio.functional — mel filterbanks, dct, window helpers.
≙ reference «python/paddle/audio/functional/» [U]."""
from __future__ import annotations

import math

import numpy as np
import jax.numpy as jnp

from ..core.tensor import Tensor, apply, to_tensor

__all__ = ["hz_to_mel", "mel_to_hz", "mel_frequencies", "fft_frequencies",
           "compute_fbank_matrix", "create_dct", "power_to_db",
           "get_window"]


def hz_to_mel(freq, htk: bool = False):
    """Scalar/array Hz -> mel (Slaney by default, HTK optional)."""
    f = np.asarray(freq, np.float64)
    if htk:
        out = 2595.0 * np.log10(1.0 + f / 700.0)
    else:
        f_min, f_sp = 0.0, 200.0 / 3
        out = (f - f_min) / f_sp
        min_log_hz = 1000.0
        min_log_mel = (min_log_hz - f_min) / f_sp
        logstep = math.log(6.4) / 27.0
        out = np.where(f >= min_log_hz,
                       min_log_mel + np.log(np.maximum(f, 1e-10)
                                            / min_log_hz) / logstep, out)
    return out.item() if np.isscalar(freq) or out.ndim == 0 else out


def mel_to_hz(mel, htk: bool = False):
    m = np.asarray(mel, np.float64)
    if htk:
        out = 700.0 * (10.0 ** (m / 2595.0) - 1.0)
    else:
        f_min, f_sp = 0.0, 200.0 / 3
        out = f_min + f_sp * m
        min_log_hz = 1000.0
        min_log_mel = (min_log_hz - f_min) / f_sp
        logstep = math.log(6.4) / 27.0
        out = np.where(m >= min_log_mel,
                       min_log_hz * np.exp(logstep * (m - min_log_mel)),
                       out)
    return out.item() if np.isscalar(mel) or out.ndim == 0 else out


def mel_frequencies(n_mels=64, f_min=0.0, f_max=11025.0, htk=False):
    mels = np.linspace(hz_to_mel(f_min, htk), hz_to_mel(f_max, htk),
                       n_mels)
    return mel_to_hz(mels, htk)


def fft_frequencies(sr, n_fft):
    return np.linspace(0, sr / 2, 1 + n_fft // 2)


def compute_fbank_matrix(sr, n_fft, n_mels=64, f_min=0.0, f_max=None,
                         htk=False, norm="slaney"):
    """(n_mels, 1 + n_fft//2) triangular mel filterbank."""
    f_max = f_max or sr / 2
    fft_f = fft_frequencies(sr, n_fft)
    mel_f = mel_frequencies(n_mels + 2, f_min, f_max, htk)
    fdiff = np.diff(mel_f)
    ramps = mel_f[:, None] - fft_f[None, :]
    lower = -ramps[:-2] / fdiff[:-1, None]
    upper = ramps[2:] / fdiff[1:, None]
    fb = np.maximum(0.0, np.minimum(lower, upper))
    if norm == "slaney":
        enorm = 2.0 / (mel_f[2:n_mels + 2] - mel_f[:n_mels])
        fb *= enorm[:, None]
    return fb.astype(np.float32)


def create_dct(n_mfcc, n_mels, norm="ortho"):
    """(n_mels, n_mfcc) DCT-II basis."""
    n = np.arange(n_mels)
    k = np.arange(n_mfcc)[None, :]
    basis = np.cos(np.pi / n_mels * (n[:, None] + 0.5) * k)
    if norm == "ortho":
        basis[:, 0] *= 1.0 / math.sqrt(2)
        basis *= math.sqrt(2.0 / n_mels)
    else:
        basis *= 2.0
    return basis.astype(np.float32)


def power_to_db(x, ref_value=1.0, amin=1e-10, top_db=80.0):
    """10*log10(x/ref) with floor; Tensor in, Tensor out."""
    t = x if isinstance(x, Tensor) else to_tensor(x)

    def fn(v):
        db = 10.0 * jnp.log10(jnp.maximum(v, amin))
        db = db - 10.0 * jnp.log10(jnp.maximum(jnp.asarray(ref_value),
                                               amin))
        if top_db is not None:
            db = jnp.maximum(db, jnp.max(db) - top_db)
        return db
    return apply("power_to_db", fn, (t,))


def get_window(window: str, win_length: int, fftbins: bool = True):
    """hann/hamming/blackman/ones as a Tensor."""
    n = win_length
    m = n if fftbins else n - 1
    x = np.arange(n)
    if window in ("hann", "hanning"):
        w = 0.5 - 0.5 * np.cos(2 * np.pi * x / m)
    elif window == "hamming":
        w = 0.54 - 0.46 * np.cos(2 * np.pi * x / m)
    elif window == "blackman":
        w = (0.42 - 0.5 * np.cos(2 * np.pi * x / m)
             + 0.08 * np.cos(4 * np.pi * x / m))
    elif window in ("ones", "rect", "boxcar"):
        w = np.ones(n)
    else:
        raise ValueError(f"unknown window {window!r}")
    return to_tensor(w.astype(np.float32))
