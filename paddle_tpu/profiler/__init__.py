"""Profiler. ≙ reference «python/paddle/profiler/» (Profiler + make_scheduler
state machine, RecordEvent spans, chrome trace export, summary tables) and the
C++ host/CUPTI tracers «paddle/fluid/platform/profiler/» (SURVEY.md §5) [U].

TPU-native: device tracing is XLA's XPlane via jax.profiler (TensorBoard /
Perfetto); RecordEvent forwards to jax.profiler.TraceAnnotation so host spans
land in the same timeline. `summary()` renders host-side op statistics
collected by the eager dispatch layer."""
from __future__ import annotations

import enum
import os
import time
from collections import defaultdict
from contextlib import contextmanager
from typing import Callable, Iterable, Optional

import jax


class ProfilerState(enum.IntEnum):
    CLOSED = 0
    READY = 1
    RECORD = 2
    RECORD_AND_RETURN = 3


class ProfilerTarget(enum.IntEnum):
    CPU = 0
    GPU = 1
    XPU = 2
    CUSTOM_DEVICE = 3
    TPU = 4


def make_scheduler(*, closed: int, ready: int, record: int, repeat: int = 0,
                   skip_first: int = 0) -> Callable[[int], ProfilerState]:
    """≙ paddle.profiler.make_scheduler: CLOSED(closed)→READY(ready)→
    RECORD(record-1)→RECORD_AND_RETURN, repeating."""
    period = closed + ready + record

    def scheduler(step: int) -> ProfilerState:
        if step < skip_first:
            return ProfilerState.CLOSED
        s = step - skip_first
        if repeat > 0 and s >= repeat * period:
            return ProfilerState.CLOSED
        pos = s % period
        if pos < closed:
            return ProfilerState.CLOSED
        if pos < closed + ready:
            return ProfilerState.READY
        if pos < period - 1:
            return ProfilerState.RECORD
        return ProfilerState.RECORD_AND_RETURN
    return scheduler


def export_chrome_tracing(dir_name: str, worker_name: str | None = None):
    """on_trace_ready callback: the jax trace directory already contains
    perfetto/chrome-compatible output; this records where it went."""
    os.makedirs(dir_name, exist_ok=True)

    def handle(prof):
        prof._last_export_dir = dir_name
    return handle


def export_protobuf(dir_name: str, worker_name: str | None = None):
    return export_chrome_tracing(dir_name, worker_name)


class RecordEvent:
    """Host span; shows up in the XLA timeline via TraceAnnotation.
    ≙ paddle.profiler.RecordEvent."""

    _host_stats: dict[str, list] = defaultdict(list)

    def __init__(self, name: str, event_type=None):
        self.name = name
        self._ann = None
        self._t0 = None

    def begin(self):
        self._ann = jax.profiler.TraceAnnotation(self.name)
        self._ann.__enter__()
        self._t0 = time.perf_counter()

    def end(self):
        if self._ann is not None:
            dt = time.perf_counter() - self._t0
            RecordEvent._host_stats[self.name].append(dt)
            self._ann.__exit__(None, None, None)
            self._ann = None

    def __enter__(self):
        self.begin()
        return self

    def __exit__(self, *a):
        self.end()
        return False


def load_profiler_result(filename: str):
    raise NotImplementedError(
        "load_profiler_result: inspect the exported TensorBoard/perfetto "
        "trace directory instead (xplane format).")


class Profiler:
    """≙ paddle.profiler.Profiler."""

    def __init__(self, *, targets: Iterable = None, scheduler=None,
                 on_trace_ready=None, record_shapes=False, profile_memory=False,
                 timer_only=False, emit_nvtx=False, custom_device_types=None,
                 with_flops=False):
        if scheduler is None:
            self._scheduler = lambda step: ProfilerState.RECORD
        elif isinstance(scheduler, (tuple, list)):
            lo, hi = scheduler
            self._scheduler = lambda step: (
                ProfilerState.RECORD if lo <= step < hi
                else ProfilerState.CLOSED)
        else:
            self._scheduler = scheduler
        self._on_trace_ready = on_trace_ready
        self._timer_only = timer_only
        self.step_num = 0
        self._state = ProfilerState.CLOSED
        self._tracing = False
        self._trace_dir = None
        self._last_export_dir = None
        self._step_times: list[float] = []
        self._t_last = None
        # per-step HBM accounting (≙ StatAllocator / max_memory_allocated,
        # SURVEY.md §5): sample the live allocator counters at every
        # step() boundary; empty on backends without memory_stats (CPU)
        self._profile_memory = profile_memory
        self._mem_samples: list[dict] = []

    def _sample_memory(self):
        if not self._profile_memory:
            return
        try:
            st = jax.devices()[0].memory_stats() or {}
        except Exception:
            st = {}
        self._mem_samples.append({
            "step": self.step_num,
            "bytes_in_use": st.get("bytes_in_use", 0),
            "peak_bytes_in_use": st.get("peak_bytes_in_use", 0),
        })

    def start(self):
        self._t_last = time.perf_counter()
        self._transition(self._scheduler(self.step_num))

    def stop(self):
        if self._tracing:
            jax.profiler.stop_trace()
            self._tracing = False
            if self._on_trace_ready:
                self._on_trace_ready(self)

    def step(self, num_samples: int | None = None):
        now = time.perf_counter()
        if self._t_last is not None:
            self._step_times.append(now - self._t_last)
        self._t_last = now
        self._sample_memory()
        self.step_num += 1
        self._transition(self._scheduler(self.step_num))

    def _transition(self, new_state: ProfilerState):
        if self._timer_only:
            self._state = new_state
            return
        want_trace = new_state in (ProfilerState.RECORD,
                                   ProfilerState.RECORD_AND_RETURN)
        if want_trace and not self._tracing:
            self._trace_dir = self._trace_dir or os.path.join(
                os.getcwd(), "profiler_log")
            os.makedirs(self._trace_dir, exist_ok=True)
            jax.profiler.start_trace(self._trace_dir)
            self._tracing = True
        elif not want_trace and self._tracing:
            jax.profiler.stop_trace()
            self._tracing = False
            if self._on_trace_ready:
                self._on_trace_ready(self)
        self._state = new_state

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *a):
        self.stop()
        return False

    def export(self, path: str, format: str = "json"):
        # jax writes traces at stop_trace time into the trace dir
        pass

    def summary(self, sorted_by=None, op_detail=True, thread_sep=False,
                time_unit="ms"):
        lines = ["-" * 72,
                 f"{'Host span':40s}{'calls':>8s}{'total(ms)':>12s}"
                 f"{'avg(ms)':>10s}",
                 "-" * 72]
        for name, times in sorted(RecordEvent._host_stats.items(),
                                  key=lambda kv: -sum(kv[1])):
            tot = sum(times) * 1e3
            lines.append(f"{name[:40]:40s}{len(times):8d}{tot:12.3f}"
                         f"{tot / len(times):10.3f}")
        if self._step_times:
            st = self._step_times
            lines.append("-" * 72)
            lines.append(
                f"steps: {len(st)}  avg step: {1e3 * sum(st) / len(st):.3f} "
                f"ms  min: {1e3 * min(st):.3f}  max: {1e3 * max(st):.3f}")
        if self._mem_samples and any(
                s["peak_bytes_in_use"] for s in self._mem_samples):
            peak = max(s["peak_bytes_in_use"] for s in self._mem_samples)
            last = self._mem_samples[-1]["bytes_in_use"]
            lines.append(
                f"device memory: peak {peak / 2**20:.1f} MiB, "
                f"in-use (last step) {last / 2**20:.1f} MiB "
                f"({len(self._mem_samples)} samples)")
        elif self._profile_memory:
            lines.append("device memory: allocator stats unavailable on "
                         "this backend (use utils.memory."
                         "compiled_memory_stats for AOT numbers)")
        if self._trace_dir:
            lines.append(f"device trace (XPlane): {self._trace_dir} — view "
                         f"with TensorBoard or Perfetto")
        out = "\n".join(lines)
        print(out)
        return out


@contextmanager
def profile_span(name: str):
    with RecordEvent(name):
        yield
