"""Autograd public API. ≙ reference «python/paddle/autograd/» [U]."""
from __future__ import annotations

from ..core.tape import (no_grad, enable_grad, is_grad_enabled,  # noqa: F401
                         set_grad_enabled, grad)
from ..core.tensor import Tensor, apply


def backward(tensors, grad_tensors=None, retain_graph=False):
    """≙ paddle.autograd.backward."""
    tensors = tensors if isinstance(tensors, (list, tuple)) else [tensors]
    if grad_tensors is None:
        grad_tensors = [None] * len(tensors)
    for t, g in zip(tensors, grad_tensors):
        t.backward(g, retain_graph=retain_graph)


class PyLayerContext:
    def __init__(self):
        self._saved = ()
        self.materialize_grads = True

    def save_for_backward(self, *tensors):
        self._saved = tensors

    @property
    def saved_tensor(self):
        return self._saved

    def saved_tensors(self):
        return self._saved

    def set_materialize_grads(self, value):
        self.materialize_grads = value

    def mark_not_inplace(self, *a):
        pass

    def mark_non_differentiable(self, *a):
        pass


class PyLayer:
    """Custom autograd op. ≙ reference `paddle.autograd.PyLayer` [U].

    Subclass with static `forward(ctx, *args)` and `backward(ctx, *grads)`.
    The forward runs outside the tape; a custom grad node stitches the
    user-defined backward into the tape traversal."""

    @staticmethod
    def forward(ctx, *args, **kwargs):
        raise NotImplementedError

    @staticmethod
    def backward(ctx, *grads):
        raise NotImplementedError

    @classmethod
    def apply(cls, *args, **kwargs):
        from ..core import tape
        from ..core.tape import Node

        ctx = PyLayerContext()
        tensor_args = [a for a in args if isinstance(a, Tensor)]

        with no_grad():
            outs = cls.forward(ctx, *args, **kwargs)
        multi = isinstance(outs, (list, tuple))
        out_list = list(outs) if multi else [outs]
        out_list = [o if isinstance(o, Tensor) else Tensor(o)
                    for o in out_list]

        needs = is_grad_enabled() and any(
            not t.stop_gradient for t in tensor_args)
        if needs:
            def vjp_fn(cots):
                cots = cots if isinstance(cots, tuple) else (cots,)
                gin = cls.backward(ctx, *[Tensor(c) for c in cots])
                gin = gin if isinstance(gin, (list, tuple)) else (gin,)
                vals = []
                gi = iter(gin)
                for a in args:
                    if isinstance(a, Tensor):
                        g = next(gi, None)
                        vals.append(None if g is None else
                                    (g._value if isinstance(g, Tensor) else g))
                return tuple(vals)

            from ..core.tape import Ref
            node = Node(
                name=f"PyLayer<{cls.__name__}>",
                vjp_fn=lambda cots: vjp_fn(cots),
                inputs=[Ref(t) for t in tensor_args],
                n_outputs=len(out_list),
                out_shapes=[tuple(o.shape) for o in out_list],
                out_dtypes=[o._value.dtype for o in out_list],
            )
            for i, o in enumerate(out_list):
                o._node, o._out_index = node, i
                o.stop_gradient = False
        if multi:
            return type(outs)(out_list)
        return out_list[0]


def jacobian(func, xs, create_graph=False):
    """≙ paddle.autograd.jacobian [U]. Functional form (func, xs) — the
    tape is first-order, so the Jacobian is computed by jax.jacrev over
    the function (incubate.autograd), not by double backward over a
    stored graph."""
    from ..incubate.autograd import jacobian as _j
    return _j(func, xs, create_graph=create_graph)


def hessian(func, xs, create_graph=False):
    """≙ paddle.autograd.hessian [U] (functional form, see jacobian)."""
    from ..incubate.autograd import hessian as _h
    return _h(func, xs, create_graph=create_graph)
