"""paddle_tpu.jit — the compile path.

≙ reference `@paddle.jit.to_static` + SOT/dy2static + PIR + CINN +
InterpreterCore (SURVEY.md §3.4) collapsed into ONE mechanism: because every
eager op in this framework is a traceable JAX computation (including the
autograd tape and the optimizer update), re-executing the user's eager train
step under `jax.jit` tracing yields a single fused XLA program per step —
no bytecode interpretation, no separate IR. Data-dependent Python control
flow is a graph break: under `to_static` (full_graph=False, the reference
default) it logs and falls back to eager (SOT-lite); under full_graph=True
or `TrainStep` it raises the pointed GraphBreakError.

Key pieces:
* `to_static(fn_or_layer)`   — jit a function/Layer forward (inference path).
* `TrainStep(model, opt)`    — whole-train-step compilation with buffer
  donation: params/opt-state are threaded as traced inputs and donated, so
  updates are in-place in HBM (≙ the reference's inplace AdamW kernels).
* `jit.save/load`            — serialize compiled functions via jax.export
  (StableHLO), ≙ paddle.jit.save inference programs [U].
"""
from __future__ import annotations

import functools
import warnings
from typing import Any, Callable, Optional, Sequence

import numpy as np
import jax
import jax.numpy as jnp

from ..core.tensor import GraphBreakError, Parameter, Tensor
from ..tensor.random import default_generator


def _tensors_to_values(tree):
    return jax.tree_util.tree_map(
        lambda x: x._value if isinstance(x, Tensor) else x, tree,
        is_leaf=lambda x: isinstance(x, Tensor))


def _spec_of(tree):
    return jax.tree_util.tree_map(
        lambda x: isinstance(x, Tensor), tree,
        is_leaf=lambda x: isinstance(x, Tensor))


#: record of every graph break that fell back to eager this process:
#: list of (qualname, reason) — ≙ the reference SOT's break-graph log
#: (`sot.opcode_translator` info logs). Inspect with jit.sot_graph_breaks().
_graph_break_log: list = []


def sot_graph_breaks() -> list:
    """(qualname, reason) for every to_static graph break that fell back
    to eager execution in this process (SOT-lite diagnostics)."""
    return list(_graph_break_log)


class StaticFunction:
    """jit wrapper for a pure function or a Layer's forward.

    SOT-lite contract (≙ reference `python/paddle/jit/sot/` [U]): with
    full_graph=False (the default, matching the reference), data-dependent
    Python control flow on a traced Tensor does not error — the graph break
    is logged and the function falls back to EAGER execution (numerics
    identical, per-op dispatch instead of one fused XLA program). The
    fallback decision is cached per function: the reference re-traces
    subgraphs between breaks; here the unit of capture is the whole
    function, which is the bounded version of the same contract.
    full_graph=True keeps the pointed GraphBreakError."""

    def __init__(self, function, layer=None, input_spec=None,
                 full_graph=False, **kwargs):
        self._fn = function
        self._layer = layer
        self._input_spec = input_spec
        self._jitted = None
        self._full_graph = full_graph
        self.graph_break_reason = None   # set on first fallback
        functools.update_wrapper(self, function)

    def _build(self):
        layer = self._layer
        fn = self._fn

        if layer is not None:
            params = list(layer.parameters())
            buffers = list(layer.buffers())

            def pure(param_vals, buf_vals, arg_vals, kw_vals):
                old_p = [p._value for p in params]
                old_b = [b._value for b in buffers]
                try:
                    for p, v in zip(params, param_vals):
                        p._value = v
                    for b, v in zip(buffers, buf_vals):
                        b._value = v
                    args = jax.tree_util.tree_map(Tensor, arg_vals)
                    kwargs = jax.tree_util.tree_map(Tensor, kw_vals)
                    out = fn(*args, **kwargs)
                    return _tensors_to_values(out)
                finally:
                    for p, v in zip(params, old_p):
                        p._value = v
                    for b, v in zip(buffers, old_b):
                        b._value = v
            self._jitted = jax.jit(pure)
        else:
            def pure(arg_vals, kw_vals):
                args = jax.tree_util.tree_map(Tensor, arg_vals)
                kwargs = jax.tree_util.tree_map(Tensor, kw_vals)
                out = fn(*args, **kwargs)
                return _tensors_to_values(out)
            self._jitted = jax.jit(pure)

    def _call_eager(self, args, kwargs):
        # same input normalization as the compiled path (every array leaf
        # becomes a Tensor) so numerics and types match trace-mode exactly
        args = jax.tree_util.tree_map(Tensor, _tensors_to_values(list(args)))
        kwargs = jax.tree_util.tree_map(Tensor,
                                        _tensors_to_values(dict(kwargs)))
        return self._fn(*args, **kwargs)

    def __call__(self, *args, **kwargs):
        if self.graph_break_reason is not None:
            return self._call_eager(args, kwargs)
        if self._jitted is None:
            self._build()
        arg_vals = _tensors_to_values(list(args))
        kw_vals = _tensors_to_values(dict(kwargs))
        try:
            if self._layer is not None:
                pv = [p._value for p in self._layer.parameters()]
                bv = [b._value for b in self._layer.buffers()]
                out_vals = self._jitted(pv, bv, arg_vals, kw_vals)
            else:
                out_vals = self._jitted(arg_vals, kw_vals)
        except (GraphBreakError, jax.errors.ConcretizationTypeError,
                jax.errors.TracerArrayConversionError,
                jax.errors.TracerIntegerConversionError) as e:
            # GraphBreakError: a framework Tensor coercion (`if t:`,
            # float(t), .item(), .numpy()) under trace; the jax errors:
            # the same coercions on a raw jax array in user code (the
            # Array/Integer variants do NOT subclass
            # ConcretizationTypeError in the installed jax).
            if self._full_graph:
                raise
            reason = str(e).splitlines()[0]
            self.graph_break_reason = reason
            name = getattr(self._fn, "__qualname__", repr(self._fn))
            _graph_break_log.append((name, reason))
            warnings.warn(
                f"to_static: graph break in {name!r} — falling back to "
                f"eager execution for this function (numerics unchanged, "
                f"no XLA fusion). Reason: {reason}  Pass full_graph=True "
                "to error instead.", stacklevel=2)
            return self._call_eager(args, kwargs)
        return jax.tree_util.tree_map(Tensor, out_vals)

    @property
    def code(self):
        import inspect
        try:
            return inspect.getsource(self._fn)
        except OSError:
            return "<source unavailable>"


def to_static(function=None, input_spec=None, build_strategy=None,
              backend=None, full_graph=False, **kwargs):
    """≙ @paddle.jit.to_static. Works on functions of Tensors and on
    nn.Layer instances (forward gets compiled with params as traced inputs).

    full_graph=False (default, reference parity): graph breaks fall back
    to eager with a warning (SOT-lite). full_graph=True: graph breaks
    raise GraphBreakError with a pointed diagnostic."""
    from ..nn.layer.layers import Layer

    def decorate(obj):
        if isinstance(obj, Layer):
            sf = StaticFunction(obj.forward, layer=obj,
                                input_spec=input_spec,
                                full_graph=full_graph)
            obj.forward = sf
            return obj
        return StaticFunction(obj, input_spec=input_spec,
                              full_graph=full_graph)

    if function is not None:
        return decorate(function)
    return decorate


def not_to_static(fn):
    fn._not_to_static = True
    return fn


class ignore_module:
    def __init__(self, modules):
        pass

    def __enter__(self):
        return self

    def __exit__(self, *a):
        return False


class TrainStep:
    """Whole-train-step XLA compilation with state donation.

    Usage::

        step = paddle_tpu.jit.TrainStep(model, opt,
                                        loss_fn=lambda m, x, y: F.cross_entropy(m(x), y))
        loss = step(x, y)      # one compiled XLA program; params updated

    The eager tape + optimizer run under jax tracing; params, optimizer
    accumulators and master weights are inputs AND outputs of the compiled
    program, donated to keep updates in-place in HBM. The RNG key is threaded
    so dropout differs per step (≙ the reference's RNG state tracker).

    `accumulate_steps=k` (≙ fleet gradient-merge meta-optimizer /
    `pipeline_configs['accumulate_steps']`, SURVEY.md §2.4) splits the batch
    into k micro-batches inside the ONE compiled program: each micro-loss is
    scaled by 1/k, backward accumulates into the grads, the optimizer steps
    once. Loss returned is the mean micro-loss. Leading dim of every input
    must be divisible by k.
    """

    def __init__(self, model, optimizer=None, loss_fn=None, scaler=None,
                 donate=True, accumulate_steps=1):
        self.model = model
        self.optimizer = optimizer
        self.loss_fn = loss_fn
        self.scaler = scaler
        self.donate = donate
        self.accumulate_steps = int(accumulate_steps)
        self._params = [p for p in model.parameters()]
        self._buffers = list(model.buffers())
        self._jitted = None
        self._step_i = 0

    def _make_pure(self):
        model, opt, loss_fn = self.model, self.optimizer, self.loss_fn
        params, buffers = self._params, self._buffers
        scaler = self.scaler

        def pure(param_vals, buf_vals, acc_tree, master_list, key, lr,
                 step_count, arg_vals):
            old_key = default_generator._key
            old_p = [p._value for p in params]
            old_g = [p.grad for p in params]
            old_b = [b._value for b in buffers]
            old_acc = opt._accumulators if opt is not None else None
            old_master = opt._master_weights if opt is not None else None
            old_step = opt._step_count if opt is not None else None
            old_get_lr = opt.get_lr if opt is not None else None
            try:
                for p, v in zip(params, param_vals):
                    p._value = v
                    p.grad = None
                for b, v in zip(buffers, buf_vals):
                    b._value = v
                default_generator._key = key
                if opt is not None:
                    opt._accumulators = {
                        name: {id(params[i]): arr
                               for i, arr in store.items()}
                        for name, store in acc_tree.items()}
                    opt._master_weights = {
                        id(params[i]): arr for i, arr in master_list.items()}
                    opt._step_count = step_count
                    opt.get_lr = lambda: lr
                args = jax.tree_util.tree_map(Tensor, arg_vals)
                k = self.accumulate_steps

                def run_micro(margs):
                    out = (loss_fn(model, *margs) if loss_fn is not None
                           else model(*margs))
                    a = None
                    if isinstance(out, (tuple, list)):
                        out, a = out[0], out[1:]
                    scaled = out / k if k > 1 else out
                    if scaler is not None and scaler._enable:
                        scaled = scaler.scale(scaled)
                    scaled.backward()
                    return out, a

                if k > 1:
                    def slice_micro(t, j):
                        b = t.shape[0]
                        if b % k:
                            raise ValueError(
                                f"accumulate_steps={k} does not divide "
                                f"batch dim {b}")
                        mb = b // k
                        return t[j * mb:(j + 1) * mb]
                    micro_losses = []
                    micro_aux = []
                    for j in range(k):
                        margs = jax.tree_util.tree_map(
                            lambda t: slice_micro(t, j), args,
                            is_leaf=lambda x: isinstance(x, Tensor))
                        mloss, maux = run_micro(margs)
                        micro_losses.append(mloss._value)
                        micro_aux.append(maux)
                    loss = Tensor(
                        jnp.mean(jnp.stack(micro_losses)),
                        stop_gradient=True)
                    # re-assemble per-example aux (logits etc.) across the
                    # micro-batches so callers see the FULL batch, not the
                    # last micro-batch mislabeled as the whole step
                    aux = None
                    if micro_aux[0] is not None:
                        aux = jax.tree_util.tree_map(
                            lambda *xs: Tensor(jnp.concatenate(
                                [x._value if isinstance(x, Tensor)
                                 else x for x in xs], axis=0)),
                            *micro_aux,
                            is_leaf=lambda x: isinstance(x, Tensor))
                else:
                    loss, aux = run_micro(args)
                if opt is not None:
                    opt.step()
                new_params = [p._value for p in params]
                new_bufs = [b._value for b in buffers]
                new_acc = {
                    name: {i: store[id(params[i])]
                           for i in range(len(params))
                           if id(params[i]) in store}
                    for name, store in (opt._accumulators if opt else {}
                                        ).items()}
                new_master = {i: opt._master_weights[id(params[i])]
                              for i in range(len(params))
                              if opt and id(params[i]) in opt._master_weights}
                out_key = default_generator._key
                loss_val = loss._value
                aux_vals = _tensors_to_values(list(aux)) if aux else []
                return (new_params, new_bufs, new_acc, new_master, out_key,
                        loss_val, aux_vals)
            finally:
                default_generator._key = old_key
                for p, v, g in zip(params, old_p, old_g):
                    p._value = v
                    p.grad = g
                for b, v in zip(buffers, old_b):
                    b._value = v
                if opt is not None:
                    # restore python-side optimizer state: tracing (e.g.
                    # memory_analysis, or an aborted trace) must not leak
                    # tracers into _accumulators/_step_count/get_lr
                    opt._accumulators = old_acc
                    opt._master_weights = old_master
                    opt._step_count = old_step
                    opt.get_lr = old_get_lr

        donate = (0, 2, 3) if self.donate else ()
        return jax.jit(pure, donate_argnums=donate)

    def _materialize_state(self):
        """Run one eager warmup step ONLY to create optimizer accumulators
        lazily? Instead: pre-create accumulators with zeros so the compiled
        program's signature is stable from step 0."""
        opt = self.optimizer
        if opt is None:
            return {}, {}
        # touch accumulators for all trainable params by running the
        # optimizer's state creation paths
        acc_by_index = {}
        for name, store in opt._accumulators.items():
            acc_by_index[name] = {
                i: store[id(p)] for i, p in enumerate(self._params)
                if id(p) in store}
        master = {i: opt._master_weights[id(p)]
                  for i, p in enumerate(self._params)
                  if id(p) in opt._master_weights}
        return acc_by_index, master

    def __call__(self, *args):
        if self._jitted is None:
            self._warmup(*args)
        opt = self.optimizer
        acc, master = self._materialize_state()
        lr = np.float32(opt.get_lr()) if opt else np.float32(0.0)
        key = default_generator._key
        arg_vals = _tensors_to_values(list(args))
        # pass the PRE-step count; opt.step() increments it inside the trace
        step_count = opt._step_count if opt else 0
        (new_p, new_b, new_acc, new_master, out_key, loss_val,
         aux_vals) = self._jitted(
            [p._value for p in self._params],
            [b._value for b in self._buffers],
            acc, master, key, lr, np.int32(step_count), arg_vals)
        for p, v in zip(self._params, new_p):
            p._value = v
            p.grad = None
        for b, v in zip(self._buffers, new_b):
            b._value = v
        if opt is not None:
            for name, store in new_acc.items():
                opt._accumulators[name] = {
                    id(self._params[i]): arr for i, arr in store.items()}
            opt._master_weights = {
                id(self._params[i]): arr
                for i, arr in new_master.items()}
            opt._step_count = step_count + 1
            if hasattr(opt._learning_rate, "step"):
                pass  # user drives scheduler.step() as in the reference
        default_generator._key = out_key
        loss = Tensor(loss_val)
        if aux_vals:
            return (loss,) + tuple(jax.tree_util.tree_map(Tensor, aux_vals))
        return loss

    def _warmup(self, *args):
        """Create optimizer state eagerly (zeros) so the jitted signature is
        stable, then build the compiled function. State creation is
        optimizer-owned (`Optimizer.ensure_state`) — a new optimizer
        subclass only overrides `_create_state` and compiled mode works."""
        if self.optimizer is not None:
            self.optimizer.ensure_state()
        self._jitted = self._make_pure()

    def memory_analysis(self, *args):
        """XLA buffer-assignment sizes for THIS train step at the given
        example inputs (utils.memory.compiled_memory_stats over the same
        pure function __call__ runs): the per-step HBM accounting that
        defends remat/ZeRO/pipeline memory claims. ≙ the reference's
        `max_memory_allocated` + StatAllocator observability (SURVEY.md
        §5), but ahead-of-time and exact."""
        if self._jitted is None:
            self._warmup(*args)
        opt = self.optimizer
        acc, master = self._materialize_state()
        lr = np.float32(opt.get_lr()) if opt else np.float32(0.0)
        arg_vals = _tensors_to_values(list(args))
        lowered = self._jitted.lower(
            [p._value for p in self._params],
            [b._value for b in self._buffers],
            acc, master, default_generator._key, lr,
            np.int32(opt._step_count if opt else 0), arg_vals)
        from ..utils.memory import analysis_dict
        return analysis_dict(lowered.compile().memory_analysis())


def save(layer, path, input_spec=None, **configs):
    """≙ paddle.jit.save: serialize (a) params via paddle save format and
    (b) the traced StableHLO program via jax.export when input_spec given."""
    from ..framework import io as fio
    from ..nn.layer.layers import Layer

    if isinstance(layer, Layer):
        fio.save(layer.state_dict(), path + ".pdiparams")
        if input_spec is not None:
            try:
                from jax import export as jexport
                # derive BOTH lists from state_dict: that is exactly
                # what .pdiparams serializes and what TranslatedLayer
                # rebinds positionally at load — same membership
                # (non-persistable buffers excluded; they bake as
                # constants) and same ORDER, or the arity/binding drifts
                sd = layer.state_dict()
                params = [t for t in sd.values()
                          if isinstance(t, Parameter)]
                buffers = [t for t in sd.values()
                           if isinstance(t, Tensor)
                           and not isinstance(t, Parameter)]

                def pure(param_vals, buf_vals, *arg_vals):
                    # bind_state restores the live values afterwards —
                    # without it the export trace left TRACERS on the
                    # model's parameters (caught by the predictor-API
                    # tests: the model was unusable after jit.save)
                    from ..models.generation import bind_state
                    with bind_state(params, buffers, param_vals,
                                    buf_vals):
                        out = layer(*[Tensor(a) for a in arg_vals])
                        return _tensors_to_values(out)
                specs = [jax.ShapeDtypeStruct(tuple(s.shape), s.dtype)
                         for s in input_spec]
                exp = jexport.export(jax.jit(pure))(
                    [p._value for p in params],
                    [b._value for b in buffers], *specs)
                with open(path + ".pdmodel", "wb") as f:
                    f.write(exp.serialize())
                # sidecar metadata: the REAL input arity/names, so the
                # Predictor never has to reverse-engineer them from
                # flat-aval arithmetic (advisor r4: that breaks when
                # buffers bake as constants or inputs are pytrees)
                import json
                meta = {
                    "input_names": [
                        getattr(s, "name", None) or f"input_{i}"
                        for i, s in enumerate(input_spec)],
                    "n_inputs": len(list(input_spec)),
                    "n_params": len(params),
                    "n_buffers": len(buffers),
                }
                with open(path + ".pdmeta", "w") as f:
                    json.dump(meta, f)
            except Exception as e:  # export is best-effort
                import warnings
                warnings.warn(f"StableHLO export skipped: {e}")
    else:
        raise TypeError("jit.save expects an nn.Layer")


def load(path, params_file=None, **configs):
    """≙ paddle.jit.load — returns a TranslatedLayer-like callable.
    `params_file` overrides the default `<path>.pdiparams`."""
    from ..framework import io as fio
    state = fio.load(params_file or path + ".pdiparams")

    class TranslatedLayer:
        def __init__(self):
            self.state = state
            self._exported = None
            self.meta = None
            import os
            if os.path.exists(path + ".pdmodel"):
                from jax import export as jexport
                with open(path + ".pdmodel", "rb") as f:
                    self._exported = jexport.deserialize(f.read())
            if os.path.exists(path + ".pdmeta"):
                import json
                with open(path + ".pdmeta") as f:
                    self.meta = json.load(f)

        def state_dict(self):
            return self.state

        def __call__(self, *args):
            if self._exported is None:
                raise RuntimeError(
                    "no serialized program; jit.save was called without "
                    "input_spec")
            params = [t._value for t in self.state.values()
                      if isinstance(t, Parameter)]
            bufs = [t._value for t in self.state.values()
                    if isinstance(t, Tensor) and not isinstance(t, Parameter)]
            vals = [a._value if isinstance(a, Tensor) else jnp.asarray(a)
                    for a in args]
            out = self._exported.call(params, bufs, *vals)
            return jax.tree_util.tree_map(Tensor, out)

    return TranslatedLayer()


class InputSpec:
    """≙ paddle.static.InputSpec."""

    def __init__(self, shape, dtype="float32", name=None, stop_gradient=True):
        from ..core import dtype as dtypes
        self.shape = tuple(-1 if s is None else int(s) for s in shape)
        self.dtype = dtypes.convert_dtype(dtype)
        self.name = name

    def __repr__(self):
        return f"InputSpec(shape={self.shape}, dtype={self.dtype})"


def enable_to_static(flag: bool = True):
    pass
