"""pdt-lint: the AST-based invariant analyzer for the serving stack.

Public API (stdlib-only; see core.py for the framework and
docs/static_analysis.md for the checker catalog)::

    from paddle_tpu.analysis import lint_repo

    result = lint_repo("/path/to/repo")
    assert not result.failed, [f.render() for f in result.new]

The CLI is ``paddle-tpu-lint`` / ``python -m paddle_tpu.analysis``.
"""
from __future__ import annotations

import os
from typing import Optional, Sequence

from .checkers import (ALL_CHECKER_CLASSES, by_code,     # noqa: F401
                       default_checkers)
from .core import (Baseline, Checker, Finding, LintResult,  # noqa: F401
                   Project, SourceFile, Suppression, run_checkers)

__all__ = ["Finding", "Checker", "Project", "SourceFile", "Baseline",
           "Suppression", "LintResult", "run_checkers",
           "default_checkers", "by_code", "ALL_CHECKER_CLASSES",
           "lint_repo"]


def lint_repo(root: str, codes: Optional[Sequence[str]] = None,
              baseline: Optional[str] = None,
              respect_suppressions: bool = True,
              use_baseline: bool = True) -> LintResult:
    """Run the default checker set over `root`'s ``paddle_tpu``
    package, against the committed baseline when present — the
    programmatic equivalent of ``paddle-tpu-lint paddle_tpu/`` (the
    tier-1 gate in tests/test_lint.py calls this)."""
    from .__main__ import BASELINE_NAME
    bl = None
    if use_baseline:
        bpath = baseline or os.path.join(root, BASELINE_NAME)
        if os.path.isfile(bpath):
            bl = Baseline.load(bpath)
    project = Project(root, [os.path.join(root, "paddle_tpu")])
    return run_checkers(project, default_checkers(codes), baseline=bl,
                        respect_suppressions=respect_suppressions)
