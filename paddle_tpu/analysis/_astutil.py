"""Shared AST helpers for the pdt-lint checkers: import-alias
resolution (so ``np.asarray``, ``numpy.asarray`` and ``from numpy
import asarray`` all resolve to the same dotted name) and
function-scope walks."""
from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Tuple

__all__ = ["import_aliases", "call_name", "dotted", "literal_str",
           "walk_functions", "body_calls"]


def import_aliases(tree: ast.AST) -> Dict[str, str]:
    """Map local names to the dotted thing they import.

    ``import numpy as np``                      -> {"np": "numpy"}
    ``from time import monotonic as mono``      -> {"mono": "time.monotonic"}
    ``from .. import observability as telemetry``
                                        -> {"telemetry": "observability"}
    ``from paddle_tpu.observability import span as telemetry_span``
                                -> {"telemetry_span": "observability.span"}

    Relative imports keep only the tail module path — checkers match on
    suffixes, so ``..observability`` and ``paddle_tpu.observability``
    resolve identically.
    """
    out: Dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                out[a.asname or a.name.split(".")[0]] = a.name
        elif isinstance(node, ast.ImportFrom):
            base = node.module or ""
            if base.startswith("paddle_tpu."):
                base = base[len("paddle_tpu."):]
            for a in node.names:
                if a.name == "*":
                    continue
                full = f"{base}.{a.name}" if base else a.name
                out[a.asname or a.name] = full
    return out


def dotted(node: ast.AST, aliases: Dict[str, str]) -> Optional[str]:
    """Resolve an expression to a dotted name, mapping the root through
    the file's import aliases. Returns None for non-name expressions."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if not isinstance(node, ast.Name):
        return None
    root = aliases.get(node.id, node.id)
    parts.append(root)
    return ".".join(reversed(parts))


def call_name(call: ast.Call, aliases: Dict[str, str]) -> Optional[str]:
    return dotted(call.func, aliases)


def literal_str(node: Optional[ast.AST]) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def walk_functions(tree: ast.AST) -> Iterator[ast.FunctionDef]:
    """Every (async) function definition in the module, any nesting."""
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node


def body_calls(fn: ast.AST) -> Iterator[ast.Call]:
    """Every call inside `fn`, including nested defs (a nested def of a
    traced function traces too)."""
    for node in ast.walk(fn):
        if isinstance(node, ast.Call):
            yield node
