"""pdt-lint core: the AST-based invariant-analysis framework.

Seven PRs of hardening produced disciplines that lived in reviewer
memory plus two regex scans: injectable clocks, trace-safe host/device
boundaries, fault-site/metric-catalog sync, the `_claim_candidate`
pin/decref pairing, never-swallow supervision errors. This package
encodes them as *checkers* — small AST passes over a parsed view of
the repo — so every future PR is checked mechanically in tier-1 for
the bug classes the repo has already paid to learn
(docs/static_analysis.md is the catalog of record).

Three layers, all stdlib-only:

* **Project** — the parsed repo: every ``*.py`` under the scanned
  roots as a :class:`SourceFile` (source text + ``ast`` tree + the
  inline-suppression table), plus raw access to non-Python files
  (docs, for the drift checkers). Parsing happens once; checkers
  share it.
* **Checker** — a pluggable pass: ``check(project)`` yields
  :class:`Finding`s. Each has a stable ``code`` (``PDT0xx``), a scope
  (fnmatch globs over repo-relative paths), and a rationale naming
  the PR that motivated the rule. The registry lives in
  ``analysis.checkers``.
* **Policy** — what separates a *finding* from a *failure*:

  - inline suppressions: ``# pdt-lint: disable=PDT0xx <reason>`` on
    the offending line (or alone on the line above). The reason is
    MANDATORY — a reasonless disable suppresses nothing and is itself
    reported (PDT000), and an unused suppression is reported too, so
    stale opt-outs cannot accumulate;
  - the committed baseline (``.pdt-lint-baseline.json``) grandfathers
    pre-existing findings by line-number-free fingerprint. It is only
    allowed to SHRINK: a baseline entry no longer matched by the tree
    is a failure ("remove it"), and ``--update-baseline`` can drop
    entries but never add one — new findings must be fixed or
    suppressed inline, with a reason, in review.
"""
from __future__ import annotations

import ast
import fnmatch
import io
import json
import os
import re
import tokenize
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Tuple

__all__ = ["Finding", "SourceFile", "Project", "Checker", "Suppression",
           "Baseline", "LintResult", "run_checkers", "SUPPRESS_RE",
           "META_CODE"]

# the meta code: malformed / unused suppressions (not a pluggable
# checker — the framework itself enforces suppression hygiene)
META_CODE = "PDT000"

SUPPRESS_RE = re.compile(
    r"#\s*pdt-lint:\s*disable=([A-Z]{3}\d{3}(?:\s*,\s*[A-Z]{3}\d{3})*)"
    r"[ \t]*(.*?)\s*$")


@dataclass
class Finding:
    """One rule violation at one source location."""

    code: str                 # "PDT001"
    path: str                 # repo-relative posix path
    line: int                 # 1-based; 0 = whole-file/doc finding
    message: str
    symbol: str = ""          # enclosing Class.func dotted name
    detail: str = ""          # stable slug (callee, site, metric name)
    checker: str = ""
    col: int = 0

    @property
    def fingerprint(self) -> str:
        """Line-number-free identity used by the baseline: findings
        survive unrelated edits shifting line numbers, but a second
        occurrence of the same defect in the same symbol is a NEW
        finding (fingerprints carry a count in the baseline)."""
        return f"{self.code}:{self.path}:{self.symbol}:{self.detail}"

    def render(self) -> str:
        loc = f"{self.path}:{self.line}"
        sym = f" [{self.symbol}]" if self.symbol else ""
        return f"{loc}: {self.code}{sym}: {self.message}"

    def to_json(self) -> dict:
        return {"code": self.code, "path": self.path, "line": self.line,
                "col": self.col, "symbol": self.symbol,
                "message": self.message, "detail": self.detail,
                "checker": self.checker,
                "fingerprint": self.fingerprint}


@dataclass
class Suppression:
    """One parsed ``# pdt-lint: disable=`` comment."""

    path: str
    line: int                  # line the comment sits on
    target_line: int           # line the suppression covers
    codes: Tuple[str, ...]
    reason: str
    used: bool = False


class SourceFile:
    """One parsed Python file: text, AST, and its suppression table."""

    def __init__(self, path: str, relpath: str, text: str):
        self.path = path
        self.relpath = relpath
        self.text = text
        self.lines = text.splitlines()
        self.tree: Optional[ast.AST] = None
        self.parse_error: Optional[str] = None
        try:
            self.tree = ast.parse(text, filename=relpath)
        except SyntaxError as e:            # surfaced as a finding
            self.parse_error = f"{e.msg} (line {e.lineno})"
        self.suppressions: List[Suppression] = []
        self.malformed: List[int] = []      # disable comments w/o reason
        self._scan_suppressions()

    def _scan_suppressions(self):
        # suppressions live in COMMENT tokens only — a docstring that
        # *mentions* the directive (this framework's own docs do) can
        # neither suppress nor be reported as malformed
        try:
            comments = [
                (tok.start[0], tok.string, tok.line)
                for tok in tokenize.generate_tokens(
                    io.StringIO(self.text).readline)
                if tok.type == tokenize.COMMENT]
        except (tokenize.TokenError, IndentationError, SyntaxError):
            return          # unparseable: already a PDT000 finding
        for i, comment, srcline in comments:
            if "pdt-lint" not in comment:
                continue
            m = SUPPRESS_RE.search(comment)
            if m is None:
                # a disable ATTEMPT that does not parse (typo'd code,
                # lowercase, missing '=') must not rot silently
                if re.search(r"pdt-lint:\s*disable", comment):
                    self.malformed.append(i)
                continue
            codes = tuple(c.strip() for c in m.group(1).split(","))
            reason = m.group(2).strip()
            if not reason:
                # a reasonless disable suppresses NOTHING — the why is
                # the reviewable part (docs/static_analysis.md)
                self.malformed.append(i)
                continue
            # a comment-only line covers the next non-comment line;
            # a trailing comment covers its own line
            target = i
            if srcline.strip().startswith("#"):
                target = i + 1
                for j in range(i, len(self.lines)):
                    if self.lines[j].strip() \
                            and not self.lines[j].strip().startswith("#"):
                        target = j + 1
                        break
            self.suppressions.append(
                Suppression(self.relpath, i, target, codes, reason))

    def suppression_for(self, code: str,
                        line: int) -> Optional[Suppression]:
        for s in self.suppressions:
            if s.target_line == line and (code in s.codes):
                return s
        return None


def _enclosing_symbols(tree: ast.AST) -> Dict[int, str]:
    """Map each function/class body line to its dotted symbol name —
    the symbol half of the baseline fingerprint."""
    out: Dict[int, str] = {}

    def walk(node, prefix):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.ClassDef)):
                name = f"{prefix}.{child.name}" if prefix else child.name
                end = getattr(child, "end_lineno", child.lineno)
                for ln in range(child.lineno, end + 1):
                    out[ln] = name
                walk(child, name)
            else:
                walk(child, prefix)

    walk(tree, "")
    return out


class Project:
    """The parsed repo view shared by every checker."""

    def __init__(self, root: str, paths: Optional[List[str]] = None):
        self.root = os.path.abspath(root)
        self.files: Dict[str, SourceFile] = {}
        roots = paths or [self.root]
        for p in roots:
            p = os.path.abspath(p)
            if os.path.isfile(p):
                self._add(p)
                continue
            for dirpath, dirnames, filenames in os.walk(p):
                dirnames[:] = [d for d in sorted(dirnames)
                               if d not in ("__pycache__", ".git",
                                            ".claude")]
                for fn in sorted(filenames):
                    if fn.endswith(".py"):
                        self._add(os.path.join(dirpath, fn))
        self._symbol_maps: Dict[str, Dict[int, str]] = {}

    def _add(self, path: str):
        rel = os.path.relpath(path, self.root).replace(os.sep, "/")
        if rel in self.files:
            return
        with open(path, encoding="utf-8") as f:
            text = f.read()
        self.files[rel] = SourceFile(path, rel, text)

    # -- checker helpers -------------------------------------------------
    def match(self, globs: Iterable[str],
              exclude: Iterable[str] = ()) -> List[SourceFile]:
        out = []
        for rel, sf in self.files.items():
            if any(fnmatch.fnmatch(rel, g) for g in globs) \
                    and not any(fnmatch.fnmatch(rel, g) for g in exclude):
                out.append(sf)
        return out

    def file(self, relpath: str) -> Optional[SourceFile]:
        return self.files.get(relpath)

    def read_text(self, relpath: str) -> Optional[str]:
        """Raw access to non-Python repo files (docs, for drift
        checkers). Returns None when absent."""
        path = os.path.join(self.root, relpath.replace("/", os.sep))
        if not os.path.isfile(path):
            return None
        with open(path, encoding="utf-8") as f:
            return f.read()

    def symbol_at(self, sf: SourceFile, line: int) -> str:
        if sf.relpath not in self._symbol_maps:
            self._symbol_maps[sf.relpath] = (
                _enclosing_symbols(sf.tree) if sf.tree else {})
        return self._symbol_maps[sf.relpath].get(line, "")


class Checker:
    """Base class for pluggable checkers. Subclasses set ``code``,
    ``name``, ``rationale`` (the repo law + motivating PR), and
    implement :meth:`check`. Scope lives in overridable constructor
    args so the fixture tests exercise checkers on synthetic trees."""

    code: str = "PDT999"
    name: str = "base"
    rationale: str = ""

    def check(self, project: Project) -> Iterable[Finding]:
        raise NotImplementedError

    def finding(self, sf: SourceFile, node, message: str,
                detail: str = "", project: Optional[Project] = None,
                ) -> Finding:
        line = getattr(node, "lineno", 0) if node is not None else 0
        col = getattr(node, "col_offset", 0) if node is not None else 0
        symbol = project.symbol_at(sf, line) if project and line else ""
        return Finding(self.code, sf.relpath, line, message,
                       symbol=symbol, detail=detail, checker=self.name,
                       col=col)


class Baseline:
    """The committed grandfather file: ``{fingerprint: {count,
    reason}}``. Shrink-only — see the module docstring."""

    def __init__(self, entries: Optional[Dict[str, dict]] = None,
                 path: Optional[str] = None):
        self.entries: Dict[str, dict] = dict(entries or {})
        self.path = path

    @classmethod
    def load(cls, path: str) -> "Baseline":
        with open(path, encoding="utf-8") as f:
            doc = json.load(f)
        if not isinstance(doc, dict) or doc.get("version") != 1 \
                or not isinstance(doc.get("findings"), dict):
            raise ValueError(
                f"{path}: not a pdt-lint baseline (need "
                '{"version": 1, "findings": {...}})')
        entries = {}
        for fp, ent in doc["findings"].items():
            if isinstance(ent, int):        # shorthand: bare count
                ent = {"count": ent}
            if not isinstance(ent, dict) or "count" not in ent:
                raise ValueError(f"{path}: malformed entry {fp!r}")
            entries[fp] = {"count": int(ent["count"]),
                           "reason": str(ent.get("reason", ""))}
        return cls(entries, path=path)

    def save(self, path: Optional[str] = None):
        path = path or self.path
        doc = {"version": 1,
               "findings": {fp: self.entries[fp]
                            for fp in sorted(self.entries)}}
        with open(path, "w", encoding="utf-8") as f:
            json.dump(doc, f, indent=2, sort_keys=False)
            f.write("\n")

    def count(self, fingerprint: str) -> int:
        ent = self.entries.get(fingerprint)
        return int(ent["count"]) if ent else 0


@dataclass
class LintResult:
    """Outcome of one lint run, after suppression + baseline policy."""

    findings: List[Finding]            # every raw (unsuppressed) finding
    new: List[Finding]                 # over the baseline: FAILURES
    baselined: List[Finding]           # grandfathered
    suppressed: List[Tuple[Finding, Suppression]]
    meta: List[Finding] = field(default_factory=list)   # PDT000
    stale_baseline: List[str] = field(default_factory=list)

    @property
    def failed(self) -> bool:
        return bool(self.new or self.meta or self.stale_baseline)

    def to_json(self) -> dict:
        return {
            "version": 1,
            "findings": [f.to_json() for f in self.new + self.meta],
            "baselined": [f.to_json() for f in self.baselined],
            "suppressed": [
                {**f.to_json(), "reason": s.reason,
                 "suppressed_at": s.line}
                for f, s in self.suppressed],
            "stale_baseline": list(self.stale_baseline),
            "summary": {
                "total": len(self.findings) + len(self.meta),
                "new": len(self.new), "meta": len(self.meta),
                "baselined": len(self.baselined),
                "suppressed": len(self.suppressed),
                "stale_baseline": len(self.stale_baseline),
                "failed": self.failed,
            },
        }


def run_checkers(project: Project, checkers: Iterable[Checker],
                 baseline: Optional[Baseline] = None,
                 respect_suppressions: bool = True) -> LintResult:
    """Run `checkers` over `project` and apply policy. With
    ``respect_suppressions=False`` every raw finding lands in
    ``new`` — the no-stale-suppressions gate in tests/test_lint.py
    uses this to prove each committed opt-out still masks a live
    finding."""
    raw: List[Finding] = []
    meta: List[Finding] = []
    for sf in project.files.values():
        if sf.parse_error is not None:
            meta.append(Finding(META_CODE, sf.relpath, 0,
                                f"unparseable: {sf.parse_error}",
                                checker="framework",
                                detail="parse-error"))
    for checker in checkers:
        raw.extend(checker.check(project))
    raw.sort(key=lambda f: (f.path, f.line, f.code, f.detail))

    suppressed: List[Tuple[Finding, Suppression]] = []
    kept: List[Finding] = []
    for f in raw:
        sf = project.file(f.path)
        sup = (sf.suppression_for(f.code, f.line)
               if (sf is not None and respect_suppressions) else None)
        if sup is not None:
            sup.used = True
            suppressed.append((f, sup))
        else:
            kept.append(f)

    if respect_suppressions:
        for sf in project.files.values():
            for ln in sf.malformed:
                meta.append(Finding(
                    META_CODE, sf.relpath, ln,
                    "malformed pdt-lint suppression (unparseable code "
                    "list or missing reason) — write: "
                    "# pdt-lint: disable=PDTxxx <why the rule does "
                    "not apply>",
                    checker="framework", detail="malformed-suppression"))
            for s in sf.suppressions:
                if not s.used:
                    meta.append(Finding(
                        META_CODE, sf.relpath, s.line,
                        f"unused suppression for {','.join(s.codes)} — "
                        "the finding it masked is gone; remove the "
                        "comment",
                        checker="framework", detail="unused-suppression"))

    new: List[Finding] = []
    baselined: List[Finding] = []
    stale: List[str] = []
    if baseline is None:
        new = kept
    else:
        seen: Dict[str, int] = {}
        for f in kept:
            seen[f.fingerprint] = seen.get(f.fingerprint, 0) + 1
            if seen[f.fingerprint] <= baseline.count(f.fingerprint):
                baselined.append(f)
            else:
                new.append(f)
        for fp, ent in sorted(baseline.entries.items()):
            have = seen.get(fp, 0)
            if have < int(ent["count"]):
                stale.append(fp)
    return LintResult(findings=raw, new=new, baselined=baselined,
                      suppressed=suppressed, meta=meta,
                      stale_baseline=stale)
