"""The ``paddle-tpu-lint`` CLI (also ``python -m paddle_tpu.analysis``).

Exit codes: 0 = clean against the committed baseline; 1 = findings
(new findings, suppression-hygiene violations, or stale baseline
entries); 2 = usage error.

::

    paddle-tpu-lint paddle_tpu/                 # human output
    paddle-tpu-lint --format json paddle_tpu/   # machine output
    paddle-tpu-lint --no-baseline paddle_tpu/   # raw view, no policy
    paddle-tpu-lint --update-baseline           # SHRINK the baseline
    paddle-tpu-lint --list-checkers

``--update-baseline`` only ever removes entries whose finding is gone
— it never adds one. New findings must be fixed, or suppressed inline
with a reason that survives review (docs/static_analysis.md).
"""
from __future__ import annotations

import argparse
import json
import os
import sys
from typing import List, Optional

from .checkers import ALL_CHECKER_CLASSES, default_checkers
from .core import Baseline, Project, run_checkers

BASELINE_NAME = ".pdt-lint-baseline.json"


def find_root(start: str) -> str:
    """Walk up from `start` to the repo root (pyproject.toml)."""
    cur = os.path.abspath(start)
    if os.path.isfile(cur):
        cur = os.path.dirname(cur)
    while True:
        if os.path.isfile(os.path.join(cur, "pyproject.toml")):
            return cur
        parent = os.path.dirname(cur)
        if parent == cur:
            return os.path.abspath(start)
        cur = parent


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="paddle-tpu-lint",
        description="AST-based invariant analyzer for the paddle_tpu "
                    "serving stack (checker catalog: "
                    "docs/static_analysis.md)")
    p.add_argument("paths", nargs="*",
                   help="files/directories to scan (default: the "
                        "paddle_tpu package under the repo root)")
    p.add_argument("--root", default=None,
                   help="repo root (default: walk up to pyproject.toml)")
    p.add_argument("--baseline", default=None,
                   help=f"baseline file (default: <root>/{BASELINE_NAME} "
                        "when present)")
    p.add_argument("--no-baseline", action="store_true",
                   help="ignore the baseline: report every finding")
    p.add_argument("--no-suppressions", action="store_true",
                   help="ignore inline suppressions (stale-opt-out "
                        "audit mode)")
    p.add_argument("--format", choices=("human", "json"),
                   default="human")
    p.add_argument("--checker", action="append", default=None,
                   metavar="PDT0xx",
                   help="run only these checkers (repeatable)")
    p.add_argument("--update-baseline", action="store_true",
                   help="drop baseline entries whose finding is gone "
                        "(shrink-only; never adds)")
    p.add_argument("--list-checkers", action="store_true")
    return p


def main(argv: Optional[List[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    if args.list_checkers:
        for cls in ALL_CHECKER_CLASSES:
            print(f"{cls.code}  {cls.name:28s} {cls.rationale}")
        return 0
    for p in args.paths:
        if not os.path.exists(p):
            print(f"paddle-tpu-lint: no such path: {p}",
                  file=sys.stderr)
            return 2
    root = args.root or find_root(
        args.paths[0] if args.paths else os.getcwd())
    paths = args.paths or [os.path.join(root, "paddle_tpu")]
    if not args.paths and not os.path.isdir(paths[0]):
        print(f"paddle-tpu-lint: default scan target {paths[0]} "
              "missing; pass paths explicitly", file=sys.stderr)
        return 2
    try:
        checkers = default_checkers(args.checker)
    except ValueError as e:
        print(f"paddle-tpu-lint: {e}", file=sys.stderr)
        return 2
    baseline = None
    if not args.no_baseline:
        bpath = args.baseline or os.path.join(root, BASELINE_NAME)
        if os.path.isfile(bpath):
            try:
                baseline = Baseline.load(bpath)
            except (ValueError, json.JSONDecodeError) as e:
                print(f"paddle-tpu-lint: bad baseline: {e}",
                      file=sys.stderr)
                return 2
        elif args.baseline:
            print(f"paddle-tpu-lint: baseline not found: {bpath}",
                  file=sys.stderr)
            return 2

    project = Project(root, paths)
    result = run_checkers(project, checkers, baseline=baseline,
                          respect_suppressions=not args.no_suppressions)

    if args.update_baseline:
        if baseline is None:
            print("paddle-tpu-lint: no baseline to update",
                  file=sys.stderr)
            return 2
        for fp in result.stale_baseline:
            # count KEPT findings (suppressed ones must not prop up a
            # baseline entry, or the entry would read stale forever)
            have = sum(1 for f in result.new + result.baselined
                       if f.fingerprint == fp)
            if have == 0:
                del baseline.entries[fp]
            else:
                baseline.entries[fp]["count"] = have
        baseline.save()
        # stderr: --format json owns stdout (machine output contract)
        print(f"baseline: {len(result.stale_baseline)} stale "
              f"entr{'y' if len(result.stale_baseline) == 1 else 'ies'}"
              " removed" if result.stale_baseline
              else "baseline: already minimal", file=sys.stderr)
        # fall through: new findings still fail the run

    if args.format == "json":
        print(json.dumps(result.to_json(), indent=2))
    else:
        for f in result.new + result.meta:
            print(f.render())
        for fp in ([] if args.update_baseline
                   else result.stale_baseline):
            print(f"stale baseline entry: {fp} — the finding is gone; "
                  f"run --update-baseline (the baseline only shrinks)")
        s = result.to_json()["summary"]
        print(f"pdt-lint: {s['new']} new, {s['meta']} hygiene, "
              f"{s['baselined']} baselined, {s['suppressed']} "
              f"suppressed, {s['stale_baseline']} stale-baseline")
    failed = bool(result.new or result.meta
                  or (result.stale_baseline
                      and not args.update_baseline))
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
