"""PDT004 — observability-catalog drift.

Repo law (PR 2/5): docs/observability.md is the catalog of record —
its metric table must equal the set of registered ``pdt_*``
instruments, and every span/event name the code emits must appear in
its trace-model section. Formerly a regex-plus-import scan in
tests/test_observability_slo.py that only covered the metric table;
the AST pass needs no imports (so it also covers modules the old
test's import list forgot) and extends to span/event names — which
immediately caught four undocumented ``checkpoint.*`` events.
"""
from __future__ import annotations

import ast
import re
from typing import Dict, Iterable, List, Set, Tuple

from .._astutil import call_name, import_aliases, literal_str
from ..core import Checker, Finding, Project

__all__ = ["CatalogDriftChecker", "collect_instruments",
           "collect_span_events", "documented_metrics"]

_METRIC_ROW_RE = re.compile(r"`(pdt_[a-z_]*[a-z])`")
_BACKTICK_NAME_RE = re.compile(r"`([a-z_]+\.[a-z_]+)`")
# backticked dotted tokens that are filenames/artifacts, not trace names
_NON_TRACE_SUFFIXES = {"py", "md", "json", "jsonl", "prom", "txt",
                       "cc", "log", "tmp", "hb"}

_REGISTRATION_TAILS = ("counter", "gauge", "histogram")
_SPAN_TAILS = ("span", "event", "telemetry_span", "telemetry_event")


def collect_instruments(project: Project, scope, exclude,
                        ) -> Dict[str, List[Tuple[str, ast.Call]]]:
    """Literal ``pdt_*`` names passed to counter()/gauge()/histogram()
    registrations, mapped to their call sites."""
    out: Dict[str, List[Tuple[str, ast.Call]]] = {}
    for sf in project.match(scope, exclude=exclude):
        if sf.tree is None:
            continue
        aliases = import_aliases(sf.tree)
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.Call):
                continue
            name = call_name(node, aliases)
            if name is None \
                    or name.split(".")[-1] not in _REGISTRATION_TAILS:
                continue
            lit = literal_str(node.args[0]) if node.args else None
            if lit is not None and lit.startswith("pdt_"):
                out.setdefault(lit, []).append((sf.relpath, node))
    return out


def collect_span_events(project: Project, scope, exclude,
                        ) -> Dict[str, List[Tuple[str, ast.Call]]]:
    """Literal dotted span/event/trace-root names the code emits."""
    out: Dict[str, List[Tuple[str, ast.Call]]] = {}

    def add(lit, sf, node):
        if lit is not None and re.fullmatch(r"[a-z_]+\.[a-z_]+", lit):
            out.setdefault(lit, []).append((sf.relpath, node))

    for sf in project.match(scope, exclude=exclude):
        if sf.tree is None:
            continue
        aliases = import_aliases(sf.tree)
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.Call):
                continue
            name = call_name(node, aliases)
            if name is None:
                continue
            tail = name.split(".")[-1]
            if tail in _SPAN_TAILS:
                add(literal_str(node.args[0]) if node.args else None,
                    sf, node)
            elif tail == "start_trace":
                kw = next((k.value for k in node.keywords
                           if k.arg == "name"), None)
                add(literal_str(kw), sf, node)
    return out


def documented_metrics(doc_text: str) -> Set[str]:
    """``pdt_*`` names in the metric-catalog table rows."""
    out: Set[str] = set()
    for ln in doc_text.splitlines():
        if ln.lstrip().startswith("|"):
            out |= set(_METRIC_ROW_RE.findall(ln))
    return out


class CatalogDriftChecker(Checker):
    code = "PDT004"
    name = "catalog-drift"
    rationale = ("docs/observability.md is the catalog of record for "
                 "pdt_* instruments and span/event names (PR 2/5)")

    DEFAULT_SCOPE = ("paddle_tpu/*.py", "paddle_tpu/**/*.py")
    # the substrate defines counter()/gauge()/histogram() and uses
    # docstring examples; it registers nothing of its own
    DEFAULT_EXCLUDE = ("paddle_tpu/observability/registry.py",
                       "paddle_tpu/analysis/*.py",
                       "paddle_tpu/analysis/**/*.py")
    DEFAULT_DOC = "docs/observability.md"

    def __init__(self, scope=DEFAULT_SCOPE, exclude=DEFAULT_EXCLUDE,
                 doc=DEFAULT_DOC):
        self.scope = scope
        self.exclude = exclude
        self.doc = doc

    def _doc_finding(self, doc_text: str, needle: str,
                     message: str, detail: str) -> Finding:
        line = 0
        for i, ln in enumerate(doc_text.splitlines(), start=1):
            if needle in ln:
                line = i
                break
        return Finding(self.code, self.doc, line, message,
                       symbol="<doc>", detail=detail, checker=self.name)

    def check(self, project: Project) -> Iterable[Finding]:
        doc_text = project.read_text(self.doc)
        if doc_text is None:
            yield Finding(self.code, self.doc, 0,
                          f"{self.doc} is missing — the observability "
                          "catalog of record must exist",
                          detail="missing-doc", checker=self.name)
            return
        # -- metric table vs registrations ------------------------------
        registered = collect_instruments(project, self.scope,
                                         self.exclude)
        documented = documented_metrics(doc_text)
        for name in sorted(set(registered) - documented):
            path, node = registered[name][0]
            sf = project.file(path)
            yield self.finding(
                sf, node,
                f"instrument \"{name}\" is registered but has no row "
                f"in the {self.doc} metric catalog — add one",
                detail=name, project=project)
        for name in sorted(documented - set(registered)):
            yield self._doc_finding(
                doc_text, name,
                f"metric-catalog row \"{name}\" matches no registered "
                "instrument — remove the row or restore the metric",
                detail=name)
        # -- span/event names vs the trace-model prose -------------------
        emitted = collect_span_events(project, self.scope, self.exclude)
        for name in sorted(emitted):
            if name not in doc_text:
                path, node = emitted[name][0]
                sf = project.file(path)
                yield self.finding(
                    sf, node,
                    f"span/event \"{name}\" is emitted but not named "
                    f"in {self.doc} — the trace model section lists "
                    "every instrumented span and point event",
                    detail=name, project=project)
        prefixes = {n.split(".")[0] for n in emitted}
        fault_sites = self._fault_sites(project)
        for name in sorted(set(_BACKTICK_NAME_RE.findall(doc_text))):
            head, tail = name.split(".", 1)
            if head not in prefixes or tail in _NON_TRACE_SUFFIXES:
                continue                 # not a trace-name reference
            if name in emitted or name in fault_sites:
                continue
            yield self._doc_finding(
                doc_text, f"`{name}`",
                f"documented span/event \"{name}\" is never emitted — "
                "remove the doc reference or restore the "
                "instrumentation",
                detail=name)

    def _fault_sites(self, project: Project) -> Set[str]:
        # fault sites share the dotted namespace (`transfer.serialize`
        # is both a span and a site); the doc may reference either
        from .faultsites import FaultSiteDriftChecker, collect_doc_sites
        return collect_doc_sites(
            project, FaultSiteDriftChecker.DEFAULT_FAULTS_FILE)
