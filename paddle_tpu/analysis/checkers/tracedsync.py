"""PDT002 — traced host/device boundary.

Repo law (PR 6, the ragged-kernel integration pitfall): code inside a
``jax.jit``- or ``pallas_call``-traced function runs under tracing —
a host sync there (``np.asarray`` on a tracer, ``.item()``,
``jax.device_get``, ``float()`` of an operand) either crashes with a
`TracerArrayConversionError` at first dispatch or, worse, silently
constant-folds a value that should be data-dependent.

The checker marks a function TRACED when it is decorated with
``jax.jit`` (bare or via ``partial``), passed to a ``jax.jit(...)``
call, or is the kernel argument of a ``pallas_call``; every call in
its body (nested defs included) is then checked against the forbidden
set. ``float()``/``int()`` are flagged only when applied directly to a
parameter of the traced function — shape arithmetic on static Python
ints stays legal.
"""
from __future__ import annotations

import ast
from typing import Iterable, List, Set, Tuple

from .._astutil import (body_calls, call_name, dotted, import_aliases,
                        walk_functions)
from ..core import Checker, Finding, Project

__all__ = ["TracedHostSyncChecker"]


class TracedHostSyncChecker(Checker):
    code = "PDT002"
    name = "traced-host-sync"
    rationale = ("no host synchronization inside jit/pallas-traced "
                 "functions (PR 6 jnp-inside-trace pitfall)")

    # serving/submesh.py joined the scope with the TP subsystem (ISSUE
    # 12): it builds shard_map/NamedSharding plumbing around the same
    # traced programs, so a host sync there hits the same pitfall
    DEFAULT_SCOPE = ("paddle_tpu/ops/*.py", "paddle_tpu/models/*.py",
                     "paddle_tpu/serving/submesh.py")

    def __init__(self, scope: Tuple[str, ...] = DEFAULT_SCOPE):
        self.scope = scope

    # -- traced-function discovery --------------------------------------
    def _is_jit_expr(self, node: ast.AST, aliases) -> bool:
        """`jax.jit` / `jit`, possibly wrapped in functools.partial."""
        name = dotted(node, aliases)
        if name is not None and (name == "jax.jit"
                                 or name.endswith(".jit")
                                 or name == "jit"):
            return True
        if isinstance(node, ast.Call):
            inner = call_name(node, aliases)
            if inner is not None and inner.split(".")[-1] == "partial":
                return any(self._is_jit_expr(a, aliases)
                           for a in node.args)
        return False

    def _traced_names(self, tree: ast.AST, aliases) -> Set[str]:
        traced: Set[str] = set()
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            name = call_name(node, aliases)
            if name is None:
                continue
            tail = name.split(".")[-1]
            if name == "jax.jit" or name == "jit" \
                    or name.endswith(".jit"):
                for a in node.args[:1]:
                    if isinstance(a, ast.Name):
                        traced.add(a.id)
            elif tail == "pallas_call":
                for a in node.args[:1]:
                    if isinstance(a, ast.Name):
                        traced.add(a.id)
                kern = next((kw.value for kw in node.keywords
                             if kw.arg == "kernel"), None)
                if isinstance(kern, ast.Name):
                    traced.add(kern.id)
            elif tail == "partial":
                # partial(kernel_fn, static...) handed to pallas_call /
                # jit: the wrapped Name traces
                if any(self._is_jit_expr(a, aliases) for a in node.args):
                    for a in node.args:
                        if isinstance(a, ast.Name):
                            traced.add(a.id)
        return traced

    def _traced_functions(self, tree: ast.AST,
                          aliases) -> List[ast.FunctionDef]:
        names = self._traced_names(tree, aliases)
        out = []
        for fn in walk_functions(tree):
            if fn.name in names:
                out.append(fn)
                continue
            for dec in fn.decorator_list:
                if self._is_jit_expr(dec, aliases):
                    out.append(fn)
                    break
        return out

    # -- forbidden-call scan --------------------------------------------
    def _forbidden(self, call: ast.Call, aliases,
                   params: Set[str]):
        name = call_name(call, aliases)
        if name is not None:
            tail = name.split(".")
            if len(tail) >= 2 and tail[-2] in ("numpy", "np") \
                    and tail[-1] in ("asarray", "array"):
                return (f"{tail[-2]}.{tail[-1]}",
                        "materializes a host array from a tracer")
            if name == "jax.device_get" or name.endswith(
                    ".device_get"):
                return ("jax.device_get", "explicit device->host sync")
        if isinstance(call.func, ast.Attribute) \
                and call.func.attr == "item" and not call.args:
            return (".item()", "scalar host sync")
        if isinstance(call.func, ast.Name) \
                and call.func.id in ("float", "int") and call.args:
            a = call.args[0]
            if isinstance(a, ast.Name) and a.id in params:
                return (f"{call.func.id}()",
                        "concretizes a traced operand")
        return None

    def check(self, project: Project) -> Iterable[Finding]:
        for sf in project.match(self.scope):
            if sf.tree is None:
                continue
            aliases = import_aliases(sf.tree)
            seen: Set[int] = set()
            for fn in self._traced_functions(sf.tree, aliases):
                params = {a.arg for a in (fn.args.args
                                          + fn.args.posonlyargs
                                          + fn.args.kwonlyargs)}
                for call in body_calls(fn):
                    key = id(call)
                    if key in seen:
                        continue
                    hit = self._forbidden(call, aliases, params)
                    if hit is None:
                        continue
                    seen.add(key)
                    what, why = hit
                    yield self.finding(
                        sf, call,
                        f"{what} inside traced function "
                        f"`{fn.name}` — {why}; move it outside the "
                        f"trace or keep the value on-device",
                        detail=f"{fn.name}:{what}", project=project)
