"""PDT005 — prefix-page pin/decref pairing.

Repo law (PR 1 paged KV + prefix trie; `_claim_candidate` docstring):
admission pins matched prefix pages (`_incref`) BEFORE the worst-case
reservation — under pool pressure `_reserve_ok` may evict the matched
chain itself — and ownership then travels with the claim until the
slot holds its own references. Two structural obligations follow:

* a **caller of `_claim_candidate`** receives pinned pages and must
  release them on every path, success or raise — i.e. a `_decref`
  inside a `finally`;
* a **pin held across the reservation** (`_incref` before
  `_reserve_ok` in the same function) must be exception-guarded: if
  the reservation raises, an unguarded pin leaks the page refcount
  and the next `check_invariants()` sweep dies far from the cause.

Both rules are purely structural, so the AST can enforce what the
docstrings could only describe. This checker found two live hits at
introduction (`_claim_candidate` and `import_pages` pinned across an
unguarded `_reserve_ok`), fixed in the same PR.
"""
from __future__ import annotations

import ast
from typing import Iterable, List, Optional, Tuple

from .._astutil import walk_functions
from ..core import Checker, Finding, Project

__all__ = ["PinPairingChecker"]


def _method_tail(call: ast.Call) -> Optional[str]:
    if isinstance(call.func, ast.Attribute):
        return call.func.attr
    if isinstance(call.func, ast.Name):
        return call.func.id
    return None


def _calls_in(node: ast.AST, names) -> List[ast.Call]:
    return [n for n in ast.walk(node)
            if isinstance(n, ast.Call) and _method_tail(n) in names]


class PinPairingChecker(Checker):
    code = "PDT005"
    name = "pin-decref-pairing"
    rationale = ("prefix-page pins must be released on every path "
                 "(PR 1 paged admission; _claim_candidate contract)")

    DEFAULT_SCOPE = ("paddle_tpu/models/serving.py",
                     "paddle_tpu/serving/*.py")

    def __init__(self, scope=DEFAULT_SCOPE,
                 incref_names=("_incref",), decref_names=("_decref",),
                 claim_names=("_claim_candidate",),
                 reserve_names=("_reserve_ok",)):
        self.scope = scope
        self.incref_names = incref_names
        self.decref_names = decref_names
        self.claim_names = claim_names
        self.reserve_names = reserve_names

    # -- rule helpers ----------------------------------------------------
    def _guarded_tries(self, fn: ast.FunctionDef) -> List[ast.Try]:
        """Try statements whose finally or except bodies release pins."""
        out = []
        for node in ast.walk(fn):
            if not isinstance(node, ast.Try):
                continue
            release = list(node.finalbody)
            for h in node.handlers:
                release.extend(h.body)
            if any(_calls_in(stmt, self.decref_names)
                   for stmt in release):
                out.append(node)
        return out

    @staticmethod
    def _encloses(outer: ast.AST, inner: ast.AST) -> bool:
        return any(n is inner for n in ast.walk(outer))

    def check(self, project: Project) -> Iterable[Finding]:
        for sf in project.match(self.scope):
            if sf.tree is None:
                continue
            for fn in walk_functions(sf.tree):
                yield from self._check_fn(project, sf, fn)

    def _check_fn(self, project: Project, sf, fn: ast.FunctionDef,
                  ) -> Iterable[Finding]:
        if fn.name in self.claim_names:
            claims = []          # the claim owner is checked by rule 2
        else:
            claims = _calls_in(fn, self.claim_names)
        guarded = self._guarded_tries(fn)
        # rule 1: claim callers release in a finally — and the guarded
        # try must ENCLOSE or FOLLOW the claim (an unrelated earlier
        # try/finally in the same function covers nothing)
        release_tries = [
            t for t in self._tries_with_finally(fn)
            if any(_calls_in(stmt, self.decref_names)
                   for stmt in t.finalbody)]
        for call in claims:
            if not any(t.lineno >= call.lineno
                       or self._encloses(t, call)
                       for t in release_tries):
                yield self.finding(
                    sf, call,
                    f"`{fn.name}` takes pinned prefix pages from "
                    f"{_method_tail(call)}() but has no "
                    "finally-guarded decref — a raise between claim "
                    "and release leaks the pins",
                    detail=f"claim:{_method_tail(call)}",
                    project=project)
        # rule 2: pin held across the reservation is exception-guarded
        increfs = _calls_in(fn, self.incref_names)
        reserves = _calls_in(fn, self.reserve_names)
        for res in reserves:
            before = [i for i in increfs if i.lineno < res.lineno]
            if not before:
                continue
            if any(self._encloses(t, res) for t in guarded):
                continue
            yield self.finding(
                sf, res,
                f"`{fn.name}` pins pages (line "
                f"{before[0].lineno}) and then calls "
                f"{_method_tail(res)}() unguarded — if the "
                "reservation raises, the pinned pages leak their "
                "refcount; wrap it so the pins release on the error "
                "path",
                detail=f"pin-across:{_method_tail(res)}",
                project=project)

    def _tries_with_finally(self, fn: ast.FunctionDef) -> List[ast.Try]:
        return [n for n in ast.walk(fn)
                if isinstance(n, ast.Try) and n.finalbody]
