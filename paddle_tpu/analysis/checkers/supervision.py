"""PDT006 — swallowed supervision errors.

Repo law (PR 4 replica supervision, PR 5 operability): on the router
and replica step paths an exception IS the signal — every broad
handler must either re-raise, charge the failure to a replica's
health (`note_failure`), or leave a trace (a metric increment or a
telemetry event). A broad handler that silently drops the error
(`except Exception: return 0`) turns a failing subsystem into an
invisible one: the fleet keeps stepping and the operator surface
shows green.

The rule is deliberately narrow to stay precise: a *bare* ``except:``
is always a finding (it eats ``KeyboardInterrupt``), and an
``except Exception`` / ``except BaseException`` handler is a finding
only when its body contains **no call at all and no raise** — pure
``pass`` / ``continue`` / ``return <constant>`` swallows. A handler
that calls anything is assumed to be handling (the fixed live hit:
`_restore_spill` returned 0 on any engine import error, so failed
cache warm-ups were indistinguishable from cold misses).
"""
from __future__ import annotations

import ast
from typing import Iterable

from ..core import Checker, Finding, Project

__all__ = ["SwallowedErrorChecker"]

_BROAD = {"Exception", "BaseException"}


def _handler_types(handler: ast.ExceptHandler):
    t = handler.type
    if t is None:
        return None                      # bare except
    if isinstance(t, ast.Tuple):
        return [e.id if isinstance(e, ast.Name) else None
                for e in t.elts]
    return [t.id if isinstance(t, ast.Name) else None]


class SwallowedErrorChecker(Checker):
    code = "PDT006"
    name = "swallowed-supervision-error"
    rationale = ("router/replica step paths must re-raise, charge "
                 "health, or count a metric/event for every broad "
                 "exception (PR 4/5)")

    DEFAULT_SCOPE = ("paddle_tpu/serving/*.py",
                     "paddle_tpu/models/serving.py")

    def __init__(self, scope=DEFAULT_SCOPE):
        self.scope = scope

    def check(self, project: Project) -> Iterable[Finding]:
        for sf in project.match(self.scope):
            if sf.tree is None:
                continue
            for node in ast.walk(sf.tree):
                if not isinstance(node, ast.ExceptHandler):
                    continue
                types = _handler_types(node)
                if types is None:
                    yield self.finding(
                        sf, node,
                        "bare `except:` on a supervision path — it "
                        "eats KeyboardInterrupt/SystemExit; catch "
                        "Exception at the broadest",
                        detail="bare-except", project=project)
                    continue
                if not any(t in _BROAD for t in types if t):
                    continue
                has_raise = any(isinstance(n, ast.Raise)
                                for n in ast.walk(node))
                has_call = any(isinstance(n, ast.Call)
                               for n in ast.walk(node))
                if has_raise or has_call:
                    continue
                yield self.finding(
                    sf, node,
                    "broad except swallows the error with no "
                    "re-raise, health charge, metric, or event — a "
                    "failing subsystem becomes invisible to the "
                    "operator surface",
                    detail="swallow", project=project)
