"""PDT010 — model-key discipline.

Repo law (ISSUE 17, the multi-model serving plane): model identity has
ONE canonical spelling — ``serving/model_store.py``'s ``model_id(base,
adapter)`` / ``split_model_id(mid)`` pair (and ``admission.budget_key``
for the (tenant, model) budget axis built on top of it). Every cache,
golden, budget, journal record, and telemetry label keyed on a model
must key on that spelling, because a second ad-hoc spelling of the
same identity is a split-brain key: the canary golden lands under
``"base/a1"`` while the store's resident set says ``"base+a1"``, the
quarantine arm grades the replica against the WRONG model's stream,
and the per-model terminal ledger silently forks.

The check: inside ``paddle_tpu/serving/`` (minus the two helper
modules that DEFINE the spelling), flag any expression that re-derives
a model-identity string by hand instead of calling the helpers:

* f-strings joining two dynamic parts with the model separator ``+``
  or the budget separator ``@`` — ``f"{base}+{adapter}"``,
  ``f"{tenant}@{model}"``;
* string concatenation through a bare ``"+"`` / ``"@"`` literal —
  ``base + "+" + adapter``;
* hand-splitting a model id — ``mid.split("+")`` /
  ``mid.partition("+")`` — instead of ``split_model_id``.

Constant strings (``"base+a1"`` in a test fixture or a docstring) are
NOT flagged: the rule targets key *derivation*, not key *values*.
"""
from __future__ import annotations

import ast
from typing import Iterable, Iterator, Optional, Tuple

from ..core import Checker, Finding, Project

__all__ = ["ModelKeyChecker"]

# the identity separators with one canonical spelling each:
# model_store._SEP ("+", base+adapter) and admission.budget_key's "@"
# (tenant@model)
_SEPARATORS = ("+", "@")
_SPLITTERS = frozenset({"split", "rsplit", "partition", "rpartition"})


def _sep_const(node: ast.AST) -> Optional[str]:
    if (isinstance(node, ast.Constant)
            and isinstance(node.value, str)
            and node.value in _SEPARATORS):
        return node.value
    return None


def _enclosing_names(tree: ast.AST) -> Iterator[Tuple[str, ast.AST]]:
    """Yield ``(scope_name, node)`` for every node, where scope_name is
    the innermost enclosing function (or ``<module>``)."""
    def visit(node: ast.AST, scope: str) -> Iterator[Tuple[str, ast.AST]]:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                yield (scope, child)
                yield from visit(child, child.name)
            else:
                yield (scope, child)
                yield from visit(child, scope)
    yield from visit(tree, "<module>")


class ModelKeyChecker(Checker):
    code = "PDT010"
    name = "model-key"
    rationale = ("model identity has one canonical spelling — "
                 "model_id()/split_model_id()/budget_key() (ISSUE 17 "
                 "— an ad-hoc re-spelling forks every cache, golden, "
                 "and budget keyed on it)")

    DEFAULT_SCOPE = ("paddle_tpu/serving/*.py",)
    # the helpers' home modules define the spelling; everyone else
    # calls them
    DEFAULT_ALLOW: Tuple[str, ...] = (
        "paddle_tpu/serving/model_store.py",
        "paddle_tpu/serving/admission.py",
    )

    def __init__(self, scope: Tuple[str, ...] = DEFAULT_SCOPE,
                 allow: Tuple[str, ...] = DEFAULT_ALLOW):
        self.scope = scope
        self.allow = allow

    def check(self, project: Project) -> Iterable[Finding]:
        for sf in project.match(self.scope, exclude=self.allow):
            if sf.tree is None:
                continue
            for scope_name, node in _enclosing_names(sf.tree):
                hit = self._classify(node)
                if hit is None:
                    continue
                kind, sep = hit
                helper = ("budget_key()" if sep == "@"
                          else "model_id()/split_model_id()")
                yield self.finding(
                    sf, node,
                    f"ad-hoc model-identity {kind} through {sep!r} — "
                    f"key caches/goldens/budgets via the canonical "
                    f"{helper} helper (PDT010: a second spelling of "
                    "the same model id forks every structure keyed "
                    "on it)",
                    detail=f"{scope_name}:{kind}{sep}",
                    project=project)

    @staticmethod
    def _classify(node: ast.AST) -> Optional[Tuple[str, str]]:
        # f"{a}+{b}" — a separator Constant sandwiched between two
        # FormattedValues
        if isinstance(node, ast.JoinedStr):
            vals = node.values
            for i in range(1, len(vals) - 1):
                sep = _sep_const(vals[i])
                if (sep is not None
                        and isinstance(vals[i - 1], ast.FormattedValue)
                        and isinstance(vals[i + 1], ast.FormattedValue)):
                    return ("join", sep)
            return None
        # a + "+" + b — a separator literal as either operand of a
        # string Add (``a + "+"`` is the inner BinOp of the chain)
        if isinstance(node, ast.BinOp) and isinstance(node.op, ast.Add):
            sep = _sep_const(node.left) or _sep_const(node.right)
            if sep is not None:
                return ("concat", sep)
            return None
        # mid.split("+") / mid.partition("+") — hand-splitting the id
        if (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in _SPLITTERS
                and node.args):
            sep = _sep_const(node.args[0])
            if sep is not None:
                return ("split", sep)
        return None
