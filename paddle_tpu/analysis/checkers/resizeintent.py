"""PDT009 — resize-intent discipline.

Repo law (ISSUE 16, the elastic autoscaling control plane): the fleet
topology — replica count, the prefill:decode roles mix, the tp carve —
mutates ONLY inside a two-phase journal transaction. A durable
``resize_intent`` record must land BEFORE the first handle is built or
torn down, and a ``resize_commit`` after; a SIGKILL between the two
rolls FORWARD at replay. A topology mutation the journal never heard
about is the one crash window ``ServingRouter.recover()`` cannot
close: the journal would rehydrate the fleet into a shape that no
longer exists, stranding every live request on submeshes nobody
carved.

The check: inside ``paddle_tpu/serving/``, every CALL of a
fleet-topology mutator (``_apply_topology`` and the ``_topology_*``
family) must be textually dominated — an earlier call in the same
enclosing function — by either ``append_resize_intent`` (the resize
transaction's phase 1) or ``replay`` (crash recovery: the journaled
intent/commit IS the dominator, already durable). Calls inside the
mutator family itself are exempt (the discipline holds at the
transaction boundary, and mutators compose: ``_apply_topology``
fans out to grow/shrink/recarve under the caller's intent record).

Textual order is a sound approximation here because the mutation sites
live in straight-line transaction bodies (``resize()``/
``_rehydrate()``); a mutator call reached down a branch that skips the
intent append still flags, which is exactly the bug class the rule
exists for.
"""
from __future__ import annotations

import ast
from typing import Iterable, Tuple

from .._astutil import walk_functions
from ..core import Checker, Finding, Project

__all__ = ["ResizeIntentChecker"]

# the fleet-topology mutation surface (serving/router.py): each of
# these rebuilds, adds, removes, or re-roles replica handles
MUTATORS = frozenset({
    "_apply_topology", "_topology_grow", "_topology_shrink",
    "_topology_recarve", "_topology_set_roles", "_topology_recover",
})
# phase-1 appenders: an earlier call to one of these in the same
# function establishes the journal transaction (replay = recovery,
# where the journaled intent/commit is already durable)
DOMINATORS = frozenset({"append_resize_intent", "replay"})


def _called(node: ast.Call) -> str:
    """The bare trailing name of a call: ``self._topology_grow(...)``
    and ``_topology_grow(...)`` both give ``_topology_grow``."""
    fn = node.func
    if isinstance(fn, ast.Attribute):
        return fn.attr
    if isinstance(fn, ast.Name):
        return fn.id
    return ""


class ResizeIntentChecker(Checker):
    code = "PDT009"
    name = "resize-intent"
    rationale = ("fleet-topology mutations happen only inside a "
                 "two-phase journal transaction (ISSUE 16 — an "
                 "unjournaled resize is a crash window recover() "
                 "cannot close)")

    DEFAULT_SCOPE = ("paddle_tpu/serving/*.py",)
    DEFAULT_ALLOW: Tuple[str, ...] = ()

    def __init__(self, scope: Tuple[str, ...] = DEFAULT_SCOPE,
                 allow: Tuple[str, ...] = DEFAULT_ALLOW):
        self.scope = scope
        self.allow = allow

    def check(self, project: Project) -> Iterable[Finding]:
        for sf in project.match(self.scope, exclude=self.allow):
            if sf.tree is None:
                continue
            for fn in walk_functions(sf.tree):
                if fn.name in MUTATORS:
                    # inside the mutator family the discipline is the
                    # CALLER's: mutators compose under one intent
                    continue
                calls = [n for n in ast.walk(fn)
                         if isinstance(n, ast.Call)]
                dominator_lines = sorted(
                    n.lineno for n in calls
                    if _called(n) in DOMINATORS)
                for node in calls:
                    name = _called(node)
                    if name not in MUTATORS:
                        continue
                    if any(ln < node.lineno
                           for ln in dominator_lines):
                        continue
                    yield self.finding(
                        sf, node,
                        f"{name}() mutates the fleet topology with no "
                        "earlier append_resize_intent() in "
                        f"{fn.name}() — every resize must journal a "
                        "durable INTENT record before the first "
                        "handle changes (two-phase resize, ISSUE 16), "
                        "or a SIGKILL here strands recovery on a "
                        "topology the journal never heard of",
                        detail=f"{fn.name}:{name}", project=project)
