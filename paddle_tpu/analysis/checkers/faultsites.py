"""PDT003 — fault-site drift.

Repo law (PR 1 fault injection, PR 5 drift guard): the module
docstring of ``utils/faults.py`` is the catalog of record for fault
sites — chaos tests arm sites by name, and the
``pdt_faults_fired_total{site=...}`` series uses the same names. A
``fault_point()`` call the docstring does not list (or a documented
site no code declares) silently breaks both.

Formerly a word-boundary regex scan in
tests/test_observability_slo.py; now an AST pass, which also catches
what the regex could not: a ``fault_point(non_literal)`` call that no
text scan can account for.
"""
from __future__ import annotations

import ast
import re
from typing import Dict, Iterable, List, Set, Tuple

from .._astutil import call_name, import_aliases, literal_str
from ..core import Checker, Finding, Project

__all__ = ["FaultSiteDriftChecker", "collect_code_sites",
           "collect_doc_sites"]

_DOC_SITE_RE = re.compile(r"``([a-z_]+\.[a-z_]+)``")


def collect_code_sites(project: Project, scope, faults_file,
                       ) -> Dict[str, List[Tuple[str, ast.Call]]]:
    """``fault_point("...")`` / ``fault_value("...", ...)`` /
    ``value_armed("...")`` literal sites across `scope` (excluding the
    declaring module itself): {site: [(relpath, call node)]}. VALUE
    sites (ISSUE 14 corrupt mode) are declarations exactly like raise
    sites — the docstring catalog covers both, and `value_armed` is
    counted so a gather-guard without its paired `fault_value` still
    registers the site it guards."""
    sites: Dict[str, List[Tuple[str, ast.Call]]] = {}
    for sf in project.match(scope, exclude=(faults_file,)):
        if sf.tree is None:
            continue
        aliases = import_aliases(sf.tree)
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.Call):
                continue
            name = call_name(node, aliases)
            if name is None or name.split(".")[-1] not in (
                    "fault_point", "fault_value", "value_armed"):
                continue
            lit = literal_str(node.args[0]) if node.args else None
            key = lit if lit is not None else ""
            sites.setdefault(key, []).append((sf.relpath, node))
    return sites


def collect_doc_sites(project: Project, faults_file) -> Set[str]:
    """The ``site`` tokens of the faults.py module docstring."""
    sf = project.file(faults_file)
    if sf is None or sf.tree is None:
        return set()
    doc = ast.get_docstring(sf.tree) or ""
    return set(_DOC_SITE_RE.findall(doc))


class FaultSiteDriftChecker(Checker):
    code = "PDT003"
    name = "fault-site-drift"
    rationale = ("the faults.py docstring, the fault_point() call "
                 "sites, and the pdt_faults_fired_total site labels "
                 "are one catalog (PR 1/5)")

    DEFAULT_SCOPE = ("paddle_tpu/*.py", "paddle_tpu/**/*.py")
    DEFAULT_FAULTS_FILE = "paddle_tpu/utils/faults.py"

    def __init__(self, scope=DEFAULT_SCOPE,
                 faults_file=DEFAULT_FAULTS_FILE):
        self.scope = scope
        self.faults_file = faults_file

    def check(self, project: Project) -> Iterable[Finding]:
        faults_sf = project.file(self.faults_file)
        if faults_sf is None:
            return
        code_sites = collect_code_sites(project, self.scope,
                                        self.faults_file)
        doc_sites = collect_doc_sites(project, self.faults_file)
        for path, node in code_sites.pop("", []):
            sf = project.file(path)
            yield self.finding(
                sf, node,
                "fault_point() with a non-literal site name — chaos "
                "tests and the docstring catalog can only track "
                "literal sites",
                detail="non-literal", project=project)
        for site in sorted(set(code_sites) - doc_sites):
            path, node = code_sites[site][0]
            sf = project.file(path)
            yield self.finding(
                sf, node,
                f"fault site \"{site}\" is not listed in the "
                f"{self.faults_file} docstring — add it (the "
                "docstring is the chaos-site catalog of record)",
                detail=site, project=project)
        for site in sorted(doc_sites - set(code_sites)):
            line = 0
            for i, ln in enumerate(faults_sf.lines, start=1):
                if f"``{site}``" in ln:
                    line = i
                    break
            yield Finding(
                self.code, faults_sf.relpath, line,
                f"documented fault site \"{site}\" has no "
                "fault_point() call in the tree — remove the "
                "docstring entry or restore the site",
                symbol="<module docstring>", detail=site,
                checker=self.name)
