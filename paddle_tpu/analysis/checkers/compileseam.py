"""PDT012 — compile-seam discipline in the serving engine.

Repo law (ISSUE 20, the performance attribution plane): every jitted
program the engine creates must flow through the ONE metered seam —
``_jit_lru`` for keyed caches, ``_jit_singleton`` for one-off
programs — because that seam is where compile observability lives:
``pdt_jit_compiles_total{family}``, the compile-seconds histogram, the
``jit.compile`` span, cache entry/eviction gauges, and the
retrace-storm detector. A ``jax.jit`` (or ``pallas_call``) result
stashed on ``self`` directly, or a hand-rolled ``self._foo_jits[key] =
...`` store, is a compile the profiler never sees — the warm-window
zero-compile assertion in bench.py and the retrace-storm alarm both go
blind to it.

Three shapes are flagged, all scoped to the engine file:

* a ``jax.jit(...)`` / ``pallas_call(...)`` call outside a ``_build*``
  builder method (builders RETURN the jitted program; the seam calls
  them and meters the result — jitting anywhere else bypasses it);
* a subscript store into a ``*_jits`` cache outside ``_jit_lru``
  (keyed caches are the seam's property);
* an assignment to a ``self.*_jit`` attribute whose RHS is neither
  ``self._jit_singleton(...)`` nor ``None`` (the reset idiom).
"""
from __future__ import annotations

import ast
from typing import Iterable, Iterator, Tuple

from .._astutil import call_name, import_aliases
from ..core import Checker, Finding, Project

__all__ = ["CompileSeamChecker"]


class CompileSeamChecker(Checker):
    code = "PDT012"
    name = "compile-seam"
    rationale = ("every engine jit must flow through the metered "
                 "_jit_lru/_jit_singleton seam so compile counters, "
                 "the jit.compile span, and the retrace-storm detector "
                 "see it (ISSUE 20 compile observability)")

    # the engine file: the only place the repo creates decode/prefill
    # programs. models/llama.py holds pure module code (no jit), and
    # generate()-style scripts outside the engine are not under the
    # warm-window zero-compile contract
    DEFAULT_SCOPE = ("paddle_tpu/models/serving.py",)
    # builder methods whose RETURN VALUE is the jitted program — the
    # seam calls these and meters the result
    BUILDER_PREFIX = "_build"
    # the seam itself
    SEAM_FUNCS = ("_jit_lru", "_jit_singleton")

    def __init__(self, scope: Tuple[str, ...] = DEFAULT_SCOPE):
        self.scope = scope

    def _functions(self, tree: ast.AST
                   ) -> Iterator[ast.FunctionDef]:
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef,
                                 ast.AsyncFunctionDef)):
                yield node

    @staticmethod
    def _innermost(tree: ast.AST, node: ast.AST) -> str:
        """Name of the innermost enclosing function of `node` (by
        line span — fixtures and the engine file never overlap defs on
        one line), or ``<module>``."""
        best, best_span = "<module>", None
        for fn in ast.walk(tree):
            if not isinstance(fn, (ast.FunctionDef,
                                   ast.AsyncFunctionDef)):
                continue
            end = fn.end_lineno or fn.lineno
            if fn.lineno <= node.lineno <= end:
                span = end - fn.lineno
                if best_span is None or span < best_span:
                    best, best_span = fn.name, span
        return best

    def _is_jit_call(self, call: ast.Call, aliases) -> str:
        name = call_name(call, aliases)
        if name == "jax.jit" or (name is not None
                                 and name.endswith(".jit")
                                 and name.split(".")[0] == "jax"):
            return "jax.jit"
        if name is not None and (name == "pallas_call"
                                 or name.endswith(".pallas_call")):
            return "pallas_call"
        return ""

    @staticmethod
    def _is_seam_rhs(value: ast.AST) -> bool:
        """``self._jit_singleton(...)`` or ``None`` — the two legal
        right-hand sides for a ``self.*_jit`` slot."""
        if isinstance(value, ast.Constant) and value.value is None:
            return True
        if isinstance(value, ast.Call) \
                and isinstance(value.func, ast.Attribute) \
                and value.func.attr == "_jit_singleton":
            return True
        return False

    def check(self, project: Project) -> Iterable[Finding]:
        for sf in project.match(self.scope):
            if sf.tree is None:
                continue
            aliases = import_aliases(sf.tree)
            for node in ast.walk(sf.tree):
                if isinstance(node, ast.Call):
                    what = self._is_jit_call(node, aliases)
                    if not what:
                        continue
                    fn = self._innermost(sf.tree, node)
                    if fn.startswith(self.BUILDER_PREFIX) \
                            or fn in self.SEAM_FUNCS:
                        continue
                    yield self.finding(
                        sf, node,
                        f"{what} in `{fn}` — compiles outside the "
                        f"metered seam; return the program from a "
                        f"_build* method and route it through "
                        f"_jit_lru/_jit_singleton",
                        detail=f"{fn}:{what}", project=project)
                elif isinstance(node, ast.Assign):
                    for tgt in node.targets:
                        if isinstance(tgt, ast.Subscript) \
                                and isinstance(tgt.value,
                                               ast.Attribute) \
                                and tgt.value.attr.endswith("_jits"):
                            fn = self._innermost(sf.tree, node)
                            if fn in self.SEAM_FUNCS:
                                continue
                            yield self.finding(
                                sf, node,
                                f"direct store into "
                                f"`{tgt.value.attr}` in `{fn}` — "
                                f"keyed jit caches are _jit_lru's "
                                f"property (evictions and entry "
                                f"counts are metered there)",
                                detail=f"{fn}:{tgt.value.attr}[]",
                                project=project)
                        elif isinstance(tgt, ast.Attribute) \
                                and tgt.attr.endswith("_jit") \
                                and not self._is_seam_rhs(node.value):
                            fn = self._innermost(sf.tree, node)
                            if fn in self.SEAM_FUNCS:
                                continue
                            yield self.finding(
                                sf, node,
                                f"`{tgt.attr}` assigned in `{fn}` "
                                f"from something other than "
                                f"self._jit_singleton(...) or None — "
                                f"the compile is invisible to "
                                f"pdt_jit_compiles_total",
                                detail=f"{fn}:{tgt.attr}",
                                project=project)
