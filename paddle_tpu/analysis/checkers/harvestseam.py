"""PDT011 — harvest-seam discipline in the serving hot loop.

Repo law (ISSUE 18, the pipelined decode loop): the engine/router
decode path must stay free of host synchronization so the deferred-
harvest window actually overlaps — one stray ``np.asarray`` on the
device token ring re-serializes every dispatch and silently turns
``harvest_every=8`` back into the synchronous loop, with none of the
tests noticing (the streams stay bit-identical; only the overlap
dies). Host syncs belong in the DESIGNATED harvest functions
(``_harvest*`` / ``quiesce*``), which are the one seam where the
window closes: D2H pull, token commits, journal/mirror/sentry work.

The forbidden set is PDT002's (``np.asarray``/``np.array``,
``jax.device_get``, argless ``.item()``, ``float()``/``int()`` of a
bare parameter) — the same syncs, policed in a different place: PDT002
bans them INSIDE traced functions, PDT011 bans them in the HOST-side
decode path outside the harvest seam. Subscript reads like
``int(self._tok[i])`` stay legal: by the time the commit loop runs
they index a harvested host array.
"""
from __future__ import annotations

import ast
from typing import Iterable, Set, Tuple

from .._astutil import body_calls, call_name, import_aliases
from ..core import Checker, Finding, Project

__all__ = ["HarvestSeamChecker"]


class HarvestSeamChecker(Checker):
    code = "PDT011"
    name = "harvest-seam"
    rationale = ("no host sync in the engine/router decode path "
                 "outside the designated _harvest*/quiesce* functions "
                 "(ISSUE 18 pipelined-loop discipline)")

    # the serving hot loop: the engine's step/_decode pair and the
    # router's step-driven supervision around it
    DEFAULT_SCOPE = ("paddle_tpu/models/serving.py",
                     "paddle_tpu/serving/router.py")
    # host-side decode-path functions under the discipline. Deliberately
    # a closed list: most of serving.py (prefill, export, bench plumbing)
    # legitimately syncs — only the per-token hot loop must not
    DECODE_PATH = ("step", "_decode")
    # designated harvest seam: functions with these name prefixes may
    # sync (and nested defs inside them inherit the exemption)
    SEAM_PREFIXES = ("_harvest", "quiesce")

    def __init__(self, scope: Tuple[str, ...] = DEFAULT_SCOPE):
        self.scope = scope

    def _decode_path_functions(self, tree: ast.AST):
        """Top-level walk that respects the seam: a DECODE_PATH
        function yields with the set of nested seam-function call
        nodes excluded from its scan."""
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and node.name in self.DECODE_PATH:
                yield node

    def _seam_calls(self, fn: ast.AST) -> Set[int]:
        """Call nodes living inside a nested seam function (a local
        ``def _harvest_x()`` helper) — exempt."""
        out: Set[int] = set()
        for node in ast.walk(fn):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and node is not fn \
                    and node.name.startswith(self.SEAM_PREFIXES):
                for call in ast.walk(node):
                    if isinstance(call, ast.Call):
                        out.add(id(call))
        return out

    def _forbidden(self, call: ast.Call, aliases, params: Set[str]):
        name = call_name(call, aliases)
        if name is not None:
            tail = name.split(".")
            # numpy.asarray/array is the D2H pull; jax.numpy.asarray
            # is the opposite direction (host->device upload feeding
            # the dispatch) and stays legal on the hot path
            if len(tail) >= 2 and tail[-2] in ("numpy", "np") \
                    and tail[-1] in ("asarray", "array") \
                    and tail[0] != "jax":
                return (f"{tail[-2]}.{tail[-1]}",
                        "pulls a device value to host mid-window")
            if name == "jax.device_get" \
                    or name.endswith(".device_get"):
                return ("jax.device_get", "explicit device->host sync")
        if isinstance(call.func, ast.Attribute) \
                and call.func.attr == "item" and not call.args:
            return (".item()", "scalar host sync")
        if isinstance(call.func, ast.Name) \
                and call.func.id in ("float", "int") and call.args:
            a = call.args[0]
            if isinstance(a, ast.Name) and a.id in params:
                return (f"{call.func.id}()",
                        "concretizes a possibly-device value")
        return None

    def check(self, project: Project) -> Iterable[Finding]:
        for sf in project.match(self.scope):
            if sf.tree is None:
                continue
            aliases = import_aliases(sf.tree)
            seen: Set[int] = set()
            for fn in self._decode_path_functions(sf.tree):
                params = {a.arg for a in (fn.args.args
                                          + fn.args.posonlyargs
                                          + fn.args.kwonlyargs)
                          if a.arg != "self"}
                exempt = self._seam_calls(fn)
                for call in body_calls(fn):
                    key = id(call)
                    if key in seen or key in exempt:
                        continue
                    hit = self._forbidden(call, aliases, params)
                    if hit is None:
                        continue
                    seen.add(key)
                    what, why = hit
                    yield self.finding(
                        sf, call,
                        f"{what} in decode-path function `{fn.name}` "
                        f"— {why}; host syncs belong in a designated "
                        f"_harvest*/quiesce* seam function",
                        detail=f"{fn.name}:{what}", project=project)
