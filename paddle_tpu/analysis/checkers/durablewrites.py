"""PDT007 — durable-write discipline.

Repo law (ISSUE 13, the router write-ahead journal): control-plane
state under ``paddle_tpu/serving/`` reaches disk through exactly two
doors — the journal's append path (``serving/journal.py``, whose
records are checksummed, length-prefixed, and torn-tail tolerated at
replay) or the atomic tmp+rename commit helper
(``journal.commit_bytes``). A bare ``open(path, "w")`` anywhere else
in the serving layer is a torn-file crash window: a SIGKILL mid-write
leaves a half-file that no replay rule covers, which is precisely the
failure mode the journal subsystem exists to close. The checker flags

* ``open()`` / ``io.open()`` / ``os.fdopen()`` calls whose mode
  literal writes (``w``/``a``/``x``/``+``) — a NON-literal mode is
  flagged too (the discipline cannot be audited around a variable);
* ``os.open()`` (low-level descriptors have no business in the
  serving layer outside the journal);
* ``pathlib``-style ``.write_text()`` / ``.write_bytes()`` calls.

Read-mode opens pass. ``serving/journal.py`` itself is the allowlist:
it owns the append files and implements the commit helper.
"""
from __future__ import annotations

import ast
from typing import Iterable, Tuple

from .._astutil import call_name, import_aliases, literal_str
from ..core import Checker, Finding, Project

__all__ = ["DurableWriteChecker"]

_OPEN_CALLS = ("open", "io.open", "os.fdopen")
_WRITE_ATTRS = ("write_text", "write_bytes")


def _mode_of(call: ast.Call):
    """The mode argument of an open()-style call: (literal_or_None,
    present). Positional arg 1 or keyword ``mode``."""
    node = None
    if len(call.args) >= 2:
        node = call.args[1]
    for kw in call.keywords:
        if kw.arg == "mode":
            node = kw.value
    if node is None:
        return None, False
    return literal_str(node), True


class DurableWriteChecker(Checker):
    code = "PDT007"
    name = "durable-write"
    rationale = ("serving-layer state reaches disk only through the "
                 "write-ahead journal appender or the tmp+rename "
                 "commit helper (ISSUE 13 — a bare write is a "
                 "torn-file crash window)")

    DEFAULT_SCOPE = ("paddle_tpu/serving/*.py",)
    # the journal IS the durable-write implementation: its append path
    # and commit_bytes own their files
    DEFAULT_ALLOW = ("paddle_tpu/serving/journal.py",)

    def __init__(self, scope: Tuple[str, ...] = DEFAULT_SCOPE,
                 allow: Tuple[str, ...] = DEFAULT_ALLOW):
        self.scope = scope
        self.allow = allow

    def check(self, project: Project) -> Iterable[Finding]:
        for sf in project.match(self.scope, exclude=self.allow):
            if sf.tree is None:
                continue
            aliases = import_aliases(sf.tree)
            for node in ast.walk(sf.tree):
                if not isinstance(node, ast.Call):
                    continue
                # pathlib-style writes: flagged on the attribute name
                # alone (the receiver's type is not statically known,
                # and a false positive here is a reviewable
                # suppression, not a torn file)
                if isinstance(node.func, ast.Attribute) \
                        and node.func.attr in _WRITE_ATTRS:
                    yield self.finding(
                        sf, node,
                        f".{node.func.attr}() under serving/ — route "
                        "durable state through the journal appender "
                        "or journal.commit_bytes (tmp+rename), not a "
                        "direct file write",
                        detail=node.func.attr, project=project)
                    continue
                name = call_name(node, aliases)
                if name == "os.open":
                    yield self.finding(
                        sf, node,
                        "os.open() under serving/ — low-level "
                        "descriptors belong to the journal "
                        "(serving/journal.py); route writes through "
                        "its appender or journal.commit_bytes",
                        detail="os.open", project=project)
                    continue
                if name not in _OPEN_CALLS:
                    continue
                mode, present = _mode_of(node)
                if not present:
                    continue                 # bare open(p) reads
                if mode is None:
                    yield self.finding(
                        sf, node,
                        "open() with a non-literal mode under "
                        "serving/ — the durable-write discipline "
                        "cannot be audited around a variable; use a "
                        "literal mode (or the journal helpers for "
                        "writes)",
                        detail="non-literal-mode", project=project)
                elif any(c in mode for c in "wax+"):
                    yield self.finding(
                        sf, node,
                        f"open(..., {mode!r}) under serving/ — a bare "
                        "write is a torn-file crash window; route it "
                        "through the journal appender or "
                        "journal.commit_bytes (tmp+rename)",
                        detail=f"open:{mode}", project=project)
