"""PDT001 — injectable-clock discipline.

Repo law (PR 4/5): the serving, fleet, and checkpoint layers are
step-driven and clock-injectable — deterministic on the CPU test mesh,
no wall-clock reads inside the machinery. A direct ``time.time()`` /
``time.monotonic()`` / ``time.perf_counter()`` call on those paths
cannot be driven by the tests' fake clocks (the PR-8 live hit:
``serving/transfer.py`` timed migrations on ``time.perf_counter()``,
so the bench's migration-latency quantiles were fake-clock-blind).

References to the clock functions (``clock=time.monotonic`` defaults)
are fine — the law bans the *call*, not the injectable default.
"""
from __future__ import annotations

import ast
from typing import Iterable, Tuple

from .._astutil import call_name, import_aliases
from ..core import Checker, Finding, Project

__all__ = ["InjectableClockChecker"]

_CLOCK_CALLS = ("time.time", "time.monotonic", "time.perf_counter")


class InjectableClockChecker(Checker):
    code = "PDT001"
    name = "injectable-clock"
    rationale = ("serving/fleet/checkpoint code must read time through "
                 "an injected clock (PR 4 router, PR 5 SLO engine, "
                 "PR 8 transfer-plane fix)")

    DEFAULT_SCOPE = (
        "paddle_tpu/serving/*.py",
        "paddle_tpu/models/serving.py",
        "paddle_tpu/distributed/checkpoint/*.py",
        "paddle_tpu/hapi/callbacks.py",
        "paddle_tpu/distributed/fleet/elastic.py",
    )
    # clock OWNERS: the observability substrate is the one place the
    # process-wide monotonic/wall base pair may be read directly
    DEFAULT_ALLOW = (
        "paddle_tpu/observability/registry.py",
        "paddle_tpu/observability/trace.py",
    )

    def __init__(self, scope: Tuple[str, ...] = DEFAULT_SCOPE,
                 allow: Tuple[str, ...] = DEFAULT_ALLOW,
                 clock_calls: Tuple[str, ...] = _CLOCK_CALLS):
        self.scope = scope
        self.allow = allow
        self.clock_calls = clock_calls

    def check(self, project: Project) -> Iterable[Finding]:
        for sf in project.match(self.scope, exclude=self.allow):
            if sf.tree is None:
                continue
            aliases = import_aliases(sf.tree)
            for node in ast.walk(sf.tree):
                if not isinstance(node, ast.Call):
                    continue
                name = call_name(node, aliases)
                if name in self.clock_calls:
                    yield self.finding(
                        sf, node,
                        f"direct {name}() call on a clock-injectable "
                        f"path — thread the owning component's "
                        f"injected clock instead (fake clocks must be "
                        f"able to drive this timing)",
                        detail=name, project=project)
