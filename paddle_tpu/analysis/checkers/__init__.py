"""The pdt-lint checker registry. Each checker encodes one piece of
repo law; docs/static_analysis.md is the human-facing catalog with the
motivating PR for every rule."""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence

from ..core import Checker
from .catalog import CatalogDriftChecker
from .clocks import InjectableClockChecker
from .compileseam import CompileSeamChecker
from .coverage import FaultCoverageChecker
from .durablewrites import DurableWriteChecker
from .faultsites import FaultSiteDriftChecker
from .harvestseam import HarvestSeamChecker
from .modelkeys import ModelKeyChecker
from .pins import PinPairingChecker
from .resizeintent import ResizeIntentChecker
from .supervision import SwallowedErrorChecker
from .tracedsync import TracedHostSyncChecker

__all__ = ["ALL_CHECKER_CLASSES", "default_checkers", "by_code",
           "CatalogDriftChecker", "CompileSeamChecker",
           "InjectableClockChecker",
           "DurableWriteChecker", "FaultCoverageChecker",
           "FaultSiteDriftChecker", "HarvestSeamChecker",
           "ModelKeyChecker", "PinPairingChecker",
           "ResizeIntentChecker", "SwallowedErrorChecker",
           "TracedHostSyncChecker"]

ALL_CHECKER_CLASSES = (
    InjectableClockChecker,      # PDT001
    TracedHostSyncChecker,       # PDT002
    FaultSiteDriftChecker,       # PDT003
    CatalogDriftChecker,         # PDT004
    PinPairingChecker,           # PDT005
    SwallowedErrorChecker,       # PDT006
    DurableWriteChecker,         # PDT007
    FaultCoverageChecker,        # PDT008
    ResizeIntentChecker,         # PDT009
    ModelKeyChecker,             # PDT010
    HarvestSeamChecker,          # PDT011
    CompileSeamChecker,          # PDT012
)


def default_checkers(codes: Optional[Sequence[str]] = None,
                     ) -> List[Checker]:
    """Instantiate the default checker set, optionally filtered to
    specific ``PDT0xx`` codes."""
    out = [cls() for cls in ALL_CHECKER_CLASSES]
    if codes is not None:
        want = set(codes)
        unknown = want - {c.code for c in out}
        if unknown:
            raise ValueError(f"unknown checker code(s): "
                             f"{sorted(unknown)} (have "
                             f"{[c.code for c in out]})")
        out = [c for c in out if c.code in want]
    return out


def by_code() -> Dict[str, type]:
    return {cls.code: cls for cls in ALL_CHECKER_CLASSES}
