"""PDT008 — fault-site coverage.

Repo law (ISSUE 14): every fault site in the ``utils/faults.py``
docstring registry must be ARMED by at least one test under
``tests/`` — a fault site nobody drills is a failure branch nobody
has ever executed, which is exactly the untested-recovery-path bug
class the injector exists to kill. New sites therefore cannot land
undrilled: adding a ``fault_point``/``fault_value`` call (PDT003
forces the docstring entry) makes this checker fail until a test arms
it.

What counts as "armed", mechanically: an AST scan of the test tree
for

* ``arm("site.name", ...)`` / ``arm_corrupt("site.name", ...)`` calls
  with a LITERAL first argument, plus
* any non-docstring string literal equal to a documented site in a
  file that calls ``arm``/``arm_corrupt`` at all — test helpers
  routinely take the site as a parameter
  (``self._run(model, fault=("speculative.draft", ...))``), and the
  literal-plus-armer heuristic keeps those honest without a full
  dataflow analysis. A site named only in DOCSTRINGS does not count.

This is a coverage FLOOR, not a proof the drill is good — review owns
that — but it is the difference between "forgot to drill it" failing
in tier-1 versus failing in production.
"""
from __future__ import annotations

import ast
import os
from typing import Iterable, Set

from .._astutil import literal_str
from ..core import Checker, Finding, Project
from .faultsites import collect_doc_sites

__all__ = ["FaultCoverageChecker", "collect_armed_sites"]

_ARMERS = ("arm", "arm_corrupt")


def _docstring_spans(tree: ast.AST) -> Set[int]:
    """Line numbers occupied by module/class/function docstrings —
    string literals there describe sites, they do not arm them."""
    spans: Set[int] = set()
    for node in ast.walk(tree):
        if not isinstance(node, (ast.Module, ast.ClassDef,
                                 ast.FunctionDef,
                                 ast.AsyncFunctionDef)):
            continue
        body = getattr(node, "body", [])
        if body and isinstance(body[0], ast.Expr) \
                and isinstance(body[0].value, ast.Constant) \
                and isinstance(body[0].value.value, str):
            doc = body[0].value
            end = getattr(doc, "end_lineno", doc.lineno)
            spans.update(range(doc.lineno, end + 1))
    return spans


def collect_armed_sites(project: Project, scope,
                        known_sites: Set[str]) -> Set[str]:
    """Sites armed by the test tree (module docstring for what
    counts). `known_sites` bounds the bare-literal heuristic to real
    site names."""
    armed: Set[str] = set()
    for sf in project.match(scope):
        if sf.tree is None:
            continue
        literals: Set[str] = set()
        has_armer = False
        doc_lines = _docstring_spans(sf.tree)
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.Call):
                func = node.func
                name = func.attr if isinstance(func, ast.Attribute) \
                    else func.id if isinstance(func, ast.Name) else None
                if name in _ARMERS:
                    has_armer = True
                    lit = literal_str(node.args[0]) if node.args \
                        else None
                    if lit is not None:
                        armed.add(lit)
            elif isinstance(node, ast.Constant) \
                    and isinstance(node.value, str) \
                    and node.value in known_sites \
                    and node.lineno not in doc_lines:
                literals.add(node.value)
        if has_armer:
            armed |= literals
    return armed


class FaultCoverageChecker(Checker):
    code = "PDT008"
    name = "fault-site-coverage"
    rationale = ("every documented fault site must be armed by at "
                 "least one test — an undrilled site is an untested "
                 "recovery path (ISSUE 14)")

    DEFAULT_SCOPE = ("tests/*.py", "tests/**/*.py")
    DEFAULT_FAULTS_FILE = "paddle_tpu/utils/faults.py"
    DEFAULT_TESTS_DIR = "tests"

    def __init__(self, scope=DEFAULT_SCOPE,
                 faults_file=DEFAULT_FAULTS_FILE,
                 tests_dir=DEFAULT_TESTS_DIR):
        self.scope = scope
        self.faults_file = faults_file
        self.tests_dir = tests_dir

    def _tests_project(self, project: Project) -> Project:
        """The CLI's default Project scans ``paddle_tpu/`` only; this
        checker needs the TEST tree. Reuse the given project when it
        already contains matching files (fixture projects do),
        otherwise parse ``<root>/tests`` on demand."""
        if project.match(self.scope):
            return project
        return Project(project.root,
                       [os.path.join(project.root, self.tests_dir)])

    def check(self, project: Project) -> Iterable[Finding]:
        faults_sf = project.file(self.faults_file)
        if faults_sf is None:
            return
        doc_sites = collect_doc_sites(project, self.faults_file)
        if not doc_sites:
            return
        tests = self._tests_project(project)
        if not tests.match(self.scope):
            return          # no test tree to grade (fixture projects)
        armed = collect_armed_sites(tests, self.scope, doc_sites)
        for site in sorted(doc_sites - armed):
            line = 0
            for i, ln in enumerate(faults_sf.lines, start=1):
                if f"``{site}``" in ln:
                    line = i
                    break
            yield Finding(
                self.code, faults_sf.relpath, line,
                f"fault site \"{site}\" is armed by no test under "
                f"{self.tests_dir}/ — add a drill (arm(\"{site}\", "
                "...) or arm_corrupt) so the failure branch it guards "
                "is actually executed",
                symbol="<module docstring>", detail=site,
                checker=self.name)
