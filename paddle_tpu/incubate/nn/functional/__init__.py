"""≙ paddle.incubate.nn.functional fused ops [U] — aliases over the
Pallas kernel library (paddle_tpu.ops) plus compositions XLA fuses."""
from ....ops.flash_attention import flash_attention  # noqa: F401
from ....ops.flash_varlen import flash_attention_varlen  # noqa: F401
from ....ops.paged_attention import paged_attention  # noqa: F401
from ....ops.rope import fused_rotary_position_embedding  # noqa: F401
from ....ops.norm_kernels import rms_norm as fused_rms_norm  # noqa: F401
from ....ops.norm_kernels import layer_norm as fused_layer_norm  # noqa: F401


def fused_bias_dropout_residual_layer_norm(
        x, residual, bias=None, ln_scale=None, ln_bias=None,
        dropout_rate=0.5, ln_epsilon=1e-5, training=True,
        mode="upscale_in_train", name=None):
    """≙ paddle.incubate.nn.functional.fused_bias_dropout_residual_layer_norm
    [U]: LayerNorm(residual + dropout(x + bias)). The reference fuses this
    as one CUDA kernel; under XLA the composition fuses into the
    surrounding matmuls, and the LayerNorm core is the Pallas kernel via
    nn.functional.layer_norm.
    """
    from ....nn import functional as F
    if bias is not None:
        x = x + bias
    if dropout_rate:
        # F.dropout owns the training/inference behavior per `mode`
        # (downscale_in_infer scales by (1-p) at inference)
        x = F.dropout(x, p=dropout_rate, training=training, mode=mode)
    y = residual + x
    return F.layer_norm(y, y.shape[-1:], weight=ln_scale, bias=ln_bias,
                        epsilon=ln_epsilon)


def fused_multi_head_attention(x, qkv_weight, linear_weight,
                               pre_layer_norm=False, pre_ln_scale=None,
                               pre_ln_bias=None, ln_scale=None,
                               ln_bias=None, pre_ln_epsilon=1e-5,
                               qkv_bias=None, linear_bias=None,
                               cache_kv=None, attn_mask=None,
                               dropout_rate=0.5, attn_dropout_rate=0.5,
                               ln_epsilon=1e-5, training=True,
                               mode="upscale_in_train", ring_id=-1,
                               add_residual=True, num_heads=-1,
                               transpose_qkv_wb=False, name=None):
    """≙ paddle.incubate.nn.functional.fused_multi_head_attention [U]:
    (pre-)LN -> fused QKV projection -> attention -> out projection ->
    dropout -> residual -> (post-)LN, in one call. On TPU the fusion is
    XLA's job — this composes the same ops so the compiler fuses them;
    the attention core routes through scaled_dot_product_attention
    (Pallas flash kernel when shapes allow).

    qkv_weight: (3, num_heads, head_dim, embed_dim) paddle layout, or
    (embed_dim, 3 * embed_dim) with transpose_qkv_wb=True.
    """
    import paddle_tpu as paddle
    from .... import nn
    from ....nn import functional as F

    if cache_kv is not None:
        raise NotImplementedError(
            "fused_multi_head_attention cache_kv: use the model-level KV "
            "cache (LlamaAttention past_key_value) for decoding")
    residual = x
    if pre_layer_norm:
        x = F.layer_norm(x, x.shape[-1:], weight=pre_ln_scale,
                         bias=pre_ln_bias, epsilon=pre_ln_epsilon)
    b, s = x.shape[0], x.shape[1]
    e = x.shape[-1]
    if transpose_qkv_wb:
        if num_heads <= 0:
            raise ValueError("num_heads required with transpose_qkv_wb")
        h, hd = num_heads, e // num_heads
        qkv = paddle.matmul(x, qkv_weight)          # (B, S, 3E)
        if qkv_bias is not None:
            qkv = qkv + qkv_bias
        qkv = qkv.reshape([b, s, 3, h, hd])
    else:
        h, hd = qkv_weight.shape[1], qkv_weight.shape[2]
        w = qkv_weight.reshape([3 * h * hd, e])
        qkv = paddle.matmul(x, w, transpose_y=True)  # (B, S, 3*H*hd)
        if qkv_bias is not None:
            qkv = qkv + qkv_bias.reshape([-1])
        qkv = qkv.reshape([b, s, 3, h, hd])
    q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
    out = F.scaled_dot_product_attention(
        q, k, v, attn_mask=attn_mask,
        dropout_p=attn_dropout_rate if training else 0.0,
        training=training)
    out = out.reshape([b, s, h * hd])
    out = paddle.matmul(out, linear_weight)
    if linear_bias is not None:
        out = out + linear_bias
    if dropout_rate and training:
        out = F.dropout(out, p=dropout_rate, training=training, mode=mode)
    if add_residual:
        out = residual + out
    if not pre_layer_norm:
        out = F.layer_norm(out, out.shape[-1:], weight=ln_scale,
                           bias=ln_bias, epsilon=ln_epsilon)
    return out


def swiglu(x, y=None, name=None):
    """≙ paddle.incubate.nn.functional.swiglu [U]: silu(x) * y, or with
    y=None split x in half along the last dim (fused-gate convention).
    XLA fuses this into the surrounding matmuls on TPU."""
    from ....nn import functional as F
    if y is None:
        half = x.shape[-1] // 2
        x, y = x[..., :half], x[..., half:]
    return F.silu(x) * y


def fused_linear(x, weight, bias=None, transpose_weight=False, name=None):
    """≙ paddle.incubate.nn.functional.fused_linear (cuBLASLt epilogue in
    the reference; one fused XLA dot+add here)."""
    import paddle_tpu as paddle
    out = paddle.matmul(x, weight, transpose_y=transpose_weight)
    return out + bias if bias is not None else out


def fused_linear_activation(x, y, bias, trans_x=False, trans_y=False,
                            activation="gelu", name=None):
    """≙ paddle.incubate.nn.functional.fused_linear_activation [U]."""
    import paddle_tpu as paddle
    from ....nn import functional as F
    out = paddle.matmul(x, y, transpose_x=trans_x, transpose_y=trans_y)
    out = out + bias
    if activation in ("gelu", "relu"):
        return getattr(F, activation)(out)
    if activation in (None, "none", ""):
        return out
    raise ValueError(f"unsupported activation {activation}")
