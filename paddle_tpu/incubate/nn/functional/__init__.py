"""≙ paddle.incubate.nn.functional fused ops [U] — aliases over the
Pallas kernel library (paddle_tpu.ops)."""
from ....ops.flash_attention import flash_attention  # noqa: F401
from ....ops.rope import fused_rotary_position_embedding  # noqa: F401
from ....ops.norm_kernels import rms_norm as fused_rms_norm  # noqa: F401
from ....ops.norm_kernels import layer_norm as fused_layer_norm  # noqa: F401


def fused_multi_head_attention(x, qkv_weight, linear_weight, *args, **kw):
    raise NotImplementedError(
        "fused_multi_head_attention: compose q/k/v projections with "
        "paddle_tpu.nn.functional.scaled_dot_product_attention — XLA fuses "
        "the projections; the attention core is the Pallas flash kernel.")
