"""incubate.nn fused layer classes.

≙ reference «python/paddle/incubate/nn/layer/fused_transformer.py» [U]
(FusedMultiHeadAttention, FusedFeedForward, FusedTransformerEncoderLayer,
FusedBiasDropoutResidualLayerNorm, FusedLinear, FusedDropoutAdd;
SURVEY.md §2.2 incubate row). On TPU "fused" means: composed so XLA fuses
into the surrounding program — parameters laid out exactly like the
reference's fused kernels expect (single QKV weight, etc.)."""
from __future__ import annotations

import numpy as np

from ...nn.layer.layers import Layer
from ...nn import functional as F
from . import functional as IF


class FusedLinear(Layer):
    """≙ paddle.incubate.nn.FusedLinear (cuBLASLt fused epilogue in the
    reference; on TPU XLA fuses bias+activation into the matmul)."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 bias_attr=None, transpose_weight=False, name=None):
        super().__init__()
        from ...nn.initializer import XavierNormal, Constant
        shape = ((out_features, in_features) if transpose_weight
                 else (in_features, out_features))
        self.weight = self.create_parameter(
            shape, attr=weight_attr, default_initializer=XavierNormal())
        self.bias = (None if bias_attr is False else self.create_parameter(
            (out_features,), attr=bias_attr, is_bias=True,
            default_initializer=Constant(0.0)))
        self._transpose = transpose_weight

    def forward(self, x):
        import paddle_tpu as paddle
        w = self.weight
        y = paddle.matmul(x, w, transpose_y=self._transpose)
        if self.bias is not None:
            y = y + self.bias
        return y


class FusedDropoutAdd(Layer):
    """≙ paddle.incubate.nn.FusedDropoutAdd: dropout(x) + y in one
    fusion."""

    def __init__(self, p=0.5, mode="upscale_in_train", name=None):
        super().__init__()
        self._p, self._mode = p, mode

    def forward(self, x, y):
        return F.dropout(x, p=self._p, training=self.training,
                         mode=self._mode) + y


class FusedBiasDropoutResidualLayerNorm(Layer):
    """≙ paddle.incubate.nn.FusedBiasDropoutResidualLayerNorm."""

    def __init__(self, embed_dim, dropout_rate=0.5, weight_attr=None,
                 bias_attr=None, epsilon=1e-5, name=None):
        super().__init__()
        from ...nn.initializer import Constant
        self.linear_bias = self.create_parameter(
            (embed_dim,), attr=bias_attr, is_bias=True,
            default_initializer=Constant(0.0))
        self.ln_scale = self.create_parameter(
            (embed_dim,), attr=weight_attr,
            default_initializer=Constant(1.0))
        self.ln_bias = self.create_parameter(
            (embed_dim,), is_bias=True, default_initializer=Constant(0.0))
        self._dropout_rate = dropout_rate
        self._epsilon = epsilon

    def forward(self, x, residual):
        return IF.fused_bias_dropout_residual_layer_norm(
            x, residual, bias=self.linear_bias, ln_scale=self.ln_scale,
            ln_bias=self.ln_bias, dropout_rate=self._dropout_rate,
            ln_epsilon=self._epsilon, training=self.training)


class FusedMultiHeadAttention(Layer):
    """≙ paddle.incubate.nn.FusedMultiHeadAttention — parameters stored in
    the reference's fused QKV layout (3, H, D, E)."""

    def __init__(self, embed_dim, num_heads, dropout_rate=0.5,
                 attn_dropout_rate=0.5, kdim=None, vdim=None,
                 normalize_before=False, need_weights=False,
                 qkv_weight_attr=None, qkv_bias_attr=None,
                 linear_weight_attr=None, linear_bias_attr=None,
                 pre_ln_scale_attr=None, pre_ln_bias_attr=None,
                 ln_scale_attr=None, ln_bias_attr=None, epsilon=1e-5,
                 nranks=1, ring_id=-1, name=None):
        super().__init__()
        assert embed_dim % num_heads == 0
        from ...nn.initializer import XavierNormal, Constant
        hd = embed_dim // num_heads
        self.num_heads = num_heads
        self.normalize_before = normalize_before
        self.qkv_weight = self.create_parameter(
            (3, num_heads, hd, embed_dim), attr=qkv_weight_attr,
            default_initializer=XavierNormal())
        self.qkv_bias = self.create_parameter(
            (3, num_heads, hd), attr=qkv_bias_attr, is_bias=True,
            default_initializer=Constant(0.0))
        self.linear_weight = self.create_parameter(
            (embed_dim, embed_dim), attr=linear_weight_attr,
            default_initializer=XavierNormal())
        self.linear_bias = self.create_parameter(
            (embed_dim,), attr=linear_bias_attr, is_bias=True,
            default_initializer=Constant(0.0))
        self.pre_ln_scale = self.create_parameter(
            (embed_dim,), attr=pre_ln_scale_attr,
            default_initializer=Constant(1.0))
        self.pre_ln_bias = self.create_parameter(
            (embed_dim,), attr=pre_ln_bias_attr, is_bias=True,
            default_initializer=Constant(0.0))
        self.ln_scale = self.create_parameter(
            (embed_dim,), attr=ln_scale_attr,
            default_initializer=Constant(1.0))
        self.ln_bias = self.create_parameter(
            (embed_dim,), attr=ln_bias_attr, is_bias=True,
            default_initializer=Constant(0.0))
        self._dropout_rate = dropout_rate
        self._attn_dropout_rate = attn_dropout_rate
        self._epsilon = epsilon

    def forward(self, query, key=None, value=None, attn_mask=None,
                cache=None):
        return IF.fused_multi_head_attention(
            query, self.qkv_weight, self.linear_weight,
            pre_layer_norm=self.normalize_before,
            pre_ln_scale=self.pre_ln_scale, pre_ln_bias=self.pre_ln_bias,
            ln_scale=self.ln_scale, ln_bias=self.ln_bias,
            pre_ln_epsilon=self._epsilon, qkv_bias=self.qkv_bias,
            linear_bias=self.linear_bias, cache_kv=cache,
            attn_mask=attn_mask, dropout_rate=self._dropout_rate,
            attn_dropout_rate=self._attn_dropout_rate,
            ln_epsilon=self._epsilon, training=self.training,
            num_heads=self.num_heads)


class FusedFeedForward(Layer):
    """≙ paddle.incubate.nn.FusedFeedForward."""

    def __init__(self, d_model, dim_feedforward, dropout_rate=0.1,
                 epsilon=1e-5, activation="relu", act_dropout_rate=None,
                 normalize_before=False, linear1_weight_attr=None,
                 linear1_bias_attr=None, linear2_weight_attr=None,
                 linear2_bias_attr=None, ln1_scale_attr=None,
                 ln1_bias_attr=None, ln2_scale_attr=None,
                 ln2_bias_attr=None, nranks=1, ring_id=-1, name=None):
        super().__init__()
        from ...nn.initializer import XavierNormal, Constant
        self.linear1_weight = self.create_parameter(
            (d_model, dim_feedforward), attr=linear1_weight_attr,
            default_initializer=XavierNormal())
        self.linear1_bias = self.create_parameter(
            (dim_feedforward,), attr=linear1_bias_attr, is_bias=True,
            default_initializer=Constant(0.0))
        self.linear2_weight = self.create_parameter(
            (dim_feedforward, d_model), attr=linear2_weight_attr,
            default_initializer=XavierNormal())
        self.linear2_bias = self.create_parameter(
            (d_model,), attr=linear2_bias_attr, is_bias=True,
            default_initializer=Constant(0.0))
        self.ln_scale = self.create_parameter(
            (d_model,), attr=ln1_scale_attr,
            default_initializer=Constant(1.0))
        self.ln_bias = self.create_parameter(
            (d_model,), is_bias=True, default_initializer=Constant(0.0))
        self._dropout_rate = dropout_rate
        self._act_dropout = (dropout_rate if act_dropout_rate is None
                             else act_dropout_rate)
        self._act = activation
        self._epsilon = epsilon
        self.normalize_before = normalize_before

    def forward(self, src):
        import paddle_tpu as paddle
        residual = src
        if self.normalize_before:
            src = F.layer_norm(src, src.shape[-1], self.ln_scale,
                               self.ln_bias, self._epsilon)
        h = paddle.matmul(src, self.linear1_weight) + self.linear1_bias
        h = getattr(F, self._act)(h)
        h = F.dropout(h, p=self._act_dropout, training=self.training)
        h = paddle.matmul(h, self.linear2_weight) + self.linear2_bias
        h = F.dropout(h, p=self._dropout_rate, training=self.training)
        out = residual + h
        if not self.normalize_before:
            out = F.layer_norm(out, out.shape[-1], self.ln_scale,
                               self.ln_bias, self._epsilon)
        return out


class FusedTransformerEncoderLayer(Layer):
    """≙ paddle.incubate.nn.FusedTransformerEncoderLayer = fused MHA +
    fused FFN."""

    def __init__(self, d_model, nhead, dim_feedforward, dropout_rate=0.1,
                 activation="relu", attn_dropout_rate=None,
                 act_dropout_rate=None, normalize_before=False):
        super().__init__()
        ad = dropout_rate if attn_dropout_rate is None else \
            attn_dropout_rate
        self.fused_attn = FusedMultiHeadAttention(
            d_model, nhead, dropout_rate=dropout_rate,
            attn_dropout_rate=ad, normalize_before=normalize_before)
        self.ffn = FusedFeedForward(
            d_model, dim_feedforward, dropout_rate=dropout_rate,
            activation=activation, act_dropout_rate=act_dropout_rate,
            normalize_before=normalize_before)

    def forward(self, src, src_mask=None, cache=None):
        return self.ffn(self.fused_attn(src, attn_mask=src_mask,
                                        cache=cache))


class FusedRMSNorm(Layer):
    """TPU-native extra (paddle.incubate.nn.FusedRMSNorm-alike) wrapping
    the Pallas rms_norm kernel."""

    def __init__(self, hidden_size, epsilon=1e-6):
        super().__init__()
        from ...nn.initializer import Constant
        self.weight = self.create_parameter(
            (hidden_size,), default_initializer=Constant(1.0))
        self._epsilon = epsilon

    def forward(self, x):
        return IF.fused_rms_norm(x, self.weight, epsilon=self._epsilon)
