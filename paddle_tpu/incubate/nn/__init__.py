"""paddle_tpu.incubate.nn — fused-op python APIs.
≙ reference «python/paddle/incubate/nn/functional/» fused ops [U]. The
fused kernels live in paddle_tpu.ops; these are the incubate-namespace
aliases the reference exposes."""
from . import functional  # noqa: F401
from .layer import (FusedLinear, FusedDropoutAdd,  # noqa: F401
                    FusedBiasDropoutResidualLayerNorm,
                    FusedMultiHeadAttention, FusedFeedForward,
                    FusedTransformerEncoderLayer, FusedRMSNorm)
