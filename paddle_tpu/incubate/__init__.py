"""paddle_tpu.incubate — experimental APIs.
≙ reference «python/paddle/incubate/» (fused-op python APIs, MoE layers,
experimental dist features — SURVEY.md §2.2)."""
from . import autograd  # noqa: F401
from . import moe  # noqa: F401
from . import nn  # noqa: F401
