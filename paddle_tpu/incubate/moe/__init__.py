"""Mixture-of-Experts with expert parallelism — TPU-native.

≙ reference «python/paddle/incubate/distributed/models/moe/» (MoELayer,
GShard/Switch gates) + the `global_scatter`/`global_gather` alltoall
dispatch ops («paddle/fluid/operators/collective/global_scatter_op*» [U?],
SURVEY.md §2.3 EP row).

TPU-native design: dispatch/combine are dense one-hot einsums (GShard
style, MXU-friendly, static shapes — no ragged recompilations); experts
are ONE stacked parameter (E, ...) sharded over the `ep` mesh axis, and
the alltoall the reference hand-codes is inserted by XLA from the
sharding of the dispatched (E, C, d) tensor. Capacity-based top-k routing
with the standard load-balancing auxiliary loss.
"""
from __future__ import annotations

import math
from typing import Optional

import numpy as np
import jax
import jax.numpy as jnp

from ...core.tensor import Tensor, apply
from ...nn import initializer as I
from ...nn.layer.layers import Layer

__all__ = ["moe_gating_values", "moe_ffn_values", "MoELayer", "shard_moe"]


def moe_gating_values(logits, top_k: int, capacity: int):
    """GShard-style top-k capacity gating (all static shapes).

    logits: (T, E) router scores.
    Returns (dispatch (T, E, C) float {0,1}, combine (T, E, C) float,
    aux_loss scalar). Priority is choice-major: every token's 1st choice
    is placed before any 2nd choice, matching the reference gate.
    """
    t, e = logits.shape
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)   # (T, E)
    gate_vals, gate_idx = jax.lax.top_k(probs, top_k)             # (T, K)

    # one-hot per choice: (K, T, E), then position of each (choice, token)
    # inside its expert's queue by cumulative count in priority order
    oh = jax.nn.one_hot(gate_idx.T, e, dtype=jnp.float32)         # (K, T, E)
    flat = oh.reshape(top_k * t, e)
    pos = jnp.cumsum(flat, axis=0) - flat                         # (K*T, E)
    pos = jnp.sum(pos * flat, axis=-1).astype(jnp.int32)          # (K*T,)
    keep = (pos < capacity) & (jnp.sum(flat, -1) > 0)
    pos_oh = jax.nn.one_hot(pos, capacity, dtype=jnp.float32) \
        * keep[:, None]                                           # (K*T, C)
    # (K, T, E, C): expert one-hot x capacity one-hot
    disp = (flat.reshape(top_k, t, e)[..., None]
            * pos_oh.reshape(top_k, t, 1, capacity))
    dispatch = jnp.sum(disp, axis=0)                              # (T, E, C)
    combine = jnp.sum(disp * gate_vals.T[..., None, None], axis=0)

    # load-balance aux (Switch/GShard): E * sum_e f_e * p_e, over 1st choice
    f = jnp.mean(oh[0], axis=0)            # fraction routed to e (choice 0)
    p = jnp.mean(probs, axis=0)            # mean router prob
    aux = e * jnp.sum(f * p)
    return dispatch, combine, aux


def moe_ffn_values(x2, gate_w, w_gate, w_up, w_down, top_k: int,
                   capacity_factor: float, ep_axis: Optional[str] = None,
                   mesh=None):
    """Dense-dispatch MoE SwiGLU FFN. x2: (T, H); gate_w: (H, E);
    stacked experts w_gate/w_up: (E, H, I), w_down: (E, I, H)."""
    t, h = x2.shape
    e = gate_w.shape[1]
    capacity = max(int(math.ceil(top_k * t / e * capacity_factor)), 1)
    logits = x2.astype(jnp.float32) @ gate_w.astype(jnp.float32)
    dispatch, combine, aux = moe_gating_values(logits, top_k, capacity)

    xe = jnp.einsum("tec,th->ech", dispatch.astype(x2.dtype), x2)  # (E,C,H)
    if ep_axis is not None and mesh is not None and \
            ep_axis in mesh.dim_names:
        from ...distributed.mesh import shard_constraint
        xe = shard_constraint(xe, ep_axis, None, None, mesh=mesh)
    hgate = jnp.einsum("ech,ehi->eci", xe, w_gate.astype(xe.dtype))
    hup = jnp.einsum("ech,ehi->eci", xe, w_up.astype(xe.dtype))
    ho = jax.nn.silu(hgate.astype(jnp.float32)).astype(xe.dtype) * hup
    oe = jnp.einsum("eci,eih->ech", ho, w_down.astype(xe.dtype))  # (E,C,H)
    if ep_axis is not None and mesh is not None and \
            ep_axis in mesh.dim_names:
        from ...distributed.mesh import shard_constraint
        oe = shard_constraint(oe, ep_axis, None, None, mesh=mesh)
    out = jnp.einsum("tec,ech->th", combine.astype(oe.dtype), oe)
    return out.astype(x2.dtype), aux


class MoELayer(Layer):
    """Sparse SwiGLU MoE block (+ optional dense shared experts).
    ≙ paddle.incubate MoELayer / Qwen2-MoE & DeepSeekMoE sparse MLP [U?].

    forward(x) -> (out, aux_loss); x: (..., H).
    """

    def __init__(self, hidden_size: int, intermediate_size: int,
                 num_experts: int, top_k: int = 2,
                 capacity_factor: float = 1.25,
                 shared_intermediate_size: int = 0,
                 ep_axis: str = "ep", name=None):
        super().__init__()
        self.hidden_size = hidden_size
        self.intermediate_size = intermediate_size
        self.num_experts = num_experts
        self.top_k = top_k
        self.capacity_factor = capacity_factor
        self.ep_axis = ep_axis
        e, h, i = num_experts, hidden_size, intermediate_size
        self.gate_weight = self.create_parameter(
            (h, e), default_initializer=I.Normal(0.0, 0.02))
        self.w_gate = self.create_parameter(
            (e, h, i), default_initializer=I.XavierNormal(fan_in=h,
                                                          fan_out=i))
        self.w_up = self.create_parameter(
            (e, h, i), default_initializer=I.XavierNormal(fan_in=h,
                                                          fan_out=i))
        self.w_down = self.create_parameter(
            (e, i, h), default_initializer=I.XavierNormal(fan_in=i,
                                                          fan_out=h))
        if shared_intermediate_size:
            from ...nn import Linear
            self.shared_gate = Linear(h, shared_intermediate_size,
                                      bias_attr=False)
            self.shared_up = Linear(h, shared_intermediate_size,
                                    bias_attr=False)
            self.shared_down = Linear(shared_intermediate_size, h,
                                      bias_attr=False)
        else:
            self.shared_gate = None

    def forward(self, x):
        from ...distributed.mesh import get_mesh
        shape = x.shape
        h = shape[-1]
        mesh = get_mesh()
        top_k, cf, ep = self.top_k, self.capacity_factor, self.ep_axis

        def fn(xv, gw, wg, wu, wd):
            x2 = xv.reshape(-1, h)
            out, aux = moe_ffn_values(x2, gw, wg, wu, wd, top_k, cf,
                                      ep, mesh)
            return out.reshape(xv.shape), aux

        out, aux = apply("moe_ffn", fn,
                         (x, self.gate_weight, self.w_gate, self.w_up,
                          self.w_down), multi_output=True)
        if self.shared_gate is not None:
            from ...nn import functional as F
            out = out + self.shared_down(
                F.silu(self.shared_gate(x)) * self.shared_up(x))
        return out, aux


def shard_moe(layer, mesh, ep_axis: str = "ep"):
    """Place stacked expert params Shard(0) over the `ep` axis (the
    reference's expert-parallel group); gate + shared experts replicate."""
    from ...distributed.mesh import Replicate, Shard, shard_tensor
    if ep_axis not in mesh.dim_names:
        return layer
    for sub in layer.sublayers(include_self=True):
        if isinstance(sub, MoELayer):
            for pname in ("w_gate", "w_up", "w_down"):
                p = getattr(sub, pname)
                if p._value.shape[0] % mesh.get_dim_size(ep_axis):
                    continue
                placements = [Replicate() for _ in mesh.dim_names]
                placements[mesh.dim_names.index(ep_axis)] = Shard(0)
                s = shard_tensor(p, mesh, placements)
                p._value = s._value
                p.dist_attr = s.dist_attr
    return layer
