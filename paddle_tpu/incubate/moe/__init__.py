"""Mixture-of-Experts with expert parallelism — TPU-native.

≙ reference «python/paddle/incubate/distributed/models/moe/» (MoELayer,
GShard/Switch gates) + the `global_scatter`/`global_gather` alltoall
dispatch ops («paddle/fluid/operators/collective/global_scatter_op*» [U?],
SURVEY.md §2.3 EP row).

TPU-native design — two dispatch strategies behind one MoELayer API:

* capacity (dense) path: dispatch/combine are one-hot einsums (GShard
  style, static shapes); experts are ONE stacked parameter (E, ...)
  sharded over the `ep` mesh axis, and the alltoall the reference
  hand-codes is inserted by XLA from the sharding of the dispatched
  (E, C, d) tensor. O(T·E·C) dispatch memory — fine at small E, used
  for expert-parallel execution.
* dropless (ragged, megablox-style) path: tokens sort by expert
  (O(T·k) memory, no token dropping, no capacity hyperparameter) and
  the expert FFN runs as grouped matmuls — the Pallas kernel in
  ops/grouped_matmul.py on TPU (block-padded groups), ragged_dot
  elsewhere. This is the DeepSeekMoE-scale path (E=64+), where the
  dense (T, E, C) tensors are catastrophic.

Both use the standard load-balancing auxiliary loss.
"""
from __future__ import annotations

import math
from typing import Optional

import numpy as np
import jax
import jax.numpy as jnp

from ...core.tensor import Tensor, apply
from ...nn import initializer as I
from ...nn.layer.layers import Layer

__all__ = ["moe_gating_values", "moe_ffn_values",
           "moe_ffn_dropless_values", "MoELayer", "shard_moe"]


def moe_gating_values(logits, top_k: int, capacity: int):
    """GShard-style top-k capacity gating (all static shapes).

    logits: (T, E) router scores.
    Returns (dispatch (T, E, C) float {0,1}, combine (T, E, C) float,
    aux_loss scalar). Priority is choice-major: every token's 1st choice
    is placed before any 2nd choice, matching the reference gate.
    """
    t, e = logits.shape
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)   # (T, E)
    gate_vals, gate_idx = jax.lax.top_k(probs, top_k)             # (T, K)

    # one-hot per choice: (K, T, E), then position of each (choice, token)
    # inside its expert's queue by cumulative count in priority order
    oh = jax.nn.one_hot(gate_idx.T, e, dtype=jnp.float32)         # (K, T, E)
    flat = oh.reshape(top_k * t, e)
    pos = jnp.cumsum(flat, axis=0) - flat                         # (K*T, E)
    pos = jnp.sum(pos * flat, axis=-1).astype(jnp.int32)          # (K*T,)
    keep = (pos < capacity) & (jnp.sum(flat, -1) > 0)
    pos_oh = jax.nn.one_hot(pos, capacity, dtype=jnp.float32) \
        * keep[:, None]                                           # (K*T, C)
    # (K, T, E, C): expert one-hot x capacity one-hot
    disp = (flat.reshape(top_k, t, e)[..., None]
            * pos_oh.reshape(top_k, t, 1, capacity))
    dispatch = jnp.sum(disp, axis=0)                              # (T, E, C)
    combine = jnp.sum(disp * gate_vals.T[..., None, None], axis=0)

    return dispatch, combine, _aux_loss(probs, gate_idx)


def moe_ffn_values(x2, gate_w, w_gate, w_up, w_down, top_k: int,
                   capacity_factor: float, ep_axis: Optional[str] = None,
                   mesh=None):
    """Dense-dispatch MoE SwiGLU FFN. x2: (T, H); gate_w: (H, E);
    stacked experts w_gate/w_up: (E, H, I), w_down: (E, I, H)."""
    t, h = x2.shape
    e = gate_w.shape[1]
    capacity = max(int(math.ceil(top_k * t / e * capacity_factor)), 1)
    logits = x2.astype(jnp.float32) @ gate_w.astype(jnp.float32)
    dispatch, combine, aux = moe_gating_values(logits, top_k, capacity)

    xe = jnp.einsum("tec,th->ech", dispatch.astype(x2.dtype), x2)  # (E,C,H)
    if ep_axis is not None and mesh is not None and \
            ep_axis in mesh.dim_names:
        from ...distributed.mesh import shard_constraint
        xe = shard_constraint(xe, ep_axis, None, None, mesh=mesh)
    hgate = jnp.einsum("ech,ehi->eci", xe, w_gate.astype(xe.dtype))
    hup = jnp.einsum("ech,ehi->eci", xe, w_up.astype(xe.dtype))
    ho = jax.nn.silu(hgate.astype(jnp.float32)).astype(xe.dtype) * hup
    oe = jnp.einsum("eci,eih->ech", ho, w_down.astype(xe.dtype))  # (E,C,H)
    if ep_axis is not None and mesh is not None and \
            ep_axis in mesh.dim_names:
        from ...distributed.mesh import shard_constraint
        oe = shard_constraint(oe, ep_axis, None, None, mesh=mesh)
    out = jnp.einsum("tec,ech->th", combine.astype(oe.dtype), oe)
    return out.astype(x2.dtype), aux


def _aux_loss(probs, gate_idx):
    """Switch/GShard load-balance loss: E * sum_e f_e * p_e over choice 0."""
    e = probs.shape[-1]
    f = jnp.mean(jax.nn.one_hot(gate_idx[:, 0], e, dtype=jnp.float32),
                 axis=0)
    p = jnp.mean(probs, axis=0)
    return e * jnp.sum(f * p)


def moe_ffn_dropless_values(x2, gate_w, w_gate, w_up, w_down, top_k: int):
    """Dropless sort-based MoE SwiGLU FFN (megablox-style).

    x2: (T, H); gate_w: (H, E); w_gate/w_up: (E, H, I); w_down: (E, I, H).
    Dispatch memory is O(T·k·H): tokens are gathered into expert-sorted
    order and the expert matmuls run grouped. No capacity, no drops.
    On TPU, rows are additionally laid out with each expert's group padded
    to a block_m boundary so the Pallas grouped-matmul kernel applies
    (bounded O(E·block_m·H) padding cost).
    """
    from ...ops import on_tpu
    from ...ops.grouped_matmul import (DEFAULT_BLOCK, _HAS_PLTPU,
                                       grouped_matmul_values)
    t, h = x2.shape
    e = gate_w.shape[1]
    i_size = w_gate.shape[2]
    tk = t * top_k

    logits = x2.astype(jnp.float32) @ gate_w.astype(jnp.float32)  # (T, E)
    probs = jax.nn.softmax(logits, axis=-1)
    gate_vals, gate_idx = jax.lax.top_k(probs, top_k)             # (T, K)

    flat = gate_idx.reshape(-1)                   # slot f=t*K+k -> expert
    order = jnp.argsort(flat, stable=True)        # (T*K,) expert-sorted
    tok = order // top_k                          # source token per row
    counts = jnp.bincount(flat, length=e)         # (E,)

    block_m = DEFAULT_BLOCK
    block_aligned = (on_tpu() and _HAS_PLTPU and h % block_m == 0
                     and i_size % block_m == 0)
    if block_aligned:
        # pad each expert's group to a block_m multiple so no m-tile of
        # the Pallas kernel straddles a group boundary
        es = flat[order]                                       # (T*K,)
        co = jnp.concatenate([jnp.zeros(1, counts.dtype),
                              jnp.cumsum(counts)[:-1]])        # excl. offs
        padded = ((counts + block_m - 1) // block_m) * block_m
        po = jnp.concatenate([jnp.zeros(1, padded.dtype),
                              jnp.cumsum(padded)[:-1]])
        rank = jnp.arange(tk) - co[es]
        pos = po[es] + rank                                    # padded row
        m_pad = ((tk + e * block_m) // block_m + 1) * block_m  # static
        xs = jnp.zeros((m_pad, h), x2.dtype).at[pos].set(x2[tok])
        gs = padded
    else:
        pos = None
        xs = x2[tok]                                           # (T*K, H)
        gs = counts

    hg = grouped_matmul_values(xs, w_gate.astype(xs.dtype), gs,
                               block_aligned)
    hu = grouped_matmul_values(xs, w_up.astype(xs.dtype), gs,
                               block_aligned)
    act = jax.nn.silu(hg.astype(jnp.float32)).astype(xs.dtype) * hu
    rows = grouped_matmul_values(act, w_down.astype(xs.dtype), gs,
                                 block_aligned)                # (M, H)
    if pos is not None:
        rows = rows[pos]                                       # (T*K, H)

    wv = gate_vals.reshape(-1)[order].astype(jnp.float32)
    out = jnp.zeros((t, h), jnp.float32).at[tok].add(
        rows.astype(jnp.float32) * wv[:, None])
    return out.astype(x2.dtype), _aux_loss(probs, gate_idx)


class MoELayer(Layer):
    """Sparse SwiGLU MoE block (+ optional dense shared experts).
    ≙ paddle.incubate MoELayer / Qwen2-MoE & DeepSeekMoE sparse MLP [U?].

    forward(x) -> (out, aux_loss); x: (..., H).
    """

    def __init__(self, hidden_size: int, intermediate_size: int,
                 num_experts: int, top_k: int = 2,
                 capacity_factor: float = 1.25,
                 shared_intermediate_size: int = 0,
                 ep_axis: str = "ep", dropless: bool = False, name=None):
        super().__init__()
        self.hidden_size = hidden_size
        self.intermediate_size = intermediate_size
        self.num_experts = num_experts
        self.top_k = top_k
        self.capacity_factor = capacity_factor
        self.ep_axis = ep_axis
        self.dropless = dropless
        e, h, i = num_experts, hidden_size, intermediate_size
        self.gate_weight = self.create_parameter(
            (h, e), default_initializer=I.Normal(0.0, 0.02))
        self.w_gate = self.create_parameter(
            (e, h, i), default_initializer=I.XavierNormal(fan_in=h,
                                                          fan_out=i))
        self.w_up = self.create_parameter(
            (e, h, i), default_initializer=I.XavierNormal(fan_in=h,
                                                          fan_out=i))
        self.w_down = self.create_parameter(
            (e, i, h), default_initializer=I.XavierNormal(fan_in=i,
                                                          fan_out=h))
        if shared_intermediate_size:
            from ...nn import Linear
            self.shared_gate = Linear(h, shared_intermediate_size,
                                      bias_attr=False)
            self.shared_up = Linear(h, shared_intermediate_size,
                                    bias_attr=False)
            self.shared_down = Linear(shared_intermediate_size, h,
                                      bias_attr=False)
        else:
            self.shared_gate = None

    def forward(self, x):
        from ...distributed.mesh import get_mesh
        shape = x.shape
        h = shape[-1]
        mesh = get_mesh()
        top_k, cf, ep = self.top_k, self.capacity_factor, self.ep_axis
        # the dropless (sorted/ragged) layout does not compose with the
        # ep-sharded alltoall dispatch — expert parallelism keeps the
        # static-shape capacity path (reference EP also runs capacity)
        ep_active = (mesh is not None and ep in mesh.dim_names
                     and mesh.get_dim_size(ep) > 1)
        use_dropless = self.dropless and not ep_active

        def fn(xv, gw, wg, wu, wd):
            x2 = xv.reshape(-1, h)
            if use_dropless:
                out, aux = moe_ffn_dropless_values(x2, gw, wg, wu, wd,
                                                   top_k)
            else:
                out, aux = moe_ffn_values(x2, gw, wg, wu, wd, top_k, cf,
                                          ep, mesh)
            return out.reshape(xv.shape), aux

        out, aux = apply("moe_ffn", fn,
                         (x, self.gate_weight, self.w_gate, self.w_up,
                          self.w_down), multi_output=True)
        if self.shared_gate is not None:
            from ...nn import functional as F
            out = out + self.shared_down(
                F.silu(self.shared_gate(x)) * self.shared_up(x))
        return out, aux


def shard_moe(layer, mesh, ep_axis: str = "ep"):
    """Place stacked expert params Shard(0) over the `ep` axis (the
    reference's expert-parallel group); gate + shared experts replicate."""
    from ...distributed.mesh import Replicate, Shard, shard_tensor
    if ep_axis not in mesh.dim_names:
        return layer
    for sub in layer.sublayers(include_self=True):
        if isinstance(sub, MoELayer):
            for pname in ("w_gate", "w_up", "w_down"):
                p = getattr(sub, pname)
                if p._value.shape[0] % mesh.get_dim_size(ep_axis):
                    continue
                placements = [Replicate() for _ in mesh.dim_names]
                placements[mesh.dim_names.index(ep_axis)] = Shard(0)
                s = shard_tensor(p, mesh, placements)
                p._value = s._value
                p.dist_attr = s.dist_attr
    return layer
