"""Mixture-of-Experts with expert parallelism — TPU-native.

≙ reference «python/paddle/incubate/distributed/models/moe/» (MoELayer,
GShard/Switch gates) + the `global_scatter`/`global_gather` alltoall
dispatch ops («paddle/fluid/operators/collective/global_scatter_op*» [U?],
SURVEY.md §2.3 EP row).

TPU-native design — two dispatch strategies behind one MoELayer API:

* capacity (dense) path: dispatch/combine are one-hot einsums (GShard
  style, static shapes); experts are ONE stacked parameter (E, ...)
  sharded over the `ep` mesh axis, and the alltoall the reference
  hand-codes is inserted by XLA from the sharding of the dispatched
  (E, C, d) tensor. O(T·E·C) dispatch memory — fine at small E, used
  for expert-parallel execution.
* dropless (ragged, megablox-style) path: tokens sort by expert
  (O(T·k) memory, no capacity hyperparameter) and the expert FFN runs
  as grouped matmuls — the Pallas kernel in ops/grouped_matmul.py on
  TPU (block-padded groups), ragged_dot elsewhere. This is the
  DeepSeekMoE-scale path (E=64+), where the dense (T, E, C) tensors
  are catastrophic. Under expert parallelism, TWO dispatch modes:

  - exact mode (default, `ep_pair_capacity_factor=None`): ZERO drops
    under any routing skew. On TPU this is a TWO-PHASE exchange —
    per-pair counts are all-gathered, then `lax.ragged_all_to_all`
    moves ONLY the real rows, so just the ragged payload rides the ICI
    (the TPU-native equivalent of the reference's
    `global_scatter`/`global_gather` exactness); the receive buffer is
    still sized to the static ep·T_local·k worst case, the price of
    exactness under XLA's static shapes. On backends where XLA has no
    `ragged-all-to-all` (CPU — the 8-virtual-device test mesh), the
    same exactness is kept by a dense `lax.all_to_all` of worst-case
    per-pair buffers (ep× the bandwidth of the actual load); the two
    paths are numerically identical.
  - capacity mode (`ep_pair_capacity_factor=f`): static per-pair
    budget buffers (cheapest memory, bounded bandwidth); tokens beyond
    a pair's budget are DROPPED, and the layer surfaces a hard
    per-step drop counter (`MoELayer.last_drop_count`) so silent
    degradation is impossible.

Both use the standard load-balancing auxiliary loss.
"""
from __future__ import annotations

import math
from typing import Optional

import numpy as np
import jax
import jax.numpy as jnp

from ...core.tensor import Tensor, apply
from ...nn import initializer as I
from ...nn.layer.layers import Layer

__all__ = ["moe_gating_values", "moe_ffn_values",
           "moe_ffn_dropless_values", "moe_ffn_dropless_ep_values",
           "MoELayer", "shard_moe"]


def moe_gating_values(logits, top_k: int, capacity: int):
    """GShard-style top-k capacity gating (all static shapes).

    logits: (T, E) router scores.
    Returns (dispatch (T, E, C) float {0,1}, combine (T, E, C) float,
    aux_loss scalar). Priority is choice-major: every token's 1st choice
    is placed before any 2nd choice, matching the reference gate.
    """
    t, e = logits.shape
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)   # (T, E)
    gate_vals, gate_idx = jax.lax.top_k(probs, top_k)             # (T, K)

    # one-hot per choice: (K, T, E), then position of each (choice, token)
    # inside its expert's queue by cumulative count in priority order
    oh = jax.nn.one_hot(gate_idx.T, e, dtype=jnp.float32)         # (K, T, E)
    flat = oh.reshape(top_k * t, e)
    pos = jnp.cumsum(flat, axis=0) - flat                         # (K*T, E)
    pos = jnp.sum(pos * flat, axis=-1).astype(jnp.int32)          # (K*T,)
    keep = (pos < capacity) & (jnp.sum(flat, -1) > 0)
    pos_oh = jax.nn.one_hot(pos, capacity, dtype=jnp.float32) \
        * keep[:, None]                                           # (K*T, C)
    # (K, T, E, C): expert one-hot x capacity one-hot
    disp = (flat.reshape(top_k, t, e)[..., None]
            * pos_oh.reshape(top_k, t, 1, capacity))
    dispatch = jnp.sum(disp, axis=0)                              # (T, E, C)
    combine = jnp.sum(disp * gate_vals.T[..., None, None], axis=0)

    return dispatch, combine, _aux_loss(probs, gate_idx)


def moe_ffn_values(x2, gate_w, w_gate, w_up, w_down, top_k: int,
                   capacity_factor: float, ep_axis: Optional[str] = None,
                   mesh=None):
    """Dense-dispatch MoE SwiGLU FFN. x2: (T, H); gate_w: (H, E);
    stacked experts w_gate/w_up: (E, H, I), w_down: (E, I, H).
    Returns (out, aux, drops) — drops = routed slots beyond expert
    capacity (int32 scalar)."""
    t, h = x2.shape
    e = gate_w.shape[1]
    capacity = max(int(math.ceil(top_k * t / e * capacity_factor)), 1)
    logits = x2.astype(jnp.float32) @ gate_w.astype(jnp.float32)
    dispatch, combine, aux = moe_gating_values(logits, top_k, capacity)

    # capacity drops: routed slots that found no queue position
    drops = (jnp.float32(t * top_k)
             - jnp.sum(dispatch)).astype(jnp.int32)

    xe = jnp.einsum("tec,th->ech", dispatch.astype(x2.dtype), x2)  # (E,C,H)
    if ep_axis is not None and mesh is not None and \
            ep_axis in mesh.dim_names:
        from ...distributed.mesh import shard_constraint
        xe = shard_constraint(xe, ep_axis, None, None, mesh=mesh)
    hgate = jnp.einsum("ech,ehi->eci", xe, w_gate.astype(xe.dtype))
    hup = jnp.einsum("ech,ehi->eci", xe, w_up.astype(xe.dtype))
    ho = jax.nn.silu(hgate.astype(jnp.float32)).astype(xe.dtype) * hup
    oe = jnp.einsum("eci,eih->ech", ho, w_down.astype(xe.dtype))  # (E,C,H)
    if ep_axis is not None and mesh is not None and \
            ep_axis in mesh.dim_names:
        from ...distributed.mesh import shard_constraint
        oe = shard_constraint(oe, ep_axis, None, None, mesh=mesh)
    out = jnp.einsum("tec,ech->th", combine.astype(oe.dtype), oe)
    return out.astype(x2.dtype), aux, drops


def _aux_loss(probs, gate_idx):
    """Switch/GShard load-balance loss: E * sum_e f_e * p_e over choice 0."""
    e = probs.shape[-1]
    f = jnp.mean(jax.nn.one_hot(gate_idx[:, 0], e, dtype=jnp.float32),
                 axis=0)
    p = jnp.mean(probs, axis=0)
    return e * jnp.sum(f * p)


def _expert_ffn_rows(xs_in, eid, w_gate, w_up, w_down, e: int):
    """Grouped SwiGLU FFN over rows with per-row expert ids.

    xs_in: (N, H); eid: (N,) int32 in [0, e) — rows that should not
    contribute must be ZERO rows (SwiGLU with no bias maps 0 -> 0).
    Returns (N, H) outputs in the caller's row order. Sorts by expert,
    runs the grouped matmul (Pallas kernel when block-aligned), unsorts.
    """
    from ...ops import on_tpu
    from ...ops.grouped_matmul import (DEFAULT_BLOCK, _HAS_PLTPU,
                                       grouped_matmul_values)
    n, h = xs_in.shape
    i_size = w_gate.shape[2]

    order = jnp.argsort(eid, stable=True)         # expert-sorted row index
    es = eid[order]                               # (N,) sorted expert ids
    counts = jnp.bincount(eid, length=e)          # (E,)

    block_m = DEFAULT_BLOCK
    block_aligned = (on_tpu() and _HAS_PLTPU and h % block_m == 0
                     and i_size % block_m == 0)
    if block_aligned:
        # pad each expert's group to a block_m multiple so no m-tile of
        # the Pallas kernel straddles a group boundary
        co = jnp.concatenate([jnp.zeros(1, counts.dtype),
                              jnp.cumsum(counts)[:-1]])        # excl. offs
        padded = ((counts + block_m - 1) // block_m) * block_m
        po = jnp.concatenate([jnp.zeros(1, padded.dtype),
                              jnp.cumsum(padded)[:-1]])
        rank = jnp.arange(n) - co[es]
        pos = po[es] + rank                                    # padded row
        m_pad = ((n + e * block_m) // block_m + 1) * block_m   # static
        xs = jnp.zeros((m_pad, h), xs_in.dtype).at[pos].set(xs_in[order])
        gs = padded
    else:
        pos = None
        xs = xs_in[order]
        gs = counts

    hg = grouped_matmul_values(xs, w_gate.astype(xs.dtype), gs,
                               block_aligned)
    hu = grouped_matmul_values(xs, w_up.astype(xs.dtype), gs,
                               block_aligned)
    act = jax.nn.silu(hg.astype(jnp.float32)).astype(xs.dtype) * hu
    rows = grouped_matmul_values(act, w_down.astype(xs.dtype), gs,
                                 block_aligned)                # (M, H)
    if pos is not None:
        rows = rows[pos]                                       # (N, H)
    # unsort back to the caller's order
    return jnp.zeros_like(rows).at[order].set(rows)


def moe_ffn_dropless_values(x2, gate_w, w_gate, w_up, w_down, top_k: int):
    """Dropless sort-based MoE SwiGLU FFN (megablox-style).

    x2: (T, H); gate_w: (H, E); w_gate/w_up: (E, H, I); w_down: (E, I, H).
    Dispatch memory is O(T·k·H): tokens are gathered into expert-sorted
    order and the expert matmuls run grouped. No capacity, no drops.
    """
    t, h = x2.shape
    e = gate_w.shape[1]
    logits = x2.astype(jnp.float32) @ gate_w.astype(jnp.float32)  # (T, E)
    probs = jax.nn.softmax(logits, axis=-1)
    gate_vals, gate_idx = jax.lax.top_k(probs, top_k)             # (T, K)

    flat = gate_idx.reshape(-1)                   # slot f=t*K+k -> expert
    tok = jnp.arange(t * top_k) // top_k          # source token per slot
    rows = _expert_ffn_rows(x2[tok], flat, w_gate, w_up, w_down, e)
    wv = gate_vals.reshape(-1).astype(jnp.float32)
    out = jnp.zeros((t, h), jnp.float32).at[tok].add(
        rows.astype(jnp.float32) * wv[:, None])
    return out.astype(x2.dtype), _aux_loss(probs, gate_idx)


def _ragged_ep_offsets(counts, me):
    """Offset bookkeeping for the two-phase ragged exchange.

    counts: (ep, ep) int32, counts[s, j] = rows shard s sends to shard
    j (the all-gathered per-pair counts). Receivers lay incoming rows
    out in sender order. For shard `me` returns, all (ep,) int32:
      out_off[j]      where my rows land in receiver j's buffer
      recv_sizes[s]   rows I receive from sender s
      recv_off[s]     where sender s's rows sit in my receive buffer
      back_out_off[s] where my returned rows land in sender s's
                      dst-sorted send layout (= s's own send offsets
                      toward me, recomputed here from the shared counts)
    """
    out_off = (jnp.cumsum(counts, axis=0) - counts)[me]
    recv_sizes = counts[:, me]
    recv_off = jnp.cumsum(recv_sizes) - recv_sizes
    back_out_off = (jnp.cumsum(counts, axis=1) - counts)[:, me]
    return out_off, recv_sizes, recv_off, back_out_off


def moe_ffn_dropless_ep_values(x2, gate_w, w_gate_l, w_up_l, w_down_l,
                               top_k: int, ep_size: int, axis_name: str,
                               token_axes, pair_capacity: int,
                               ragged: bool = False):
    """Per-shard body of the dropless × expert-parallel path. Runs INSIDE
    shard_map: x2 is this program's (T_local, H) token shard; w_*_l are
    the E/ep experts this shard owns.

    ≙ the reference's `global_scatter`/`global_gather` alltoall dispatch
    (SURVEY.md §2.3 EP row). Two exchange strategies:

    * ragged=False (every backend): each (src, dst) shard pair exchanges
      a fixed `pair_capacity`-row buffer via `lax.all_to_all` over the
      `ep` ICI axis. With pair_capacity = T_local·k (the static worst
      case — MoELayer's 'exact' mode, the default) NO routing skew can
      overflow a pair's buffer, so the exchange is EXACT like the
      reference's; with a smaller budget ('capacity' mode) overflow
      tokens are dropped and the returned drop counter (globally
      psum-reduced) surfaces exactly how many.
    * ragged=True (TPU only — XLA:CPU has no ragged-all-to-all thunk;
      always exact, `pair_capacity` is ignored): per-pair counts are
      all-gathered, then THREE `lax.ragged_all_to_all`s move only the
      real rows (tokens out, expert ids out, FFN rows home), so just
      the ragged payload rides the ICI. The receive buffer stays at the
      static ep·T_local·k worst case — static shapes — but bandwidth is
      proportional to the actual routed load, like `global_scatter`.

    Expert compute is the same grouped-matmul FFN either way.

    Returns (out (T_local, H), aux scalar, drops scalar int32 —
    replicated global count of dropped token-choices this step; always
    0 when ragged).
    """
    t_l, h = x2.shape
    e = gate_w.shape[1]
    e_l = e // ep_size
    cap = pair_capacity
    n = t_l * top_k

    logits = x2.astype(jnp.float32) @ gate_w.astype(jnp.float32)
    probs = jax.nn.softmax(logits, axis=-1)
    gate_vals, gate_idx = jax.lax.top_k(probs, top_k)             # (T_l, K)

    flat = gate_idx.reshape(-1)                   # (N,) global expert id
    tok = jnp.arange(n) // top_k
    dst = flat // e_l                             # target ep shard

    if ragged:
        out, aux = _moe_ep_ragged(x2, tok, flat, dst, gate_vals, probs,
                                  gate_idx, w_gate_l, w_up_l, w_down_l,
                                  e, e_l, ep_size, axis_name, token_axes)
        return out, aux, jnp.zeros((), jnp.int32)
    # rank of each slot within its destination's buffer (priority = slot
    # order, i.e. token-major / choice-minor)
    oh = jax.nn.one_hot(dst, ep_size, dtype=jnp.int32)
    rank = (jnp.cumsum(oh, axis=0) - oh)[jnp.arange(n), dst]
    keep = rank < cap
    idx = jnp.where(keep, dst * cap + rank, ep_size * cap)  # overflow slot

    send_x = jnp.zeros((ep_size * cap + 1, h), x2.dtype) \
        .at[idx].set(jnp.where(keep[:, None], x2[tok], 0))[:-1]
    send_e = jnp.zeros((ep_size * cap + 1,), jnp.int32) \
        .at[idx].set(flat % e_l)[:-1]

    recv_x = jax.lax.all_to_all(send_x, axis_name, 0, 0, tiled=True)
    recv_e = jax.lax.all_to_all(send_e, axis_name, 0, 0, tiled=True)

    rows = _expert_ffn_rows(recv_x, jnp.clip(recv_e, 0, e_l - 1),
                            w_gate_l, w_up_l, w_down_l, e_l)

    back = jax.lax.all_to_all(rows.astype(x2.dtype), axis_name, 0, 0,
                              tiled=True)         # (ep*cap, H)
    slot_rows = jnp.where(keep[:, None],
                          back[jnp.minimum(idx, ep_size * cap - 1)], 0)
    wv = gate_vals.reshape(-1).astype(jnp.float32)
    out = jnp.zeros((t_l, h), jnp.float32).at[tok].add(
        slot_rows.astype(jnp.float32) * wv[:, None])
    # hard drop counter: every shard counts its overflowed slots; psum
    # over every token-sharding axis gives the replicated global count
    drops = jnp.sum(~keep).astype(jnp.int32)
    for ax in token_axes:
        drops = jax.lax.psum(drops, ax)
    # aux loss: pmean the FACTORS (routed fraction f, mean prob p) across
    # token shards before multiplying, so the scalar equals the
    # single-shard global aux exactly (mean of per-shard products would
    # be a biased estimator) and is replicated (out_spec P())
    f = jnp.mean(jax.nn.one_hot(gate_idx[:, 0], e, dtype=jnp.float32),
                 axis=0)
    p = jnp.mean(probs, axis=0)
    for ax in token_axes:
        f = jax.lax.pmean(f, ax)
        p = jax.lax.pmean(p, ax)
    aux = e * jnp.sum(f * p)
    return out.astype(x2.dtype), aux, drops


def _moe_ep_ragged(x2, tok, flat, dst, gate_vals, probs, gate_idx,
                   w_gate_l, w_up_l, w_down_l, e, e_l, ep_size,
                   axis_name, token_axes):
    """Two-phase exact exchange: count all-gather + ragged_all_to_all.
    See moe_ffn_dropless_ep_values (ragged=True). TPU-only at runtime."""
    t_l, h = x2.shape
    n = t_l * gate_vals.shape[1]

    # dst-sorted send layout: receiver j's rows are contiguous
    order = jnp.argsort(dst, stable=True)                     # (N,)
    send_x = x2[tok[order]]
    send_e = (flat % e_l)[order].astype(jnp.int32)
    send_sizes = jnp.bincount(dst, length=ep_size).astype(jnp.int32)
    in_off = (jnp.cumsum(send_sizes) - send_sizes).astype(jnp.int32)

    # phase 1: per-pair counts ride a (tiny) all_gather
    counts = jax.lax.all_gather(send_sizes, axis_name)        # (ep, ep)
    me = jax.lax.axis_index(axis_name)
    out_off, recv_sizes, recv_off, back_out_off = \
        _ragged_ep_offsets(counts, me)

    # phase 2: only the real rows move; the receive buffer keeps the
    # static worst-case size (zeros beyond the received region — zero
    # rows contribute zero through the bias-free SwiGLU)
    r_buf = ep_size * n
    recv_x = jax.lax.ragged_all_to_all(
        send_x, jnp.zeros((r_buf, h), send_x.dtype), in_off,
        send_sizes, out_off, recv_sizes, axis_name=axis_name)
    recv_e = jax.lax.ragged_all_to_all(
        send_e, jnp.zeros((r_buf,), jnp.int32), in_off,
        send_sizes, out_off, recv_sizes, axis_name=axis_name)

    rows = _expert_ffn_rows(recv_x, recv_e, w_gate_l, w_up_l, w_down_l,
                            e_l)

    # route rows home into the sender's dst-sorted layout, then unsort
    back = jax.lax.ragged_all_to_all(
        rows.astype(x2.dtype), jnp.zeros((n, h), x2.dtype), recv_off,
        recv_sizes, back_out_off, send_sizes, axis_name=axis_name)
    slot_rows = jnp.zeros_like(back).at[order].set(back)

    wv = gate_vals.reshape(-1).astype(jnp.float32)
    out = jnp.zeros((t_l, h), jnp.float32).at[tok].add(
        slot_rows.astype(jnp.float32) * wv[:, None])
    # aux loss: pmean the factors (see the dense path's comment)
    f = jnp.mean(jax.nn.one_hot(gate_idx[:, 0], e, dtype=jnp.float32),
                 axis=0)
    p = jnp.mean(probs, axis=0)
    for ax in token_axes:
        f = jax.lax.pmean(f, ax)
        p = jax.lax.pmean(p, ax)
    aux = e * jnp.sum(f * p)
    return out.astype(x2.dtype), aux


def _ragged_ep_supported() -> bool:
    """Gate for the ragged exact-EP exchange: XLA has a
    ragged-all-to-all thunk on TPU but not on CPU (verified UNIMPLEMENTED
    on jax 0.9.0 XLA:CPU). PDT_MOE_RAGGED=1/0 overrides for tests."""
    import os
    ov = os.environ.get("PDT_MOE_RAGGED")
    if ov is not None:
        return ov == "1"
    from ...ops import on_tpu
    return on_tpu() and hasattr(jax.lax, "ragged_all_to_all")


class MoELayer(Layer):
    """Sparse SwiGLU MoE block (+ optional dense shared experts).
    ≙ paddle.incubate MoELayer / Qwen2-MoE & DeepSeekMoE sparse MLP [U?].

    forward(x) -> (out, aux_loss); x: (..., H).
    """

    def __init__(self, hidden_size: int, intermediate_size: int,
                 num_experts: int, top_k: int = 2,
                 capacity_factor: float = 1.25,
                 shared_intermediate_size: int = 0,
                 ep_axis: str = "ep", dropless: bool = False,
                 ep_pair_capacity_factor: Optional[float] = None,
                 name=None):
        """ep_pair_capacity_factor: None (default) = EXACT dropless-EP
        dispatch — per-pair buffers sized to the T_local·k worst case so
        no routing skew can drop a token (≙ reference global_scatter
        exactness; costs ep× the bandwidth of the uniform load). A float
        f bounds each pair's buffer at ≈ f·uniform-load instead; skewed
        routing beyond it drops tokens, and the global count lands in
        `self.last_drop_count` after every eager forward."""
        super().__init__()
        self.hidden_size = hidden_size
        self.intermediate_size = intermediate_size
        self.num_experts = num_experts
        self.top_k = top_k
        self.capacity_factor = capacity_factor
        self.ep_axis = ep_axis
        self.dropless = dropless
        self.ep_pair_capacity_factor = ep_pair_capacity_factor
        self.last_drop_count: Optional[int] = None
        e, h, i = num_experts, hidden_size, intermediate_size
        self.gate_weight = self.create_parameter(
            (h, e), default_initializer=I.Normal(0.0, 0.02))
        self.w_gate = self.create_parameter(
            (e, h, i), default_initializer=I.XavierNormal(fan_in=h,
                                                          fan_out=i))
        self.w_up = self.create_parameter(
            (e, h, i), default_initializer=I.XavierNormal(fan_in=h,
                                                          fan_out=i))
        self.w_down = self.create_parameter(
            (e, i, h), default_initializer=I.XavierNormal(fan_in=i,
                                                          fan_out=h))
        if shared_intermediate_size:
            from ...nn import Linear
            self.shared_gate = Linear(h, shared_intermediate_size,
                                      bias_attr=False)
            self.shared_up = Linear(h, shared_intermediate_size,
                                    bias_attr=False)
            self.shared_down = Linear(shared_intermediate_size, h,
                                      bias_attr=False)
        else:
            self.shared_gate = None

    def forward(self, x):
        from ...distributed.mesh import get_mesh
        shape = x.shape
        h = shape[-1]
        e = self.num_experts
        mesh = get_mesh()
        top_k, cf, ep = self.top_k, self.capacity_factor, self.ep_axis
        ep_active = (mesh is not None and ep in mesh.dim_names
                     and mesh.get_dim_size(ep) > 1)
        pcf = self.ep_pair_capacity_factor

        def fn(xv, gw, wg, wu, wd):
            x2 = xv.reshape(-1, h)
            t = x2.shape[0]
            if self.dropless and ep_active:
                # dropless × EP: shard_map ragged-alltoall dispatch
                # (static per-pair buffers), ≙ global_scatter/gather
                ep_size = mesh.get_dim_size(ep)
                tok_axes = tuple(
                    a for a in ("dp", ep)
                    if a in mesh.dim_names and mesh.get_dim_size(a) > 1)
                n_shards = int(np.prod(
                    [mesh.get_dim_size(a) for a in tok_axes]))
                if t % n_shards == 0 and e % ep_size == 0:
                    try:
                        from jax import shard_map as _shard_map
                    except ImportError:  # pragma: no cover
                        from jax.experimental.shard_map import \
                            shard_map as _shard_map
                    from jax.sharding import PartitionSpec as P
                    t_l = t // n_shards
                    use_ragged = False
                    if pcf is None:
                        # exact mode: zero drops under ANY routing
                        # (≙ global_scatter exactness). On TPU the
                        # two-phase ragged exchange moves only real
                        # rows; elsewhere the dense worst-case buffer
                        # (one shard can never send more than its own
                        # T_local*k slots to one destination) keeps
                        # the same exactness at ep× the bandwidth.
                        cap = t_l * top_k
                        use_ragged = _ragged_ep_supported()
                    else:
                        cap = max(1, min(
                            int(math.ceil(top_k * t_l / ep_size * pcf)),
                            t_l * top_k))

                    def body(x_l, gw_, wg_l, wu_l, wd_l):
                        return moe_ffn_dropless_ep_values(
                            x_l, gw_, wg_l, wu_l, wd_l, top_k, ep_size,
                            ep, list(tok_axes), cap, ragged=use_ragged)
                    from ...distributed.collective import _SM_KW
                    # check_vma off: the grouped-matmul pallas_call in
                    # _expert_ffn_rows can't annotate vma on its outputs
                    mapped = _shard_map(
                        body, mesh=mesh.jax_mesh,
                        in_specs=(P(tok_axes, None), P(None, None),
                                  P(ep, None, None), P(ep, None, None),
                                  P(ep, None, None)),
                        out_specs=(P(tok_axes, None), P(), P()),
                        **_SM_KW)
                    out, aux, drops = mapped(x2, gw, wg, wu, wd)
                    return out.reshape(xv.shape), aux, drops
                # fall through to capacity path on indivisible shapes
            elif self.dropless:
                out, aux = moe_ffn_dropless_values(x2, gw, wg, wu, wd,
                                                   top_k)
                return (out.reshape(xv.shape), aux,
                        jnp.zeros((), jnp.int32))
            out, aux, drops = moe_ffn_values(x2, gw, wg, wu, wd, top_k,
                                             cf, ep, mesh)
            return out.reshape(xv.shape), aux, drops

        out, aux, drops = apply("moe_ffn", fn,
                                (x, self.gate_weight, self.w_gate,
                                 self.w_up, self.w_down),
                                multi_output=True)
        # surface the hard drop counter when running eagerly (a traced
        # value would leak a tracer — skip inside jit)
        try:
            self.last_drop_count = int(drops._value)
        except Exception:
            self.last_drop_count = None
        if self.shared_gate is not None:
            from ...nn import functional as F
            out = out + self.shared_down(
                F.silu(self.shared_gate(x)) * self.shared_up(x))
        return out, aux


def shard_moe(layer, mesh, ep_axis: str = "ep"):
    """Place stacked expert params Shard(0) over the `ep` axis (the
    reference's expert-parallel group); gate + shared experts replicate."""
    from ...distributed.mesh import Replicate, Shard, shard_tensor
    if ep_axis not in mesh.dim_names:
        return layer
    for sub in layer.sublayers(include_self=True):
        if isinstance(sub, MoELayer):
            for pname in ("w_gate", "w_up", "w_down"):
                p = getattr(sub, pname)
                if p._value.shape[0] % mesh.get_dim_size(ep_axis):
                    import warnings
                    warnings.warn(
                        f"shard_moe: {pname} has {p._value.shape[0]} "
                        f"experts, not divisible by ep="
                        f"{mesh.get_dim_size(ep_axis)}; leaving it "
                        "replicated")
                    continue
                placements = [Replicate() for _ in mesh.dim_names]
                placements[mesh.dim_names.index(ep_axis)] = Shard(0)
                s = shard_tensor(p, mesh, placements)
                p._value = s._value
                p.dist_attr = s.dist_attr
    return layer
