"""paddle_tpu.incubate.autograd — functional higher-order autodiff.

≙ reference `paddle.incubate.autograd` (jacobian / hessian / jvp / vjp over
the prim/decomposition machinery, «paddle/fluid/primitive/» + Python API
[U], SURVEY.md §2.1 prim row). TPU-native design: there is no prim op set
to decompose into — every eager op here is already a JAX-traceable pure
function, so higher-order derivatives come straight from composing
`jax.jacfwd` / `jax.jacrev` / `jax.jvp` / `jax.vjp` over the values-level
computation. This is the functional escape hatch the eager tape's
first-order `backward()` points to for `create_graph`-style use.

`func` takes Tensors and returns a Tensor (or tuple); extra non-Tensor
args pass through statically.
"""
from __future__ import annotations

from typing import Callable, Sequence, Union

import jax
import jax.numpy as jnp

from ...core.tensor import Tensor

__all__ = ["vjp", "jvp", "jacobian", "hessian", "grad"]


def _as_list(x):
    return list(x) if isinstance(x, (list, tuple)) else [x]


def _values(ts):
    return [t._value if isinstance(t, Tensor) else jnp.asarray(t)
            for t in ts]


def _wrap(vals):
    return jax.tree_util.tree_map(Tensor, vals)


def _values_fn(func: Callable, n_inputs: int):
    """Lift a Tensor->Tensor function to a values->values function."""
    def fn(*vals):
        out = func(*[Tensor(v) for v in vals[:n_inputs]])
        if isinstance(out, (tuple, list)):
            return tuple(o._value if isinstance(o, Tensor) else o
                         for o in out)
        return out._value if isinstance(out, Tensor) else out
    return fn


def vjp(func: Callable, xs, v=None):
    """(outputs, vjp-result): reverse-mode products. ≙ incubate.autograd.vjp.

    v defaults to ones like the output (scalar-loss convention)."""
    xs = _as_list(xs)
    fn = _values_fn(func, len(xs))
    out_vals, vjp_fn = jax.vjp(fn, *_values(xs))
    if v is None:
        cot = jax.tree_util.tree_map(jnp.ones_like, out_vals)
    else:
        v_list = _as_list(v)
        cot = tuple(_values(v_list)) if isinstance(out_vals, tuple) \
            else _values(v_list)[0]
    grads = vjp_fn(cot)
    outs = _wrap(out_vals)
    gs = _wrap(list(grads))
    return outs, gs if len(gs) > 1 else gs[0]


def jvp(func: Callable, xs, v=None):
    """(outputs, jvp-result): forward-mode products. ≙ incubate.autograd.jvp."""
    xs = _as_list(xs)
    fn = _values_fn(func, len(xs))
    primals = _values(xs)
    if v is None:
        tangents = [jnp.ones_like(p) for p in primals]
    else:
        tangents = _values(_as_list(v))
    out_vals, tang_out = jax.jvp(fn, tuple(primals), tuple(tangents))
    return _wrap(out_vals), _wrap(tang_out)


def jacobian(func: Callable, xs, create_graph: bool = False):
    """Full Jacobian d func / d xs (reverse mode). Single input -> one
    Tensor; multiple inputs -> tuple. Differentiable (compose freely)."""
    xs = _as_list(xs)
    fn = _values_fn(func, len(xs))
    jac = jax.jacrev(fn, argnums=tuple(range(len(xs))))(*_values(xs))
    jac = _wrap(jac)
    return jac if len(xs) > 1 else jac[0]


def hessian(func: Callable, xs, create_graph: bool = False):
    """Hessian of a scalar-output func (fwd-over-rev)."""
    xs = _as_list(xs)
    fn = _values_fn(func, len(xs))

    def scalar_fn(*vals):
        out = fn(*vals)
        out0 = out[0] if isinstance(out, tuple) else out
        if out0.ndim:
            raise ValueError("hessian expects a scalar-output function")
        return out0
    h = jax.hessian(scalar_fn, argnums=tuple(range(len(xs))))(*_values(xs))
    h = _wrap(h)
    return h if len(xs) > 1 else h[0][0]


def grad(func: Callable, argnums: Union[int, Sequence[int]] = 0):
    """jax.grad over a Tensor function — returns a Tensor function.
    Composable: grad(grad(f)) gives second derivatives (the create_graph
    path the eager tape does not provide)."""
    def grad_fn(*xs):
        n = len(xs)
        fn = _values_fn(func, n)

        def scalar_fn(*vals):
            out = fn(*vals)
            return out[0] if isinstance(out, tuple) else out
        g = jax.grad(scalar_fn, argnums=argnums)(*_values(xs))
        return _wrap(g)
    return grad_fn
