"""paddle_tpu.fft — discrete Fourier transforms.
≙ reference «python/paddle/fft.py» [U] (tensor.fft module). All functions
delegate to jnp.fft (XLA FFT HLO — natively supported on TPU) through the
tape so gradients flow."""
from __future__ import annotations

import jax.numpy as jnp

from .core.tensor import Tensor, apply, to_tensor

__all__ = [
    "fft", "ifft", "rfft", "irfft", "hfft", "ihfft",
    "fft2", "ifft2", "rfft2", "irfft2", "hfft2", "ihfft2",
    "fftn", "ifftn", "rfftn", "irfftn", "hfftn", "ihfftn",
    "fftfreq", "rfftfreq", "fftshift", "ifftshift",
]

_NORMS = (None, "backward", "ortho", "forward")


def _t(x):
    return x if isinstance(x, Tensor) else to_tensor(x)


def _norm(norm):
    if norm not in _NORMS:
        raise ValueError(f"Unexpected norm: {norm!r}; expected one of "
                         f"{_NORMS[1:]}")
    return norm or "backward"


def _wrap1(jfn, name):
    def f(x, n=None, axis=-1, norm="backward", name_=None):
        nm = _norm(norm)
        return apply(name, lambda v: jfn(v, n=n, axis=axis, norm=nm),
                     (_t(x),))
    f.__name__ = name
    f.__doc__ = f"≙ paddle.fft.{name} [U]."
    return f


def _wrap2(jfn, name):
    def f(x, s=None, axes=(-2, -1), norm="backward", name_=None):
        nm = _norm(norm)
        return apply(name, lambda v: jfn(v, s=s, axes=tuple(axes), norm=nm),
                     (_t(x),))
    f.__name__ = name
    f.__doc__ = f"≙ paddle.fft.{name} [U]."
    return f


def _wrapn(jfn, name):
    def f(x, s=None, axes=None, norm="backward", name_=None):
        nm = _norm(norm)
        ax = tuple(axes) if axes is not None else None
        return apply(name, lambda v: jfn(v, s=s, axes=ax, norm=nm),
                     (_t(x),))
    f.__name__ = name
    f.__doc__ = f"≙ paddle.fft.{name} [U]."
    return f


fft = _wrap1(jnp.fft.fft, "fft")
ifft = _wrap1(jnp.fft.ifft, "ifft")
rfft = _wrap1(jnp.fft.rfft, "rfft")
irfft = _wrap1(jnp.fft.irfft, "irfft")
hfft = _wrap1(jnp.fft.hfft, "hfft")
ihfft = _wrap1(jnp.fft.ihfft, "ihfft")

fft2 = _wrap2(jnp.fft.fft2, "fft2")
ifft2 = _wrap2(jnp.fft.ifft2, "ifft2")
rfft2 = _wrap2(jnp.fft.rfft2, "rfft2")
irfft2 = _wrap2(jnp.fft.irfft2, "irfft2")

fftn = _wrapn(jnp.fft.fftn, "fftn")
ifftn = _wrapn(jnp.fft.ifftn, "ifftn")
rfftn = _wrapn(jnp.fft.rfftn, "rfftn")
irfftn = _wrapn(jnp.fft.irfftn, "irfftn")


def hfft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    """≙ paddle.fft.hfft2 — real output from Hermitian input, 2-D."""
    nm = _norm(norm)
    return apply("hfft2", lambda v: jnp.fft.irfftn(
        jnp.conj(v), s=s, axes=tuple(axes), norm=nm) *
        _hfft_scale(v, s, axes, nm), (_t(x),))


def _hfft_scale(v, s, axes, nm):
    # hfft(x) == irfft(conj(x)) * n (backward norm)
    import numpy as np
    n = s[-1] if s is not None else 2 * (v.shape[axes[-1]] - 1)
    if nm == "backward":
        sizes = [s[i] if s is not None else
                 (2 * (v.shape[axes[i]] - 1) if i == len(axes) - 1
                  else v.shape[axes[i]]) for i in range(len(axes))]
        return float(np.prod(sizes))
    return 1.0


def ihfft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    nm = _norm(norm)

    def fn(v):
        out = jnp.fft.rfftn(v, s=s, axes=tuple(axes), norm=nm)
        scale = 1.0
        if nm == "backward":
            import numpy as np
            sizes = [s[i] if s is not None else v.shape[axes[i]]
                     for i in range(len(axes))]
            scale = 1.0 / float(np.prod(sizes))
        return jnp.conj(out) * scale
    return apply("ihfft2", fn, (_t(x),))


def hfftn(x, s=None, axes=None, norm="backward", name=None):
    ax = tuple(axes) if axes is not None else tuple(
        range(-_t(x)._value.ndim, 0))
    return hfft2(x, s=s, axes=ax, norm=norm)


def ihfftn(x, s=None, axes=None, norm="backward", name=None):
    ax = tuple(axes) if axes is not None else tuple(
        range(-_t(x)._value.ndim, 0))
    return ihfft2(x, s=s, axes=ax, norm=norm)


def fftfreq(n, d=1.0, dtype=None, name=None):
    return Tensor(jnp.fft.fftfreq(n, d=d).astype(dtype or jnp.float32))


def rfftfreq(n, d=1.0, dtype=None, name=None):
    return Tensor(jnp.fft.rfftfreq(n, d=d).astype(dtype or jnp.float32))


def fftshift(x, axes=None, name=None):
    ax = tuple(axes) if isinstance(axes, (list, tuple)) else axes
    return apply("fftshift", lambda v: jnp.fft.fftshift(v, axes=ax),
                 (_t(x),))


def ifftshift(x, axes=None, name=None):
    ax = tuple(axes) if isinstance(axes, (list, tuple)) else axes
    return apply("ifftshift", lambda v: jnp.fft.ifftshift(v, axes=ax),
                 (_t(x),))
