"""Trainer callbacks. ≙ reference «python/paddle/hapi/callbacks.py» [U]:
ProgBarLogger, ModelCheckpoint, EarlyStopping, LRScheduler, VisualDL —
the VisualDL writer is replaced by a plain JSONL scalar logger (TensorBoard
is available via paddle_tpu.profiler traces instead)."""
from __future__ import annotations

import json
import os
import time

import numpy as np


class Callback:
    def set_params(self, params):
        self.params = params

    def set_model(self, model):
        self.model = model

    def on_train_begin(self, logs=None):
        pass

    def on_train_end(self, logs=None):
        pass

    def on_eval_begin(self, logs=None):
        pass

    def on_eval_end(self, logs=None):
        pass

    def on_epoch_begin(self, epoch, logs=None):
        pass

    def on_epoch_end(self, epoch, logs=None):
        pass

    def on_train_batch_begin(self, step, logs=None):
        pass

    def on_train_batch_end(self, step, logs=None):
        pass

    def on_eval_batch_begin(self, step, logs=None):
        pass

    def on_eval_batch_end(self, step, logs=None):
        pass


class CallbackList:
    def __init__(self, callbacks):
        self.callbacks = list(callbacks)

    def set_params(self, params):
        for c in self.callbacks:
            c.set_params(params)

    def set_model(self, model):
        for c in self.callbacks:
            c.set_model(model)

    def __getattr__(self, name):
        if name.startswith("on_"):
            def dispatch(*args, **kwargs):
                for c in self.callbacks:
                    getattr(c, name)(*args, **kwargs)
            return dispatch
        raise AttributeError(name)


class ProgBarLogger(Callback):
    """≙ hapi ProgBarLogger: per-epoch progress + metric lines.
    `clock` is injectable (pdt-lint PDT001) so tests can pin the
    printed epoch duration."""

    def __init__(self, log_freq=1, verbose=2, clock=time.time):
        self.log_freq = log_freq
        self.verbose = verbose
        self._clock = clock

    def on_epoch_begin(self, epoch, logs=None):
        self._epoch = epoch
        self._t0 = self._clock()
        if self.verbose:
            total = self.params.get("epochs")
            print(f"Epoch {epoch + 1}/{total}")

    def on_train_batch_end(self, step, logs=None):
        if self.verbose > 1 and step % self.log_freq == 0:
            items = " - ".join(f"{k}: {v:.4f}" if isinstance(
                v, (int, float)) else f"{k}: {v}"
                for k, v in (logs or {}).items())
            print(f"step {step}: {items}")

    def on_epoch_end(self, epoch, logs=None):
        if self.verbose:
            dt = self._clock() - self._t0
            items = " - ".join(f"{k}: {v:.4f}" if isinstance(
                v, (int, float)) else f"{k}: {v}"
                for k, v in (logs or {}).items())
            print(f"Epoch {epoch + 1} done in {dt:.1f}s - {items}")


class ModelCheckpoint(Callback):
    """≙ hapi ModelCheckpoint: save every `save_freq` epochs."""

    def __init__(self, save_freq=1, save_dir=None):
        self.save_freq = save_freq
        self.save_dir = save_dir

    def on_epoch_end(self, epoch, logs=None):
        if self.save_dir and epoch % self.save_freq == 0:
            path = os.path.join(self.save_dir, str(epoch))
            self.model.save(path)

    def on_train_end(self, logs=None):
        if self.save_dir:
            self.model.save(os.path.join(self.save_dir, "final"))


class EarlyStopping(Callback):
    """≙ hapi EarlyStopping."""

    def __init__(self, monitor="loss", mode="auto", patience=0,
                 verbose=1, min_delta=0, baseline=None,
                 save_best_model=True):
        self.monitor = monitor
        self.patience = patience
        self.verbose = verbose
        self.min_delta = abs(min_delta)
        self.baseline = baseline
        self.save_best_model = save_best_model
        if mode == "auto":
            mode = "min" if "loss" in monitor else "max"
        self.mode = mode
        self.stopped = False
        self.wait = 0
        self.best = None

    def _better(self, cur):
        if self.best is None:
            return True
        if self.mode == "min":
            return cur < self.best - self.min_delta
        return cur > self.best + self.min_delta

    def on_eval_end(self, logs=None):
        logs = logs or {}
        cur = logs.get(self.monitor)
        if cur is None:
            return
        cur = float(np.asarray(cur).reshape(-1)[0])
        if self._better(cur):
            self.best = cur
            self.wait = 0
        else:
            self.wait += 1
            if self.wait >= self.patience:
                self.stopped = True
                if self.verbose:
                    print(f"EarlyStopping: no {self.monitor} improvement "
                          f"for {self.patience} evals")


class LRSchedulerCallback(Callback):
    """≙ hapi LRScheduler callback: steps the optimizer's scheduler."""

    def __init__(self, by_step=True, by_epoch=False):
        self.by_step = by_step
        self.by_epoch = by_epoch

    def _sched(self):
        opt = getattr(self.model, "_optimizer", None)
        lr = getattr(opt, "_learning_rate", None)
        return lr if hasattr(lr, "step") else None

    def on_train_batch_end(self, step, logs=None):
        s = self._sched()
        if self.by_step and s is not None:
            s.step()

    def on_epoch_end(self, epoch, logs=None):
        s = self._sched()
        if self.by_epoch and s is not None:
            s.step()


class ScalarLogger(Callback):
    """JSONL scalar stream (plays VisualDL's role)."""

    def __init__(self, log_dir="./logs"):
        self.log_dir = log_dir
        self._f = None

    def on_train_begin(self, logs=None):
        os.makedirs(self.log_dir, exist_ok=True)
        self._f = open(os.path.join(self.log_dir, "scalars.jsonl"), "a")

    def on_train_batch_end(self, step, logs=None):
        if self._f and logs:
            rec = {"step": step}
            rec.update({k: float(v) for k, v in logs.items()
                        if isinstance(v, (int, float))})
            self._f.write(json.dumps(rec) + "\n")

    def on_train_end(self, logs=None):
        if self._f:
            self._f.close()
            self._f = None


VisualDL = ScalarLogger  # alias for the reference's callback name
