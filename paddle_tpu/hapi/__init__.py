"""paddle_tpu.hapi — high-level trainer. ≙ reference «python/paddle/hapi/»
(`paddle.Model.fit/evaluate/predict`, SURVEY.md §2.2 hapi row, §7 stage 8).

TPU-native: `fit` compiles the whole train step once via jit.TrainStep
(forward+backward+update donated in HBM) instead of the reference's
per-batch dygraph dispatch; everything else (callbacks, metrics, ckpt
cadence) is trainer bookkeeping on the host.
"""
from __future__ import annotations

import os
from typing import Optional, Sequence

import numpy as np

from ..core.tensor import Tensor, to_tensor
from . import callbacks as cb_mod
from .callbacks import (Callback, CallbackList, EarlyStopping,  # noqa: F401
                        LRSchedulerCallback, ModelCheckpoint, ProgBarLogger,
                        ScalarLogger, VisualDL)

__all__ = ["Model", "Callback", "ProgBarLogger", "ModelCheckpoint",
           "EarlyStopping", "LRSchedulerCallback", "ScalarLogger",
           "VisualDL", "summary"]


def _cat_batches(items):
    """Concatenate loader batches (numpy arrays or Tensors) along dim 0."""
    import numpy as np
    from ..core.tensor import Tensor
    arrs = [np.asarray(it._value) if isinstance(it, Tensor)
            else np.asarray(it) for it in items]
    return np.concatenate(arrs, axis=0)


def _to_list(x):
    if x is None:
        return []
    return list(x) if isinstance(x, (list, tuple)) else [x]


def _metric_logs(m):
    """Metric name()/accumulate() may return scalars or aligned lists."""
    names = m.name()
    names = list(names) if isinstance(names, (list, tuple)) else [names]
    vals = m.accumulate()
    vals = list(vals) if isinstance(vals, (list, tuple)) else [vals]
    return dict(zip(names, vals))


class Model:
    """≙ paddle.Model: trainer facade over an nn.Layer.

    model = paddle.Model(network)
    model.prepare(optimizer, loss, metrics)
    model.fit(train_loader, eval_loader, epochs=2)
    """

    def __init__(self, network, inputs=None, labels=None):
        self.network = network
        self._optimizer = None
        self._loss = None
        self._metrics = []
        self._train_step = None
        self.stop_training = False

    # -- setup ---------------------------------------------------------------
    def prepare(self, optimizer=None, loss=None, metrics=None,
                amp_configs=None):
        self._optimizer = optimizer
        self._loss = loss
        self._metrics = _to_list(metrics)
        self._train_step = None  # rebuilt lazily with the new opt/loss
        return self

    # -- core steps ----------------------------------------------------------
    def _ensure_train_step(self):
        if self._train_step is None:
            from ..jit import TrainStep

            def loss_fn(net, *batch):
                *xs, y = batch
                out = net(*xs)
                out0 = out[0] if isinstance(out, (tuple, list)) else out
                return self._loss(out0, y), out0

            self._train_step = TrainStep(
                self.network, self._optimizer, loss_fn=loss_fn,
                accumulate_steps=getattr(self, "_accumulate_steps", 1))
        return self._train_step

    def train_batch(self, inputs, labels=None):
        """One jitted train step; returns ([loss], metrics-dict)."""
        self.network.train()
        inputs = _to_list(inputs)
        labels = _to_list(labels)
        step = self._ensure_train_step()
        res = step(*inputs, *labels)
        if isinstance(res, tuple):
            loss, out = res[0], res[1]
        else:
            loss, out = res, None
        metrics = {}
        for m in self._metrics:
            if out is not None and labels:
                m.update(m.compute(out, labels[0]))
                metrics.update(_metric_logs(m))
        return [float(loss)], metrics

    def eval_batch(self, inputs, labels=None):
        self.network.eval()
        from ..core.tape import no_grad
        inputs = _to_list(inputs)
        labels = _to_list(labels)
        with no_grad():
            out = self.network(*inputs)
        out0 = out[0] if isinstance(out, (tuple, list)) else out
        res = {}
        if self._loss is not None and labels:
            res["loss"] = float(self._loss(out0, labels[0]))
        for m in self._metrics:
            m.update(m.compute(out0, labels[0]))
            res.update(_metric_logs(m))
        return res, out0

    def predict_batch(self, inputs):
        self.network.eval()
        from ..core.tape import no_grad
        with no_grad():
            out = self.network(*_to_list(inputs))
        return out

    # -- loops ---------------------------------------------------------------
    @staticmethod
    def _unpack(batch):
        if isinstance(batch, (tuple, list)):
            *xs, y = batch
            return xs, [y]
        return [batch], []

    def fit(self, train_data=None, eval_data=None, batch_size=1, epochs=1,
            eval_freq=1, log_freq=10, save_dir=None, save_freq=1,
            verbose=2, drop_last=False, shuffle=True, num_workers=0,
            callbacks=None, accumulate_grad_batches=1, num_iters=None):
        from ..io import DataLoader, Dataset
        accum = max(int(accumulate_grad_batches), 1)
        if accum != getattr(self, "_accumulate_steps", 1):
            # gradient-merge ≙ fleet meta-optimizer (SURVEY.md §2.4),
            # Paddle semantics: N loader batches merge into ONE optimizer
            # step (effective batch = N x batch_size). The N batches are
            # concatenated and the compiled TrainStep micro-batches them
            # back internally, so peak activation memory stays one batch.
            self._accumulate_steps = accum
            self._train_step = None
        if isinstance(train_data, Dataset):
            train_data = DataLoader(train_data, batch_size=batch_size,
                                    shuffle=shuffle,
                                    drop_last=drop_last or accum > 1,
                                    num_workers=num_workers)
        if isinstance(eval_data, Dataset):
            eval_data = DataLoader(eval_data, batch_size=batch_size,
                                   num_workers=num_workers)
        cbs = CallbackList([ProgBarLogger(log_freq, verbose=verbose),
                            LRSchedulerCallback()]
                           + ([ModelCheckpoint(save_freq, save_dir)]
                              if save_dir else [])
                           + _to_list(callbacks))
        cbs.set_model(self)
        cbs.set_params({"epochs": epochs, "verbose": verbose,
                        "metrics": ["loss"] + [m.name()
                                               for m in self._metrics]})
        cbs.on_train_begin()
        it_count = 0
        for epoch in range(epochs):
            for m in self._metrics:
                m.reset()
            cbs.on_epoch_begin(epoch)
            logs = {}
            buf = []
            for step, batch in enumerate(train_data):
                cbs.on_train_batch_begin(step)
                xs, ys = self._unpack(batch)
                if accum > 1:
                    buf.append((xs, ys))
                    if len(buf) < accum:
                        cbs.on_train_batch_end(step, logs)
                        continue
                    xs = [_cat_batches([b[0][i] for b in buf])
                          for i in range(len(xs))]
                    ys = [_cat_batches([b[1][i] for b in buf])
                          for i in range(len(ys))]
                    buf = []
                losses, metrics = self.train_batch(xs, ys)
                logs = {"loss": losses[0]}
                logs.update(metrics)
                cbs.on_train_batch_end(step, logs)
                it_count += 1
                if num_iters is not None and it_count >= num_iters:
                    break
            # a partial accumulation window at epoch end is dropped
            # (gradient-merge convention; matches drop_last)
            cbs.on_epoch_end(epoch, logs)
            if eval_data is not None and (epoch + 1) % eval_freq == 0:
                eval_logs = self.evaluate(eval_data, verbose=0,
                                          callbacks=None,
                                          _cbs=cbs)
                for c in cbs.callbacks:
                    if isinstance(c, EarlyStopping) and c.stopped:
                        self.stop_training = True
            if self.stop_training or (num_iters is not None
                                      and it_count >= num_iters):
                break
        cbs.on_train_end(logs)
        return self

    def evaluate(self, eval_data, batch_size=1, log_freq=10, verbose=2,
                 num_workers=0, callbacks=None, num_iters=None, _cbs=None):
        from ..io import DataLoader, Dataset
        if isinstance(eval_data, Dataset):
            eval_data = DataLoader(eval_data, batch_size=batch_size,
                                   num_workers=num_workers)
        for m in self._metrics:
            m.reset()
        cbs = _cbs or CallbackList(_to_list(callbacks))
        cbs.set_model(self)
        cbs.on_eval_begin()
        logs = {}
        losses = []
        for step, batch in enumerate(eval_data):
            xs, ys = self._unpack(batch)
            res, _ = self.eval_batch(xs, ys)
            if "loss" in res:
                losses.append(res["loss"])
            logs = dict(res)
            if num_iters is not None and step + 1 >= num_iters:
                break
        if losses:
            logs["loss"] = float(np.mean(losses))
        cbs.on_eval_end(logs)
        return logs

    def predict(self, test_data, batch_size=1, num_workers=0,
                stack_outputs=False, callbacks=None, verbose=1):
        from ..io import DataLoader, Dataset
        if isinstance(test_data, Dataset):
            test_data = DataLoader(test_data, batch_size=batch_size,
                                   num_workers=num_workers)
        outs = []
        for batch in test_data:
            xs = _to_list(batch)
            if self._loss is not None and len(xs) > 1:
                xs = xs[:-1]  # (inputs..., label) dataset: drop the label
            out = self.predict_batch(xs)
            outs.append(out)
        return outs

    # -- persistence & introspection ----------------------------------------
    def save(self, path, training=True):
        import paddle_tpu as paddle
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        paddle.save(self.network.state_dict(), path + ".pdparams")
        if training and self._optimizer is not None and \
                hasattr(self._optimizer, "state_dict"):
            paddle.save(self._optimizer.state_dict(), path + ".pdopt")

    def load(self, path, skip_mismatch=False, reset_optimizer=False):
        import paddle_tpu as paddle
        self.network.set_state_dict(paddle.load(path + ".pdparams"))
        opt_path = path + ".pdopt"
        if not reset_optimizer and os.path.exists(opt_path) and \
                self._optimizer is not None and \
                hasattr(self._optimizer, "set_state_dict"):
            self._optimizer.set_state_dict(paddle.load(opt_path))
        return self

    def parameters(self, *args, **kwargs):
        return self.network.parameters(*args, **kwargs)

    def summary(self, input_size=None, dtype=None):
        return summary(self.network)


def summary(network, input_size=None, dtypes=None):
    """≙ paddle.summary — parameter-count table."""
    rows = []
    total = 0
    trainable = 0
    for name, p in network.named_parameters():
        n = int(np.prod(p.shape)) if p.shape else 1
        total += n
        if not p.stop_gradient:
            trainable += n
        rows.append((name, tuple(p.shape), n))
    width = max([len(r[0]) for r in rows], default=20) + 2
    lines = [f"{'Layer (param)':<{width}}{'Shape':<20}{'Param #':>12}"]
    lines += [f"{n:<{width}}{str(s):<20}{c:>12,}" for n, s, c in rows]
    lines += [f"Total params: {total:,}",
              f"Trainable params: {trainable:,}",
              f"Non-trainable params: {total - trainable:,}"]
    table = "\n".join(lines)
    print(table)
    return {"total_params": total, "trainable_params": trainable}


def flops(net, input_size, custom_ops=None, print_detail=False):
    """≙ paddle.flops («python/paddle/hapi/dynamic_flops.py» [U]): count
    multiply-accumulate FLOPs of one forward pass via forward-post hooks.
    `input_size` is the full input shape incl. batch; returns total FLOPs
    (Paddle convention: MACs, elementwise counted once)."""
    import paddle_tpu as paddle
    from ..nn import layer as L
    from ..nn.layer.conv import _ConvNd
    from ..nn.layer.common import Linear as _Linear
    from ..nn.layer.norm import (BatchNorm2D, LayerNorm, _BatchNormBase)

    counts = {}
    handles = []

    def count_conv(layer, inp, out):
        w = layer.weight
        kernel_ops = int(np.prod(w.shape[1:]))  # cin/g * kh * kw
        bias_ops = 1 if getattr(layer, "bias", None) is not None else 0
        n = int(np.prod(out.shape)) if not isinstance(out, (tuple, list)) \
            else int(np.prod(out[0].shape))
        counts[id(layer)] = counts.get(id(layer), 0) \
            + n * (kernel_ops + bias_ops)

    def count_linear(layer, inp, out):
        w = layer.weight
        n_out = int(np.prod(out.shape))
        counts[id(layer)] = counts.get(id(layer), 0) + n_out * w.shape[0]

    def count_norm(layer, inp, out):
        n = int(np.prod(out.shape))
        counts[id(layer)] = counts.get(id(layer), 0) + 2 * n

    def count_act(layer, inp, out):
        n = int(np.prod(out.shape))
        counts[id(layer)] = counts.get(id(layer), 0) + n

    table = {
        _ConvNd: count_conv,
        _Linear: count_linear,
        _BatchNormBase: count_norm,
        LayerNorm: count_norm,
        L.activation.ReLU: count_act,
        L.activation.Sigmoid: count_act,
    }
    if custom_ops:
        table.update(custom_ops)

    names = {}
    for name, sub in net.named_sublayers():
        for cls, fn in table.items():
            if isinstance(sub, cls):
                handles.append(sub.register_forward_post_hook(fn))
                names[id(sub)] = (name, type(sub).__name__)
                break

    was_training = net.training
    net.eval()
    x = paddle.zeros(list(input_size), dtype="float32")
    try:
        net(x)
    finally:
        for h in handles:
            h.remove()
        if was_training:
            net.train()

    total = sum(counts.values())
    if print_detail:
        for lid, c in counts.items():
            nm, cls = names.get(lid, ("?", "?"))
            print(f"{nm:<40}{cls:<20}{c:>16,}")
    print(f"Total Flops: {total}")
    return total
