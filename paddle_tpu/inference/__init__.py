"""paddle_tpu.inference — the predictor-style load-and-serve API.

≙ reference «paddle/fluid/inference/» `AnalysisConfig` /
`AnalysisPredictor` / `paddle_infer.create_predictor` (SURVEY.md §1 L10,
§2.1 inference-engine row). TPU-native: a saved model is the
`paddle.jit.save` artifact pair (params + StableHLO program); the
predictor loads it once and every `run()` executes the ALREADY-COMPILED
XLA program — the reference's ~400 IR fusion passes collapse into the
XLA pipeline that ran at save time. No TensorRT/oneDNN analogue is
needed: XLA:TPU is the optimizing backend.

The handle-based API (`get_input_names` / `get_input_handle` /
`copy_from_cpu` / `run` / `copy_to_cpu`) matches the reference predictor
so serving scripts port verbatim.
"""
from __future__ import annotations

import os
from typing import Dict, List, Optional, Sequence

import numpy as np
import jax
import jax.numpy as jnp

from ..core.tensor import Tensor

__all__ = ["Config", "Predictor", "create_predictor", "PlaceType"]


class PlaceType:
    CPU = "cpu"
    GPU = "tpu"      # accelerator alias: device placement is XLA's job
    XPU = "tpu"
    TPU = "tpu"


class Config:
    """≙ paddle.inference.Config(prog_file_or_prefix[, params_file]).

    Accepts the `paddle.jit.save` prefix (loads `<prefix>.pdmodel` +
    `<prefix>.pdiparams`). The CUDA/TensorRT/oneDNN toggles are accepted
    for script compatibility and recorded as no-ops (XLA owns
    optimization on TPU)."""

    def __init__(self, prog_file: Optional[str] = None,
                 params_file: Optional[str] = None):
        if prog_file is not None and prog_file.endswith(".pdmodel"):
            prog_file = prog_file[:-len(".pdmodel")]
        self.prefix = prog_file
        self.params_file = params_file
        self._device = "tpu"
        self._flags: Dict[str, object] = {}

    # -- device toggles (recorded; placement is XLA's) -----------------
    def enable_use_gpu(self, memory_pool_init_size_mb=100, device_id=0):
        self._device = "tpu"

    def disable_gpu(self):
        self._device = "cpu"

    def enable_xpu(self, *a, **k):
        self._device = "tpu"

    def set_cpu_math_library_num_threads(self, n):
        self._flags["cpu_threads"] = n

    # -- optimization toggles (no-ops on XLA; kept for porting) --------
    def enable_tensorrt_engine(self, *a, **k):
        self._flags["tensorrt"] = False

    def enable_mkldnn(self, *a, **k):
        self._flags["mkldnn"] = False

    def switch_ir_optim(self, flag=True):
        self._flags["ir_optim"] = flag

    def enable_memory_optim(self, flag=True):
        self._flags["memory_optim"] = flag

    def switch_use_feed_fetch_ops(self, flag):
        pass

    def switch_specify_input_names(self, flag=True):
        pass

    def set_model(self, prog_file, params_file=None):
        if prog_file.endswith(".pdmodel"):
            prog_file = prog_file[:-len(".pdmodel")]
        self.prefix = prog_file
        self.params_file = params_file

    def model_dir(self):
        return os.path.dirname(self.prefix or "")

    def summary(self):
        return (f"Config(prefix={self.prefix}, device={self._device}, "
                f"flags={self._flags})")


class _IOHandle:
    """≙ paddle_infer input/output handle: a named host<->device slot."""

    def __init__(self, name: str):
        self.name = name
        self._value = None

    def reshape(self, shape):
        pass  # shapes flow from copy_from_cpu

    def copy_from_cpu(self, arr: np.ndarray):
        self._value = jnp.asarray(arr)

    def share_external_data(self, t):
        self._value = t._value if isinstance(t, Tensor) else jnp.asarray(t)

    def copy_to_cpu(self) -> np.ndarray:
        if self._value is None:
            raise RuntimeError(f"handle {self.name!r}: run() first")
        return np.asarray(self._value)

    def shape(self):
        return list(self._value.shape) if self._value is not None else []


class Predictor:
    """≙ AnalysisPredictor over a jit.save artifact: the StableHLO
    program is deserialized once; run() calls it directly."""

    def __init__(self, config: Config):
        from ..jit import load as jit_load
        if config.prefix is None:
            raise ValueError("Config has no model prefix")
        self.config = config
        self._layer = jit_load(config.prefix,
                               params_file=config.params_file)
        if self._layer._exported is None:
            raise RuntimeError(
                f"{config.prefix}.pdmodel missing or unreadable — "
                "jit.save must be called with input_spec to produce the "
                "serialized program")
        meta = getattr(self._layer, "meta", None)
        if meta is not None:
            # authoritative arity/names from the jit.save sidecar
            self._inputs = [_IOHandle(n) for n in meta["input_names"]]
        else:
            # legacy artifact without .pdmeta: the exported signature is
            # (params..., buffers..., *inputs) flattened — approximate
            # input count = total avals - state tensors (wrong if
            # buffers baked as constants; re-save to get the sidecar)
            n_state = sum(1 for t in self._layer.state.values()
                          if isinstance(t, Tensor))
            n_in = max(len(self._layer._exported.in_avals) - n_state, 1)
            self._inputs = [_IOHandle(f"input_{i}") for i in range(n_in)]
        # output handles exist UP FRONT (the reference script fetches
        # them before the run loop) and are STABLE across runs — run()
        # refreshes their values, never replaces the objects
        n_out = max(len(self._layer._exported.out_avals), 1)
        self._outputs = [_IOHandle(f"output_{i}") for i in range(n_out)]

    # -- handle API ----------------------------------------------------
    def get_input_names(self) -> List[str]:
        return [h.name for h in self._inputs]

    def get_input_handle(self, name: str) -> _IOHandle:
        for h in self._inputs:
            if h.name == name:
                return h
        raise KeyError(name)

    def get_output_names(self) -> List[str]:
        return [h.name for h in self._outputs]

    def get_output_handle(self, name: str) -> _IOHandle:
        for h in self._outputs:
            if h.name == name:
                return h
        raise KeyError(name)

    def run(self, inputs: Optional[Sequence[np.ndarray]] = None):
        """Execute the compiled program. Either pre-load the input
        handles (reference style) or pass arrays directly (convenience);
        returns the output arrays and fills the output handles."""
        if inputs is not None:
            for h, a in zip(self._inputs, inputs):
                h.copy_from_cpu(np.asarray(a))
        vals = [h._value for h in self._inputs]
        if any(v is None for v in vals):
            missing = [h.name for h in self._inputs if h._value is None]
            raise RuntimeError(f"inputs not set: {missing}")
        out = self._layer(*[Tensor(v) for v in vals])
        leaves = jax.tree_util.tree_leaves(
            out, is_leaf=lambda x: isinstance(x, Tensor))
        if len(leaves) != len(self._outputs):
            self._outputs = [_IOHandle(f"output_{i}")
                             for i in range(len(leaves))]
        for h, t in zip(self._outputs, leaves):
            h._value = t._value if isinstance(t, Tensor) else jnp.asarray(t)
        return [np.asarray(h._value) for h in self._outputs]

    def clear_intermediate_tensor(self):
        pass

    def try_shrink_memory(self):
        pass


def create_predictor(config: Config) -> Predictor:
    """≙ paddle.inference.create_predictor."""
    return Predictor(config)
