"""paddle_tpu.static — the static-graph compatibility surface.

≙ «python/paddle/static/» (Program/Executor/data/program_guard — the
reference's largest migration surface, SURVEY.md §2.2 Static API row).

TPU-native design: a `Program` is NOT a ProgramDesc/PIR graph — it is an
op-replay record. While a `program_guard` is active, every framework op
(all of them funnel through `core.tensor.apply`) appends (op name, the
op's value-level function, input/output slots) to the active Program;
`static.data` registers feed slots, and parameters are captured the
first time an op consumes them. `Executor.run(program, feed,
fetch_list)` then replays the op list as ONE pure function under
`jax.jit` — the InterpreterCore + pass stack of the reference collapses
into a single XLA compilation, and `optimizer.minimize(loss)` recorded
in the program turns the replay into a full fwd+bwd+update train step
(`jax.value_and_grad` over the captured parameters, optimizer update
traced exactly like `paddle.jit.TrainStep`).

Semantics notes vs the reference:
* shapes: `static.data(shape=[None, ...])` placeholders record with the
  unknown dims as 1; the replay re-executes the op functions on the REAL
  feed shapes, so any batch size works (one compile per feed signature).
* randomness: ops that drew RNG keys at construction time replay with
  the captured keys (deterministic across `run` calls).
* AMP lists are resolved at record time, not replay time.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np
import jax
import jax.numpy as jnp

from ..core import dtype as dtypes
from ..core import tensor as core_tensor
from ..core.tensor import Parameter, Tensor

__all__ = ["Program", "program_guard", "data", "Executor",
           "default_main_program", "default_startup_program",
           "name_scope", "InputSpec", "nn", "global_scope",
           "save_inference_model", "load_inference_model", "save", "load",
           "cpu_places", "cuda_places", "device_guard", "py_func",
           "in_static_mode"]


class _OpRec:
    __slots__ = ("name", "fn", "in_refs", "out_slots", "multi")

    def __init__(self, name, fn, in_refs, out_slots, multi):
        self.name = name
        self.fn = fn
        self.in_refs = in_refs        # ("var", slot) | ("const", value)
        self.out_slots = out_slots
        self.multi = multi


class Program:
    """≙ paddle.static.Program — an op-replay record (see module doc)."""

    def __init__(self):
        self.ops: List[_OpRec] = []
        self._slot_of: Dict[int, int] = {}
        self._keep: List[Tensor] = []    # strong refs: stable ids
        self.n_slots = 0
        self.feeds: Dict[str, Tuple[int, tuple, str]] = {}
        self.params: Dict[int, Parameter] = {}
        self._init_snapshot: Dict[int, Any] = {}
        self._minimize = None            # (optimizer, loss_slot)
        self._paired_startup: Optional["Program"] = None
        self._exec_cache: Dict[Any, Any] = {}

    # -- slot management -----------------------------------------------
    def _slot(self, t) -> Optional[int]:
        return self._slot_of.get(id(t))

    def _new_slot(self, t) -> int:
        s = self.n_slots
        self.n_slots += 1
        self._slot_of[id(t)] = s
        self._keep.append(t)
        return s

    def _ref_of(self, t):
        s = self._slot(t)
        if s is not None:
            return ("var", s)
        if isinstance(t, Parameter):
            s = self._new_slot(t)
            self.params[s] = t
            # independent copy: the live buffer gets DONATED by the
            # jitted train step, which would delete an aliased snapshot
            self._init_snapshot[s] = jnp.array(t._value, copy=True)
            return ("var", s)
        return ("const", t._value)

    # -- recording -----------------------------------------------------
    def _record(self, name, fn, in_tensors, out, multi):
        in_refs = [self._ref_of(t) for t in in_tensors]
        outs = tuple(out) if multi else (out,)
        out_slots = [self._new_slot(t) for t in outs]
        self.ops.append(_OpRec(name, fn, in_refs, out_slots, multi))
        self._exec_cache.clear()

    def _slot_by_name(self, name: str) -> Optional[int]:
        """Resolve a named Tensor recorded in this Program to its slot.
        Lazy reverse scan (not a dict kept at record time) because users
        often set `.name` AFTER the op that created the variable ran;
        last definition wins, matching the reference's name->var scope
        lookup (≙ Block.var, «python/paddle/base/framework.py» [U])."""
        for t in reversed(self._keep):
            if getattr(t, "name", None) == name:
                return self._slot_of.get(id(t))
        return None

    # -- introspection (migration helpers) -----------------------------
    def list_vars(self):
        return list(self._keep)

    def all_parameters(self):
        return list(self.params.values())

    def global_block(self):
        return self

    @property
    def num_ops(self):
        return len(self.ops)

    def __repr__(self):
        return (f"Program(ops={len(self.ops)}, feeds="
                f"{list(self.feeds)}, params={len(self.params)})")


# the recording stack + lazily-created defaults (enable_static installs
# the default main program as the ambient recorder)
_guard_stack: List[Tuple[Program, Optional[Program]]] = []
_default_main = Program()
_default_startup = Program()
_static_mode = False


def default_main_program() -> Program:
    return _guard_stack[-1][0] if _guard_stack else _default_main


def default_startup_program() -> Program:
    if _guard_stack and _guard_stack[-1][1] is not None:
        return _guard_stack[-1][1]
    return _default_startup


def _recording_program() -> Optional[Program]:
    if _guard_stack:
        return _guard_stack[-1][0]
    if _static_mode:
        return _default_main
    return None


_suspended = 0


class _suspend_recording:
    """Executor.run executes ops (replay + optimizer update) that must
    NOT be re-recorded into the program."""

    def __enter__(self):
        global _suspended
        _suspended += 1
        return self

    def __exit__(self, *a):
        global _suspended
        _suspended -= 1
        return False


def _hook(name, fn, in_tensors, out, multi):
    if _suspended:
        return
    prog = _recording_program()
    if prog is not None:
        prog._record(name, fn, in_tensors, out, multi)


def _sync_hook():
    core_tensor._op_recorder = (_hook if (_guard_stack or _static_mode)
                                else None)


def enable_static():
    """≙ paddle.enable_static: ops now record into
    default_main_program() (or the innermost program_guard)."""
    global _static_mode
    _static_mode = True
    _sync_hook()


def disable_static():
    global _static_mode
    _static_mode = False
    _sync_hook()


def in_static_mode() -> bool:
    return _static_mode or bool(_guard_stack)


class program_guard:
    """≙ paddle.static.program_guard(main, startup=None)."""

    def __init__(self, main_program: Program,
                 startup_program: Optional[Program] = None):
        self.main = main_program
        self.startup = startup_program

    def __enter__(self):
        if self.startup is not None:
            self.main._paired_startup = self.startup
            self.startup._paired_main = self.main
        _guard_stack.append((self.main, self.startup))
        _sync_hook()
        return self

    def __exit__(self, *exc):
        _guard_stack.pop()
        _sync_hook()
        return False


def data(name: str, shape, dtype="float32", lod_level=0) -> Tensor:
    """≙ paddle.static.data: a feed placeholder. Unknown dims (None/-1)
    record as 1; Executor.run replays with the real feed shapes."""
    prog = _recording_program()
    if prog is None:
        raise RuntimeError(
            "paddle.static.data() outside a static context — call "
            "paddle.enable_static() or use static.program_guard")
    conc = tuple(1 if (s is None or int(s) < 0) else int(s)
                 for s in shape)
    t = Tensor(jnp.zeros(conc, dtypes.convert_dtype(dtype)),
               stop_gradient=True)
    t.name = name
    slot = prog._new_slot(t)
    prog.feeds[name] = (slot, tuple(shape), str(dtype))
    return t


class InputSpec:
    def __init__(self, shape, dtype="float32", name=None):
        self.shape = tuple(shape)
        self.dtype = dtype
        self.name = name


class name_scope:
    def __init__(self, prefix=None):
        pass

    def __enter__(self):
        return self

    def __exit__(self, *a):
        return False


def global_scope():
    return default_main_program()


def cpu_places(device_count=None):
    return ["cpu"]


def cuda_places(device_ids=None):
    return ["tpu"]


class device_guard:
    def __init__(self, device=None):
        pass

    def __enter__(self):
        return self

    def __exit__(self, *a):
        return False


def py_func(func, x, out, backward_func=None, skip_vars_in_backward_input=None):
    raise NotImplementedError(
        "static.py_func embeds arbitrary Python in the graph, which "
        "cannot be compiled to XLA; wrap the computation in framework "
        "ops or run it outside the Executor")


class Executor:
    """≙ paddle.static.Executor: replays a Program as one jitted XLA
    program. run(startup) re-applies the captured parameter initial
    values; run(main, feed, fetch_list) executes (and trains, when the
    program recorded optimizer.minimize)."""

    def __init__(self, place=None):
        self.place = place

    # -- startup -------------------------------------------------------
    def _run_startup(self, program: Program):
        main = getattr(program, "_paired_main", None)
        target = main if main is not None else program
        for slot, p in target.params.items():
            snap = target._init_snapshot.get(slot)
            if snap is not None:
                # a copy: the installed value will be donated by the
                # next train step, and the snapshot must survive it
                p._value = jnp.array(snap, copy=True)
        return []

    def run(self, program: Optional[Program] = None, feed=None,
            fetch_list=None, return_numpy=True, **kwargs):
        program = program if program is not None else default_main_program()
        if not isinstance(program, Program):
            raise TypeError(f"Executor.run expects a static.Program, got "
                            f"{type(program)}")
        if not program.ops:
            return self._run_startup(program)
        if feed is None and fetch_list is None:
            # NEVER silently reset a trained program — the reference
            # executes it; we need feeds to replay, so be explicit
            raise ValueError(
                "Executor.run on a program with ops needs feed= and "
                "fetch_list= (run(startup_program) initializes "
                "parameters; it is identified by having no ops)")

        feed = dict(feed or {})
        fetch_list = list(fetch_list or [])
        fetch_slots = []
        for f in fetch_list:
            if isinstance(f, str):
                if f in program.feeds:
                    fetch_slots.append(program.feeds[f][0])
                    continue
                slot = program._slot_by_name(f)
                if slot is None:
                    raise KeyError(
                        f"fetch name {f!r} matches no feed and no named "
                        "variable recorded in this Program; pass the "
                        "Tensor variable itself or set .name on it")
                fetch_slots.append(slot)
            else:
                s = program._slot(f)
                if s is None:
                    raise ValueError(
                        "fetch target was not created inside this "
                        "Program (unknown variable)")
                fetch_slots.append(s)

        feed_names = sorted(program.feeds)
        missing = [n for n in feed_names if n not in feed]
        if missing:
            raise KeyError(f"missing feeds: {missing}")
        feed_vals = [jnp.asarray(feed[n]) for n in feed_names]

        key = (len(program.ops), tuple(fetch_slots),
               tuple((n, tuple(v.shape), str(v.dtype))
                     for n, v in zip(feed_names, feed_vals)),
               program._minimize is not None)
        runner = program._exec_cache.get(key)
        if runner is None:
            runner = self._build(program, feed_names, fetch_slots)
            program._exec_cache[key] = runner
        with _suspend_recording():
            outs = runner(feed_vals)
        if return_numpy:
            outs = [np.asarray(o) for o in outs]
        return outs

    # -- replay build --------------------------------------------------
    def _build(self, program: Program, feed_names, fetch_slots):
        param_slots = sorted(program.params)
        params = [program.params[s] for s in param_slots]

        def replay(env):
            for rec in program.ops:
                ins = [env[r[1]] if r[0] == "var" else r[1]
                       for r in rec.in_refs]
                out = rec.fn(*ins)
                if rec.multi:
                    for s, v in zip(rec.out_slots, out):
                        env[s] = v
                else:
                    env[rec.out_slots[0]] = out
            return env

        def base_env(feed_vals, param_vals):
            env: Dict[int, Any] = {}
            for n, v in zip(feed_names, feed_vals):
                env[program.feeds[n][0]] = v
            for s, v in zip(param_slots, param_vals):
                env[s] = v
            return env

        if program._minimize is None:
            def pure(feed_vals, param_vals):
                env = replay(base_env(feed_vals, param_vals))
                return [env[s] for s in fetch_slots]
            jitted = jax.jit(pure)

            def runner(feed_vals):
                pv = [p._value for p in params]
                return jitted(feed_vals, pv)
            return runner

        opt, loss_slot = program._minimize
        opt.ensure_state()

        def acc_trees():
            acc = {name: {i: store[id(p)]
                          for i, p in enumerate(params) if id(p) in store}
                   for name, store in opt._accumulators.items()}
            master = {i: opt._master_weights[id(p)]
                      for i, p in enumerate(params)
                      if id(p) in opt._master_weights}
            return acc, master

        def pure(feed_vals, param_vals, acc, master, lr, step_count):
            def loss_of(pv):
                env = replay(base_env(feed_vals, pv))
                return env[loss_slot].astype(jnp.float32), env

            (loss, env), grads = jax.value_and_grad(
                loss_of, has_aux=True)(param_vals)
            old_state = [(p._value, p.grad) for p in params]
            # restore ALL python-side optimizer state in finally: an
            # aborted trace must not leak tracers into the optimizer
            # (same failure mode jit.TrainStep guards against)
            old_acc = opt._accumulators
            old_master = opt._master_weights
            old_step = opt._step_count
            old_get_lr = opt.get_lr
            try:
                for p, v, g in zip(params, param_vals, grads):
                    p._value = v
                    p.grad = Tensor(g)
                opt._accumulators = {
                    name: {id(params[i]): arr for i, arr in store.items()}
                    for name, store in acc.items()}
                opt._master_weights = {
                    id(params[i]): arr for i, arr in master.items()}
                opt._step_count = step_count
                opt.get_lr = lambda: lr
                opt.step()
                new_params = [p._value for p in params]
                new_acc = {
                    name: {i: store[id(params[i])]
                           for i in range(len(params))
                           if id(params[i]) in store}
                    for name, store in opt._accumulators.items()}
                new_master = {i: opt._master_weights[id(params[i])]
                              for i in range(len(params))
                              if id(params[i]) in opt._master_weights}
            finally:
                for p, (v, g) in zip(params, old_state):
                    p._value = v
                    p.grad = g
                opt._accumulators = old_acc
                opt._master_weights = old_master
                opt._step_count = old_step
                opt.get_lr = old_get_lr
            return ([env[s] for s in fetch_slots], new_params, new_acc,
                    new_master)

        jitted = jax.jit(pure, donate_argnums=(1, 2, 3))

        def runner(feed_vals):
            acc, master = acc_trees()
            lr = np.float32(opt.get_lr())
            outs, new_p, new_acc, new_master = jitted(
                feed_vals, [p._value for p in params], acc, master, lr,
                np.int32(opt._step_count))
            for p, v in zip(params, new_p):
                p._value = v
                p.grad = None
            for name, store in new_acc.items():
                opt._accumulators[name] = {
                    id(params[i]): arr for i, arr in store.items()}
            opt._master_weights = {
                id(params[i]): arr for i, arr in new_master.items()}
            opt._step_count += 1
            return outs
        return runner


# -- static.nn ---------------------------------------------------------
def _keep_layer(layer):
    """Pin a construction-time layer on the active Program so its
    parameters outlive the guard (and return it)."""
    prog = _recording_program()
    if prog is not None:
        if not hasattr(prog, "_layers"):
            prog._layers = []
        prog._layers.append(layer)
    return layer


class _StaticNN:
    """≙ paddle.static.nn — the construction-time layer helpers. Each
    call creates real parameters (kept alive on the active Program) and
    records the ops like any eager layer call."""

    @staticmethod
    def fc(x, size, num_flatten_dims=1, activation=None, name=None,
           weight_attr=None, bias_attr=None):
        from .. import nn as _nn
        in_dim = int(np.prod(x.shape[num_flatten_dims:]))
        layer = _keep_layer(_nn.Linear(in_dim, size))
        xin = x
        if len(x.shape) > num_flatten_dims + 1:
            xin = x.reshape(list(x.shape[:num_flatten_dims]) + [in_dim])
        out = layer(xin)
        if activation:
            from ..nn import functional as F
            out = getattr(F, activation)(out)
        return out

    @staticmethod
    def batch_norm(x, *a, **k):
        from .. import nn as _nn
        return _keep_layer(_nn.BatchNorm(int(x.shape[1])))(x)

    @staticmethod
    def embedding(x, size, name=None, **k):
        from .. import nn as _nn
        return _keep_layer(_nn.Embedding(size[0], size[1]))(x)


nn = _StaticNN()


# -- save/load (param-level; ≙ static.save/static.load) ----------------
def save(program: Program, model_path: str, protocol=4):
    from ..framework import io as fio
    state = {f"param_{s}": p for s, p in sorted(program.params.items())}
    fio.save(state, model_path + ".pdparams")


def load(program: Program, model_path: str, executor=None, var_list=None):
    from ..framework import io as fio
    state = fio.load(model_path + ".pdparams")
    for s, p in sorted(program.params.items()):
        t = state.get(f"param_{s}")
        if t is not None:
            p._value = (t._value if isinstance(t, Tensor)
                        else jnp.asarray(t)).astype(p._value.dtype)


def save_inference_model(path_prefix, feed_vars, fetch_vars, executor,
                         program: Optional[Program] = None, **kwargs):
    """≙ paddle.static.save_inference_model: parameters + the replay
    metadata needed for load_inference_model in this process family
    (cross-language serving goes through paddle.jit.save/StableHLO)."""
    import pickle
    program = program if program is not None else default_main_program()
    from ..framework import io as fio
    state = {f"param_{s}": p for s, p in sorted(program.params.items())}
    fio.save(state, path_prefix + ".pdiparams")
    meta = {
        "feeds": [getattr(v, "name", None) for v in feed_vars],
        "fetch_slots": [program._slot(v) for v in fetch_vars],
    }
    with open(path_prefix + ".pdmeta", "wb") as f:
        pickle.dump(meta, f)


def load_inference_model(path_prefix, executor, **kwargs):
    import pickle
    from ..framework import io as fio
    state = fio.load(path_prefix + ".pdiparams")
    with open(path_prefix + ".pdmeta", "rb") as f:
        meta = pickle.load(f)
    return state, meta["feeds"], meta["fetch_slots"]
