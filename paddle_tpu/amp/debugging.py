"""paddle.amp.debugging — numerics debugging utilities.

≙ reference «python/paddle/amp/debugging.py» [U] (check_numerics,
collect operator stats, TensorCheckerConfig). The per-op blame machinery
is the framework-wide FLAGS_check_nan_inf path (core.tensor.apply); these
helpers give the explicit-call surface.
"""
from __future__ import annotations

import contextlib

import numpy as np
import jax.numpy as jnp

from ..core.tensor import Tensor

__all__ = ["check_numerics", "enable_operator_stats_collection",
           "disable_operator_stats_collection",
           "collect_operator_stats", "DebugMode", "TensorCheckerConfig",
           "enable_tensor_checker", "disable_tensor_checker"]


class DebugMode:
    CHECK_NAN_INF_AND_ABORT = 0
    CHECK_NAN_INF = 1
    CHECK_ALL = 4


class TensorCheckerConfig:
    def __init__(self, enable=False, debug_mode=DebugMode.
                 CHECK_NAN_INF_AND_ABORT, **kwargs):
        self.enable = enable
        self.debug_mode = debug_mode


def check_numerics(tensor, op_type="", var_name="", debug_mode=None):
    """Raise if tensor holds NaN/Inf (≙ paddle.amp.debugging.
    check_numerics). Returns (num_nan, num_inf, num_zero) tensors like
    the reference."""
    t = tensor if isinstance(tensor, Tensor) else Tensor(jnp.asarray(
        tensor))
    v = t._value
    n_nan = jnp.sum(jnp.isnan(v)).astype(jnp.int64)
    n_inf = jnp.sum(jnp.isinf(v)).astype(jnp.int64)
    n_zero = jnp.sum(v == 0).astype(jnp.int64)
    if int(n_nan) or int(n_inf):
        raise RuntimeError(
            f"check_numerics: {op_type or 'tensor'} {var_name} contains "
            f"{int(n_nan)} NaN / {int(n_inf)} Inf")
    return Tensor(n_nan), Tensor(n_inf), Tensor(n_zero)


def enable_tensor_checker(config: TensorCheckerConfig):
    """Flip the framework-wide per-op NaN scan on (FLAGS_check_nan_inf)."""
    from ..utils.flags import set_flags
    if config.enable:
        set_flags({"FLAGS_check_nan_inf": True})


def disable_tensor_checker():
    from ..utils.flags import set_flags
    set_flags({"FLAGS_check_nan_inf": False})


# operator stats: counts of ops executed per dtype between enable/disable
_op_stats: dict | None = None


def enable_operator_stats_collection():
    global _op_stats
    _op_stats = {}
    from ..core import tensor as _ct

    def observer(name, tensors):
        if _op_stats is not None:
            dt = (str(tensors[0]._value.dtype) if tensors else "-")
            key = f"{name}:{dt}"
            _op_stats[key] = _op_stats.get(key, 0) + 1

    # every op module binds `apply` by reference, so the observer lives
    # INSIDE core.tensor.apply (module-level hook), not a monkeypatch
    _ct._op_observer = observer


def disable_operator_stats_collection():
    global _op_stats
    from ..core import tensor as _ct
    _ct._op_observer = None
    stats, _op_stats = _op_stats, None
    if stats:
        print("op call counts (op:dtype -> n):")
        for k in sorted(stats):
            print(f"  {k:<40}{stats[k]:>8}")
    return stats


@contextlib.contextmanager
def collect_operator_stats():
    enable_operator_stats_collection()
    try:
        yield
    finally:
        disable_operator_stats_collection()
