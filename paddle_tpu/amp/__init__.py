"""AMP: auto_cast + GradScaler. ≙ reference «python/paddle/amp/» [U].

On TPU the recommended mode is bf16 (no loss scaling needed — same exponent
range as fp32); fp16 + dynamic loss scaling is implemented for parity."""
from __future__ import annotations

from contextlib import contextmanager

import numpy as np
import jax.numpy as jnp

from ..core import amp_state as _amp
from ..core.tensor import Tensor


@contextmanager
def auto_cast(enable=True, custom_white_list=None, custom_black_list=None,
              level="O1", dtype="bfloat16", use_promote=True):
    """≙ paddle.amp.auto_cast."""
    s = _amp.amp_state
    prev = (s.enabled, s.dtype, s.level, s.custom_white_list,
            s.custom_black_list)
    s.enabled = enable
    s.dtype = dtype
    s.level = level
    s.custom_white_list = set(custom_white_list or ())
    s.custom_black_list = set(custom_black_list or ())
    try:
        yield
    finally:
        (s.enabled, s.dtype, s.level, s.custom_white_list,
         s.custom_black_list) = prev


amp_guard = auto_cast


def decorate(models, optimizers=None, level="O1", dtype="bfloat16",
             master_weight=None, save_dtype=None):
    """≙ paddle.amp.decorate: O2 casts model params to the low dtype and
    enables optimizer master weights."""
    single_model = not isinstance(models, (list, tuple))
    model_list = [models] if single_model else list(models)
    if level == "O2":
        for m in model_list:
            m.to(dtype=dtype)
        if optimizers is not None:
            opts = [optimizers] if not isinstance(
                optimizers, (list, tuple)) else optimizers
            for opt in opts:
                opt._multi_precision = True if master_weight is None \
                    else bool(master_weight)
    if optimizers is None:
        return models if single_model else model_list
    return (models if single_model else model_list), optimizers


def is_auto_cast_enabled() -> bool:
    return _amp.amp_state.enabled


def get_amp_dtype() -> str:
    return _amp.amp_state.dtype


class GradScaler:
    """Dynamic loss scaling. ≙ paddle.amp.GradScaler [U]. With bf16 the
    scale stays 1.0 and this is a pass-through."""

    def __init__(self, enable=True, init_loss_scaling=65536.0,
                 incr_ratio=2.0, decr_ratio=0.5, incr_every_n_steps=2000,
                 decr_every_n_nan_or_inf=1, use_dynamic_loss_scaling=True):
        self._enable = enable
        self._scale = float(init_loss_scaling) if enable else 1.0
        self._incr_ratio = incr_ratio
        self._decr_ratio = decr_ratio
        self._incr_every = incr_every_n_steps
        self._decr_every = decr_every_n_nan_or_inf
        self._dynamic = use_dynamic_loss_scaling
        self._good_steps = 0
        self._bad_steps = 0
        self._found_inf = False

    def scale(self, var):
        if not self._enable:
            return var
        return var * self._scale

    def unscale_(self, optimizer):
        if not self._enable:
            return
        inv = 1.0 / self._scale
        found = False
        for p in optimizer._parameter_list:
            if p.grad is not None:
                g = p.grad._value.astype(jnp.float32) * inv
                finite = bool(jnp.all(jnp.isfinite(g)))
                found = found or not finite
                p.grad._value = g.astype(p.grad._value.dtype)
        self._found_inf = found

    def step(self, optimizer):
        if not self._enable:
            optimizer.step()
            return
        self.unscale_(optimizer)
        if not self._found_inf:
            optimizer.step()
        self.update()

    def minimize(self, optimizer, scaled_loss):
        scaled_loss.backward()
        self.step(optimizer)

    def update(self):
        if not (self._enable and self._dynamic):
            return
        if self._found_inf:
            self._bad_steps += 1
            self._good_steps = 0
            if self._bad_steps >= self._decr_every:
                self._scale = max(self._scale * self._decr_ratio, 1.0)
                self._bad_steps = 0
        else:
            self._good_steps += 1
            self._bad_steps = 0
            if self._good_steps >= self._incr_every:
                self._scale *= self._incr_ratio
                self._good_steps = 0
        self._found_inf = False

    def is_enable(self):
        return self._enable

    def is_use_dynamic_loss_scaling(self):
        return self._dynamic

    def get_init_loss_scaling(self):
        return self._scale

    def set_init_loss_scaling(self, v):
        self._scale = float(v)

    def state_dict(self):
        return {"scale": self._scale, "incr_ratio": self._incr_ratio,
                "decr_ratio": self._decr_ratio,
                "incr_every_n_steps": self._incr_every,
                "decr_every_n_nan_or_inf": self._decr_every,
                "good_steps": self._good_steps, "bad_steps": self._bad_steps}

    def set_state_dict(self, sd):
        self._scale = sd.get("scale", self._scale)
        self._good_steps = sd.get("good_steps", 0)
        self._bad_steps = sd.get("bad_steps", 0)


def debugging_check_numerics(x, name=""):
    """≙ paddle.amp.debugging / FLAGS_check_nan_inf per-op blame."""
    v = x._value if isinstance(x, Tensor) else x
    if not bool(jnp.all(jnp.isfinite(v))):
        raise FloatingPointError(f"NaN/Inf detected in {name or 'tensor'}")
    return x
