"""Tensor-parallel serving submeshes: one replica = one GSPMD submesh.

Training already dry-runs 4D dp/mp/pp/ep meshes (distributed/mesh.py —
"this IS GSPMD", PAPERS.md arxiv 2105.04663); this module gives the
SERVING fleet the same footing. A `SubMesh` is a disjoint slice of the
global device set wrapped in a one-axis `jax.sharding.Mesh` (axis
`"tp"`), and a replica engine built over it shards its model math and
its paged KV cache across that slice:

* **Weights** — Megatron column/row placements expressed as
  NamedShardings (the `shard_llama` patterns, serving-side): q/k/v,
  gate/up and lm_head shard their OUTPUT dim over `tp`; embeddings
  shard the vocab dim. In the default **exact** mode o_proj/down_proj
  stay replicated and the engine fences their inputs with a
  replicate constraint (`distributed.mesh.serving_tp_replicate`), so
  the forward pass contains NO cross-device reduction — float
  accumulation order never changes and greedy outputs are
  BIT-IDENTICAL to tp=1 by construction. `TpConfig(mode="fast")`
  row-shards o_proj/down_proj instead (input dim over `tp`,
  partial-sum all-reduce), trading the determinism guarantee for the
  full Megatron compute split — bench-only until a tolerance-graded
  quality gate exists.
* **KV pages** — the page pools (HK, P, page_size, D) shard the
  KV-HEAD axis over `tp`: one LOGICAL page = `tp` local shards, each
  holding HK/tp heads of every resident token. The page allocator,
  block tables, and ragged descriptors stay host-side REPLICATED
  scalars — sharding never touches the accounting, so
  `check_invariants()` is unchanged and migration/export walk the
  same block-table windows.
* **Activations** — GSPMD propagation carries the head/feature
  sharding through rope, the ragged scatter, and attention (each
  device computes ITS heads' attention exactly as tp=1 does for those
  heads); the exact-mode fences above are the only explicit
  constraints.

`carve_submeshes(n, TpConfig(tp=k))` partitions `jax.devices()` into n
DISJOINT k-device slices — 8 devices serve 4 replicas x tp=2 or
2 x tp=4 — and `ServingRouter(tp=...)` hands one slice to each
`ReplicaHandle`, which keeps it across restarts: replica identity is
(submesh, generation). Failover needs no page movement (the router
re-prefills from its token mirror onto the survivor's own submesh);
migration serializes one payload FRAGMENT per shard
(`kv_fragments`, engine `export_pages`) so transfer bytes stay local
to each device's host link.

Telemetry (`pdt_tp_*`, docs/observability.md): carved-submesh gauge +
`tp.carve` event, sharded-dispatch counter, per-shard migration bytes.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence

import numpy as np
import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from .. import observability as telemetry
from ..distributed import mesh as mesh_mod

__all__ = ["TP_AXIS", "TpConfig", "SubMesh", "carve_submeshes",
           "kv_fragments", "record_shard_bytes"]

# The ONE mesh-axis name serving shardings use. docs/serving.md
# "Tensor parallelism" documents it in the axis table, and a drift
# guard (tests/test_tp_serving.py) asserts the two stay equal — axis
# names are stringly-typed, and a silent rename would turn every
# NamedSharding below into a KeyError at first dispatch.
TP_AXIS = "tp"

_M_SUBMESHES = telemetry.gauge(
    "pdt_tp_submeshes",
    "Tensor-parallel submeshes carved by the most recent "
    "carve_submeshes call.")
_M_SHARDS = telemetry.gauge(
    "pdt_tp_shards",
    "Shards per replica (tp degree) of the most recently built "
    "TP engine.")
_M_DISPATCHES = telemetry.counter(
    "pdt_tp_dispatches_total",
    "Engine dispatches compiled/ran over a TP submesh (admission, "
    "decode, spec draft/verify, migration installs).")
_M_SHARD_BYTES = telemetry.counter(
    "pdt_tp_migration_shard_bytes_total",
    "Migration payload bytes serialized per TP shard (each fragment "
    "stays local to its device's host link).", ("shard",))


@dataclass
class TpConfig:
    """Tensor-parallel degree + determinism mode for serving replicas.

    `tp` devices per replica; `mode="exact"` (default) guarantees
    greedy outputs bit-identical to tp=1 (no cross-device reductions —
    module docstring), `mode="fast"` row-shards o_proj/down_proj for
    the full Megatron split (partial-sum all-reduce; NOT bit-exact)."""

    tp: int = 1
    mode: str = "exact"

    def __post_init__(self):
        if int(self.tp) < 1:
            raise ValueError(f"tp must be >= 1, got {self.tp}")
        if self.mode not in ("exact", "fast"):
            raise ValueError(f"mode {self.mode!r}: exact|fast")
        self.tp = int(self.tp)


class SubMesh:
    """One replica's device slice as a one-axis GSPMD mesh.

    Carries everything the engine needs to shard itself: the jax Mesh
    (axis `tp`), cached NamedShardings, the weight-spec table, and the
    `replicate_rows` flag `distributed.mesh.serving_tp_replicate`
    reads at trace time (True in exact mode — the determinism fence)."""

    def __init__(self, devices: Sequence, config: TpConfig):
        devices = list(devices)
        if len(devices) != config.tp:
            raise ValueError(f"submesh needs exactly tp={config.tp} "
                             f"devices, got {len(devices)}")
        self.config = config
        self.tp = config.tp
        self.devices = tuple(devices)
        self.device_ids = tuple(int(d.id) for d in devices)
        self.jax_mesh = Mesh(np.asarray(devices), (TP_AXIS,))
        self.replicate_rows = config.mode == "exact"
        self._repl = NamedSharding(self.jax_mesh, PartitionSpec())

    # -- shardings -------------------------------------------------------
    def replicated(self) -> NamedSharding:
        return self._repl

    def sharding(self, *axes) -> NamedSharding:
        """NamedSharding with `tp` on the named tensor dims (None =
        unsharded dim), e.g. ``sharding(TP_AXIS, None)``."""
        return NamedSharding(self.jax_mesh, PartitionSpec(*axes))

    def kv_sharding(self, num_kv_heads: int) -> NamedSharding:
        """Page pools (HK, P, page_size, D): shard the KV-head axis
        when `tp` divides it (one logical page = tp local shards),
        replicate otherwise (draft pools with hk < tp)."""
        if num_kv_heads % self.tp == 0 and self.tp > 1:
            return self.sharding(TP_AXIS, None, None, None)
        return self._repl

    def validate_model(self, cfg) -> None:
        """A TARGET model must split cleanly: the whole TP story rests
        on per-head attention over head-sharded pages, so the head
        counts must divide (a replicated-page 'TP' engine would just
        be tp copies of the same work)."""
        if self.tp == 1:
            return
        bad = []
        if cfg.num_attention_heads % self.tp:
            bad.append(f"num_attention_heads {cfg.num_attention_heads}")
        if cfg.num_key_value_heads % self.tp:
            bad.append(f"num_key_value_heads {cfg.num_key_value_heads}")
        if bad:
            raise ValueError(
                f"model does not split over tp={self.tp}: "
                + ", ".join(bad) + " must be divisible by tp")

    def _param_spec(self, name: str, shape) -> PartitionSpec:
        """The serving-side Megatron placement table (mirrors
        `models.llama.shard_llama`'s mp patterns; weight layout is
        (in, out) — nn.Linear). Falls back to replicated whenever the
        would-be sharded dim does not divide."""
        nm = name.lower()
        spec = PartitionSpec()
        if "embed_tokens" in nm:
            spec = PartitionSpec(TP_AXIS)          # vocab rows; the
            # gather's cross-shard combine only ever adds exact zeros
        elif any(k in nm for k in ("q_proj", "k_proj", "v_proj",
                                   "gate_proj", "up_proj", "lm_head")):
            spec = PartitionSpec(None, TP_AXIS)    # column parallel
        elif any(k in nm for k in ("o_proj", "down_proj")):
            if self.replicate_rows:
                spec = PartitionSpec()             # exact mode: the
                # row matmul runs replicated behind the activation
                # all-gather fence — no partial-sum reduction, ever
            else:
                spec = PartitionSpec(TP_AXIS, None)  # fast: row split
        for tdim, ax in enumerate(spec):
            if ax is not None and shape[tdim] % self.tp:
                return PartitionSpec()             # does not divide
        return spec

    def shard_model_values(self, model):
        """device_put every parameter/buffer VALUE onto this submesh
        per the placement table; returns (param_values, buffer_values)
        aligned with `model.parameters()` / `model.buffers()`. The
        model OBJECT is untouched — replicas on different submeshes
        share it, each engine holding its own placed copies."""
        specs: Dict[int, PartitionSpec] = {}
        for name, p in model.named_parameters():
            specs[id(p)] = self._param_spec(name, p._value.shape)
        pv = [jax.device_put(
            p._value, NamedSharding(self.jax_mesh,
                                    specs.get(id(p), PartitionSpec())))
            for p in model.parameters()]
        bv = [jax.device_put(b._value, self._repl)
              for b in model.buffers()]
        _M_SHARDS.set(self.tp)
        return pv, bv

    def replicate_values(self, model):
        """Fully-replicated placement on this submesh (the draft model
        of a spec-decode TP engine: small by design, and its scan must
        live on the same devices as the verify pass)."""
        pv = [jax.device_put(p._value, self._repl)
              for p in model.parameters()]
        bv = [jax.device_put(b._value, self._repl)
              for b in model.buffers()]
        return pv, bv

    # -- trace scope -----------------------------------------------------
    def scope(self):
        """Context manager the engine wraps around jit dispatch calls:
        trace-time reads (`serving_tp_replicate` in llama.py) then see
        THIS submesh. Counting dispatches here keeps the metric at the
        one choke point every TP program passes through."""
        _M_DISPATCHES.inc()
        return mesh_mod.serving_tp_scope(self)

    def describe(self) -> Dict[str, object]:
        """Operator-facing placement summary (fleet_info/status.py)."""
        return {"tp": self.tp, "mode": self.config.mode,
                "devices": list(self.device_ids)}

    def __repr__(self):
        return (f"SubMesh(tp={self.tp}, mode={self.config.mode}, "
                f"devices={list(self.device_ids)})")


def carve_submeshes(num_replicas: int, config: TpConfig,
                    devices: Optional[Sequence] = None) -> List[SubMesh]:
    """Partition the device set into `num_replicas` DISJOINT contiguous
    tp-sized slices (contiguity keeps each replica's shards
    ICI-adjacent on real topologies — jax.devices() order is the
    platform's physical order). Raises when the fleet does not fit:
    submeshes never overlap, so a dead replica's compute cannot take a
    survivor down with it."""
    devs = list(devices if devices is not None else jax.devices())
    need = num_replicas * config.tp
    if need > len(devs):
        raise ValueError(
            f"{num_replicas} replicas x tp={config.tp} needs {need} "
            f"devices, have {len(devs)}")
    meshes = [SubMesh(devs[i * config.tp:(i + 1) * config.tp], config)
              for i in range(num_replicas)]
    _M_SUBMESHES.set(len(meshes))
    telemetry.event("tp.carve", replicas=num_replicas, tp=config.tp,
                    mode=config.mode,
                    devices=[m.device_ids for m in meshes])
    return meshes


def kv_fragments(arr, pages: np.ndarray) -> List[np.ndarray]:
    """Per-shard host gathers of one page pool's selected page columns:
    one (hk_local, n_pages, page_size, hd) numpy fragment per TP shard,
    ordered by head offset. The gather `shard.data[:, pages]` executes
    ON that shard's device and only its result crosses to the host —
    migration bytes stay local to each device's host link (the
    serialize half of per-shard transfer; `export_pages`). Replicated
    arrays yield one fragment (every shard holds the whole pool)."""
    by_off: Dict[int, object] = {}
    for s in arr.addressable_shards:
        off = s.index[0].start or 0
        if off not in by_off:               # replicated: keep one copy
            by_off[off] = s.data
    return [np.asarray(by_off[off][:, pages])
            for off in sorted(by_off)]


def record_shard_bytes(nbytes_per_shard: Sequence[int]) -> None:
    """Count one migration's serialized payload bytes per shard index
    (`export_pages` passes each shard's total across layers)."""
    for i, nb in enumerate(nbytes_per_shard):
        _M_SHARD_BYTES.inc(int(nb), shard=str(i))
