"""KV page transfer plane: migrate a request between replica engines.

Disaggregated prefill/decode serving (ISSUE 8, ≙ the TPU serving
split every production stack runs) moves a request from the replica
that PREFILLED it to the replica that will DECODE it. What moves is
exactly what the engine holds for the request:

* its resident KV pages (`engine.export_pages` gathers the block-table
  window to host numpy — the serialize side is READ-ONLY, the source
  stays consistent no matter what happens next);
* its request state — original prompt, tokens streamed so far, token
  budget, remaining deadline, preemption count, stable `request_id`.

`install_request` re-materializes that state inside the target engine
(`engine.import_pages`): a free slot is claimed, any prompt prefix the
target's own trie already holds attaches READ-ONLY (a migrated system
prompt costs no page copies the second time), the remaining pages are
allocated and their contents written by one donated device program,
and the installed chain re-registers in the target's prefix structures
so it is warm for the NEXT migration. Page-accounting invariants
(`check_invariants`) hold on both engines at every boundary.

Failure semantics (the failover contract, docs/serving.md
"Disaggregation"): a fault or SIGKILL at EITHER endpoint mid-transfer
leaves both engines consistent — serialize never mutates, install
backs its slot out — so the router simply falls back to the PR-4
failover machinery: re-prefill on a survivor with the streamed tokens
folded in, greedy outputs bit-identical to a colocated fleet. Fault
sites `transfer.serialize` / `transfer.install` (utils/faults.py)
force both halves deterministically.

Payload integrity (ISSUE 13, the manifest.py hashing discipline):
`export_pages` attaches a sha256 checksum per KV shard fragment
(`payload["kv_sha256"]`) and `import_pages` verifies it BEFORE any
target mutation — a flipped byte in flight surfaces as
:class:`PayloadCorruption`, counted as
``pdt_transfer_failures_total{stage="verify"}`` with a
`transfer.failed` event, and the request keeps decoding on its
consistent source (ordinary failover covers a source that later dies).

Quantized serving (engine ``quant=QuantServingConfig``, ISSUE 15): a
quantized source's payload carries the int8 page bytes, the per-page
dequant scale rows (``kv_scales``), and a ``kv_quant`` mode tag; the
bytes move VERBATIM (never re-quantized — migrated streams stay
bit-identical) and are roughly half a bf16 payload / a quarter of an
f32 one (`payload_nbytes` counts the scales too). `import_pages`
refuses a cross-mode payload with :class:`QuantMismatch` BEFORE any
target mutation — booked ``stage="install"`` like any install
refusal — because int8 lattice bytes installed into a full-width pool
(or vice versa) would be silent corruption, not a conversion.

Speculative decoding (engine ``spec_decode=``, ISSUE 10): the payload
carries TARGET pages only — a source engine's DRAFT-model cache is
deliberately DROPPED at the hand-off (`evict_request` releases the
slot's draft pages with the slot), and the target rebuilds it lazily
from the migrated stream on its first spec round, exactly as it does
after a preemption's token-folding re-prefill. Serializing draft
pages would buy one backfill prefill at the cost of coupling the
transfer format to the draft model's geometry (and failover — whose
payload is just the token mirror — could never honor it anyway), so
the one rebuild is the contract: draft caches are rebuilt or dropped,
never torn, on every path that moves a request between engines.

Telemetry: `pdt_transfer_*` counters/histogram plus `transfer.serialize`
/ `transfer.install` spans that join the request's distributed trace
via its `request_id` (docs/observability.md).
"""
from __future__ import annotations

import time
from typing import Callable, Optional, Tuple

from .. import observability as telemetry
from ..models.serving import (ContinuousBatchingEngine, EngineOverloaded,
                              PayloadCorruption, PoolExhausted,
                              QuantMismatch, Request,
                              assemble_payload_kv, verify_payload)
from ..utils.faults import fault_point, fault_value, value_armed

__all__ = ["serialize_request", "install_request", "migrate_request",
           "payload_nbytes", "assemble_payload_kv", "PayloadCorruption",
           "QuantMismatch", "verify_payload", "TransferStageTimeout"]


class TransferStageTimeout(RuntimeError):
    """A migration stage RETURNED but overran its per-stage deadline
    (`migrate_request(stage_deadline=)`, ISSUE 14 satellite): the
    migration is refused — a late install is backed out of the target
    first — counted as ``pdt_transfer_failures_total{stage="timeout"}``
    so the router can defer it and charge the SLOW endpoint's health.
    ``stage`` names the offender (``serialize`` | ``install``).

    Scope, honestly: deadlines are checked at stage BOUNDARIES on the
    injectable clock. A stage that never returns is still the
    replica-level ``wedge_timeout``'s job one level up (no threads in
    the step path by design); what this closes is the gray zone below
    it — a serialize/install that finishes, but so slowly it would
    otherwise silently eat the router tick every tick."""

    def __init__(self, message: str, stage: str):
        super().__init__(message)
        self.stage = stage


_M_MIGRATIONS = telemetry.counter(
    "pdt_transfer_migrations_total",
    "Requests migrated between engines through the KV transfer plane.")
_M_FAILURES = telemetry.counter(
    "pdt_transfer_failures_total",
    "Transfer-plane failures by stage (serialize | verify | install; "
    "capacity deferrals — no free slot / no pages on the target — are "
    "not failures and retry next step).", ("stage",))
_M_BYTES = telemetry.counter(
    "pdt_transfer_bytes_total",
    "KV page bytes serialized out of source engines.")
_M_SECONDS = telemetry.histogram(
    "pdt_transfer_seconds",
    "Wall time of one complete migration (serialize + install + "
    "source evict).")


def payload_nbytes(payload: dict) -> int:
    """Host bytes of the payload's KV page content. A tensor-parallel
    source serializes per-shard FRAGMENTS (`kv_shards` — engine
    `export_pages`, serving/submesh.py) instead of assembled rows;
    counting the fragments keeps this honest: the sum IS the bytes
    that crossed a device->host link, with no double count for an
    assembled view. A QUANTIZED payload's per-page scale rows
    (`kv_scales`) count too — they cross the wire with the int8
    bytes, and the bench's migration-payload A/B must not flatter
    the quantized side by dropping them."""
    n = 0
    if payload.get("kv") is not None:
        n += sum(k.nbytes + v.nbytes for k, v in payload["kv"])
    else:
        n += sum(k.nbytes + v.nbytes
                 for shard in payload["kv_shards"] for k, v in shard)
    if payload.get("kv_scales") is not None:
        n += sum(ks.nbytes + vs.nbytes
                 for ks, vs in payload["kv_scales"])
    return n


def _corrupt_payload_site(payload: dict, tag=None) -> None:
    """The ``transfer.payload`` VALUE fault site (utils/faults.py
    CORRUPT mode): mutate the first KV leaf — layer-0 keys of the
    first shard fragment — AFTER `export_pages` attached its sha256
    manifest. That is in-flight wire damage by construction, and the
    PR-13 `verify_payload` gate must refuse it at install
    (``stage="verify"``), never let it reach a target pool. `tag` is
    the SOURCE engine's `fault_tag` (a fleet replica's index), so a
    tag-pinned rule damages one replica's outbound payloads only —
    the same sick-chip pinning the engine sites honor."""
    if not value_armed("transfer.payload", tag):
        return
    shards = [payload["kv"]] if payload.get("kv") is not None \
        else payload["kv_shards"]
    k, v = shards[0][0]
    mut = fault_value("transfer.payload", k, tag=tag)
    if mut is not k:
        shards[0][0] = (mut, v)


def serialize_request(engine: ContinuousBatchingEngine,
                      rid: int) -> dict:
    """Serialize one RUNNING request's pages + state out of `engine`.
    Read-only: the source still owns the request until
    `engine.evict_request`. Fault sites: ``transfer.serialize``
    (raise) and ``transfer.payload`` (corrupt-mode damage to the
    serialized bytes, post-manifest)."""
    req = engine.get_request(rid)
    request_id = req.request_id if req is not None else str(rid)
    with telemetry.span("transfer.serialize", rid=rid,
                        request_id=request_id):
        fault_point("transfer.serialize")
        payload = engine.export_pages(rid)
    _corrupt_payload_site(payload, getattr(engine, "fault_tag", None))
    return payload


def install_request(engine: ContinuousBatchingEngine, payload: dict,
                    *, deadline: Optional[float] = None) -> Request:
    """Install a serialized request into `engine`'s paged cache;
    returns the live target-engine Request (the router mirrors its
    stream exactly like a dispatched one). `deadline` is the remaining
    budget in seconds on the target engine's clock (the router
    re-derives it so fleet deadlines stay exact across the move).
    Raises `EngineOverloaded` / `PoolExhausted` when the target lacks a
    slot / pages RIGHT NOW — deferrals, not failures. Fault site:
    ``transfer.install`` (fires before any target mutation)."""
    with telemetry.span("transfer.install",
                        request_id=payload["request_id"],
                        tokens=len(payload["output"]),
                        pages=payload["n_pages"]):
        fault_point("transfer.install")
        return engine.import_pages(payload, deadline=deadline)


def _stage_overrun(stage: str, elapsed: float, deadline: float,
                   rid: int) -> TransferStageTimeout:
    """Book one per-stage deadline overrun (counter + event) and
    build the typed error the router defers on."""
    _M_FAILURES.inc(stage="timeout")
    err = TransferStageTimeout(
        f"migration {stage} took {elapsed:.3f}s, over the "
        f"{deadline:.3f}s per-stage deadline — migration deferred, "
        f"slow endpoint degraded", stage)
    telemetry.event("transfer.failed", stage="timeout", rid=rid,
                    error=f"{type(err).__name__}: {err}")
    return err


def migrate_request(src: ContinuousBatchingEngine,
                    dst: ContinuousBatchingEngine, rid: int,
                    *, deadline: Optional[float] = None,
                    clock: Callable[[], float] = time.perf_counter,
                    stage_deadline: Optional[float] = None,
                    ) -> Tuple[Request, dict]:
    """One complete migration: serialize from `src`, install into
    `dst`, then evict the source copy (ordered so a failure at any
    point leaves the request live on exactly one engine — never zero).
    Returns (target Request, payload). Capacity refusals
    (`EngineOverloaded`/`PoolExhausted`) propagate untouched for the
    router to defer on; anything else counts a
    `pdt_transfer_failures_total{stage=...}` before re-raising.
    `clock` times the `pdt_transfer_seconds` observation — the router
    passes ITS injected clock, so the tests' fake clocks drive the
    bench's migration-latency quantiles (PDT001, the pdt-lint rule
    this module was the live hit for). `stage_deadline` bounds each
    stage on the same clock (:class:`TransferStageTimeout` — before
    it, the only bound on a slow serialize/install was the replica
    wedge_timeout, which covers ENGINE steps, not the migration pass:
    a hung stage wedged the router tick with nothing counting)."""
    t0 = clock()
    stage = "serialize"
    try:
        payload = serialize_request(src, rid)
        if stage_deadline is not None \
                and clock() - t0 > stage_deadline:
            # slow source: nothing was installed — refuse before
            # touching the target at all
            raise _stage_overrun("serialize", clock() - t0,
                                 stage_deadline, rid)
        stage = "install"
        # deadline-only reads: callers drive `clock` with exact tick
        # sequences (TestMigrationTiming) — never consume ticks the
        # un-deadlined path did not
        t1 = clock() if stage_deadline is not None else t0
        req = install_request(dst, payload, deadline=deadline)
        if stage_deadline is not None \
                and clock() - t1 > stage_deadline:
            # slow target: the install LANDED, so back it out — the
            # source never evicted and stays authoritative, both
            # engines consistent (the transactional contract)
            dst.evict_request(req.rid)
            raise _stage_overrun("install", clock() - t1,
                                 stage_deadline, rid)
    except (EngineOverloaded, PoolExhausted):
        raise                       # target capacity: defer, not a fault
    except TransferStageTimeout:
        raise                       # counted by _stage_overrun already
    except PayloadCorruption as e:
        # the integrity gate refused the payload before any target
        # mutation: book it at its own stage — corruption is a
        # different operational signal than an install that died
        _M_FAILURES.inc(stage="verify")
        telemetry.event("transfer.failed", stage="verify", rid=rid,
                        error=f"{type(e).__name__}: {e}")
        raise
    except BaseException as e:
        _M_FAILURES.inc(stage=stage)
        telemetry.event("transfer.failed", stage=stage, rid=rid,
                        error=f"{type(e).__name__}: {e}")
        raise
    # both engines hold the request for this instant; evicting second
    # means a crash window can only DUPLICATE (idempotent per
    # request_id), never lose
    src.evict_request(rid)
    _M_MIGRATIONS.inc()
    _M_BYTES.inc(payload_nbytes(payload))
    _M_SECONDS.observe(clock() - t0)
    return req, payload
