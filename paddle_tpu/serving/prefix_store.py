"""Fleet-wide prefix store: shared warmth + host-RAM spill for KV chains.

The engines run vLLM-style automatic prefix caching keyed on
PAGE-ALIGNED token prefixes (models/serving.py), and PR 4's
`prefix_affinity` policy mirrored that structure as *per-replica* LRU
sets — a prefix was an asset of exactly one replica, and died with it.
Disaggregated fleets (serving/transfer.py, ISSUE 8) need the fleet view:

* **Warmth tracking** — one `FleetPrefixStore` replaces the policy's
  per-replica sets: every chain hash (rolling hash per FULL page,
  h_f = hash((h_{f-1}, page_f tokens)) — the engine-trie shape) maps to
  the set of replicas believed to hold it warm, so a prefix warm on ANY
  prefill replica is reachable by all (the router routes to it).
* **Host-RAM spill** — cold chains keep their actual KV page CONTENT in
  host RAM under a byte budget: the transfer plane already serializes a
  migrating request's prompt pages to host memory, so spilling them is
  free, and when every replica holding a chain dies (or evicts it), the
  next request with that prefix re-installs the spilled pages into its
  prefill replica (`engine.import_prefix`) instead of recomputing the
  prefill. Spill entries are LRU-bounded by `spill_budget_bytes`
  (content dropped, warmth records kept).

The store is process-local host state (≙ a serving cell's prefix
directory + host-RAM cache tier): no device memory, no threads,
deterministic given the call sequence — the router drives it from its
step loop. Telemetry rides `pdt_prefix_store_*` (docs/observability.md).
"""
from __future__ import annotations

from collections import OrderedDict
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from .. import observability as telemetry

__all__ = ["FleetPrefixStore", "chain_hashes"]


_M_CHAINS = telemetry.gauge(
    "pdt_prefix_store_chains",
    "Chains tracked by the fleet prefix store.")
_M_SPILL_BYTES = telemetry.gauge(
    "pdt_prefix_store_spilled_bytes",
    "Host-RAM bytes held by spilled chain KV content.")
_M_HITS = telemetry.counter(
    "pdt_prefix_store_hits_total",
    "Store lookups that found the prefix warm, by source "
    "(replica = routed to a warm replica, spill = re-installed from "
    "host RAM).", ("source",))
_M_MISSES = telemetry.counter(
    "pdt_prefix_store_misses_total",
    "Store lookups that found the prefix nowhere in the fleet.")
_M_EVICTIONS = telemetry.counter(
    "pdt_prefix_store_evictions_total",
    "Chain records or spill payloads evicted under the store bounds.")


def chain_hashes(prompt: Sequence[int], page_size: int) -> List[int]:
    """Rolling hash per FULL page of `prompt`, capped one page short of
    the whole prompt (the engine can never share the final token — its
    logits seed decoding), mirroring the engine trie and
    `ContinuousBatchingEngine._match_prefix`'s match cap. The shared
    definition for `PrefixAffinityPolicy` and the fleet store — the two
    must agree or warmth tracking silently diverges from routing."""
    ps = int(page_size)
    n = (len(prompt) - 1) // ps
    hashes, h = [], 0
    for f in range(n):
        h = hash((h, tuple(prompt[f * ps:(f + 1) * ps])))
        hashes.append(h)
    return hashes


class FleetPrefixStore:
    """Fleet-wide chain warmth + host-RAM spill (module docstring).

    One entry per chain hash, LRU-ordered: ``replicas`` is the set of
    replica indices believed warm; spilled entries additionally carry
    the page's token tuple and per-layer KV content (numpy, host RAM).
    `max_chains` bounds the entry count; `spill_budget_bytes` bounds
    the CONTENT bytes (evicting content keeps the warmth record)."""

    def __init__(self, page_size: int, max_chains: int = 4096,
                 spill_budget_bytes: int = 32 << 20):
        self.page_size = int(page_size)
        self.max_chains = int(max_chains)
        self.spill_budget_bytes = int(spill_budget_bytes)
        # hash -> {"parent": hash|None, "replicas": set,
        #          "tokens": tuple|None, "kv": [(k, v)]|None, "bytes": int}
        self._chains: "OrderedDict[int, dict]" = OrderedDict()
        self.spilled_bytes = 0
        # python-side counters so fleet_info works without telemetry
        self.hits = 0
        self.spill_hits = 0
        self.misses = 0
        self.evictions = 0

    # -- warmth ----------------------------------------------------------
    def _touch(self, h: int, parent: Optional[int]) -> dict:
        entry = self._chains.get(h)
        if entry is None:
            entry = {"parent": parent, "replicas": set(),
                     "tokens": None, "kv": None, "bytes": 0,
                     "scales": None, "quant": None}
            self._chains[h] = entry
            self._cap_chains()
        else:
            self._chains.move_to_end(h)
        return entry

    def record(self, replica_index: int, prompt: Sequence[int]):
        """Replica `replica_index` now holds this prompt's chain warm
        (a dispatch placed it there, or a migration installed it)."""
        parent = None
        for h in chain_hashes(prompt, self.page_size):
            self._touch(h, parent)["replicas"].add(int(replica_index))
            parent = h
        _M_CHAINS.set(len(self._chains))

    def longest_warm(self, replica_index: int,
                     hashes: Sequence[int]) -> int:
        """Pages of `hashes` warm on `replica_index`, from the front."""
        depth = 0
        for h in hashes:
            entry = self._chains.get(h)
            if entry is None or replica_index not in entry["replicas"]:
                break
            depth += 1
        return depth

    def forget_replica(self, replica_index: int):
        """The replica died: its warmth is gone (its KV pool died with
        it) — but spilled content lives in HOST RAM and survives."""
        for entry in self._chains.values():
            entry["replicas"].discard(int(replica_index))

    # -- host-RAM spill --------------------------------------------------
    def spill_payload(self, payload: dict) -> int:
        """Spill the prompt chain of one transfer payload
        (`serving.transfer.serialize_request` dict contract: `prompt`,
        `page_size`, `freed`, and per-layer `kv` page arrays shaped
        (hk, n_pages, page_size, hd)). The content is already host-side
        numpy, so this is bookkeeping, not a device read. Returns the
        number of pages spilled (0 for window engines — slid-out pages
        make prompt KV non-stable — or a page-size mismatch)."""
        if payload.get("freed") or payload["page_size"] != self.page_size:
            return 0
        # a tensor-parallel source ships per-shard fragments; the spill
        # stores the LOGICAL rows (import_prefix re-splits them onto
        # whatever submesh restores the chain). Assembly copies the
        # whole payload, so defer it until a page actually needs
        # spilling — the common already-spilled chain stays free
        kv_layers = None
        quant = payload.get("kv_quant")
        kv_scales = payload.get("kv_scales")
        prompt = payload["prompt"]
        ps = self.page_size
        hashes = chain_hashes(prompt, ps)
        spilled, parent = 0, None
        for f, h in enumerate(hashes):
            entry = self._touch(h, parent)
            parent = h
            if entry["kv"] is not None:
                continue                       # already spilled
            if kv_layers is None:
                from ..models.serving import assemble_payload_kv
                kv_layers = assemble_payload_kv(payload)
            kv = [(np.asarray(kp[:, f]), np.asarray(vp[:, f]))
                  for kp, vp in kv_layers]
            nbytes = sum(a.nbytes + b.nbytes for a, b in kv)
            if kv_scales is not None:
                # quantized chains spill HALF-WIDTH: int8 page bytes
                # plus one (page_size,) f32 scale row pair per layer —
                # double the prefix warmth per byte of host RAM
                scales = [(np.asarray(ks[f]), np.asarray(vs[f]))
                          for ks, vs in kv_scales]
                nbytes += sum(a.nbytes + b.nbytes for a, b in scales)
                entry["scales"] = scales
            entry["quant"] = quant
            entry["tokens"] = tuple(prompt[f * ps:(f + 1) * ps])
            entry["kv"] = kv
            entry["bytes"] = nbytes
            self.spilled_bytes += nbytes
            spilled += 1
        self._cap_spill()
        _M_CHAINS.set(len(self._chains))
        _M_SPILL_BYTES.set(self.spilled_bytes)
        return spilled

    def fetch(self, prompt: Sequence[int]):
        """Longest spilled chain prefix of `prompt`, ready for
        `engine.import_prefix`: (page token tuples, per-layer (k, v)
        arrays shaped (hk, n, page_size, hd)), or None when nothing is
        spilled for this prefix. A QUANTIZED chain (spilled from a
        ``kv_quant`` engine) returns a third element — per-layer
        (k_scale, v_scale) rows shaped (n, page_size) — and the walk
        stops at any entry whose quant mode differs from the chain
        head's (mixed-mode bytes are not one installable chain)."""
        chain = []
        for h in chain_hashes(prompt, self.page_size):
            entry = self._chains.get(h)
            if entry is None or entry["kv"] is None:
                break
            if chain and entry.get("quant") != chain[0].get("quant"):
                break
            self._chains.move_to_end(h)
            chain.append(entry)
        if not chain:
            return None
        tokens = [list(e["tokens"]) for e in chain]
        layers = len(chain[0]["kv"])
        kv_rows = [(np.stack([e["kv"][li][0] for e in chain], axis=1),
                    np.stack([e["kv"][li][1] for e in chain], axis=1))
                   for li in range(layers)]
        if chain[0].get("scales") is not None:
            kv_scales = [
                (np.stack([e["scales"][li][0] for e in chain], axis=0),
                 np.stack([e["scales"][li][1] for e in chain], axis=0))
                for li in range(layers)]
            return tokens, kv_rows, kv_scales
        return tokens, kv_rows

    # -- accounting ------------------------------------------------------
    def note_lookup(self, source: str):
        """One routing decision's outcome: `replica` (warm replica
        found), `spill` (restored from host RAM), or `miss`."""
        if source == "replica":
            self.hits += 1
            _M_HITS.inc(source="replica")
        elif source == "spill":
            self.spill_hits += 1
            _M_HITS.inc(source="spill")
        else:
            self.misses += 1
            _M_MISSES.inc()

    def stats(self) -> Dict[str, object]:
        lookups = self.hits + self.spill_hits + self.misses
        return {
            "chains": len(self._chains),
            "spilled_chains": sum(1 for e in self._chains.values()
                                  if e["kv"] is not None),
            "spilled_bytes": self.spilled_bytes,
            "hits": self.hits,
            "spill_hits": self.spill_hits,
            "misses": self.misses,
            "hit_rate": round((self.hits + self.spill_hits) / lookups, 4)
            if lookups else None,
        }

    # -- bounds ----------------------------------------------------------
    def _drop_content(self, entry: dict):
        if entry["kv"] is not None:
            self.spilled_bytes -= entry["bytes"]
            entry["kv"] = None
            entry["tokens"] = None
            entry["scales"] = None
            entry["bytes"] = 0
            self.evictions += 1
            _M_EVICTIONS.inc()

    def _cap_chains(self):
        while len(self._chains) > self.max_chains:
            _, entry = self._chains.popitem(last=False)     # LRU
            if entry["kv"] is not None:
                self._drop_content(entry)   # counts the eviction
            else:
                self.evictions += 1
                _M_EVICTIONS.inc()
        _M_SPILL_BYTES.set(self.spilled_bytes)

    def _cap_spill(self):
        if self.spilled_bytes <= self.spill_budget_bytes:
            return
        for entry in self._chains.values():                 # LRU order
            if self.spilled_bytes <= self.spill_budget_bytes:
                break
            self._drop_content(entry)
        _M_SPILL_BYTES.set(self.spilled_bytes)
