"""Silent-corruption sentries + canary probes (ISSUE 14).

Every failure the fleet survived before this layer was FAIL-STOP:
SIGKILL, raised faults, torn files, wedged ticks. A replica that keeps
answering but answers *wrong* — a flipped KV page, NaN-poisoned
logits, a sick chip — is a GRAY failure: liveness supervision cannot
see it, and every token it streams is a lie served to a user. This
module is the detection half of the gray-failure defense
(docs/serving.md "Gray failures"); the response half (SUSPECT ->
QUARANTINED, tainted-token re-serve, probation) lives in
`replica.py` / `router.py`.

Two detectors, two cost classes:

* :class:`NumericSentry` — per-dispatch numeric checks inside the
  engine's step path (`ContinuousBatchingEngine.attach_sentry`):

  - **token in-vocab check, every step**: every harvested sampled
    token must lie in ``[0, vocab)``. Greedy argmax can only leave
    that range through corruption of the harvested value itself, so
    a trip is proof, not heuristic. Cost: one numpy compare over B
    ints — noise.
  - **logit scan, every Nth step** (``scan_every``): the decode
    program returns its sampled-row logits alongside the tokens and
    the sentry pulls them to host every Nth step, checking
    finiteness and an ``|logit| <= logit_abs_max`` ceiling. The scan
    amortizes: the bench-verified budget is <= 3% decode tokens/sec
    at the default stride (bench.py `detail.sentry`, measured in
    situ — the sentry clocks its own in-step work into ``spent``).

  A trip NEVER raises — the step completes (suspect tokens are
  re-verified by the quarantine machinery, not lost here) and the
  trip surfaces as ``pdt_sentry_trips_total{kind=}`` + a
  ``sentry.trip`` event; the router reads ``trips`` after each
  replica step and marks the replica SUSPECT.

* **Canary probes** (:class:`CanaryConfig`) — a fixed prompt whose
  golden greedy stream is computed ONCE per (model, tp) at fleet
  build on a scratch engine from the same factory. The router replays
  it through each replica's ordinary step path on a clock-driven
  schedule and immediately on suspicion. Greedy decoding is
  batching-invariant (bit-identity under continuous batching is
  test-pinned since PR 1), so a mismatch is PROOF of corruption, not
  load — which is exactly what quarantine needs to act on. A canary
  occupies one engine slot while it runs; its engine-side terminal
  counters are accounted to the fleet's `sentry` section, never to
  client traffic.

Telemetry: ``pdt_sentry_*`` (docs/observability.md catalog).
"""
from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Optional, Tuple

import numpy as np

from .. import observability as telemetry

__all__ = ["SentryConfig", "NumericSentry", "CanaryConfig"]


_M_CHECKS = telemetry.counter(
    "pdt_sentry_checks_total",
    "Numeric sentry checks run, by kind (token | logit_scan).",
    ("kind",))
_M_TRIPS = telemetry.counter(
    "pdt_sentry_trips_total",
    "Numeric sentry violations, by kind (token_oov | logit_nonfinite "
    "| logit_absmax).", ("kind",))
_M_SCAN_SECONDS = telemetry.histogram(
    "pdt_sentry_scan_seconds",
    "Wall time of one every-Nth-step logit scan (host pull + checks).")
_M_DETECTION_LAG = telemetry.histogram(
    "pdt_sentry_detection_lag_steps",
    "Decode steps between a dispatch and the harvest that sentry-"
    "checked it — 0 on the synchronous loop, <= harvest_every-1 on "
    "the pipelined one (the bounded-staleness detection window, "
    "ISSUE 18).",
    buckets=(0, 1, 2, 3, 4, 7, 8, 15, 16, 31, 32))
_M_CANARY_RUNS = telemetry.counter(
    "pdt_sentry_canary_runs_total",
    "Canary probe completions, by result (pass | dirty | fail | "
    "aborted).", ("result",))
_M_CANARY_SECONDS = telemetry.histogram(
    "pdt_sentry_canary_seconds",
    "Wall time of one canary probe, launch to verdict, on the "
    "router's clock.")
_M_QUARANTINES = telemetry.counter(
    "pdt_sentry_quarantines_total",
    "Replicas quarantined on canary evidence, by replica.",
    ("replica",))
_M_TAINTED = telemetry.counter(
    "pdt_sentry_tainted_tokens_total",
    "Mirrored tokens DROPPED at quarantine (streamed since the "
    "replica's last clean canary — regenerated on a healthy replica, "
    "never delivered).")


def note_canary(result: str, seconds: float) -> None:
    """Book one canary completion (the router's verdict path)."""
    _M_CANARY_RUNS.inc(result=result)
    if telemetry.enabled():
        _M_CANARY_SECONDS.observe(seconds)


def note_quarantine(replica: int) -> None:
    _M_QUARANTINES.inc(replica=str(replica))


def note_tainted(n: int) -> None:
    _M_TAINTED.inc(n)


@dataclass
class SentryConfig:
    """Numeric-sentry knobs. ``scan_every=0`` disables the logit scan
    (token checks still run every step); ``scan_every=1`` scans every
    step (the bench A/B's expensive arm). ``logit_abs_max`` is the
    finite ceiling a healthy model's logits never cross — size it per
    model family; the default is generous for fp32/bf16 heads."""

    scan_every: int = 8
    logit_abs_max: float = 1e4

    def __post_init__(self):
        if int(self.scan_every) < 0:
            raise ValueError(
                f"scan_every must be >= 0, got {self.scan_every}")
        if float(self.logit_abs_max) <= 0:
            raise ValueError(
                f"logit_abs_max must be > 0, got {self.logit_abs_max}")


@dataclass
class CanaryConfig:
    """Canary-probe knobs (module docstring). ``interval`` is the
    clock-driven replay period per replica on the ROUTER's injectable
    clock (None = suspicion/probation-triggered only);
    ``max_suspect_rounds`` caps consecutive inconclusive canaries on a
    SUSPECT replica — a canary whose tokens match golden but whose run
    window saw fresh sentry trips is a DIRTY pass, and a replica that
    cannot produce a clean one is quarantined as persistently sick."""

    prompt: Tuple[int, ...] = (3, 1, 4, 1, 5, 9, 2, 6)
    max_new_tokens: int = 8
    interval: Optional[float] = 60.0
    max_suspect_rounds: int = 2

    def __post_init__(self):
        if not self.prompt:
            raise ValueError("canary prompt must be non-empty")
        if int(self.max_new_tokens) < 1:
            raise ValueError("canary max_new_tokens must be >= 1")
        if self.interval is not None and float(self.interval) <= 0:
            raise ValueError(
                f"canary interval must be > 0 or None, got "
                f"{self.interval}")
        if int(self.max_suspect_rounds) < 1:
            raise ValueError("max_suspect_rounds must be >= 1")


class NumericSentry:
    """Per-engine numeric sentry (one per replica INCARNATION — a
    restarted replica gets a fresh one, like its engine). The engine
    calls `observe_tokens` / `observe_logits` from its step path;
    `trips` is the running violation count the router polls. `spent`
    accumulates the sentry's own wall seconds (checks + the logit
    host pull happens in the engine, which adds it via `note_cost`) —
    the in-situ denominator bench.py's overhead bar divides by.

    `clock` is injectable for tests; the default measures REAL wall
    (the sentry's cost is a hardware-honesty number, like
    decode_step_seconds)."""

    def __init__(self, config: SentryConfig, vocab_size: int,
                 replica: Optional[int] = None,
                 clock=time.perf_counter):
        self.config = config
        self.vocab = int(vocab_size)
        self.replica = replica
        self._clock = clock
        self.trips = 0
        self.last_trip: Optional[dict] = None
        self.steps = 0
        self.scans = 0
        self.spent = 0.0               # sentry-seconds, in-step

    # -- engine-facing ------------------------------------------------
    @property
    def wants_logits(self) -> bool:
        """True when the engine's decode program must return its
        sampled-row logits (the every-Nth scan needs them)."""
        return int(self.config.scan_every) > 0

    def step_tick(self) -> bool:
        """One decode step happened; returns True when THIS step's
        logits should be harvested and scanned (every Nth)."""
        due = self.wants_logits \
            and self.steps % int(self.config.scan_every) == 0
        self.steps += 1
        return due

    def observe_tokens(self, tokens) -> None:
        """In-vocab check over one dispatch's harvested sampled
        tokens (active rows only)."""
        t0 = self._clock()
        toks = np.asarray(tokens)
        _M_CHECKS.inc(kind="token")
        if toks.size and (np.any(toks < 0)
                          or np.any(toks >= self.vocab)):
            bad = toks[(toks < 0) | (toks >= self.vocab)]
            self._trip("token_oov",
                       f"sampled token(s) {bad[:4].tolist()} outside "
                       f"[0, {self.vocab})")
        self.spent += self._clock() - t0

    def observe_logits(self, logits) -> None:
        """Finiteness + abs-max scan over one step's sampled-row
        logits (already on host; the engine pulled them)."""
        t0 = self._clock()
        lg = np.asarray(logits)
        self.scans += 1
        _M_CHECKS.inc(kind="logit_scan")
        if lg.size and not np.all(np.isfinite(lg)):
            n = int(np.size(lg) - np.count_nonzero(np.isfinite(lg)))
            self._trip("logit_nonfinite",
                       f"{n} non-finite logit value(s) in the decode "
                       "step's sampled rows")
        elif lg.size and float(np.max(np.abs(lg))) \
                > float(self.config.logit_abs_max):
            self._trip("logit_absmax",
                       f"|logit| {float(np.max(np.abs(lg))):.3g} over "
                       f"the {self.config.logit_abs_max:g} ceiling")
        dt = self._clock() - t0
        self.spent += dt
        if telemetry.enabled():
            _M_SCAN_SECONDS.observe(dt)

    def note_cost(self, seconds: float) -> None:
        """Engine-side sentry work done outside observe_* (the logit
        D2H pull) — folded into `spent` so the bench's in-situ
        overhead number covers the WHOLE sentry cost."""
        self.spent += seconds

    def note_lag(self, steps: int) -> None:
        """Book the detection lag of one dispatch: how many decode
        steps elapsed between that dispatch and the harvest that ran
        its sentry checks. 0 on the synchronous loop; bounded at
        ``harvest_every - 1`` on the pipelined one. Pure metering —
        no `spent` charge (it is not sentry WORK, it is staleness)."""
        if telemetry.enabled():
            _M_DETECTION_LAG.observe(int(steps))

    # -- internals ----------------------------------------------------
    def _trip(self, kind: str, detail: str):
        self.trips += 1
        self.last_trip = {"kind": kind, "detail": detail,
                          "step": self.steps}
        _M_TRIPS.inc(kind=kind)
        telemetry.event("sentry.trip", kind=kind, detail=detail,
                        replica=self.replica, step=self.steps)

    def info(self) -> dict:
        return {"trips": self.trips, "steps": self.steps,
                "scans": self.scans, "last_trip": self.last_trip}
