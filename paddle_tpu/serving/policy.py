"""Pluggable dispatch policies for the serving fleet router.

A policy answers one question: given the replicas currently willing to
accept traffic, where should this prompt go? Three built-ins:

* ``round_robin`` — rotate through accepting replicas; the baseline.
* ``least_outstanding`` — fewest waiting+running requests wins (ties
  break to the lowest index); the classic load balancer.
* ``prefix_affinity`` — the TPU-serving-shaped one. The engines run
  vLLM-style automatic prefix caching keyed on PAGE-ALIGNED token
  prefixes (models/serving.py), so which replica a prompt lands on
  directly decides whether its system-prompt KV is recomputed or
  attached read-only from the replica's page trie. The policy mirrors
  that structure host-side: every dispatched prompt's page-aligned
  prefix is folded into a per-replica set of rolling chain hashes
  (h_f = hash((h_{f-1}, page_f tokens)) — one hash per full page, same
  parent-chain shape as the engine trie), and a new prompt prefers the
  replica holding its LONGEST warm chain, falling back to
  least-outstanding when nothing is warm or scores tie. Replica death
  forgets that replica's chains (its cache died with it).

Policies are deterministic given the same dispatch sequence — no RNG,
no wall clock — so fleet placement (and therefore the whole router) is
reproducible in tests.
"""
from __future__ import annotations

from collections import OrderedDict
from typing import Dict, List, Optional, Sequence

from .prefix_store import FleetPrefixStore, chain_hashes
from .replica import ReplicaHandle

__all__ = ["DispatchPolicy", "RoundRobinPolicy", "LeastOutstandingPolicy",
           "PrefixAffinityPolicy", "POLICIES", "make_policy"]


class DispatchPolicy:
    """Interface: `select` picks a replica from the accepting
    candidates (never empty); `on_dispatch` observes the router's final
    placement (including forced failover placements, so warmth tracking
    follows the requests); `forget` drops per-replica state when a
    replica dies."""

    name = "base"

    def select(self, candidates: Sequence[ReplicaHandle],
               prompt: List[int]) -> ReplicaHandle:
        raise NotImplementedError

    def on_dispatch(self, replica: ReplicaHandle, prompt: List[int]):
        pass

    def forget(self, replica_index: int):
        pass


class RoundRobinPolicy(DispatchPolicy):
    name = "round_robin"

    def __init__(self):
        self._next = 0

    def select(self, candidates, prompt):
        # rotate over replica INDICES, not the candidate list: with a
        # replica missing from the candidates the remaining ones must
        # still alternate instead of collapsing onto one
        chosen = min(candidates,
                     key=lambda h: ((h.index - self._next)
                                    % (max(c.index for c in candidates)
                                       + 1), h.index))
        self._next = chosen.index + 1
        return chosen


class LeastOutstandingPolicy(DispatchPolicy):
    name = "least_outstanding"

    def select(self, candidates, prompt):
        return min(candidates, key=lambda h: (h.outstanding(), h.index))


class PrefixAffinityPolicy(DispatchPolicy):
    """Prefer the replica whose prefix cache is warm for this prompt's
    page-aligned prefix; fall back by load (module docstring)."""

    name = "prefix_affinity"

    def __init__(self, page_size: int = 16, max_tracked: int = 4096,
                 store: Optional[FleetPrefixStore] = None):
        self.page_size = int(page_size)
        self.max_tracked = int(max_tracked)
        # replica index -> LRU set of warm chain hashes; superseded by
        # the FLEET prefix store when one is attached (role-aware
        # fleets): warmth then lives in one shared structure that also
        # spills cold chains to host RAM (prefix_store.py)
        self._warm: Dict[int, "OrderedDict[int, None]"] = {}
        self.store = store
        # select() diagnostics the router reads for the hit-rate metric
        self.last_match_pages = 0

    def _chain_hashes(self, prompt: List[int]) -> List[int]:
        """Rolling hash per FULL page of the prompt (the shared
        definition in prefix_store.py — one scheme for the policy, the
        fleet store, and the engine-trie shape they both mirror).
        Tuple-of-int hashing is stable within a process and unsalted
        across runs."""
        return chain_hashes(prompt, self.page_size)

    def _longest_warm(self, replica_index: int,
                      hashes: List[int]) -> int:
        if self.store is not None:
            return self.store.longest_warm(replica_index, hashes)
        warm = self._warm.get(replica_index)
        if not warm:
            return 0
        depth = 0
        for h in hashes:
            if h not in warm:
                break
            depth += 1
        return depth

    def select(self, candidates, prompt):
        hashes = self._chain_hashes(prompt)
        best: Optional[ReplicaHandle] = None
        best_depth = 0
        for h in candidates:
            depth = self._longest_warm(h.index, hashes)
            if depth > best_depth:
                best, best_depth = h, depth
        self.last_match_pages = best_depth
        if best is not None:
            return best
        # nothing warm: place by load so cold prefixes spread out
        return min(candidates, key=lambda h: (h.outstanding(), h.index))

    def on_dispatch(self, replica, prompt):
        if self.store is not None:
            self.store.record(replica.index, prompt)
            return
        warm = self._warm.setdefault(replica.index, OrderedDict())
        for h in self._chain_hashes(prompt):
            if h in warm:
                warm.move_to_end(h)
            else:
                warm[h] = None
        while len(warm) > self.max_tracked:
            warm.popitem(last=False)

    def forget(self, replica_index: int):
        if self.store is not None:
            self.store.forget_replica(replica_index)
        self._warm.pop(replica_index, None)


POLICIES = {
    RoundRobinPolicy.name: RoundRobinPolicy,
    LeastOutstandingPolicy.name: LeastOutstandingPolicy,
    PrefixAffinityPolicy.name: PrefixAffinityPolicy,
}


def make_policy(policy, page_size: int = 16,
                store: Optional[FleetPrefixStore] = None
                ) -> DispatchPolicy:
    """Accepts a policy NAME (see `POLICIES`) or an instance.
    `page_size` seeds prefix-affinity hashing and must match the
    engines' page size for warmth tracking to mirror their tries.
    `store` (role-aware fleets) attaches the fleet-wide prefix store
    to prefix-affinity warmth tracking."""
    if isinstance(policy, DispatchPolicy):
        if store is not None and isinstance(policy,
                                            PrefixAffinityPolicy) \
                and policy.store is None:
            policy.store = store
        return policy
    if policy in POLICIES:
        if policy == PrefixAffinityPolicy.name:
            return PrefixAffinityPolicy(page_size=page_size,
                                        store=store)
        return POLICIES[policy]()
    raise ValueError(f"unknown dispatch policy {policy!r}: "
                     f"{sorted(POLICIES)} or a DispatchPolicy instance")
