"""Pluggable dispatch policies for the serving fleet router.

A policy answers one question: given the replicas currently willing to
accept traffic, where should this prompt go? Three built-ins:

* ``round_robin`` — rotate through accepting replicas; the baseline.
* ``least_outstanding`` — fewest waiting+running requests wins (ties
  break to the lowest index); the classic load balancer.
* ``model_affinity`` — the multi-model one (ISSUE 17). With a
  `FleetModelStore` attached the fleet hosts several models/LoRA
  fine-tunes over shared replicas, and which replica a request lands
  on decides whether its model's weights are already RESIDENT (warm —
  dispatch is just a row-id tag) or must be cold-installed through the
  store's byte-budgeted LRU first. The policy prefers the
  least-loaded replica where `store.is_resident(replica, model)`,
  falling back to least-outstanding when nothing is warm (the router
  then cold-installs on that replica before dispatch).
* ``prefix_affinity`` — the TPU-serving-shaped one. The engines run
  vLLM-style automatic prefix caching keyed on PAGE-ALIGNED token
  prefixes (models/serving.py), so which replica a prompt lands on
  directly decides whether its system-prompt KV is recomputed or
  attached read-only from the replica's page trie. The policy mirrors
  that structure host-side: every dispatched prompt's page-aligned
  prefix is folded into a per-replica set of rolling chain hashes
  (h_f = hash((h_{f-1}, page_f tokens)) — one hash per full page, same
  parent-chain shape as the engine trie), and a new prompt prefers the
  replica holding its LONGEST warm chain, falling back to
  least-outstanding when nothing is warm or scores tie. Replica death
  forgets that replica's chains (its cache died with it).

Policies are deterministic given the same dispatch sequence — no RNG,
no wall clock — so fleet placement (and therefore the whole router) is
reproducible in tests.
"""
from __future__ import annotations

from collections import OrderedDict
from typing import Dict, List, Optional, Sequence

from .prefix_store import FleetPrefixStore, chain_hashes
from .replica import ReplicaHandle

__all__ = ["DispatchPolicy", "RoundRobinPolicy", "LeastOutstandingPolicy",
           "PrefixAffinityPolicy", "ModelAffinityPolicy", "POLICIES",
           "make_policy"]


class DispatchPolicy:
    """Interface: `select` picks a replica from the accepting
    candidates (never empty); `on_dispatch` observes the router's final
    placement (including forced failover placements, so warmth tracking
    follows the requests); `forget` drops per-replica state when a
    replica dies."""

    name = "base"

    def select(self, candidates: Sequence[ReplicaHandle],
               prompt: List[int],
               model: Optional[str] = None) -> ReplicaHandle:
        raise NotImplementedError

    def on_dispatch(self, replica: ReplicaHandle, prompt: List[int]):
        pass

    def forget(self, replica_index: int):
        pass


class RoundRobinPolicy(DispatchPolicy):
    name = "round_robin"

    def __init__(self):
        self._next = 0

    def select(self, candidates, prompt, model=None):
        # rotate over replica INDICES, not the candidate list: with a
        # replica missing from the candidates the remaining ones must
        # still alternate instead of collapsing onto one
        chosen = min(candidates,
                     key=lambda h: ((h.index - self._next)
                                    % (max(c.index for c in candidates)
                                       + 1), h.index))
        self._next = chosen.index + 1
        return chosen


class LeastOutstandingPolicy(DispatchPolicy):
    name = "least_outstanding"

    def select(self, candidates, prompt, model=None):
        return min(candidates, key=lambda h: (h.outstanding(), h.index))


class PrefixAffinityPolicy(DispatchPolicy):
    """Prefer the replica whose prefix cache is warm for this prompt's
    page-aligned prefix; fall back by load (module docstring)."""

    name = "prefix_affinity"

    def __init__(self, page_size: int = 16, max_tracked: int = 4096,
                 store: Optional[FleetPrefixStore] = None):
        self.page_size = int(page_size)
        self.max_tracked = int(max_tracked)
        # replica index -> LRU set of warm chain hashes; superseded by
        # the FLEET prefix store when one is attached (role-aware
        # fleets): warmth then lives in one shared structure that also
        # spills cold chains to host RAM (prefix_store.py)
        self._warm: Dict[int, "OrderedDict[int, None]"] = {}
        self.store = store
        # select() diagnostics the router reads for the hit-rate metric
        self.last_match_pages = 0

    def _chain_hashes(self, prompt: List[int]) -> List[int]:
        """Rolling hash per FULL page of the prompt (the shared
        definition in prefix_store.py — one scheme for the policy, the
        fleet store, and the engine-trie shape they both mirror).
        Tuple-of-int hashing is stable within a process and unsalted
        across runs."""
        return chain_hashes(prompt, self.page_size)

    def _longest_warm(self, replica_index: int,
                      hashes: List[int]) -> int:
        if self.store is not None:
            return self.store.longest_warm(replica_index, hashes)
        warm = self._warm.get(replica_index)
        if not warm:
            return 0
        depth = 0
        for h in hashes:
            if h not in warm:
                break
            depth += 1
        return depth

    def select(self, candidates, prompt, model=None):
        hashes = self._chain_hashes(prompt)
        best: Optional[ReplicaHandle] = None
        best_depth = 0
        for h in candidates:
            depth = self._longest_warm(h.index, hashes)
            if depth > best_depth:
                best, best_depth = h, depth
        self.last_match_pages = best_depth
        if best is not None:
            return best
        # nothing warm: place by load so cold prefixes spread out
        return min(candidates, key=lambda h: (h.outstanding(), h.index))

    def on_dispatch(self, replica, prompt):
        if self.store is not None:
            self.store.record(replica.index, prompt)
            return
        warm = self._warm.setdefault(replica.index, OrderedDict())
        for h in self._chain_hashes(prompt):
            if h in warm:
                warm.move_to_end(h)
            else:
                warm[h] = None
        while len(warm) > self.max_tracked:
            warm.popitem(last=False)

    def forget(self, replica_index: int):
        if self.store is not None:
            self.store.forget_replica(replica_index)
        self._warm.pop(replica_index, None)


class ModelAffinityPolicy(DispatchPolicy):
    """Prefer replicas where the request's model is already resident
    in the attached `FleetModelStore` (module docstring). Warmth is
    read straight from the store's per-replica resident sets — the
    policy keeps NO shadow state, so install/evict churn (the store's
    byte-budgeted LRU) is reflected on the next `select` without a
    coherence protocol. `last_warm` reports whether the last pick was
    warm (the router feeds the cold-install counter from it)."""

    name = "model_affinity"

    def __init__(self, model_store=None):
        self.model_store = model_store
        self.last_warm = False

    def select(self, candidates, prompt, model=None):
        store = self.model_store
        if store is not None and model is not None:
            warm = [h for h in candidates
                    if store.is_resident(h.index, model)]
            if warm:
                self.last_warm = True
                return min(warm,
                           key=lambda h: (h.outstanding(), h.index))
        self.last_warm = False
        # nothing warm (or no store/model): least-outstanding — the
        # cold install lands where there's slack to absorb it
        return min(candidates, key=lambda h: (h.outstanding(), h.index))

    def forget(self, replica_index: int):
        if self.model_store is not None:
            self.model_store.forget_replica(replica_index)


POLICIES = {
    RoundRobinPolicy.name: RoundRobinPolicy,
    LeastOutstandingPolicy.name: LeastOutstandingPolicy,
    PrefixAffinityPolicy.name: PrefixAffinityPolicy,
    ModelAffinityPolicy.name: ModelAffinityPolicy,
}


def make_policy(policy, page_size: int = 16,
                store: Optional[FleetPrefixStore] = None,
                model_store=None) -> DispatchPolicy:
    """Accepts a policy NAME (see `POLICIES`) or an instance.
    `page_size` seeds prefix-affinity hashing and must match the
    engines' page size for warmth tracking to mirror their tries.
    `store` (role-aware fleets) attaches the fleet-wide prefix store
    to prefix-affinity warmth tracking; `model_store` (multi-model
    fleets) attaches the fleet model store to model-affinity."""
    if isinstance(policy, DispatchPolicy):
        if store is not None and isinstance(policy,
                                            PrefixAffinityPolicy) \
                and policy.store is None:
            policy.store = store
        if model_store is not None \
                and isinstance(policy, ModelAffinityPolicy) \
                and policy.model_store is None:
            policy.model_store = model_store
        return policy
    if policy in POLICIES:
        if policy == PrefixAffinityPolicy.name:
            return PrefixAffinityPolicy(page_size=page_size,
                                        store=store)
        if policy == ModelAffinityPolicy.name:
            return ModelAffinityPolicy(model_store=model_store)
        return POLICIES[policy]()
    raise ValueError(f"unknown dispatch policy {policy!r}: "
                     f"{sorted(POLICIES)} or a DispatchPolicy instance")
