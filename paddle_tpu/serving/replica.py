"""Replica supervision: one engine behind a health state machine.

A `ReplicaHandle` wraps one :class:`ContinuousBatchingEngine` the way a
fleet supervisor wraps a serving process: the engine object stands in
for a whole replica (its HBM-resident KV pool included), and the handle
tracks whether that replica should receive traffic at all.

Health state machine (driven by the router's injectable clock — no
wall-clock reads, so every transition is forcible in tests)::

    HEALTHY --consecutive failures >= degraded_after--> DEGRADED
    DEGRADED --one successful step--> HEALTHY
    DEGRADED --consecutive failures >= dead_after--> DEAD
    HEALTHY|DEGRADED --no step progress for wedge_timeout s
                       while work is outstanding--> DEAD   ("wedged")
    any live state --drain()--> DRAINING
    DRAINING --in-flight work reaches zero--> DEAD         ("drained")
    DEAD --router restart after exponential backoff--> HEALTHY

Gray-failure arm (ISSUE 14, docs/serving.md "Gray failures") — the
states above all describe LIVENESS; these describe CORRECTNESS, and
only exist on fleets with a canary configured (`ServingRouter(
sentry=, canary=)`)::

    HEALTHY|DEGRADED --numeric sentry trip--> SUSPECT
    SUSPECT --canary passes with a clean sentry window--> HEALTHY
    SUSPECT --canary token mismatch, or max_suspect_rounds
              dirty passes--> QUARANTINED
    QUARANTINED --backoff restart--> PROBATION
    DEAD --backoff restart (canary-gated fleets)--> PROBATION
    PROBATION --canary passes--> HEALTHY       (restart budget resets)
    PROBATION --canary token mismatch--> QUARANTINED

SUSPECT replicas keep stepping their in-flight work (the streams are
re-verified if quarantine lands) but accept nothing new, donate no
migrations, and their terminals PARK until the canary's verdict — a
tainted stream must not finalize. QUARANTINED is DEAD-shaped (the
engine is discarded: a corrupt chip's state is untrustworthy) but
distinct, so operators can tell corruption from crash; it restarts on
the SAME backoff ladder and re-enters through PROBATION, where it must
reproduce the canary's golden stream before taking real traffic — and
ONLY a passed canary (or real served work) resets the restart budget,
closing the PR-4 hole where an idle restarted replica sat HEALTHY
without ever proving it works.

Death is SIGKILL-shaped: the engine object is DISCARDED the moment the
replica dies (``self.engine = None``) — its queues, slots, and KV pages
are unrecoverable, exactly as if the serving process had been killed.
Zero-loss failover therefore lives one layer up: the router mirrors
every replica's token stream as it is produced (the tokens a real
router would have streamed to clients already) and re-prefills
survivors from that mirror (`router.py`).

Fault sites (`utils.faults`): ``router.dispatch`` fires before a
request is handed to the engine; ``router.step`` fires before a step of
a replica that has outstanding work (so `nth=`/`times=` arming can
target one replica of a fleet deterministically — idle replicas do not
consume visits); ``router.health`` fires inside every health probe.
"""
from __future__ import annotations

import random
from typing import Callable, List, Optional

from .. import observability as telemetry
from ..distributed.launch import restart_backoff
from ..models.serving import ContinuousBatchingEngine, Request
from ..utils.faults import fault_point

__all__ = ["ReplicaHandle", "ReplicaState", "ReplicaRole",
           "ReplicaOpRefused"]


class ReplicaOpRefused(RuntimeError):
    """A manual scaling primitive (`drain`/`restore`) was refused
    because the replica's current state makes the operation ambiguous
    — e.g. restoring a replica that is still draining, or draining one
    whose canary verdict is unresolved. Typed so operators (and the
    autoscaler, which drives these primitives in a loop) can tell a
    refusal from a crash; plain repeats of an already-applied
    operation are idempotent no-ops instead (ISSUE 16)."""


class ReplicaRole:
    """Disaggregated serving roles (ISSUE 8, router.py `roles=`):
    `prefill` replicas take fresh admissions and hand finished
    prefills to the KV transfer plane, `decode` replicas receive
    migrated pages and run the decode loop, `colocated` does both (the
    PR-4 default). Roles steer SCHEDULING only — every engine keeps
    both capabilities, which is what lets failover re-prefill stranded
    work on ANY survivor, role notwithstanding."""

    PREFILL = "prefill"
    DECODE = "decode"
    COLOCATED = "colocated"
    ALL = frozenset({PREFILL, DECODE, COLOCATED})
    # fresh submits may land here; decode replicas only take migrations
    PREFILL_CAPABLE = frozenset({PREFILL, COLOCATED})


class ReplicaState:
    """Replica health states + the numeric encoding exported on the
    `pdt_router_replica_state` gauge (0-3: the liveness ladder,
    higher = less healthy; 4-6: the gray-failure arm, appended so the
    PR-4 encodings stay stable)."""

    HEALTHY = "healthy"
    DEGRADED = "degraded"
    DRAINING = "draining"
    DEAD = "dead"
    # gray-failure arm (module docstring): correctness, not liveness
    SUSPECT = "suspect"
    QUARANTINED = "quarantined"
    PROBATION = "probation"
    LIVE = frozenset({HEALTHY, DEGRADED, DRAINING, SUSPECT, PROBATION})
    # engine discarded, restart pending on the backoff ladder
    DOWN = frozenset({DEAD, QUARANTINED})
    # gauge encoding: docs/serving.md "Fleet" metric catalog
    CODE = {HEALTHY: 0, DEGRADED: 1, DRAINING: 2, DEAD: 3,
            SUSPECT: 4, QUARANTINED: 5, PROBATION: 6}


_M_STATE = telemetry.gauge(
    "pdt_router_replica_state",
    "Replica health state (0=healthy 1=degraded 2=draining 3=dead "
    "4=suspect 5=quarantined 6=probation).",
    ("replica",))
_M_QDEPTH = telemetry.gauge(
    "pdt_router_replica_queue_depth",
    "Outstanding (waiting + running) requests per replica.",
    ("replica",))
_M_RESTARTS = telemetry.counter(
    "pdt_router_replica_restarts_total",
    "Replica restarts after death, by replica.", ("replica",))


class ReplicaHandle:
    """One engine + its health state (see module docstring).

    `engine_factory(index)` builds a fresh engine — called at
    construction and again on every restart, so a restarted replica
    comes back with empty queues and a cold KV pool, like a respawned
    process. When a `submesh` is attached (TP fleets) the factory is
    called as `engine_factory(index, submesh)` instead, so every
    incarnation is built on the SAME device slice. Restart pacing reuses the elastic launcher's
    `restart_backoff` shape (exponential, jittered via the injectable
    `rng`, capped) expressed as a *next-restart deadline* on the
    injectable clock rather than a sleep — the router is step-driven.
    """

    def __init__(self, index: int,
                 engine_factory: Callable[..., ContinuousBatchingEngine],
                 *, clock: Callable[[], float],
                 degraded_after: int = 1,
                 dead_after: int = 3,
                 wedge_timeout: Optional[float] = None,
                 max_outstanding: Optional[int] = None,
                 restart_backoff_base: float = 1.0,
                 restart_backoff_max: float = 60.0,
                 max_restarts: Optional[int] = 5,
                 rng: Optional[random.Random] = None,
                 role: str = ReplicaRole.COLOCATED,
                 submesh=None,
                 sentry_config=None,
                 probation_gate: bool = False):
        if role not in ReplicaRole.ALL:
            raise ValueError(f"unknown replica role {role!r}: "
                             f"{sorted(ReplicaRole.ALL)}")
        self.role = role
        # tensor parallelism (serving/submesh.py): the replica's device
        # slice. It belongs to the SLOT, not the engine incarnation —
        # a restarted replica comes back on the SAME submesh, so
        # replica identity is (submesh, generation)
        self.submesh = submesh
        # transfer-plane traffic (survives restarts — the counters
        # describe the SLOT in the fleet, not one engine incarnation)
        self.migrations_in = 0
        self.migrations_out = 0
        self.index = int(index)
        self._factory = engine_factory
        self._clock = clock
        self.degraded_after = int(degraded_after)
        self.dead_after = int(dead_after)
        self.wedge_timeout = wedge_timeout
        self.max_outstanding = max_outstanding
        self._backoff_base = float(restart_backoff_base)
        self._backoff_cap = float(restart_backoff_max)
        self.max_restarts = max_restarts
        self._rng = rng if rng is not None else random.Random(index)
        # -- gray-failure defense (ISSUE 14, serving/sentry.py) --------
        # sentry_config builds one NumericSentry per engine INCARNATION
        # (attached in _build_engine); probation_gate=True (set by a
        # router with a canary) makes every restart land in PROBATION —
        # canary-gated readmission — instead of HEALTHY
        self.sentry_config = sentry_config
        self.probation_gate = bool(probation_gate)
        self.sentry = None
        self.sentry_seen = 0          # trips the router has acted on
        self.canary = None            # in-flight canary probe state
        self.canary_seq = 0
        self.last_canary_start: Optional[float] = clock()
        self.last_canary_pass: Optional[float] = None
        self.suspect_rounds = 0       # consecutive dirty canary passes
        self.canary_runs = 0
        self.canary_failures = 0
        # terminals harvested while SUSPECT: (FleetRequest, Request)
        # pairs the router parks until the canary's verdict
        self.parked: List[tuple] = []
        self.engine: Optional[ContinuousBatchingEngine] = \
            self._build_engine()
        # bumped on every restart: a request dispatched to generation g
        # is STRANDED once the handle runs generation g+1 — the fresh
        # engine never heard of it, however alive the replica looks
        self.generation = 0
        self.state = ReplicaState.HEALTHY
        self.consecutive_failures = 0
        self.last_error: Optional[str] = None
        self.death_reason: Optional[str] = None
        self.restarts = 0                  # completed restarts
        self.restart_attempt = 0           # backoff exponent (resets on
        self._stabilizing = False          # first post-restart success)
        self.next_restart_time: Optional[float] = None
        self.auto_restart = True           # False for drained replicas
        self.last_progress = clock()
        # prefix-cache + speculation counters folded in from engines
        # this handle has already discarded, so fleet aggregates
        # survive replica death
        self.retired_prefix_hits = 0
        self.retired_prefix_tokens_reused = 0
        self.retired_sentry_trips = 0
        self.retired_spec = {"rounds": 0, "proposed": 0, "accepted": 0,
                             "degraded": 0}
        _M_STATE.set(ReplicaState.CODE[self.state], replica=str(index))

    def _build_engine(self) -> ContinuousBatchingEngine:
        """Factory invocation, submesh-aware: a TP fleet's factory
        takes (index, submesh) — the router carved the slice and every
        incarnation of this replica lives on it. Every incarnation
        gets its replica index as the engine `fault_tag` (corrupt-mode
        drills pin a sick chip to one replica, utils/faults.py) and,
        on sentried fleets, a FRESH NumericSentry — a restarted
        replica's trip history must not follow it."""
        if self.submesh is not None:
            eng = self._factory(self.index, self.submesh)
        else:
            eng = self._factory(self.index)
        eng.fault_tag = str(self.index)
        self.sentry = None
        self.sentry_seen = 0
        if self.sentry_config is not None:
            from .sentry import NumericSentry
            self.sentry = NumericSentry(
                self.sentry_config,
                vocab_size=eng.model.config.vocab_size,
                replica=self.index)
            eng.attach_sentry(self.sentry)
        return eng

    # -- introspection ---------------------------------------------------
    def outstanding(self) -> int:
        """Waiting + running requests on this replica (0 when dead)."""
        if self.engine is None:
            return 0
        info = self.engine.lifecycle_info()
        return info["waiting"] + info["running"]

    def pending_harvest(self) -> int:
        """Dispatches in the engine's deferred-harvest window that no
        host state has seen yet (0 when dead or on the synchronous
        harvest_every=1 loop) — the operator-visible depth of the
        bounded-staleness window (ISSUE 18)."""
        if self.engine is None:
            return 0
        return len(getattr(self.engine, "_pending", ()))

    def real_outstanding(self) -> int:
        """`outstanding()` minus an in-flight canary probe: the
        did-work ledger (restart-budget resets, busy-step accounting)
        must not count infra probes as served traffic — a canary
        RUNNING proves nothing, only its PASS does."""
        n = self.outstanding()
        if n and self.canary is not None and self.engine is not None \
                and self.canary["generation"] == self.generation \
                and self.engine.get_request(self.canary["rid"]) \
                is not None:
            n -= 1
        return n

    def can_accept(self) -> bool:
        """Eligible for NEW dispatches: healthy/degraded with room in
        the bounded per-replica queue. Draining and dead replicas never
        accept (failover force-dispatch uses `alive()` instead)."""
        if self.state not in (ReplicaState.HEALTHY, ReplicaState.DEGRADED):
            return False
        return (self.max_outstanding is None
                or self.outstanding() < self.max_outstanding)

    def alive(self) -> bool:
        return self.state in ReplicaState.LIVE and self.engine is not None

    def prefix_hits(self) -> int:
        live = self.engine.prefix_hits if self.engine is not None else 0
        return self.retired_prefix_hits + live

    def prefix_tokens_reused(self) -> int:
        live = (self.engine.prefix_tokens_reused
                if self.engine is not None else 0)
        return self.retired_prefix_tokens_reused + live

    def sentry_trips(self) -> int:
        """Numeric-sentry trips for this replica SLOT (live sentry +
        retired incarnations) — the fleet aggregate must keep the
        evidence that explained a quarantine after the engine (and
        its sentry) were discarded by it."""
        live = self.sentry.trips if self.sentry is not None else 0
        return self.retired_sentry_trips + live

    def spec_info(self) -> dict:
        """Speculative-decoding counters for this replica SLOT (live
        engine + retired incarnations): a killed spec replica's
        acceptance history must survive into the fleet aggregate."""
        out = dict(self.retired_spec)
        if self.engine is not None:
            live = self.engine.spec_info()
            for k in out:
                out[k] += live[k]
        out["acceptance_rate"] = out["accepted"] / max(out["proposed"],
                                                       1)
        return out

    # -- traffic ---------------------------------------------------------
    def dispatch(self, prompt: List[int], max_new_tokens: int,
                 request_id: str,
                 deadline: Optional[float] = None,
                 max_queue_time: Optional[float] = None,
                 priority: int = 0,
                 adapter: Optional[str] = None) -> Request:
        """Hand one request to this replica's engine; returns the live
        engine Request so the router can mirror its token stream.
        `priority` is the QoS lane's engine queue priority (lane-aware
        ordering, models/serving.py); `adapter` is the LoRA adapter
        the request decodes under (multi-model fleets — the router
        made it resident via the model store before dispatching)."""
        fault_point("router.dispatch")
        assert self.engine is not None, f"dispatch to dead replica " \
                                        f"{self.index}"
        rid = self.engine.add_request(prompt, max_new_tokens,
                                      deadline=deadline,
                                      max_queue_time=max_queue_time,
                                      request_id=request_id,
                                      priority=priority,
                                      adapter=adapter)
        req = self.engine.get_request(rid)
        assert req is not None
        return req

    def step(self) -> List[Request]:
        """One engine step. The `router.step` fault site fires only when
        this replica has outstanding work, so chaos tests can target a
        specific busy replica with visit counting. Busy steps run under
        a `router.replica_step` span carrying the replica index, so
        every engine span inside (prefill, decode) has a replica
        ancestor — that is how the Chrome-trace exporter assigns
        pid=replica to engine-side work."""
        if not self.outstanding():
            return self.engine.step()
        fault_point("router.step")
        with telemetry.span("router.replica_step", replica=self.index,
                            generation=self.generation):
            return self.engine.step()

    # -- health state machine --------------------------------------------
    def _transition(self, state: str, reason: str):
        if state == self.state:
            return
        prev, self.state = self.state, state
        _M_STATE.set(ReplicaState.CODE[state], replica=str(self.index))
        telemetry.event("router.replica_state", replica=self.index,
                        prev=prev, state=state, reason=reason)

    def note_success(self, now: float, did_work: bool = True):
        """A step completed: progress happened, failures stop counting,
        a DEGRADED replica recovers. The restart-backoff budget resets
        only when the step served REAL work (`did_work`) — an idle tick
        after a restart proves nothing, and resetting on it would let a
        dies-under-load replica restart forever. SUSPECT and PROBATION
        never clear here: a step that merely COMPLETED is liveness
        evidence, and those states question correctness — only a
        canary verdict moves them (`note_canary_pass`)."""
        self.consecutive_failures = 0
        self.last_progress = now
        if self._stabilizing and did_work:
            self._stabilizing = False
            self.restart_attempt = 0       # backoff resets once stable
        if self.state == ReplicaState.DEGRADED:
            self._transition(ReplicaState.HEALTHY, "recovered")

    def note_failure(self, now: float, error: BaseException) -> bool:
        """A step / dispatch / health probe failed. Returns True when
        the failure killed the replica (caller must fail over)."""
        self.consecutive_failures += 1
        self.last_error = f"{type(error).__name__}: {error}"
        if self.state in ReplicaState.DOWN:
            return False
        if self.consecutive_failures >= self.dead_after:
            self.die("failures", now)
            return True
        if self.state == ReplicaState.HEALTHY \
                and self.consecutive_failures >= self.degraded_after:
            self._transition(ReplicaState.DEGRADED, self.last_error)
        return False

    # -- gray-failure arm (module docstring; ISSUE 14) -------------------
    def mark_suspect(self, reason: str):
        """A numeric sentry tripped on this replica's data: stop
        taking new work, keep stepping what is in flight (its stream
        is re-verified if quarantine lands), and let the router run a
        canary immediately. Only HEALTHY/DEGRADED replicas move —
        draining or down replicas are already on their way out."""
        if self.state in (ReplicaState.HEALTHY, ReplicaState.DEGRADED):
            self._transition(ReplicaState.SUSPECT, reason)

    def note_canary_pass(self, now: float):
        """A canary reproduced the golden stream with a clean sentry
        window: suspicion lifts, probation ends, and — the ISSUE-14
        restart-budget rule — a restarted replica's backoff budget
        resets HERE (proof of correct work), not on an idle tick."""
        self.last_canary_pass = now
        self.suspect_rounds = 0
        if self.state == ReplicaState.SUSPECT:
            self._transition(ReplicaState.HEALTHY, "canary_pass")
        elif self.state == ReplicaState.PROBATION:
            self._transition(ReplicaState.HEALTHY, "probation_pass")
            self._stabilizing = False
            self.restart_attempt = 0

    def check_health(self, now: float):
        """Health probe, run by the router once per step tick. Raises
        (counted as a failure by the caller) when the armed
        `router.health` fault site fires; kills the replica directly
        when it is WEDGED — outstanding work but no step progress for
        `wedge_timeout` seconds on the injectable clock."""
        if not self.alive():
            return
        fault_point("router.health")
        if self.wedge_timeout is not None and self.outstanding() > 0 \
                and now - self.last_progress > self.wedge_timeout:
            self.die("wedged", now)

    def drain(self) -> bool:
        """Stop dispatching to this replica; in-flight work completes,
        then the replica parks DEAD (reason `drained`) without
        auto-restart — `ServingRouter.restore_replica` brings it back.
        auto_restart drops immediately: a replica that dies MID-drain
        (wedge, failure storm) must stay decommissioned too, not
        restart itself back into traffic.

        Idempotence contract (ISSUE 16): draining a DRAINING replica
        is a no-op (returns False); draining a DOWN replica cancels
        any pending auto-restart — "drained" means "stay out" — and
        returns False; draining a SUSPECT/PROBATION replica raises
        :class:`ReplicaOpRefused` (the canary must rule first: a
        drain would let a possibly-tainted stream finalize as a
        normal drain-out). Returns True only when this call started
        the drain."""
        if self.state in (ReplicaState.HEALTHY, ReplicaState.DEGRADED):
            self.auto_restart = False
            self._transition(ReplicaState.DRAINING, "drain requested")
            return True
        if self.state == ReplicaState.DRAINING:
            return False                       # idempotent repeat
        if self.state in ReplicaState.DOWN:
            # decommission: a dead replica told to drain must not
            # restart itself back into traffic
            self.auto_restart = False
            self.next_restart_time = None
            return False
        raise ReplicaOpRefused(
            f"replica {self.index} is {self.state}: the canary must "
            "rule before it can drain (quarantine or restore it "
            "instead)")

    def finish_drain_if_empty(self, now: float):
        if self.state == ReplicaState.DRAINING and self.outstanding() == 0:
            self.auto_restart = False
            self.die("drained", now)

    def die(self, reason: str, now: float,
            to_state: str = ReplicaState.DEAD):
        """SIGKILL-shaped death: the engine object (queues, slots, KV
        pool) is discarded outright. The router re-routes this
        replica's in-flight requests from its own mirror.
        ``to_state=QUARANTINED`` is the gray-failure flavor — same
        discard and same backoff ladder (a corrupt chip's engine
        state is untrustworthy, exactly like a killed process's), but
        a distinct state so corruption reads differently from crash."""
        if self.state in ReplicaState.DOWN:
            return
        if self.engine is not None:        # fold counters before discard
            self.retired_prefix_hits += self.engine.prefix_hits
            self.retired_prefix_tokens_reused += \
                self.engine.prefix_tokens_reused
            live_spec = self.engine.spec_info()
            for k in self.retired_spec:
                self.retired_spec[k] += live_spec[k]
        self.engine = None
        if self.sentry is not None:
            # fold trips like the prefix/spec counters above: the
            # evidence trail that EXPLAINS a quarantine must survive
            # the engine discard it causes
            self.retired_sentry_trips += self.sentry.trips
        self.sentry = None                 # died with its incarnation
        self.canary = None
        self.suspect_rounds = 0
        self.death_reason = reason
        self._transition(to_state, reason)
        _M_QDEPTH.set(0, replica=str(self.index))
        if self.auto_restart and (self.max_restarts is None
                                  or self.restart_attempt
                                  < self.max_restarts):
            self.restart_attempt += 1
            delay = restart_backoff(self.restart_attempt,
                                    self._backoff_base,
                                    self._backoff_cap, self._rng)
            self.next_restart_time = now + delay
            telemetry.event("router.replica_death", replica=self.index,
                            reason=reason, restart_in_s=delay,
                            attempt=self.restart_attempt)
        else:
            self.next_restart_time = None  # permanently out
            telemetry.event("router.replica_death", replica=self.index,
                            reason=reason, restart_in_s=None,
                            attempt=self.restart_attempt)

    def maybe_restart(self, now: float) -> bool:
        """Restart a dead/quarantined replica once its backoff
        deadline passes: fresh engine from the factory, cold caches.
        Canary-gated fleets (`probation_gate`) land EVERY restart in
        PROBATION — no real traffic, and no restart-budget reset,
        until a canary passes (the ISSUE-14 readmission rule; without
        a canary there is nothing to gate with, so plain fleets keep
        the PR-4 HEALTHY + real-work-resets semantics). Returns True
        when a restart happened this tick."""
        if self.state not in ReplicaState.DOWN \
                or self.next_restart_time is None \
                or now < self.next_restart_time:
            return False
        self.engine = self._build_engine()
        self.generation += 1
        self.consecutive_failures = 0
        self.death_reason = None
        self.next_restart_time = None
        self.last_progress = now
        self.restarts += 1
        self._stabilizing = True
        if self.probation_gate:
            self._transition(ReplicaState.PROBATION, "restarted")
        else:
            self._transition(ReplicaState.HEALTHY, "restarted")
        _M_RESTARTS.inc(replica=str(self.index))
        telemetry.event("router.replica_restart", replica=self.index,
                        restarts=self.restarts)
        return True

    def restore(self, now: float) -> bool:
        """Manually bring back a drained (or permanently dead) replica:
        immediate fresh engine, no backoff — an operator action, not a
        crash recovery. Canary-gated fleets still route the fresh
        engine through PROBATION — operators cannot waive the proof.

        Idempotence contract (ISSUE 16): restoring a replica that is
        already live is a no-op (returns False); restoring one that is
        still DRAINING raises :class:`ReplicaOpRefused` — the two
        intents conflict, and silently un-draining would race the
        drain's completion. Wait for the drain to park it DEAD, or
        kill it, then restore. Returns True when a fresh engine came
        up."""
        if self.state == ReplicaState.DRAINING:
            raise ReplicaOpRefused(
                f"replica {self.index} is still draining: wait for "
                "the drain to finish (or kill it) before restoring")
        if self.state not in ReplicaState.DOWN:
            return False                       # already live: no-op
        self.auto_restart = True
        self.restart_attempt = 0
        self.next_restart_time = now
        self.maybe_restart(now)
        return True

    def start_in_probation(self, reason: str = "scale_up"):
        """Canary-gated fleets route a freshly ADDED replica (scale-up,
        ISSUE 16) through PROBATION exactly like a restarted one: no
        real traffic until its canary reproduces the golden stream.
        No-op on fleets without a canary (nothing to gate with)."""
        if self.probation_gate and self.state == ReplicaState.HEALTHY:
            self._stabilizing = True
            self._transition(ReplicaState.PROBATION, reason)

    def update_gauges(self):
        _M_QDEPTH.set(self.outstanding(), replica=str(self.index))
