"""Crash-durable router write-ahead journal (ISSUE 13).

Every layer below the router survives SIGKILL — replica failover
(PR 4), torn-checkpoint quarantine (PR 3), duplicate-never-lose
migration (PR 7) — but the router itself held every in-flight
`FleetRequest`, its mirrored token stream, and all QoS context in
process memory: kill the control plane and accepted work vanished
silently. This module closes that last zero-loss gap with a
write-ahead journal of the exact state the router already mirrors:

* **submit** — the durability point. `ServingRouter.submit()` appends
  the request (prompt, budget, lane/tenant/priority, absolute
  deadline) BEFORE any dispatch, so a crash at any later instant is
  recoverable. A submit the fleet then refused appends a `rejected`
  record — replay must not resurrect work the client saw refused.
* **progress** — one batched record per router step tick holding the
  NEW tokens each live request streamed since the last mirror (the
  journal diffs against its own state table, so the router just hands
  it the full mirrors). Greedy decoding makes these records an
  OPTIMIZATION, not a durability requirement: a lost progress suffix
  re-generates bit-identically from the folded re-prefill.
* **terminal** — final status + the complete token stream, appended at
  the router's single terminal transition. Recovery restores these
  WITHOUT re-execution (idempotent-per-request_id, the transfer-plane
  contract) so a finished response is redeliverable until
  `release_request` appends the `release` that lets compaction drop it.
* **resize_intent / resize_commit** — the two-phase fleet-topology
  records behind `ServingRouter.resize()` (ISSUE 16): the INTENT
  (full target topology: replica count, roles mix, tp carve) is
  durable BEFORE any fleet mutation, the COMMIT after the last one.
  Replay resolves deterministically: an open INTENT without its
  COMMIT rolls FORWARD (recovery rebuilds the fleet on the intended
  topology and appends the closing COMMIT), so a SIGKILL at any
  instant mid-resize recovers into exactly the old topology (killed
  before the INTENT reached disk) or the new one (any later instant)
  — never a half-resized fleet. Compaction preserves the resolved
  state in one ``topology`` record.
* **rewind** — the ONE exception to the append-only mirror contract
  (ISSUE 14, docs/serving.md "Gray failures"): a gray-failure
  quarantine dropped a request's TAINTED token suffix (streamed since
  the corrupt replica's last clean canary), and the journal must
  forget it too — replay truncates the request's stream to the
  journaled verified length, so a recovery that lands between the
  quarantine and the request's terminal re-prefills from the verified
  prefix, never the tainted one. Durable like a terminal (a LOST
  rewind would resurrect tainted tokens at recovery — the flush/fsync
  rung below).

Wire format — append-only segments of checksummed, length-prefixed
records::

    <u32 payload_len> <u32 crc32(payload)> <payload: compact JSON>

Segments (``seg-%08d.wal``) rotate at `segment_bytes`; every journal
OPEN starts a fresh segment rather than appending after a possibly
torn tail. Compaction (`compact()`, auto-triggered after
`compact_finalized` terminals) condenses the whole journal into one
``snap`` record per retained request — live requests keep their
folded state, un-released terminals keep their final stream, released
terminals drop — written to a ``.tmp`` sibling and committed with one
atomic ``os.replace`` (`commit_bytes`, the tmp+rename helper the
PDT007 durable-write lint points everything else at), after which the
superseded segments delete. A crash anywhere in that window replays
consistently: ``snap`` records override earlier state, and stray
``.tmp`` files are ignored.

Torn-tail tolerance (the `parse_done` tradition, docs/checkpointing.md):
a truncated or checksum-failing record ends its segment's replay —
the committed prefix is recovered, the tear is COUNTED
(`pdt_journal_corrupt_tail_total` + the replay result's
`corrupt_dropped`), and nothing raises. `tests/test_journal.py`
fuzzes a truncation at every byte offset of the final record.

Durability knob — ``fsync=``:

* ``"step"``     — flush + fsync after every append (every mirror tick
  pays a disk round-trip; the strongest guarantee, the bench's worst
  case);
* ``"terminal"`` — submit/terminal/rejected records flush + fsync (the
  default: the real durability points); progress/release records ride
  the write buffer and reach the OS at the next durable record,
  segment rotation, compaction, or close — so a crash of ANY kind may
  lose only a progress suffix, which greedy recovery re-generates
  bit-identically;
* ``"off"``      — like ``"terminal"`` minus the fsyncs (tests, A/B
  benches): durable kinds still flush, so a process SIGKILL keeps
  every accepted submit and delivered terminal, but an OS crash may
  lose the tail.

Fault sites ``journal.append`` / ``journal.replay`` (utils/faults.py)
make both halves killable in chaos tests: the router treats a submit
append fault as a failed submit (nothing was dispatched), counts any
other append fault (`pdt_journal_append_failures_total` +
`journal.append_failed` — recovery then re-derives the lost suffix by
re-execution), and a replay fault propagates to the `recover()`
caller — recovery must never silently pretend an unreadable journal
was empty.

Telemetry: `pdt_journal_*` counters/histogram (docs/observability.md)
plus the `journal.replay` span recovery runs under.
"""
from __future__ import annotations

import json
import os
import struct
import time
import zlib
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

from .. import observability as telemetry
from ..utils.faults import fault_point

__all__ = ["RouterJournal", "JournalReplay", "ReplayedRequest",
           "commit_bytes", "note_append_failure", "note_recovered",
           "note_deduped", "observe_recovery_seconds"]

_HEADER = struct.Struct("<II")
# a length prefix beyond any sane record is treated as tail corruption
# (a torn header can decode to garbage lengths; reading gigabytes off
# it would turn one flipped byte into an OOM)
_MAX_RECORD = 64 << 20

FSYNC_MODES = ("step", "terminal", "off")
# record kinds whose loss breaks a durability contract — under
# fsync="terminal" only these pay the disk round-trip
_DURABLE_KINDS = frozenset({"submit", "terminal", "rejected",
                            "rewind", "resize_intent",
                            "resize_commit"})

_M_RECORDS = telemetry.counter(
    "pdt_journal_records_total",
    "Records appended to the router write-ahead journal, by kind "
    "(`terminal` reconciles exactly with "
    "pdt_router_requests_terminal_total on a journal-attached router).",
    ("kind",))
_M_BYTES = telemetry.counter(
    "pdt_journal_bytes_total",
    "Bytes appended to the router journal (headers included).")
_M_FSYNCS = telemetry.counter(
    "pdt_journal_fsyncs_total",
    "fsync() calls issued by the journal under its durability policy.")
_M_COMPACTIONS = telemetry.counter(
    "pdt_journal_compactions_total",
    "Journal compactions (finalized-request history condensed into "
    "one atomically-committed snapshot segment).")
_M_APPEND_FAILURES = telemetry.counter(
    "pdt_journal_append_failures_total",
    "Journal appends that failed on a non-durability-critical path "
    "(progress/terminal/release) — counted and survived; recovery "
    "re-derives the lost suffix by re-execution.")
_M_CORRUPT_TAIL = telemetry.counter(
    "pdt_journal_corrupt_tail_total",
    "Truncated or checksum-failing tail records dropped at replay "
    "(one count per torn segment tail, never fatal).")
_M_REPLAY_RECOVERED = telemetry.counter(
    "pdt_journal_replay_recovered_total",
    "Un-finalized requests rehydrated onto fresh replicas by "
    "ServingRouter.recover().")
_M_REPLAY_DEDUPED = telemetry.counter(
    "pdt_journal_replay_deduped_total",
    "Already-finished request_ids recovery restored WITHOUT "
    "re-execution (idempotent-per-request_id dedupe).")
_M_RECOVERY_SECONDS = telemetry.histogram(
    "pdt_journal_recovery_seconds",
    "Wall time of one ServingRouter.recover() rehydration (replay + "
    "re-dispatch), on the router clock.")


def note_append_failure(error: BaseException, where: str) -> None:
    """Count one survived append failure (progress/terminal/release —
    NOT the submit durability point, which raises). Shared by every
    router call site so the counter means one thing (PDT006: counted
    and evented, never silently swallowed)."""
    _M_APPEND_FAILURES.inc()
    telemetry.event("journal.append_failed", where=where,
                    error=f"{type(error).__name__}: {error}")


def note_recovered(n: int = 1) -> None:
    if n:
        _M_REPLAY_RECOVERED.inc(n)


def note_deduped(n: int = 1) -> None:
    if n:
        _M_REPLAY_DEDUPED.inc(n)


def observe_recovery_seconds(dt: float) -> None:
    _M_RECOVERY_SECONDS.observe(dt)


def _fsync_dir(path: str) -> None:
    """fsync a DIRECTORY: file-level fsync makes a file's bytes
    durable but not its directory ENTRY — a newly created (or renamed,
    or deleted) name can vanish on an OS crash even though the inode's
    contents were fsync'd. Every durability point below that changes
    the segment directory's name set follows up with one of these."""
    fd = os.open(path, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def commit_bytes(path: str, data: bytes, *, fsync: bool = True) -> None:
    """Atomic whole-file commit: write `data` to ``path + ".tmp"``,
    fsync, then ``os.replace`` over `path` (and fsync the parent
    directory so the rename itself survives an OS crash) — the
    tmp+rename discipline every durable write under serving/ must use
    when it is not a journal append (pdt-lint PDT007,
    docs/static_analysis.md). A crash leaves either the old file or
    the new one, never a torn mix."""
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(data)
        f.flush()
        if fsync:
            os.fsync(f.fileno())
    os.replace(tmp, path)
    if fsync:
        _fsync_dir(os.path.dirname(os.path.abspath(path)))


def _encode(obj: dict) -> bytes:
    payload = json.dumps(obj, separators=(",", ":"),
                         sort_keys=True).encode("utf-8")
    return _HEADER.pack(len(payload),
                        zlib.crc32(payload) & 0xFFFFFFFF) + payload


def _decode_stream(blob: bytes) -> tuple:
    """Decode one segment's records. Returns (records, torn): `torn`
    is True when trailing bytes existed but did not form a complete,
    checksum-valid record — the torn-tail rule drops them (and
    anything after, which is unreachable without a valid length
    prefix anyway)."""
    records, off, n = [], 0, len(blob)
    while off < n:
        if n - off < _HEADER.size:
            return records, True              # torn header
        length, crc = _HEADER.unpack_from(blob, off)
        if length > _MAX_RECORD or off + _HEADER.size + length > n:
            return records, True              # torn / garbage length
        payload = blob[off + _HEADER.size:off + _HEADER.size + length]
        if zlib.crc32(payload) & 0xFFFFFFFF != crc:
            return records, True              # checksum fail
        try:
            records.append(json.loads(payload.decode("utf-8")))
        except (UnicodeDecodeError, json.JSONDecodeError):
            return records, True              # crc collision / garbage
        off += _HEADER.size + length
    return records, False


@dataclass
class ReplayedRequest:
    """One request's journal-derived state after replay."""

    request_id: str
    prompt: List[int]
    max_new_tokens: int
    lane: str = "interactive"
    tenant: Optional[str] = None
    priority: int = 0
    model: Optional[str] = None            # canonical model_id
    deadline_abs: Optional[float] = None   # journal/router clock
    max_queue_time: Optional[float] = None
    tokens: List[int] = field(default_factory=list)
    status: Optional[str] = None           # None = still live
    error: Optional[str] = None
    released: bool = False

    @property
    def live(self) -> bool:
        return self.status is None


@dataclass
class JournalReplay:
    """The outcome of one `RouterJournal.replay()`: `live` and
    `finished` preserve journal (= submit) order; `corrupt_dropped`
    counts torn segment tails (never fatal)."""

    live: Dict[str, ReplayedRequest]
    finished: Dict[str, ReplayedRequest]
    records: int = 0
    segments: int = 0
    corrupt_dropped: int = 0
    rejected: int = 0
    # resolved two-phase resize state: `topology` is the fleet shape
    # recovery must rebuild (None = whatever the caller constructs),
    # `resize_rolled_forward` marks an INTENT whose COMMIT never
    # landed — recovery applies it and appends the closing COMMIT
    topology: Optional[dict] = None
    resize_seq: int = 0
    resize_rolled_forward: bool = False


class RouterJournal:
    """Append-only write-ahead journal for one `ServingRouter`
    (module docstring). `path` is a DIRECTORY of segments; opening an
    existing path always starts a fresh segment (never appends after
    a possibly-torn tail) and leaves every earlier segment for
    `replay()`.

    Deadline clock semantics: journaled `deadline_abs` values are
    meaningful only against the clock of the incarnation that wrote
    them (`time.monotonic` epochs are per-process). `replay()`
    therefore RE-ANCHORS every live deadline as
    remaining-time-at-last-journal-write: each incarnation's records
    form one "boot run" (the first `open` a journal instance writes
    carries a ``boot`` marker), the replayer tracks the latest clock
    stamp inside each run, computes ``remaining = deadline_abs -
    last_stamp_of_that_run`` and rewrites ``deadline_abs =
    recovering_clock() + remaining``. A slow restart can no longer
    mass-expire live requests (dead time between incarnations burns
    no deadline budget), while a deadline that had already expired at
    the crash (negative remaining) still finalizes as an honest
    TIMEOUT — and the two incarnations no longer need to share a
    clock source."""

    def __init__(self, path: str, *, fsync: str = "terminal",
                 segment_bytes: int = 1 << 20,
                 compact_finalized: Optional[int] = 256,
                 clock: Optional[Callable[[], float]] = None):
        if fsync not in FSYNC_MODES:
            raise ValueError(f"fsync must be one of {FSYNC_MODES}, "
                             f"got {fsync!r}")
        if segment_bytes < 1:
            raise ValueError(f"segment_bytes must be >= 1, got "
                             f"{segment_bytes}")
        if compact_finalized is not None and compact_finalized < 1:
            raise ValueError("compact_finalized must be >= 1 or None, "
                             f"got {compact_finalized}")
        self.path = str(path)
        self.fsync = fsync
        self.segment_bytes = int(segment_bytes)
        self.compact_finalized = compact_finalized
        self._clock = clock if clock is not None else time.monotonic
        os.makedirs(self.path, exist_ok=True)
        self._state: Dict[str, ReplayedRequest] = {}
        self._finalized_since_compact = 0
        self._file = None
        # two-phase resize state: each is {"seq": int, "topology":
        # dict} or None; an open intent without its commit rolls
        # FORWARD at replay (class docstring)
        self._resize_intent: Optional[dict] = None
        self._resize_committed: Optional[dict] = None
        self._booted = False
        self._seg_index = self._max_segment_index()
        self._open_segment()

    # -- segments --------------------------------------------------------
    def _segments(self) -> List[str]:
        out = [fn for fn in os.listdir(self.path)
               if fn.startswith("seg-") and fn.endswith(".wal")]
        return sorted(out)

    def _max_segment_index(self) -> int:
        idx = 0
        for fn in self._segments():
            try:
                idx = max(idx, int(fn[4:-4]))
            except ValueError:
                continue                       # foreign file: ignore
        return idx

    def _seg_path(self, index: int) -> str:
        return os.path.join(self.path, f"seg-{index:08d}.wal")

    def _open_segment(self):
        if self._file is not None:
            self._file.close()
        self._seg_index += 1
        self._file = open(self._seg_path(self._seg_index), "ab")
        if self.fsync != "off":
            # make the segment's directory ENTRY durable before any
            # fsync'd record inside it can matter: without this an OS
            # crash could drop the whole file, fsync'd submits included
            _fsync_dir(self.path)
        self._seg_written = 0
        rec = {"kind": "open", "v": 1, "segment": self._seg_index,
               "t": self._clock()}
        if not self._booted:
            # the FIRST open of this journal instance marks a fresh
            # process incarnation: replay partitions records into
            # boot runs at these markers so deadlines re-anchor
            # against the right clock epoch (class docstring)
            rec["boot"] = True
            self._booted = True
        self._write(rec)

    # -- the append path -------------------------------------------------
    def _write(self, obj: dict):
        blob = _encode(obj)
        self._file.write(blob)
        self._seg_written += len(blob)
        kind = obj["kind"]
        _M_RECORDS.inc(kind=kind)
        _M_BYTES.inc(len(blob))
        # flush policy mirrors the fsync ladder one level down: DURABLE
        # kinds always reach the OS page cache immediately (a SIGKILL
        # of the process must never lose an accepted submit or a
        # delivered terminal — fsync is about the OS dying), while
        # progress/release records ride the stdio buffer under
        # "terminal"/"off" and land wholesale at the next durable
        # flush, rotation, compaction, or close (the buffer is FIFO,
        # so a flush commits every earlier record too). A process kill
        # can then lose only a buffered progress suffix, which greedy
        # recovery re-generates bit-identically — the flush syscall
        # was the decode hot path's single biggest journal cost
        # (~140 us cold, vs ~2 us of buffered write).
        if self.fsync == "step":
            self._file.flush()
            os.fsync(self._file.fileno())
            _M_FSYNCS.inc()
        elif kind in _DURABLE_KINDS:
            self._file.flush()
            if self.fsync == "terminal":
                os.fsync(self._file.fileno())
                _M_FSYNCS.inc()

    def _append(self, obj: dict):
        fault_point("journal.append")
        if self._seg_written >= self.segment_bytes:
            self._open_segment()
        self._write(obj)

    def append_submit(self, *, request_id: str, prompt: List[int],
                      max_new_tokens: int, lane: str = "interactive",
                      tenant: Optional[str] = None, priority: int = 0,
                      model: Optional[str] = None,
                      deadline_abs: Optional[float] = None,
                      max_queue_time: Optional[float] = None) -> None:
        """The durability point: called by `ServingRouter.submit()`
        BEFORE dispatch. Raises on failure — work the journal cannot
        record must not be accepted. `model` is the canonical model_id
        (multi-model fleets): durable at submit so recovery restores
        the request onto the RIGHT weights."""
        self._append({"kind": "submit", "rid": str(request_id),
                      "prompt": [int(t) for t in prompt],
                      "max_new_tokens": int(max_new_tokens),
                      "lane": lane, "tenant": tenant,
                      "priority": int(priority),
                      "model": model,
                      "deadline_abs": deadline_abs,
                      "max_queue_time": max_queue_time,
                      "t": self._clock()})
        self._state[str(request_id)] = ReplayedRequest(
            str(request_id), [int(t) for t in prompt],
            int(max_new_tokens), lane=lane, tenant=tenant,
            priority=int(priority), model=model,
            deadline_abs=deadline_abs,
            max_queue_time=max_queue_time)

    def append_rejected(self, request_id: str) -> None:
        """The submit was journaled but the fleet then refused it:
        replay must drop the id entirely (the client saw the 429)."""
        self._append({"kind": "rejected", "rid": str(request_id)})
        self._state.pop(str(request_id), None)

    def step_mirror(self, mirrors: Dict[str, List[int]]) -> int:
        """One batched progress record per router step — which on the
        pipelined decode loop (engine `harvest_every=k`, ISSUE 18)
        means one GROUP-COMMIT per harvest window: mirrors only move
        at harvest ticks, every step in between diffs empty and
        appends NOTHING, so the per-record encode/fsync cost amortizes
        over the whole window's tokens. `mirrors` maps request_id ->
        the FULL token stream mirrored so far; the journal records
        only each stream's new suffix (token mirrors are append-only
        by the router's fold-in contract). Returns the number of
        requests with new tokens (0 = nothing appended)."""
        delta: Dict[str, List[int]] = {}
        for rid, tokens in mirrors.items():
            st = self._state.get(str(rid))
            have = len(st.tokens) if st is not None else 0
            if len(tokens) > have:
                delta[str(rid)] = [int(t) for t in tokens[have:]]
        if not delta:
            return 0
        # the stamp tightens deadline re-anchoring to one-tick
        # granularity: time the router spent ALIVE burns deadline
        # budget even when no durable record landed in between
        self._append({"kind": "progress", "d": delta,
                      "t": self._clock()})
        for rid, toks in delta.items():
            st = self._state.get(rid)
            if st is not None:
                st.tokens.extend(toks)
        return len(delta)

    def rewind(self, request_id: str, length: int) -> None:
        """Truncate a request's journaled token stream to `length` —
        the gray-failure quarantine path (module docstring: the one
        exception to the append-only mirror contract). Later
        `step_mirror` calls then diff against the truncated stream, so
        the healthy replica's regenerated suffix journals at the
        RIGHT offsets, and a replay that lands before the request's
        terminal recovers the verified prefix only."""
        rid = str(request_id)
        self._append({"kind": "rewind", "rid": rid,
                      "len": max(0, int(length))})
        st = self._state.get(rid)
        if st is not None and st.status is None:
            # finalized streams are authoritative (terminal records
            # carry the COMPLETE stream) — same guard as replay
            st.tokens = st.tokens[:max(0, int(length))]

    def append_terminal(self, request_id: str, status: str,
                        tokens: List[int],
                        error: Optional[str] = None) -> None:
        """Final status + the COMPLETE stream, so a recovered router
        can redeliver a finished response without re-execution."""
        rid = str(request_id)
        self._append({"kind": "terminal", "rid": rid, "status": status,
                      "tokens": [int(t) for t in tokens],
                      "error": error, "t": self._clock()})
        st = self._state.get(rid)
        if st is None:
            st = ReplayedRequest(rid, [], 0)
            self._state[rid] = st
        st.status = status
        st.tokens = [int(t) for t in tokens]
        st.error = error
        self._finalized_since_compact += 1
        if self.compact_finalized is not None \
                and self._finalized_since_compact \
                >= self.compact_finalized:
            self.compact()

    def append_release(self, request_id: str) -> None:
        """The terminal response was delivered and acknowledged
        (`ServingRouter.release_request`): compaction may now drop the
        request entirely."""
        rid = str(request_id)
        self._append({"kind": "release", "rid": rid})
        st = self._state.get(rid)
        if st is not None:
            if st.status is not None:
                self._state.pop(rid, None)
            else:
                st.released = True

    # -- two-phase fleet resize ------------------------------------------
    def append_resize_intent(self, seq: int, topology: dict) -> None:
        """Durable INTENT for one `ServingRouter.resize()` — appended
        BEFORE any fleet mutation (module docstring). `topology` is
        the full target: ``{"num_replicas": int, "roles": [...] |
        None, "tp": int | None}``. Raises on failure — a resize the
        journal cannot record must not start."""
        self._append({"kind": "resize_intent", "seq": int(seq),
                      "topology": dict(topology),
                      "t": self._clock()})
        self._resize_intent = {"seq": int(seq),
                               "topology": dict(topology)}

    def append_resize_commit(self, seq: int) -> None:
        """Durable COMMIT closing the matching INTENT — appended after
        the last fleet mutation of the resize (or by recovery after
        rolling an open intent forward)."""
        self._append({"kind": "resize_commit", "seq": int(seq),
                      "t": self._clock()})
        if self._resize_intent is not None \
                and self._resize_intent["seq"] == int(seq):
            self._resize_committed = self._resize_intent
        self._resize_intent = None

    # -- compaction ------------------------------------------------------
    def compact(self) -> int:
        """Condense the journal: one ``snap`` record per retained
        request (live state, or an un-released terminal's final
        stream), committed as a fresh segment via tmp+rename
        (`commit_bytes`), after which every earlier segment deletes.
        Returns the number of requests retained. Crash-safe at every
        point: before the rename the old segments rule; after it the
        snapshot overrides them on replay; segment deletes are
        idempotent."""
        blob = bytearray()
        blob += _encode({"kind": "open", "v": 1,
                         "segment": self._seg_index + 1,
                         "compacted": True, "t": self._clock()})
        retained = 0
        for rid, st in self._state.items():
            blob += _encode({
                "kind": "snap", "rid": rid, "prompt": st.prompt,
                "max_new_tokens": st.max_new_tokens, "lane": st.lane,
                "tenant": st.tenant, "priority": st.priority,
                "model": st.model,
                "deadline_abs": st.deadline_abs,
                "max_queue_time": st.max_queue_time,
                "tokens": st.tokens, "status": st.status,
                "error": st.error})
            retained += 1
        topo_snapped = (self._resize_intent is not None
                        or self._resize_committed is not None)
        if topo_snapped:
            # resolved resize state must survive segment deletion
            blob += _encode({"kind": "topology",
                             "committed": self._resize_committed,
                             "intent": self._resize_intent,
                             "t": self._clock()})
        old = self._segments()
        self._seg_index += 1
        commit_bytes(self._seg_path(self._seg_index), bytes(blob),
                     fsync=self.fsync != "off")
        _M_RECORDS.inc(kind="open")
        if retained:
            _M_RECORDS.inc(retained, kind="snap")
        if topo_snapped:
            _M_RECORDS.inc(kind="topology")
        _M_BYTES.inc(len(blob))
        if self.fsync != "off":
            _M_FSYNCS.inc()
        # the commit landed: the active segment (in `old`) and every
        # earlier one are superseded by the snapshot
        self._file.close()
        self._file = None
        for fn in old:
            try:
                os.remove(os.path.join(self.path, fn))
            except OSError:
                pass         # a lagging delete re-runs next compaction
        self._open_segment()
        self._finalized_since_compact = 0
        _M_COMPACTIONS.inc()
        telemetry.event("journal.compacted", retained=retained,
                        segments_dropped=len(old))
        return retained

    # -- replay ----------------------------------------------------------
    def replay(self) -> JournalReplay:
        """Rebuild the journal's request table from disk (the
        recovering incarnation's view). Torn or checksum-failing
        segment tails are dropped and counted, NEVER fatal; `snap`
        records override earlier state (a crash between a compaction
        commit and its segment deletes replays consistently). Also
        refreshes this journal's own state table, so a recovered
        router keeps compacting correctly."""
        fault_point("journal.replay")
        table: Dict[str, ReplayedRequest] = {}
        records = corrupt = rejected = 0
        # deadline re-anchoring (class docstring): `boot` counts boot
        # runs, `last_t` the latest clock stamp seen inside each, and
        # `deadline_boot` the run whose clock defined each request's
        # current deadline_abs (its submit — or snap, which a
        # compacting incarnation rewrote into its own epoch)
        boot = 0
        last_t: Dict[int, float] = {}
        deadline_boot: Dict[str, int] = {}
        intent: Optional[dict] = None
        committed: Optional[dict] = None
        resize_seq = 0
        segments = self._segments()
        for fn in segments:
            with open(os.path.join(self.path, fn), "rb") as f:
                recs, torn = _decode_stream(f.read())
            if torn:
                corrupt += 1
                _M_CORRUPT_TAIL.inc()
                telemetry.event("journal.corrupt_tail", segment=fn,
                                committed_records=len(recs))
            for rec in recs:
                records += 1
                kind = rec.get("kind")
                if kind == "open" and rec.get("boot"):
                    boot += 1
                t = rec.get("t")
                if t is not None:
                    last_t[boot] = float(t)  # appends are clock-ordered
                if kind == "open":
                    if rec.get("v") != 1:
                        raise ValueError(
                            f"journal segment {fn} has version "
                            f"{rec.get('v')!r}; this reader speaks "
                            "v1 only")
                elif kind in ("submit", "snap"):
                    st = ReplayedRequest(
                        rec["rid"], list(rec.get("prompt") or ()),
                        int(rec["max_new_tokens"]),
                        lane=rec.get("lane") or "interactive",
                        tenant=rec.get("tenant"),
                        priority=int(rec.get("priority") or 0),
                        model=rec.get("model"),
                        deadline_abs=rec.get("deadline_abs"),
                        max_queue_time=rec.get("max_queue_time"))
                    if kind == "snap":
                        st.tokens = list(rec.get("tokens") or ())
                        st.status = rec.get("status")
                        st.error = rec.get("error")
                    table[st.request_id] = st
                    deadline_boot[st.request_id] = boot
                elif kind == "progress":
                    for rid, toks in rec.get("d", {}).items():
                        st = table.get(rid)
                        if st is not None and st.status is None:
                            st.tokens.extend(int(t) for t in toks)
                elif kind == "rewind":
                    # quarantine dropped a tainted suffix: the replay
                    # stream forgets it exactly like the live mirror
                    st = table.get(rec["rid"])
                    if st is not None and st.status is None:
                        st.tokens = st.tokens[:max(
                            0, int(rec.get("len") or 0))]
                elif kind == "terminal":
                    st = table.get(rec["rid"])
                    if st is None:
                        st = ReplayedRequest(rec["rid"], [], 0)
                        table[rec["rid"]] = st
                    st.status = rec["status"]
                    st.tokens = list(rec.get("tokens") or ())
                    st.error = rec.get("error")
                elif kind == "rejected":
                    table.pop(rec["rid"], None)
                    rejected += 1
                elif kind == "release":
                    st = table.get(rec["rid"])
                    if st is not None:
                        if st.status is not None:
                            table.pop(rec["rid"], None)
                        else:
                            st.released = True
                elif kind == "resize_intent":
                    intent = {"seq": int(rec.get("seq") or 0),
                              "topology": rec.get("topology")}
                    resize_seq = max(resize_seq, intent["seq"])
                elif kind == "resize_commit":
                    seq = int(rec.get("seq") or 0)
                    if intent is not None and intent["seq"] == seq:
                        committed = intent
                    intent = None
                    resize_seq = max(resize_seq, seq)
                elif kind == "topology":
                    committed = rec.get("committed")
                    intent = rec.get("intent")
                    for s in (committed, intent):
                        if s is not None:
                            resize_seq = max(resize_seq,
                                             int(s.get("seq") or 0))
        # re-anchor live deadlines onto the recovering clock: the
        # remaining budget at the writing incarnation's last journal
        # write carries over; dead time between incarnations burns
        # nothing (class docstring)
        now = self._clock()
        for rid, st in table.items():
            if st.status is None and st.deadline_abs is not None:
                t_ref = last_t.get(deadline_boot.get(rid, boot))
                if t_ref is not None:
                    st.deadline_abs = now + (st.deadline_abs - t_ref)
        live = {rid: st for rid, st in table.items() if st.live}
        finished = {rid: st for rid, st in table.items()
                    if not st.live}
        self._state = table
        self._finalized_since_compact = 0
        self._resize_intent = intent
        self._resize_committed = committed
        target = intent if intent is not None else committed
        return JournalReplay(live=live, finished=finished,
                             records=records, segments=len(segments),
                             corrupt_dropped=corrupt,
                             rejected=rejected,
                             topology=(None if target is None
                                       else target.get("topology")),
                             resize_seq=resize_seq,
                             resize_rolled_forward=intent is not None)

    # -- introspection / lifecycle ---------------------------------------
    def stats(self) -> Dict[str, object]:
        segs = self._segments()
        nbytes = 0
        for fn in segs:
            try:
                nbytes += os.path.getsize(os.path.join(self.path, fn))
            except OSError:
                pass
        live = sum(1 for st in self._state.values() if st.live)
        return {"path": self.path, "segments": len(segs),
                "bytes": nbytes, "fsync": self.fsync,
                "tracked_requests": len(self._state),
                "tracked_live": live}

    def flush(self) -> None:
        """Push any buffered non-durable records (progress/release
        under ``fsync="terminal"``/``"off"``) to the OS — a manual
        durability barrier between the fsync ladder's rungs."""
        if self._file is not None:
            self._file.flush()

    def close(self):
        if self._file is not None:
            self._file.close()
            self._file = None

    def __enter__(self) -> "RouterJournal":
        return self

    def __exit__(self, *exc) -> bool:
        self.close()
        return False
