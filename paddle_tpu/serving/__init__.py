"""Multi-replica serving fleet: the layer above the batching engine.

`models/serving.py` is one engine — one compiled decode program, one KV
page pool, one process worth of HBM. This package scales it
horizontally and makes its death survivable, the way TPU serving
deployments actually run (a replica fleet behind a router — Ragged
Paged Attention, arXiv:2604.15464; Gemma serving on Cloud TPU,
arXiv:2605.25645):

* `replica.py`  — `ReplicaHandle`: one engine under a health state
  machine (HEALTHY -> DEGRADED -> DRAINING -> DEAD) with SIGKILL-shaped
  death and backoff-paced restarts.
* `policy.py`   — pluggable dispatch (`round_robin`,
  `least_outstanding`, `prefix_affinity` — co-locate page-aligned
  shared prefixes with the replica whose prefix-cache trie is warm).
* `router.py`   — `ServingRouter`: deterministic step-driven admission
  through bounded per-replica queues (`FleetOverloaded` + retry-after),
  replica supervision via the `router.*` fault sites, and ZERO-LOSS
  failover (streamed tokens fold into a survivor's re-prefill — the
  engine-preemption recovery shape, one level up).
* `transfer.py` — the KV page transfer plane (ISSUE 8): serialize a
  finished prefill's pages + request state out of one engine and
  install them into another's paged cache, the disaggregated
  prefill/decode hand-off (`transfer.serialize`/`transfer.install`
  fault sites, `pdt_transfer_*` telemetry).
* `prefix_store.py` — the fleet-wide prefix store: page-aligned chain
  hashes shared across replicas (replacing per-replica warmth sets for
  role-aware fleets) with host-RAM spill for cold chains, so a warm
  prefix outlives the replicas that computed it.
* `submesh.py`  — tensor-parallel replicas (ISSUE 12): one replica =
  one GSPMD submesh carved from the global device set
  (`ServingRouter(tp=...)`), Megatron column/row weight shardings +
  KV pages sharded over the head axis (one logical page = tp local
  shards), exact-mode determinism fences keeping tp>=2 greedy outputs
  bit-identical to tp=1, and per-shard migration payload fragments.
* `admission.py` — the QoS admission brain (ISSUE 11): interactive vs
  batch priority lanes, sliding-window per-tenant token budgets, and
  SLO-arbitrated load shedding (the PR-5 burn-rate engine decides
  WHEN to shed, lane/tenant ordering decides WHO) with one
  `derive_retry_after` semantics across every refusal surface; fails
  OPEN to plain FIFO when the controller itself breaks.

* `sentry.py`   — the gray-failure defense (ISSUE 14): per-dispatch
  numeric sentries (sampled-token in-vocab every step, amortized
  every-Nth-step logit finiteness/abs-max scan) and canary probes (a
  fixed prompt's golden greedy stream replayed through each replica
  on a schedule and on suspicion — greedy is batching-invariant, so
  a mismatch is PROOF of corruption). The router grows
  SUSPECT -> QUARANTINED on top of the health machine, drops tainted
  token suffixes and re-serves them from healthy replicas, and gates
  every restart through canary PROBATION.

* `model_store.py` — the multi-model serving plane (ISSUE 17):
  `FleetModelStore` makes model identity a first-class fleet
  dimension — registered full checkpoints and LoRA adapters over a
  shared base, per-replica resident sets with byte-budgeted LRU
  install/evict through the engine's `install_weights` /
  `install_adapter` seam, `model_id`/`split_model_id` as THE
  canonical model-identity spelling (pdt-lint PDT010).
  `ServingRouter(model_store=...)` + `submit(model=)` route by model
  (the `model_affinity` policy prefers warm replicas, cold installs
  fall back through the store), requests for different LoRA
  fine-tunes batch into ONE ragged dispatch (`ops/lora_epilogue.py`),
  and per-hosted-model canary goldens keep the gray-failure arm
  grading every replica against ITS model's stream.

* `autoscaler.py` — the elastic control plane (ISSUE 16):
  `FleetAutoscaler`, a deterministic step-driven loop observing
  arrival rate / queue depth / SLO burn and steering replica count,
  the prefill:decode roles mix, and the tp carve through
  `ServingRouter.resize()` — every transition a two-phase
  INTENT/COMMIT journal transaction (SIGKILL mid-resize recovers into
  old or new topology, zero lost tokens), scale-down drains via
  migration, scale-up lands in canary PROBATION, with hysteresis +
  cooldown + max-step flapping guards and degraded-mode refusals
  while any replica is QUARANTINED or the journal is failing.

* `journal.py`  — the crash-durable control plane (ISSUE 13): a
  checksummed, length-prefixed write-ahead journal of submits
  (BEFORE dispatch — the durability point), per-step token-progress
  mirrors, and terminals, with atomic tmp+rename compaction and
  torn-tail-tolerant replay; `ServingRouter.recover(journal, ...)`
  rebuilds a SIGKILLed router with zero loss and greedy outputs
  bit-identical to an uninterrupted fleet.

Telemetry rides `pdt_router_*` / `pdt_transfer_*` /
`pdt_prefix_store_*` (docs/serving.md "Fleet" + "Disaggregation");
every future scale layer (autoscaling, multi-host replicas) builds on
this one.

    from paddle_tpu.serving import ServingRouter

    router = ServingRouter(lambda i: ContinuousBatchingEngine(model),
                           roles="prefill:2,decode:2",
                           policy="prefix_affinity", page_size=16)
    rid = router.submit(prompt, max_new_tokens=64)
    outputs = router.run()          # {request_id: tokens}
"""
from .admission import (AdmissionDecision, Lane,  # noqa: F401
                        QosAdmission, TenantBudget, budget_key,
                        derive_retry_after)
from .model_store import (FleetModelStore, model_id,  # noqa: F401
                          split_model_id)
from .policy import (DispatchPolicy, LeastOutstandingPolicy,  # noqa: F401
                     ModelAffinityPolicy, POLICIES,
                     PrefixAffinityPolicy, RoundRobinPolicy,
                     make_policy)
from .prefix_store import FleetPrefixStore, chain_hashes  # noqa: F401
from .replica import (ReplicaHandle, ReplicaOpRefused,  # noqa: F401
                      ReplicaRole, ReplicaState)
from .autoscaler import (AutoscaleObservation,  # noqa: F401
                         AutoscalePolicy, FleetAutoscaler)
from .journal import (JournalReplay, ReplayedRequest,  # noqa: F401
                      RouterJournal, commit_bytes)
from .submesh import (SubMesh, TP_AXIS, TpConfig,  # noqa: F401
                      carve_submeshes)
from .router import (FleetOverloaded, FleetRequest,  # noqa: F401
                     QosShed, ServingRouter, parse_roles)
from .sentry import (CanaryConfig, NumericSentry,  # noqa: F401
                     SentryConfig)
from .transfer import (TransferStageTimeout,  # noqa: F401
                       install_request, migrate_request,
                       payload_nbytes, serialize_request)

__all__ = [
    "ServingRouter", "FleetRequest", "FleetOverloaded", "QosShed",
    "parse_roles",
    "Lane", "QosAdmission", "TenantBudget", "AdmissionDecision",
    "budget_key", "derive_retry_after",
    "ReplicaHandle", "ReplicaState", "ReplicaRole", "ReplicaOpRefused",
    "FleetAutoscaler", "AutoscalePolicy", "AutoscaleObservation",
    "DispatchPolicy", "RoundRobinPolicy", "LeastOutstandingPolicy",
    "PrefixAffinityPolicy", "ModelAffinityPolicy", "POLICIES",
    "make_policy",
    "FleetPrefixStore", "chain_hashes",
    "FleetModelStore", "model_id", "split_model_id",
    "RouterJournal", "JournalReplay", "ReplayedRequest",
    "commit_bytes",
    "serialize_request", "install_request", "migrate_request",
    "payload_nbytes", "TransferStageTimeout",
    "SentryConfig", "NumericSentry", "CanaryConfig",
    "SubMesh", "TP_AXIS", "TpConfig", "carve_submeshes",
]
