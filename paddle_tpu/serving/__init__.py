"""Multi-replica serving fleet: the layer above the batching engine.

`models/serving.py` is one engine — one compiled decode program, one KV
page pool, one process worth of HBM. This package scales it
horizontally and makes its death survivable, the way TPU serving
deployments actually run (a replica fleet behind a router — Ragged
Paged Attention, arXiv:2604.15464; Gemma serving on Cloud TPU,
arXiv:2605.25645):

* `replica.py`  — `ReplicaHandle`: one engine under a health state
  machine (HEALTHY -> DEGRADED -> DRAINING -> DEAD) with SIGKILL-shaped
  death and backoff-paced restarts.
* `policy.py`   — pluggable dispatch (`round_robin`,
  `least_outstanding`, `prefix_affinity` — co-locate page-aligned
  shared prefixes with the replica whose prefix-cache trie is warm).
* `router.py`   — `ServingRouter`: deterministic step-driven admission
  through bounded per-replica queues (`FleetOverloaded` + retry-after),
  replica supervision via the `router.*` fault sites, and ZERO-LOSS
  failover (streamed tokens fold into a survivor's re-prefill — the
  engine-preemption recovery shape, one level up).

Telemetry rides `pdt_router_*` (docs/serving.md "Fleet"); every future
scale layer (disaggregated prefill, autoscaling, multi-host replicas)
builds on this one.

    from paddle_tpu.serving import ServingRouter

    router = ServingRouter(lambda i: ContinuousBatchingEngine(model),
                           num_replicas=4, policy="prefix_affinity",
                           page_size=16)
    rid = router.submit(prompt, max_new_tokens=64)
    outputs = router.run()          # {request_id: tokens}
"""
from .policy import (DispatchPolicy, LeastOutstandingPolicy,  # noqa: F401
                     POLICIES, PrefixAffinityPolicy, RoundRobinPolicy,
                     make_policy)
from .replica import ReplicaHandle, ReplicaState  # noqa: F401
from .router import (FleetOverloaded, FleetRequest,  # noqa: F401
                     ServingRouter)

__all__ = [
    "ServingRouter", "FleetRequest", "FleetOverloaded",
    "ReplicaHandle", "ReplicaState",
    "DispatchPolicy", "RoundRobinPolicy", "LeastOutstandingPolicy",
    "PrefixAffinityPolicy", "POLICIES", "make_policy",
]
