"""QoS-tiered admission: priority lanes, tenant budgets, SLO-arbitrated
load shedding (ISSUE 11 — docs/serving.md "Admission & QoS").

Production fleets are not graded on whether they survive overload —
open-loop traffic guarantees they will be overloaded — but on WHO they
fail when they do. This module is the admission brain the router (and,
one level down, the engine's `admission_policy` hook) consults before
accepting work:

* **Priority lanes.** Every request rides a lane — `interactive`
  (latency-sensitive, protected) or `batch` (throughput work, shed
  first). The lane maps to the engine's queue priority
  (`Lane.PRIORITY`), so an admitted interactive request also *admits
  into a slot* ahead of queued batch work (models/serving.py
  lane-aware queue ordering) — batch can never starve interactive at
  either layer.
* **Tenant budgets.** `TenantBudget` meters admitted tokens
  (prompt + worst-case output, the same reservation currency as the
  engine's page admission) over a sliding window: charges expire
  `window_s` after their admit tick, which IS the refill — no
  separate refill clock. Over-budget tenants are the first shed.
* **SLO-arbitrated shedding.** The PR-5 burn-rate engine decides WHEN
  to shed: while `shed_objective`'s burn rate (fraction of the error
  budget being consumed) is >= `shed_burn`, the controller sheds —
  and the lane/tenant ordering decides WHO: over-budget tenants
  first (any lane), then the whole batch lane. In-budget interactive
  traffic is never QoS-shed; only hard backpressure
  (`FleetOverloaded`) can refuse it.
* **One retry_after.** `derive_retry_after` is the single semantics
  for every refusal surface — router backpressure AND QoS shed — the
  strongest of the queue-drain estimate, the burn-proportional
  backoff, and any pending-restart wait, floored at `base` and capped.
* **Fail OPEN.** The `admission.decide` fault site makes the
  controller killable in chaos tests; every caller (router submit,
  engine hook) degrades a controller failure to plain FIFO admission
  — QoS is an optimization, and a broken brain must never wedge
  submits (`pdt_admission_failopen_total` + `admission.failopen`
  keep the degradation visible).

Deterministic: clock-injectable throughout (PDT001), the burn
evaluation is cached on the same clock (`reeval_interval_s`), and
nothing here reads wall time — the loadgen soak drives it in virtual
time.

Telemetry: `pdt_admission_*` (docs/observability.md). Admissions are
counted at COMMIT (after the fleet accepted the request), so the
ledger reconciles exactly with the router's terminal counters:
``admit decisions == fleet terminal requests`` once the fleet drains
(recipes/fleet_soak.py asserts this) — refusals between the admit
verdict and dispatch (`fleet_full`, request-shaped rejections) book
nothing, and fail-OPEN admissions are deliberately outside the
ledger (visible via `pdt_admission_failopen_total` instead).
"""
from __future__ import annotations

import time
from collections import deque
from dataclasses import dataclass, field
from typing import Callable, Deque, Dict, Optional, Tuple

from .. import observability as telemetry
from ..utils.faults import fault_point

__all__ = ["Lane", "TenantBudget", "AdmissionDecision", "QosAdmission",
           "budget_key", "derive_retry_after", "note_failopen"]


class Lane:
    """QoS lanes and their engine queue priorities (lower admits
    first). `interactive` is the protected latency lane; `batch` is
    throughput work that sheds first under SLO burn."""

    INTERACTIVE = "interactive"
    BATCH = "batch"
    ALL = frozenset({INTERACTIVE, BATCH})
    PRIORITY = {INTERACTIVE: 0, BATCH: 1}

    @classmethod
    def of_priority(cls, priority: int) -> str:
        return cls.INTERACTIVE if priority <= 0 else cls.BATCH


_M_DECISIONS = telemetry.counter(
    "pdt_admission_decisions_total",
    "QoS admission decisions, by lane and verdict.",
    ("lane", "decision"))
_M_SHED = telemetry.counter(
    "pdt_admission_shed_total",
    "QoS sheds by lane and arbitration reason.", ("lane", "reason"))
_M_RETRY_AFTER = telemetry.histogram(
    "pdt_admission_retry_after_seconds",
    "retry_after hints attached to QoS sheds.",
    buckets=(0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 15.0,
             60.0))
_M_BURN = telemetry.gauge(
    "pdt_admission_burn_rate",
    "The controller's cached arbitration burn rate (shed_objective).")
_M_OVER_BUDGET = telemetry.gauge(
    "pdt_admission_tenants_over_budget",
    "Tenants currently over their sliding-window token budget.")
_M_FAILOPEN = telemetry.counter(
    "pdt_admission_failopen_total",
    "Admission-controller failures degraded to plain FIFO admission.")


def derive_retry_after(base: float, *, queue_depth: int = 0,
                       burn_rate: float = 0.0,
                       restart_wait: Optional[float] = None,
                       cap: float = 60.0) -> float:
    """ONE retry_after semantics for every refusal surface (router
    backpressure and QoS shed — docs/serving.md "Admission & QoS"):
    the strongest of

    * the queue-drain estimate (``queue_depth * base``),
    * the burn backoff (``base * burn_rate`` — clients back off
      proportionally to how fast the SLO budget is burning),
    * the restart wait (seconds until the next replica returns),

    floored at ``base`` and capped at ``cap`` (an infinite burn must
    not tell clients to go away forever)."""
    hint = max(float(base), queue_depth * float(base),
               float(base) * max(float(burn_rate), 0.0))
    if restart_wait is not None:
        hint = max(hint, float(restart_wait))
    return min(hint, float(cap))


def note_failopen(error: BaseException, where: str) -> None:
    """Record one fail-open degradation (a broken/faulted admission
    controller answered by plain FIFO admission). Shared by the router
    submit path and the engine-hook wrapper so the counter means the
    same thing everywhere."""
    _M_FAILOPEN.inc()
    telemetry.event("admission.failopen", where=where,
                    error=f"{type(error).__name__}: {error}")


def budget_key(tenant: str, model: "Optional[str]" = None) -> str:
    """The tenant-budget map key: the tenant alone, or
    ``tenant@model`` on multi-model fleets — so QoS budgets meter per
    (tenant, model) and one tenant's burst on one fine-tune cannot
    starve its traffic on another. `model` must already be a CANONICAL
    model id (`serving.model_store.model_id` — pdt-lint PDT010), so
    this key can never fork from routing."""
    if model is None:
        return str(tenant)
    return f"{tenant}@{model}"


class TenantBudget:
    """Sliding-window token meter for one tenant: `charge()` records
    admitted tokens at a clock tick, charges expire `window_s` later
    (expiry IS the refill), `used()`/`over()` answer against the
    bound. O(1) amortized; deterministic on the injected clock."""

    def __init__(self, budget_tokens: int, window_s: float,
                 clock: Callable[[], float]):
        if budget_tokens < 1 or window_s <= 0:
            raise ValueError("budget_tokens must be >= 1 and window_s "
                             f"> 0, got {budget_tokens}/{window_s}")
        self.budget_tokens = int(budget_tokens)
        self.window_s = float(window_s)
        self._clock = clock
        self._charges: Deque[Tuple[float, int]] = deque()
        self._used = 0

    def _expire(self, now: float):
        cutoff = now - self.window_s
        while self._charges and self._charges[0][0] <= cutoff:
            self._used -= self._charges.popleft()[1]

    def charge(self, tokens: int, now: Optional[float] = None):
        now = self._clock() if now is None else now
        self._expire(now)
        self._charges.append((now, int(tokens)))
        self._used += int(tokens)

    def used(self, now: Optional[float] = None) -> int:
        self._expire(self._clock() if now is None else now)
        return self._used

    def over(self, now: Optional[float] = None) -> bool:
        return self.used(now) > self.budget_tokens


@dataclass
class AdmissionDecision:
    """One `QosAdmission.decide` verdict. `cost_tokens` is the
    reservation the caller commits against the tenant budget once the
    fleet actually accepted the request (`QosAdmission.commit`)."""

    admit: bool
    lane: str
    tenant: str
    reason: str = "ok"             # ok | burn | tenant_budget
    retry_after: float = 0.0
    burn_rate: float = 0.0
    cost_tokens: int = 0
    # canonical model id (multi-model fleets): commit() charges the
    # (tenant, model) budget this decision was arbitrated against
    model: Optional[str] = None


class QosAdmission:
    """The admission brain (module docstring). Decide/commit is
    two-phase on the router path: `decide()` arbitrates and counts the
    decision, the router calls `commit()` only after `_dispatch`
    succeeded — so a fleet_full refusal right after an admit verdict
    never charges the tenant for work the fleet refused.

    `slo_monitor` is the PR-5 `observability.slo.SloMonitor` the
    router already feeds; `shed_objective` names the objective whose
    BURN RATE arbitrates shedding (use a lane-scoped objective such as
    ``SloObjective("interactive_ttft_p95", "ttft.interactive", ...)``
    — the router feeds per-lane TTFT signals ``ttft.<lane>`` alongside
    the stock ``ttft``). Without a monitor the burn is 0 and nothing
    is ever QoS-shed (budgets may still shed with
    ``enforce_budgets="always"``).

    Budgets: `tenant_budget_tokens` is the default per-tenant bound
    (None = unlimited); `budgets` overrides per tenant. Unknown
    tenants inherit the default lazily.
    """

    def __init__(self, *, slo_monitor=None,
                 shed_objective: str = "ttft_p95",
                 shed_burn: float = 1.0,
                 tenant_budget_tokens: Optional[int] = None,
                 tenant_window_s: float = 60.0,
                 budgets: Optional[Dict[str, int]] = None,
                 enforce_budgets: str = "under_burn",
                 default_tenant: str = "anon",
                 retry_after_base: float = 0.05,
                 retry_after_cap: float = 60.0,
                 reeval_interval_s: float = 0.25,
                 clock: Optional[Callable[[], float]] = None):
        if enforce_budgets not in ("under_burn", "always"):
            raise ValueError("enforce_budgets must be 'under_burn' or "
                             f"'always', got {enforce_budgets!r}")
        if shed_burn <= 0:
            raise ValueError(f"shed_burn must be > 0, got {shed_burn}")
        if tenant_budget_tokens is not None \
                and int(tenant_budget_tokens) < 1:
            # fail HERE, not in the first lazy budget_for() — a commit
            # after dispatch must never be the place this surfaces
            raise ValueError("tenant_budget_tokens must be >= 1, got "
                             f"{tenant_budget_tokens}")
        self.slo_monitor = slo_monitor
        self.shed_objective = shed_objective
        self.shed_burn = float(shed_burn)
        self.default_budget_tokens = tenant_budget_tokens
        self.tenant_window_s = float(tenant_window_s)
        self._budget_overrides = dict(budgets or {})
        self.enforce_budgets = enforce_budgets
        self.default_tenant = default_tenant
        self.retry_after_base = float(retry_after_base)
        self.retry_after_cap = float(retry_after_cap)
        self.reeval_interval_s = float(reeval_interval_s)
        self._clock = clock if clock is not None else time.monotonic
        self._budgets: Dict[str, TenantBudget] = {}
        for name, tokens in self._budget_overrides.items():
            self._budgets[name] = TenantBudget(
                tokens, self.tenant_window_s, self._clock)
        # stats mirror of the pdt_admission_* counters, kept locally so
        # fleet_info/stats() work with telemetry disabled
        self.admitted: Dict[str, int] = {}
        self.shed: Dict[Tuple[str, str], int] = {}
        self._burn: float = 0.0
        self._burn_ts: Optional[float] = None
        self._over_gauge_ts: Optional[float] = None

    # -- burn arbitration ------------------------------------------------
    def current_burn(self, now: Optional[float] = None) -> float:
        """The shed objective's burn rate, re-evaluated at most every
        `reeval_interval_s` on the injected clock (an `evaluate()` per
        submit would make admission O(window) at soak rates)."""
        if self.slo_monitor is None:
            return 0.0
        now = self._clock() if now is None else now
        if self._burn_ts is None \
                or not 0 <= now - self._burn_ts < self.reeval_interval_s:
            st = self.slo_monitor.evaluate().get(self.shed_objective)
            self._burn = float(st.burn_rate) if st is not None else 0.0
            self._burn_ts = now
            _M_BURN.set(min(self._burn, 1e9))
        return self._burn

    def shedding(self, now: Optional[float] = None) -> bool:
        return self.current_burn(now) >= self.shed_burn

    def _over_count(self, now: float) -> int:
        out = 0
        for tenant, b in list(self._budgets.items()):
            if b.over(now):
                out += 1
            else:
                self._maybe_prune(tenant, b, now)
        return out

    def _refresh_over_gauge(self, now: float):
        """Keep `pdt_admission_tenants_over_budget` fresh from the
        DECISION path (a scrape must not depend on someone polling
        fleet_info), rate-limited on `reeval_interval_s` like the burn
        — the count is O(tenants with live charges)."""
        if self._over_gauge_ts is not None \
                and 0 <= now - self._over_gauge_ts \
                < self.reeval_interval_s:
            return
        self._over_gauge_ts = now
        _M_OVER_BUDGET.set(self._over_count(now))

    # -- tenant budgets --------------------------------------------------
    def budget_for(self, tenant: str) -> Optional[TenantBudget]:
        """The tenant's budget, creating one lazily from the default
        bound. Only COMMIT creates entries (an admitted request is
        about to charge); read paths use `_budgets.get` so shed
        verdicts and adversarial tenant strings never grow the map."""
        b = self._budgets.get(tenant)
        if b is None and self.default_budget_tokens is not None:
            b = TenantBudget(self.default_budget_tokens,
                             self.tenant_window_s, self._clock)
            self._budgets[tenant] = b
        return b

    def over_budget(self, tenant: str,
                    now: Optional[float] = None) -> bool:
        b = self._budgets.get(tenant)
        if b is None:
            return False               # no charges yet: cannot be over
        if not b.over(now):
            self._maybe_prune(tenant, b, now)
            return False
        return True

    def _maybe_prune(self, tenant: str, b: TenantBudget,
                     now: Optional[float]):
        """Drop a default-budget tenant whose window has fully
        drained — the map stays proportional to tenants with LIVE
        charges, not tenants ever seen (per-user tenant ids at
        million-user scale must not leak)."""
        if tenant not in self._budget_overrides and b.used(now) == 0:
            self._budgets.pop(tenant, None)

    # -- the decision ----------------------------------------------------
    def decide(self, *, prompt_tokens: int, max_new_tokens: int,
               lane: str = Lane.INTERACTIVE,
               tenant: Optional[str] = None,
               model: Optional[str] = None,
               queue_depth: int = 0) -> AdmissionDecision:
        """Arbitrate one submission. Never raises on the healthy path
        (shed is a RETURNED verdict, not an exception — the caller
        owns the refusal surface); the `admission.decide` fault site
        makes the controller itself killable, and every caller fails
        OPEN to plain FIFO admission (module docstring)."""
        fault_point("admission.decide")
        if lane not in Lane.ALL:
            raise ValueError(f"unknown lane {lane!r}: "
                             f"{sorted(Lane.ALL)}")
        tenant = tenant if tenant is not None else self.default_tenant
        now = self._clock()
        cost = int(prompt_tokens) + int(max_new_tokens)
        burn = self.current_burn(now)
        # per-(tenant, model) metering on multi-model fleets: the
        # budget consulted here is the one commit() later charges
        over = self.over_budget(budget_key(tenant, model), now)
        self._refresh_over_gauge(now)
        reason = None
        if burn >= self.shed_burn:
            if over:
                reason = "tenant_budget"
            elif lane == Lane.BATCH:
                reason = "burn"
        elif over and self.enforce_budgets == "always":
            reason = "tenant_budget"
        if reason is None:
            # the admit DECISION is not yet an admission: counters and
            # stats move in commit(), once the fleet actually accepted
            # — that is what keeps the admit ledger reconciling
            # EXACTLY with the router's terminal counters
            return AdmissionDecision(True, lane, tenant,
                                     burn_rate=burn, cost_tokens=cost,
                                     model=model)
        retry_after = derive_retry_after(
            self.retry_after_base, queue_depth=queue_depth,
            burn_rate=burn, cap=self.retry_after_cap)
        _M_DECISIONS.inc(lane=lane, decision="shed")
        _M_SHED.inc(lane=lane, reason=reason)
        _M_RETRY_AFTER.observe(retry_after)
        self.shed[(lane, reason)] = self.shed.get((lane, reason), 0) + 1
        telemetry.event("admission.shed", lane=lane, tenant=tenant,
                        reason=reason, burn_rate=round(burn, 4),
                        retry_after=retry_after)
        return AdmissionDecision(False, lane, tenant, reason=reason,
                                 retry_after=retry_after,
                                 burn_rate=burn, cost_tokens=cost,
                                 model=model)

    def commit(self, decision: AdmissionDecision,
               now: Optional[float] = None):
        """Book an ADMITTED decision the fleet actually accepted:
        count the admission (`pdt_admission_decisions_total{admit}` is
        a ledger of COMMITTED admissions, which is what makes it equal
        the router's terminal count once the fleet drains) and charge
        the tenant budget (reservation currency: prompt + worst-case
        output tokens, expiring with the sliding window). A dispatch
        refusal or request-shaped rejection between decide() and here
        books nothing anywhere in this ledger."""
        if not decision.admit:
            return
        _M_DECISIONS.inc(lane=decision.lane, decision="admit")
        self.admitted[decision.lane] = \
            self.admitted.get(decision.lane, 0) + 1
        b = self.budget_for(budget_key(decision.tenant,
                                       decision.model))
        if b is not None:
            b.charge(decision.cost_tokens, now)

    # -- the engine hook -------------------------------------------------
    def engine_policy(self):
        """An `admission_policy` callable for
        `ContinuousBatchingEngine(admission_policy=...)` — the same
        brain one layer down for direct-engine callers: lane inferred
        from the request's queue priority, tenant untracked (the
        engine has no tenant concept), decide+commit single-phase
        (nothing can refuse after the hook), and controller failures
        fail OPEN to plain FIFO exactly like the router path."""
        def policy(engine, req) -> bool:
            try:
                d = self.decide(
                    prompt_tokens=len(req.prompt),
                    max_new_tokens=req.max_new_tokens,
                    lane=Lane.of_priority(req.priority),
                    queue_depth=len(engine._queue))
            except Exception as e:
                note_failopen(e, where="engine.admission_policy")
                return True
            if d.admit:
                try:
                    self.commit(d)
                except Exception as e:
                    note_failopen(e, where="engine.admission_policy")
            return d.admit
        return policy

    # -- operator surface ------------------------------------------------
    def stats(self) -> Dict[str, object]:
        """The `fleet_info()["admission"]` section
        (observability/status.py renders it)."""
        now = self._clock()
        tenants = {}
        for name, b in list(self._budgets.items()):
            used = b.used(now)
            if used == 0 and name not in self._budget_overrides:
                self._budgets.pop(name, None)    # drained: prune
                continue
            tenants[name] = {"used_tokens": used,
                             "budget_tokens": b.budget_tokens,
                             "over": b.over(now)}
        over_now = sum(1 for t in tenants.values() if t["over"])
        _M_OVER_BUDGET.set(over_now)
        self._over_gauge_ts = now
        lanes = {}
        for lane in sorted(Lane.ALL):
            sheds = {r: n for (ln, r), n in sorted(self.shed.items())
                     if ln == lane}
            lanes[lane] = {"admitted": self.admitted.get(lane, 0),
                           "shed": sum(sheds.values()),
                           "shed_reasons": sheds}
        return {"objective": self.shed_objective,
                "burn_rate": self._burn,
                "shedding": self._burn >= self.shed_burn,
                "shed_burn": self.shed_burn,
                "lanes": lanes,
                "tenants": tenants,
                "tenants_over_budget": over_now}
