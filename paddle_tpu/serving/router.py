"""Health-aware fleet router over replica engines: prefix-affinity
dispatch, replica supervision, zero-loss failover.

The layer above `models/serving.py`: one `ServingRouter` fronts N
`ReplicaHandle`s (each wrapping a `ContinuousBatchingEngine`), the way
a TPU serving deployment fronts a replica fleet with a request router —
dispatch policy decides KV prefix-cache hit rate and tail latency, the
supervisor decides whether a replica kill is an outage or a blip.

Design (everything is step-driven and clock-injectable — deterministic
on the CPU test mesh, no threads, no sleeps inside `step()`):

* **Admission** — `submit()` routes through the pluggable policy
  (`policy.py`) over replicas that `can_accept()` (healthy/degraded
  with room in their bounded queue). When no replica can take the
  request the router sheds load FLEET-WIDE: `FleetOverloaded`
  (a subclass of the engine's `EngineOverloaded`, so front ends treat
  both as a 429) carrying a `retry_after` hint — queue-depth-derived
  when replicas are merely full, next-restart-derived when the whole
  fleet is down, and burn-boosted when a `QosAdmission` controller is
  attached (`admission.derive_retry_after` is the ONE retry_after
  semantics for every refusal surface).
* **QoS** — with `admission=QosAdmission(...)` (serving/admission.py,
  docs/serving.md "Admission & QoS") every submit carries a `lane`
  (interactive | batch) and optional `tenant`: the controller
  arbitrates by SLO burn rate + tenant budgets BEFORE dispatch and a
  shed surfaces as `QosShed` (a FleetOverloaded) with a burn-derived
  `retry_after`; admitted requests dispatch with their lane's engine
  queue priority, so interactive work admits into slots ahead of
  queued batch work. A controller failure (the `admission.decide`
  fault site) fails OPEN to plain FIFO admission — QoS never wedges
  submits.
* **Mirroring** — the router keeps a `FleetRequest` per submission and,
  after every replica step, copies the tokens each live engine Request
  has produced (`folded + output`). This is exactly the information a
  real router already holds — the tokens it streamed to the client —
  and it is what makes failover zero-loss without reading a dead
  engine.
* **Supervision** — each step tick: restart-due replicas come back
  (exponential backoff with jitter, the launcher's `restart_backoff`
  shape), health probes run (`router.health` fault site + wedge
  detection on the injectable clock), every live replica steps
  (`router.step` fault site), and step/dispatch/health failures drive
  the HEALTHY -> DEGRADED -> DEAD machine in `replica.py`.
* **Failover** — when a replica dies (consecutive failures, wedge,
  or `kill_replica`), its engine is already gone (SIGKILL semantics).
  Every non-terminal mirrored request assigned to it is re-dispatched
  to a survivor with its streamed tokens FOLDED INTO the re-prefill
  prompt and its token budget reduced by what was already produced —
  the same recovery shape as the engine's own preemption (PR 1), so
  greedy outputs are bit-identical to an unfaulted run. Re-dispatch is
  idempotent per `request_id`; with no survivor the request parks
  orphaned and retries after the next restart.

* **Disaggregation** — with `roles="prefill:N,decode:M"` the fleet
  splits the engine's two phases (docs/serving.md "Disaggregation"):
  fresh submits land only on PREFILL-CAPABLE replicas (prefix-affine
  dispatch as before), and every finished prefill migrates — KV pages
  + request state through the transfer plane (`transfer.py`,
  `router.migrate` span, `pdt_transfer_*`) — to the decode replica
  with the fewest outstanding slots. The fleet-wide prefix store
  (`prefix_store.py`) replaces per-replica warmth sets and spills cold
  chains to host RAM, so a prefix outlives the replicas that computed
  it. A SIGKILL of either transfer endpoint degrades to the ordinary
  failover path: re-prefill on a survivor, greedy outputs
  bit-identical to a colocated fleet.

* **Gray failures** — with `sentry=SentryConfig(...)` and
  `canary=CanaryConfig(...)` (serving/sentry.py, docs/serving.md
  "Gray failures") the fleet defends the CORRECTNESS of its outputs,
  not just the liveness of its processes: every replica incarnation
  carries a numeric sentry (token in-vocab every step, every-Nth-step
  logit scan), a trip marks the replica SUSPECT (no new traffic,
  terminals PARK), and a canary probe — a fixed prompt whose golden
  greedy stream was computed once at fleet build — replays through
  the replica's ordinary step path immediately on suspicion and on a
  clock-driven schedule. A token mismatch is proof of corruption
  (greedy decode is batching-invariant): the replica QUARANTINES
  (engine discarded, backoff restart into canary-gated PROBATION),
  its in-flight work re-dispatches zero-loss, and tokens streamed
  since its last clean canary are TAINTED — dropped from the mirror
  and re-generated on a healthy replica, so users get correct
  streams, not fast wrong ones. A clean canary restores a SUSPECT
  replica with zero failovers and advances every resident request's
  verified-prefix frontier.

* **Durability** — with `journal=RouterJournal(...)` (serving/
  journal.py, docs/serving.md "Durability") the router write-ahead
  journals the state it already mirrors: every submit BEFORE dispatch
  (the durability point), one batched token-progress record per step
  tick, and every terminal with its final stream. A SIGKILL of the
  ROUTER process is then zero-loss: `ServingRouter.recover(journal,
  factory, ...)` builds a fresh incarnation that rehydrates every
  un-finalized request onto fresh replicas (journaled tokens folded
  into re-prefill — the PR-4 failover shape), restores finished
  requests WITHOUT re-execution (idempotent per request_id), restores
  QoS lane/tenant/budget context, and finalizes honest timeouts for
  deadlines that died with the old incarnation. Greedy outputs stay
  bit-identical to an uninterrupted fleet.

Telemetry (`pdt_router_*`, docs/serving.md "Fleet"): dispatch counters
by {policy, replica}, failover/restart counters, per-replica state and
queue-depth gauges, affinity hit-rate, fleet terminal counters that
reconcile exactly with the engines' `pdt_serving_*` counters.

Observability (docs/observability.md): `submit()` opens a REQUEST-
SCOPED TRACE keyed by the stable request_id (`trace.start_trace`);
every dispatch attempt runs under a `router.dispatch` span, and the
engine's prefill/decode spans + terminal/failover events join the same
trace automatically via their `request_id` attrs — so one request's
dispatch, queue wait, prefill, decode steps, and failover re-dispatch
form a single causal tree across replicas, exportable as a Perfetto
trace. An optional read-only `slo_monitor=` (observability.slo) is fed
each terminal outcome + the fleet-level TTFT (submit to first mirrored
token on the router clock — robust across failover), and
`fleet_info()` then reports fleet and per-replica SLO state alongside
health.
"""
from __future__ import annotations

import random
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

from .. import observability as telemetry
from ..observability import profile as _profile
from ..observability import trace as tracing
from ..models.serving import (ContinuousBatchingEngine, EngineOverloaded,
                              PoolExhausted, Request, RequestStatus)
from ..utils.faults import fault_point
from . import transfer
from . import journal as journal_mod
from .admission import (Lane, QosAdmission, budget_key,
                        derive_retry_after, note_failopen)
from .journal import RouterJournal
from .model_store import FleetModelStore, split_model_id
from .policy import (DispatchPolicy, ModelAffinityPolicy,
                     PrefixAffinityPolicy, make_policy)
from .prefix_store import FleetPrefixStore
from .replica import ReplicaHandle, ReplicaRole, ReplicaState
from . import sentry as sentry_mod
from .sentry import CanaryConfig, SentryConfig

__all__ = ["ServingRouter", "FleetRequest", "FleetOverloaded",
           "QosShed", "parse_roles"]


def parse_roles(roles):
    """Normalize a role spec into a per-replica role list: None (all
    colocated), a ``"prefill:2,decode:1"`` string, a ``{role: count}``
    dict, or an explicit per-index list. String/dict forms order
    replicas prefill, then decode, then colocated — so
    ``"prefill:2,decode:2"`` puts prefill on indices 0-1."""
    if roles is None:
        return None
    if isinstance(roles, str):
        spec = {}
        for part in roles.split(","):
            if not part.strip():
                continue
            name, _, count = part.partition(":")
            spec[name.strip()] = int(count) if count.strip() else 1
        roles = spec
    if isinstance(roles, dict):
        out = []
        for name, count in roles.items():
            if name not in ReplicaRole.ALL:
                raise ValueError(f"unknown replica role {name!r}: "
                                 f"{sorted(ReplicaRole.ALL)}")
            if int(count) < 1:
                raise ValueError(
                    f"role count must be >= 1, got {name}:{count}")
        for name in (ReplicaRole.PREFILL, ReplicaRole.DECODE,
                     ReplicaRole.COLOCATED):
            out.extend([name] * int(roles.get(name, 0)))
        return out
    out = [str(r) for r in roles]
    for name in out:
        if name not in ReplicaRole.ALL:
            raise ValueError(f"unknown replica role {name!r}: "
                             f"{sorted(ReplicaRole.ALL)}")
    return out


_M_DISPATCH = telemetry.counter(
    "pdt_router_dispatch_total",
    "Requests dispatched to a replica, by policy and replica "
    "(failover re-dispatches included).", ("policy", "replica"))
_M_REJECTIONS = telemetry.counter(
    "pdt_router_rejections_total",
    "Fleet-level submit refusals by reason.", ("reason",))
_M_FAILOVERS = telemetry.counter(
    "pdt_router_failovers_total",
    "In-flight requests re-routed off a dead replica.")
_M_TERMINAL = telemetry.counter(
    "pdt_router_requests_terminal_total",
    "Fleet requests reaching a terminal state, by final status.",
    ("status",))
_M_AFF_LOOKUPS = telemetry.counter(
    "pdt_router_affinity_lookups_total",
    "Prefix-affinity placement decisions.")
_M_AFF_HITS = telemetry.counter(
    "pdt_router_affinity_hits_total",
    "Placements that found a warm prefix chain on some replica.")
_M_AFF_RATE = telemetry.gauge(
    "pdt_router_affinity_hit_rate",
    "Warm-placement fraction of prefix-affinity decisions so far.")
_M_STEPS = telemetry.counter(
    "pdt_router_steps_total", "Router step ticks.")
_M_MODEL_COLD = telemetry.counter(
    "pdt_router_model_cold_installs_total",
    "Placements that had to cold-install the request's model on the "
    "chosen replica through the fleet model store (the model-affinity "
    "miss path), by canonical model id.", ("model",))
_M_RESIZES = telemetry.counter(
    "pdt_router_resizes_total",
    "Completed fleet resizes by kind (grow | shrink | recarve | "
    "roles), each a two-phase INTENT/COMMIT journal transaction on "
    "journal-attached fleets.", ("kind",))


class FleetOverloaded(EngineOverloaded):
    """Fleet-wide admission refusal. `retry_after` hints (seconds) when
    capacity is likely back: queue-drain-derived when replicas are
    full, restart-backoff-derived when the whole fleet is down."""

    def __init__(self, message: str, retry_after: float):
        super().__init__(f"{message} (retry after ~{retry_after:.2f}s)")
        self.retry_after = retry_after


class QosShed(FleetOverloaded):
    """A QoS admission shed (serving/admission.py): the fleet COULD
    take the request but the SLO burn / tenant-budget arbitration
    refused it. Same 429 surface as FleetOverloaded; `retry_after` is
    burn-derived through the shared `derive_retry_after` semantics."""

    def __init__(self, message: str, retry_after: float, *,
                 lane: str, tenant: str, reason: str,
                 burn_rate: float):
        super().__init__(message, retry_after)
        self.lane = lane
        self.tenant = tenant
        self.reason = reason
        self.burn_rate = burn_rate


@dataclass
class FleetRequest:
    """Router-side mirror of one submitted request (module docstring:
    the basis of zero-loss failover). `tokens` is the full stream the
    fleet has produced; `folded` is the part baked into the CURRENT
    replica's re-prefill prompt after failovers."""

    request_id: str
    prompt: List[int]
    max_new_tokens: int
    deadline_abs: Optional[float] = None    # router-clock absolute
    max_queue_time: Optional[float] = None
    # QoS (serving/admission.py): the lane rides into the engine as a
    # queue priority; the tenant is admission-side bookkeeping only
    lane: str = Lane.INTERACTIVE
    tenant: Optional[str] = None
    priority: int = 0
    # canonical model id (serving/model_store.py) on multi-model
    # fleets; None on fleets without a model store. Durable at submit,
    # re-ensured on every (re-)dispatch — failover, recovery, and
    # quarantine re-serve all land the request back on ITS weights
    model: Optional[str] = None
    # gray-failure taint frontier (docs/serving.md "Gray failures"):
    # tokens[:verified_len] are trusted — folded at dispatch onto the
    # current replica, or mirrored before that replica's last CLEAN
    # canary. On quarantine the suffix past it is dropped and
    # re-generated on a healthy replica
    verified_len: int = 0
    # bounded-staleness durability frontier (ISSUE 18): tokens
    # [:durable_len] are journaled (group-commit at harvest ticks) —
    # a router SIGKILL loses at most the suffix past it, and replay
    # re-generates that suffix bit-identically. Monotone except at
    # quarantine, which clamps it to verified_len with the taint
    # rewind. Always <= len(tokens) <= device_len: the engine may be
    # up to harvest_every-1 dispatches ahead of everything mirrored
    durable_len: int = 0
    # router-clock request timeline: TTFT for SLO purposes is measured
    # HERE (first mirrored token minus submit), not on any one engine's
    # clock — an engine's arrival_time resets on every failover
    # re-dispatch, which would under-report exactly when failover
    # added the latency
    submit_time: float = 0.0
    first_token_time: Optional[float] = None
    status: str = RequestStatus.QUEUED
    tokens: List[int] = field(default_factory=list)
    folded: List[int] = field(default_factory=list)
    replica: Optional[int] = None
    generation: int = -1       # replica incarnation it was dispatched to
    engine_req: Optional[Request] = None
    dispatches: int = 0
    failovers: int = 0
    error: Optional[str] = None

    @property
    def done(self) -> bool:
        return self.status in RequestStatus.TERMINAL

    @property
    def device_len(self) -> int:
        """Tokens the serving engine has COMMITTED ON DEVICE for this
        request — the top of the staleness contract
        ``durable_len <= verified_len/len(tokens) <= device_len``.
        On the pipelined loop (harvest_every>1) this runs up to k-1
        ahead of ``tokens``; those tokens are discardable (a crash
        mid-window re-generates them bit-identically from the
        harvested prefix)."""
        if self.engine_req is None:
            return len(self.tokens)
        return len(self.folded) + max(self.engine_req.device_len,
                                      len(self.engine_req.output))


class ServingRouter:
    """Deterministic, step-driven router over a replica fleet.

    `engine_factory(index)` builds one replica's engine; it is called
    N times up front and again on every restart. With `tp=` set the
    router carves one submesh per replica and calls the factory as
    `engine_factory(index, submesh)` — pass the submesh through to
    `ContinuousBatchingEngine(submesh=...)`. Pass the router's
    `clock` into the engines it builds when per-request deadlines must
    stay exact across failover (the router re-derives the remaining
    budget on the same clock).

    Drive it like the engine: `submit()` then `run()`, or `step()`
    yourself. `sleep` is only used by `run()` while the whole fleet
    waits on a restart backoff (tests pass the fake clock's `advance`).
    """

    def __init__(self, engine_factory:
                 Callable[..., ContinuousBatchingEngine],
                 num_replicas: int = 2,
                 policy="least_outstanding",
                 *, page_size: int = 16,
                 roles=None,
                 tp=None,
                 prefix_store: Optional[FleetPrefixStore] = None,
                 model_store: Optional[FleetModelStore] = None,
                 max_replica_outstanding: Optional[int] = None,
                 degraded_after: int = 1,
                 dead_after: int = 3,
                 wedge_timeout: Optional[float] = None,
                 restart_backoff_base: float = 1.0,
                 restart_backoff_max: float = 60.0,
                 max_restarts: Optional[int] = 5,
                 retry_after_per_request: float = 0.05,
                 clock: Optional[Callable[[], float]] = None,
                 sleep: Callable[[float], None] = time.sleep,
                 slo_monitor=None,
                 admission: Optional[QosAdmission] = None,
                 journal: Optional[RouterJournal] = None,
                 sentry: Optional[SentryConfig] = None,
                 canary: Optional[CanaryConfig] = None,
                 transfer_stage_deadline: Optional[float] = None,
                 seed: int = 0):
        # roles (disaggregated prefill/decode, docs/serving.md
        # "Disaggregation"): a spec — see `parse_roles` — defines both
        # the fleet SIZE and each replica's role; without one every
        # replica is colocated and num_replicas rules
        role_list = parse_roles(roles)
        if role_list is not None:
            num_replicas = len(role_list)
        else:
            role_list = [ReplicaRole.COLOCATED] * num_replicas
        if num_replicas < 1:
            raise ValueError(f"num_replicas must be >= 1, got "
                             f"{num_replicas}")
        if not any(r in ReplicaRole.PREFILL_CAPABLE for r in role_list):
            raise ValueError(
                "a fleet needs at least one prefill-capable replica "
                "(prefill or colocated) — decode-only fleets can "
                "never admit")
        self.roles_enabled = any(r != ReplicaRole.COLOCATED
                                 for r in role_list)
        self._clock = clock if clock is not None else time.monotonic
        self._sleep = sleep
        # read-only observability hook (observability.slo.SloMonitor):
        # fed terminal outcomes + TTFT; never consulted for routing
        self.slo_monitor = slo_monitor
        # QoS admission brain (serving/admission.py) — consulted by
        # submit() BEFORE dispatch; unlike slo_monitor it DOES shape
        # traffic. Build it over the same monitor/clock for
        # burn-arbitrated shedding
        self.admission = admission
        # crash durability (serving/journal.py): submits journal BEFORE
        # dispatch, token mirrors once per step, terminals with their
        # final stream — ServingRouter.recover() is the read side
        self.journal = journal
        # the fleet-wide prefix store rides along whenever roles are on
        # (its spill is what makes a prefix outlive its replica); pass
        # `prefix_store=` to share one across routers or tune bounds
        if prefix_store is None and self.roles_enabled:
            prefix_store = FleetPrefixStore(page_size=page_size)
        self.prefix_store = prefix_store
        # the fleet model store (serving/model_store.py, ISSUE 17):
        # model identity becomes a routing dimension — submit(model=)
        # validates against it, _dispatch ensures residency through
        # it, and the model_affinity policy reads its resident sets
        self.model_store = model_store
        self.policy: DispatchPolicy = make_policy(
            policy, page_size=page_size, store=prefix_store,
            model_store=model_store)
        self._retry_cost = float(retry_after_per_request)
        # tensor parallelism (serving/submesh.py, docs/serving.md
        # "Tensor parallelism"): `tp=` (an int or a TpConfig) carves
        # `num_replicas` DISJOINT tp-device submeshes from the global
        # device set at construction — one per replica slot, kept
        # across restarts — and the factory must take (index, submesh)
        self.submeshes = None
        self._tp_cfg = None
        if tp is not None:
            from .submesh import TpConfig, carve_submeshes
            self._tp_cfg = tp if isinstance(tp, TpConfig) \
                else TpConfig(tp=int(tp))
            self.submeshes = carve_submeshes(num_replicas, self._tp_cfg)
        # gray-failure defense (serving/sentry.py, docs/serving.md
        # "Gray failures"): sentry trips need a canary to clear or
        # condemn them — a SUSPECT replica with no probe would park
        # forever, so the pairing is mandatory
        if sentry is not None and canary is None:
            raise ValueError(
                "sentry= requires canary= — a SUSPECT replica can "
                "only be cleared or condemned by a canary probe")
        self.sentry_cfg = sentry
        self.canary_cfg = canary
        # per-stage migration deadline (serving/transfer.py): a slow
        # serialize/install is counted, deferred, and charged to the
        # slow endpoint's health instead of silently eaten
        self.transfer_stage_deadline = transfer_stage_deadline
        self._canary_golden: Optional[List[int]] = None
        # per-hosted-BASE canary goldens on multi-model fleets: a
        # replica whose base was swapped is graded against ITS model's
        # golden stream, lazily computed per base (`_golden_for`)
        self._canary_goldens: Dict[str, List[int]] = {}
        if canary is not None:
            self._canary_golden = self._compute_canary_golden(
                engine_factory)
            if model_store is not None:
                self._canary_goldens[model_store.base_model] = \
                    self._canary_golden
        # everything _make_handle needs to build a replica slot again
        # later: the resize API (ISSUE 16) grows/shrinks/recarves the
        # fleet after construction with handles identical to these
        self._engine_factory = engine_factory
        self._page_size = page_size
        self._fleet_rng = random.Random(seed)
        self._handle_kw = dict(
            degraded_after=degraded_after, dead_after=dead_after,
            wedge_timeout=wedge_timeout,
            max_outstanding=max_replica_outstanding,
            restart_backoff_base=restart_backoff_base,
            restart_backoff_max=restart_backoff_max,
            max_restarts=max_restarts)
        self.replicas: List[ReplicaHandle] = [
            self._make_handle(i, role_list[i],
                              None if self.submeshes is None
                              else self.submeshes[i])
            for i in range(num_replicas)]
        self.num_quarantines = 0
        self.num_tainted_tokens = 0
        self.num_migrations = 0
        self.requests: Dict[str, FleetRequest] = {}
        # non-terminal requests only: the per-step harvest/failover
        # scans iterate THIS index, not every request ever submitted
        self._live: Dict[str, FleetRequest] = {}
        self._next_id = 0
        self.num_failovers = 0
        self.num_restarts = 0
        self.num_resizes = 0
        # monotone two-phase resize sequence (recovery resumes it past
        # the highest journaled seq)
        self._resize_seq = 0
        # observation counters for the autoscaler (serving/
        # autoscaler.py): submit ATTEMPTS (refusals included — arrival
        # rate must see the load the fleet is shedding) and survived
        # journal append failures (degraded mode refuses scale-up
        # while the journal is failing)
        self.num_submit_attempts = 0
        self.journal_append_failures = 0
        # per-model accounting (multi-model fleets, fleet_info
        # "models"/"autoscale"): submit attempts and cold installs by
        # canonical model id, terminals by (model id, final status) —
        # the exact-reconciliation ledger the soak recipe checks
        self.num_submit_attempts_by_model: Dict[str, int] = {}
        self.num_cold_installs_by_model: Dict[str, int] = {}
        self.num_terminal_by_model: Dict[str, Dict[str, int]] = {}
        # requests finalized OUTSIDE the step tick (e.g. a deadline that
        # expires during a submit-time failover) are delivered by the
        # next step() — same never-lose-a-terminal shape as the engine's
        # _finished_backlog
        self._terminal_backlog: List[FleetRequest] = []

    def _make_handle(self, index: int, role: str, submesh,
                     generation: int = 0) -> ReplicaHandle:
        """Build one replica slot (construction and every resize use
        the same recipe). A non-zero `generation` seeds a REPLACEMENT
        slot (tp recarve) past its predecessor's, so requests
        dispatched to the old incarnation read as stranded and fail
        over — the fresh engine never heard of them."""
        h = ReplicaHandle(index, self._engine_factory,
                          clock=self._clock, submesh=submesh,
                          rng=random.Random(self._fleet_rng.random()),
                          role=role, sentry_config=self.sentry_cfg,
                          probation_gate=self.canary_cfg is not None,
                          **self._handle_kw)
        if generation:
            h.generation = generation
        return h

    def _note_append_failure(self, error: BaseException,
                             where: str) -> None:
        """Counted-but-survived journal append failure — the shared
        module counter/event plus a router-local tally the autoscaler
        reads: a journal that is failing fsync puts the fleet in
        degraded mode (scale-up refused, serving/autoscaler.py)."""
        self.journal_append_failures += 1
        journal_mod.note_append_failure(error, where=where)

    # -- admission -------------------------------------------------------
    def submit(self, prompt, max_new_tokens: int = 32,
               request_id: Optional[str] = None,
               deadline: Optional[float] = None,
               max_queue_time: Optional[float] = None,
               lane: str = Lane.INTERACTIVE,
               tenant: Optional[str] = None,
               model: Optional[str] = None) -> str:
        """Admit one request into the fleet; returns its stable
        request_id. Re-submitting an id already known to the router is
        a no-op returning the same id (idempotent retries: a client
        that lost the response resubmits without double-generating).
        `lane`/`tenant` feed the QoS controller when one is attached
        (`admission=`): a QoS refusal raises `QosShed`, hard
        backpressure raises `FleetOverloaded` — both 429-shaped with
        one `retry_after` semantics. Raises FleetOverloaded when no
        replica can accept.

        `model` (multi-model fleets, `model_store=`) is the canonical
        model id the request must decode under — a registered full
        checkpoint or ``base+adapter`` LoRA fine-tune. Unregistered
        ids refuse HERE (typed, before any journal/dispatch work);
        omitting it on a multi-model fleet pins the store's builtin
        base, so a replica whose base was swapped away still serves
        the base-model stream."""
        if request_id is not None and request_id in self.requests:
            return request_id
        if lane not in Lane.ALL:
            raise ValueError(f"unknown lane {lane!r}: "
                             f"{sorted(Lane.ALL)}")
        if model is not None:
            if self.model_store is None:
                raise ValueError(
                    "submit(model=) needs a model_store= attached to "
                    "the router (serving.model_store.FleetModelStore)")
            if not self.model_store.known(model):
                _M_REJECTIONS.inc(reason="unknown_model")
                raise ValueError(
                    f"unknown model {model!r}: the fleet store hosts "
                    f"{self.model_store.models()} — register_model/"
                    "register_adapter it first")
        elif self.model_store is not None:
            model = self.model_store.base_model
        # arrival-rate observation (refusals INCLUDED: the autoscaler
        # must see the demand the fleet is shedding, not just what it
        # admitted)
        self.num_submit_attempts += 1
        if model is not None:
            self.num_submit_attempts_by_model[model] = \
                self.num_submit_attempts_by_model.get(model, 0) + 1
        toks = [int(t) for t in prompt]
        decision = None
        if self.admission is not None:
            try:
                decision = self.admission.decide(
                    prompt_tokens=len(toks),
                    max_new_tokens=int(max_new_tokens),
                    lane=lane, tenant=tenant, model=model,
                    queue_depth=min(
                        (h.outstanding() for h in self.replicas
                         if h.alive()), default=0))
            except Exception as e:
                # fail OPEN: a broken/faulted admission brain degrades
                # to plain FIFO admission — never wedge submits
                note_failopen(e, where="router.submit")
                decision = None
            if decision is not None and not decision.admit:
                _M_REJECTIONS.inc(reason="qos_shed")
                raise QosShed(
                    f"QoS shed ({decision.reason}): lane "
                    f"{decision.lane!r}, tenant {decision.tenant!r}, "
                    f"burn {decision.burn_rate:.2f}",
                    decision.retry_after, lane=decision.lane,
                    tenant=decision.tenant, reason=decision.reason,
                    burn_rate=decision.burn_rate)
        if request_id is None:
            # skip ids the caller already used — colliding would
            # silently overwrite an in-flight record
            while f"fleet-{self._next_id}" in self.requests:
                self._next_id += 1
            request_id = f"fleet-{self._next_id}"
            self._next_id += 1
        now = self._clock()
        rec = FleetRequest(
            request_id, toks, int(max_new_tokens),
            deadline_abs=None if deadline is None else now + deadline,
            max_queue_time=max_queue_time, submit_time=now,
            lane=lane, tenant=tenant, priority=Lane.PRIORITY[lane],
            model=model)
        if self.journal is not None:
            # the DURABILITY point (docs/serving.md "Durability"): the
            # submit record lands BEFORE any dispatch, so a router
            # SIGKILL at any later instant is recoverable. An append
            # failure here refuses the submit — work the journal
            # cannot record must not be accepted
            self.journal.append_submit(
                request_id=request_id, prompt=toks,
                max_new_tokens=int(max_new_tokens), lane=lane,
                tenant=tenant, priority=rec.priority, model=model,
                deadline_abs=rec.deadline_abs,
                max_queue_time=max_queue_time)
        # one distributed trace per request, keyed by the stable id:
        # every span/event below that carries this request_id (dispatch
        # attempts, engine prefill/first-token/terminal, failovers)
        # joins it, across replicas and restarts
        tracing.start_trace(request_id, name="router.submit",
                            request_id=request_id,
                            prompt_tokens=len(toks),
                            max_new_tokens=int(max_new_tokens))
        try:
            self._dispatch(rec, forced=False)
        except BaseException:
            if self.journal is not None:
                # the journaled submit must not be resurrected by
                # recover(): the client saw this refusal
                try:
                    self.journal.append_rejected(request_id)
                except Exception as e:
                    self._note_append_failure(
                        e, where="router.submit_rejected")
            tracing.end_trace(request_id)   # refused: nothing to trace
            raise
        # budget charge only AFTER the fleet actually accepted — a
        # fleet_full refusal must not bill the tenant for nothing.
        # Fail OPEN like decide(): the request is ALREADY dispatched,
        # so a broken commit must lose the bookkeeping, never the
        # request
        if decision is not None:
            try:
                self.admission.commit(decision)
            except Exception as e:
                note_failopen(e, where="router.commit")
        self.requests[request_id] = rec
        self._live[request_id] = rec
        return request_id

    def _accepting(self) -> List[ReplicaHandle]:
        """Replicas eligible for new work, HEALTHY before DEGRADED (a
        degraded replica takes traffic only when no healthy one can).
        Fresh submits are PREFILL-CAPABLE only: decode-role replicas
        receive work exclusively through the transfer plane."""
        capable = [h for h in self.replicas
                   if h.role in ReplicaRole.PREFILL_CAPABLE
                   and h.can_accept()]
        healthy = [h for h in capable
                   if h.state == ReplicaState.HEALTHY]
        if healthy:
            return healthy
        return [h for h in capable
                if h.state == ReplicaState.DEGRADED]

    def _burn_hint(self) -> float:
        """The QoS controller's cached burn rate for retry_after
        derivation (0 without a controller — and 0 when the controller
        is broken: the hint is best-effort, fail open)."""
        if self.admission is None:
            return 0.0
        try:
            return self.admission.current_burn()
        except Exception as e:
            # same fail-open surface as a decide() fault: degraded,
            # never silent (PDT006)
            note_failopen(e, where="router.retry_after")
            return 0.0

    def _overloaded(self) -> FleetOverloaded:
        # both refusal reasons derive retry_after through the SAME
        # semantics as a QoS shed (admission.derive_retry_after):
        # queue drain vs burn backoff vs restart wait, whichever is
        # strongest
        now = self._clock()
        # DRAINING replicas are alive but their capacity is never
        # coming back for NEW work — they must not feed a
        # queue-will-drain retry hint
        alive = [h for h in self.replicas
                 if h.state in (ReplicaState.HEALTHY,
                                ReplicaState.DEGRADED)
                 and h.engine is not None
                 and h.role in ReplicaRole.PREFILL_CAPABLE]
        if alive:
            _M_REJECTIONS.inc(reason="fleet_full")
            depth = min(h.outstanding() for h in alive)
            return FleetOverloaded(
                f"every replica queue is full "
                f"({len(alive)} alive, min depth {depth})",
                retry_after=derive_retry_after(
                    self._retry_cost, queue_depth=depth,
                    burn_rate=self._burn_hint()))
        _M_REJECTIONS.inc(reason="no_replicas")
        pending = [h.next_restart_time - now for h in self.replicas
                   if h.next_restart_time is not None]
        return FleetOverloaded(
            "no live replicas",
            retry_after=derive_retry_after(
                0.001, burn_rate=self._burn_hint(),
                restart_wait=max(0.001, min(pending))
                if pending else 1.0))

    def _dispatch(self, rec: FleetRequest, forced: bool):
        """Place `rec` on a replica. `forced` (failover) ignores the
        bounded-queue cap — zero-loss beats backpressure for work the
        fleet already accepted — but still respects health states.
        A dispatch failure counts against that replica's health and the
        next candidate is tried (each replica at most once per call);
        with none left: FleetOverloaded (fresh submits) or an orphaned
        park (failovers, retried next step)."""
        tried = set()
        while True:
            if forced:
                # zero-loss beats role purity: stranded work prefers
                # prefill-capable survivors but re-prefills on a decode
                # replica when nothing else is left standing
                tiers = (
                    [h for h in self.replicas
                     if h.state == ReplicaState.HEALTHY
                     and h.role in ReplicaRole.PREFILL_CAPABLE],
                    [h for h in self.replicas
                     if h.state == ReplicaState.DEGRADED
                     and h.role in ReplicaRole.PREFILL_CAPABLE],
                    [h for h in self.replicas
                     if h.state == ReplicaState.HEALTHY],
                    [h for h in self.replicas
                     if h.state == ReplicaState.DEGRADED],
                )
                cands = next((t for t in tiers if t), [])
            else:
                cands = self._accepting()
            cands = [h for h in cands if h.index not in tried]
            if not cands:
                if forced:
                    rec.replica, rec.engine_req = None, None
                    rec.status = RequestStatus.QUEUED
                    return
                raise self._overloaded()
            if rec.model is not None \
                    or isinstance(self.policy, ModelAffinityPolicy):
                h = self.policy.select(cands,
                                       self._effective_prompt(rec),
                                       model=rec.model)
            else:
                # legacy two-arg call: user-supplied policies predating
                # the model dimension keep working on model-less fleets
                h = self.policy.select(cands, self._effective_prompt(rec))
            if isinstance(self.policy, PrefixAffinityPolicy):
                _M_AFF_LOOKUPS.inc()
                if self.policy.last_match_pages > 0:
                    _M_AFF_HITS.inc()
                if telemetry.enabled():
                    lookups = telemetry.value(
                        "pdt_router_affinity_lookups_total")
                    if lookups:
                        _M_AFF_RATE.set(telemetry.value(
                            "pdt_router_affinity_hits_total") / lookups)
            if not tried:
                # once per PLACEMENT, not per retried candidate: the
                # store's hit/miss accounting describes routing
                # decisions, and the spill restore warms the
                # first-choice replica only (a retry's replica gets
                # warmed by its own next placement)
                spilled = self._restore_spill(
                    h, self._effective_prompt(rec))
                if self.prefix_store is not None \
                        and isinstance(self.policy,
                                       PrefixAffinityPolicy):
                    self.prefix_store.note_lookup(
                        "replica" if self.policy.last_match_pages > 0
                        else "spill" if spilled else "miss")
            tried.add(h.index)
            if self.model_store is not None and rec.model is not None:
                # make the request's model resident BEFORE the engine
                # sees the request: warm replicas are a move-to-end,
                # cold ones install through the store's byte-budgeted
                # LRU (full-checkpoint swaps need an idle engine — a
                # busy replica's refusal is a capacity event, not a
                # health event: try the next candidate, shed if none)
                try:
                    with telemetry.span("router.model_install",
                                        request_id=rec.request_id,
                                        replica=h.index,
                                        model=rec.model):
                        cold = self.model_store.ensure(
                            h.index, h.engine, rec.model)
                except Exception as e:
                    telemetry.event("router.model_install_failed",
                                    request_id=rec.request_id,
                                    replica=h.index, model=rec.model,
                                    error=f"{type(e).__name__}: {e}")
                    continue
                if cold:
                    _M_MODEL_COLD.inc(model=rec.model)
                    self.num_cold_installs_by_model[rec.model] = \
                        self.num_cold_installs_by_model.get(
                            rec.model, 0) + 1
            try:
                # one span per ATTEMPT: failed candidates stay in the
                # trace with their error, so a failover's path across
                # replicas reads straight off the request tree
                # candidate = how many replicas THIS placement pass has
                # tried (incl. this one) — truthful per-call ordering;
                # use `seq` to order across passes
                with telemetry.span("router.dispatch",
                                    request_id=rec.request_id,
                                    replica=h.index,
                                    policy=self.policy.name,
                                    forced=forced,
                                    candidate=len(tried)):
                    rec.engine_req = h.dispatch(
                        self._effective_prompt(rec),
                        self._remaining_budget(rec), rec.request_id,
                        deadline=self._remaining_deadline(rec),
                        max_queue_time=rec.max_queue_time,
                        priority=rec.priority,
                        adapter=self._adapter_of(rec))
            except EngineOverloaded:
                # the engine's OWN admission bound refused (a factory
                # that set max_waiting): not a health event — try the
                # next replica
                continue
            except ValueError as e:
                # request-shaped refusal (empty prompt, zero budget,
                # a prompt that could never fit the pool): the
                # CALLER's fault, not the replica's — charging it to
                # health would let one malformed submit degrade the
                # whole fleet
                if not forced:
                    raise
                rec.status = RequestStatus.FAILED
                rec.error = f"failover re-dispatch rejected: {e}"
                rec.engine_req = None
                self._terminal_backlog.append(rec)
                self._live.pop(rec.request_id, None)
                self._journal_terminal(rec)
                _M_TERMINAL.inc(status=rec.status)
                self._count_model_terminal(rec)
                telemetry.event("router.terminal",
                                request_id=rec.request_id,
                                status=rec.status, replica=None,
                                tokens=len(rec.tokens),
                                failovers=rec.failovers)
                self._slo_feed(rec)
                tracing.end_trace(rec.request_id)
                return
            except Exception as e:          # router.dispatch fault etc.
                if h.note_failure(self._clock(), e):
                    self._failover_replica(h)
                continue
            rec.replica = h.index
            rec.generation = h.generation
            rec.folded = list(rec.tokens)
            # the folded prefix is the trusted baseline on the new
            # replica: whatever it streams past this point is inside
            # ITS taint window until a clean canary advances the
            # frontier (quarantine truncates back to here)
            rec.verified_len = len(rec.tokens)
            rec.status = RequestStatus.QUEUED
            rec.dispatches += 1
            if self.model_store is not None and rec.model is not None:
                # in-flight pin: the store's LRU may not evict this
                # model off this replica until the matching unpin
                # (_finalize / migration hand-off; replica death
                # clears pins wholesale via forget_replica)
                self.model_store.pin(h.index, rec.model)
            self.policy.on_dispatch(h, self._effective_prompt(rec))
            _M_DISPATCH.inc(policy=self.policy.name,
                            replica=str(h.index))
            return

    def _effective_prompt(self, rec: FleetRequest) -> List[int]:
        """What the next replica must prefill: the original prompt plus
        every token the fleet already streamed (the engine-preemption
        fold-in shape, one level up)."""
        return rec.prompt + rec.tokens if rec.tokens else rec.prompt

    def _adapter_of(self, rec: FleetRequest) -> Optional[str]:
        """The engine-side adapter name for this request's model id
        (None for a bare checkpoint or a model-less fleet)."""
        if rec.model is None:
            return None
        return split_model_id(rec.model)[1]

    def _unpin_model(self, rec: FleetRequest):
        """Release the in-flight residency pin taken at dispatch (a
        dead replica's pins were already cleared wholesale by
        `forget_replica`, where unpin is a no-op)."""
        if self.model_store is not None and rec.model is not None \
                and rec.replica is not None:
            self.model_store.unpin(rec.replica, rec.model)

    def _count_model_terminal(self, rec: FleetRequest):
        """Per-(model, status) terminal ledger — reconciles EXACTLY
        with per-model submits once the fleet drains (the multimodel
        soak's check), alongside `pdt_router_requests_terminal_total`."""
        if rec.model is None:
            return
        row = self.num_terminal_by_model.setdefault(rec.model, {})
        row[rec.status] = row.get(rec.status, 0) + 1

    def _remaining_budget(self, rec: FleetRequest) -> int:
        return rec.max_new_tokens - len(rec.tokens)

    def _remaining_deadline(self, rec: FleetRequest) -> Optional[float]:
        if rec.deadline_abs is None:
            return None
        return rec.deadline_abs - self._clock()

    # -- the step tick ---------------------------------------------------
    def step(self) -> List[FleetRequest]:
        """One fleet tick: restarts due -> health probes -> step every
        live replica (harvesting token streams and terminal requests)
        -> fail over work stranded on replicas that died this tick.
        Returns the fleet requests that reached a terminal state."""
        _M_STEPS.inc()
        now = self._clock()
        finished = self._terminal_backlog
        self._terminal_backlog = []
        for h in self.replicas:
            if h.maybe_restart(now):
                self.num_restarts += 1
        unhealthy = set()
        for h in self.replicas:
            try:
                h.check_health(now)     # may kill a wedged replica
            except Exception as e:      # router.health fault fired
                h.note_failure(now, e)
                # a replica that just failed its probe sits this tick
                # out — otherwise an immediately-successful step would
                # erase the probe failure and the probe would mean
                # nothing
                unhealthy.add(h.index)
        # canary probes launch where due (suspect/probation replicas
        # immediately, healthy ones on the schedule) so this same
        # tick's replica steps start serving them
        self._launch_canaries(now)
        for h in self.replicas:
            if not h.alive() or h.index in unhealthy:
                continue
            # canary probes are infra, not traffic: they neither make
            # a step "busy" for the restart-budget ledger nor count as
            # served work — only a canary PASS proves anything
            busy = h.real_outstanding() > 0
            try:
                done = h.step()
            except Exception as e:
                h.note_failure(self._clock(), e)
                continue
            canary_id = (h.canary["request_id"]
                         if h.canary is not None else None)
            # an idle tick is not evidence of stability: only steps that
            # served real work reset the restart-backoff budget
            h.note_success(self._clock(),
                           did_work=busy or any(
                               r.request_id != canary_id for r in done))
            # poll sentry trips BEFORE delivering this step's
            # terminals: a trip raised inside h.step() must park the
            # very terminals it casts doubt on
            if h.sentry is not None and h.sentry.trips > h.sentry_seen:
                h.sentry_seen = h.sentry.trips
                h.mark_suspect("sentry_trip")
            canary_done = None
            for req in done:
                if canary_id is not None \
                        and req.request_id == canary_id:
                    canary_done = req
                    continue
                rec = self.requests.get(req.request_id)
                if rec is None:
                    continue
                if h.state == ReplicaState.SUSPECT:
                    # a terminal from a replica under suspicion must
                    # not finalize until the canary rules — its stream
                    # may be tainted (docs/serving.md "Gray failures")
                    h.parked.append((rec, req))
                else:
                    self._finalize(rec, req, finished)
            self._harvest(h)
            if canary_done is not None:
                self._canary_verdict(h, canary_done, finished,
                                     self._clock())
            h.finish_drain_if_empty(self._clock())
        # disaggregation hand-off: finished prefills on prefill-role
        # replicas migrate to decode replicas through the transfer
        # plane, BEFORE the failover scan (a migrated request must not
        # read as stranded on its source)
        if self.roles_enabled:
            self._migrate_ready()
        # suspicion that resolved WITHOUT a canary verdict (the
        # replica died, was killed, or drained mid-suspicion): deliver
        # the parked terminals as the engine reported them — the taint
        # window closes unproven, a documented detection-latency hole
        # (docs/serving.md failure matrix), not silent data loss
        for h in self.replicas:
            if h.parked and h.state != ReplicaState.SUSPECT:
                for rec, req in h.parked:
                    if not rec.done:
                        self._finalize(rec, req, finished)
                h.parked = []
        # failover pass: anything mirrored onto a replica that is no
        # longer alive (died in the health or step pass, or was killed
        # between ticks), plus orphans parked by an earlier all-dead tick
        for h in self.replicas:
            if not h.alive():
                self._forget_caches(h.index)   # its warm cache is gone
        for rec in list(self._live.values()):
            if rec.done:
                continue
            h = (self.replicas[rec.replica]
                 if rec.replica is not None else None)
            if h is None or not h.alive() \
                    or rec.generation != h.generation:
                # a generation mismatch means the replica died AND
                # restarted since this request was dispatched — the
                # fresh engine never heard of it, however alive the
                # handle looks now
                self._failover_one(rec)
        finished += self._terminal_backlog
        self._terminal_backlog = []
        # durability: mirror this tick's new tokens into the journal
        # AFTER harvests and failovers, so one batched progress record
        # reflects exactly what the router would have streamed
        if self.journal is not None and telemetry.enabled():
            # pdt-lint: disable=PDT001 the journal component of the
            # decode-round decomposition is REAL wall (fsync cost) —
            # a fake clock would fabricate the durability overhead
            j0 = time.perf_counter()
            self._journal_mirror()
            # pdt-lint: disable=PDT001 same real-wall measurement
            _profile.note_round("journal", time.perf_counter() - j0)
        else:
            self._journal_mirror()
        for h in self.replicas:
            h.update_gauges()
        return finished

    def _forget_caches(self, index: int):
        """A replica's warm state died with it: the dispatch policy
        AND the fleet prefix store both forget (the store's host-RAM
        spill survives — that is the point of it)."""
        self.policy.forget(index)
        if self.prefix_store is not None:
            self.prefix_store.forget_replica(index)
        if self.model_store is not None:
            # residency (and every in-flight pin) was device state —
            # it died with the engine; artifacts are host state and
            # survive for the next cold install
            self.model_store.forget_replica(index)

    def _restore_spill(self, h: ReplicaHandle, prompt) -> int:
        """Re-install a host-RAM-spilled prefix chain into the chosen
        replica BEFORE dispatch, so a chain that outlived every warm
        replica (prefix_store.py) still saves the prefill — admission
        then matches the engine's trie as if the chain had always
        lived there. Best-effort: cache warming never fails a
        dispatch. Returns the pages installed."""
        store = self.prefix_store
        if store is None or h.engine is None:
            return 0
        if isinstance(self.policy, PrefixAffinityPolicy) \
                and self.policy.last_match_pages > 0:
            return 0               # a warm replica was found: no need
        entry = store.fetch(prompt)
        if entry is None:
            return 0
        try:
            installed = h.engine.import_prefix(*entry)
        except Exception as e:
            # best-effort still means VISIBLE: a failing spill restore
            # must not read as an ordinary cold miss (PDT006 — this
            # handler swallowed errors silently before pdt-lint)
            telemetry.event("router.prefix_restore_failed",
                            replica=h.index,
                            error=f"{type(e).__name__}: {e}")
            return 0
        if installed:
            telemetry.event("router.prefix_restore", replica=h.index,
                            pages=installed)
        return installed

    def _migrate_ready(self):
        """The disaggregation hand-off (one pass per step tick): every
        request whose PREFILL has finished on a prefill-role replica
        migrates — pages + state, serving/transfer.py — to the decode
        replica with the fewest outstanding slots (decode dispatch
        balances decode slots, where prefill dispatch stays
        prefix-affine). Capacity refusals defer to the next tick with
        the request decoding where it is: migration is an
        optimization, never a dependency. Transfer FAILURES leave both
        engines consistent (serialize is read-only, install backs its
        slot out), so the request simply stays on its source — if the
        source then dies mid-transfer, the ordinary failover pass
        re-prefills it on a survivor with its streamed tokens folded
        in, bit-identical to a colocated fleet."""
        targets = [h for h in self.replicas
                   if h.role == ReplicaRole.DECODE and h.alive()]
        for rec in list(self._live.values()):
            if rec.done or rec.replica is None \
                    or rec.engine_req is None:
                continue
            src = self.replicas[rec.replica]
            if src.role != ReplicaRole.PREFILL or not src.alive() \
                    or rec.generation != src.generation \
                    or src.state == ReplicaState.SUSPECT:
                # a SUSPECT source neither donates nor receives
                # migrations: its pages are in question, and moving
                # them would carry the taint outside the quarantine
                # machinery's reach
                continue
            req = rec.engine_req
            if req.status != RequestStatus.RUNNING or not req.output:
                continue           # not prefilled yet (or requeued)
            # re-check can_accept PER migration: each install raises a
            # target's outstanding count, and the bounded per-replica
            # queue (max_replica_outstanding) must hold for migrated
            # work exactly as it does for fresh dispatches
            avail = [t for t in targets if t.can_accept()]
            if not avail:
                return             # no decode capacity this tick
            dst = min(avail, key=lambda t: (t.outstanding(), t.index))
            if self.model_store is not None and rec.model is not None:
                # the target must host this request's model BEFORE the
                # pages move — `import_pages` refuses a cross-model
                # import with a typed ModelMismatch (pages are a
                # function of the weights), so a target the store
                # cannot prepare right now (busy base swap) simply
                # defers the migration to a later tick
                try:
                    self.model_store.ensure(dst.index, dst.engine,
                                            rec.model)
                except Exception as e:
                    telemetry.event("router.model_install_failed",
                                    request_id=rec.request_id,
                                    replica=dst.index, model=rec.model,
                                    error=f"{type(e).__name__}: {e}")
                    continue
            try:
                # the span joins the request's distributed trace via
                # request_id — migration shows up between the source's
                # prefill and the target's decode steps
                with telemetry.span("router.migrate",
                                    request_id=rec.request_id,
                                    from_replica=src.index,
                                    to_replica=dst.index,
                                    tokens=len(rec.tokens)):
                    new_req, payload = transfer.migrate_request(
                        src.engine, dst.engine, req.rid,
                        deadline=self._remaining_deadline(rec),
                        clock=self._clock,
                        stage_deadline=self.transfer_stage_deadline)
            except (EngineOverloaded, PoolExhausted):
                # target full RIGHT NOW: try other targets for later
                # requests, retry this one next tick
                targets = [t for t in targets if t is not dst]
                continue
            except transfer.TransferStageTimeout as e:
                # a stage that RETURNED but overran its deadline: the
                # migration is deferred (both engines are consistent —
                # a late install was backed out) and the SLOW endpoint
                # is charged a health failure, so a persistently slow
                # replica degrades instead of wedging every tick's
                # migration pass (transfer.py already counted
                # stage="timeout" + the transfer.failed event)
                slow = src if e.stage == "serialize" else dst
                if slow.note_failure(self._clock(), e):
                    self._failover_replica(slow)
                continue
            # pdt-lint: disable=PDT006 transfer.migrate_request already
            # counted pdt_transfer_failures_total{stage=} and emitted
            # transfer.failed before re-raising — a second count here
            # would double-book the same fault
            except Exception:
                # both engines are consistent and a dead endpoint is
                # the health/failover machinery's job — leave the
                # request where it is
                continue
            if self.model_store is not None and rec.model is not None:
                # the residency pin follows the request across the
                # hand-off
                self.model_store.unpin(src.index, rec.model)
                self.model_store.pin(dst.index, rec.model)
            rec.replica, rec.generation = dst.index, dst.generation
            rec.engine_req = new_req    # rec.folded is unchanged: the
            #                             target holds the same output
            #                             stream the source did
            # hand-off closes the source's taint window (same scope
            # rule as a dispatch fold-in): the target's window opens
            # at the full mirrored stream
            rec.verified_len = len(rec.tokens)
            rec.dispatches += 1
            self.num_migrations += 1
            src.migrations_out += 1
            dst.migrations_in += 1
            if self.prefix_store is not None:
                # the serialized prompt pages are host-side already —
                # spilling them is free, and makes the chain outlive
                # every replica that ever computed it
                self.prefix_store.spill_payload(payload)
                self.prefix_store.record(dst.index, payload["prompt"])

    def _harvest(self, h: ReplicaHandle):
        """Mirror the token streams of this replica's live requests —
        the 'already streamed to the client' state failover folds in.
        The first harvest that sees tokens stamps the request's
        fleet-level first-token time (router clock)."""
        for rec in self._live.values():
            if rec.replica == h.index and not rec.done \
                    and rec.generation == h.generation \
                    and rec.engine_req is not None:
                rec.tokens = rec.folded + list(rec.engine_req.output)
                if rec.tokens and rec.first_token_time is None:
                    rec.first_token_time = self._clock()

    def _journal_terminal(self, rec: FleetRequest):
        """Append one terminal record (final status + the complete
        stream). Counted-but-survived on failure: the request IS
        terminal regardless, and a greedy recovery re-derives a lost
        terminal by re-execution, bit-identically."""
        if self.journal is None:
            return
        try:
            self.journal.append_terminal(rec.request_id, rec.status,
                                         rec.tokens, rec.error)
            rec.durable_len = len(rec.tokens)
        except Exception as e:
            self._note_append_failure(e, where="router.terminal")

    def _journal_mirror(self):
        """One batched progress record per step tick: the journal
        diffs the full mirrors against its own table and records only
        new suffixes. Counted-but-survived on failure (a lost suffix
        re-generates bit-identically from the folded re-prefill)."""
        if self.journal is None:
            return
        try:
            self.journal.step_mirror(
                {rec.request_id: rec.tokens
                 for rec in self._live.values() if rec.tokens})
            # the whole mirrored prefix is now journaled: advance each
            # live request's durability frontier to it. On pipelined
            # replicas mirrors only change at harvest ticks, so this
            # is naturally one group-commit per window
            for rec in self._live.values():
                if rec.tokens:
                    rec.durable_len = len(rec.tokens)
        except Exception as e:
            self._note_append_failure(e, where="router.step")

    def _finalize(self, rec: FleetRequest, req: Request,
                  finished: List[FleetRequest]):
        rec.tokens = rec.folded + list(req.output)
        if rec.tokens and rec.first_token_time is None:
            rec.first_token_time = self._clock()
        rec.status = req.status
        rec.error = req.error
        rec.engine_req = None
        self._unpin_model(rec)
        self._live.pop(rec.request_id, None)
        finished.append(rec)
        self._journal_terminal(rec)
        _M_TERMINAL.inc(status=rec.status)
        self._count_model_terminal(rec)
        telemetry.event("router.terminal", request_id=rec.request_id,
                        status=rec.status, replica=rec.replica,
                        tokens=len(rec.tokens),
                        failovers=rec.failovers)
        self._slo_feed(rec)
        tracing.end_trace(rec.request_id)

    def _failover_replica(self, h: ReplicaHandle):
        """Re-route everything mirrored onto `h` (which just died)."""
        self._forget_caches(h.index)
        for rec in list(self._live.values()):
            if rec.replica == h.index and not rec.done:
                self._failover_one(rec)

    def _failover_one(self, rec: FleetRequest):
        """Zero-loss re-dispatch of one stranded request: streamed
        tokens fold into the survivor's re-prefill, budget shrinks by
        what was already produced, the id stays stable (idempotent)."""
        from_replica = rec.replica
        if rec.deadline_abs is not None \
                and self._clock() >= rec.deadline_abs:
            # its budget elapsed while its replica was dead: finalize
            # honestly instead of re-prefilling doomed work
            rec.status = RequestStatus.TIMEOUT
            rec.error = "deadline expired during failover"
            rec.engine_req = None
            self._live.pop(rec.request_id, None)
            self._terminal_backlog.append(rec)
            self._journal_terminal(rec)
            _M_TERMINAL.inc(status=rec.status)
            self._count_model_terminal(rec)
            telemetry.event("router.terminal",
                            request_id=rec.request_id,
                            status=rec.status, replica=from_replica,
                            tokens=len(rec.tokens),
                            failovers=rec.failovers)
            self._slo_feed(rec)
            tracing.end_trace(rec.request_id)
            return
        if from_replica is not None:
            # an orphan being retried (replica=None) already counted
            # when it left its dead replica — don't inflate per retry
            rec.failovers += 1
            self.num_failovers += 1
            _M_FAILOVERS.inc()
            telemetry.event("router.failover",
                            request_id=rec.request_id,
                            from_replica=from_replica,
                            tokens_folded=len(rec.tokens),
                            budget_left=self._remaining_budget(rec))
        self._dispatch(rec, forced=True)
        if rec.replica is None:
            telemetry.event("router.orphaned",
                            request_id=rec.request_id,
                            tokens_folded=len(rec.tokens))

    def _slo_feed(self, rec: FleetRequest):
        """Read-only SLO hook: one terminal outcome (+ the fleet-level
        TTFT when a first token was streamed) per request, tagged with
        the replica that held it last. TTFT is submit-to-first-
        mirrored-token on the ROUTER clock, so time a request spent on
        a replica that died before producing anything counts — the
        client waited through it. Nothing here influences routing."""
        mon = self.slo_monitor
        if mon is None:
            return
        replica = None if rec.replica is None else str(rec.replica)
        mon.observe_outcome("outcome",
                            rec.status == RequestStatus.FINISHED,
                            replica=replica)
        if rec.first_token_time is not None:
            ttft = rec.first_token_time - rec.submit_time
            mon.observe("ttft", ttft, replica=replica)
            # lane-scoped signal (`ttft.interactive` / `ttft.batch`)
            # so QoS arbitration can burn on the PROTECTED lane's
            # objective alone — docs/serving.md "Admission & QoS"
            mon.observe(f"ttft.{rec.lane}", ttft, replica=replica)

    # -- gray-failure defense (serving/sentry.py, ISSUE 14) --------------
    def _compute_canary_golden(self, engine_factory,
                               base_mid: Optional[str] = None
                               ) -> List[int]:
        """The canary's golden greedy stream, computed ONCE per
        (model, tp, quant) at fleet build on a SCRATCH engine from the
        same factory (replica-0 signature, same submesh under TP, same
        `quant=` mode — a QUANTIZED replica's correct stream differs
        from bf16's, so a golden from any other configuration would
        false-quarantine healthy replicas; deriving it from the fleet's
        own factory is what keeps the golden in the replicas' numeric
        regime by construction) — a live replica's engine would be
        left warm and its counters skewed. Greedy decoding is
        batching-invariant (test-pinned since PR 1), so any healthy
        replica must reproduce this stream exactly, whatever traffic
        it is serving alongside."""
        cfg = self.canary_cfg
        if self.submeshes is not None:
            eng = engine_factory(0, self.submeshes[0])
        else:
            eng = engine_factory(0)
        if base_mid is not None and self.model_store is not None \
                and base_mid != self.model_store.base_model:
            # per-hosted-model goldens (multi-model fleets): host the
            # checkpoint on the scratch engine through the store's own
            # install path, then drop the scratch replica's residency
            # accounting — the golden must come from the SAME install
            # seam the fleet's replicas use
            self.model_store.ensure("__golden__", eng, base_mid)
            self.model_store.forget_replica("__golden__")
        rid = eng.add_request(list(cfg.prompt),
                              int(cfg.max_new_tokens))
        out = eng.run()[rid]
        return [int(t) for t in out]

    def _golden_for(self, h: ReplicaHandle) -> Optional[List[int]]:
        """The canary golden for the base checkpoint `h` currently
        HOSTS: on multi-model fleets a swapped replica is graded
        against ITS model's stream (grading it against any other
        base's golden would false-quarantine a healthy replica — the
        PR-14 arm must fire on the right stream), lazily computed per
        base on a scratch engine. The canary probe itself carries no
        adapter, so its stream is a pure function of the base."""
        if self.model_store is None:
            return self._canary_golden
        base = self.model_store.replica_base(h.index)
        g = self._canary_goldens.get(base)
        if g is None:
            g = self._compute_canary_golden(self._engine_factory, base)
            self._canary_goldens[base] = g
        return g

    def _launch_canaries(self, now: float):
        """Start canary probes where due: immediately on SUSPECT and
        PROBATION replicas, on the clock-driven schedule for healthy
        ones. The probe is an ordinary engine request (reserved
        ``__canary_*`` id, never a FleetRequest) riding the replica's
        normal step path — corruption in that engine corrupts the
        canary too, which is the point. An overloaded engine defers
        the launch to the next tick."""
        if self.canary_cfg is None:
            return
        for h in self.replicas:
            if not h.alive() or h.engine is None \
                    or h.canary is not None:
                continue
            if h.state in (ReplicaState.SUSPECT,
                           ReplicaState.PROBATION):
                due = True
            elif h.state in (ReplicaState.HEALTHY,
                             ReplicaState.DEGRADED):
                itv = self.canary_cfg.interval
                due = itv is not None \
                    and now - h.last_canary_start >= itv
            else:
                due = False            # draining: on its way out
            if not due:
                continue
            cid = f"__canary_{h.index}_{h.canary_seq}"
            try:
                rid = h.engine.add_request(
                    list(self.canary_cfg.prompt),
                    int(self.canary_cfg.max_new_tokens),
                    request_id=cid)
            except EngineOverloaded:
                continue               # full queue: retry next tick
            h.canary_seq += 1
            h.last_canary_start = now
            h.canary = {"request_id": cid, "rid": rid,
                        "generation": h.generation, "started": now,
                        "trips0": h.sentry.trips
                        if h.sentry is not None else 0}

    def _canary_verdict(self, h: ReplicaHandle, req: Request,
                        finished: List[FleetRequest], now: float):
        """One canary completed on `h`: grade it and act.

        * **pass** — tokens == golden AND no sentry trips in the
          run's window: suspicion lifts / probation ends (restart
          budget resets), parked terminals deliver with ZERO
          failovers, and every resident request's verified-prefix
          frontier advances to its full mirror.
        * **dirty** — tokens match but the sentry tripped during the
          run: inconclusive. Stay SUSPECT and probe again; after
          `max_suspect_rounds` consecutive dirty passes the replica
          is quarantined as persistently sick.
        * **fail** — token mismatch: PROOF of corruption (greedy is
          batching-invariant) — quarantine.
        * **aborted** — the probe finalized without finishing
          (starved/timed out): no verdict; relaunch next tick.
        """
        state = h.canary
        h.canary = None
        trips = (h.sentry.trips - state["trips0"]) \
            if h.sentry is not None else 0
        if req.status != RequestStatus.FINISHED:
            result = "aborted"
        elif [int(t) for t in req.output] != self._golden_for(h):
            result = "fail"
        elif trips > 0:
            result = "dirty"
        else:
            result = "pass"
        h.canary_runs += 1
        sentry_mod.note_canary(result, now - state["started"])
        telemetry.event("sentry.canary", replica=h.index,
                        result=result, tokens=len(req.output),
                        trips=trips, probe=state["request_id"])
        if result == "pass":
            for rec in self._live.values():
                if rec.replica == h.index \
                        and rec.generation == h.generation \
                        and not rec.done:
                    rec.verified_len = len(rec.tokens)
            for prec, preq in h.parked:
                if not prec.done:      # delivered: zero failovers
                    self._finalize(prec, preq, finished)
            h.parked = []
            h.note_canary_pass(now)
        elif result == "dirty":
            h.canary_failures += 1
            h.suspect_rounds += 1
            if h.suspect_rounds >= self.canary_cfg.max_suspect_rounds:
                self._quarantine(h, "sentry_dirty")
        elif result == "fail":
            h.canary_failures += 1
            self._quarantine(h, "canary_mismatch")

    def _quarantine(self, h: ReplicaHandle, reason: str):
        """Canary evidence condemned `h`: drop every resident
        request's TAINTED suffix (tokens mirrored since the replica's
        last clean canary — `verified_len` is the frontier), then
        kill the replica into QUARANTINED. The same step's failover
        scan re-dispatches the stranded work from the truncated
        mirrors — greedy re-generates the dropped suffix
        bit-identically on a healthy replica, so zero tainted tokens
        can reach a finished stream. Parked terminals re-serve the
        same way (their recs never left `_live`)."""
        now = self._clock()
        h.parked = []
        for rec in list(self._live.values()):
            if rec.replica != h.index \
                    or rec.generation != h.generation or rec.done:
                continue
            dropped = len(rec.tokens) - rec.verified_len
            if dropped > 0:
                self.num_tainted_tokens += dropped
                sentry_mod.note_tainted(dropped)
                telemetry.event("sentry.tainted",
                                request_id=rec.request_id,
                                replica=h.index, dropped=dropped,
                                kept=rec.verified_len)
                rec.tokens = rec.tokens[:rec.verified_len]
                # the taint rewind is the ONE sanctioned retreat of
                # the durability frontier: journaled-but-tainted
                # tokens are no longer durable once the rewind record
                # supersedes them
                rec.durable_len = min(rec.durable_len,
                                      rec.verified_len)
                if self.journal is not None:
                    # the journal mirrored the tainted suffix as
                    # progress records — it must forget it too, or a
                    # recovery landing before this request's terminal
                    # would fold tainted tokens back in as a trusted
                    # prefix (and later suffixes would journal at
                    # misaligned offsets). Counted-but-survived like
                    # a terminal append; the double-fault window
                    # (rewind append lost AND router killed pre-
                    # terminal) is in the failure matrix
                    try:
                        self.journal.rewind(rec.request_id,
                                            rec.verified_len)
                    except Exception as e:
                        self._note_append_failure(
                            e, where="router.quarantine")
            rec.engine_req = None
        self.num_quarantines += 1
        sentry_mod.note_quarantine(h.index)
        telemetry.event("replica.quarantine", replica=h.index,
                        reason=reason,
                        suspect_rounds=h.suspect_rounds)
        h.die(reason, now, to_state=ReplicaState.QUARANTINED)
        self._forget_caches(h.index)   # its warm pages are condemned

    # -- operator surface ------------------------------------------------
    def _replica_at(self, index: int) -> ReplicaHandle:
        """Typed index validation for the manual scaling primitives:
        an out-of-range index is an operator error, reported as such —
        never a bare IndexError from fleet internals (and after a
        scale-down, yesterday's valid index may be gone)."""
        if not 0 <= int(index) < len(self.replicas):
            raise ValueError(
                f"no replica {index}: fleet has "
                f"{len(self.replicas)} replicas (0.."
                f"{len(self.replicas) - 1})")
        return self.replicas[int(index)]

    def kill_replica(self, index: int, reason: str = "killed"):
        """SIGKILL-style drill switch: the replica dies NOW (engine
        discarded), restart is scheduled with backoff, and the next
        step() re-routes its in-flight work. `tests/test_chaos.py` and
        the llama_serve drill use this for deterministic mid-decode
        kills."""
        h = self._replica_at(index)
        h.die(reason, self._clock())
        self._forget_caches(h.index)

    def drain_replica(self, index: int) -> bool:
        """Graceful decommission: no new traffic, in-flight completes,
        then the replica parks dead until `restore_replica`. Repeats
        are idempotent no-ops and conflicting states raise
        `ReplicaOpRefused` — `ReplicaHandle.drain` has the contract."""
        return self._replica_at(index).drain()

    def restore_replica(self, index: int) -> bool:
        """Bring a drained/dead replica back (fresh engine, no
        backoff). Restoring a live replica is an idempotent no-op;
        restoring one still draining raises `ReplicaOpRefused` —
        `ReplicaHandle.restore` has the contract."""
        return self._replica_at(index).restore(self._clock())

    def release_request(self, request_id: str):
        """Drop a TERMINAL request's record once its result has been
        delivered — a long-running fleet must evict, or `requests`
        grows without bound. Releasing a live request is refused."""
        rec = self.requests.get(request_id)
        if rec is None:
            return
        if not rec.done:
            raise ValueError(f"request {request_id!r} is still "
                             f"{rec.status}; only terminal requests "
                             "can be released")
        del self.requests[request_id]
        if self.journal is not None:
            # the client acknowledged delivery: compaction may drop
            # the request's journal history entirely
            try:
                self.journal.append_release(request_id)
            except Exception as e:
                self._note_append_failure(e,
                                          where="router.release")

    # -- elastic resize (ISSUE 16) ---------------------------------------
    def _current_topology(self) -> dict:
        return {"num_replicas": len(self.replicas),
                "roles": [h.role for h in self.replicas],
                "tp": None if self._tp_cfg is None
                else self._tp_cfg.tp}

    def resize(self, num_replicas: Optional[int] = None,
               roles=None, tp=None, *,
               reason: str = "operator") -> dict:
        """Change the fleet's topology — replica count, roles mix,
        and/or tp carve — as ONE crash-durable transaction
        (docs/serving.md "Autoscaling"). On journal-attached fleets
        the full target topology is journaled as a ``resize_intent``
        BEFORE any fleet mutation and a ``resize_commit`` lands after
        the last one, so a router SIGKILL at any instant recovers via
        `recover()` into exactly the old topology (killed before the
        intent reached disk) or the new one (any later instant) with
        zero lost tokens.

        * **grow** — new replica slots append at the top indices; on
          canary fleets they land in PROBATION and take no real
          traffic until their canary passes.
        * **shrink** — the top slots drain via MIGRATION: running
          work moves to survivors through the transfer plane (prefix
          payloads spill warm), anything unmovable re-prefills on a
          survivor with its mirrored stream folded in (zero loss,
          greedy bit-identical either way).
        * **tp change** — a full recarve: every slot gets a fresh
          engine on the new submesh carve and every live request
          re-enters through the ordinary failover fold-in.

        An impossible target (no prefill-capable replica, a carve
        that does not fit the device mesh) refuses BEFORE the intent
        is journaled. Returns a summary dict; ``changed=False`` means
        the target equals the current topology and nothing was done.

        The ``autoscale.resize`` fault site fires at every journal
        record boundary (before/after INTENT, mid-mutation,
        before/after COMMIT) so chaos drills can kill the router at
        each of them."""
        from .submesh import TpConfig, carve_submeshes
        role_list = parse_roles(roles)
        if role_list is not None:
            num_replicas = len(role_list)
        n_new = len(self.replicas) if num_replicas is None \
            else int(num_replicas)
        if n_new < 1:
            raise ValueError(f"num_replicas must be >= 1, got {n_new}")
        if role_list is None:
            # surviving slots keep their roles; added slots colocate
            cur = [h.role for h in self.replicas]
            role_list = (cur + [ReplicaRole.COLOCATED]
                         * max(0, n_new - len(cur)))[:n_new]
        if not any(r in ReplicaRole.PREFILL_CAPABLE
                   for r in role_list):
            raise ValueError(
                "a fleet needs at least one prefill-capable replica "
                "(prefill or colocated) — decode-only fleets can "
                "never admit")
        if tp is None:
            tp_cfg = self._tp_cfg
        else:
            tp_cfg = tp if isinstance(tp, TpConfig) \
                else TpConfig(tp=int(tp))
        tp_changed = ((None if tp_cfg is None else tp_cfg.tp)
                      != (None if self._tp_cfg is None
                          else self._tp_cfg.tp))
        if tp_cfg is not None:
            # validate the carve BEFORE journaling: an intent the
            # mutation could never honor must not reach the journal
            carve_submeshes(n_new, tp_cfg)
        target = {"num_replicas": n_new, "roles": list(role_list),
                  "tp": None if tp_cfg is None else tp_cfg.tp}
        if target == self._current_topology():
            return {"changed": False, "topology": target}
        n_old = len(self.replicas)
        kind = ("recarve" if tp_changed
                else "grow" if n_new > n_old
                else "shrink" if n_new < n_old else "roles")
        seq = self._resize_seq + 1
        fault_point("autoscale.resize")   # kill: before the INTENT
        if self.journal is not None:
            # raises on failure: a resize the journal cannot record
            # must not start (the submit-append rule, one level up)
            self.journal.append_resize_intent(seq, target)
        self._resize_seq = seq
        telemetry.event("router.resize", phase="intent", seq=seq,
                        kind=kind, reason=reason,
                        num_replicas=n_new, tp=target["tp"])
        fault_point("autoscale.resize")   # kill: INTENT durable,
        #                                   fleet untouched
        self._apply_topology(n_new, role_list, tp_cfg, tp_changed)
        fault_point("autoscale.resize")   # kill: mutated, no COMMIT
        if self.journal is not None:
            try:
                self.journal.append_resize_commit(seq)
            except Exception as e:
                # counted-but-survived: recovery rolls the open
                # intent forward into the SAME topology the live
                # fleet is already running
                self._note_append_failure(
                    e, where="router.resize_commit")
        self.num_resizes += 1
        _M_RESIZES.inc(kind=kind)
        telemetry.event("router.resize", phase="commit", seq=seq,
                        kind=kind, reason=reason,
                        num_replicas=n_new, tp=target["tp"])
        fault_point("autoscale.resize")   # kill: after the COMMIT
        return {"changed": True, "seq": seq, "kind": kind,
                "topology": target}

    def _apply_topology(self, n_new: int, role_list: List[str],
                        tp_cfg, tp_changed: bool) -> None:
        """The mutation half of a resize — only ever reached through
        an intent: `resize()` journals the ``resize_intent`` first,
        and `_topology_recover` replays one (pdt-lint PDT009 pins
        this dominance for every topology-mutation call site)."""
        if tp_changed:
            self._topology_recarve(n_new, role_list, tp_cfg)
        else:
            if n_new < len(self.replicas):
                self._topology_shrink(n_new)
            elif n_new > len(self.replicas):
                self._topology_grow(n_new, role_list)
            self._topology_set_roles(role_list)
        fault_point("autoscale.resize")   # kill: fleet mutated,
        #                                   stranded work not re-routed
        self._reroute_stranded()

    def _topology_shrink(self, n_new: int) -> None:
        """Retire the top `len - n_new` slots: drain-via-migration
        (running work moves warm through the transfer plane), then
        the slot dies decommissioned and its handle is removed. Work
        that could not migrate is re-routed by `_reroute_stranded`
        through the zero-loss failover fold-in."""
        now = self._clock()
        survivors = self.replicas[:n_new]
        victims = self.replicas[n_new:]
        for v in victims:
            self._evacuate(v, survivors)
        for v in victims:
            v.auto_restart = False     # removed slots must stay gone
            if v.state not in ReplicaState.DOWN:
                v.die("scale_down", now)
            self._forget_caches(v.index)
        del self.replicas[n_new:]
        if self.submeshes is not None:
            # the carve is deterministic contiguous slices, so the
            # surviving prefix is exactly the old slots' submeshes
            self.submeshes = self.submeshes[:n_new]

    def _evacuate(self, victim: ReplicaHandle,
                  survivors: List[ReplicaHandle]) -> None:
        """Scale-down drain: move the victim's RUNNING requests to
        survivors through the transfer plane — pages + state, no
        recompute — spilling each prefix payload warm into the fleet
        store. Best-effort: a refusal (capacity, transfer fault,
        not-yet-prefilled) leaves the request for the failover
        fold-in, which re-prefills it bit-identically."""
        if victim.engine is None \
                or victim.state == ReplicaState.SUSPECT:
            return      # nothing to donate / taint must not spread
        for rec in list(self._live.values()):
            if rec.done or rec.replica != victim.index \
                    or rec.generation != victim.generation \
                    or rec.engine_req is None:
                continue
            req = rec.engine_req
            if req.status != RequestStatus.RUNNING or not req.output:
                continue   # not prefilled: re-dispatch costs nothing
            avail = [t for t in survivors
                     if t.alive() and t.can_accept()
                     and t.state != ReplicaState.SUSPECT]
            if not avail:
                return     # no survivor capacity: failover handles it
            dst = min(avail, key=lambda t: (t.outstanding(), t.index))
            if self.model_store is not None and rec.model is not None:
                # same discipline as the disagg hand-off: the survivor
                # must host this request's model BEFORE the pages move
                # (`import_pages` refuses cross-model payloads typed);
                # a survivor the store cannot prepare leaves the
                # request for the failover fold-in
                try:
                    self.model_store.ensure(dst.index, dst.engine,
                                            rec.model)
                except Exception as e:
                    telemetry.event("router.model_install_failed",
                                    request_id=rec.request_id,
                                    replica=dst.index, model=rec.model,
                                    error=f"{type(e).__name__}: {e}")
                    continue
            try:
                with telemetry.span("router.migrate",
                                    request_id=rec.request_id,
                                    from_replica=victim.index,
                                    to_replica=dst.index,
                                    tokens=len(rec.tokens)):
                    new_req, payload = transfer.migrate_request(
                        victim.engine, dst.engine, req.rid,
                        deadline=self._remaining_deadline(rec),
                        clock=self._clock,
                        stage_deadline=self.transfer_stage_deadline)
            # pdt-lint: disable=PDT006 transfer.migrate_request already
            # counted pdt_transfer_failures_total{stage=} and emitted
            # transfer.failed before re-raising — a second count here
            # would double-book the same fault
            except Exception:
                # both engines stay consistent on any refusal/fault;
                # the stranded request re-prefills on a survivor
                continue
            if self.model_store is not None and rec.model is not None:
                # the residency pin follows the request across the
                # hand-off
                self.model_store.unpin(victim.index, rec.model)
                self.model_store.pin(dst.index, rec.model)
            rec.replica, rec.generation = dst.index, dst.generation
            rec.engine_req = new_req
            rec.verified_len = len(rec.tokens)
            rec.dispatches += 1
            self.num_migrations += 1
            victim.migrations_out += 1
            dst.migrations_in += 1
            if self.prefix_store is not None:
                self.prefix_store.spill_payload(payload)
                self.prefix_store.record(dst.index, payload["prompt"])

    def _topology_grow(self, n_new: int,
                       role_list: List[str]) -> None:
        """Append fresh slots at the top indices. Under tp the carve
        re-derives for the larger fleet — deterministic contiguous
        slices, so existing slots keep their exact device sets. On
        canary fleets every added slot lands in PROBATION."""
        n_old = len(self.replicas)
        if self._tp_cfg is not None:
            from .submesh import carve_submeshes
            self.submeshes = carve_submeshes(n_new, self._tp_cfg)
        for i in range(n_old, n_new):
            h = self._make_handle(i, role_list[i],
                                  None if self.submeshes is None
                                  else self.submeshes[i])
            h.start_in_probation("scale_up")
            self.replicas.append(h)

    def _topology_recarve(self, n_new: int, role_list: List[str],
                          tp_cfg) -> None:
        """Change the tp width: every engine's sharding changes, so
        every slot is rebuilt on the new carve (the GSPMD
        re-partitioning shape). Replacement slots seed their
        generation PAST the old one, so every live request reads as
        stranded and re-enters through the failover fold-in — greedy
        keeps the streams bit-identical. The canary golden recomputes
        for the new carve (a different sharding is a different
        numeric regime)."""
        from .submesh import carve_submeshes
        now = self._clock()
        self._tp_cfg = tp_cfg
        self.submeshes = None if tp_cfg is None \
            else carve_submeshes(n_new, tp_cfg)
        old = self.replicas
        fresh: List[ReplicaHandle] = []
        for i in range(n_new):
            gen = old[i].generation + 1 if i < len(old) else 0
            fresh.append(self._make_handle(
                i, role_list[i],
                None if self.submeshes is None else self.submeshes[i],
                generation=gen))
        for h in old:
            h.auto_restart = False
            if h.state not in ReplicaState.DOWN:
                h.die("recarve", now)
            self._forget_caches(h.index)
        self.replicas = fresh
        if self.canary_cfg is not None:
            self._canary_golden = self._compute_canary_golden(
                self._engine_factory)

    def _topology_set_roles(self, role_list: List[str]) -> None:
        """Re-role the (already right-sized) fleet: roles steer
        scheduling only, so this is pure relabeling — plus the
        fleet-wide prefix store coming up if roles just turned on."""
        for h, role in zip(self.replicas, role_list):
            if role not in ReplicaRole.ALL:
                raise ValueError(f"unknown replica role {role!r}: "
                                 f"{sorted(ReplicaRole.ALL)}")
            h.role = role
        self.roles_enabled = any(r != ReplicaRole.COLOCATED
                                 for r in role_list)
        if self.roles_enabled and self.prefix_store is None:
            self.prefix_store = FleetPrefixStore(
                page_size=self._page_size)
            if isinstance(self.policy, PrefixAffinityPolicy) \
                    and getattr(self.policy, "store", None) is None:
                self.policy.store = self.prefix_store

    def _reroute_stranded(self) -> None:
        """Post-mutation failover pass: anything mirrored onto a slot
        that no longer exists, died, or changed generation re-enters
        NOW through the zero-loss fold-in — a resize is
        zero-downtime, not wait-for-the-next-tick."""
        n = len(self.replicas)
        for rec in list(self._live.values()):
            if rec.done:
                continue
            h = (self.replicas[rec.replica]
                 if rec.replica is not None and rec.replica < n
                 else None)
            if h is None or not h.alive() \
                    or rec.generation != h.generation:
                self._failover_one(rec)

    def _topology_recover(self, target: dict) -> None:
        """Rebuild this (fresh, empty) incarnation onto a
        journal-resolved topology during `recover()` — the replayed
        ``resize_intent``/``resize_commit`` records are the
        dominating intent here (`journal.replay()` precedes this on
        every path, which is how PDT009 reads it)."""
        from .submesh import TpConfig
        n_new = int(target["num_replicas"])
        roles = list(target.get("roles")
                     or [ReplicaRole.COLOCATED] * n_new)
        tp = target.get("tp")
        if tp is None:
            tp_cfg = None
        elif self._tp_cfg is not None and self._tp_cfg.tp == int(tp):
            tp_cfg = self._tp_cfg    # keep the constructor's config
        else:
            tp_cfg = TpConfig(tp=int(tp))
        tp_changed = ((None if tp_cfg is None else tp_cfg.tp)
                      != (None if self._tp_cfg is None
                          else self._tp_cfg.tp))
        self._apply_topology(n_new, roles, tp_cfg, tp_changed)

    # -- crash recovery (serving/journal.py) -----------------------------
    @classmethod
    def recover(cls, journal: RouterJournal, engine_factory,
                **router_kwargs) -> "ServingRouter":
        """Build a fresh router incarnation from a write-ahead journal
        after the previous incarnation died (SIGKILL-shaped — nothing
        of the old process survives but the journal). Every
        un-finalized journaled request rehydrates onto the fresh
        replicas with its journaled tokens FOLDED into re-prefill and
        its budget shrunk (the PR-4 failover shape, so greedy outputs
        are bit-identical to an uninterrupted fleet); already-finished
        request_ids restore WITHOUT re-execution (idempotent per
        request_id — their final streams stay redeliverable and a
        client's re-submit of the same id is a no-op); deadlines that
        expired while the router was dead finalize as honest timeouts;
        QoS lane/tenant budgets re-charge for the live work
        (`admission=` in `router_kwargs`). Replay is torn-tail
        tolerant but an unreadable journal (the `journal.replay` fault
        site) RAISES — recovery must not silently pretend the journal
        was empty. `router_kwargs` are the ordinary constructor
        arguments (replicas, policy, clocks, admission, ...); the
        journal is re-attached, so the new incarnation keeps
        journaling where the old one stopped."""
        router = cls(engine_factory, journal=journal, **router_kwargs)
        router._rehydrate()
        return router

    def _rehydrate(self):
        """Replay the attached journal into this (fresh) router — see
        `recover()`. Runs under the `journal.replay` span; counts
        recovered/deduped and the recovery-seconds histogram."""
        assert self.journal is not None, "recovery needs a journal"
        t0 = self._clock()
        with telemetry.span("journal.replay", path=self.journal.path):
            replay = self.journal.replay()
        now = self._clock()
        # journaled topology rules over the constructor's: rebuild the
        # fleet BEFORE rehydrating work so live requests land on the
        # resolved shape. An intent without its commit rolls FORWARD —
        # the closing commit is appended here, so the transaction is
        # settled for every later recovery (counted-but-survived on
        # failure: the next recovery simply rolls forward again)
        self._resize_seq = max(self._resize_seq, replay.resize_seq)
        if replay.topology is not None \
                and replay.topology != self._current_topology():
            self._topology_recover(replay.topology)
        if replay.resize_rolled_forward:
            telemetry.event("router.resize", phase="rollforward",
                            seq=replay.resize_seq,
                            num_replicas=len(self.replicas))
            try:
                self.journal.append_resize_commit(replay.resize_seq)
            except Exception as e:
                self._note_append_failure(
                    e, where="router.resize_commit")
        for st in replay.finished.values():
            if st.request_id in self.requests:
                continue
            # finished before the crash: restore the terminal record
            # (status + final stream) and NEVER re-execute — the
            # dedupe half of the idempotent-per-request_id contract
            rec = FleetRequest(st.request_id, list(st.prompt),
                               st.max_new_tokens, lane=st.lane,
                               tenant=st.tenant, priority=st.priority,
                               model=st.model, submit_time=now)
            rec.status = st.status
            rec.tokens = list(st.tokens)
            rec.durable_len = len(rec.tokens)  # it CAME from the journal
            rec.error = st.error
            self.requests[st.request_id] = rec
            # the restored terminal re-enters the per-model ledger:
            # num_terminal_by_model must reconcile EXACTLY with
            # per-model submits ACROSS incarnations (the multimodel
            # soak's check), and the old incarnation's ledger died
            # with its process
            self._count_model_terminal(rec)
        journal_mod.note_deduped(len(replay.finished))
        for st in replay.live.values():           # journal/submit order
            if st.request_id in self.requests:
                continue
            rec = FleetRequest(st.request_id, list(st.prompt),
                               st.max_new_tokens,
                               deadline_abs=st.deadline_abs,
                               max_queue_time=st.max_queue_time,
                               lane=st.lane, tenant=st.tenant,
                               priority=st.priority, model=st.model,
                               submit_time=now)
            rec.tokens = list(st.tokens)
            rec.durable_len = len(rec.tokens)  # replayed = durable
            self.requests[st.request_id] = rec
            self._live[st.request_id] = rec
            if self.admission is not None:
                # restore the tenant BUDGET charge (reservation
                # currency, same as submit-time commit) — but NOT the
                # admit ledger: the OLD incarnation already counted
                # this admission, so the cross-incarnation identity is
                # terminals == committed admits + replay-recovered
                # (docs/serving.md "Durability"). Fail OPEN like every
                # admission surface — recovery never wedges on
                # bookkeeping
                try:
                    budget = self.admission.budget_for(budget_key(
                        st.tenant if st.tenant is not None
                        else self.admission.default_tenant, st.model))
                    if budget is not None:
                        budget.charge(len(st.prompt)
                                      + st.max_new_tokens)
                except Exception as e:
                    note_failopen(e, where="router.recover")
            # a fresh trace root: the old incarnation's carrier died
            # with it, and the recovered request's re-prefill/decode
            # spans should join ONE reconstructable tree
            tracing.start_trace(st.request_id, name="router.recover",
                                request_id=st.request_id,
                                tokens_folded=len(rec.tokens),
                                budget_left=self._remaining_budget(rec))
            # the failover shape, one incarnation up: expired
            # deadlines finalize honestly, everything else re-prefills
            # with the journaled stream folded in (replica=None, so no
            # failover counters inflate)
            self._failover_one(rec)
        journal_mod.note_recovered(len(replay.live))
        journal_mod.observe_recovery_seconds(self._clock() - t0)
        telemetry.event("journal.recovered",
                        live=len(replay.live),
                        deduped=len(replay.finished),
                        corrupt_dropped=replay.corrupt_dropped,
                        records=replay.records,
                        segments=replay.segments)

    # -- drive-to-completion --------------------------------------------
    def run(self) -> Dict[str, List[int]]:
        """Step until every submitted request is terminal; returns
        {request_id: tokens}. While the WHOLE fleet is down awaiting a
        restart backoff, waits via the injectable `sleep` (pass the
        fake clock's `advance` in tests). Raises RuntimeError if work
        remains but every replica is permanently dead."""
        while True:
            pending = [r for r in self._live.values() if not r.done]
            if not pending:
                return {rid: rec.tokens
                        for rid, rec in self.requests.items()}
            if not any(h.alive() for h in self.replicas):
                now = self._clock()
                waits = [h.next_restart_time - now
                         for h in self.replicas
                         if h.next_restart_time is not None]
                if not waits:
                    raise RuntimeError(
                        f"{len(pending)} requests pending but every "
                        "replica is permanently dead (restart budget "
                        "exhausted or drained)")
                if max(0.0, min(waits)) > 0:
                    self._sleep(min(waits))
            self.step()

    # -- introspection ---------------------------------------------------
    def fleet_info(self) -> Dict[str, object]:
        """Operator snapshot: per-replica state/queue/restarts plus
        fleet counters and the prefix-cache aggregate (hits survive
        replica death — the handles fold in retired engine counters).
        With an `slo_monitor` attached, each replica row also carries
        its worst SLO state over its own traffic, and a fleet-level
        `slo` section holds every objective's verdict — render with
        `observability.render_fleet_status`."""
        pending = len(self._live)
        info = {
            "replicas": [
                {"index": h.index, "role": h.role, "state": h.state,
                 "outstanding": h.outstanding(),
                 "pending_harvest": h.pending_harvest(),
                 "consecutive_failures": h.consecutive_failures,
                 "restarts": h.restarts,
                 "migrations_in": h.migrations_in,
                 "migrations_out": h.migrations_out,
                 "death_reason": h.death_reason,
                 # operator visibility of PLACEMENT: which devices
                 # this replica's engine (every incarnation) lives on
                 "submesh": None if h.submesh is None
                 else h.submesh.describe()}
                for h in self.replicas],
            "pending": pending,
            "submitted": len(self.requests),
            "failovers": self.num_failovers,
            "restarts": self.num_restarts,
            "resizes": self.num_resizes,
            "resize_seq": self._resize_seq,
            "migrations": self.num_migrations,
            "prefix_hits": sum(h.prefix_hits() for h in self.replicas),
            "prefix_tokens_reused": sum(h.prefix_tokens_reused()
                                        for h in self.replicas),
        }
        if self.submeshes is not None:
            info["tp"] = {"tp": self.submeshes[0].tp,
                          "mode": self.submeshes[0].config.mode,
                          "submeshes": [m.describe()
                                        for m in self.submeshes]}
        if self.roles_enabled:
            # per-role aggregates: migrations count OUT of prefill and
            # INTO decode (the same transfers seen from each end)
            agg: Dict[str, dict] = {}
            for h in self.replicas:
                row = agg.setdefault(h.role, {"replicas": 0,
                                              "queue_depth": 0,
                                              "migrations": 0})
                row["replicas"] += 1
                row["queue_depth"] += h.outstanding()
                row["migrations"] += (h.migrations_out
                                      if h.role == ReplicaRole.PREFILL
                                      else h.migrations_in)
            info["roles"] = agg
        if self.prefix_store is not None:
            info["prefix_store"] = self.prefix_store.stats()
        if self.model_store is not None:
            # multi-model surface: store accounting, per-model
            # request ledgers (submits/pending/cold installs/terminal
            # by status — the exact-reconciliation set), and per-model
            # autoscaling pressure (pending work per serving replica
            # — what a per-model FleetAutoscaler votes on)
            serving = sum(1 for h in self.replicas
                          if h.state in (ReplicaState.HEALTHY,
                                         ReplicaState.DEGRADED))
            per_model: Dict[str, dict] = {}
            for mid in self.model_store.models():
                per_model[mid] = {
                    "submitted":
                        self.num_submit_attempts_by_model.get(mid, 0),
                    "pending": 0,
                    "cold_installs":
                        self.num_cold_installs_by_model.get(mid, 0),
                    "resident_replicas": sum(
                        1 for h in self.replicas
                        if self.model_store.is_resident(h.index, mid)),
                    "terminal": dict(
                        self.num_terminal_by_model.get(mid, {})),
                }
            for rec in self._live.values():
                if rec.model in per_model:
                    per_model[rec.model]["pending"] += 1
            info["model_store"] = self.model_store.stats()
            info["models"] = per_model
            info["autoscale"] = {
                "per_model": {
                    mid: {"pending": row["pending"],
                          "submitted": row["submitted"],
                          "pressure": row["pending"] / max(1, serving)}
                    for mid, row in per_model.items()}}
        # performance attribution surface (observability/profile.py):
        # the pdt_mem_bytes{pool} memory ledger over every live
        # engine + the compile-cache counters — render with
        # render_fleet_status, drill down with `paddle-tpu-obs
        # profile`
        info["perf"] = _profile.perf_section(
            (h.engine for h in self.replicas),
            prefix_store=self.prefix_store,
            model_store=self.model_store)
        if self.journal is not None:
            # durability surface: segment/byte footprint + how much
            # request state the journal is currently carrying
            info["journal"] = self.journal.stats()
        if self.canary_cfg is not None:
            # gray-failure surface: canary verdicts, quarantines, and
            # the tainted tokens that were dropped instead of served
            trips = sum(h.sentry_trips() for h in self.replicas)
            info["sentry"] = {
                "canary_runs": sum(h.canary_runs
                                   for h in self.replicas),
                "canary_failures": sum(h.canary_failures
                                       for h in self.replicas),
                "quarantines": self.num_quarantines,
                "tainted_tokens_dropped": self.num_tainted_tokens,
                "sentry_trips": trips,
                "golden_tokens": len(self._canary_golden or ()),
            }
            for row, h in zip(info["replicas"], self.replicas):
                row["canary_runs"] = h.canary_runs
                row["last_canary_pass"] = h.last_canary_pass
        # speculative decoding (engine spec_decode=): fleet-wide
        # acceptance aggregate, retired incarnations folded in by the
        # handles — the operator's one look at whether speculation is
        # actually paying (a sagging acceptance rate means the draft
        # has drifted from the traffic)
        spec_rows = [h.spec_info() for h in self.replicas]
        if any(r["rounds"] or r["degraded"] for r in spec_rows) \
                or any(h.engine is not None and h.engine.spec_enabled
                       for h in self.replicas):
            agg = {k: sum(r[k] for r in spec_rows)
                   for k in ("rounds", "proposed", "accepted",
                             "degraded")}
            agg["acceptance_rate"] = (agg["accepted"]
                                      / max(agg["proposed"], 1))
            info["speculation"] = agg
        if self.admission is not None:
            # lane admit/shed counts, tenant budget occupancy, and the
            # arbitration burn — render with render_fleet_status
            info["admission"] = self.admission.stats()
        if self.slo_monitor is not None:
            statuses = self.slo_monitor.evaluate()
            info["slo"] = {
                name: {"state": st.state, "value": st.value,
                       "burn_rate": st.burn_rate,
                       "samples": st.samples}
                for name, st in statuses.items()}
            for row in info["replicas"]:
                row["slo"] = self.slo_monitor.replica_state(
                    str(row["index"]))
        return info
