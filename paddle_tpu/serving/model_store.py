"""Fleet-wide model store: model identity as a first-class fleet
dimension (ISSUE 17).

The PR-7 prefix store made KV *chains* fleet assets; this store does
the same for *weights*. Registered artifacts are full checkpoints
(``register_model``) and LoRA adapters over a shared base
(``register_adapter`` — production TPU serving multiplexes fine-tunes
over one base, PAPERS.md arxiv 2605.25645). Every replica has a
RESIDENT SET — the artifacts its engine can decode under right now —
maintained by ``ensure()`` through the engine's ``bind_state`` seam:

* a full checkpoint installs via ``engine.install_weights`` (idle-only
  value-list swap, stamped ``model_tag``); when the store was built
  with ``quant_weights`` the matmul entries are PRE-QUANTIZED at
  registration (`ops.quant_matmul.QuantizedWeight`), so the stored and
  installed footprint is the halved one;
* a LoRA adapter installs via ``engine.install_adapter`` into the
  stacked epilogue tensors (`ops/lora_epilogue.py`) — safe mid-flight,
  which is what makes the router's cold-install fallback cheap.

Residency is byte-budgeted per replica (``byte_budget_per_replica``):
a cold install first LRU-evicts unpinned adapters. ``pin``/``unpin``
bracket every in-flight request, and ``engine.evict_adapter`` itself
refuses while a request is queued or decoding under the adapter — an
eviction can never strand an in-flight request, by two independent
interlocks. Installs are transactional on the engine side, so a raise
anywhere leaves both the engine and the store's accounting unchanged
(`check_invariants`-clean).

Adapter ranks are PADDED to the store constant ``max_rank`` at
registration: padded rank columns contribute exact zeros, so a mixed
fleet hosting different adapter subsets produces greedy streams
bit-identical to a dedicated single-model fleet (the row-0 argument in
`ops/lora_epilogue.py`).

``model_id``/``split_model_id`` are THE canonical model-identity
spelling — every cache, canary golden, QoS budget, and counter keyed
on model identity must go through them (pdt-lint PDT010), so a key
never silently forks from routing.

The store is process-local host state, deterministic given the call
sequence — the router drives it from its dispatch loop. Telemetry
rides ``pdt_model_store_*`` (docs/observability.md).
"""
from __future__ import annotations

from collections import OrderedDict
from typing import Dict, List, Optional, Tuple

import numpy as np

from .. import observability as telemetry

__all__ = ["FleetModelStore", "model_id", "split_model_id"]


_M_ARTIFACTS = telemetry.gauge(
    "pdt_model_store_artifacts",
    "Artifacts registered with the fleet model store (the builtin "
    "base + full checkpoints + adapters).")
_M_RESIDENT_BYTES = telemetry.gauge(
    "pdt_model_store_resident_bytes",
    "Artifact bytes resident across all replicas, by the store's "
    "accounting (full-checkpoint swaps + adapter stacks).")
_M_INSTALLS = telemetry.counter(
    "pdt_model_store_installs_total",
    "Cold installs the store drove into an engine, by artifact kind "
    "(full = install_weights swap, adapter = install_adapter row).",
    ("kind",))
_M_EVICTIONS = telemetry.counter(
    "pdt_model_store_evictions_total",
    "Artifacts the store evicted from a replica under its byte "
    "budget, by kind.", ("kind",))
_M_HITS = telemetry.counter(
    "pdt_model_store_hits_total",
    "ensure() calls that found the model already resident (warm "
    "replica).")
_M_MISSES = telemetry.counter(
    "pdt_model_store_misses_total",
    "ensure() calls that had to cold-install at least one artifact.")


# the id separator: base and adapter names must not contain it, so the
# canonical spelling parses back losslessly
_SEP = "+"


def model_id(base: str, adapter: Optional[str] = None) -> str:
    """THE canonical model-identity key (pdt-lint PDT010): ``base``
    for a bare checkpoint, ``base+adapter`` for a LoRA fine-tune over
    it. Everything keyed on model identity — canary goldens, QoS
    budgets, per-model counters, residency sets — uses this spelling,
    so keys can never silently fork from routing."""
    base = str(base)
    if not base or _SEP in base:
        raise ValueError(f"model base name {base!r} must be non-empty "
                         f"and must not contain {_SEP!r}")
    if adapter is None:
        return base
    adapter = str(adapter)
    if not adapter or _SEP in adapter:
        raise ValueError(f"adapter name {adapter!r} must be non-empty "
                         f"and must not contain {_SEP!r}")
    return base + _SEP + adapter


def split_model_id(mid: str) -> Tuple[str, Optional[str]]:
    """Inverse of `model_id`: ``(base, adapter-or-None)``."""
    base, sep, adapter = str(mid).partition(_SEP)
    if not base or (sep and not adapter):
        raise ValueError(f"malformed model id {mid!r}")
    return base, (adapter if sep else None)


def _values_nbytes(values: dict) -> int:
    n = 0
    for v in values.values():
        n += int(getattr(v, "nbytes", 0))
    return n


class FleetModelStore:
    """Registered model/adapter artifacts + per-replica resident sets
    (module docstring). ``base_model`` names the checkpoint every
    engine is BUILT with (an engine whose ``model_tag`` is None hosts
    it); it is registered implicitly with no stored values.
    ``byte_budget_per_replica`` bounds each replica's resident
    artifact bytes (None = unbounded); ``max_rank`` is the fixed rank
    every adapter pads to; ``quant_weights`` ('int8'|'fp8') pre-
    quantizes full checkpoints' matmul entries at registration."""

    def __init__(self, base_model: str = "base",
                 byte_budget_per_replica: Optional[int] = None,
                 max_rank: int = 8,
                 quant_weights: Optional[str] = None):
        self.base_model = model_id(base_model)
        self.byte_budget_per_replica = \
            None if byte_budget_per_replica is None \
            else int(byte_budget_per_replica)
        self.max_rank = int(max_rank)
        if self.max_rank < 1:
            raise ValueError(f"max_rank must be >= 1, got {max_rank}")
        if quant_weights not in (None, "int8", "fp8"):
            raise ValueError(
                f"quant_weights {quant_weights!r}: int8|fp8|None")
        self.quant_weights = quant_weights
        # mid -> {"kind": "base"|"full"|"lora", "base": mid|None,
        #         "values"|"deltas": ..., "scale": f, "nbytes": int}
        self._artifacts: Dict[str, dict] = {
            self.base_model: {"kind": "base", "base": None,
                              "nbytes": 0},
        }
        # per base mid: the adapter target-parameter schema every
        # adapter over that base must share (engine stacks are
        # homogeneous per ISSUE 17's bit-identity requirement)
        self._schemas: Dict[str, Tuple[str, ...]] = {}
        # replica -> LRU-ordered resident set: mid -> nbytes
        self._resident: Dict[object, "OrderedDict[str, int]"] = {}
        # replica -> mid -> pin count (in-flight requests)
        self._pins: Dict[object, Dict[str, int]] = {}
        # python-side counters so fleet_info works without telemetry
        self.installs = 0
        self.evictions = 0
        self.hits = 0
        self.misses = 0
        self.evict_refusals = 0
        _M_ARTIFACTS.set(len(self._artifacts))

    # -- registration --------------------------------------------------
    def register_model(self, name: str, values: dict) -> str:
        """Register a FULL checkpoint: ``values`` maps every parameter
        name to its array. With ``quant_weights`` set, 2D matmul
        entries (models.serving.QUANT_MATMULS) are quantized NOW —
        the store holds (and later installs) the halved footprint.
        Returns the canonical model id."""
        mid = model_id(name)
        if mid in self._artifacts:
            raise ValueError(f"model {mid!r} already registered")
        if not values:
            raise ValueError(f"model {name!r} registered with no "
                             "values")
        vals = dict(values)
        if self.quant_weights is not None:
            from ..models.serving import QUANT_MATMULS
            from ..ops.quant_matmul import (QuantizedWeight,
                                            quantize_weight_values)
            for nm, v in list(vals.items()):
                lnm = nm.lower()
                if getattr(v, "ndim", 0) == 2 \
                        and not isinstance(v, QuantizedWeight) \
                        and any(k in lnm for k in QUANT_MATMULS):
                    qw, sc = quantize_weight_values(
                        np.asarray(v), self.quant_weights)
                    vals[nm] = QuantizedWeight(qw, sc)
        self._artifacts[mid] = {"kind": "full", "base": None,
                                "values": vals,
                                "nbytes": _values_nbytes(vals)}
        _M_ARTIFACTS.set(len(self._artifacts))
        return mid

    def register_adapter(self, name: str, deltas: dict,
                         base: Optional[str] = None,
                         scale: float = 1.0) -> str:
        """Register a LoRA adapter over ``base`` (default: the builtin
        base): ``deltas`` maps adapted parameter names to ``(A, B)``
        pairs — A (K, r), B (r, N), r <= max_rank. Ranks pad to
        ``max_rank`` HERE with exact-zero columns, so every fleet
        hosting any subset of adapters runs identical stacked shapes
        (the bit-identity invariance). All adapters over one base must
        adapt the same parameter set. Returns the canonical id."""
        base_mid = self.base_model if base is None else model_id(base)
        art = self._artifacts.get(base_mid)
        if art is None:
            raise ValueError(f"adapter base {base_mid!r} is not a "
                             "registered model")
        if art["kind"] == "lora":
            raise ValueError(f"adapter base {base_mid!r} is itself an "
                             "adapter — adapters stack on checkpoints "
                             "only")
        mid = model_id(base_mid, name)
        if mid in self._artifacts:
            raise ValueError(f"adapter {mid!r} already registered")
        if not deltas:
            raise ValueError(f"adapter {name!r} registered with no "
                             "deltas")
        schema = tuple(sorted(deltas))
        want = self._schemas.get(base_mid)
        if want is not None and schema != want:
            raise ValueError(
                f"adapter {name!r} adapts {list(schema)} but adapters "
                f"over {base_mid!r} adapt {list(want)} — one target "
                "set per base (pad missing targets with zero deltas)")
        padded = {}
        for nm, (a, b) in deltas.items():
            a = np.asarray(a, np.float32)
            b = np.asarray(b, np.float32)
            if a.ndim != 2 or b.ndim != 2 or a.shape[1] != b.shape[0]:
                raise ValueError(
                    f"adapter {name!r} delta for {nm!r}: A {a.shape} "
                    f"/ B {b.shape} is not a rank factorization")
            r = a.shape[1]
            if r > self.max_rank:
                raise ValueError(
                    f"adapter {name!r} rank {r} exceeds the store's "
                    f"max_rank {self.max_rank}")
            if r < self.max_rank:
                a = np.concatenate(
                    [a, np.zeros((a.shape[0], self.max_rank - r),
                                 np.float32)], axis=1)
                b = np.concatenate(
                    [b, np.zeros((self.max_rank - r, b.shape[1]),
                                 np.float32)], axis=0)
            padded[nm] = (a, b)
        nbytes = sum(a.nbytes + b.nbytes for a, b in padded.values())
        self._artifacts[mid] = {"kind": "lora", "base": base_mid,
                                "deltas": padded, "scale": float(scale),
                                "nbytes": nbytes}
        if want is None:
            self._schemas[base_mid] = schema
        _M_ARTIFACTS.set(len(self._artifacts))
        return mid

    def known(self, mid: str) -> bool:
        return mid in self._artifacts

    def models(self) -> List[str]:
        """Every registered model id (bases + adapters), sorted."""
        return sorted(self._artifacts)

    # -- residency -----------------------------------------------------
    def _rset(self, replica) -> "OrderedDict[str, int]":
        rset = self._resident.get(replica)
        if rset is None:
            # a fresh replica hosts the builtin base by construction
            rset = OrderedDict({self.base_model: 0})
            self._resident[replica] = rset
            self._pins[replica] = {}
        return rset

    def resident(self, replica) -> Tuple[str, ...]:
        return tuple(self._rset(replica))

    def is_resident(self, replica, mid: str) -> bool:
        return mid in self._rset(replica)

    def replica_base(self, replica) -> str:
        """The base checkpoint `replica` currently hosts (always the
        first resident entry — `_ensure_base` installs it before any
        adapter). The canary machinery grades a replica against THIS
        model's golden stream."""
        for mid in self._rset(replica):
            art = self._artifacts.get(mid)
            if art is not None and art["kind"] in ("base", "full"):
                return mid
        return self.base_model

    def resident_bytes(self, replica) -> int:
        return sum(self._rset(replica).values())

    def pin(self, replica, mid: str):
        """One in-flight request depends on `mid` at `replica`: the
        LRU may not evict it until the matching `unpin`."""
        pins = self._pins.setdefault(replica, {})
        pins[mid] = pins.get(mid, 0) + 1

    def unpin(self, replica, mid: str):
        pins = self._pins.setdefault(replica, {})
        n = pins.get(mid, 0) - 1
        if n > 0:
            pins[mid] = n
        else:
            pins.pop(mid, None)

    def forget_replica(self, replica):
        """The replica died or left the fleet: its residency (device
        state) died with it. Registered artifacts are host state and
        survive — the next ensure() reinstalls."""
        self._resident.pop(replica, None)
        self._pins.pop(replica, None)
        self._set_resident_bytes()

    def _set_resident_bytes(self):
        _M_RESIDENT_BYTES.set(
            sum(sum(r.values()) for r in self._resident.values()))

    # -- install/evict -------------------------------------------------
    def ensure(self, replica, engine, mid: str) -> bool:
        """Make `mid` resident on `replica`'s engine, cold-installing
        whatever is missing (base checkpoint first, then the adapter),
        LRU-evicting unpinned adapters past the byte budget. Returns
        True when a cold install happened, False when the replica was
        already warm. Raises KeyError for an unregistered id and
        propagates the engine's refusals (e.g. install_weights on a
        busy engine) with the store's accounting unchanged — installs
        are transactional end to end."""
        art = self._artifacts.get(mid)
        if art is None:
            raise KeyError(f"model {mid!r} is not registered with the "
                           "fleet store")
        rset = self._rset(replica)
        if mid in rset:
            rset.move_to_end(mid)
            base = art.get("base")
            if base is not None and base in rset:
                rset.move_to_end(base)    # the adapter keeps its base
            self.hits += 1
            _M_HITS.inc()
            return False
        if art["kind"] == "lora":
            self._ensure_base(replica, engine, art["base"], rset)
            self._make_room(replica, engine, rset, art["nbytes"])
            _, aname = split_model_id(mid)
            engine.install_adapter(aname, art["deltas"],
                                   scale=art["scale"])
            rset[mid] = art["nbytes"]
            self.installs += 1
            _M_INSTALLS.inc(kind="adapter")
        else:
            self._ensure_base(replica, engine, mid, rset)
        self.misses += 1
        _M_MISSES.inc()
        self._set_resident_bytes()
        return True

    def _ensure_base(self, replica, engine, base_mid: str,
                     rset: "OrderedDict[str, int]") -> bool:
        """Host checkpoint `base_mid` on the engine, swapping away the
        current base (and every adapter over it — they die with their
        base on both the engine and in the store's accounting)."""
        if base_mid in rset:
            rset.move_to_end(base_mid)
            return False
        art = self._artifacts[base_mid]
        # the swap: idle-only on the engine side; refusals propagate
        # BEFORE any accounting changes
        if art["kind"] == "base":
            engine.reset_weights()
        else:
            engine.install_weights(art["values"],
                                   tag=base_mid)
        # the old base and its adapters are gone from the device
        rset.clear()
        pins = self._pins.setdefault(replica, {})
        pins.clear()
        rset[base_mid] = art["nbytes"]
        if art["kind"] != "base":
            self.installs += 1
            _M_INSTALLS.inc(kind="full")
        return True

    def _make_room(self, replica, engine,
                   rset: "OrderedDict[str, int]", need: int):
        """LRU-evict unpinned ADAPTERS until `need` more bytes fit the
        replica budget. Pinned entries, the resident base, and
        adapters the engine still has in flight (its own refusal) are
        skipped — an eviction never strands a request."""
        budget = self.byte_budget_per_replica
        if budget is None:
            return
        pins = self._pins.setdefault(replica, {})
        used = sum(rset.values())
        for mid in list(rset):
            if used + need <= budget:
                break
            art = self._artifacts.get(mid)
            if art is None or art["kind"] != "lora":
                continue                      # bases never LRU out
            if pins.get(mid, 0):
                self.evict_refusals += 1
                continue
            _, aname = split_model_id(mid)
            try:
                engine.evict_adapter(aname)
            except ValueError:
                # the engine still has it in flight (e.g. a request
                # the router hasn't unpinned yet) — skip, never strand
                self.evict_refusals += 1
                continue
            used -= rset.pop(mid)
            self.evictions += 1
            _M_EVICTIONS.inc(kind="adapter")
        # over budget with nothing evictable is legal: pinned work
        # outranks the budget (the budget is advisory under pressure)

    # -- accounting ----------------------------------------------------
    def stats(self) -> Dict[str, object]:
        return {
            "artifacts": len(self._artifacts),
            "adapters": sum(1 for a in self._artifacts.values()
                            if a["kind"] == "lora"),
            "replicas": len(self._resident),
            "resident_bytes": {str(r): sum(rs.values())
                               for r, rs in self._resident.items()},
            "installs": self.installs,
            "evictions": self.evictions,
            "evict_refusals": self.evict_refusals,
            "hits": self.hits,
            "misses": self.misses,
        }
