"""Elastic autoscaling control plane over `ServingRouter` (ISSUE 16).

Every primitive a production autoscaler needs already exists one layer
down as a manual operator call — `drain_replica`/`restore_replica`,
zero-loss migration, prefix spill, the write-ahead journal, burn-rate
SLOs, canary probation. This module closes the loop: a deterministic,
step-driven control loop that observes **arrival rate** (submit
attempts per second, refusals included), **queue depth** (outstanding
work per serving replica), and **SLO burn** (the QoS controller's
cached burn rate), and resizes the fleet through
`ServingRouter.resize()` — replica count, the prefill:decode roles
mix, and the tp carve — every transition a journaled two-phase
INTENT/COMMIT transaction, so a SIGKILL mid-resize recovers into the
old or the new topology with zero lost tokens.

Control discipline (the flapping guard, docs/serving.md
"Autoscaling"):

* **hysteresis** — a scale-up needs `up_ticks` CONSECUTIVE
  high-pressure observations, a scale-down `down_ticks` consecutive
  low-pressure ones (down is slower than up on purpose: adding
  capacity late costs latency, removing it late costs only
  chip-hours);
* **cooldown** — after any action the loop holds for
  `cooldown_for(obs)` seconds, which is `max(cooldown_s,
  derive_retry_after(...))` — the cooldown can never undercut the
  retry-after hint the fleet handed its shed clients, so capacity
  cannot disappear before the clients it turned away were told to
  come back;
* **max-step clamp** — one action changes the replica count by at
  most `max_step`, bounded to [min_replicas, max_replicas].

Degraded mode (graceful degradation over oscillation): scale-UP is
refused while any replica is QUARANTINED (a corrupt chip means the
fleet's capacity math is lying — growing it doubles down on a sick
mesh) or while the journal is failing appends (a resize intent that
cannot reach disk must not mutate the fleet); scale-down and holds
proceed. Refusals are counted (`pdt_autoscaler_refusals_total`) and
evented (`autoscale.refused`), never silent.

tp scaling (the GSPMD re-partitioning shape on the 8-device harness):
with `wide_tp` set, a fleet that has been idle long enough to sit at
`min_replicas` trades replicas for wider tensor-parallel engines
(fewer, faster replicas — the latency-optimized carve); the first
scale-up pressure recarves back to the base tp before count-growth
resumes (more, narrower replicas — the throughput carve). Both
directions are ordinary `resize()` transactions.

Everything is driven by `tick()` — call it from the serving loop
(`loadgen.SoakDriver(autoscaler=...)` does) on the router's injectable
clock; there are no threads and no wall-clock reads, so every decision
is reproducible in tests.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

from .. import observability as telemetry
from .admission import derive_retry_after
from .replica import ReplicaRole, ReplicaState

__all__ = ["AutoscalePolicy", "AutoscaleObservation",
           "FleetAutoscaler"]

_M_DECISIONS = telemetry.counter(
    "pdt_autoscaler_decisions_total",
    "Autoscaler evaluations by outcome (grow | shrink | recarve | "
    "hold).", ("action",))
_M_REFUSALS = telemetry.counter(
    "pdt_autoscaler_refusals_total",
    "Scale-ups refused by degraded mode, by reason (quarantined | "
    "journal_failing | resize_failed).", ("reason",))
_M_TARGET = telemetry.gauge(
    "pdt_autoscaler_replicas_target",
    "Replica count the autoscaler last steered the fleet to.")
_M_REACTION = telemetry.histogram(
    "pdt_autoscaler_reaction_seconds",
    "Burst reaction time: first high-pressure observation to the "
    "scale-up that answered it, on the router clock.")


@dataclass
class AutoscalePolicy:
    """Knobs for one `FleetAutoscaler` (module docstring has the
    control discipline). Depth thresholds are OUTSTANDING WORK PER
    SERVING REPLICA; `replica_qps` (optional) adds an arrival-rate
    capacity model: pressure is high whenever arrivals exceed
    `replica_qps * serving_replicas`."""

    min_replicas: int = 1
    max_replicas: int = 4
    scale_up_depth: float = 4.0      # per-replica outstanding
    scale_down_depth: float = 1.0
    replica_qps: Optional[float] = None
    burn_up: float = 1.0             # SLO burn >= this votes UP
    up_ticks: int = 2                # consecutive observations needed
    down_ticks: int = 5
    cooldown_s: float = 10.0
    max_step: int = 1
    # roles-mix policy: target prefill share of a role-managed fleet
    # (None = leave roles alone). Applied on every resize action.
    prefill_fraction: Optional[float] = None
    # tp policy: the latency-optimized wide carve to recarve INTO at
    # sustained min-replicas idle (None = never touch tp)
    wide_tp: Optional[int] = None

    def __post_init__(self):
        if self.min_replicas < 1:
            raise ValueError("min_replicas must be >= 1")
        if self.max_replicas < self.min_replicas:
            raise ValueError("max_replicas must be >= min_replicas")
        if self.max_step < 1:
            raise ValueError("max_step must be >= 1")
        if self.scale_down_depth > self.scale_up_depth:
            raise ValueError("scale_down_depth must not exceed "
                             "scale_up_depth (hysteresis band)")
        if self.up_ticks < 1 or self.down_ticks < 1:
            raise ValueError("up_ticks/down_ticks must be >= 1")


@dataclass
class AutoscaleObservation:
    """One tick's inputs, all on the router clock."""

    t: float
    arrival_qps: float
    queue_depth: float        # per-serving-replica outstanding
    queue_min: int            # min outstanding (the shed-hint depth)
    burn: float
    replicas: int             # current slot count
    serving: int              # slots in a traffic-taking state
    quarantined: int
    journal_failing: bool
    # multi-model fleets (router.model_store): per canonical model id
    # {"arrival_qps", "pending", "pressure"} — pressure is pending
    # work per serving replica, the per-model vote a model-aware
    # operator reads off fleet_info()["autoscale"] too
    per_model: Optional[Dict[str, dict]] = None


class FleetAutoscaler:
    """The deterministic control loop (module docstring). Drive it by
    calling `tick()` from the serving loop; it evaluates at most once
    per `interval_s` on the router's clock and returns the action dict
    it took (or the refusal/hold), None between evaluations."""

    def __init__(self, router, policy: Optional[AutoscalePolicy] = None,
                 *, interval_s: float = 1.0,
                 clock: Optional[Callable[[], float]] = None):
        self.router = router
        self.policy = policy if policy is not None else AutoscalePolicy()
        self.interval_s = float(interval_s)
        self._clock = clock if clock is not None else router._clock
        # the fleet's construction tp is the throughput carve the
        # wide_tp mode recarves back to under pressure
        self._base_tp = (None if router._tp_cfg is None
                         else router._tp_cfg.tp)
        self._next_eval = self._clock()
        self._cooldown_until: Optional[float] = None
        self._hi_streak = 0
        self._lo_streak = 0
        self._hi_since: Optional[float] = None
        self._seen_submits = router.num_submit_attempts
        self._seen_submits_by_model: Dict[str, int] = dict(
            router.num_submit_attempts_by_model)
        self._seen_journal_failures = router.journal_append_failures
        # the last per-model observation, surfaced through stats()
        self.last_per_model: Optional[Dict[str, dict]] = None
        self._last_obs_t: Optional[float] = None
        self.actions: List[dict] = []     # every grow/shrink/recarve
        self.reactions: List[float] = []  # burst reaction samples (s)
        self.num_refusals = 0
        self.num_holds = 0

    # -- observation -----------------------------------------------------
    def observe(self) -> AutoscaleObservation:
        """One snapshot of the three control inputs plus fleet health,
        from plain router state (no telemetry dependency: the loop
        must steer even with recording off)."""
        r = self.router
        now = self._clock()
        dt = (now - self._last_obs_t) \
            if self._last_obs_t is not None else 0.0
        submits = r.num_submit_attempts
        arrival = ((submits - self._seen_submits) / dt) \
            if dt > 0 else 0.0
        self._seen_submits = submits
        self._last_obs_t = now
        serving_states = (ReplicaState.HEALTHY, ReplicaState.DEGRADED)
        serving = [h for h in r.replicas if h.state in serving_states]
        depths = [h.outstanding() for h in serving]
        accepting = [h.outstanding() for h in serving
                     if h.role in ReplicaRole.PREFILL_CAPABLE]
        quarantined = sum(1 for h in r.replicas
                          if h.state == ReplicaState.QUARANTINED)
        failures = r.journal_append_failures
        journal_failing = failures > self._seen_journal_failures
        self._seen_journal_failures = failures
        per_model = None
        if r.model_store is not None:
            # per-model control inputs: arrival rate from the same
            # delta-over-dt the fleet aggregate uses, queue depth from
            # the live mirrors' model tags
            pending: Dict[str, int] = {}
            for rec in r._live.values():
                if rec.model is not None and not rec.done:
                    pending[rec.model] = pending.get(rec.model, 0) + 1
            per_model = {}
            for mid in r.model_store.models():
                subs = r.num_submit_attempts_by_model.get(mid, 0)
                seen = self._seen_submits_by_model.get(mid, 0)
                self._seen_submits_by_model[mid] = subs
                per_model[mid] = {
                    "arrival_qps": ((subs - seen) / dt) if dt > 0
                    else 0.0,
                    "pending": pending.get(mid, 0),
                    "pressure": pending.get(mid, 0)
                    / max(1, len(serving)),
                }
            self.last_per_model = per_model
        return AutoscaleObservation(
            t=now, arrival_qps=arrival,
            queue_depth=sum(depths) / max(1, len(serving)),
            queue_min=min(accepting, default=0),
            burn=r._burn_hint(),
            replicas=len(r.replicas), serving=len(serving),
            quarantined=quarantined,
            journal_failing=journal_failing,
            per_model=per_model)

    def cooldown_for(self, obs: AutoscaleObservation) -> float:
        """Post-action hold time. By construction never below the
        retry-after hint shed clients were handed under the same
        pressure (`derive_retry_after` on the router's own base cost
        and the same depth/burn — the satellite-3 invariant,
        tests/test_admission.py), so capacity the autoscaler just
        changed cannot flap away before told-to-retry clients return."""
        return max(self.policy.cooldown_s,
                   derive_retry_after(self.router._retry_cost,
                                      queue_depth=obs.queue_min,
                                      burn_rate=obs.burn))

    # -- the control loop ------------------------------------------------
    def _pressure(self, obs: AutoscaleObservation) -> int:
        """+1 = scale-up pressure, -1 = scale-down room, 0 = in the
        hysteresis band."""
        p = self.policy
        high = (obs.queue_depth >= p.scale_up_depth
                or obs.burn >= p.burn_up
                or (p.replica_qps is not None
                    and obs.arrival_qps
                    > p.replica_qps * max(1, obs.serving)))
        if high:
            return 1
        low = (obs.queue_depth <= p.scale_down_depth
               and obs.burn < p.burn_up
               and (p.replica_qps is None
                    or obs.arrival_qps
                    <= p.replica_qps * max(1, obs.serving - 1)))
        return -1 if low else 0

    def _roles_for(self, n: int):
        """The roles spec a resize should carry: the policy's target
        prefill share when one is set (single-replica fleets colocate
        — a decode-only or prefill-only fleet cannot serve), else None
        (resize keeps existing roles)."""
        frac = self.policy.prefill_fraction
        if frac is None or n < 2:
            return None
        p = min(n - 1, max(1, round(frac * n)))
        return ([ReplicaRole.PREFILL] * p
                + [ReplicaRole.DECODE] * (n - p))

    def tick(self) -> Optional[dict]:
        """Evaluate once per `interval_s`: observe, vote, and act
        through `router.resize()` (every action a journaled two-phase
        transaction). Returns the action/hold/refusal dict when an
        evaluation ran, None between evaluations."""
        now = self._clock()
        if now < self._next_eval:
            return None
        self._next_eval = now + self.interval_s
        obs = self.observe()
        pressure = self._pressure(obs)
        if pressure > 0:
            if self._hi_streak == 0:
                self._hi_since = obs.t  # the burst-reaction stopwatch
            self._hi_streak += 1
            self._lo_streak = 0
        elif pressure < 0:
            self._lo_streak += 1
            self._hi_streak = 0
            self._hi_since = None
        else:
            self._hi_streak = 0
            self._lo_streak = 0
            self._hi_since = None
        p = self.policy
        n = len(self.router.replicas)
        cur_tp = (None if self.router._tp_cfg is None
                  else self.router._tp_cfg.tp)
        up_due = self._hi_streak >= p.up_ticks
        down_due = self._lo_streak >= p.down_ticks
        if self._cooldown_until is not None \
                and now < self._cooldown_until and (up_due or down_due):
            self.num_holds += 1
            _M_DECISIONS.inc(action="hold")
            return {"action": "hold", "reason": "cooldown",
                    "until": self._cooldown_until}
        # -- scale-up lane (count growth, or recarve back to the
        # throughput carve when sitting on the wide one)
        if up_due:
            if obs.quarantined or obs.journal_failing:
                return self._refuse(
                    "quarantined" if obs.quarantined
                    else "journal_failing", obs)
            if p.wide_tp is not None and cur_tp == p.wide_tp \
                    and cur_tp != self._base_tp:
                return self._act("recarve", obs,
                                 num_replicas=n, tp=self._base_tp
                                 if self._base_tp is not None else 1)
            target = min(p.max_replicas, n + p.max_step)
            if target > n:
                return self._act("grow", obs, num_replicas=target)
            self.num_holds += 1
            _M_DECISIONS.inc(action="hold")
            return {"action": "hold", "reason": "at_max_replicas"}
        # -- scale-down lane (count shrink, then the wide recarve once
        # the floor is reached and the fleet stays idle)
        if down_due:
            target = max(p.min_replicas, n - p.max_step)
            if target < n:
                return self._act("shrink", obs, num_replicas=target)
            if p.wide_tp is not None and cur_tp != p.wide_tp:
                return self._act("recarve", obs,
                                 num_replicas=n, tp=p.wide_tp)
            self.num_holds += 1
            _M_DECISIONS.inc(action="hold")
            return {"action": "hold", "reason": "at_min_replicas"}
        _M_DECISIONS.inc(action="hold")
        return {"action": "hold", "reason": "hysteresis",
                "pressure": pressure}

    def _refuse(self, reason: str, obs: AutoscaleObservation) -> dict:
        """Degraded mode: the scale-up does NOT happen, visibly."""
        self.num_refusals += 1
        _M_REFUSALS.inc(reason=reason)
        telemetry.event("autoscale.refused", reason=reason,
                        replicas=obs.replicas,
                        quarantined=obs.quarantined,
                        queue_depth=round(obs.queue_depth, 3))
        # the streak stays: the moment the fleet heals, the pent-up
        # pressure acts without re-accumulating hysteresis
        return {"action": "refused", "reason": reason}

    def _act(self, action: str, obs: AutoscaleObservation,
             **resize_kw) -> dict:
        n_target = resize_kw.get("num_replicas",
                                 len(self.router.replicas))
        roles = self._roles_for(n_target)
        if roles is not None:
            resize_kw["roles"] = roles
            resize_kw.pop("num_replicas", None)
        try:
            result = self.router.resize(reason="autoscaler",
                                        **resize_kw)
        except Exception as e:
            # a refused/failed resize (journal intent append fault,
            # impossible carve) is a degraded-mode event, not a crash
            # of the control loop
            self.num_refusals += 1
            _M_REFUSALS.inc(reason="resize_failed")
            telemetry.event("autoscale.refused",
                            reason="resize_failed",
                            error=f"{type(e).__name__}: {e}")
            return {"action": "refused", "reason": "resize_failed",
                    "error": str(e)}
        now = self._clock()
        self._cooldown_until = now + self.cooldown_for(obs)
        reaction = None
        if action in ("grow", "recarve") and self._hi_since is not None:
            reaction = now - self._hi_since
            self.reactions.append(reaction)
            _M_REACTION.observe(reaction)
        self._hi_streak = 0
        self._lo_streak = 0
        self._hi_since = None
        _M_DECISIONS.inc(action=action)
        _M_TARGET.set(len(self.router.replicas))
        entry = {"action": action, "t": now,
                 "replicas": len(self.router.replicas),
                 "topology": result.get("topology"),
                 "changed": result.get("changed", False),
                 "reaction_s": reaction,
                 "arrival_qps": round(obs.arrival_qps, 3),
                 "queue_depth": round(obs.queue_depth, 3),
                 "burn": round(obs.burn, 3)}
        self.actions.append(entry)
        telemetry.event("autoscale.decision", action=action,
                        replicas=len(self.router.replicas),
                        queue_depth=round(obs.queue_depth, 3),
                        arrival_qps=round(obs.arrival_qps, 3),
                        burn=round(obs.burn, 3),
                        reaction_s=reaction)
        return entry

    # -- introspection ---------------------------------------------------
    def stats(self) -> dict:
        out = {"replicas": len(self.router.replicas),
               "actions": len(self.actions),
               "refusals": self.num_refusals,
               "holds": self.num_holds,
               "resizes": self.router.num_resizes,
               "reaction_max_s": max(self.reactions, default=None),
               "cooldown_until": self._cooldown_until}
        if self.last_per_model is not None:
            out["per_model"] = self.last_per_model
        return out
