"""paddle_tpu.distribution — probability distributions.
≙ reference «python/paddle/distribution/» [U]: Distribution base +
Normal/Uniform/Bernoulli/Categorical/Beta/Dirichlet/Exponential/Gamma/
Geometric/Gumbel/Laplace/LogNormal/Multinomial/Poisson + kl_divergence
registry. Sampling threads the framework's stateful RNG key
(tensor.random.default_generator), math is jnp/jax.scipy."""
from __future__ import annotations

import math
from typing import Optional, Sequence

import numpy as np
import jax
import jax.numpy as jnp

from ..core.tensor import Tensor, to_tensor
from ..tensor.random import default_generator

__all__ = ["Distribution", "Normal", "Uniform", "Bernoulli", "Categorical",
           "Beta", "Dirichlet", "Exponential", "Gamma", "Geometric",
           "Gumbel", "Laplace", "LogNormal", "Multinomial", "Poisson",
           "kl_divergence", "register_kl"]


def _v(x):
    if isinstance(x, Tensor):
        return x._value.astype(jnp.float32)
    return jnp.asarray(np.asarray(x), jnp.float32)


def _key():
    return default_generator.next_key()


def _shape(sample_shape, base):
    return tuple(int(s) for s in sample_shape) + tuple(base)


class Distribution:
    """≙ paddle.distribution.Distribution."""

    def __init__(self, batch_shape=(), event_shape=()):
        self._batch_shape = tuple(batch_shape)
        self._event_shape = tuple(event_shape)

    @property
    def batch_shape(self):
        return self._batch_shape

    @property
    def event_shape(self):
        return self._event_shape

    @property
    def mean(self):
        raise NotImplementedError

    @property
    def variance(self):
        raise NotImplementedError

    def sample(self, shape=()):
        raise NotImplementedError

    def rsample(self, shape=()):
        raise NotImplementedError

    def log_prob(self, value):
        raise NotImplementedError

    def prob(self, value):
        return Tensor(jnp.exp(self.log_prob(value)._value))

    def entropy(self):
        raise NotImplementedError

    def kl_divergence(self, other):
        return kl_divergence(self, other)


class Normal(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc = _v(loc)
        self.scale = _v(scale)
        shape = jnp.broadcast_shapes(self.loc.shape, self.scale.shape)
        super().__init__(shape)

    @property
    def mean(self):
        return Tensor(jnp.broadcast_to(self.loc, self.batch_shape))

    @property
    def variance(self):
        return Tensor(jnp.broadcast_to(self.scale ** 2, self.batch_shape))

    @property
    def stddev(self):
        return Tensor(jnp.broadcast_to(self.scale, self.batch_shape))

    def sample(self, shape=()):
        eps = jax.random.normal(_key(), _shape(shape, self.batch_shape))
        return Tensor(self.loc + self.scale * eps)

    rsample = sample

    def log_prob(self, value):
        v = _v(value)
        var = self.scale ** 2
        return Tensor(-((v - self.loc) ** 2) / (2 * var)
                      - jnp.log(self.scale) - 0.5 * math.log(2 * math.pi))

    def entropy(self):
        e = 0.5 + 0.5 * math.log(2 * math.pi) + jnp.log(self.scale)
        return Tensor(jnp.broadcast_to(e, self.batch_shape))

    def cdf(self, value):
        return Tensor(jax.scipy.stats.norm.cdf(_v(value), self.loc,
                                               self.scale))


class Uniform(Distribution):
    def __init__(self, low, high, name=None):
        self.low = _v(low)
        self.high = _v(high)
        super().__init__(jnp.broadcast_shapes(self.low.shape,
                                              self.high.shape))

    @property
    def mean(self):
        return Tensor(jnp.broadcast_to((self.low + self.high) / 2,
                                       self.batch_shape))

    @property
    def variance(self):
        return Tensor(jnp.broadcast_to((self.high - self.low) ** 2 / 12,
                                       self.batch_shape))

    def sample(self, shape=()):
        u = jax.random.uniform(_key(), _shape(shape, self.batch_shape))
        return Tensor(self.low + (self.high - self.low) * u)

    rsample = sample

    def log_prob(self, value):
        v = _v(value)
        inside = (v >= self.low) & (v < self.high)
        lp = -jnp.log(self.high - self.low)
        return Tensor(jnp.where(inside, lp, -jnp.inf))

    def entropy(self):
        return Tensor(jnp.broadcast_to(jnp.log(self.high - self.low),
                                       self.batch_shape))


class Bernoulli(Distribution):
    def __init__(self, probs=None, logits=None, name=None):
        if (probs is None) == (logits is None):
            raise ValueError("pass exactly one of probs/logits")
        if probs is not None:
            self.probs = _v(probs)
            self.logits = jnp.log(self.probs) - jnp.log1p(-self.probs)
        else:
            self.logits = _v(logits)
            self.probs = jax.nn.sigmoid(self.logits)
        super().__init__(self.probs.shape)

    @property
    def mean(self):
        return Tensor(self.probs)

    @property
    def variance(self):
        return Tensor(self.probs * (1 - self.probs))

    def sample(self, shape=()):
        u = jax.random.uniform(_key(), _shape(shape, self.batch_shape))
        return Tensor((u < self.probs).astype(jnp.float32))

    def log_prob(self, value):
        v = _v(value)
        return Tensor(v * jnp.log(jnp.clip(self.probs, 1e-12))
                      + (1 - v) * jnp.log(jnp.clip(1 - self.probs, 1e-12)))

    def entropy(self):
        p = jnp.clip(self.probs, 1e-12, 1 - 1e-12)
        return Tensor(-(p * jnp.log(p) + (1 - p) * jnp.log(1 - p)))


class Categorical(Distribution):
    def __init__(self, logits=None, probs=None, name=None):
        if logits is not None and probs is None:
            self.logits = _v(logits)
            self.probs = jax.nn.softmax(self.logits, -1)
        elif probs is not None:
            self.probs = _v(probs) / jnp.sum(_v(probs), -1, keepdims=True)
            self.logits = jnp.log(jnp.clip(self.probs, 1e-12))
        else:
            raise ValueError("pass logits or probs")
        super().__init__(self.probs.shape[:-1])

    @property
    def mean(self):
        raise NotImplementedError("Categorical has no mean")

    def sample(self, shape=()):
        out = jax.random.categorical(
            _key(), self.logits, shape=_shape(shape, self.batch_shape))
        return Tensor(out)

    def log_prob(self, value):
        v = _v(value).astype(jnp.int32)
        lp = jax.nn.log_softmax(self.logits, -1)
        return Tensor(jnp.take_along_axis(lp, v[..., None],
                                          -1)[..., 0])

    def probs_of(self, value):
        return Tensor(jnp.exp(self.log_prob(value)._value))

    def entropy(self):
        lp = jax.nn.log_softmax(self.logits, -1)
        return Tensor(-jnp.sum(jnp.exp(lp) * lp, -1))


class Beta(Distribution):
    def __init__(self, alpha, beta, name=None):
        self.alpha = _v(alpha)
        self.beta = _v(beta)
        super().__init__(jnp.broadcast_shapes(self.alpha.shape,
                                              self.beta.shape))

    @property
    def mean(self):
        return Tensor(self.alpha / (self.alpha + self.beta))

    @property
    def variance(self):
        t = self.alpha + self.beta
        return Tensor(self.alpha * self.beta / (t ** 2 * (t + 1)))

    def sample(self, shape=()):
        s = jax.random.beta(_key(), self.alpha, self.beta,
                            _shape(shape, self.batch_shape))
        return Tensor(s)

    def log_prob(self, value):
        v = _v(value)
        return Tensor(jax.scipy.stats.beta.logpdf(v, self.alpha, self.beta))

    def entropy(self):
        a, b = self.alpha, self.beta
        from jax.scipy.special import betaln, digamma
        return Tensor(betaln(a, b) - (a - 1) * digamma(a)
                      - (b - 1) * digamma(b)
                      + (a + b - 2) * digamma(a + b))


class Dirichlet(Distribution):
    def __init__(self, concentration, name=None):
        self.concentration = _v(concentration)
        super().__init__(self.concentration.shape[:-1],
                         self.concentration.shape[-1:])

    @property
    def mean(self):
        return Tensor(self.concentration
                      / jnp.sum(self.concentration, -1, keepdims=True))

    def sample(self, shape=()):
        return Tensor(jax.random.dirichlet(
            _key(), self.concentration,
            _shape(shape, self.batch_shape)))

    def log_prob(self, value):
        return Tensor(jax.scipy.stats.dirichlet.logpdf(
            jnp.moveaxis(_v(value), -1, 0), self.concentration))

    def entropy(self):
        from jax.scipy.special import digamma, gammaln
        a = self.concentration
        a0 = jnp.sum(a, -1)
        k = a.shape[-1]
        lnB = jnp.sum(gammaln(a), -1) - gammaln(a0)
        return Tensor(lnB + (a0 - k) * digamma(a0)
                      - jnp.sum((a - 1) * digamma(a), -1))


class Exponential(Distribution):
    def __init__(self, rate, name=None):
        self.rate = _v(rate)
        super().__init__(self.rate.shape)

    @property
    def mean(self):
        return Tensor(1 / self.rate)

    @property
    def variance(self):
        return Tensor(1 / self.rate ** 2)

    def sample(self, shape=()):
        e = jax.random.exponential(_key(),
                                   _shape(shape, self.batch_shape))
        return Tensor(e / self.rate)

    rsample = sample

    def log_prob(self, value):
        v = _v(value)
        return Tensor(jnp.where(v >= 0, jnp.log(self.rate) - self.rate * v,
                                -jnp.inf))

    def entropy(self):
        return Tensor(1 - jnp.log(self.rate))


class Gamma(Distribution):
    def __init__(self, concentration, rate, name=None):
        self.concentration = _v(concentration)
        self.rate = _v(rate)
        super().__init__(jnp.broadcast_shapes(self.concentration.shape,
                                              self.rate.shape))

    @property
    def mean(self):
        return Tensor(self.concentration / self.rate)

    @property
    def variance(self):
        return Tensor(self.concentration / self.rate ** 2)

    def sample(self, shape=()):
        g = jax.random.gamma(_key(), self.concentration,
                             _shape(shape, self.batch_shape))
        return Tensor(g / self.rate)

    def log_prob(self, value):
        return Tensor(jax.scipy.stats.gamma.logpdf(
            _v(value), self.concentration, scale=1 / self.rate))

    def entropy(self):
        from jax.scipy.special import digamma, gammaln
        a = self.concentration
        return Tensor(a - jnp.log(self.rate) + gammaln(a)
                      + (1 - a) * digamma(a))


class Geometric(Distribution):
    """P(X=k) = (1-p)^k p, k >= 0. ≙ paddle.distribution.Geometric."""

    def __init__(self, probs, name=None):
        self.probs = _v(probs)
        super().__init__(self.probs.shape)

    @property
    def mean(self):
        return Tensor((1 - self.probs) / self.probs)

    @property
    def variance(self):
        return Tensor((1 - self.probs) / self.probs ** 2)

    def sample(self, shape=()):
        u = jax.random.uniform(_key(), _shape(shape, self.batch_shape),
                               minval=1e-7)
        return Tensor(jnp.floor(jnp.log(u) / jnp.log1p(-self.probs)))

    def log_prob(self, value):
        v = _v(value)
        return Tensor(v * jnp.log1p(-self.probs) + jnp.log(self.probs))

    def entropy(self):
        p = self.probs
        return Tensor(-((1 - p) * jnp.log1p(-p) + p * jnp.log(p)) / p)


class Gumbel(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc = _v(loc)
        self.scale = _v(scale)
        super().__init__(jnp.broadcast_shapes(self.loc.shape,
                                              self.scale.shape))

    @property
    def mean(self):
        return Tensor(self.loc + self.scale * np.float32(np.euler_gamma))

    @property
    def variance(self):
        return Tensor(jnp.broadcast_to(
            (math.pi ** 2 / 6) * self.scale ** 2, self.batch_shape))

    def sample(self, shape=()):
        g = jax.random.gumbel(_key(), _shape(shape, self.batch_shape))
        return Tensor(self.loc + self.scale * g)

    rsample = sample

    def log_prob(self, value):
        z = (_v(value) - self.loc) / self.scale
        return Tensor(-(z + jnp.exp(-z)) - jnp.log(self.scale))

    def entropy(self):
        return Tensor(jnp.broadcast_to(
            jnp.log(self.scale) + 1 + np.float32(np.euler_gamma),
            self.batch_shape))


class Laplace(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc = _v(loc)
        self.scale = _v(scale)
        super().__init__(jnp.broadcast_shapes(self.loc.shape,
                                              self.scale.shape))

    @property
    def mean(self):
        return Tensor(jnp.broadcast_to(self.loc, self.batch_shape))

    @property
    def variance(self):
        return Tensor(jnp.broadcast_to(2 * self.scale ** 2,
                                       self.batch_shape))

    def sample(self, shape=()):
        l = jax.random.laplace(_key(), _shape(shape, self.batch_shape))
        return Tensor(self.loc + self.scale * l)

    rsample = sample

    def log_prob(self, value):
        return Tensor(-jnp.abs(_v(value) - self.loc) / self.scale
                      - jnp.log(2 * self.scale))

    def entropy(self):
        return Tensor(jnp.broadcast_to(1 + jnp.log(2 * self.scale),
                                       self.batch_shape))


class LogNormal(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc = _v(loc)
        self.scale = _v(scale)
        self._normal = Normal(loc, scale)
        super().__init__(jnp.broadcast_shapes(self.loc.shape,
                                              self.scale.shape))

    @property
    def mean(self):
        return Tensor(jnp.exp(self.loc + self.scale ** 2 / 2))

    @property
    def variance(self):
        s2 = self.scale ** 2
        return Tensor((jnp.exp(s2) - 1) * jnp.exp(2 * self.loc + s2))

    def sample(self, shape=()):
        return Tensor(jnp.exp(self._normal.sample(shape)._value))

    def log_prob(self, value):
        v = _v(value)
        lv = jnp.log(v)
        return Tensor(self._normal.log_prob(Tensor(lv))._value - lv)

    def entropy(self):
        return Tensor(self._normal.entropy()._value + self.loc)


class Multinomial(Distribution):
    def __init__(self, total_count, probs, name=None):
        self.total_count = int(total_count)
        self.probs = _v(probs) / jnp.sum(_v(probs), -1, keepdims=True)
        super().__init__(self.probs.shape[:-1], self.probs.shape[-1:])

    @property
    def mean(self):
        return Tensor(self.total_count * self.probs)

    @property
    def variance(self):
        return Tensor(self.total_count * self.probs * (1 - self.probs))

    def sample(self, shape=()):
        logits = jnp.log(jnp.clip(self.probs, 1e-12))
        draws = jax.random.categorical(
            _key(), logits,
            shape=(self.total_count,) + _shape(shape, self.batch_shape))
        k = self.probs.shape[-1]
        oh = jax.nn.one_hot(draws, k)
        return Tensor(jnp.sum(oh, axis=0))

    def log_prob(self, value):
        from jax.scipy.special import gammaln
        v = _v(value)
        return Tensor(gammaln(self.total_count + 1.0)
                      - jnp.sum(gammaln(v + 1.0), -1)
                      + jnp.sum(v * jnp.log(jnp.clip(self.probs, 1e-12)),
                                -1))


class Poisson(Distribution):
    def __init__(self, rate, name=None):
        self.rate = _v(rate)
        super().__init__(self.rate.shape)

    @property
    def mean(self):
        return Tensor(self.rate)

    @property
    def variance(self):
        return Tensor(self.rate)

    def sample(self, shape=()):
        return Tensor(jax.random.poisson(
            _key(), self.rate,
            _shape(shape, self.batch_shape)).astype(jnp.float32))

    def log_prob(self, value):
        from jax.scipy.special import gammaln
        v = _v(value)
        return Tensor(v * jnp.log(self.rate) - self.rate - gammaln(v + 1))


# -- KL registry -------------------------------------------------------------
_KL_TABLE = {}


def register_kl(p_cls, q_cls):
    """≙ paddle.distribution.register_kl decorator."""
    def deco(fn):
        _KL_TABLE[(p_cls, q_cls)] = fn
        return fn
    return deco


def kl_divergence(p: Distribution, q: Distribution) -> Tensor:
    fn = _KL_TABLE.get((type(p), type(q)))
    if fn is None:
        for (pc, qc), f in _KL_TABLE.items():
            if isinstance(p, pc) and isinstance(q, qc):
                fn = f
                break
    if fn is None:
        raise NotImplementedError(
            f"kl_divergence not registered for {type(p).__name__} || "
            f"{type(q).__name__}")
    return fn(p, q)


@register_kl(Normal, Normal)
def _kl_normal(p, q):
    vr = (p.scale / q.scale) ** 2
    t1 = ((p.loc - q.loc) / q.scale) ** 2
    return Tensor(0.5 * (vr + t1 - 1 - jnp.log(vr)))


@register_kl(Uniform, Uniform)
def _kl_uniform(p, q):
    res = jnp.log((q.high - q.low) / (p.high - p.low))
    out = jnp.where((q.low <= p.low) & (p.high <= q.high), res, jnp.inf)
    return Tensor(out)


@register_kl(Bernoulli, Bernoulli)
def _kl_bernoulli(p, q):
    pp = jnp.clip(p.probs, 1e-12, 1 - 1e-12)
    qq = jnp.clip(q.probs, 1e-12, 1 - 1e-12)
    return Tensor(pp * (jnp.log(pp) - jnp.log(qq))
                  + (1 - pp) * (jnp.log1p(-pp) - jnp.log1p(-qq)))


@register_kl(Categorical, Categorical)
def _kl_categorical(p, q):
    lp = jax.nn.log_softmax(p.logits, -1)
    lq = jax.nn.log_softmax(q.logits, -1)
    return Tensor(jnp.sum(jnp.exp(lp) * (lp - lq), -1))


@register_kl(Exponential, Exponential)
def _kl_exponential(p, q):
    r = q.rate / p.rate
    return Tensor(jnp.log(p.rate) - jnp.log(q.rate) + r - 1)


@register_kl(Laplace, Laplace)
def _kl_laplace(p, q):
    d = jnp.abs(p.loc - q.loc)
    r = p.scale / q.scale
    return Tensor(jnp.log(q.scale / p.scale) + r * jnp.exp(-d / p.scale)
                  + d / q.scale - 1)


@register_kl(Beta, Beta)
def _kl_beta(p, q):
    from jax.scipy.special import betaln, digamma
    pa, pb = p.alpha, p.beta
    qa, qb = q.alpha, q.beta
    return Tensor(betaln(qa, qb) - betaln(pa, pb)
                  + (pa - qa) * digamma(pa) + (pb - qb) * digamma(pb)
                  + (qa - pa + qb - pb) * digamma(pa + pb))


@register_kl(Gamma, Gamma)
def _kl_gamma(p, q):
    from jax.scipy.special import digamma, gammaln
    pa, pr = p.concentration, p.rate
    qa, qr = q.concentration, q.rate
    return Tensor((pa - qa) * digamma(pa) - gammaln(pa) + gammaln(qa)
                  + qa * (jnp.log(pr) - jnp.log(qr)) + pa * (qr - pr) / pr)


@register_kl(Dirichlet, Dirichlet)
def _kl_dirichlet(p, q):
    from jax.scipy.special import digamma, gammaln
    a, b = p.concentration, q.concentration
    a0 = jnp.sum(a, -1)
    return Tensor(gammaln(a0) - jnp.sum(gammaln(a), -1)
                  - gammaln(jnp.sum(b, -1)) + jnp.sum(gammaln(b), -1)
                  + jnp.sum((a - b) * (digamma(a)
                                       - digamma(a0[..., None])), -1))


# -- round-3 additions -------------------------------------------------------
class Cauchy(Distribution):
    """≙ paddle.distribution.Cauchy [U]."""

    def __init__(self, loc, scale, name=None):
        self.loc = _v(loc)
        self.scale = _v(scale)
        super().__init__(jnp.broadcast_shapes(self.loc.shape,
                                              self.scale.shape))

    @property
    def mean(self):
        raise ValueError("Cauchy distribution has no mean")

    @property
    def variance(self):
        raise ValueError("Cauchy distribution has no variance")

    @property
    def stddev(self):
        raise ValueError("Cauchy distribution has no stddev")

    def sample(self, shape=()):
        u = jax.random.uniform(_key(), _shape(shape, self.batch_shape),
                               minval=1e-7, maxval=1.0 - 1e-7)
        return Tensor(self.loc + self.scale * jnp.tan(
            math.pi * (u - 0.5)))

    rsample = sample

    def log_prob(self, value):
        z = (_v(value) - self.loc) / self.scale
        return Tensor(-jnp.log(math.pi * self.scale * (1 + z * z)))

    def entropy(self):
        e = jnp.log(4 * math.pi * self.scale)
        return Tensor(jnp.broadcast_to(e, self.batch_shape))

    def cdf(self, value):
        z = (_v(value) - self.loc) / self.scale
        return Tensor(jnp.arctan(z) / math.pi + 0.5)


class StudentT(Distribution):
    """≙ paddle.distribution.StudentT [U]."""

    def __init__(self, df, loc, scale, name=None):
        self.df = _v(df)
        self.loc = _v(loc)
        self.scale = _v(scale)
        super().__init__(jnp.broadcast_shapes(self.df.shape, self.loc.shape,
                                              self.scale.shape))

    @property
    def mean(self):
        m = jnp.where(self.df > 1, self.loc, jnp.nan)
        return Tensor(jnp.broadcast_to(m, self.batch_shape))

    @property
    def variance(self):
        v = jnp.where(
            self.df > 2, self.scale ** 2 * self.df / (self.df - 2),
            jnp.where(self.df > 1, jnp.inf, jnp.nan))
        return Tensor(jnp.broadcast_to(v, self.batch_shape))

    def sample(self, shape=()):
        z = jax.random.t(_key(), self.df,
                         _shape(shape, self.batch_shape))
        return Tensor(self.loc + self.scale * z)

    rsample = sample

    def log_prob(self, value):
        from jax.scipy.special import gammaln
        d = self.df
        z = (_v(value) - self.loc) / self.scale
        lp = (gammaln((d + 1) / 2) - gammaln(d / 2)
              - 0.5 * jnp.log(d * math.pi) - jnp.log(self.scale)
              - (d + 1) / 2 * jnp.log1p(z * z / d))
        return Tensor(lp)

    def entropy(self):
        from jax.scipy.special import digamma, gammaln
        d = self.df
        e = ((d + 1) / 2 * (digamma((d + 1) / 2) - digamma(d / 2))
             + 0.5 * jnp.log(d) + jnp.log(self.scale)
             + gammaln(d / 2) + gammaln(0.5)
             - gammaln((d + 1) / 2))
        return Tensor(jnp.broadcast_to(e, self.batch_shape))


class MultivariateNormal(Distribution):
    """≙ paddle.distribution.MultivariateNormal (full covariance) [U]."""

    def __init__(self, loc, covariance_matrix=None, precision_matrix=None,
                 scale_tril=None, name=None):
        self.loc = _v(loc)
        n = self.loc.shape[-1]
        if scale_tril is not None:
            self._tril = _v(scale_tril)
        elif covariance_matrix is not None:
            self._tril = jnp.linalg.cholesky(_v(covariance_matrix))
        elif precision_matrix is not None:
            self._tril = jnp.linalg.cholesky(
                jnp.linalg.inv(_v(precision_matrix)))
        else:
            raise ValueError("one of covariance_matrix / precision_matrix "
                             "/ scale_tril is required")
        super().__init__(self.loc.shape[:-1], (n,))

    @property
    def mean(self):
        return Tensor(self.loc)

    @property
    def covariance_matrix(self):
        return Tensor(self._tril @ jnp.swapaxes(self._tril, -1, -2))

    @property
    def variance(self):
        return Tensor(jnp.sum(self._tril ** 2, axis=-1))

    def sample(self, shape=()):
        z = jax.random.normal(
            _key(), _shape(shape, self.batch_shape + self.event_shape))
        return Tensor(self.loc + jnp.einsum("...ij,...j->...i",
                                            self._tril, z))

    rsample = sample

    def log_prob(self, value):
        d = _v(value) - self.loc
        n = self.event_shape[0]
        # solve L y = d  ->  mahalanobis = |y|^2 (tril broadcast over the
        # value's batch dims: triangular_solve wants matching batch ranks)
        tril = jnp.broadcast_to(self._tril,
                                d.shape[:-1] + self._tril.shape[-2:])
        y = jax.scipy.linalg.solve_triangular(tril, d[..., None],
                                              lower=True)[..., 0]
        half_logdet = jnp.sum(jnp.log(jnp.diagonal(
            self._tril, axis1=-2, axis2=-1)), -1)
        return Tensor(-0.5 * (n * math.log(2 * math.pi)
                              + jnp.sum(y * y, -1)) - half_logdet)

    def entropy(self):
        n = self.event_shape[0]
        half_logdet = jnp.sum(jnp.log(jnp.diagonal(
            self._tril, axis1=-2, axis2=-1)), -1)
        e = 0.5 * n * (1 + math.log(2 * math.pi)) + half_logdet
        return Tensor(jnp.broadcast_to(e, self.batch_shape))


class Binomial(Distribution):
    """≙ paddle.distribution.Binomial [U]."""

    def __init__(self, total_count, probs, name=None):
        self.total_count = _v(total_count)
        self.probs = _v(probs)
        super().__init__(jnp.broadcast_shapes(self.total_count.shape,
                                              self.probs.shape))

    @property
    def mean(self):
        return Tensor(jnp.broadcast_to(self.total_count * self.probs,
                                       self.batch_shape))

    @property
    def variance(self):
        return Tensor(jnp.broadcast_to(
            self.total_count * self.probs * (1 - self.probs),
            self.batch_shape))

    def sample(self, shape=()):
        n = jnp.broadcast_to(self.total_count, self.batch_shape)
        p = jnp.broadcast_to(self.probs, self.batch_shape)
        out = jax.random.binomial(_key(), n, p,
                                  shape=_shape(shape, self.batch_shape))
        return Tensor(out)

    def log_prob(self, value):
        from jax.scipy.special import gammaln
        k = _v(value)
        n = self.total_count
        p = jnp.clip(self.probs, 1e-7, 1 - 1e-7)
        return Tensor(gammaln(n + 1) - gammaln(k + 1) - gammaln(n - k + 1)
                      + k * jnp.log(p) + (n - k) * jnp.log1p(-p))

    def entropy(self):
        # exact sum over the support (reference does the same)
        n = int(np.max(np.asarray(self.total_count)))
        ks = jnp.arange(n + 1, dtype=jnp.float32)
        shape = (n + 1,) + tuple(1 for _ in self.batch_shape)
        lp = self.log_prob(Tensor(ks.reshape(shape)))._value
        return Tensor(-jnp.sum(jnp.where(jnp.isfinite(lp),
                                         jnp.exp(lp) * lp, 0.0), 0))


class ContinuousBernoulli(Distribution):
    """≙ paddle.distribution.ContinuousBernoulli [U]."""

    def __init__(self, probs, lims=(0.499, 0.501), name=None):
        self.probs = _v(probs)
        self._lims = lims
        super().__init__(self.probs.shape)

    def _log_norm(self):
        p = self.probs
        lo, hi = self._lims
        # C(p) = 2 atanh(1-2p) / (1-2p), with the removable singularity at
        # p=1/2 handled by a Taylor cutout (the reference does the same)
        safe = jnp.where((p < lo) | (p > hi), p, 0.25)
        c = jnp.log(2 * jnp.abs(jnp.arctanh(1 - 2 * safe))) \
            - jnp.log(jnp.abs(1 - 2 * safe))
        taylor = math.log(2.0) + 4.0 / 3.0 * (p - 0.5) ** 2
        return jnp.where((p < lo) | (p > hi), c, taylor)

    @property
    def mean(self):
        p = self.probs
        lo, hi = self._lims
        safe = jnp.where((p < lo) | (p > hi), p, 0.25)
        m = safe / (2 * safe - 1) \
            + 1 / (2 * jnp.arctanh(1 - 2 * safe))
        taylor = 0.5 + (p - 0.5) / 3.0
        return Tensor(jnp.where((p < lo) | (p > hi), m, taylor))

    @property
    def variance(self):
        # numerically: var = E[x^2] - mean^2 via the closed form
        p = self.probs
        lo, hi = self._lims
        safe = jnp.where((p < lo) | (p > hi), p, 0.25)
        t = jnp.arctanh(1 - 2 * safe)
        v = safe * (safe - 1) / (1 - 2 * safe) ** 2 + 1 / (4 * t * t)
        taylor = 1.0 / 12.0 - (p - 0.5) ** 2 / 3.0
        return Tensor(jnp.where((p < lo) | (p > hi), v, taylor))

    def sample(self, shape=()):
        u = jax.random.uniform(_key(), _shape(shape, self.batch_shape),
                               minval=1e-7, maxval=1 - 1e-7)
        p = self.probs
        lo, hi = self._lims
        safe = jnp.where((p < lo) | (p > hi), p, 0.25)
        s = (jnp.log1p(u * (2 * safe - 1) / (1 - safe))
             / (jnp.log(safe) - jnp.log1p(-safe)))
        return Tensor(jnp.where((p < lo) | (p > hi), s, u))

    rsample = sample

    def log_prob(self, value):
        x = _v(value)
        p = jnp.clip(self.probs, 1e-7, 1 - 1e-7)
        return Tensor(x * jnp.log(p) + (1 - x) * jnp.log1p(-p)
                      + self._log_norm())


class Independent(Distribution):
    """≙ paddle.distribution.Independent: reinterpret batch dims as event
    dims [U]."""

    def __init__(self, base, reinterpreted_batch_rank, name=None):
        self.base = base
        r = int(reinterpreted_batch_rank)
        self._r = r
        bs = base.batch_shape
        super().__init__(bs[:len(bs) - r],
                         bs[len(bs) - r:] + tuple(base.event_shape))

    @property
    def mean(self):
        return self.base.mean

    @property
    def variance(self):
        return self.base.variance

    def sample(self, shape=()):
        return self.base.sample(shape)

    def rsample(self, shape=()):
        return self.base.rsample(shape)

    def log_prob(self, value):
        lp = self.base.log_prob(value)._value
        return Tensor(jnp.sum(lp, axis=tuple(range(lp.ndim - self._r,
                                                   lp.ndim))))

    def entropy(self):
        e = self.base.entropy()._value
        return Tensor(jnp.sum(e, axis=tuple(range(e.ndim - self._r,
                                                  e.ndim))))


class Transform:
    """≙ paddle.distribution.Transform base (forward/inverse +
    log-det-jacobian) [U]."""

    def forward(self, x):
        return Tensor(self._forward(_v(x)))

    def inverse(self, y):
        return Tensor(self._inverse(_v(y)))

    def forward_log_det_jacobian(self, x):
        return Tensor(self._fldj(_v(x)))

    def inverse_log_det_jacobian(self, y):
        return Tensor(-self._fldj(self._inverse(_v(y))))


class AffineTransform(Transform):
    def __init__(self, loc, scale):
        self.loc, self.scale = _v(loc), _v(scale)

    def _forward(self, x):
        return self.loc + self.scale * x

    def _inverse(self, y):
        return (y - self.loc) / self.scale

    def _fldj(self, x):
        return jnp.broadcast_to(jnp.log(jnp.abs(self.scale)), x.shape)


class ExpTransform(Transform):
    def _forward(self, x):
        return jnp.exp(x)

    def _inverse(self, y):
        return jnp.log(y)

    def _fldj(self, x):
        return x


class SigmoidTransform(Transform):
    def _forward(self, x):
        return jax.nn.sigmoid(x)

    def _inverse(self, y):
        return jnp.log(y) - jnp.log1p(-y)

    def _fldj(self, x):
        return -jax.nn.softplus(-x) - jax.nn.softplus(x)


class TanhTransform(Transform):
    def _forward(self, x):
        return jnp.tanh(x)

    def _inverse(self, y):
        return jnp.arctanh(y)

    def _fldj(self, x):
        return 2.0 * (math.log(2.0) - x - jax.nn.softplus(-2.0 * x))


class TransformedDistribution(Distribution):
    """≙ paddle.distribution.TransformedDistribution [U]."""

    def __init__(self, base, transforms, name=None):
        self.base = base
        self.transforms = (transforms if isinstance(transforms, (list,
                                                                 tuple))
                           else [transforms])
        super().__init__(base.batch_shape, base.event_shape)

    def sample(self, shape=()):
        x = self.base.sample(shape)
        for t in self.transforms:
            x = t.forward(x)
        return x

    def rsample(self, shape=()):
        x = self.base.rsample(shape)
        for t in self.transforms:
            x = t.forward(x)
        return x

    def log_prob(self, value):
        y = _v(value)
        ldj = jnp.zeros_like(y)
        for t in reversed(self.transforms):
            x = t._inverse(y)
            ldj = ldj + t._fldj(x)
            y = x
        return Tensor(self.base.log_prob(Tensor(y))._value - ldj)


__all__ += ["Cauchy", "StudentT", "MultivariateNormal", "Binomial",
            "ContinuousBernoulli", "Independent", "Transform",
            "AffineTransform", "ExpTransform", "SigmoidTransform",
            "TanhTransform", "TransformedDistribution"]


@register_kl(Cauchy, Cauchy)
def _kl_cauchy(p, q):
    # closed form (Chyzak & Nielsen 2019)
    return Tensor(jnp.log(
        ((p.scale + q.scale) ** 2 + (p.loc - q.loc) ** 2)
        / (4 * p.scale * q.scale)))


@register_kl(MultivariateNormal, MultivariateNormal)
def _kl_mvn(p, q):
    n = p.event_shape[0]
    dl = jnp.diagonal(p._tril, axis1=-2, axis2=-1)
    dq = jnp.diagonal(q._tril, axis1=-2, axis2=-1)
    logdet = jnp.sum(jnp.log(dq), -1) - jnp.sum(jnp.log(dl), -1)
    m = jax.scipy.linalg.solve_triangular(
        q._tril, p._tril, lower=True)
    tr = jnp.sum(m * m, axis=(-2, -1))
    d = jax.scipy.linalg.solve_triangular(
        q._tril, (p.loc - q.loc)[..., None], lower=True)[..., 0]
    return Tensor(logdet + 0.5 * (tr + jnp.sum(d * d, -1) - n))


class AbsTransform(Transform):
    """≙ paddle.distribution.AbsTransform [U] (not bijective; inverse
    returns the positive branch like the reference)."""

    def _forward(self, x):
        return jnp.abs(x)

    def _inverse(self, y):
        return y

    def _fldj(self, x):
        return jnp.zeros_like(x)


class PowerTransform(Transform):
    """≙ paddle.distribution.PowerTransform [U]: y = x^p (x > 0)."""

    def __init__(self, power):
        self.power = _v(power)

    def _forward(self, x):
        return jnp.power(x, self.power)

    def _inverse(self, y):
        return jnp.power(y, 1.0 / self.power)

    def _fldj(self, x):
        return jnp.log(jnp.abs(self.power * jnp.power(x, self.power - 1)))


class ChainTransform(Transform):
    """≙ paddle.distribution.ChainTransform [U]: composition t_n ∘ … ∘
    t_1 (applied left to right on forward)."""

    def __init__(self, transforms):
        self.transforms = list(transforms)

    def _forward(self, x):
        for t in self.transforms:
            x = t._forward(x)
        return x

    def _inverse(self, y):
        for t in reversed(self.transforms):
            y = t._inverse(y)
        return y

    def _fldj(self, x):
        total = jnp.zeros_like(x)
        for t in self.transforms:
            total = total + t._fldj(x)
            x = t._forward(x)
        return total


class StackTransform(Transform):
    """≙ paddle.distribution.StackTransform [U]: apply the i-th transform
    to the i-th slice along `axis`."""

    def __init__(self, transforms, axis=0):
        self.transforms = list(transforms)
        self.axis = axis

    def _map(self, fn_name, x):
        parts = jnp.split(x, len(self.transforms), axis=self.axis)
        outs = [getattr(t, fn_name)(p)
                for t, p in zip(self.transforms, parts)]
        return jnp.concatenate(outs, axis=self.axis)

    def _forward(self, x):
        return self._map("_forward", x)

    def _inverse(self, y):
        return self._map("_inverse", y)

    def _fldj(self, x):
        return self._map("_fldj", x)


__all__ += ["AbsTransform", "PowerTransform", "ChainTransform",
            "StackTransform"]
