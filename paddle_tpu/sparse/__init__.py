"""paddle_tpu.sparse — COO/CSR sparse tensors and ops.

≙ reference «python/paddle/sparse/» + PHI `SparseCooTensor`/
`SparseCsrTensor` kernels (SURVEY.md §2.1/§2.2 — the ~45k-LoC sparse
subsystem). TPU-native design:

* A sparse tensor is (static index pattern, live value Tensor): the
  VALUES are a first-class autograd Tensor routed through the same
  `core.tensor.apply` op path as dense ops, so every sparse op here is
  differentiable w.r.t. values (and dense operands) through the eager
  tape and under jit — `sp.values().grad` works like the reference.
* Compute lowers to XLA gather/scatter/segment programs (and
  jax.experimental.sparse BCOO/BCSR for storage interop). Patterns are
  static per tensor; pattern-producing ops (fromdense, coalesce, binary
  union/intersection) run eagerly on concrete indices.
* 3D sparse/submanifold convolution is DENSE-BACKED (lax.conv on the
  densified volume, output masked to the active sites for SubmConv):
  semantics match the reference exactly and are tested; the
  point-cloud-scale gather/scatter kernel is a perf project for a
  later round, documented here rather than silently absent.
"""
from __future__ import annotations

from typing import Optional, Sequence

import numpy as np
import jax
import jax.numpy as jnp
from jax.experimental import sparse as jsparse

import paddle_tpu as paddle
from ..core.tensor import Tensor, apply, to_tensor

__all__ = [
    "sparse_coo_tensor", "sparse_csr_tensor", "SparseCooTensor",
    "SparseCsrTensor", "is_same_shape", "add", "subtract", "multiply",
    "divide", "matmul", "masked_matmul", "mv", "addmm", "relu",
    "transpose", "sum", "coalesce", "is_coalesced", "nn",
    # unary value ops (≙ paddle.sparse unary zoo)
    "sin", "tan", "asin", "atan", "sinh", "tanh", "asinh", "atanh",
    "sqrt", "square", "log1p", "abs", "pow", "neg", "expm1", "cast",
]


def _val(x):
    if isinstance(x, Tensor):
        return x._value
    return jnp.asarray(np.asarray(x))


class SparseCooTensor:
    """COO sparse tensor: static (nnz, ndim) indices + live value Tensor.
    ≙ phi::SparseCooTensor («paddle/phi/core/sparse_coo_tensor.h» [U])."""

    def __init__(self, bcoo_or_indices, values=None, shape=None,
                 coalesced=False):
        if isinstance(bcoo_or_indices, jsparse.BCOO):
            b = bcoo_or_indices
            self._indices = jnp.asarray(b.indices, jnp.int32)
            self._values = Tensor(b.data)
            self._shape = tuple(b.shape)
        else:
            self._indices = jnp.asarray(_val(bcoo_or_indices), jnp.int32)
            self._values = (values if isinstance(values, Tensor)
                            else Tensor(_val(values)))
            self._shape = tuple(int(s) for s in shape)
        self._coalesced = coalesced

    # -- paddle surface ------------------------------------------------
    @property
    def _bcoo(self) -> jsparse.BCOO:
        return jsparse.BCOO((self._values._value, self._indices),
                            shape=self._shape)

    @property
    def shape(self):
        return list(self._shape)

    @property
    def dtype(self):
        return self._values.dtype

    @property
    def stop_gradient(self):
        return self._values.stop_gradient

    @stop_gradient.setter
    def stop_gradient(self, v):
        self._values.stop_gradient = v

    def indices(self) -> Tensor:
        return Tensor(jnp.swapaxes(self._indices, 0, 1))

    def values(self) -> Tensor:
        """The LIVE value Tensor — gradients accumulate on it."""
        return self._values

    def nnz(self) -> int:
        return int(self._indices.shape[0])

    def to_dense(self) -> Tensor:
        idx, shape = self._indices, self._shape

        def fn(v):
            dense = jnp.zeros(shape, v.dtype)
            return dense.at[tuple(idx[:, d]
                                  for d in range(idx.shape[1]))].add(v)
        return apply("sparse_to_dense", fn, (self._values,))

    def to_sparse_csr(self) -> "SparseCsrTensor":
        c = self.coalesce()
        b = jsparse.BCSR.from_bcoo(c._bcoo)
        return SparseCsrTensor(b.indptr, b.indices, c._values,
                               self._shape)

    def coalesce(self) -> "SparseCooTensor":
        """Sum duplicate indices (≙ paddle coalesce): the output pattern
        is computed eagerly; values flow differentiably (segment-sum)."""
        if self._coalesced:
            return self
        idx = np.asarray(self._indices)
        flat = np.ravel_multi_index(
            tuple(idx[:, d] for d in range(idx.shape[1])), self._shape)
        uniq, inv = np.unique(flat, return_inverse=True)
        new_idx = jnp.asarray(np.stack(
            np.unravel_index(uniq, self._shape), axis=1), jnp.int32)
        seg = jnp.asarray(inv, jnp.int32)
        n_out = len(uniq)

        def fn(v):
            return jax.ops.segment_sum(v, seg, num_segments=n_out)
        vals = apply("sparse_coalesce", fn, (self._values,))
        return SparseCooTensor(new_idx, vals, self._shape,
                               coalesced=True)

    def is_coalesced(self) -> bool:
        return self._coalesced

    def is_sparse_coo(self):
        return True

    def is_sparse_csr(self):
        return False

    def astype(self, dtype):
        return cast(self, value_dtype=dtype)

    def __repr__(self):
        return (f"SparseCooTensor(shape={self.shape}, nnz={self.nnz()}, "
                f"dtype={self.dtype})")

    # arithmetic (dispatch to module fns)
    def __add__(self, other):
        return add(self, other)

    def __sub__(self, other):
        return subtract(self, other)

    def __mul__(self, other):
        return multiply(self, other)

    def __matmul__(self, other):
        return matmul(self, other)

    def T(self):
        return transpose(self, list(range(len(self.shape)))[::-1])


class SparseCsrTensor:
    """CSR sparse tensor: static indptr/cols + live value Tensor.
    ≙ phi::SparseCsrTensor [U]."""

    def __init__(self, bcsr_or_crows, cols=None, values=None, shape=None):
        if isinstance(bcsr_or_crows, jsparse.BCSR):
            b = bcsr_or_crows
            self._indptr = jnp.asarray(b.indptr, jnp.int32)
            self._cols = jnp.asarray(b.indices, jnp.int32)
            self._values = Tensor(b.data)
            self._shape = tuple(b.shape)
        else:
            self._indptr = jnp.asarray(_val(bcsr_or_crows), jnp.int32)
            self._cols = jnp.asarray(_val(cols), jnp.int32)
            self._values = (values if isinstance(values, Tensor)
                            else Tensor(_val(values)))
            self._shape = tuple(int(s) for s in shape)

    @property
    def _bcsr(self) -> jsparse.BCSR:
        return jsparse.BCSR((self._values._value, self._cols,
                             self._indptr), shape=self._shape)

    @property
    def shape(self):
        return list(self._shape)

    @property
    def dtype(self):
        return self._values.dtype

    def crows(self) -> Tensor:
        return Tensor(self._indptr)

    def cols(self) -> Tensor:
        return Tensor(self._cols)

    def values(self) -> Tensor:
        return self._values

    def nnz(self) -> int:
        return int(self._cols.shape[0])

    def _row_ids(self):
        counts = np.diff(np.asarray(self._indptr))
        return jnp.asarray(np.repeat(np.arange(len(counts)), counts),
                           jnp.int32)

    def to_dense(self) -> Tensor:
        rows, cols, shape = self._row_ids(), self._cols, self._shape

        def fn(v):
            return jnp.zeros(shape, v.dtype).at[rows, cols].add(v)
        return apply("sparse_csr_to_dense", fn, (self._values,))

    def to_sparse_coo(self, sparse_dim=None) -> SparseCooTensor:
        idx = jnp.stack([self._row_ids(), self._cols], axis=1)
        return SparseCooTensor(idx, self._values, self._shape,
                               coalesced=True)

    def is_sparse_coo(self):
        return False

    def is_sparse_csr(self):
        return True

    def __repr__(self):
        return (f"SparseCsrTensor(shape={self.shape}, nnz={self.nnz()}, "
                f"dtype={self.dtype})")

    def __matmul__(self, other):
        return matmul(self, other)


def sparse_coo_tensor(indices, values, shape=None, dtype=None, place=None,
                      stop_gradient=True):
    """≙ paddle.sparse.sparse_coo_tensor: indices (ndim, nnz), values
    (nnz, ...)."""
    idx = _val(indices).astype(jnp.int32)
    vals = _val(values)
    if dtype is not None:
        from ..core.dtype import convert_dtype
        vals = vals.astype(convert_dtype(dtype))
    if shape is None:
        shape = tuple(int(i) + 1 for i in np.asarray(idx.max(axis=1)))
    t = Tensor(vals, stop_gradient=stop_gradient)
    return SparseCooTensor(jnp.swapaxes(idx, 0, 1), t, shape)


def sparse_csr_tensor(crows, cols, values, shape, dtype=None, place=None,
                      stop_gradient=True):
    """≙ paddle.sparse.sparse_csr_tensor."""
    vals = _val(values)
    if dtype is not None:
        from ..core.dtype import convert_dtype
        vals = vals.astype(convert_dtype(dtype))
    t = Tensor(vals, stop_gradient=stop_gradient)
    return SparseCsrTensor(_val(crows).astype(jnp.int32),
                           _val(cols).astype(jnp.int32), t, shape)


def is_same_shape(x, y) -> bool:
    return list(x.shape) == list(y.shape)


def _coo(x):
    if isinstance(x, SparseCsrTensor):
        return x.to_sparse_coo()
    return x


def coalesce(x, name=None):
    return _coo(x).coalesce()


def is_coalesced(x) -> bool:
    return _coo(x).is_coalesced()


# -- unary value ops ---------------------------------------------------
def _unary(name, f):
    def op(x, name=None):
        c = _coo(x) if isinstance(x, SparseCsrTensor) else x
        vals = apply(f"sparse_{op.__name__}", f, (c._values,))
        out = SparseCooTensor(c._indices, vals, c._shape,
                              coalesced=c._coalesced)
        return out.to_sparse_csr() if isinstance(x, SparseCsrTensor) \
            else out
    op.__name__ = name
    op.__doc__ = f"≙ paddle.sparse.{name} (element-wise on values)."
    return op


sin = _unary("sin", jnp.sin)
tan = _unary("tan", jnp.tan)
asin = _unary("asin", jnp.arcsin)
atan = _unary("atan", jnp.arctan)
sinh = _unary("sinh", jnp.sinh)
tanh = _unary("tanh", jnp.tanh)
asinh = _unary("asinh", jnp.arcsinh)
atanh = _unary("atanh", jnp.arctanh)
sqrt = _unary("sqrt", jnp.sqrt)
square = _unary("square", jnp.square)
log1p = _unary("log1p", jnp.log1p)
abs = _unary("abs", jnp.abs)
neg = _unary("neg", jnp.negative)
expm1 = _unary("expm1", jnp.expm1)
relu = _unary("relu", jax.nn.relu)


def pow(x, factor, name=None):
    op = _unary("pow", lambda v: jnp.power(v, factor))
    return op(x)


def cast(x, index_dtype=None, value_dtype=None, name=None):
    """≙ paddle.sparse.cast. Note: index storage is int32 (XLA's native
    index width; int64 needs jax x64 mode) — an int64 request that
    cannot be honored warns instead of silently no-op'ing."""
    from ..core.dtype import convert_dtype
    c = _coo(x) if isinstance(x, SparseCsrTensor) else x
    idx = c._indices
    if index_dtype is not None:
        dt_i = convert_dtype(index_dtype)
        idx = idx.astype(dt_i)
        if idx.dtype != np.dtype(dt_i):
            import warnings
            warnings.warn(
                f"sparse.cast: index_dtype={index_dtype} not "
                f"representable without jax x64 mode; indices stay "
                f"{idx.dtype}")
    vals = c._values
    if value_dtype is not None:
        dt = convert_dtype(value_dtype)
        vals = apply("sparse_cast", lambda v: v.astype(dt), (vals,))
    out = SparseCooTensor(idx, vals, c._shape, coalesced=c._coalesced)
    out._indices = idx          # preserve the requested index dtype
    return out.to_sparse_csr() if isinstance(x, SparseCsrTensor) else out


# -- binary ops (union / intersection patterns, differentiable) --------
def _binary(x, y, op, name, intersect=False):
    if not (isinstance(x, (SparseCooTensor, SparseCsrTensor))
            and isinstance(y, (SparseCooTensor, SparseCsrTensor))):
        raise TypeError(f"{name}: both operands must be sparse")
    was_csr = isinstance(x, SparseCsrTensor)
    cx, cy = _coo(x).coalesce(), _coo(y).coalesce()
    if cx._shape != cy._shape:
        raise ValueError(f"sparse.{name}: shape mismatch "
                         f"{cx._shape} vs {cy._shape}")
    shape = cx._shape
    fx = np.ravel_multi_index(
        tuple(np.asarray(cx._indices)[:, d]
              for d in range(len(shape))), shape)
    fy = np.ravel_multi_index(
        tuple(np.asarray(cy._indices)[:, d]
              for d in range(len(shape))), shape)
    if intersect:
        out_flat = np.intersect1d(fx, fy)
    else:
        out_flat = np.union1d(fx, fy)

    def _gather_plan(f, n_out):
        """(clamped positions, validity mask) of out entries in f —
        empty-operand-safe (validity all False when f is empty)."""
        if len(f) == 0:
            return (jnp.zeros((n_out,), jnp.int32),
                    jnp.zeros((n_out,), bool))
        p = np.searchsorted(f, out_flat)
        valid = (p < len(f)) & (f[np.minimum(p, len(f) - 1)]
                                == out_flat)
        return (jnp.asarray(np.minimum(p, len(f) - 1), jnp.int32),
                jnp.asarray(valid))

    n_out = len(out_flat)
    gx, mx = _gather_plan(fx, n_out)
    gy, my = _gather_plan(fy, n_out)
    new_idx = jnp.asarray(np.stack(
        np.unravel_index(out_flat, shape), axis=1).reshape(
            n_out, len(shape)), jnp.int32)

    def fn(vx, vy):
        a = jnp.where(mx, vx[gx], 0) if vx.shape[0] else \
            jnp.zeros((n_out,), vy.dtype)
        b = jnp.where(my, vy[gy], 0) if vy.shape[0] else \
            jnp.zeros((n_out,), vx.dtype)
        return op(a, b)
    vals = apply(f"sparse_{name}", fn, (cx._values, cy._values))
    out = SparseCooTensor(new_idx, vals, shape, coalesced=True)
    return out.to_sparse_csr() if was_csr else out


def add(x, y, name=None):
    return _binary(x, y, jnp.add, "add")


def subtract(x, y, name=None):
    return _binary(x, y, jnp.subtract, "subtract")


def multiply(x, y, name=None):
    return _binary(x, y, jnp.multiply, "multiply", intersect=True)


def divide(x, y, name=None):
    def _div(a, b):
        return jnp.where(b != 0, a / jnp.where(b == 0, 1, b), 0)
    return _binary(x, y, _div, "divide", intersect=True)


# -- matmul family -----------------------------------------------------
def matmul(x, y, name=None):
    """sparse @ dense (SpMM, differentiable in values AND the dense
    operand; 1-D dense routes to mv), or sparse @ sparse (dense
    result). ≙ paddle.sparse.matmul."""
    if isinstance(y, (Tensor, np.ndarray, jnp.ndarray)):
        c = _coo(x)
        if len(c._shape) != 2:
            raise ValueError(
                f"sparse.matmul supports 2-D sparse operands, got "
                f"shape {c._shape}")
        yt = y if isinstance(y, Tensor) else Tensor(_val(y))
        if yt._value.ndim == 1:
            return mv(c, yt)
        if yt._value.ndim != 2:
            raise ValueError(
                f"sparse.matmul dense operand must be 1-D or 2-D, got "
                f"{yt._value.ndim}-D")
        rows = c._indices[:, 0]
        cols = c._indices[:, 1]
        n_rows = c._shape[0]

        def fn(v, yv):
            contrib = v[:, None] * yv[cols]            # (nnz, N)
            return jax.ops.segment_sum(contrib, rows,
                                       num_segments=n_rows)
        return apply("sparse_matmul", fn, (c._values, yt))
    if isinstance(y, (SparseCooTensor, SparseCsrTensor)):
        cx, cy = _coo(x), _coo(y)
        xd = cx.to_dense()
        yd = cy.to_dense()
        return paddle.matmul(xd, yd)
    raise TypeError("matmul: unsupported operand types")


def mv(x, vec, name=None):
    """sparse (M, N) @ dense vector (N,). ≙ paddle.sparse.mv."""
    c = _coo(x)
    rows, cols = c._indices[:, 0], c._indices[:, 1]
    n_rows = c._shape[0]
    vt = vec if isinstance(vec, Tensor) else Tensor(_val(vec))

    def fn(v, yv):
        return jax.ops.segment_sum(v * yv[cols], rows,
                                   num_segments=n_rows)
    return apply("sparse_mv", fn, (c._values, vt))


def addmm(input, x, y, beta=1.0, alpha=1.0, name=None):
    """beta*input + alpha*(x @ y), x sparse. ≙ paddle.sparse.addmm."""
    prod = matmul(x, y)
    it = input if isinstance(input, Tensor) else Tensor(_val(input))
    return it * beta + prod * alpha


def masked_matmul(x, y, mask, name=None):
    """SDDMM: (x @ y) sampled at mask's sparsity pattern — the sparse
    output never densifies. Differentiable in x and y.
    ≙ paddle.sparse.masked_matmul."""
    m = _coo(mask)
    rows, cols = m._indices[:, 0], m._indices[:, 1]
    xt = x if isinstance(x, Tensor) else Tensor(_val(x))
    yt = y if isinstance(y, Tensor) else Tensor(_val(y))

    def fn(xv, yv):
        return jnp.einsum("nk,nk->n", xv[rows, :],
                          jnp.swapaxes(yv, 0, 1)[cols])
    vals = apply("sparse_sddmm", fn, (xt, yt))
    return SparseCooTensor(m._indices, vals, m._shape,
                           coalesced=m._coalesced)


def transpose(x, perm, name=None):
    c = _coo(x)
    perm = tuple(perm)
    new_idx = c._indices[:, list(perm)]
    new_shape = tuple(c._shape[p] for p in perm)
    out = SparseCooTensor(new_idx, c._values, new_shape)
    return out.to_sparse_csr() if isinstance(x, SparseCsrTensor) else out


def sum(x, axis=None, dtype=None, keepdim=False, name=None):
    """≙ paddle.sparse.sum (dense result; differentiable in values)."""
    c = _coo(x)
    idx, shape = c._indices, c._shape

    def fn(v):
        dense = jnp.zeros(shape, v.dtype).at[
            tuple(idx[:, d] for d in range(idx.shape[1]))].add(v)
        return jnp.sum(dense, axis=axis, keepdims=keepdim)
    out = apply("sparse_sum", fn, (c._values,))
    if dtype is not None:
        out = out.astype(dtype)
    return out


from . import nn  # noqa: E402,F401
