"""paddle_tpu.sparse — COO/CSR sparse tensors and ops.

≙ reference «python/paddle/sparse/» + PHI `SparseCooTensor`/
`SparseCsrTensor` kernels (SURVEY.md §2.1/§2.2). TPU-native substrate is
jax.experimental.sparse (BCOO/BCSR): XLA lowers sparse ops to
gather/scatter/segment-sum programs. Dense fallbacks keep semantics exact
where BCOO lacks an op.
"""
from __future__ import annotations

from typing import Optional, Sequence

import numpy as np
import jax
import jax.numpy as jnp
from jax.experimental import sparse as jsparse

import paddle_tpu as paddle
from ..core.tensor import Tensor, to_tensor

__all__ = ["sparse_coo_tensor", "sparse_csr_tensor", "SparseCooTensor",
           "SparseCsrTensor", "is_same_shape", "add", "subtract",
           "multiply", "divide", "matmul", "masked_matmul", "relu",
           "transpose", "sum", "nn"]


class SparseCooTensor:
    """COO sparse tensor wrapping jax BCOO.
    ≙ phi::SparseCooTensor («paddle/phi/core/sparse_coo_tensor.h» [U])."""

    def __init__(self, bcoo: jsparse.BCOO):
        self._bcoo = bcoo

    # -- paddle surface ------------------------------------------------------
    @property
    def shape(self):
        return list(self._bcoo.shape)

    @property
    def dtype(self):
        return self._bcoo.dtype

    def indices(self) -> Tensor:
        return Tensor(jnp.swapaxes(self._bcoo.indices, 0, 1))

    def values(self) -> Tensor:
        return Tensor(self._bcoo.data)

    def nnz(self) -> int:
        return int(self._bcoo.nse)

    def to_dense(self) -> Tensor:
        return Tensor(self._bcoo.todense())

    def to_sparse_csr(self) -> "SparseCsrTensor":
        return SparseCsrTensor(jsparse.BCSR.from_bcoo(
            self._bcoo.sum_duplicates()))

    def coalesce(self) -> "SparseCooTensor":
        return SparseCooTensor(self._bcoo.sum_duplicates())

    def is_sparse_coo(self):
        return True

    def is_sparse_csr(self):
        return False

    def __repr__(self):
        return (f"SparseCooTensor(shape={self.shape}, nnz={self.nnz()}, "
                f"dtype={self.dtype})")

    # arithmetic (dispatch to module fns)
    def __add__(self, other):
        return add(self, other)

    def __sub__(self, other):
        return subtract(self, other)

    def __mul__(self, other):
        return multiply(self, other)

    def __matmul__(self, other):
        return matmul(self, other)

    def T(self):
        return transpose(self, list(range(len(self.shape)))[::-1])


class SparseCsrTensor:
    """CSR sparse tensor wrapping jax BCSR.
    ≙ phi::SparseCsrTensor [U]."""

    def __init__(self, bcsr: jsparse.BCSR):
        self._bcsr = bcsr

    @property
    def shape(self):
        return list(self._bcsr.shape)

    @property
    def dtype(self):
        return self._bcsr.dtype

    def crows(self) -> Tensor:
        return Tensor(self._bcsr.indptr)

    def cols(self) -> Tensor:
        return Tensor(self._bcsr.indices)

    def values(self) -> Tensor:
        return Tensor(self._bcsr.data)

    def nnz(self) -> int:
        return int(self._bcsr.nse)

    def to_dense(self) -> Tensor:
        return Tensor(self._bcsr.todense())

    def to_sparse_coo(self, sparse_dim=None) -> SparseCooTensor:
        return SparseCooTensor(self._bcsr.to_bcoo())

    def is_sparse_coo(self):
        return False

    def is_sparse_csr(self):
        return True

    def __repr__(self):
        return (f"SparseCsrTensor(shape={self.shape}, nnz={self.nnz()}, "
                f"dtype={self.dtype})")

    def __matmul__(self, other):
        return matmul(self, other)


def _val(x):
    if isinstance(x, Tensor):
        return x._value
    return jnp.asarray(np.asarray(x))


def sparse_coo_tensor(indices, values, shape=None, dtype=None, place=None,
                      stop_gradient=True):
    """≙ paddle.sparse.sparse_coo_tensor: indices (ndim, nnz), values
    (nnz, ...)."""
    idx = _val(indices).astype(jnp.int32)
    vals = _val(values)
    if dtype is not None:
        from ..core.dtype import convert_dtype
        vals = vals.astype(convert_dtype(dtype))
    if shape is None:
        shape = tuple(int(i) + 1 for i in np.asarray(idx.max(axis=1)))
    bcoo = jsparse.BCOO((vals, jnp.swapaxes(idx, 0, 1)),
                        shape=tuple(shape))
    return SparseCooTensor(bcoo)


def sparse_csr_tensor(crows, cols, values, shape, dtype=None, place=None,
                      stop_gradient=True):
    """≙ paddle.sparse.sparse_csr_tensor."""
    vals = _val(values)
    if dtype is not None:
        from ..core.dtype import convert_dtype
        vals = vals.astype(convert_dtype(dtype))
    bcsr = jsparse.BCSR((vals, _val(cols).astype(jnp.int32),
                         _val(crows).astype(jnp.int32)),
                        shape=tuple(shape))
    return SparseCsrTensor(bcsr)


def is_same_shape(x, y) -> bool:
    return list(x.shape) == list(y.shape)


def _coo(x):
    if isinstance(x, SparseCsrTensor):
        return x.to_sparse_coo()
    return x


def _binary(x, y, op, name):
    if isinstance(x, (SparseCooTensor, SparseCsrTensor)) and \
            isinstance(y, (SparseCooTensor, SparseCsrTensor)):
        was_csr = isinstance(x, SparseCsrTensor)
        xd = _coo(x)._bcoo.todense()
        yd = _coo(y)._bcoo.todense()
        dense = op(xd, yd)
        out = SparseCooTensor(jsparse.BCOO.fromdense(dense))
        return out.to_sparse_csr() if was_csr else out
    raise TypeError(f"{name}: both operands must be sparse")


def add(x, y, name=None):
    return _binary(x, y, jnp.add, "add")


def subtract(x, y, name=None):
    return _binary(x, y, jnp.subtract, "subtract")


def multiply(x, y, name=None):
    return _binary(x, y, jnp.multiply, "multiply")


def divide(x, y, name=None):
    def _div(a, b):
        return jnp.where(b != 0, a / jnp.where(b == 0, 1, b), 0)
    return _binary(x, y, _div, "divide")


def matmul(x, y, name=None):
    """sparse @ dense (spmm) or sparse @ sparse (result dense → sparse).
    ≙ paddle.sparse.matmul."""
    if isinstance(y, Tensor) or isinstance(y, (np.ndarray, jnp.ndarray)):
        yv = _val(y)
        if isinstance(x, SparseCsrTensor):
            out = x._bcsr @ yv
        else:
            out = x._bcoo @ yv
        return Tensor(out)
    if isinstance(y, (SparseCooTensor, SparseCsrTensor)):
        xd = _coo(x)._bcoo.todense() if isinstance(
            x, (SparseCooTensor, SparseCsrTensor)) else _val(x)
        yd = _coo(y)._bcoo.todense()
        return Tensor(xd @ yd)
    raise TypeError("matmul: unsupported operand types")


def masked_matmul(x, y, mask, name=None):
    """dense @ dense with sparse output pattern (SDDMM).
    ≙ paddle.sparse.masked_matmul."""
    xv, yv = _val(x), _val(y)
    m = _coo(mask)._bcoo
    rows = m.indices[:, 0]
    cols = m.indices[:, 1]
    vals = jnp.einsum("nk,nk->n", xv[rows, :], jnp.swapaxes(yv, 0, 1)[cols])
    return SparseCooTensor(jsparse.BCOO((vals, m.indices), shape=m.shape))


def relu(x, name=None):
    c = _coo(x)
    out = SparseCooTensor(jsparse.BCOO(
        (jax.nn.relu(c._bcoo.data), c._bcoo.indices), shape=c._bcoo.shape))
    return out.to_sparse_csr() if isinstance(x, SparseCsrTensor) else out


def transpose(x, perm, name=None):
    c = _coo(x)
    out = SparseCooTensor(c._bcoo.transpose(tuple(perm)))
    return out.to_sparse_csr() if isinstance(x, SparseCsrTensor) else out


def sum(x, axis=None, dtype=None, keepdim=False, name=None):
    c = _coo(x)
    dense = c._bcoo.todense()
    out = jnp.sum(dense, axis=axis, keepdims=keepdim)
    if dtype is not None:
        from ..core.dtype import convert_dtype
        out = out.astype(convert_dtype(dtype))
    return Tensor(out)


class _SparseNN:
    """paddle.sparse.nn subset: functional relu/softmax on sparse values."""

    class ReLU:
        def __call__(self, x):
            return relu(x)

    @staticmethod
    def functional_relu(x):
        return relu(x)


nn = _SparseNN()
