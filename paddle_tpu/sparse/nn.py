"""paddle_tpu.sparse.nn — sparse layers + functional.

≙ reference «python/paddle/sparse/nn/» (ReLU/Softmax layers, sparse
attention, Conv3D/SubmConv3D, BatchNorm, MaxPool3D). See the package
docstring for the dense-backed-conv design note.
"""
from __future__ import annotations

from typing import Optional

import numpy as np
import jax
import jax.numpy as jnp

from ..core.tensor import Tensor, apply
from ..nn.layer.layers import Layer

__all__ = ["functional", "ReLU", "ReLU6", "LeakyReLU", "Softmax",
           "Conv3D", "SubmConv3D", "BatchNorm", "MaxPool3D"]


class functional:
    """paddle.sparse.nn.functional."""

    @staticmethod
    def relu(x, name=None):
        from . import relu as _relu
        return _relu(x)

    @staticmethod
    def relu6(x, name=None):
        from . import _unary
        return _unary("relu6", lambda v: jnp.clip(v, 0, 6))(x)

    @staticmethod
    def leaky_relu(x, negative_slope=0.01, name=None):
        from . import _unary
        return _unary("leaky_relu",
                      lambda v: jnp.where(v > 0, v,
                                          negative_slope * v))(x)

    @staticmethod
    def softmax(x, axis=-1, name=None):
        """Row-wise softmax over the LAST sparse dim's stored entries
        (≙ paddle.sparse.nn.functional.softmax on 2-D CSR/COO: absent
        entries are -inf, i.e. excluded). Differentiable in values."""
        from . import SparseCooTensor, SparseCsrTensor, _coo
        if axis not in (-1, len(x.shape) - 1):
            raise ValueError("sparse softmax supports the last axis")
        c = _coo(x)
        nd = len(c._shape)
        # segment = all leading dims flattened (a 'row')
        if nd == 1:
            seg = jnp.zeros((c.nnz(),), jnp.int32)
            n_seg = 1
        else:
            lead = np.asarray(c._indices[:, :nd - 1])
            sizes = c._shape[:nd - 1]
            seg = jnp.asarray(np.ravel_multi_index(
                tuple(lead[:, d] for d in range(nd - 1)), sizes),
                jnp.int32)
            n_seg = int(np.prod(sizes))

        def fn(v):
            m = jax.ops.segment_max(v, seg, num_segments=n_seg)
            e = jnp.exp(v - m[seg])
            z = jax.ops.segment_sum(e, seg, num_segments=n_seg)
            return e / z[seg]
        vals = apply("sparse_softmax", fn, (c._values,))
        out = SparseCooTensor(c._indices, vals, c._shape,
                              coalesced=c._coalesced)
        return out.to_sparse_csr() if isinstance(x, SparseCsrTensor) \
            else out

    @staticmethod
    def attention(query, key, value, sparse_mask, key_padding_mask=None,
                  attn_mask=None, name=None):
        """Mask-driven sparse attention (≙ paddle.sparse sparse_attention
        / nn.functional.attention): scores are computed ONLY at the
        mask's (S, S) sparsity pattern (SDDMM), row-softmaxed over the
        stored entries, then combined with V (SpMM) — the (S, S) dense
        score matrix never exists. query/key/value: (B, H, S, D); the
        pattern is shared across batch and heads. Differentiable in
        q/k/v."""
        from . import _coo
        m = _coo(sparse_mask)
        rows = m._indices[:, 0]
        cols = m._indices[:, 1]
        s_len = m._shape[0]
        qt, kt, vt = query, key, value

        def fn(q, k, v):
            d = q.shape[-1]
            qr = q[..., rows, :]                        # (B, H, nnz, D)
            kc = k[..., cols, :]
            scores = jnp.einsum("...nd,...nd->...n", qr, kc) \
                / jnp.sqrt(jnp.float32(d)).astype(q.dtype)
            sm = jax.ops.segment_max(
                jnp.moveaxis(scores, -1, 0), rows, num_segments=s_len)
            e = jnp.exp(jnp.moveaxis(scores, -1, 0) - sm[rows])
            z = jax.ops.segment_sum(e, rows, num_segments=s_len)
            p = e / z[rows]                             # (nnz, B, H)
            contrib = p[..., None] * jnp.moveaxis(
                v, -2, 0)[cols]                         # (nnz, B, H, D)
            out = jax.ops.segment_sum(contrib, rows,
                                      num_segments=s_len)
            return jnp.moveaxis(out, 0, -2)             # (B, H, S, D)
        return apply("sparse_attention", fn, (qt, kt, vt))


class ReLU(Layer):
    def forward(self, x):
        return functional.relu(x)


class ReLU6(Layer):
    def forward(self, x):
        return functional.relu6(x)


class LeakyReLU(Layer):
    def __init__(self, negative_slope=0.01):
        super().__init__()
        self.negative_slope = negative_slope

    def forward(self, x):
        return functional.leaky_relu(x, self.negative_slope)


class Softmax(Layer):
    def __init__(self, axis=-1):
        super().__init__()
        self.axis = axis

    def forward(self, x):
        return functional.softmax(x, self.axis)


def _dense_conv3d(xd, w, b, stride, padding, subm_mask=None):
    """x (N, D, H, W, C) dense, w (kd, kh, kw, Cin, Cout)."""
    out = jax.lax.conv_general_dilated(
        xd, w, window_strides=(stride,) * 3,
        padding=[(padding, padding)] * 3,
        dimension_numbers=("NDHWC", "DHWIO", "NDHWC"))
    if b is not None:
        out = out + b
    if subm_mask is not None:
        out = out * subm_mask
    return out


class _ConvBase(Layer):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, subm=False, bias_attr=None):
        super().__init__()
        from ..nn import initializer as I
        k = (kernel_size,) * 3 if isinstance(kernel_size, int) \
            else tuple(kernel_size)
        self.stride = stride if isinstance(stride, int) else stride[0]
        self.padding = padding if isinstance(padding, int) else padding[0]
        self.subm = subm
        fan_in = in_channels * int(np.prod(k))
        self.weight = self.create_parameter(
            k + (in_channels, out_channels),
            default_initializer=I.XavierNormal(fan_in=fan_in,
                                               fan_out=out_channels))
        self.bias = (None if bias_attr is False else
                     self.create_parameter(
                         (out_channels,),
                         default_initializer=I.Constant(0.0)))

    def forward(self, x):
        """x: SparseCooTensor (N, D, H, W, C). Dense-backed compute; the
        output pattern is the active output sites (SubmConv: exactly the
        input sites; Conv3D: nonzero outputs)."""
        from . import SparseCooTensor
        xd = x.to_dense()
        idx = x._indices

        if self.subm:
            if self.stride != 1:
                raise ValueError("SubmConv3D requires stride 1")
            mask_np = np.zeros(tuple(x._shape[:4]) + (1,), np.float32)
            sites = np.asarray(idx)[:, :4]
            mask_np[tuple(sites[:, d] for d in range(4))] = 1.0
            mask = jnp.asarray(mask_np)
        else:
            mask = None

        args = (xd, self.weight) + (() if self.bias is None
                                    else (self.bias,))

        def fn(xv, wv, *bv):
            return _dense_conv3d(xv, wv.astype(xv.dtype),
                                 bv[0].astype(xv.dtype) if bv else None,
                                 self.stride, self.padding, mask)
        out_dense = apply("sparse_conv3d", fn, args)

        if self.subm:
            # output sites == input SPATIAL sites (the submanifold
            # property) x every output channel
            sites = np.unique(np.asarray(idx)[:, :4], axis=0)
            cout = int(self.weight.shape[-1])
            ch = np.arange(cout)
            out_idx = jnp.asarray(np.concatenate(
                [np.repeat(sites, cout, 0),
                 np.tile(ch[:, None], (len(sites), 1))], axis=1),
                jnp.int32)
        else:
            dn = np.asarray(out_dense._value)
            nz = np.argwhere(np.any(dn != 0, axis=-1))
            ch = np.arange(dn.shape[-1])
            out_idx = jnp.asarray(np.concatenate(
                [np.repeat(nz, len(ch), 0),
                 np.tile(ch[:, None], (len(nz), 1))], axis=1), jnp.int32)
        rows = tuple(out_idx[:, d] for d in range(out_idx.shape[1]))

        def gather(dv):
            return dv[rows]
        vals = apply("sparse_conv3d_gather", gather, (out_dense,))
        return SparseCooTensor(out_idx, vals,
                               tuple(out_dense._value.shape)
                               if not self.subm else
                               tuple(x._shape[:4])
                               + (self.weight.shape[-1],))


class Conv3D(_ConvBase):
    """≙ paddle.sparse.nn.Conv3D (dense-backed; see package doc)."""

    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, bias_attr=None,
                 data_format="NDHWC"):
        super().__init__(in_channels, out_channels, kernel_size, stride,
                         padding, subm=False, bias_attr=bias_attr)


class SubmConv3D(_ConvBase):
    """≙ paddle.sparse.nn.SubmConv3D: submanifold convolution — outputs
    exist ONLY at input active sites, so sparsity never dilates (the
    point-cloud property). Dense-backed compute with an active-site
    mask; semantics exact."""

    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, bias_attr=None,
                 key=None, data_format="NDHWC"):
        super().__init__(in_channels, out_channels, kernel_size, stride,
                         padding, subm=True, bias_attr=bias_attr)


class BatchNorm(Layer):
    """≙ paddle.sparse.nn.BatchNorm: normalizes the VALUES per channel
    (last dim) over the stored entries only."""

    def __init__(self, num_features, momentum=0.9, epsilon=1e-5,
                 data_format="NDHWC", name=None):
        super().__init__()
        from ..nn import initializer as I
        self.epsilon = epsilon
        self.momentum = momentum
        self.weight = self.create_parameter(
            (num_features,), default_initializer=I.Constant(1.0))
        self.bias = self.create_parameter(
            (num_features,), default_initializer=I.Constant(0.0))
        self.register_buffer("_mean", Tensor(jnp.zeros(num_features)))
        self.register_buffer("_variance",
                             Tensor(jnp.ones(num_features)))

    def forward(self, x):
        """x: SparseCooTensor whose LAST index dim is the channel
        (values are flat per-entry scalars): per-channel stats over the
        stored entries via channel-segmented reductions."""
        from . import SparseCooTensor
        c = x
        training = self.training
        mom, eps = self.momentum, self.epsilon
        rm, rv = self._mean, self._variance
        ch = c._indices[:, -1]
        nf = int(self.weight.shape[0])

        def fn(v, w, b, m, va):
            if training:
                cnt = jnp.maximum(jax.ops.segment_sum(
                    jnp.ones_like(v), ch, num_segments=nf), 1.0)
                mean = jax.ops.segment_sum(v, ch,
                                           num_segments=nf) / cnt
                var = jax.ops.segment_sum(
                    jnp.square(v), ch, num_segments=nf) / cnt \
                    - jnp.square(mean)
            else:
                mean, var = m, va
            out = (v - mean[ch]) * jax.lax.rsqrt(var[ch] + eps) \
                * w[ch] + b[ch]
            return out, mean, var
        vals, mean, var = apply("sparse_batch_norm", fn,
                                (c._values, self.weight, self.bias,
                                 rm, rv), multi_output=True)
        if training:
            self._mean._value = (mom * rm._value
                                 + (1 - mom) * mean._value)
            self._variance._value = (mom * rv._value
                                     + (1 - mom) * var._value)
        return SparseCooTensor(c._indices, vals, c._shape,
                               coalesced=c._coalesced)


class MaxPool3D(Layer):
    """≙ paddle.sparse.nn.MaxPool3D (dense-backed)."""

    def __init__(self, kernel_size, stride=None, padding=0,
                 data_format="NDHWC", name=None):
        super().__init__()
        self.k = (kernel_size,) * 3 if isinstance(kernel_size, int) \
            else tuple(kernel_size)
        s = stride if stride is not None else kernel_size
        self.s = (s,) * 3 if isinstance(s, int) else tuple(s)
        self.p = padding

    def forward(self, x):
        from . import SparseCooTensor
        xd = x.to_dense()
        k, s, p = self.k, self.s, self.p
        win = ((1,) + k + (1,), (1,) + s + (1,),
               [(0, 0)] + [(p, p)] * 3 + [(0, 0)])

        # occupancy mask: empty cells pool as -inf (stored-entries-only
        # semantics), and the output pattern is windows containing ANY
        # active site — value sign must not decide liveness
        occ = np.zeros(tuple(x._shape), np.float32)
        ii = np.asarray(x._indices)
        occ[tuple(ii[:, d] for d in range(ii.shape[1]))] = 1.0
        occ_j = jnp.asarray(occ) > 0

        def fn(v):
            filled = jnp.where(occ_j, v, -jnp.inf)
            return jax.lax.reduce_window(filled, -jnp.inf, jax.lax.max,
                                         *win)
        dense = apply("sparse_max_pool3d", fn, (xd,))
        occ_pooled = np.asarray(jax.lax.reduce_window(
            jnp.asarray(occ), -jnp.inf, jax.lax.max, *win))
        nz = np.argwhere(occ_pooled > 0)
        idx = jnp.asarray(nz, jnp.int32)
        rows = tuple(idx[:, d] for d in range(idx.shape[1]))
        vals = apply("sparse_pool_gather", lambda dv: dv[rows], (dense,))
        return SparseCooTensor(idx, vals, occ_pooled.shape)
