"""paddle_tpu.geometric — graph learning ops.

≙ reference «python/paddle/geometric/» [U] (segment ops + graph
message-passing send/recv, SURVEY.md §2.2 Python-API row). TPU-first
design: everything lowers to `jax.ops.segment_*` scatter-reductions,
which XLA compiles to efficient sorted-segment kernels; there is no
dynamic shape anywhere as long as `out_size`/`num_segments` is given
(mandatory under jit — eager falls back to `max(ids) + 1`, which incurs
a D2H sync, exactly like the reference's dynamic-shape GPU kernels).
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ..core.tensor import Tensor, apply, to_tensor

__all__ = [
    "segment_sum", "segment_mean", "segment_min", "segment_max",
    "send_u_recv", "send_ue_recv", "send_uv",
]


def _t(x):
    return x if isinstance(x, Tensor) else to_tensor(x)


def _num_segments(ids: Tensor, out_size) -> int:
    if out_size is not None:
        return int(out_size)
    if not ids.shape[0]:
        return 0
    try:
        # eager-only path: concretize (D2H sync)
        return int(np.asarray(ids._value).max()) + 1
    except jax.errors.TracerArrayConversionError:
        raise ValueError(
            "segment op under jit needs a static segment count: XLA has no "
            "dynamic output shapes. Use send_u_recv(..., out_size=N) or "
            "call the segment op outside the traced region.") from None


_SEG = {
    "sum": jax.ops.segment_sum,
    "mean": jax.ops.segment_sum,     # divided by counts below
    "min": jax.ops.segment_min,
    "max": jax.ops.segment_max,
}


def _segment(op_name, data, segment_ids, n, reduce):
    def fn(d, ids):
        out = _SEG[reduce](d, ids, num_segments=n)
        if reduce == "mean":
            cnt = jax.ops.segment_sum(jnp.ones(ids.shape, d.dtype), ids,
                                      num_segments=n)
            out = out / jnp.maximum(cnt, 1).reshape(
                (-1,) + (1,) * (d.ndim - 1))
        if reduce in ("min", "max"):
            # empty segments come back +/-inf; the reference zeroes them
            cnt = jax.ops.segment_sum(jnp.ones(ids.shape, jnp.int32), ids,
                                      num_segments=n)
            mask = (cnt > 0).reshape((-1,) + (1,) * (d.ndim - 1))
            out = jnp.where(mask, out, jnp.zeros_like(out))
        return out
    return apply(op_name, fn, (data, segment_ids))


def segment_sum(data, segment_ids, name=None):
    """≙ paddle.geometric.segment_sum. segment_ids must be sorted in the
    reference; here any order works (scatter-add)."""
    data, segment_ids = _t(data), _t(segment_ids)
    return _segment("segment_sum", data, segment_ids,
                    _num_segments(segment_ids, None), "sum")


def segment_mean(data, segment_ids, name=None):
    data, segment_ids = _t(data), _t(segment_ids)
    return _segment("segment_mean", data, segment_ids,
                    _num_segments(segment_ids, None), "mean")


def segment_min(data, segment_ids, name=None):
    data, segment_ids = _t(data), _t(segment_ids)
    return _segment("segment_min", data, segment_ids,
                    _num_segments(segment_ids, None), "min")


def segment_max(data, segment_ids, name=None):
    data, segment_ids = _t(data), _t(segment_ids)
    return _segment("segment_max", data, segment_ids,
                    _num_segments(segment_ids, None), "max")


def send_u_recv(x, src_index, dst_index, reduce_op="sum", out_size=None,
                name=None):
    """Graph message passing: gather x at src_index, scatter-reduce onto
    dst_index. ≙ paddle.geometric.send_u_recv («python/paddle/geometric/
    message_passing/send_recv.py» [U])."""
    if reduce_op not in _SEG:
        raise ValueError(f"reduce_op must be one of {list(_SEG)}, "
                         f"got {reduce_op}")
    x, src_index, dst_index = _t(x), _t(src_index), _t(dst_index)
    n = out_size if out_size is not None else x.shape[0]

    def fn(v, src, dst):
        msg = jnp.take(v, src, axis=0)
        out = _SEG[reduce_op](msg, dst, num_segments=n)
        if reduce_op == "mean":
            cnt = jax.ops.segment_sum(jnp.ones(dst.shape, v.dtype), dst,
                                      num_segments=n)
            out = out / jnp.maximum(cnt, 1).reshape(
                (-1,) + (1,) * (v.ndim - 1))
        if reduce_op in ("min", "max"):
            cnt = jax.ops.segment_sum(jnp.ones(dst.shape, jnp.int32), dst,
                                      num_segments=n)
            mask = (cnt > 0).reshape((-1,) + (1,) * (v.ndim - 1))
            out = jnp.where(mask, out, jnp.zeros_like(out))
        return out
    return apply("send_u_recv", fn, (x, src_index, dst_index))


_MSG = {
    "add": jnp.add,
    "sub": jnp.subtract,
    "mul": jnp.multiply,
    "div": jnp.divide,
}


def send_ue_recv(x, y, src_index, dst_index, message_op="add",
                 reduce_op="sum", out_size=None, name=None):
    """Node features x combined with edge features y along each edge, then
    scatter-reduced. ≙ paddle.geometric.send_ue_recv."""
    if message_op not in _MSG:
        raise ValueError(f"message_op must be one of {list(_MSG)}")
    if reduce_op not in _SEG:
        raise ValueError(f"reduce_op must be one of {list(_SEG)}")
    x, y = _t(x), _t(y)
    src_index, dst_index = _t(src_index), _t(dst_index)
    n = out_size if out_size is not None else x.shape[0]

    def fn(v, e, src, dst):
        msg = _MSG[message_op](jnp.take(v, src, axis=0), e)
        out = _SEG[reduce_op](msg, dst, num_segments=n)
        if reduce_op == "mean":
            cnt = jax.ops.segment_sum(jnp.ones(dst.shape, msg.dtype), dst,
                                      num_segments=n)
            out = out / jnp.maximum(cnt, 1).reshape(
                (-1,) + (1,) * (msg.ndim - 1))
        if reduce_op in ("min", "max"):
            cnt = jax.ops.segment_sum(jnp.ones(dst.shape, jnp.int32), dst,
                                      num_segments=n)
            mask = (cnt > 0).reshape((-1,) + (1,) * (msg.ndim - 1))
            out = jnp.where(mask, out, jnp.zeros_like(out))
        return out
    return apply("send_ue_recv", fn, (x, y, src_index, dst_index))


def send_uv(x, y, src_index, dst_index, message_op="add", name=None):
    """Per-edge message from both endpoint features (no reduction):
    out[e] = x[src[e]] (op) y[dst[e]]. ≙ paddle.geometric.send_uv."""
    if message_op not in _MSG:
        raise ValueError(f"message_op must be one of {list(_MSG)}")
    x, y = _t(x), _t(y)
    src_index, dst_index = _t(src_index), _t(dst_index)

    def fn(a, b, src, dst):
        return _MSG[message_op](jnp.take(a, src, axis=0),
                                jnp.take(b, dst, axis=0))
    return apply("send_uv", fn, (x, y, src_index, dst_index))


def sample_neighbors(row, colptr, input_nodes, sample_size=-1,
                     eids=None, return_eids=False, perm_buffer=None,
                     name=None):
    """≙ paddle.geometric.sample_neighbors (CSC graph neighbor sampling)
    [U]. Host-side op like the reference's CPU kernel — sampling is data-
    dependent-shaped, so it runs eagerly in numpy and is not a jit
    target."""
    rr = np.asarray(_t(row)._value)
    cp = np.asarray(_t(colptr)._value)
    nodes = np.asarray(_t(input_nodes)._value)
    ev = np.asarray(_t(eids)._value) if eids is not None else None
    rng = np.random.default_rng()
    out_n, out_cnt, out_e = [], [], []
    for n in nodes.reshape(-1):
        lo, hi = int(cp[n]), int(cp[n + 1])
        neigh = rr[lo:hi]
        idx = np.arange(lo, hi)
        if 0 <= sample_size < neigh.shape[0]:
            pick = rng.choice(neigh.shape[0], size=sample_size,
                              replace=False)
            neigh, idx = neigh[pick], idx[pick]
        out_n.append(neigh)
        out_cnt.append(neigh.shape[0])
        if return_eids:
            out_e.append(ev[idx] if ev is not None else idx)
    neighbors = to_tensor(np.concatenate(out_n) if out_n
                          else np.zeros((0,), rr.dtype))
    counts = to_tensor(np.asarray(out_cnt, np.int32))
    if return_eids:
        eout = to_tensor(np.concatenate(out_e) if out_e
                         else np.zeros((0,), np.int64))
        return neighbors, counts, eout
    return neighbors, counts


def reindex_graph(x, neighbors, count=None, value_buffer=None,
                  index_buffer=None, name=None):
    """≙ paddle.geometric.reindex_graph: compact the union of seed nodes
    `x` and their `neighbors` to contiguous ids (seeds first) [U].
    Host-side like sample_neighbors."""
    xs = np.asarray(_t(x)._value).reshape(-1)
    ns = np.asarray(_t(neighbors)._value).reshape(-1)
    mapping: dict = {}
    for v in xs:
        mapping.setdefault(int(v), len(mapping))
    for v in ns:
        mapping.setdefault(int(v), len(mapping))
    reindexed = np.asarray([mapping[int(v)] for v in ns], np.int64)
    out_nodes = np.empty(len(mapping), xs.dtype)
    for v, i in mapping.items():
        out_nodes[i] = v
    # reindex_dst: seeds repeated per their neighbor counts
    if count is not None:
        cnt = np.asarray(_t(count)._value).reshape(-1)
        dst = np.repeat(np.arange(len(xs), dtype=np.int64), cnt)
    else:
        dst = np.zeros((0,), np.int64)
    return (to_tensor(reindexed), to_tensor(dst), to_tensor(out_nodes))


__all__ += ["sample_neighbors", "reindex_graph"]
