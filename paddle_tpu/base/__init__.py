"""paddle_tpu.base — migration shim for `paddle.base` (ex-`fluid`).

≙ «python/paddle/base/» (SURVEY.md §2.2 base/framework-glue row). The
reference's Program/Block/Variable machinery is replaced by the
op-replay `paddle.static` surface and the trace-to-XLA `paddle.jit`
path; this module re-exports the handful of `paddle.base.*` touchpoints
real migration scripts reach for (core feature probes, dygraph guard,
executor, ParamAttr, unique_name), each backed by the TPU-native
equivalent. Anything deeper (LayerHelper, custom C++ op registration)
has no analogue by design — see docs/migration.md.
"""
from __future__ import annotations

import contextlib

from ..framework import ParamAttr  # noqa: F401
from ..utils import unique_name  # noqa: F401
from ..static import (Executor, Program, default_main_program,  # noqa: F401
                      default_startup_program, global_scope,
                      program_guard)


class core:
    """≙ paddle.base.core feature probes (the libpaddle module)."""

    @staticmethod
    def is_compiled_with_cuda() -> bool:
        return False

    @staticmethod
    def is_compiled_with_rocm() -> bool:
        return False

    @staticmethod
    def is_compiled_with_xpu() -> bool:
        return False

    @staticmethod
    def is_compiled_with_ipu() -> bool:
        return False

    class CPUPlace:
        pass

    class CUDAPlace:
        def __init__(self, device_id=0):
            self.device_id = device_id

    @staticmethod
    def get_cuda_device_count() -> int:
        return 0


class framework:
    """≙ paddle.base.framework essentials."""

    @staticmethod
    def in_dygraph_mode() -> bool:
        import paddle_tpu as paddle
        return paddle.in_dynamic_mode()

    in_dynamic_mode = in_dygraph_mode

    @staticmethod
    def default_main_program():
        return default_main_program()

    @staticmethod
    def default_startup_program():
        return default_startup_program()


class dygraph:
    """≙ paddle.base.dygraph: guard() is the ambient mode here."""

    @staticmethod
    @contextlib.contextmanager
    def guard(place=None):
        import paddle_tpu as paddle
        was_static = not paddle.in_dynamic_mode()
        if was_static:
            paddle.disable_static()
        try:
            yield
        finally:
            if was_static:
                paddle.enable_static()

    @staticmethod
    def to_variable(value, name=None, zero_copy=None, dtype=None):
        import paddle_tpu as paddle
        return paddle.to_tensor(value, dtype=dtype)


class executor:
    Executor = Executor

    @staticmethod
    def global_scope():
        return global_scope()


def is_compiled_with_cuda() -> bool:
    return False
