"""Seeded, replayable open-loop workload traces (ISSUE 11).

Production serving systems are graded under OPEN-LOOP arrival
processes: arrivals do not wait for completions, so overload is real
and admission control is load-bearing (the closed-loop drills the
fleet has seen so far can never overload it — every completed request
gates the next submit). This module generates the traffic side of
that grading, deterministically:

* **Arrival process.** A rate-modulated Poisson process sampled by
  stepwise inversion: inter-arrival gaps are exponential at the rate
  in force at the previous arrival. The rate is `base_qps` modulated
  by a **diurnal** sinusoid (amplitude/period) and by **burst
  episodes** (a Markov-modulated on/off state: each off-state arrival
  starts an episode with `burst_start_prob`, episodes last
  exp(`burst_mean_s`) and multiply the rate by `burst_multiplier`) —
  the two overload shapes a fleet actually sees.
* **Heavy-tailed lengths.** Prompt and output lengths draw from
  clamped lognormals (`*_median`, `*_sigma`, `*_max`) — a few huge
  requests among many small ones, the tail that actually exercises
  preemption and page pressure.
* **Tenant / lane mix.** Each arrival carries a tenant (weighted
  choice) and a QoS lane (`interactive` with `interactive_fraction`,
  else `batch`) — the axes the admission controller arbitrates on.
* **Model mix.** With `model_mix` set (per-tenant weighted model-id
  pools, ISSUE 17), each arrival from a listed tenant also carries a
  `model` drawn from that tenant's pool — the multi-model soak's
  traffic shape (one tenant's fine-tune mix differs from another's).
  Tenants without an entry submit `model=None` (the fleet's base),
  and an empty `model_mix` makes ZERO extra RNG draws, so every
  pre-existing config replays its exact historical event sequence.
* **Shared prefixes.** With `num_system_prompts` > 0, a fraction of
  prompts (`shared_prefix_prob`) prepend one of a fixed pool of
  system prompts, giving the fleet prefix store something real to do.

Everything is driven by one `random.Random(seed)`: the same config
yields the IDENTICAL event sequence, so a soak is replayable
bit-for-bit (tests/test_loadgen.py pins this). Times are VIRTUAL
seconds — the driver (driver.py) maps them onto the fleet's
injectable clock, never wall time. Stdlib-only by design: a trace can
be generated (and inspected) without importing the serving stack.
"""
from __future__ import annotations

import math
import random
from dataclasses import dataclass
from typing import Dict, Iterator, List, Optional, Tuple

__all__ = ["TraceConfig", "ArrivalEvent", "iter_trace",
           "generate_trace"]

# lane literals mirror serving.admission.Lane (stdlib-only module:
# the constants are duplicated, the TESTS assert they match)
LANE_INTERACTIVE = "interactive"
LANE_BATCH = "batch"


@dataclass(frozen=True)
class TraceConfig:
    """One replayable workload (module docstring). All times/rates are
    virtual seconds / events-per-virtual-second."""

    seed: int = 0
    duration_s: float = 60.0
    base_qps: float = 10.0
    # diurnal modulation: rate = base * (1 + A * sin(2*pi*t/P))
    diurnal_amplitude: float = 0.0        # 0..1
    diurnal_period_s: float = 240.0
    # burst episodes (Markov-modulated): see module docstring
    burst_start_prob: float = 0.0
    burst_mean_s: float = 5.0
    burst_multiplier: float = 4.0
    # heavy-tailed lognormal lengths, clamped to [min, max]
    prompt_len_median: float = 16.0
    prompt_len_sigma: float = 0.6
    prompt_len_min: int = 2
    prompt_len_max: int = 48
    output_len_median: float = 8.0
    output_len_sigma: float = 0.8
    output_len_min: int = 1
    output_len_max: int = 32
    # tenant mix: (name, weight) pairs; lane mix
    tenants: Tuple[Tuple[str, float], ...] = (("acme", 3.0),
                                              ("bidco", 1.0))
    interactive_fraction: float = 0.7
    # per-tenant model mix (ISSUE 17): (tenant, ((model_id, weight),
    # ...)) pairs — model ids are the store's canonical spelling
    # (serving.model_id). Empty = model-less trace (no extra draws).
    model_mix: Tuple[Tuple[str, Tuple[Tuple[str, float], ...]],
                     ...] = ()
    # shared system prompts (fleet prefix-store realism)
    num_system_prompts: int = 0
    system_prompt_len: int = 16
    shared_prefix_prob: float = 0.5
    vocab_size: int = 64
    request_id_prefix: str = "soak"

    def __post_init__(self):
        if self.base_qps <= 0 or self.duration_s <= 0:
            raise ValueError("base_qps and duration_s must be > 0")
        if not 0.0 <= self.diurnal_amplitude <= 1.0:
            raise ValueError("diurnal_amplitude must be in [0, 1], "
                             f"got {self.diurnal_amplitude}")
        if not self.tenants:
            raise ValueError("tenants must be non-empty")
        if self.prompt_len_min < 1 or self.output_len_min < 1:
            raise ValueError("length minima must be >= 1")
        for tenant, pool in self.model_mix:
            if not pool:
                raise ValueError(f"model_mix for tenant {tenant!r} "
                                 "must be non-empty")


@dataclass(frozen=True)
class ArrivalEvent:
    """One session arrival: submit `prompt` for `max_new_tokens` at
    virtual time `t` on lane `lane` for `tenant`."""

    t: float
    request_id: str
    tenant: str
    lane: str
    prompt: Tuple[int, ...]
    max_new_tokens: int
    # the model id to serve this session with (None = the fleet base);
    # drawn from the tenant's `model_mix` pool when one is configured
    model: Optional[str] = None


def _rate(cfg: TraceConfig, t: float, bursting: bool) -> float:
    r = cfg.base_qps * (1.0 + cfg.diurnal_amplitude
                        * math.sin(2.0 * math.pi * t
                                   / cfg.diurnal_period_s))
    if bursting:
        r *= cfg.burst_multiplier
    return max(r, 1e-9)


def _length(rng: random.Random, median: float, sigma: float,
            lo: int, hi: int) -> int:
    # lognormal parameterized by its median: exp(mu) = median
    n = int(round(rng.lognormvariate(math.log(max(median, 1.0)),
                                     sigma)))
    return max(lo, min(n, hi))


def iter_trace(cfg: TraceConfig) -> Iterator[ArrivalEvent]:
    """Yield the trace's arrivals in time order. Pure function of the
    config (one seeded RNG): the same config replays identically."""
    rng = random.Random(cfg.seed)
    sys_prompts = [
        tuple(rng.randrange(1, cfg.vocab_size)
              for _ in range(cfg.system_prompt_len))
        for _ in range(cfg.num_system_prompts)]
    names = [n for n, _ in cfg.tenants]
    weights = [w for _, w in cfg.tenants]
    model_pools: Dict[str, Tuple[List[str], List[float]]] = {
        tenant: ([m for m, _ in pool], [w for _, w in pool])
        for tenant, pool in cfg.model_mix}
    t = 0.0
    burst_until = -1.0
    i = 0
    while True:
        bursting = t < burst_until
        t += rng.expovariate(_rate(cfg, t, bursting))
        if t >= cfg.duration_s:
            return
        if not bursting and cfg.burst_start_prob > 0 \
                and rng.random() < cfg.burst_start_prob:
            burst_until = t + rng.expovariate(1.0 / cfg.burst_mean_s)
        tenant = rng.choices(names, weights)[0]
        model: Optional[str] = None
        pool = model_pools.get(tenant)
        if pool is not None:
            model = rng.choices(pool[0], pool[1])[0]
        lane = LANE_INTERACTIVE \
            if rng.random() < cfg.interactive_fraction else LANE_BATCH
        p_len = _length(rng, cfg.prompt_len_median,
                        cfg.prompt_len_sigma, cfg.prompt_len_min,
                        cfg.prompt_len_max)
        o_len = _length(rng, cfg.output_len_median,
                        cfg.output_len_sigma, cfg.output_len_min,
                        cfg.output_len_max)
        prefix: Tuple[int, ...] = ()
        if sys_prompts and rng.random() < cfg.shared_prefix_prob:
            prefix = rng.choice(sys_prompts)
        tail = tuple(rng.randrange(1, cfg.vocab_size)
                     for _ in range(p_len))
        yield ArrivalEvent(t, f"{cfg.request_id_prefix}-{i}", tenant,
                           lane, prefix + tail, o_len, model)
        i += 1


def generate_trace(cfg: TraceConfig) -> List[ArrivalEvent]:
    """The whole trace as a list (hundreds of thousands of events are
    fine — an event is a few dozen ints); `iter_trace` streams."""
    return list(iter_trace(cfg))
