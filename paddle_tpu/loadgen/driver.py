"""Open-loop soak driver: replayable traces against the REAL fleet
router, in virtual time (ISSUE 11).

The driver owns a `VirtualClock` and steps a `ServingRouter` built on
that same clock: each tick advances virtual time by `step_dt` (the
simulated wall cost of one fleet step), submits every trace arrival
whose time has come — arrivals NEVER wait for completions, so
overload is real — and harvests terminal requests into a per-session
result table. Hundreds of thousands of sessions run in minutes of
real time because only the model math is real; every latency (TTFT,
queue wait, retry_after) is measured in virtual seconds on the shared
injectable clock, which also makes a soak DETERMINISTIC: same trace
seed + same fleet ⇒ identical metrics, bit for bit
(tests/test_loadgen.py pins this).

Outcomes per session: the router's terminal statuses (`finished` /
`timeout` / `failed` / `preempted`) plus the two refusal surfaces —
`shed` (a `QosShed` from the admission controller, with lane / tenant
/ reason / retry_after recorded) and `overloaded` (hard
`FleetOverloaded` backpressure). `SoakResult.summary()` aggregates
per lane (exact p50/p95 TTFT via the SLO engine's quantile math),
per tenant, and per shed reason — the numbers
`recipes/fleet_soak.py` grades and `bench.py` regresses on.

Telemetry: `pdt_loadgen_*` (docs/observability.md) + one
`loadgen.soak` span around the drive, so a soak's scrape carries the
workload side (arrivals, outcomes, virtual time) next to the fleet's
own counters — the reconciliation the recipe asserts reads entirely
off one snapshot.
"""
from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional

from .. import observability as telemetry
from ..observability.slo import exact_quantile
from ..serving.router import FleetOverloaded, QosShed, ServingRouter
from .trace import ArrivalEvent

__all__ = ["VirtualClock", "SessionRecord", "SoakResult",
           "SoakDriver", "binary_search_qps"]

_M_ARRIVALS = telemetry.counter(
    "pdt_loadgen_arrivals_total",
    "Trace arrivals submitted to the fleet, by lane.", ("lane",))
_M_OUTCOMES = telemetry.counter(
    "pdt_loadgen_outcomes_total",
    "Soak session outcomes (terminal statuses + shed/overloaded "
    "refusals).", ("outcome",))
_M_OPEN = telemetry.gauge(
    "pdt_loadgen_open_sessions",
    "Sessions submitted but not yet terminal.")
_M_VTIME = telemetry.gauge(
    "pdt_loadgen_virtual_seconds",
    "The soak's virtual clock (seconds since soak start).")


class VirtualClock:
    """The injectable-clock discipline's loadgen face: a monotonic
    virtual clock shared by the trace driver, the router, its engines,
    the SLO monitor, and the admission controller. `advance` doubles
    as the router's `sleep` (a whole-fleet restart wait just jumps
    virtual time)."""

    def __init__(self, start: float = 0.0):
        self.t = float(start)

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float):
        if dt < 0:
            raise ValueError(f"cannot rewind a monotonic clock ({dt})")
        self.t += dt


@dataclass
class SessionRecord:
    """One trace session's fate."""

    request_id: str
    tenant: str
    lane: str
    arrival_s: float
    outcome: str                    # finished|timeout|failed|preempted
    #                                 |shed|overloaded|invalid
    ttft_s: Optional[float] = None  # virtual seconds, router clock
    tokens: int = 0
    retry_after: Optional[float] = None
    shed_reason: Optional[str] = None
    model: Optional[str] = None     # the arrival's model id (None =
    #                                 the fleet base / model-less)


# refusal outcomes never entered the fleet; everything else is a
# router terminal status
REFUSAL_OUTCOMES = ("shed", "overloaded", "invalid")


@dataclass
class SoakResult:
    """The harvest of one soak: the per-session table plus the fleet
    snapshot taken after drain. `wall_s` is real seconds (excluded
    from `summary()` so the summary is replay-deterministic)."""

    duration_s: float          # full drive incl. the post-trace drain
    trace_span_s: float        # first..last arrival (the open window)
    steps: int
    wall_s: float
    replica_steps: int = 0     # alive-replica-steps (chip-time proxy)
    sessions: List[SessionRecord] = field(default_factory=list)
    fleet_info: Dict[str, object] = field(default_factory=dict)

    def lane_sessions(self, lane: str) -> List[SessionRecord]:
        return [s for s in self.sessions if s.lane == lane]

    def summary(self) -> Dict[str, object]:
        """Deterministic aggregate (virtual-time quantities only)."""
        by_outcome: Dict[str, int] = {}
        for s in self.sessions:
            by_outcome[s.outcome] = by_outcome.get(s.outcome, 0) + 1
        lanes: Dict[str, dict] = {}
        for lane in sorted({s.lane for s in self.sessions}):
            rows = self.lane_sessions(lane)
            ttfts = [s.ttft_s for s in rows if s.ttft_s is not None]
            lanes[lane] = {
                "sessions": len(rows),
                "finished": sum(1 for s in rows
                                if s.outcome == "finished"),
                "shed": sum(1 for s in rows if s.outcome == "shed"),
                "overloaded": sum(1 for s in rows
                                  if s.outcome == "overloaded"),
                "tokens": sum(s.tokens for s in rows),
                "ttft_p50_s": exact_quantile(ttfts, 0.50),
                "ttft_p95_s": exact_quantile(ttfts, 0.95),
            }
        sheds_by_tenant: Dict[str, int] = {}
        sheds_by_reason: Dict[str, int] = {}
        for s in self.sessions:
            if s.outcome == "shed":
                sheds_by_tenant[s.tenant] = \
                    sheds_by_tenant.get(s.tenant, 0) + 1
                reason = s.shed_reason or "?"
                sheds_by_reason[reason] = \
                    sheds_by_reason.get(reason, 0) + 1
        finished = by_outcome.get("finished", 0)
        return {
            "sessions": len(self.sessions),
            "duration_s": self.duration_s,
            "trace_span_s": self.trace_span_s,
            "steps": self.steps,
            "replica_steps": self.replica_steps,
            "outcomes": dict(sorted(by_outcome.items())),
            # arrival rate over the ARRIVAL window — duration_s also
            # spans the drain, which would understate the offered load
            "arrival_qps": round(len(self.sessions)
                                 / max(self.trace_span_s, 1e-9), 4),
            # service rate over the whole busy period, drain included
            "completed_qps": round(finished
                                   / max(self.duration_s, 1e-9), 4),
            "tokens_total": sum(s.tokens for s in self.sessions),
            "lanes": lanes,
            "sheds_by_tenant": dict(sorted(sheds_by_tenant.items())),
            "sheds_by_reason": dict(sorted(sheds_by_reason.items())),
        }


class SoakDriver:
    """Drive one trace through one router (module docstring).

    The router (and everything it feeds: engines, SLO monitor,
    admission controller) MUST be built on `clock` — the driver
    advances that clock and the whole stack moves together; a
    wall-clock component would see frozen time. `step_dt` is the
    virtual wall cost charged per router step. `max_wall_s` is a REAL
    wall-time safety valve for runaway soaks (raises RuntimeError);
    `release_terminal` drops router records as sessions finish so a
    100k-session soak holds memory proportional to in-flight work.
    """

    def __init__(self, router: ServingRouter,
                 arrivals: Iterable[ArrivalEvent], *,
                 clock: VirtualClock, step_dt: float = 0.05,
                 release_terminal: bool = True,
                 max_wall_s: Optional[float] = None,
                 autoscaler=None):
        if step_dt <= 0:
            raise ValueError(f"step_dt must be > 0, got {step_dt}")
        self.router = router
        self.arrivals = arrivals
        self.clock = clock
        self.step_dt = float(step_dt)
        self.release_terminal = release_terminal
        self.max_wall_s = max_wall_s
        # a serving.FleetAutoscaler ticked once per driver step, AFTER
        # harvest — elastic soaks (recipes/fleet_soak.py --autoscale)
        # grade its replica-step savings against a static fleet
        self.autoscaler = autoscaler
        self._live: Dict[str, SessionRecord] = {}

    # -- submit / harvest ------------------------------------------------
    def _submit(self, evt: ArrivalEvent) -> SessionRecord:
        _M_ARRIVALS.inc(lane=evt.lane)
        rec = SessionRecord(evt.request_id, evt.tenant, evt.lane,
                            evt.t, outcome="open",
                            model=getattr(evt, "model", None))
        try:
            kw = {}
            if rec.model is not None:
                kw["model"] = rec.model
            self.router.submit(list(evt.prompt),
                               max_new_tokens=evt.max_new_tokens,
                               request_id=evt.request_id,
                               lane=evt.lane, tenant=evt.tenant, **kw)
        except QosShed as e:
            rec.outcome = "shed"
            rec.retry_after = e.retry_after
            rec.shed_reason = e.reason
        except FleetOverloaded as e:
            rec.outcome = "overloaded"
            rec.retry_after = e.retry_after
        except ValueError as e:
            # a request the fleet could NEVER serve (prompt past
            # max_seq_len): record it, keep soaking — visible in the
            # outcome table, never a wedge
            rec.outcome = "invalid"
            telemetry.event("loadgen.invalid",
                            request_id=evt.request_id,
                            error=f"{type(e).__name__}: {e}")
        if rec.outcome in REFUSAL_OUTCOMES:
            _M_OUTCOMES.inc(outcome=rec.outcome)
        else:
            self._live[evt.request_id] = rec
        return rec

    def _harvest(self, done) -> None:
        for fr in done:
            rec = self._live.pop(fr.request_id, None)
            if rec is None:
                continue               # not one of this soak's sessions
            rec.outcome = fr.status
            rec.tokens = len(fr.tokens)
            if fr.first_token_time is not None:
                rec.ttft_s = fr.first_token_time - fr.submit_time
            _M_OUTCOMES.inc(outcome=rec.outcome)
            if self.release_terminal:
                self.router.release_request(fr.request_id)

    # -- the drive -------------------------------------------------------
    def run(self) -> SoakResult:
        t_start = self.clock()
        wall0 = time.perf_counter()
        sessions: List[SessionRecord] = []
        steps = 0
        replica_steps = 0
        last_arrival = 0.0
        it = iter(self.arrivals)
        nxt = next(it, None)
        with telemetry.span("loadgen.soak", step_dt=self.step_dt):
            while nxt is not None or self._live:
                if self.max_wall_s is not None \
                        and time.perf_counter() - wall0 \
                        > self.max_wall_s:
                    raise RuntimeError(
                        f"soak exceeded max_wall_s={self.max_wall_s} "
                        f"({len(sessions)} sessions, "
                        f"{len(self._live)} still open)")
                now = self.clock() - t_start
                # open loop: submit EVERYTHING due by now, completions
                # notwithstanding
                while nxt is not None and nxt.t <= now:
                    last_arrival = nxt.t
                    sessions.append(self._submit(nxt))
                    nxt = next(it, None)
                if nxt is None and not self._live:
                    break
                if not any(h.alive() for h in self.router.replicas):
                    # whole fleet down: jump to the next restart the
                    # way router.run()'s sleep does, rather than
                    # crawling there step_dt at a time
                    waits = [h.next_restart_time - self.clock()
                             for h in self.router.replicas
                             if h.next_restart_time is not None]
                    if waits and min(waits) > self.step_dt:
                        self.clock.advance(min(waits))
                self.clock.advance(self.step_dt)
                self._harvest(self.router.step())
                steps += 1
                # replica-steps: the soak's chip-time proxy — one unit
                # per serving replica per driver step, the denominator
                # the --autoscale grade saves against a static fleet
                replica_steps += sum(1 for h in self.router.replicas
                                     if h.alive())
                if self.autoscaler is not None:
                    self.autoscaler.tick()
                _M_OPEN.set(len(self._live))
                _M_VTIME.set(self.clock() - t_start)
        return SoakResult(
            duration_s=self.clock() - t_start,
            trace_span_s=last_arrival, steps=steps,
            replica_steps=replica_steps,
            wall_s=time.perf_counter() - wall0, sessions=sessions,
            fleet_info=self.router.fleet_info())


def binary_search_qps(sustainable, lo: float, hi: float, *,
                      iters: int = 6, grow: float = 2.0,
                      max_grow_steps: int = 6) -> float:
    """Max-sustainable-QPS search: `sustainable(qps) -> bool` runs one
    (fresh-fleet) soak probe. `hi` doubles until unsustainable (capped
    at `max_grow_steps` doublings — then HI itself is sustainable and
    returned), then `iters` bisection rounds tighten the bracket.
    Returns the highest known-sustainable rate. `lo` is trusted
    sustainable (pick it trivially small)."""
    if sustainable(hi):
        for _ in range(max_grow_steps):
            lo, hi = hi, hi * grow
            if not sustainable(hi):
                break
        else:
            return hi                  # never found a ceiling
    for _ in range(iters):
        mid = (lo + hi) / 2.0
        if sustainable(mid):
            lo = mid
        else:
            hi = mid
    return lo
