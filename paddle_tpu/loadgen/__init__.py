"""Trace-driven fleet load generation (ISSUE 11).

The traffic plane for the million-user north star: `trace.py` builds
seeded, REPLAYABLE open-loop arrival traces (diurnal + burst rate
modulation, heavy-tailed prompt/output lengths, tenant/lane mix) and
`driver.py` fires them at the real `ServingRouter` on a shared
virtual clock — arrivals never wait for completions, so overload is
real and the QoS admission controller (serving/admission.py) has
something true to arbitrate. `recipes/fleet_soak.py` is the graded
drill; `bench.py detail.soak` reports max-sustainable-QPS by binary
search over the arrival rate.

    from paddle_tpu.loadgen import (TraceConfig, generate_trace,
                                    SoakDriver, VirtualClock)

    clock = VirtualClock()
    router = ServingRouter(factory, clock=clock, sleep=clock.advance,
                           admission=QosAdmission(...))
    result = SoakDriver(router, generate_trace(TraceConfig(seed=0)),
                        clock=clock, step_dt=0.05).run()
    print(result.summary())
"""
from .driver import (SessionRecord, SoakDriver,  # noqa: F401
                     SoakResult, VirtualClock, binary_search_qps)
from .trace import (ArrivalEvent, TraceConfig,  # noqa: F401
                    generate_trace, iter_trace)

__all__ = [
    "TraceConfig", "ArrivalEvent", "iter_trace", "generate_trace",
    "VirtualClock", "SessionRecord", "SoakResult", "SoakDriver",
    "binary_search_qps",
]
