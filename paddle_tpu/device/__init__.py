"""Device API. ≙ reference «python/paddle/device/» [U]: set/get device,
synchronize, stream shims, memory stats. On TPU there are no user-visible
streams (XLA owns scheduling); the stream/event classes are functional no-ops
kept for API parity."""
from __future__ import annotations

import jax

_current_device = None


def get_all_devices():
    return [f"{d.platform}:{i}" for i, d in enumerate(jax.devices())]


def get_available_device():
    return get_all_devices()


def get_device() -> str:
    global _current_device
    if _current_device is None:
        d = jax.devices()[0]
        _current_device = f"{d.platform}:0"
    return _current_device


def set_device(device: str):
    """Accepts 'tpu', 'tpu:0', 'cpu', 'gpu:0' (alias for accelerator)."""
    global _current_device
    plat = device.split(":")[0].lower()
    idx = int(device.split(":")[1]) if ":" in device else 0
    alias = {"gpu": "tpu", "cuda": "tpu", "xpu": "tpu"}
    plat = alias.get(plat, plat)
    try:
        devs = jax.devices(plat)
    except RuntimeError:
        devs = jax.devices()
    d = devs[min(idx, len(devs) - 1)]
    jax.config.update("jax_default_device", d)
    _current_device = f"{d.platform}:{idx}"
    return d


def is_compiled_with_cuda() -> bool:
    return False


def is_compiled_with_rocm() -> bool:
    return False


def is_compiled_with_xpu() -> bool:
    return False


def is_compiled_with_ipu() -> bool:
    return False


def is_compiled_with_custom_device(name: str = "tpu") -> bool:
    return any(d.platform == name for d in jax.devices()) or name in (
        "tpu", "axon")


def device_count() -> int:
    return len(jax.devices())


def synchronize(device=None):
    """Block until all queued device work completes (≙ cudaDeviceSynchronize)."""
    jax.effects_barrier()


class Stream:
    """No-op stream for API parity: XLA schedules asynchronously itself."""

    def __init__(self, device=None, priority=2):
        self.device = device

    def synchronize(self):
        synchronize()

    def wait_event(self, event):
        pass

    def wait_stream(self, stream):
        pass

    def record_event(self, event=None):
        return event or Event()

    def query(self):
        return True


class Event:
    def __init__(self, enable_timing=False, blocking=False, interprocess=False):
        pass

    def record(self, stream=None):
        pass

    def query(self):
        return True

    def synchronize(self):
        synchronize()


_default_stream = Stream()


def current_stream(device=None) -> Stream:
    return _default_stream


def set_stream(stream):
    return _default_stream


def stream_guard(stream):
    from contextlib import nullcontext
    return nullcontext()


class cuda:
    """Compat shim namespace (paddle.device.cuda): memory stats map to the
    TPU allocator's live stats via jax device memory_stats()."""

    Stream = Stream
    Event = Event

    @staticmethod
    def device_count():
        return len([d for d in jax.devices() if d.platform != "cpu"])

    @staticmethod
    def synchronize(device=None):
        synchronize()

    @staticmethod
    def current_stream(device=None):
        return _default_stream

    @staticmethod
    def max_memory_allocated(device=None):
        st = jax.devices()[0].memory_stats() or {}
        return st.get("peak_bytes_in_use", 0)

    @staticmethod
    def max_memory_reserved(device=None):
        st = jax.devices()[0].memory_stats() or {}
        return st.get("peak_bytes_in_use", 0)

    @staticmethod
    def memory_allocated(device=None):
        st = jax.devices()[0].memory_stats() or {}
        return st.get("bytes_in_use", 0)

    @staticmethod
    def memory_reserved(device=None):
        st = jax.devices()[0].memory_stats() or {}
        return st.get("bytes_limit", 0)

    @staticmethod
    def empty_cache():
        pass

    @staticmethod
    def get_device_properties(device=None):
        d = jax.devices()[0]
        class _P:
            name = str(d.device_kind)
            major, minor = 0, 0
            total_memory = (d.memory_stats() or {}).get("bytes_limit", 0)
            multi_processor_count = getattr(d, "num_cores", 1)
        return _P()
