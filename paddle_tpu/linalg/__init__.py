"""paddle.linalg namespace. ≙ reference «python/paddle/linalg.py» [U]."""
from ..tensor.linalg import *  # noqa: F401,F403
from ..tensor.linalg import (norm, det, slogdet, inv, pinv, solve,  # noqa: F401
                             cholesky, qr, svd, eig, eigh, eigvals,
                             eigvalsh, matrix_power, matrix_rank, multi_dot,
                             lstsq, cond, corrcoef, lu, lu_unpack,
                             triangular_solve, cholesky_solve,
                             householder_product, matrix_exp, pca_lowrank,
                             svd_lowrank, vector_norm, matrix_norm)
from ..tensor.stat import cov  # noqa: F401
