"""Vision transforms (numpy/host-side, feeding the DataLoader).
≙ reference «python/paddle/vision/transforms/» [U]."""
from __future__ import annotations

import numpy as np

from ..core.tensor import Tensor, to_tensor


class Compose:
    def __init__(self, transforms):
        self.transforms = transforms

    def __call__(self, x):
        for t in self.transforms:
            x = t(x)
        return x


class BaseTransform:
    def __call__(self, x):
        return self._apply_image(x)

    def _apply_image(self, x):
        raise NotImplementedError


class ToTensor(BaseTransform):
    def __init__(self, data_format="CHW", keys=None):
        self.data_format = data_format

    def _apply_image(self, img):
        arr = np.asarray(img, np.float32) / 255.0
        if arr.ndim == 2:
            arr = arr[:, :, None]
        if self.data_format == "CHW":
            arr = arr.transpose(2, 0, 1)
        return to_tensor(arr)


class Normalize(BaseTransform):
    def __init__(self, mean=0.0, std=1.0, data_format="CHW", to_rgb=False,
                 keys=None):
        self.mean = np.asarray(mean, np.float32)
        self.std = np.asarray(std, np.float32)
        self.data_format = data_format

    def _apply_image(self, img):
        arr = img.numpy() if isinstance(img, Tensor) else np.asarray(
            img, np.float32)
        shape = (-1, 1, 1) if self.data_format == "CHW" else (1, 1, -1)
        arr = (arr - self.mean.reshape(shape)) / self.std.reshape(shape)
        return to_tensor(arr) if isinstance(img, Tensor) else arr


class Resize(BaseTransform):
    def __init__(self, size, interpolation="bilinear", keys=None):
        self.size = (size, size) if isinstance(size, int) else tuple(size)

    def _apply_image(self, img):
        import jax
        import jax.numpy as jnp
        arr = img.numpy() if isinstance(img, Tensor) else np.asarray(img)
        chw = arr.ndim == 3 and arr.shape[0] in (1, 3)
        h_axis = 1 if chw else 0
        out_shape = list(arr.shape)
        out_shape[h_axis] = self.size[0]
        out_shape[h_axis + 1] = self.size[1]
        out = np.asarray(jax.image.resize(jnp.asarray(arr, jnp.float32),
                                          out_shape, "bilinear"))
        return to_tensor(out) if isinstance(img, Tensor) else out


class CenterCrop(BaseTransform):
    def __init__(self, size, keys=None):
        self.size = (size, size) if isinstance(size, int) else tuple(size)

    def _apply_image(self, img):
        arr = img.numpy() if isinstance(img, Tensor) else np.asarray(img)
        chw = arr.ndim == 3 and arr.shape[0] in (1, 3)
        h_axis = 1 if chw else 0
        h, w = arr.shape[h_axis], arr.shape[h_axis + 1]
        th, tw = self.size
        i, j = (h - th) // 2, (w - tw) // 2
        sl = [slice(None)] * arr.ndim
        sl[h_axis] = slice(i, i + th)
        sl[h_axis + 1] = slice(j, j + tw)
        out = arr[tuple(sl)]
        return to_tensor(out) if isinstance(img, Tensor) else out


class RandomHorizontalFlip(BaseTransform):
    def __init__(self, prob=0.5, keys=None):
        self.prob = prob

    def _apply_image(self, img):
        if np.random.random() < self.prob:
            arr = img.numpy() if isinstance(img, Tensor) else np.asarray(img)
            chw = arr.ndim == 3 and arr.shape[0] in (1, 3)
            out = arr[..., ::-1] if not chw else arr[:, :, ::-1]
            out = np.ascontiguousarray(out)
            return to_tensor(out) if isinstance(img, Tensor) else out
        return img


class RandomCrop(BaseTransform):
    def __init__(self, size, padding=None, pad_if_needed=False, keys=None):
        self.size = (size, size) if isinstance(size, int) else tuple(size)
        self.padding = padding

    def _apply_image(self, img):
        arr = img.numpy() if isinstance(img, Tensor) else np.asarray(img)
        chw = arr.ndim == 3 and arr.shape[0] in (1, 3)
        h_axis = 1 if chw else 0
        if self.padding:
            p = self.padding
            pads = [(0, 0)] * arr.ndim
            pads[h_axis] = (p, p)
            pads[h_axis + 1] = (p, p)
            arr = np.pad(arr, pads)
        h, w = arr.shape[h_axis], arr.shape[h_axis + 1]
        th, tw = self.size
        i = np.random.randint(0, h - th + 1)
        j = np.random.randint(0, w - tw + 1)
        sl = [slice(None)] * arr.ndim
        sl[h_axis] = slice(i, i + th)
        sl[h_axis + 1] = slice(j, j + tw)
        out = arr[tuple(sl)]
        return to_tensor(out) if isinstance(img, Tensor) else out


def to_tensor_fn(img, data_format="CHW"):
    return ToTensor(data_format)(img)


def normalize(img, mean, std, data_format="CHW", to_rgb=False):
    return Normalize(mean, std, data_format)(img)


def resize(img, size, interpolation="bilinear"):
    return Resize(size, interpolation)(img)


# -- round-3 breadth ---------------------------------------------------------
def _as_np(img):
    return (img.numpy() if isinstance(img, Tensor)
            else np.asarray(img)), isinstance(img, Tensor)


def _wrap(out, was_tensor):
    return to_tensor(np.ascontiguousarray(out)) if was_tensor else out


def _hwc_axes(arr):
    chw = arr.ndim == 3 and arr.shape[0] in (1, 3)
    return (1, 2) if chw else (0, 1)


class RandomVerticalFlip(BaseTransform):
    """≙ paddle.vision.transforms.RandomVerticalFlip [U]."""

    def __init__(self, prob=0.5, keys=None):
        self.prob = prob

    def _apply_image(self, img):
        if np.random.random() < self.prob:
            arr, wt = _as_np(img)
            ha, _ = _hwc_axes(arr)
            return _wrap(np.flip(arr, axis=ha), wt)
        return img


class Pad(BaseTransform):
    """≙ paddle.vision.transforms.Pad [U]."""

    def __init__(self, padding, fill=0, padding_mode="constant", keys=None):
        if isinstance(padding, int):
            padding = (padding,) * 4      # l, t, r, b
        elif len(padding) == 2:
            padding = (padding[0], padding[1], padding[0], padding[1])
        self.padding = padding
        self.fill = fill
        self.mode = padding_mode

    def _apply_image(self, img):
        arr, wt = _as_np(img)
        l, t, r, b = self.padding
        ha, wa = _hwc_axes(arr)
        pads = [(0, 0)] * arr.ndim
        pads[ha] = (t, b)
        pads[wa] = (l, r)
        if self.mode == "constant":
            out = np.pad(arr, pads, constant_values=self.fill)
        else:
            out = np.pad(arr, pads, mode=self.mode)
        return _wrap(out, wt)


class Grayscale(BaseTransform):
    """≙ paddle.vision.transforms.Grayscale [U] (ITU-R 601 luma)."""

    def __init__(self, num_output_channels=1, keys=None):
        self.n = num_output_channels

    def _apply_image(self, img):
        arr, wt = _as_np(img)
        arr = arr.astype(np.float32)
        chw = arr.ndim == 3 and arr.shape[0] in (1, 3)
        w = np.asarray([0.299, 0.587, 0.114], np.float32)
        if chw:
            g = np.tensordot(w, arr, axes=(0, 0))[None]
            out = np.repeat(g, self.n, axis=0) if self.n > 1 else g
        else:
            g = arr @ w
            g = g[..., None]
            out = np.repeat(g, self.n, axis=-1) if self.n > 1 else g
        return _wrap(out, wt)


class BrightnessTransform(BaseTransform):
    """≙ paddle.vision.transforms.BrightnessTransform [U]."""

    def __init__(self, value, keys=None):
        self.value = float(value)

    def _apply_image(self, img):
        arr, wt = _as_np(img)
        f = 1.0 + np.random.uniform(-self.value, self.value)
        return _wrap(np.clip(arr.astype(np.float32) * f, 0,
                             255 if arr.dtype == np.uint8 else np.inf)
                     .astype(arr.dtype), wt)


class ContrastTransform(BaseTransform):
    """≙ paddle.vision.transforms.ContrastTransform [U]."""

    def __init__(self, value, keys=None):
        self.value = float(value)

    def _apply_image(self, img):
        arr, wt = _as_np(img)
        f = 1.0 + np.random.uniform(-self.value, self.value)
        mean = arr.astype(np.float32).mean()
        out = (arr.astype(np.float32) - mean) * f + mean
        return _wrap(np.clip(out, 0,
                             255 if arr.dtype == np.uint8 else np.inf)
                     .astype(arr.dtype), wt)


class SaturationTransform(BaseTransform):
    """≙ paddle.vision.transforms.SaturationTransform [U]."""

    def __init__(self, value, keys=None):
        self.value = float(value)

    def _apply_image(self, img):
        arr, wt = _as_np(img)
        f = 1.0 + np.random.uniform(-self.value, self.value)
        gray = Grayscale(3)._apply_image(arr).astype(np.float32)
        out = arr.astype(np.float32) * f + gray * (1 - f)
        return _wrap(np.clip(out, 0,
                             255 if arr.dtype == np.uint8 else np.inf)
                     .astype(arr.dtype), wt)


class HueTransform(BaseTransform):
    """≙ paddle.vision.transforms.HueTransform [U] (HSV rotation via
    colorsys-equivalent vectorized math)."""

    def __init__(self, value, keys=None):
        assert 0 <= value <= 0.5
        self.value = float(value)

    def _apply_image(self, img):
        arr, wt = _as_np(img)
        shift = np.random.uniform(-self.value, self.value)
        chw = arr.ndim == 3 and arr.shape[0] in (1, 3)
        x = arr.astype(np.float32)
        if arr.dtype == np.uint8:
            x = x / 255.0
        if chw:
            x = x.transpose(1, 2, 0)
        import matplotlib.colors as mc  # rgb_to_hsv vectorized
        hsv = mc.rgb_to_hsv(np.clip(x, 0, 1))
        hsv[..., 0] = (hsv[..., 0] + shift) % 1.0
        out = mc.hsv_to_rgb(hsv)
        if chw:
            out = out.transpose(2, 0, 1)
        if arr.dtype == np.uint8:
            out = (out * 255.0).round().astype(np.uint8)
        return _wrap(out, wt)


class ColorJitter(BaseTransform):
    """≙ paddle.vision.transforms.ColorJitter [U] — random order of
    brightness/contrast/saturation/hue sub-transforms."""

    def __init__(self, brightness=0, contrast=0, saturation=0, hue=0,
                 keys=None):
        self.ts = []
        if brightness:
            self.ts.append(BrightnessTransform(brightness))
        if contrast:
            self.ts.append(ContrastTransform(contrast))
        if saturation:
            self.ts.append(SaturationTransform(saturation))
        if hue:
            self.ts.append(HueTransform(hue))

    def _apply_image(self, img):
        for i in np.random.permutation(len(self.ts)):
            img = self.ts[i](img)
        return img


class RandomRotation(BaseTransform):
    """≙ paddle.vision.transforms.RandomRotation [U] (nearest resample on
    the host; use vision.ops for differentiable warps)."""

    def __init__(self, degrees, interpolation="nearest", expand=False,
                 center=None, fill=0, keys=None):
        if isinstance(degrees, (int, float)):
            degrees = (-abs(degrees), abs(degrees))
        self.degrees = degrees
        self.fill = fill

    def _apply_image(self, img):
        arr, wt = _as_np(img)
        angle = np.radians(np.random.uniform(*self.degrees))
        chw = arr.ndim == 3 and arr.shape[0] in (1, 3)
        x = arr.transpose(1, 2, 0) if chw else arr
        h, w = x.shape[:2]
        cy, cx = (h - 1) / 2.0, (w - 1) / 2.0
        yy, xx = np.mgrid[0:h, 0:w]
        ys = (yy - cy) * np.cos(angle) - (xx - cx) * np.sin(angle) + cy
        xs = (yy - cy) * np.sin(angle) + (xx - cx) * np.cos(angle) + cx
        yi = np.round(ys).astype(int)
        xi = np.round(xs).astype(int)
        valid = (yi >= 0) & (yi < h) & (xi >= 0) & (xi < w)
        out = np.full_like(x, self.fill)
        out[valid] = x[np.clip(yi, 0, h - 1),
                       np.clip(xi, 0, w - 1)][valid]
        if chw:
            out = out.transpose(2, 0, 1)
        return _wrap(out, wt)


class RandomResizedCrop(BaseTransform):
    """≙ paddle.vision.transforms.RandomResizedCrop [U]."""

    def __init__(self, size, scale=(0.08, 1.0), ratio=(3 / 4, 4 / 3),
                 interpolation="bilinear", keys=None):
        self.size = (size, size) if isinstance(size, int) else tuple(size)
        self.scale = scale
        self.ratio = ratio

    def _apply_image(self, img):
        arr, wt = _as_np(img)
        ha, wa = _hwc_axes(arr)
        h, w = arr.shape[ha], arr.shape[wa]
        area = h * w
        for _ in range(10):
            target = area * np.random.uniform(*self.scale)
            ar = np.exp(np.random.uniform(np.log(self.ratio[0]),
                                          np.log(self.ratio[1])))
            cw = int(round(np.sqrt(target * ar)))
            ch = int(round(np.sqrt(target / ar)))
            if 0 < cw <= w and 0 < ch <= h:
                i = np.random.randint(0, h - ch + 1)
                j = np.random.randint(0, w - cw + 1)
                break
        else:
            ch, cw = min(h, w), min(h, w)
            i, j = (h - ch) // 2, (w - cw) // 2
        sl = [slice(None)] * arr.ndim
        sl[ha] = slice(i, i + ch)
        sl[wa] = slice(j, j + cw)
        cropped = arr[tuple(sl)]
        out = Resize(self.size)._apply_image(cropped)
        return _wrap(np.asarray(out), wt)


class RandomErasing(BaseTransform):
    """≙ paddle.vision.transforms.RandomErasing [U]."""

    def __init__(self, prob=0.5, scale=(0.02, 0.33), ratio=(0.3, 3.3),
                 value=0, inplace=False, keys=None):
        self.prob = prob
        self.scale = scale
        self.ratio = ratio
        self.value = value

    def _apply_image(self, img):
        if np.random.random() >= self.prob:
            return img
        arr, wt = _as_np(img)
        arr = arr.copy()
        ha, wa = _hwc_axes(arr)
        h, w = arr.shape[ha], arr.shape[wa]
        area = h * w
        for _ in range(10):
            target = area * np.random.uniform(*self.scale)
            ar = np.random.uniform(*self.ratio)
            eh = int(round(np.sqrt(target * ar)))
            ew = int(round(np.sqrt(target / ar)))
            if eh < h and ew < w:
                i = np.random.randint(0, h - eh + 1)
                j = np.random.randint(0, w - ew + 1)
                sl = [slice(None)] * arr.ndim
                sl[ha] = slice(i, i + eh)
                sl[wa] = slice(j, j + ew)
                arr[tuple(sl)] = self.value
                break
        return _wrap(arr, wt)


class Transpose(BaseTransform):
    """≙ paddle.vision.transforms.Transpose (HWC -> CHW by default) [U]."""

    def __init__(self, order=(2, 0, 1), keys=None):
        self.order = order

    def _apply_image(self, img):
        arr, wt = _as_np(img)
        return _wrap(arr.transpose(self.order), wt)


def hflip(img):
    arr, wt = _as_np(img)
    _, wa = _hwc_axes(arr)
    return _wrap(np.flip(arr, axis=wa), wt)


def vflip(img):
    arr, wt = _as_np(img)
    ha, _ = _hwc_axes(arr)
    return _wrap(np.flip(arr, axis=ha), wt)


def center_crop(img, output_size):
    return CenterCrop(output_size)._apply_image(img)


def crop(img, top, left, height, width):
    arr, wt = _as_np(img)
    ha, wa = _hwc_axes(arr)
    sl = [slice(None)] * arr.ndim
    sl[ha] = slice(top, top + height)
    sl[wa] = slice(left, left + width)
    return _wrap(arr[tuple(sl)], wt)


def pad(img, padding, fill=0, padding_mode="constant"):
    return Pad(padding, fill, padding_mode)._apply_image(img)


def to_grayscale(img, num_output_channels=1):
    return Grayscale(num_output_channels)._apply_image(img)


def rotate(img, angle, interpolation="nearest", expand=False, center=None,
           fill=0):
    t = RandomRotation((angle, angle), fill=fill)
    return t._apply_image(img)


def erase(img, i, j, h, w, v, inplace=False):
    arr, wt = _as_np(img)
    arr = arr if inplace else arr.copy()
    ha, wa = _hwc_axes(arr)
    sl = [slice(None)] * arr.ndim
    sl[ha] = slice(i, i + h)
    sl[wa] = slice(j, j + w)
    arr[tuple(sl)] = v
    return _wrap(arr, wt)


def adjust_brightness(img, brightness_factor):
    arr, wt = _as_np(img)
    out = np.clip(arr.astype(np.float32) * brightness_factor, 0,
                  255 if arr.dtype == np.uint8 else np.inf)
    return _wrap(out.astype(arr.dtype), wt)


def adjust_contrast(img, contrast_factor):
    arr, wt = _as_np(img)
    mean = arr.astype(np.float32).mean()
    out = (arr.astype(np.float32) - mean) * contrast_factor + mean
    out = np.clip(out, 0, 255 if arr.dtype == np.uint8 else np.inf)
    return _wrap(out.astype(arr.dtype), wt)


def adjust_hue(img, hue_factor):
    arr, wt = _as_np(img)
    chw = arr.ndim == 3 and arr.shape[0] in (1, 3)
    x = arr.astype(np.float32)
    if arr.dtype == np.uint8:
        x = x / 255.0
    if chw:
        x = x.transpose(1, 2, 0)
    import matplotlib.colors as mc
    hsv = mc.rgb_to_hsv(np.clip(x, 0, 1))
    hsv[..., 0] = (hsv[..., 0] + hue_factor) % 1.0
    out = mc.hsv_to_rgb(hsv)
    if chw:
        out = out.transpose(2, 0, 1)
    if arr.dtype == np.uint8:
        out = (out * 255.0).round().astype(np.uint8)
    return _wrap(out, wt)
