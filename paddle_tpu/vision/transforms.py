"""Vision transforms (numpy/host-side, feeding the DataLoader).
≙ reference «python/paddle/vision/transforms/» [U]."""
from __future__ import annotations

import numpy as np

from ..core.tensor import Tensor, to_tensor


class Compose:
    def __init__(self, transforms):
        self.transforms = transforms

    def __call__(self, x):
        for t in self.transforms:
            x = t(x)
        return x


class BaseTransform:
    def __call__(self, x):
        return self._apply_image(x)

    def _apply_image(self, x):
        raise NotImplementedError


class ToTensor(BaseTransform):
    def __init__(self, data_format="CHW", keys=None):
        self.data_format = data_format

    def _apply_image(self, img):
        arr = np.asarray(img, np.float32) / 255.0
        if arr.ndim == 2:
            arr = arr[:, :, None]
        if self.data_format == "CHW":
            arr = arr.transpose(2, 0, 1)
        return to_tensor(arr)


class Normalize(BaseTransform):
    def __init__(self, mean=0.0, std=1.0, data_format="CHW", to_rgb=False,
                 keys=None):
        self.mean = np.asarray(mean, np.float32)
        self.std = np.asarray(std, np.float32)
        self.data_format = data_format

    def _apply_image(self, img):
        arr = img.numpy() if isinstance(img, Tensor) else np.asarray(
            img, np.float32)
        shape = (-1, 1, 1) if self.data_format == "CHW" else (1, 1, -1)
        arr = (arr - self.mean.reshape(shape)) / self.std.reshape(shape)
        return to_tensor(arr) if isinstance(img, Tensor) else arr


class Resize(BaseTransform):
    def __init__(self, size, interpolation="bilinear", keys=None):
        self.size = (size, size) if isinstance(size, int) else tuple(size)

    def _apply_image(self, img):
        import jax
        import jax.numpy as jnp
        arr = img.numpy() if isinstance(img, Tensor) else np.asarray(img)
        chw = arr.ndim == 3 and arr.shape[0] in (1, 3)
        h_axis = 1 if chw else 0
        out_shape = list(arr.shape)
        out_shape[h_axis] = self.size[0]
        out_shape[h_axis + 1] = self.size[1]
        out = np.asarray(jax.image.resize(jnp.asarray(arr, jnp.float32),
                                          out_shape, "bilinear"))
        return to_tensor(out) if isinstance(img, Tensor) else out


class CenterCrop(BaseTransform):
    def __init__(self, size, keys=None):
        self.size = (size, size) if isinstance(size, int) else tuple(size)

    def _apply_image(self, img):
        arr = img.numpy() if isinstance(img, Tensor) else np.asarray(img)
        chw = arr.ndim == 3 and arr.shape[0] in (1, 3)
        h_axis = 1 if chw else 0
        h, w = arr.shape[h_axis], arr.shape[h_axis + 1]
        th, tw = self.size
        i, j = (h - th) // 2, (w - tw) // 2
        sl = [slice(None)] * arr.ndim
        sl[h_axis] = slice(i, i + th)
        sl[h_axis + 1] = slice(j, j + tw)
        out = arr[tuple(sl)]
        return to_tensor(out) if isinstance(img, Tensor) else out


class RandomHorizontalFlip(BaseTransform):
    def __init__(self, prob=0.5, keys=None):
        self.prob = prob

    def _apply_image(self, img):
        if np.random.random() < self.prob:
            arr = img.numpy() if isinstance(img, Tensor) else np.asarray(img)
            chw = arr.ndim == 3 and arr.shape[0] in (1, 3)
            out = arr[..., ::-1] if not chw else arr[:, :, ::-1]
            out = np.ascontiguousarray(out)
            return to_tensor(out) if isinstance(img, Tensor) else out
        return img


class RandomCrop(BaseTransform):
    def __init__(self, size, padding=None, pad_if_needed=False, keys=None):
        self.size = (size, size) if isinstance(size, int) else tuple(size)
        self.padding = padding

    def _apply_image(self, img):
        arr = img.numpy() if isinstance(img, Tensor) else np.asarray(img)
        chw = arr.ndim == 3 and arr.shape[0] in (1, 3)
        h_axis = 1 if chw else 0
        if self.padding:
            p = self.padding
            pads = [(0, 0)] * arr.ndim
            pads[h_axis] = (p, p)
            pads[h_axis + 1] = (p, p)
            arr = np.pad(arr, pads)
        h, w = arr.shape[h_axis], arr.shape[h_axis + 1]
        th, tw = self.size
        i = np.random.randint(0, h - th + 1)
        j = np.random.randint(0, w - tw + 1)
        sl = [slice(None)] * arr.ndim
        sl[h_axis] = slice(i, i + th)
        sl[h_axis + 1] = slice(j, j + tw)
        out = arr[tuple(sl)]
        return to_tensor(out) if isinstance(img, Tensor) else out


def to_tensor_fn(img, data_format="CHW"):
    return ToTensor(data_format)(img)


def normalize(img, mean, std, data_format="CHW", to_rgb=False):
    return Normalize(mean, std, data_format)(img)


def resize(img, size, interpolation="bilinear"):
    return Resize(size, interpolation)(img)
